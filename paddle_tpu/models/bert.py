"""BERT/ERNIE-base encoder + pretraining heads.

Parity target: the reference's ERNIE/BERT configs (PaddleNLP; in-tree
multihead precursor ops at paddle/fluid/operators/fused/multihead_matmul_op*
and bert_encoder_functor.cu). Config 3 of BASELINE.json — the north-star
throughput model.

TPU-first design notes:
  * one fused QKV projection per layer (one big MXU matmul instead of 3),
  * attention kept as batched matmuls over [B, H, S, D] — XLA maps these to
    the MXU directly; the pallas flash-attention kernel (ops/pallas) is the
    drop-in for long sequences,
  * bf16-friendly: all matmul weights created float32, AMP rewrites to bf16.
"""
from __future__ import annotations

import math

from .. import layers


def _attention(x, hidden, num_heads, seq_len, attn_bias=None, dropout=0.0,
               is_test=False, use_flash=True):
    """Multi-head self-attention. x: [-1, S, H].

    use_flash=True routes through the fused flash_attention op (pallas on
    TPU). Attention-probability dropout is folded out on that path — the
    standard trade of fused-attention kernels; output dropout is kept.
    use_flash=False keeps the unfused batched-matmul formulation (exact
    reference math incl. prob dropout, and the parity baseline in tests).
    """
    head_dim = hidden // num_heads
    qkv = layers.fc(x, size=3 * hidden, num_flatten_dims=2)  # [B,S,3H]
    if use_flash is True and dropout and not is_test:
        import warnings
        warnings.warn(
            "bert: flash attention folds out attention-probability "
            "dropout (output dropout kept); use use_flash=False for "
            "exact reference regularization", stacklevel=3)
    if use_flash is True and hidden % 128 == 0 and head_dim in (64, 128):
        # packed path: the kernel consumes the fused projection directly
        # (no [B,S,3H] <-> [B,h,S,d] transposes; measured ~2.4 GB/step of
        # layout traffic on the split-tensor path at seq-512)
        ctx = layers.flash_attention_qkv(qkv, num_heads, bias=attn_bias)
        return layers.fc(ctx, size=hidden, num_flatten_dims=2)
    if use_flash == "xla":
        # transpose-free: stay [B,S,h,d] and let the einsum op pick
        # layouts (measured faster than both the pallas kernel and the
        # explicit-transpose unfused path at S<=512 on v5e)
        qkv = layers.reshape(qkv, [0, seq_len, 3, num_heads, head_dim])
        q = layers.squeeze(
            layers.slice(qkv, axes=[2], starts=[0], ends=[1]), [2])
        k = layers.squeeze(
            layers.slice(qkv, axes=[2], starts=[1], ends=[2]), [2])
        v = layers.squeeze(
            layers.slice(qkv, axes=[2], starts=[2], ends=[3]), [2])
        import os
        prob_drop = (0.0 if os.environ.get("PT_BERT_NO_PROB_DROPOUT")
                     else dropout)
        ctx = layers.flash_attention(
            q, k, v, bias=attn_bias, impl="xla", layout="bshd",
            dropout_prob=prob_drop, is_test=is_test)   # [B,S,h,d]
        ctx = layers.reshape(ctx, [0, seq_len, hidden])
        return layers.fc(ctx, size=hidden, num_flatten_dims=2)
    qkv = layers.reshape(qkv, [0, seq_len, 3, num_heads, head_dim])
    qkv = layers.transpose(qkv, [2, 0, 3, 1, 4])  # [3,B,Hd,S,D]
    q = layers.squeeze(layers.slice(qkv, axes=[0], starts=[0], ends=[1]), [0])
    k = layers.squeeze(layers.slice(qkv, axes=[0], starts=[1], ends=[2]), [0])
    v = layers.squeeze(layers.slice(qkv, axes=[0], starts=[2], ends=[3]), [0])
    if use_flash:
        ctx = layers.flash_attention(q, k, v, bias=attn_bias)
    else:
        scores = layers.matmul(q, k, transpose_y=True,
                               alpha=1.0 / math.sqrt(head_dim))  # [B,Hd,S,S]
        if attn_bias is not None:
            bias4d = layers.unsqueeze(layers.unsqueeze(attn_bias, [1]), [1])
            scores = layers.elementwise_add(scores, bias4d)
        probs = layers.softmax(scores)
        if dropout and not is_test:
            probs = layers.dropout(probs, dropout, is_test=is_test,
                                   dropout_implementation="upscale_in_train")
        ctx = layers.matmul(probs, v)  # [B,Hd,S,D]
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    ctx = layers.reshape(ctx, [0, seq_len, hidden])
    return layers.fc(ctx, size=hidden, num_flatten_dims=2)


def _ffn(x, hidden, intermediate):
    h = layers.fc(x, size=intermediate, num_flatten_dims=2, act="gelu")
    return layers.fc(h, size=hidden, num_flatten_dims=2)


def bert_encoder(input_ids, token_type_ids=None, attn_mask=None,
                 vocab_size=30522, hidden=768, num_layers=12, num_heads=12,
                 seq_len=128, intermediate=3072, max_position=512,
                 type_vocab=2, dropout=0.1, is_test=False, use_flash=True):
    """Returns final hidden states [-1, S, H].

    input_ids/token_type_ids: [-1, S] int64; attn_mask: [-1, S] float32
    (1 = attend, 0 = pad) or None.
    """
    word_emb = layers.embedding(input_ids, size=[vocab_size, hidden])
    pos_ids = layers.range(0, seq_len, 1, dtype="int64")
    pos_emb = layers.embedding(pos_ids, size=[max_position, hidden])
    emb = layers.elementwise_add(word_emb, pos_emb, axis=-1)
    if token_type_ids is not None:
        type_emb = layers.embedding(token_type_ids, size=[type_vocab, hidden])
        emb = layers.elementwise_add(emb, type_emb)
    x = layers.layer_norm(emb, begin_norm_axis=2)
    if dropout and not is_test:
        x = layers.dropout(x, dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")

    attn_bias = None
    if attn_mask is not None:
        # [B,S] additive bias rows (flash path broadcasts over heads/q;
        # unfused path unsqueezes to [B,1,1,S])
        attn_bias = layers.scale(attn_mask, scale=10000.0, bias=-10000.0)

    for _ in range(num_layers):
        attn = _attention(x, hidden, num_heads, seq_len, attn_bias,
                          dropout, is_test, use_flash=use_flash)
        if dropout and not is_test:
            attn = layers.dropout(attn, dropout, is_test=is_test,
                                  dropout_implementation="upscale_in_train")
        x = layers.layer_norm(layers.elementwise_add(x, attn),
                              begin_norm_axis=2)
        ffn = _ffn(x, hidden, intermediate)
        if dropout and not is_test:
            ffn = layers.dropout(ffn, dropout, is_test=is_test,
                                 dropout_implementation="upscale_in_train")
        x = layers.layer_norm(layers.elementwise_add(x, ffn),
                              begin_norm_axis=2)
    return x


def build_bert_pretrain(batch_size=None, seq_len=128, vocab_size=30522,
                        hidden=768, num_layers=12, num_heads=12,
                        intermediate=3072, dropout=0.1, is_test=False,
                        use_flash=True, max_predictions=None):
    """MLM pretraining graph.

    Two head formulations:

    * ``max_predictions=None``: score every position over the full vocab
      ([B,S,V] logits), mask the loss.  Feeds: input_ids, token_type_ids,
      attn_mask, mlm_mask, mlm_labels — all [B,S].
    * ``max_predictions=P``: the standard pretraining data format
      (reference ERNIE/BERT create_pretraining_data): gather the P masked
      positions per sample and run the vocab projection only on them —
      head matmul and the [*,V] logits shrink by S/P (~6.7x at S=128,
      P=20), the dominant non-encoder cost.  Extra feeds: mlm_positions
      [B,P] int64, mlm_labels [B,P], mlm_weights [B,P] (0 pads unused
      slots).  Requires a fixed batch_size (the gather index builds
      a [B,P,2] coordinate tensor).

    Returns (feed_names, {'loss': ...}).
    """
    b = -1 if batch_size is None else batch_size
    input_ids = layers.data("input_ids", [b, seq_len], dtype="int64",
                            append_batch_size=False)
    token_type_ids = layers.data("token_type_ids", [b, seq_len],
                                 dtype="int64", append_batch_size=False)
    attn_mask = layers.data("attn_mask", [b, seq_len], dtype="float32",
                            append_batch_size=False)

    enc = bert_encoder(input_ids, token_type_ids, attn_mask,
                       vocab_size=vocab_size, hidden=hidden,
                       num_layers=num_layers, num_heads=num_heads,
                       seq_len=seq_len, intermediate=intermediate,
                       max_position=max(512, seq_len),
                       dropout=dropout, is_test=is_test,
                       use_flash=use_flash)

    if max_predictions is not None:
        if batch_size is None:
            raise ValueError("masked-gather head needs a fixed batch_size")
        P = int(max_predictions)
        positions = layers.data("mlm_positions", [b, P], dtype="int64",
                                append_batch_size=False)
        mlm_labels = layers.data("mlm_labels", [b, P], dtype="int64",
                                 append_batch_size=False)
        weights = layers.data("mlm_weights", [b, P], dtype="float32",
                              append_batch_size=False)
        # [B,P,2] coordinates (batch row, seq position) for gather_nd
        rows = layers.range(0, b, 1, dtype="int64")          # [B]
        rows = layers.expand(layers.unsqueeze(rows, [1]), [1, P])
        coords = layers.stack([rows, positions], axis=2)     # [B,P,2]
        picked = layers.gather_nd(enc, coords)               # [B,P,H]
        h = layers.fc(picked, size=hidden, num_flatten_dims=2, act="gelu")
        h = layers.layer_norm(h, begin_norm_axis=2)
        logits = layers.fc(h, size=vocab_size, num_flatten_dims=2)
        loss = layers.softmax_with_cross_entropy(
            logits, layers.unsqueeze(mlm_labels, [2]))       # [B,P,1]
        loss = layers.elementwise_mul(layers.squeeze(loss, [2]), weights)
        denom = layers.elementwise_add(
            layers.reduce_sum(weights),
            layers.fill_constant([1], "float32", 1e-5))
        mean_loss = layers.elementwise_div(layers.reduce_sum(loss), denom)
        feeds = ["input_ids", "token_type_ids", "attn_mask",
                 "mlm_positions", "mlm_labels", "mlm_weights"]
        return feeds, {"loss": mean_loss}

    mlm_mask = layers.data("mlm_mask", [b, seq_len], dtype="float32",
                           append_batch_size=False)
    mlm_labels = layers.data("mlm_labels", [b, seq_len], dtype="int64",
                             append_batch_size=False)
    # MLM head: transform + layernorm + vocab projection
    h = layers.fc(enc, size=hidden, num_flatten_dims=2, act="gelu")
    h = layers.layer_norm(h, begin_norm_axis=2)
    logits = layers.fc(h, size=vocab_size, num_flatten_dims=2)  # [B,S,V]
    labels = layers.unsqueeze(mlm_labels, [2])
    loss = layers.softmax_with_cross_entropy(logits, labels)  # [B,S,1]
    loss = layers.squeeze(loss, [2])
    masked = layers.elementwise_mul(loss, mlm_mask)
    denom = layers.elementwise_add(
        layers.reduce_sum(mlm_mask),
        layers.fill_constant([1], "float32", 1e-5))
    mean_loss = layers.elementwise_div(layers.reduce_sum(masked), denom)
    feeds = ["input_ids", "token_type_ids", "attn_mask", "mlm_mask",
             "mlm_labels"]
    return feeds, {"loss": mean_loss}
