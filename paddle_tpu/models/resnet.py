"""ResNet for ImageNet — the reference's PaddleClas ResNet-50 config and the
in-tree SE-ResNeXt parallel-executor test
(python/paddle/fluid/tests/unittests/seresnext_net.py) are the parity
targets. Static-graph builder, NCHW, bottleneck blocks.

TPU note: convolutions stay NCHW at the IR level; XLA lays them out for the
MXU itself. BatchNorm keeps persistable moving stats in the scope, updated
in-graph (no cross-replica sync here — sync_batch_norm is the DP variant).
"""
from __future__ import annotations

from .. import layers

_DEPTH_CFG = {
    18: ([2, 2, 2, 2], False),
    34: ([3, 4, 6, 3], False),
    50: ([3, 4, 6, 3], True),
    101: ([3, 4, 23, 3], True),
    152: ([3, 8, 36, 3], True),
}


def _conv_bn(x, num_filters, filter_size, stride=1, act=None, is_test=False):
    conv = layers.conv2d(x, num_filters=num_filters, filter_size=filter_size,
                         stride=stride, padding=(filter_size - 1) // 2,
                         bias_attr=False)
    return layers.batch_norm(conv, act=act, is_test=is_test)


def _shortcut(x, ch_out, stride, is_test):
    ch_in = x.shape[1]
    if ch_in != ch_out or stride != 1:
        return _conv_bn(x, ch_out, 1, stride, is_test=is_test)
    return x


def _basic_block(x, num_filters, stride, is_test):
    conv0 = _conv_bn(x, num_filters, 3, stride, act="relu", is_test=is_test)
    conv1 = _conv_bn(conv0, num_filters, 3, 1, is_test=is_test)
    short = _shortcut(x, num_filters, stride, is_test)
    return layers.relu(layers.elementwise_add(short, conv1))


def _bottleneck(x, num_filters, stride, is_test):
    conv0 = _conv_bn(x, num_filters, 1, act="relu", is_test=is_test)
    conv1 = _conv_bn(conv0, num_filters, 3, stride, act="relu",
                     is_test=is_test)
    conv2 = _conv_bn(conv1, num_filters * 4, 1, is_test=is_test)
    short = _shortcut(x, num_filters * 4, stride, is_test)
    return layers.relu(layers.elementwise_add(short, conv2))


def resnet(images, label=None, depth: int = 50, class_num: int = 1000,
           is_test: bool = False):
    """images: [-1, 3, H, W]; label: [-1, 1] int64."""
    stages, bottleneck = _DEPTH_CFG[depth]
    x = _conv_bn(images, 64, 7, stride=2, act="relu", is_test=is_test)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max")
    block = _bottleneck if bottleneck else _basic_block
    num_filters = [64, 128, 256, 512]
    for stage, count in enumerate(stages):
        for i in range(count):
            stride = 2 if i == 0 and stage > 0 else 1
            x = block(x, num_filters[stage], stride, is_test)
    pool = layers.adaptive_pool2d(x, pool_size=1, pool_type="avg")
    logits = layers.fc(pool, size=class_num)
    out = {"logits": logits}
    if label is not None:
        loss = layers.softmax_with_cross_entropy(logits, label)
        out["loss"] = layers.mean(loss)
        out["acc"] = layers.accuracy(layers.softmax(logits), label)
    return out


def build_resnet_train(batch_size=None, depth=50, image_size=224,
                       class_num=1000):
    b = -1 if batch_size is None else batch_size
    images = layers.data("images", [b, 3, image_size, image_size],
                         append_batch_size=False)
    label = layers.data("label", [b, 1], dtype="int64",
                        append_batch_size=False)
    outs = resnet(images, label, depth=depth, class_num=class_num)
    return ["images", "label"], outs
