"""LeNet-5 on MNIST — the reference's `recognize_digits` book model
(python/paddle/fluid/tests/book/test_recognize_digits.py, conv variant).

Static-graph builder; config 1 of BASELINE.json.
"""
from __future__ import annotations

from .. import layers


def lenet(images, label=None, class_num: int = 10):
    """Build LeNet forward (+ loss/acc when `label` given).

    images: [-1, 1, 28, 28] float32; label: [-1, 1] int64.
    Returns dict with 'prediction' and, with label, 'loss'/'acc'.
    """
    conv1 = layers.conv2d(images, num_filters=20, filter_size=5, act="relu")
    pool1 = layers.pool2d(conv1, pool_size=2, pool_stride=2, pool_type="max")
    conv2 = layers.conv2d(pool1, num_filters=50, filter_size=5, act="relu")
    pool2 = layers.pool2d(conv2, pool_size=2, pool_stride=2, pool_type="max")
    hidden = layers.fc(pool2, size=500, act="relu")
    prediction = layers.fc(hidden, size=class_num, act="softmax")
    out = {"prediction": prediction}
    if label is not None:
        loss = layers.cross_entropy(prediction, label)
        out["loss"] = layers.mean(loss)
        out["acc"] = layers.accuracy(prediction, label)
    return out


def build_mnist_train(batch_size=None):
    """Declare feed vars + LeNet + loss in the current default program.

    Returns (feed_names, outputs-dict).
    """
    bshape = [-1 if batch_size is None else batch_size]
    images = layers.data("images", bshape + [1, 28, 28],
                         append_batch_size=False)
    label = layers.data("label", bshape + [1], dtype="int64",
                        append_batch_size=False)
    outs = lenet(images, label)
    return ["images", "label"], outs
