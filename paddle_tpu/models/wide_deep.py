"""Wide & Deep CTR model (Cheng et al. 2016) — the reference's flagship
parameter-server workload (BASELINE.md tracked config; reference trains
it via PaddleRec on the CPU PS cluster, README.md:52).

Criteo-style input: ``num_sparse`` categorical slots (int64 feature ids,
hashed into one shared table space) + ``num_dense`` continuous features.

  * wide: per-slot 1-d embeddings summed with the dense features through
    a linear layer — a (sparse) logistic regression.
  * deep: per-slot ``embed_dim`` embeddings concatenated with the dense
    features through an MLP.
  * logit = wide + deep; loss = sigmoid cross entropy; metric = AUC.

With ``is_sparse=True`` (the default) the embedding tables take the
lookup_table sparse path, so under fleet PS mode they are transpiled to
server-resident tables (distributed/ps/worker.py) and the declared vocab
can exceed device HBM — set ``is_distributed=True`` for the
lazy-initialized LARGE_VOCAB server tables.
"""
from __future__ import annotations

from .. import layers

__all__ = ["wide_deep_net"]


def wide_deep_net(num_sparse: int = 26, num_dense: int = 13,
                  vocab_size: int = 1000001, embed_dim: int = 10,
                  hidden: (tuple) = (400, 400, 400),
                  is_sparse: bool = True, is_distributed: bool = False):
    """Build the static-graph Wide&Deep; returns a dict of handles."""
    sparse_ids = layers.data("sparse_ids", shape=[num_sparse], dtype="int64",
                             append_batch_size=True)
    dense_x = layers.data("dense_x", shape=[num_dense], dtype="float32",
                          append_batch_size=True)
    label = layers.data("label", shape=[1], dtype="float32",
                        append_batch_size=True)

    # ---- wide: 1-d embeddings + linear on dense --------------------------
    wide_emb = layers.embedding(
        sparse_ids, size=[vocab_size, 1], is_sparse=is_sparse,
        is_distributed=is_distributed, name="wide_embedding",
        param_attr="wide_embedding_w")
    # [b, num_sparse, 1] -> sum over slots -> [b, 1]
    wide_sum = layers.reduce_sum(wide_emb, dim=1)
    wide_dense = layers.fc(dense_x, size=1, name="wide_fc")
    wide_logit = wide_sum + wide_dense

    # ---- deep: embed_dim embeddings -> MLP -------------------------------
    deep_emb = layers.embedding(
        sparse_ids, size=[vocab_size, embed_dim], is_sparse=is_sparse,
        is_distributed=is_distributed, name="deep_embedding",
        param_attr="deep_embedding_w")
    flat = layers.flatten(deep_emb, axis=1)        # [b, num_sparse*dim]
    x = layers.concat([flat, dense_x], axis=1)
    for i, h in enumerate(hidden):
        x = layers.fc(x, size=h, act="relu", name=f"deep_fc{i}")
    deep_logit = layers.fc(x, size=1, name="deep_out")

    logit = wide_logit + deep_logit
    prob = layers.sigmoid(logit)
    loss = layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, label))
    return {"sparse_ids": sparse_ids, "dense_x": dense_x, "label": label,
            "logit": logit, "prob": prob, "loss": loss}
