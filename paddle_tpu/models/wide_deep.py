"""Wide & Deep CTR model (Cheng et al. 2016) — the reference's flagship
parameter-server workload (BASELINE.md tracked config; reference trains
it via PaddleRec on the CPU PS cluster, README.md:52).

Criteo-style input: ``num_sparse`` categorical slots (int64 feature ids,
hashed into one shared table space) + ``num_dense`` continuous features.

  * wide: per-slot 1-d embeddings summed with the dense features through
    a linear layer — a (sparse) logistic regression.
  * deep: per-slot ``embed_dim`` embeddings concatenated with the dense
    features through an MLP.
  * logit = wide + deep; loss = sigmoid cross entropy; metric = AUC.

With ``is_sparse=True`` (the default) the embedding tables take the
lookup_table sparse path so the declared vocab can exceed one device's
HBM.  ``is_distributed=True`` marks the tables for OUT-OF-GRAPH
residency: on this stack that no longer means PS transpilation — the
serving path row-shards the table across the local device ring via
``serving/embedding.py`` (``ShardedEmbeddingTable``; ``mod``/``range``
placement, hot-row cache), and :func:`wide_deep_serving_net` is the
dense remainder that runs AFTER the tier's gather.  Training-side
lookups stay in-graph regardless of the flag.
"""
from __future__ import annotations

from .. import layers

__all__ = ["wide_deep_net", "wide_deep_serving_net"]


def wide_deep_net(num_sparse: int = 26, num_dense: int = 13,
                  vocab_size: int = 1000001, embed_dim: int = 10,
                  hidden: (tuple) = (400, 400, 400),
                  is_sparse: bool = True, is_distributed: bool = False):
    """Build the static-graph Wide&Deep; returns a dict of handles."""
    sparse_ids = layers.data("sparse_ids", shape=[num_sparse], dtype="int64",
                             append_batch_size=True)
    dense_x = layers.data("dense_x", shape=[num_dense], dtype="float32",
                          append_batch_size=True)
    label = layers.data("label", shape=[1], dtype="float32",
                        append_batch_size=True)

    # ---- wide: 1-d embeddings + linear on dense --------------------------
    wide_emb = layers.embedding(
        sparse_ids, size=[vocab_size, 1], is_sparse=is_sparse,
        is_distributed=is_distributed, name="wide_embedding",
        param_attr="wide_embedding_w")
    # [b, num_sparse, 1] -> sum over slots -> [b, 1]
    wide_sum = layers.reduce_sum(wide_emb, dim=1)
    wide_dense = layers.fc(dense_x, size=1, name="wide_fc")
    wide_logit = wide_sum + wide_dense

    # ---- deep: embed_dim embeddings -> MLP -------------------------------
    deep_emb = layers.embedding(
        sparse_ids, size=[vocab_size, embed_dim], is_sparse=is_sparse,
        is_distributed=is_distributed, name="deep_embedding",
        param_attr="deep_embedding_w")
    flat = layers.flatten(deep_emb, axis=1)        # [b, num_sparse*dim]
    x = layers.concat([flat, dense_x], axis=1)
    for i, h in enumerate(hidden):
        x = layers.fc(x, size=h, act="relu", name=f"deep_fc{i}")
    deep_logit = layers.fc(x, size=1, name="deep_out")

    logit = wide_logit + deep_logit
    prob = layers.sigmoid(logit)
    loss = layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, label))
    return {"sparse_ids": sparse_ids, "dense_x": dense_x, "label": label,
            "logit": logit, "prob": prob, "loss": loss}


def wide_deep_serving_net(num_sparse: int = 26, num_dense: int = 13,
                          embed_dim: int = 10,
                          hidden: (tuple) = (400, 400, 400)):
    """The dense remainder of Wide&Deep for the serving tier: identical
    math to :func:`wide_deep_net` AFTER the embedding lookups, fed the
    already-gathered rows instead of ids.  The tier
    (``serving/embedding.py``) gathers one fused ``[vocab, 1+embed_dim]``
    row per id and feeds ``wide_rows`` (``[b, num_sparse, 1]``, the wide
    column) and ``deep_rows`` (``[b, num_sparse, embed_dim]``) here —
    so sharding/caching can never perturb the model: the graph below is
    the same fc/concat/sigmoid pipeline either way."""
    wide_rows = layers.data("wide_rows", shape=[num_sparse, 1],
                            dtype="float32", append_batch_size=True)
    deep_rows = layers.data("deep_rows", shape=[num_sparse, embed_dim],
                            dtype="float32", append_batch_size=True)
    dense_x = layers.data("dense_x", shape=[num_dense], dtype="float32",
                          append_batch_size=True)

    wide_sum = layers.reduce_sum(wide_rows, dim=1)       # [b, 1]
    wide_dense = layers.fc(dense_x, size=1, name="wide_fc")
    wide_logit = wide_sum + wide_dense

    flat = layers.flatten(deep_rows, axis=1)     # [b, num_sparse*dim]
    x = layers.concat([flat, dense_x], axis=1)
    for i, h in enumerate(hidden):
        x = layers.fc(x, size=h, act="relu", name=f"deep_fc{i}")
    deep_logit = layers.fc(x, size=1, name="deep_out")

    logit = wide_logit + deep_logit
    prob = layers.sigmoid(logit)
    return {"wide_rows": wide_rows, "deep_rows": deep_rows,
            "dense_x": dense_x, "logit": logit, "prob": prob}
