"""Attention seq2seq for machine translation (the reference book model).

Reference: python/paddle/fluid/tests/book/test_machine_translation.py and
the PaddleNLP seq2seq example (attention encoder-decoder with a
BeamSearchDecoder inference path). TPU-first choices:

  * fixed [batch, src_len]/[batch, trg_len] padded shapes with length
    masks — no LoD,
  * encoder: fused bi-GRU scan (layers.gru), the fast recurrent path,
  * decoder: GRUCell + Luong dot attention, teacher-forced unroll for
    training; fixed-shape BeamSearchDecoder + dynamic_decode for
    inference (layers/rnn.py) sharing weights by param name.

Train/infer weight sharing is by parameter NAME through the scope (the
reference contract): build_train and build_infer construct identically
named parameters in separate programs.
"""
from __future__ import annotations

from .. import layers
from ..framework.layer_helper import ParamAttr


def _attr(name):
    return ParamAttr(name=name)


class _AttentionDecoderCell(layers.RNNCell):
    """GRU cell + Luong dot attention over fixed encoder states.

    inputs: [N, E] (target embedding);
    states: (h [N, H], enc [N, S, 2H], enc_mask [N, S]).
    The encoder tensors ride in the state tuple so the
    BeamSearchDecoder's parent-reordering applies to them uniformly.
    """

    # (h, enc, mask): enc/mask are identical across beams — the decoder
    # skips their per-step parent reorder (layers/rnn.py
    # _reorder_states)
    beam_static_state = (False, True, True)

    def __init__(self, hidden, name="s2s_dec"):
        self.hidden_size = hidden
        self._cell = layers.GRUCell(hidden, name=f"{name}.gru")
        self._name = name

    def __call__(self, inputs, states):
        h, enc, mask = states
        new_h, _ = self._cell(inputs, h)
        # dot attention: scores [N, S] = enc · (W new_h)
        query = layers.fc(new_h, size=int(enc.shape[-1]),
                          param_attr=_attr(f"{self._name}.attn_w"),
                          bias_attr=False)                     # [N, 2H]
        scores = layers.squeeze(
            layers.matmul(enc, layers.unsqueeze(query, [2])), [2])
        scores = layers.elementwise_add(
            scores, layers.scale(mask, scale=10000.0, bias=-10000.0))
        w = layers.softmax(scores)                             # [N, S]
        ctxv = layers.squeeze(
            layers.matmul(layers.unsqueeze(w, [1]), enc), [1])  # [N, 2H]
        out = layers.concat([new_h, ctxv], axis=1)             # [N, H+2H]
        return out, (new_h, enc, mask)


def _encode(src_ids, src_mask, src_vocab, emb_dim, hidden):
    emb = layers.embedding(src_ids, size=[src_vocab, emb_dim],
                           param_attr=_attr("s2s.src_emb"))
    lengths = layers.cast(layers.reduce_sum(src_mask, dim=1), "int64")
    fwd, _ = layers.gru(emb, hidden, lengths=lengths,
                        param_attr=_attr("s2s.enc_fw.w"),
                        bias_attr=_attr("s2s.enc_fw.b"))
    bwd, _ = layers.gru(layers.sequence_reverse(emb, lengths=lengths),
                        hidden, lengths=lengths,
                        param_attr=_attr("s2s.enc_bw.w"),
                        bias_attr=_attr("s2s.enc_bw.b"))
    bwd = layers.sequence_reverse(bwd, lengths=lengths)
    enc = layers.concat([fwd, bwd], axis=2)                    # [B,S,2H]
    # initial decoder state from the mean of encoder states
    denom = layers.elementwise_add(
        layers.reduce_sum(src_mask, dim=1, keep_dim=True),
        layers.fill_constant([1], "float32", 1e-6))
    pooled = layers.elementwise_div(
        layers.reduce_sum(
            layers.elementwise_mul(enc, layers.unsqueeze(src_mask, [2])),
            dim=1), denom)
    h0 = layers.fc(pooled, size=hidden, act="tanh",
                   param_attr=_attr("s2s.h0_w"),
                   bias_attr=_attr("s2s.h0_b"))
    return enc, h0


def _trg_embed(ids, trg_vocab, emb_dim):
    return layers.embedding(ids, size=[trg_vocab, emb_dim],
                            param_attr=_attr("s2s.trg_emb"))


def _out_proj(x, trg_vocab, flatten=1):
    return layers.fc(x, size=trg_vocab, num_flatten_dims=flatten,
                     param_attr=_attr("s2s.out_w"),
                     bias_attr=_attr("s2s.out_b"))


def build_seq2seq_train(batch, src_len, trg_len, src_vocab, trg_vocab,
                        emb_dim=64, hidden=64):
    """Teacher-forced training graph.

    Feeds: src_ids [B,S], src_mask [B,S] f32, trg_in [B,T] (bos-shifted),
    trg_out [B,T] labels, trg_mask [B,T] f32.
    Returns (feed_names, {'loss': ...}).
    """
    src_ids = layers.data("src_ids", [batch, src_len], dtype="int64",
                          append_batch_size=False)
    src_mask = layers.data("src_mask", [batch, src_len],
                           append_batch_size=False)
    trg_in = layers.data("trg_in", [batch, trg_len], dtype="int64",
                         append_batch_size=False)
    trg_out = layers.data("trg_out", [batch, trg_len], dtype="int64",
                          append_batch_size=False)
    trg_mask = layers.data("trg_mask", [batch, trg_len],
                           append_batch_size=False)

    enc, h0 = _encode(src_ids, src_mask, src_vocab, emb_dim, hidden)
    cell = _AttentionDecoderCell(hidden)
    emb = _trg_embed(trg_in, trg_vocab, emb_dim)       # [B,T,E]
    # teacher-forced unroll (no input feeding — matches the decode path)
    states = (h0, enc, src_mask)
    outs = []
    for t in range(trg_len):
        x_t = layers.squeeze(
            layers.slice(emb, axes=[1], starts=[t], ends=[t + 1]), [1])
        out_t, states = cell(x_t, states)
        outs.append(out_t)
    dec = layers.stack(outs, axis=1)                   # [B,T,H+2H]
    logits = _out_proj(dec, trg_vocab, flatten=2)      # [B,T,V]
    loss = layers.softmax_with_cross_entropy(
        logits, layers.unsqueeze(trg_out, [2]))        # [B,T,1]
    loss = layers.elementwise_mul(layers.squeeze(loss, [2]), trg_mask)
    denom = layers.elementwise_add(layers.reduce_sum(trg_mask),
                                   layers.fill_constant([1], "float32",
                                                        1e-6))
    mean_loss = layers.elementwise_div(layers.reduce_sum(loss), denom)
    feeds = ["src_ids", "src_mask", "trg_in", "trg_out", "trg_mask"]
    return feeds, {"loss": mean_loss}


def build_seq2seq_infer(batch, src_len, src_vocab, trg_vocab, emb_dim=64,
                        hidden=64, beam_size=4, max_len=16, bos_id=0,
                        eos_id=1):
    """Beam-search inference graph (weights shared with the train graph
    by parameter name). Returns (feed_names, {'ids', 'scores',
    'lengths'}) with ids [B, beam, max_len]."""
    src_ids = layers.data("src_ids", [batch, src_len], dtype="int64",
                          append_batch_size=False)
    src_mask = layers.data("src_mask", [batch, src_len],
                           append_batch_size=False)
    enc, h0 = _encode(src_ids, src_mask, src_vocab, emb_dim, hidden)
    cell = _AttentionDecoderCell(hidden)

    decoder = layers.BeamSearchDecoder(
        cell, start_token=bos_id, end_token=eos_id,
        beam_size=beam_size,
        embedding_fn=lambda ids: _trg_embed(ids, trg_vocab, emb_dim),
        output_fn=lambda o: _out_proj(o, trg_vocab, flatten=1))
    ids, scores, lengths = layers.dynamic_decode(
        decoder, inits=(h0, enc, src_mask), max_step_num=max_len)
    return ["src_ids", "src_mask"], {"ids": ids, "scores": scores,
                                     "lengths": lengths}
