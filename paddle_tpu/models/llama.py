"""Llama-style decoder LM (config 5 of BASELINE.json: Llama-2-7B, DyGraph
DP + recompute — stretch the fluid-era API to a modern LLM).

Architecture: pre-RMSNorm, fused QKV with GQA, RoPE, causal flash
attention (pallas / ring under sp), SwiGLU MLP, untied LM head.

TPU-first notes:
  * attention via the flash_attention op — pallas kernel single-chip,
    ring attention when the sequence is sharded over `sp`;
  * all projections are single large matmuls (fused QKV, fused gate+up)
    to keep the MXU busy;
  * weights stay fp32 in the scope; AMP lowers matmuls to bf16.

Decode fast path (the generation serving workload): ``llama_block``
also runs in two KV-cache modes —

  * ``collect_kv=True`` (prefill): the post-RoPE, pre-GQA-expansion
    K/V of the whole prompt come back as extra outputs, so one forward
    populates a decode cache in one shot;
  * ``kv_cache=(cache_k, cache_v)`` + ``positions`` (cached decode):
    the block consumes persistent per-slot cache Variables, writes the
    step's fresh K/V at per-row dynamic offsets (``kv_cache_write`` —
    the op's output aliases the cache var, so the executor donates the
    buffer and XLA updates it in place in HBM) and attends the single
    new token over the cache (``cached_attention``) — O(1) work per
    token instead of O(n²) over the prefix.

With an explicit ``name`` prefix every parameter gets a deterministic
name, so the train/full-forward, prefill, and decode programs built in
one process bind the *same* scope weights (``tests/test_generation.py``
asserts cached decode logits are bit-exact against the uncached full
forward).
"""
from __future__ import annotations

from .. import layers


def _linear(x, size, pname=None, name=None):
    return layers.fc(x, size, num_flatten_dims=2, bias_attr=False,
                     param_attr=pname, name=name)


def llama_block(x, hidden, num_heads, num_kv_heads, seq_len, head_dim,
                intermediate, name=None, attn_impl="auto",
                kv_cache=None, positions=None, collect_kv=False,
                block_table=None, kv_lengths=None):
    """One decoder layer. x: [B, S, H].

    ``name`` prefixes every parameter deterministically (required when
    several programs must share one scope).  ``attn_impl`` feeds the
    flash_attention op's impl switch ("auto" | "xla" | pallas bools).

    Cache modes (mutually exclusive):
      * ``kv_cache=(cache_k, cache_v)`` with ``positions`` [B] int32 —
        cached decode: returns x with the caches updated in place.
        With ``block_table`` [B, NP] + ``kv_lengths`` [B] the caches
        are block-paged pools [P, n_kv, page_tokens, D]: the step's
        K/V scatter into the slots' current pages (``kv_pool_write``)
        and attention runs over the gathered logical view
        (``kv_pool_gather`` -> ``cached_attention``, the identical
        einsum the dense path runs — bit-exact).  ``seq_len`` > 1 in
        this mode is a *prefill chunk*: S new tokens starting at
        ``positions[b]`` attend the cache plus themselves causally.
      * ``collect_kv=True`` — prefill: returns ``(x, k, v)`` where
        k/v are the post-RoPE [B, n_kv, S, D] cache rows.
    """
    q_size = num_heads * head_dim
    kv_size = num_kv_heads * head_dim
    p = (lambda s: f"{name}.{s}") if name else (lambda s: None)
    h = layers.rms_norm(x, param_attr=p("ln1"))
    qkv = _linear(h, q_size + 2 * kv_size, pname=p("qkv.w"))
    q = layers.slice(qkv, axes=[2], starts=[0], ends=[q_size])
    k = layers.slice(qkv, axes=[2], starts=[q_size],
                     ends=[q_size + kv_size])
    v = layers.slice(qkv, axes=[2], starts=[q_size + kv_size],
                     ends=[q_size + 2 * kv_size])

    def heads(t, n):
        t = layers.reshape(t, [0, seq_len, n, head_dim])
        return layers.transpose(t, [0, 2, 1, 3])  # [B,n,S,D]

    q, k, v = heads(q, num_heads), heads(k, num_kv_heads), \
        heads(v, num_kv_heads)
    offset = positions if kv_cache is not None else None
    q = layers.rope(q, offset=offset)
    k = layers.rope(k, offset=offset)

    if kv_cache is not None:
        # cached decode: write this step's K/V at each slot's position,
        # then attend the new token(s) over the whole (updated) cache —
        # GQA expansion happens inside cached_attention
        cache_k, cache_v = kv_cache
        if block_table is not None:
            # paged: scatter into the slots' pages, then attend the
            # gathered logical view — write-before-gather makes the
            # fresh rows visible (mask admits j <= positions[b] + t,
            # which includes this step's own columns)
            cache_k = layers.kv_pool_write(cache_k, k, positions,
                                           block_table, kv_lengths)
            cache_v = layers.kv_pool_write(cache_v, v, positions,
                                           block_table, kv_lengths)
            gk = layers.kv_pool_gather(cache_k, block_table)
            gv = layers.kv_pool_gather(cache_v, block_table)
            attn = layers.cached_attention(q, gk, gv, positions)
        else:
            cache_k = layers.kv_cache_write(cache_k, k, positions)
            cache_v = layers.kv_cache_write(cache_v, v, positions)
            attn = layers.cached_attention(q, cache_k, cache_v,
                                           positions)
    else:
        cache_k = cache_v = None
        new_k, new_v = k, v  # pre-expansion rows are what a cache stores
        if num_kv_heads != num_heads:
            # repeat_interleave-style expansion [k1,k1,..,k2,k2,..]:
            # query-head group g maps to kv head g//rep, matching
            # canonical Llama GQA (block-order tile would pair queries
            # with the wrong kv heads).
            rep = num_heads // num_kv_heads

            def expand_kv(t):
                t = layers.reshape(t, [0, num_kv_heads, 1, seq_len,
                                       head_dim])
                t = layers.tile(t, [1, 1, rep, 1, 1])
                return layers.reshape(t, [0, num_heads, seq_len,
                                          head_dim])

            k, v = expand_kv(k), expand_kv(v)
        attn = layers.flash_attention(q, k, v, causal=True,
                                      impl=attn_impl)
    attn = layers.transpose(attn, [0, 2, 1, 3])
    attn = layers.reshape(attn, [0, seq_len, q_size])
    x = layers.elementwise_add(x, _linear(attn, hidden,
                                          pname=p("attn_out.w")))

    h = layers.rms_norm(x, param_attr=p("ln2"))
    gate_up = _linear(h, 2 * intermediate, pname=p("gate_up.w"))
    gate = layers.slice(gate_up, axes=[2], starts=[0], ends=[intermediate])
    up = layers.slice(gate_up, axes=[2], starts=[intermediate],
                      ends=[2 * intermediate])
    ffn = layers.elementwise_mul(layers.silu(gate), up)
    out = layers.elementwise_add(x, _linear(ffn, hidden,
                                            pname=p("ffn_out.w")))
    if collect_kv:
        return out, new_k, new_v
    return out


def llama(input_ids, vocab_size=32000, hidden=4096, num_layers=32,
          num_heads=32, num_kv_heads=None, intermediate=11008,
          seq_len=2048, name=None, attn_impl="auto"):
    """Returns logits [B, S, V]. input_ids: [B, S] int64."""
    num_kv_heads = num_kv_heads or num_heads
    head_dim = hidden // num_heads
    p = (lambda s: f"{name}.{s}") if name else (lambda s: None)
    x = layers.embedding(input_ids, size=[vocab_size, hidden],
                         param_attr=p("embed"))
    for i in range(num_layers):
        x = llama_block(x, hidden, num_heads, num_kv_heads, seq_len,
                        head_dim, intermediate,
                        name=f"{name}.blk{i}" if name else None,
                        attn_impl=attn_impl)
    x = layers.rms_norm(x, param_attr=p("ln_f"))
    return _linear(x, vocab_size, pname=p("head.w"))


def build_llama_train(batch_size=None, seq_len=2048, vocab_size=32000,
                      hidden=4096, num_layers=32, num_heads=32,
                      num_kv_heads=None, intermediate=11008):
    """Causal-LM training graph: feeds input_ids + labels [B, S]."""
    b = -1 if batch_size is None else batch_size
    input_ids = layers.data("input_ids", [b, seq_len], dtype="int64",
                            append_batch_size=False)
    labels = layers.data("labels", [b, seq_len], dtype="int64",
                         append_batch_size=False)
    logits = llama(input_ids, vocab_size, hidden, num_layers, num_heads,
                   num_kv_heads, intermediate, seq_len)
    loss = layers.softmax_with_cross_entropy(
        logits, layers.unsqueeze(labels, [2]))
    mean_loss = layers.mean(layers.squeeze(loss, [2]))
    return ["input_ids", "labels"], {"loss": mean_loss, "logits": logits}


# ---------------------------------------------------------------------------
# Generation fast path: full-forward reference / prefill / cached decode
# ---------------------------------------------------------------------------

def build_llama_forward(batch_size, seq_len, vocab_size=32000,
                        hidden=4096, num_layers=32, num_heads=32,
                        num_kv_heads=None, intermediate=11008,
                        name="llama", attn_impl="auto"):
    """Uncached full forward: feeds input_ids [B, S], fetches logits
    [B, S, V] (causal — row i depends only on tokens ≤ i, so one run
    yields every decode step's reference logits)."""
    input_ids = layers.data("input_ids", [batch_size, seq_len],
                            dtype="int64", append_batch_size=False)
    logits = llama(input_ids, vocab_size, hidden, num_layers, num_heads,
                   num_kv_heads, intermediate, seq_len, name=name,
                   attn_impl=attn_impl)
    return ["input_ids"], {"logits": logits}


def build_llama_prefill(batch_size, seq_len, vocab_size=32000,
                        hidden=4096, num_layers=32, num_heads=32,
                        num_kv_heads=None, intermediate=11008,
                        name="llama", attn_impl="auto",
                        cache_slots=None, max_seq_len=None,
                        paged=False, num_pages=None, page_tokens=None):
    """Prefill entry point: one causal forward over the (padded) prompt
    that populates a decode cache in one shot.

    Feeds: ``input_ids`` [B, S] int64 (right-padded to the bucket) and
    ``last_pos`` [B] int64 (index of the last real token).  Fetches:
    ``logits`` [B, V] (next-token logits at last_pos) and
    ``next_token`` [B] int64 (greedy).

    Cache handling, two modes:

    * ``cache_slots``/``max_seq_len`` given (the serving engine's
      path; requires ``batch_size == 1``): the per-layer post-RoPE K/V
      are written **in-graph** into the shared decode cache Variables
      ``<name>.cache_{k,v}_<i>`` at slot index feed ``slot`` [1] int32
      — the caches are mutated persistable state, so the prefill step
      donates them exactly like the decode step (no K/V fetch, no
      host-side reinsert).  With ``paged=True`` the caches are the
      block-paged pools ``<name>.pool_{k,v}_<i>`` instead and the
      slot feed is replaced by ``block_table`` [1, NP] int32 +
      ``prompt_len`` [1] int32 (rows past the real prompt length are
      redirected to the trash page).  The forward itself is the SAME
      graph either way, so paged prefill logits are bit-exact vs
      dense.
    * omitted: per-layer ``k_i``/``v_i`` [B, n_kv, S, D] rows come
      back as extra fetches for the caller to place.

    Because attention is causal, pad-tail rows never influence rows
    before the true length — the engine masks them out of the cache
    via per-slot positions."""
    from ..framework.core import default_main_program

    num_kv_heads = num_kv_heads or num_heads
    head_dim = hidden // num_heads
    input_ids = layers.data("input_ids", [batch_size, seq_len],
                            dtype="int64", append_batch_size=False)
    last_pos = layers.data("last_pos", [batch_size], dtype="int64",
                           append_batch_size=False)
    feeds = ["input_ids", "last_pos"]
    slot = block_table = prompt_len = zero_pos = None
    if cache_slots is not None:
        if batch_size != 1:
            raise ValueError("in-graph cache insert prefills one "
                             "request at a time (batch_size must be 1)")
        if max_seq_len is None or seq_len > max_seq_len:
            raise ValueError(f"prefill bucket {seq_len} exceeds cache "
                             f"max_seq_len {max_seq_len}")
        if paged:
            if not num_pages or not page_tokens:
                raise ValueError("paged prefill needs num_pages and "
                                 "page_tokens")
            np_slot = max_seq_len // page_tokens
            block_table = layers.data("block_table", [1, np_slot],
                                      dtype="int32",
                                      append_batch_size=False)
            prompt_len = layers.data("prompt_len", [1], dtype="int32",
                                     append_batch_size=False)
            feeds += ["block_table", "prompt_len"]
            zero_pos = layers.fill_constant([1], "int32", 0)
        else:
            slot = layers.data("slot", [1], dtype="int32",
                               append_batch_size=False)
            feeds.append("slot")
    x = layers.embedding(input_ids, size=[vocab_size, hidden],
                         param_attr=f"{name}.embed")
    kvs = []
    block = default_main_program().global_block()
    for i in range(num_layers):
        x, k, v = llama_block(x, hidden, num_heads, num_kv_heads,
                              seq_len, head_dim, intermediate,
                              name=f"{name}.blk{i}", attn_impl=attn_impl,
                              collect_kv=True)
        if block_table is not None:
            # paged: the prompt's K/V scatter across the slot's pages
            # from logical position 0; pad-tail rows (>= prompt_len)
            # go to the trash page
            for kind, t in (("k", k), ("v", v)):
                pool = block.create_var(
                    name=f"{name}.pool_{kind}_{i}", persistable=True,
                    shape=[num_pages, num_kv_heads, page_tokens,
                           head_dim],
                    dtype="float32", stop_gradient=True)
                layers.kv_pool_write(pool, t, zero_pos, block_table,
                                     prompt_len)
        elif slot is not None:
            for kind, t in (("k", k), ("v", v)):
                cache = block.create_var(
                    name=f"{name}.cache_{kind}_{i}", persistable=True,
                    shape=[cache_slots, num_kv_heads, max_seq_len,
                           head_dim],
                    dtype="float32", stop_gradient=True)
                layers.kv_cache_insert(cache, t, slot)
        else:
            kvs.append((k, v))
    x = layers.rms_norm(x, param_attr=f"{name}.ln_f")
    # LM head over ALL rows, then gather each row's last real position.
    # Gathering the hidden state first and projecting only that row
    # would save (S-1)·V head FLOPs, but XLA fuses the gather into the
    # projection and the fused contraction's accumulation order drifts
    # ~5e-8 from the full-forward GEMM — breaking the bit-exactness
    # contract (cached decode ≡ uncached forward, tolerance 0).
    all_logits = _linear(x, vocab_size, pname=f"{name}.head.w")
    rows = layers.range(0, batch_size, 1, dtype="int64")     # [B]
    coords = layers.stack([rows, last_pos], axis=1)          # [B, 2]
    logits = layers.gather_nd(all_logits, coords)            # [B, V]
    next_token = layers.argmax(logits, axis=-1)              # [B] int64
    fetches = {"logits": logits, "next_token": next_token}
    for i, (k, v) in enumerate(kvs):
        fetches[f"k_{i}"] = k
        fetches[f"v_{i}"] = v
    return feeds, fetches


def build_llama_decode(num_slots, max_seq_len, vocab_size=32000,
                       hidden=4096, num_layers=32, num_heads=32,
                       num_kv_heads=None, intermediate=11008,
                       name="llama", paged=False, num_pages=None,
                       page_tokens=None):
    """Cached decode step over a fixed slot grid.

    Feeds: ``tokens`` [slots, 1] int64 (each slot's current token) and
    ``positions`` [slots] int32 (each slot's pre-step sequence length =
    the cache offset this step writes at).  Per-layer cache Variables
    ``<name>.cache_k_<i>`` / ``.cache_v_<i>`` [slots, n_kv, S_max, D]
    are persistable read+written state — the executor donates them, so
    every step updates the caches in place in HBM.  Fetches: ``logits``
    [slots, V] and greedy ``next_token`` [slots] int64.

    ``paged=True`` swaps the per-slot reservation for the block-paged
    pools ``<name>.pool_{k,v}_<i>`` [num_pages, n_kv, page_tokens, D]
    and adds feeds ``block_tables`` [slots, NP] int32 (NP =
    max_seq_len // page_tokens) and ``live`` [slots] int32 (1 = the
    slot decodes this step, 0 = idle — its garbage write is redirected
    to the trash page instead of landing in a live page).

    Returns ``(feed_names, fetches, cache_names)``."""
    from ..framework.core import default_main_program

    num_kv_heads = num_kv_heads or num_heads
    head_dim = hidden // num_heads
    tokens = layers.data("tokens", [num_slots, 1], dtype="int64",
                         append_batch_size=False)
    positions = layers.data("positions", [num_slots], dtype="int32",
                            append_batch_size=False)
    feeds = ["tokens", "positions"]
    block_tables = live = None
    if paged:
        if not num_pages or not page_tokens:
            raise ValueError("paged decode needs num_pages and "
                             "page_tokens")
        np_slot = max_seq_len // page_tokens
        block_tables = layers.data("block_tables", [num_slots, np_slot],
                                   dtype="int32",
                                   append_batch_size=False)
        live = layers.data("live", [num_slots], dtype="int32",
                           append_batch_size=False)
        feeds += ["block_tables", "live"]
    block = default_main_program().global_block()
    cache_names = []
    caches = []
    for i in range(num_layers):
        if paged:
            shape = [num_pages, num_kv_heads, page_tokens, head_dim]
            knm, vnm = f"{name}.pool_k_{i}", f"{name}.pool_v_{i}"
        else:
            shape = [num_slots, num_kv_heads, max_seq_len, head_dim]
            knm, vnm = f"{name}.cache_k_{i}", f"{name}.cache_v_{i}"
        ck = block.create_var(name=knm, persistable=True, shape=shape,
                              dtype="float32", stop_gradient=True)
        cv = block.create_var(name=vnm, persistable=True, shape=shape,
                              dtype="float32", stop_gradient=True)
        caches.append((ck, cv))
        cache_names += [ck.name, cv.name]
    x = layers.embedding(tokens, size=[vocab_size, hidden],
                         param_attr=f"{name}.embed")
    for i, (ck, cv) in enumerate(caches):
        x = llama_block(x, hidden, num_heads, num_kv_heads, 1, head_dim,
                        intermediate, name=f"{name}.blk{i}",
                        kv_cache=(ck, cv), positions=positions,
                        block_table=block_tables, kv_lengths=live)
    x = layers.rms_norm(x, param_attr=f"{name}.ln_f")
    logits = _linear(x, vocab_size, pname=f"{name}.head.w")  # [slots,1,V]
    logits = layers.squeeze(logits, [1])                     # [slots, V]
    next_token = layers.argmax(logits, axis=-1)              # [slots]
    return feeds, \
        {"logits": logits, "next_token": next_token}, cache_names


def build_llama_prefill_chunk(chunk_len, max_seq_len, num_pages,
                              page_tokens, vocab_size=32000,
                              hidden=4096, num_layers=32, num_heads=32,
                              num_kv_heads=None, intermediate=11008,
                              name="llama"):
    """Paged prefill *continuation*: one slice of a prompt attends the
    slot's already-populated pages plus itself causally — the program
    behind both **chunked prefill** (a long prompt feeds in
    ``FLAGS_serving_prefill_chunk`` slices interleaved with decode
    steps) and **shared-prefix reuse** (a prefix-index hit maps the
    shared pages and only the prompt tail runs here).

    Feeds: ``chunk_ids`` [1, C] int64 (right-padded slice),
    ``base`` [1] int32 (tokens already in the slot's cache = the
    logical position of the chunk's first token), ``block_table``
    [1, NP] int32, ``chunk_len`` [1] int32 (real rows; the pad tail
    writes to the trash page), ``last_off`` [1] int64 (index of the
    last real token within the chunk).  Fetches: ``logits`` [1, V] at
    ``last_off`` and greedy ``next_token`` [1] — meaningful only for
    a prompt's final chunk.

    Returns ``(feed_names, fetches, cache_names)``."""
    from ..framework.core import default_main_program

    num_kv_heads = num_kv_heads or num_heads
    head_dim = hidden // num_heads
    np_slot = max_seq_len // page_tokens
    chunk_ids = layers.data("chunk_ids", [1, chunk_len], dtype="int64",
                            append_batch_size=False)
    base = layers.data("base", [1], dtype="int32",
                       append_batch_size=False)
    block_table = layers.data("block_table", [1, np_slot],
                              dtype="int32", append_batch_size=False)
    ck_len = layers.data("chunk_len", [1], dtype="int32",
                         append_batch_size=False)
    last_off = layers.data("last_off", [1], dtype="int64",
                           append_batch_size=False)
    block = default_main_program().global_block()
    cache_names = []
    caches = []
    for i in range(num_layers):
        ck = block.create_var(
            name=f"{name}.pool_k_{i}", persistable=True,
            shape=[num_pages, num_kv_heads, page_tokens, head_dim],
            dtype="float32", stop_gradient=True)
        cv = block.create_var(
            name=f"{name}.pool_v_{i}", persistable=True,
            shape=[num_pages, num_kv_heads, page_tokens, head_dim],
            dtype="float32", stop_gradient=True)
        caches.append((ck, cv))
        cache_names += [ck.name, cv.name]
    x = layers.embedding(chunk_ids, size=[vocab_size, hidden],
                         param_attr=f"{name}.embed")
    for i, (ck, cv) in enumerate(caches):
        # rope offset = base per row; cached_attention's validity mask
        # (j <= base + t) is exactly causal-over-prefix-plus-chunk
        x = llama_block(x, hidden, num_heads, num_kv_heads, chunk_len,
                        head_dim, intermediate, name=f"{name}.blk{i}",
                        kv_cache=(ck, cv), positions=base,
                        block_table=block_table, kv_lengths=ck_len)
    x = layers.rms_norm(x, param_attr=f"{name}.ln_f")
    all_logits = _linear(x, vocab_size, pname=f"{name}.head.w")
    rows = layers.range(0, 1, 1, dtype="int64")              # [1]
    coords = layers.stack([rows, last_off], axis=1)          # [1, 2]
    logits = layers.gather_nd(all_logits, coords)            # [1, V]
    next_token = layers.argmax(logits, axis=-1)              # [1] int64
    return ["chunk_ids", "base", "block_table", "chunk_len",
            "last_off"], \
        {"logits": logits, "next_token": next_token}, cache_names


def build_llama_verify(chunk_len, max_seq_len, num_pages, page_tokens,
                       vocab_size=32000, hidden=4096, num_layers=32,
                       num_heads=32, num_kv_heads=None,
                       intermediate=11008, name="llama"):
    """Speculative-decode verifier: the prefill-continuation forward
    (:func:`build_llama_prefill_chunk`) fetching EVERY row's greedy
    argmax + logits instead of one gathered row.

    The chunk carries ``[pending_token, draft_1..draft_K]`` at
    ``base`` = the slot's committed position; row ``t``'s argmax is
    the token a plain decode step would emit after committing the
    chunk's first ``t+1`` tokens, so the longest prefix with
    ``draft_{t+1} == argmax(row t)`` (plus the one bonus token row
    ``a`` yields) is exactly the plain greedy stream — bit-exact,
    tolerance 0.  Rows write their K/V into the slot's pages as a
    chunked prefill would (``chunk_len`` masks the pad tail to the
    trash page); rejected rows' garbage K/V is masked by the causal
    validity window (``j <= base + t``) and overwritten by the next
    real write at that position, so rollback is page ACCOUNTING, not
    a device-side undo.

    Feeds: ``chunk_ids`` [1, C] int64, ``base`` [1] int32,
    ``block_table`` [1, NP] int32, ``chunk_len`` [1] int32.
    Fetches: ``tokens`` [1, C] int64 (per-row greedy argmax) and
    ``logits`` [1, C, V].  The head projects ALL rows before the
    argmax — gathering hidden rows first would re-tile the
    contraction and drift ~5e-8 off the decode-step GEMM, breaking
    the acceptance contract (see :func:`build_llama_prefill`).

    Returns ``(feed_names, fetches, cache_names)``."""
    from ..framework.core import default_main_program

    num_kv_heads = num_kv_heads or num_heads
    head_dim = hidden // num_heads
    np_slot = max_seq_len // page_tokens
    chunk_ids = layers.data("chunk_ids", [1, chunk_len], dtype="int64",
                            append_batch_size=False)
    base = layers.data("base", [1], dtype="int32",
                       append_batch_size=False)
    block_table = layers.data("block_table", [1, np_slot],
                              dtype="int32", append_batch_size=False)
    ck_len = layers.data("chunk_len", [1], dtype="int32",
                         append_batch_size=False)
    block = default_main_program().global_block()
    cache_names = []
    caches = []
    for i in range(num_layers):
        ck = block.create_var(
            name=f"{name}.pool_k_{i}", persistable=True,
            shape=[num_pages, num_kv_heads, page_tokens, head_dim],
            dtype="float32", stop_gradient=True)
        cv = block.create_var(
            name=f"{name}.pool_v_{i}", persistable=True,
            shape=[num_pages, num_kv_heads, page_tokens, head_dim],
            dtype="float32", stop_gradient=True)
        caches.append((ck, cv))
        cache_names += [ck.name, cv.name]
    x = layers.embedding(chunk_ids, size=[vocab_size, hidden],
                         param_attr=f"{name}.embed")
    for i, (ck, cv) in enumerate(caches):
        x = llama_block(x, hidden, num_heads, num_kv_heads, chunk_len,
                        head_dim, intermediate, name=f"{name}.blk{i}",
                        kv_cache=(ck, cv), positions=base,
                        block_table=block_table, kv_lengths=ck_len)
    x = layers.rms_norm(x, param_attr=f"{name}.ln_f")
    all_logits = _linear(x, vocab_size, pname=f"{name}.head.w")
    tokens = layers.argmax(all_logits, axis=-1)              # [1, C]
    return ["chunk_ids", "base", "block_table", "chunk_len"], \
        {"logits": all_logits, "tokens": tokens}, cache_names
