"""Llama-style decoder LM (config 5 of BASELINE.json: Llama-2-7B, DyGraph
DP + recompute — stretch the fluid-era API to a modern LLM).

Architecture: pre-RMSNorm, fused QKV with GQA, RoPE, causal flash
attention (pallas / ring under sp), SwiGLU MLP, untied LM head.

TPU-first notes:
  * attention via the flash_attention op — pallas kernel single-chip,
    ring attention when the sequence is sharded over `sp`;
  * all projections are single large matmuls (fused QKV, fused gate+up)
    to keep the MXU busy;
  * weights stay fp32 in the scope; AMP lowers matmuls to bf16.
"""
from __future__ import annotations

from .. import layers


def _linear(x, size, name=None):
    return layers.fc(x, size, num_flatten_dims=2, bias_attr=False,
                     name=name)


def llama_block(x, hidden, num_heads, num_kv_heads, seq_len, head_dim,
                intermediate):
    """One decoder layer. x: [B, S, H]."""
    q_size = num_heads * head_dim
    kv_size = num_kv_heads * head_dim
    h = layers.rms_norm(x)
    qkv = _linear(h, q_size + 2 * kv_size)
    q = layers.slice(qkv, axes=[2], starts=[0], ends=[q_size])
    k = layers.slice(qkv, axes=[2], starts=[q_size],
                     ends=[q_size + kv_size])
    v = layers.slice(qkv, axes=[2], starts=[q_size + kv_size],
                     ends=[q_size + 2 * kv_size])

    def heads(t, n):
        t = layers.reshape(t, [0, seq_len, n, head_dim])
        return layers.transpose(t, [0, 2, 1, 3])  # [B,n,S,D]

    q, k, v = heads(q, num_heads), heads(k, num_kv_heads), \
        heads(v, num_kv_heads)
    q = layers.rope(q)
    k = layers.rope(k)
    if num_kv_heads != num_heads:
        # repeat_interleave-style expansion [k1,k1,..,k2,k2,..]: query-head
        # group g maps to kv head g//rep, matching canonical Llama GQA
        # (block-order tile would pair queries with the wrong kv heads).
        rep = num_heads // num_kv_heads

        def expand_kv(t):
            t = layers.reshape(t, [0, num_kv_heads, 1, seq_len, head_dim])
            t = layers.tile(t, [1, 1, rep, 1, 1])
            return layers.reshape(t, [0, num_heads, seq_len, head_dim])

        k, v = expand_kv(k), expand_kv(v)
    attn = layers.flash_attention(q, k, v, causal=True)
    attn = layers.transpose(attn, [0, 2, 1, 3])
    attn = layers.reshape(attn, [0, seq_len, q_size])
    x = layers.elementwise_add(x, _linear(attn, hidden))

    h = layers.rms_norm(x)
    gate_up = _linear(h, 2 * intermediate)
    gate = layers.slice(gate_up, axes=[2], starts=[0], ends=[intermediate])
    up = layers.slice(gate_up, axes=[2], starts=[intermediate],
                      ends=[2 * intermediate])
    ffn = layers.elementwise_mul(layers.silu(gate), up)
    return layers.elementwise_add(x, _linear(ffn, hidden))


def llama(input_ids, vocab_size=32000, hidden=4096, num_layers=32,
          num_heads=32, num_kv_heads=None, intermediate=11008,
          seq_len=2048):
    """Returns logits [B, S, V]. input_ids: [B, S] int64."""
    num_kv_heads = num_kv_heads or num_heads
    head_dim = hidden // num_heads
    x = layers.embedding(input_ids, size=[vocab_size, hidden])
    for _ in range(num_layers):
        x = llama_block(x, hidden, num_heads, num_kv_heads, seq_len,
                        head_dim, intermediate)
    x = layers.rms_norm(x)
    return _linear(x, vocab_size)


def build_llama_train(batch_size=None, seq_len=2048, vocab_size=32000,
                      hidden=4096, num_layers=32, num_heads=32,
                      num_kv_heads=None, intermediate=11008):
    """Causal-LM training graph: feeds input_ids + labels [B, S]."""
    b = -1 if batch_size is None else batch_size
    input_ids = layers.data("input_ids", [b, seq_len], dtype="int64",
                            append_batch_size=False)
    labels = layers.data("labels", [b, seq_len], dtype="int64",
                         append_batch_size=False)
    logits = llama(input_ids, vocab_size, hidden, num_layers, num_heads,
                   num_kv_heads, intermediate, seq_len)
    loss = layers.softmax_with_cross_entropy(
        logits, layers.unsqueeze(labels, [2]))
    mean_loss = layers.mean(layers.squeeze(loss, [2]))
    return ["input_ids", "labels"], {"loss": mean_loss, "logits": logits}
