"""Model zoo covering the tracked benchmark configs (BASELINE.json):

* MNIST LeNet        — models.lenet          (static, single device)
* ResNet-50 ImageNet — models.resnet         (data-parallel)
* BERT/ERNIE-base    — models.bert           (Fleet collective)
* Llama-style LLM    — models.llama          (DP + recompute + tp/sp)
* Wide&Deep CTR      — planned (parameter-server sparse path)

All are built with the paddle_tpu static-graph layers API (the reference
keeps its equivalents in separate repos — PaddleClas/PaddleNLP — plus the
in-tree book tests python/paddle/fluid/tests/book/).
"""
from .lenet import lenet, build_mnist_train  # noqa
from .resnet import resnet, build_resnet_train  # noqa
from .bert import bert_encoder, build_bert_pretrain  # noqa
from .llama import (llama, llama_block, build_llama_train,  # noqa
                    build_llama_forward, build_llama_prefill,
                    build_llama_decode)
from .seq2seq import build_seq2seq_train, build_seq2seq_infer  # noqa
