"""Training telemetry: span tracing, typed metrics, exporters, heartbeat.

The monitor's int counters answer "how many"; this module answers "why
was step N slow" and "is the job alive" without print statements:

* **Span tracer** — :func:`trace_span` ``(name, **attrs)`` context
  manager with a thread-local parent stack, monotonic-clock durations,
  and a bounded ring of completed spans exportable as chrome://tracing /
  Perfetto JSON (:func:`export_chrome_trace`, ``tools/trace_export.py``).
* **Span context** — every span carries a ``trace_id`` (inherited from
  its parent; minted fresh at a root), :func:`current_span` exposes the
  innermost open span as a handoff-able :class:`SpanContext`, and
  ``trace_span(..., parent=ctx)`` re-parents under that context on ANY
  thread — a request keeps one trace_id across queue/thread hops
  (Dapper-style propagation; the serving engine is the main user).
  ``detached=True`` spans skip the thread-local stack entirely (begun
  on one thread, ended on another); ``links=[ctx, ...]`` records
  fan-in/fan-out references to other traces (a serving batch links the
  N request traces it carries).
* **Typed metrics** — :class:`Gauge`, :class:`Timer`, and fixed-bucket
  :class:`Histogram` (p50/p95/p99 summaries) in a
  :class:`MetricsRegistry` alongside the monitor's counters.
* **Exporters** — Prometheus textfile (``metrics.prom``, atomic
  tmp+rename on a ``FLAGS_metrics_interval`` cadence), structured JSONL
  event log (``events.jsonl``: one machine-parseable line per event),
  and a ``heartbeat.json`` health file (pid, step, last-step wall ms,
  examples/sec, jax live-buffer device memory) an external watchdog can
  poll.  All land under ``FLAGS_metrics_dir``; empty dir = no files.

``FLAGS_telemetry=0`` reduces every entry point to a constant-time
no-op: :func:`trace_span` returns a shared no-op context manager,
metric writes return immediately, and no file is ever created — the
hot-path cost of disabled telemetry is one dict lookup.

Exporter writes go through the ``metrics_write`` fault-injection site
(``paddle_tpu/fault.py``) and NEVER raise into the training loop: an
I/O failure bumps ``telemetry_write_failures`` and is logged.

Metrics emitted by this module itself: ``telemetry_write_failures``
(counter), ``telemetry_events_dropped`` (counter: JSONL lines lost to
I/O faults).  Instrumented metrics are documented in their home modules
and in the README stat catalog ("Observability" section).
"""
from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import fault
from .flags import flag_value
from .monitor import monitor as _monitor
from .monitor import process_start_time, stat_add

__all__ = ["SpanContext", "new_trace_id", "trace_span", "span_begin",
           "span_end", "current_span", "get_spans", "clear_spans",
           "span_tree", "counter_sample", "get_counter_samples",
           "export_chrome_trace", "spans_to_chrome_events", "Gauge",
           "Timer", "Histogram", "MetricsRegistry", "metrics",
           "gauge_set", "histogram_observe", "timer", "log_event",
           "note_step", "prometheus_text", "write_prometheus",
           "write_heartbeat", "maybe_flush", "flush", "enabled"]

logger = logging.getLogger("paddle_tpu.telemetry")

# maps time.monotonic() to the epoch so chrome-trace timestamps are
# real wall-clock times while durations stay monotonic
_EPOCH_OFFSET = time.time() - time.monotonic()


def enabled() -> bool:
    """Master switch (``FLAGS_telemetry``): one dict lookup."""
    return bool(flag_value("FLAGS_telemetry"))


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------

class SpanContext:
    """The handoff-able identity of a span: ``(trace_id, span_id)``.

    Capture it on one thread (:func:`current_span` or
    ``span.context()``), pass it across a queue / thread-pool hop, and
    re-parent with ``trace_span(..., parent=ctx)`` — the child lands in
    the same trace regardless of which thread runs it."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id

    def __eq__(self, other):
        return (isinstance(other, SpanContext)
                and other.trace_id == self.trace_id
                and other.span_id == self.span_id)

    def __hash__(self):
        return hash((self.trace_id, self.span_id))

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def __repr__(self):
        return f"SpanContext({self.trace_id!r}, {self.span_id})"


_trace_seq = [0]
_trace_seq_lock = threading.Lock()


def new_trace_id() -> str:
    """Process-unique 16-hex-char trace id (pid + sequence: two
    processes writing one metrics dir cannot collide).  Spans mint one
    automatically at trace roots; the serving engine also stamps
    UNsampled requests with one so access-log lines and histogram
    exemplars still name the request."""
    with _trace_seq_lock:
        _trace_seq[0] += 1
        n = _trace_seq[0]
    return f"{os.getpid() & 0xffffffff:08x}{n & 0xffffffff:08x}"


class Span:
    """One completed (or in-flight) traced region.

    Durations come from ``time.monotonic()``; ``ts``/``dur`` export as
    chrome-trace microseconds.  ``parent_id`` is the span id of the
    enclosing :func:`trace_span` on the same thread — or of the
    explicit ``parent=SpanContext`` handed across a thread hop — and
    None at a root, so the tree reconstructs from the flat ring.
    ``trace_id`` is inherited from the parent (fresh at a root): every
    span of one request shares it.  ``links`` are SpanContexts of
    OTHER traces this span fans in from (a serving batch links the
    requests it serves)."""

    __slots__ = ("name", "attrs", "span_id", "parent_id", "trace_id",
                 "links", "tid", "start", "end")
    _next_id = [1]
    _id_lock = threading.Lock()

    def __init__(self, name: str, attrs: Dict[str, Any], parent_id, tid,
                 trace_id: Optional[str] = None, links=None):
        self.name = name
        self.attrs = attrs
        with Span._id_lock:
            self.span_id = Span._next_id[0]
            Span._next_id[0] += 1
        self.parent_id = parent_id
        self.trace_id = trace_id or new_trace_id()
        self.links: Tuple[SpanContext, ...] = tuple(links or ())
        self.tid = tid
        self.start = time.monotonic()
        self.end: Optional[float] = None

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    @property
    def duration_ms(self) -> Optional[float]:
        return None if self.end is None else (self.end - self.start) * 1e3

    def to_event(self) -> dict:
        """Chrome-trace complete ('X') event."""
        args = dict(self.attrs, span_id=self.span_id,
                    parent_id=self.parent_id, trace_id=self.trace_id)
        if self.links:
            args["links"] = [c.to_dict() for c in self.links]
        return {"ph": "X", "name": self.name, "cat": "paddle_tpu",
                "pid": os.getpid(), "tid": self.tid,
                "ts": (self.start + _EPOCH_OFFSET) * 1e6,
                "dur": ((self.end or time.monotonic()) - self.start) * 1e6,
                "args": args}

    def to_tracez(self, t0: Optional[float] = None) -> dict:
        """Compact JSON shape for the live ``/tracez`` endpoint."""
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "tid": self.tid,
                "start_ms": round((self.start - (t0 or 0.0)) * 1e3, 3),
                "duration_ms": None if self.end is None
                else round(self.duration_ms, 3),
                "attrs": dict(self.attrs),
                "links": [c.to_dict() for c in self.links]}

    def __repr__(self):
        d = self.duration_ms
        return (f"Span({self.name!r}, id={self.span_id}, "
                f"parent={self.parent_id}, trace={self.trace_id}, "
                f"{'open' if d is None else f'{d:.3f}ms'})")


_tls = threading.local()
_ring_lock = threading.Lock()
_ring: Optional[deque] = None


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def _get_ring() -> deque:
    global _ring
    if _ring is None:
        with _ring_lock:
            if _ring is None:
                cap = int(flag_value("FLAGS_trace_buffer_size") or 4096)
                _ring = deque(maxlen=max(1, cap))
    return _ring


class _NoopSpan:
    """Shared do-nothing context manager for FLAGS_telemetry=0."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _SpanCtx:
    __slots__ = ("_span",)

    def __init__(self, span: Span):
        self._span = span

    def __enter__(self):
        return self._span

    def __exit__(self, *exc):
        span_end(self._span)
        return False


def span_begin(name: str, parent: Optional[SpanContext] = None,
               links=None, detached: bool = False,
               trace_id: Optional[str] = None,
               **attrs) -> Optional[Span]:
    """Open a span without a ``with`` block (executor hot path); pair
    with :func:`span_end`.  Returns None when telemetry is disabled.

    ``parent`` — an explicit :class:`SpanContext` overrides the
    thread-local stack: the span joins that context's trace (same
    trace_id, parented under its span_id) even on a different thread.
    ``detached=True`` keeps the span OFF this thread's parent stack —
    required when the span will be ended on another thread (ending a
    stacked span from elsewhere would strand it), or when it outlives
    the caller (a request root span spanning submit→respond must not
    adopt later same-thread spans as children).
    ``links`` — SpanContexts of other traces to reference.
    ``trace_id`` — adopt an externally-minted trace id at a root span
    (the cross-process propagation half: a router/replica hop carries
    the id in a header and both tiers' spans join one trace).  Ignored
    when a parent supplies the trace."""
    if not enabled():
        return None
    if parent is not None:
        parent_id, trace_id = parent.span_id, parent.trace_id
    else:
        stack = _stack()
        top = stack[-1] if stack else None
        parent_id = top.span_id if top is not None else None
        if top is not None:
            trace_id = top.trace_id
    span = Span(name, attrs, parent_id, threading.get_ident(),
                trace_id=trace_id, links=links)
    if not detached:
        _stack().append(span)
    return span


def span_end(span: Optional[Span]):
    """Close `span`, recording it in the ring.  Safe from any thread:
    a span on the CURRENT thread's stack unwinds it (everything left
    open above it by an exception is closed and recorded too); a
    detached or cross-thread span is closed directly.  Double-ends are
    no-ops (a span is recorded at most once)."""
    if span is None:
        return
    stack = _stack()
    if span not in stack:
        # detached span, or a stack span being ended from another
        # thread (the queue/thread-hop half of trace propagation)
        if span.end is None:
            span.end = time.monotonic()
            ring = _get_ring()  # before the lock: _get_ring takes it
            with _ring_lock:
                ring.append(span)
        return
    now = time.monotonic()
    ring = _get_ring()
    while stack:
        top = stack.pop()
        # a span another thread already ended keeps its recorded
        # duration and must not be appended to the ring twice
        if top.end is None:
            top.end = now
            with _ring_lock:
                ring.append(top)
        if top is span:
            break


def current_span() -> Optional[SpanContext]:
    """The innermost open span on THIS thread as a handoff-able
    :class:`SpanContext` (None when nothing is open or telemetry is
    off).  Capture before a queue/thread hop, re-attach on the far
    side with ``trace_span(..., parent=ctx)``."""
    if not enabled():
        return None
    stack = getattr(_tls, "stack", None)
    return stack[-1].context() if stack else None


def trace_span(name: str, parent: Optional[SpanContext] = None,
               links=None, **attrs):
    """``with trace_span("ckpt/write", step=n): ...`` — times the block
    on the monotonic clock and records a :class:`Span` with the current
    thread's innermost open span as parent — or, with ``parent=ctx``,
    under that explicit :class:`SpanContext`'s trace regardless of
    thread.  A no-op (shared singleton, no allocation beyond the call)
    under ``FLAGS_telemetry=0``."""
    if not enabled():
        return _NOOP
    return _SpanCtx(span_begin(name, parent=parent, links=links, **attrs))


def get_spans() -> List[Span]:
    """Completed spans, oldest first (bounded by
    ``FLAGS_trace_buffer_size``)."""
    with _ring_lock:
        return list(_ring) if _ring is not None else []


def clear_spans():
    global _ring, _counter_ring
    with _ring_lock:
        _ring = None
        _counter_ring = None
    _tls.stack = []


def span_tree(spans: Optional[List[Span]] = None) -> List[dict]:
    """Reconstruct the forest from a flat span list: returns root nodes
    as ``{"span": Span, "children": [...]}``, children in completion
    order."""
    spans = get_spans() if spans is None else spans
    nodes = {s.span_id: {"span": s, "children": []} for s in spans}
    roots = []
    for s in spans:
        node = nodes[s.span_id]
        parent = nodes.get(s.parent_id)
        (parent["children"] if parent else roots).append(node)
    return roots


def spans_to_chrome_events(spans: Optional[List[Span]] = None) -> List[dict]:
    return [s.to_event() for s in (get_spans() if spans is None else spans)]


# ---------------------------------------------------------------------------
# counter samples (Perfetto counter tracks, e.g. the HBM timeline)
# ---------------------------------------------------------------------------

_counter_ring: Optional[deque] = None


def _get_counter_ring() -> deque:
    global _counter_ring
    if _counter_ring is None:
        with _ring_lock:
            if _counter_ring is None:
                cap = int(flag_value("FLAGS_trace_buffer_size") or 4096)
                _counter_ring = deque(maxlen=max(1, cap))
    return _counter_ring


def counter_sample(name: str, series):
    """Record one point of a Perfetto **counter track** (chrome-trace
    'C' phase): ``series`` is a value or a ``{series_name: value}``
    dict (multiple series render stacked on one track — the HBM
    sampler emits ``{"total": ..., "dev0": ..., ...}``).  Bounded ring
    (``FLAGS_trace_buffer_size``), no-op with telemetry off."""
    if not enabled():
        return
    if not isinstance(series, dict):
        series = {"value": float(series)}
    ring = _get_counter_ring()
    sample = (name, time.monotonic(),
              {k: float(v) for k, v in series.items()})
    with _ring_lock:
        ring.append(sample)


def get_counter_samples() -> List[tuple]:
    """``(name, monotonic_ts, {series: value})`` tuples, oldest
    first."""
    with _ring_lock:
        return list(_counter_ring) if _counter_ring is not None else []


def counters_to_chrome_events() -> List[dict]:
    return [{"ph": "C", "name": name, "cat": "paddle_tpu",
             "pid": os.getpid(), "tid": 0,
             "ts": (t + _EPOCH_OFFSET) * 1e6, "args": dict(series)}
            for name, t, series in get_counter_samples()]


def export_chrome_trace(path: str,
                        spans: Optional[List[Span]] = None) -> str:
    """Write the span ring as chrome://tracing / Perfetto JSON
    (atomic tmp+rename; survives injected metrics_write faults).
    Serialization itself honors the never-raise contract too: span
    attrs that aren't JSON-native (np scalars, paths) stringify via
    ``default=str``, and anything still unserializable drops the export
    (``telemetry_write_failures``) instead of killing the step."""
    events = spans_to_chrome_events(spans)
    if spans is None:
        # live export: include counter-track samples (HBM timeline)
        events = events + counters_to_chrome_events()
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    try:
        text = json.dumps(doc, default=str)
    except (TypeError, ValueError) as e:
        stat_add("telemetry_write_failures")
        logger.warning("trace export %s failed to serialize: %s", path, e)
        return path
    _atomic_write(path, text)
    return path


# ---------------------------------------------------------------------------
# typed metrics
# ---------------------------------------------------------------------------

class Gauge:
    """Last-value-wins float metric (feed-ring occupancy, examples/sec,
    resume duration...)."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float):
        with self._lock:
            self._v = float(v)

    def add(self, v: float):
        with self._lock:
            self._v += float(v)

    def set_max(self, v: float):
        """High-watermark update: keep the max of the current value and
        ``v`` (queue-depth peaks under bursty load — a sampled gauge
        only shows the depth at publish instants and misses the spikes
        that actually shed requests)."""
        v = float(v)
        with self._lock:
            if v > self._v:
                self._v = v

    def get(self) -> float:
        with self._lock:
            return self._v


# default buckets: milliseconds, 0.1ms .. 60s (fixed so two processes'
# histograms merge bucket-for-bucket)
DEFAULT_BUCKETS_MS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
    1000, 2500, 5000, 10000, 30000, 60000)

# recent-observation window exemplars are drawn from; top EXEMPLARS by
# value of this window = "the trace ids of recent slow samples"
_EXEMPLAR_WINDOW = 64
_EXEMPLAR_KEEP = 5


def _flag_buckets() -> Optional[Tuple[float, ...]]:
    """``FLAGS_histogram_buckets``: comma-separated upper bounds (ms)
    overriding DEFAULT_BUCKETS_MS for histograms created without
    explicit buckets.  Malformed specs fall back to the default (a bad
    flag must not take down the job)."""
    spec = flag_value("FLAGS_histogram_buckets")
    if not spec:
        return None
    try:
        vals = tuple(float(x) for x in str(spec).split(",") if x.strip())
    except ValueError:
        logger.warning("FLAGS_histogram_buckets %r is not a comma-"
                       "separated float list; using defaults", spec)
        return None
    return vals or None


class Histogram:
    """Fixed-bucket histogram with percentile summaries.

    Buckets are upper bounds (a +inf overflow bucket is implicit;
    its population is exposed as :meth:`overflow_count`).  Percentiles
    interpolate linearly inside the chosen bucket; an estimate landing
    in the overflow bucket is *censored* — reported as the top finite
    bucket edge and flagged, never extrapolated (the true value is
    only known to be ``> buckets[-1]``).  O(len(buckets)) memory
    forever.  ``observe(v, trace_id=...)`` additionally retains
    exemplars: the trace ids of recent slow samples, linking a latency
    percentile back to a concrete request trace.
    """

    __slots__ = ("name", "buckets", "_counts", "_sum", "_count", "_min",
                 "_max", "_lock", "_recent_ex")

    def __init__(self, name: str, buckets: Tuple[float, ...] = None):
        self.name = name
        self.buckets = tuple(sorted(buckets or _flag_buckets()
                                    or DEFAULT_BUCKETS_MS))
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()
        self._recent_ex: deque = deque(maxlen=_EXEMPLAR_WINDOW)

    def observe(self, v: float, trace_id: Optional[str] = None):
        v = float(v)
        i = 0
        for i, ub in enumerate(self.buckets):
            if v <= ub:
                break
        else:
            i = len(self.buckets)  # overflow bucket
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if trace_id is not None:
                self._recent_ex.append((v, trace_id, time.time()))

    def overflow_count(self) -> int:
        """Observations above the top finite bucket (the implicit +Inf
        bucket's own population)."""
        with self._lock:
            return self._counts[-1]

    def exemplars(self, k: int = _EXEMPLAR_KEEP) -> List[dict]:
        """The slowest ``k`` of the recent exemplar window, value-desc:
        ``{"value", "trace_id", "ts"}`` — the trace to pull up when the
        p99 looks wrong."""
        with self._lock:
            recent = list(self._recent_ex)
        recent.sort(key=lambda e: e[0], reverse=True)
        return [{"value": round(v, 4), "trace_id": t, "ts": round(ts, 3)}
                for v, t, ts in recent[:k]]

    def percentile(self, p: float, with_censor: bool = False):
        """p in [0, 100]; linear interpolation within the bucket.  An
        estimate in the overflow bucket returns the top bucket edge;
        ``with_censor=True`` returns ``(value, censored)`` so callers
        can mark it +Inf-censored instead of trusting the clamp."""
        with self._lock:
            counts, total = list(self._counts), self._count
            lo, hi = self._min, self._max
        censored = False
        if total == 0:
            return (0.0, censored) if with_censor else 0.0
        rank = p / 100.0 * total
        seen = 0.0
        value = hi
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if seen + c >= rank:
                if i == len(self.buckets):
                    # overflow bucket: the estimate is only a lower
                    # bound — report the censoring edge, not a guess
                    # interpolated toward one extreme max
                    value, censored = float(self.buckets[-1]), True
                    break
                b_lo = self.buckets[i - 1] if i > 0 else min(lo, 0.0)
                b_hi = self.buckets[i]
                b_lo, b_hi = max(b_lo, min(lo, b_hi)), min(b_hi, hi)
                frac = (rank - seen) / c
                value = b_lo + (b_hi - b_lo) * min(max(frac, 0.0), 1.0)
                break
            seen += c
        else:
            censored = counts[-1] > 0 and hi > self.buckets[-1]
        return (value, censored) if with_censor else value

    def summary(self) -> dict:
        with self._lock:
            if self._count == 0:
                return {"count": 0, "sum": 0.0}
            base = {"count": self._count, "sum": round(self._sum, 4),
                    "min": round(self._min, 4), "max": round(self._max, 4),
                    "mean": round(self._sum / self._count, 4),
                    "overflow": self._counts[-1]}
        censored = []
        for p in (50, 95, 99):
            v, cens = self.percentile(p, with_censor=True)
            base[f"p{p}"] = round(v, 4)
            if cens:
                censored.append(f"p{p}")
        if censored:
            # these percentiles sit in the +Inf overflow bucket: the
            # value is the top bucket edge (a floor, not an estimate)
            base["censored"] = censored
        ex = self.exemplars()
        if ex:
            base["exemplars"] = ex
        return base

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """(upper_bound, cumulative_count) pairs, +inf last (Prometheus
        histogram exposition)."""
        with self._lock:
            counts = list(self._counts)
        out, cum = [], 0
        for ub, c in zip(self.buckets, counts):
            cum += c
            out.append((ub, cum))
        out.append((math.inf, cum + counts[-1]))
        return out


class Timer:
    """Histogram-backed duration metric::

        with metrics.timer("checkpoint_write_ms").time():
            ...
    """

    __slots__ = ("hist",)

    def __init__(self, hist: Histogram):
        self.hist = hist

    def time(self):
        return _TimerCtx(self.hist)

    def observe_ms(self, ms: float):
        self.hist.observe(ms)


class _TimerCtx:
    __slots__ = ("_hist", "_t0")

    def __init__(self, hist: Histogram):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._hist.observe((time.monotonic() - self._t0) * 1e3)
        return False


class MetricsRegistry:
    """Typed-metric sibling of :class:`monitor.StatRegistry`: named
    gauges, histograms, and timers, with a combined :meth:`snapshot`
    that also embeds the monitor's counters.  Thread-safe (lock-guarded
    construction, per-metric locks on mutation)."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "MetricsRegistry":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str,
                  buckets: Tuple[float, ...] = None) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name, buckets)
            return h

    def timer(self, name: str) -> Timer:
        return Timer(self.histogram(name))

    def snapshot(self, reset_counters: bool = False) -> dict:
        """{"counters": {...}, "gauges": {...}, "histograms": {...}} —
        counters via the monitor's atomic publish.  Each histogram entry
        carries its summary plus ``buckets`` (cumulative (le, count)
        pairs), so a snapshot fully renders to Prometheus later without
        touching the live registry."""
        with self._lock:
            gauges = list(self._gauges.items())
            hists = list(self._hists.items())
        return {
            "counters": dict(_monitor.publish(reset=reset_counters)),
            "gauges": {n: g.get() for n, g in sorted(gauges)},
            "histograms": {
                n: dict(h.summary(), buckets=h.cumulative_buckets())
                for n, h in sorted(hists)},
        }


metrics = MetricsRegistry.instance()


def gauge_set(name: str, value: float):
    """Module-level shorthand (no-op when telemetry is off)."""
    if enabled():
        metrics.gauge(name).set(value)


def histogram_observe(name: str, value: float,
                      trace_id: Optional[str] = None):
    """Module-level shorthand; ``trace_id`` retains the observation as
    an exemplar (the trace behind a slow sample)."""
    if enabled():
        metrics.histogram(name).observe(value, trace_id=trace_id)


def timer(name: str):
    """``with timer("ckpt_write_ms"): ...`` — no-op context manager
    when telemetry is off."""
    if not enabled():
        return _NOOP
    return metrics.timer(name).time()


# ---------------------------------------------------------------------------
# step bookkeeping (heartbeat inputs)
# ---------------------------------------------------------------------------

_step_state = {"step": 0, "last_step_ms": None, "examples_per_sec": None,
               "host_ms": None, "last_t": None,
               "started": process_start_time()}
_step_lock = threading.Lock()


def note_step(step: int, host_ms: float, examples: int):
    """Executor per-step hook: feeds the step-duration histogram, the
    throughput gauge, and the heartbeat.

    ``host_ms`` is host wall time spent inside ``Executor.run`` (with
    async dispatch this is dispatch cost, not device step time);
    ``last_step_ms``/``examples_per_sec`` derive from the interval
    between consecutive step completions, which IS the steady-state
    step time even when dispatch runs ahead of the device."""
    if not enabled():
        return
    now = time.monotonic()
    metrics.histogram("executor_step_host_ms").observe(host_ms)
    with _step_lock:
        last_t = _step_state["last_t"]
        _step_state["last_t"] = now
        _step_state["step"] = int(step)
        _step_state["host_ms"] = round(host_ms, 4)
        if last_t is not None and now > last_t:
            dt_ms = (now - last_t) * 1e3
            _step_state["last_step_ms"] = round(dt_ms, 4)
            if examples:
                rate = examples * 1e3 / dt_ms
                prev = _step_state["examples_per_sec"]
                # EMA: smooth over dispatch jitter, converge in ~10 steps
                rate = rate if prev is None else 0.8 * prev + 0.2 * rate
                _step_state["examples_per_sec"] = round(rate, 3)
    if _step_state["examples_per_sec"] is not None:
        metrics.gauge("examples_per_sec").set(
            _step_state["examples_per_sec"])


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def _metrics_dir() -> Optional[str]:
    d = flag_value("FLAGS_metrics_dir")
    return d or None


def _atomic_write(path: str, text: str):
    """tmp + os.replace publish; never raises into the caller (I/O
    failures bump ``telemetry_write_failures``).  Routed through the
    ``metrics_write`` fault site so CI can prove the never-raises
    contract."""
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        if fault.fire("metrics_write") == "raise":
            raise fault.InjectedFault(f"injected metrics write failure "
                                      f"({os.path.basename(path)})")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except OSError as e:
        stat_add("telemetry_write_failures")
        logger.warning("telemetry write %s failed: %s", path, e)
        try:
            os.remove(tmp)
        except OSError:
            pass  # ok: tmp may never have been created


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    return f"paddle_tpu_{out}"


def prometheus_text(snapshot: Optional[dict] = None) -> str:
    """Render a :meth:`MetricsRegistry.snapshot` in the strict
    Prometheus text exposition format: per family one ``# HELP`` and
    one ``# TYPE`` line, then the samples (counters, gauges, and
    cumulative-bucket histograms with ``_sum``/``_count``).  Validated
    by ``tools/check_stat_catalog.py validate_exposition`` in tier-1.
    A passed snapshot renders exactly as captured — nothing is read
    from the live registry."""
    snap = snapshot if snapshot is not None else metrics.snapshot()
    lines = []

    def head(pn: str, kind: str, src: str):
        lines.append(f"# HELP {pn} paddle_tpu {kind} {src} "
                     f"(see README stat catalog)")
        lines.append(f"# TYPE {pn} {kind}")

    for name, v in sorted(snap.get("counters", {}).items()):
        pn = _prom_name(name)
        head(pn, "counter", name)
        lines.append(f"{pn} {v}")
    for name, v in sorted(snap.get("gauges", {}).items()):
        pn = _prom_name(name)
        head(pn, "gauge", name)
        lines.append(f"{pn} {v}")
    for name, h in sorted(snap.get("histograms", {}).items()):
        pn = _prom_name(name)
        head(pn, "histogram", name)
        for ub, cum in h.get("buckets", []):
            le = "+Inf" if math.isinf(ub) else repr(float(ub))
            lines.append(f'{pn}_bucket{{le="{le}"}} {cum}')
        lines.append(f"{pn}_sum {h.get('sum', 0.0)}")
        lines.append(f"{pn}_count {h.get('count', 0)}")
    return "\n".join(lines) + "\n"


def write_prometheus(path: Optional[str] = None) -> Optional[str]:
    if not enabled():
        return None
    d = _metrics_dir()
    if path is None:
        if d is None:
            return None
        path = os.path.join(d, "metrics.prom")
    _atomic_write(path, prometheus_text())
    return path


def _device_memory() -> Optional[dict]:
    """jax live-buffer stats for the heartbeat (None when jax is not
    imported yet — the heartbeat must not force a jax init)."""
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        live = jax.live_arrays()
        return {"live_buffers": len(live),
                "live_bytes": int(sum(int(getattr(a, "nbytes", 0) or 0)
                                      for a in live))}
    except Exception as e:
        logger.debug("live-buffer stats unavailable: %s", e)
        return None


def write_heartbeat(path: Optional[str] = None) -> Optional[str]:
    """``heartbeat.json``: liveness + progress for an external watchdog
    (atomic write; a reader never sees a torn file)."""
    if not enabled():
        return None
    d = _metrics_dir()
    if path is None:
        if d is None:
            return None
        path = os.path.join(d, "heartbeat.json")
    with _step_lock:
        state = dict(_step_state)
    state.pop("last_t", None)
    hb = {"pid": os.getpid(), "time": time.time(),
          "uptime_s": round(time.time() - state.pop("started"), 3),
          "device_memory": _device_memory()}
    hb.update(state)
    _atomic_write(path, json.dumps(hb, indent=1, sort_keys=True))
    return path


# taps the black-box flight recorder (paddle_tpu/blackbox.py) hooks at
# import time; telemetry stays import-independent of blackbox (blackbox
# imports telemetry, never the reverse) so the tap is a plain callable
# attribute, None until blackbox is loaded
_blackbox_event_tap = None   # (kind, fields_dict) -> None
_blackbox_flush_tap = None   # () -> None


def log_event(kind: str, **fields):
    """Append one machine-parseable line to ``events.jsonl``
    (step timings, guard resolutions, checkpoint publishes, restarts).
    No-op without telemetry or a metrics dir; an I/O fault drops the
    line (``telemetry_events_dropped``) instead of raising."""
    if not enabled():
        return
    # the flight recorder mirrors every event into its in-memory ring
    # even without a metrics dir (the ring needs no filesystem; the
    # dump path checks for one itself)
    if _blackbox_event_tap is not None:
        _blackbox_event_tap(kind, fields)
    d = _metrics_dir()
    if d is None:
        return
    rec = {"ts": round(time.time(), 6), "event": kind, "pid": os.getpid()}
    rec.update(fields)
    try:
        if fault.fire("metrics_write") == "raise":
            raise fault.InjectedFault("injected event-log write failure")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "events.jsonl"), "a") as f:
            f.write(json.dumps(rec, sort_keys=True, default=str) + "\n")
    except OSError as e:
        stat_add("telemetry_events_dropped")
        logger.warning("event log write failed: %s", e)


# ---------------------------------------------------------------------------
# flush cadence
# ---------------------------------------------------------------------------

_flush_state = {"last": 0.0}
_flush_lock = threading.Lock()


def _tsdb_sample():
    """Record the live registry into the in-process time-series store
    (:mod:`paddle_tpu.tsdb`) — the windowed-history half of the flush
    cadence.  It needs no metrics dir (the store is in-memory) and is
    itself gated on ``FLAGS_tsdb``."""
    from . import tsdb
    tsdb.sample_registry(metrics)


def maybe_flush() -> bool:
    """Hot-path cadence check: sample the time-series store and flush
    the file exporters if at least ``FLAGS_metrics_interval`` seconds
    passed since the last flush.  Costs one monotonic read + a
    comparison when it's not yet time.  Returns True only when the
    file exporters ran (the tsdb sample also fires on the cadence
    WITHOUT a metrics dir — windowed queries must work in-memory-only
    deployments)."""
    if not enabled():
        return False
    now = time.monotonic()
    # explicit 0.0 means flush every step — `or` would eat it
    interval = flag_value("FLAGS_metrics_interval")
    interval = 10.0 if interval is None else float(interval)
    # lock-free fast path: this runs on EVERY executor step, and with
    # the tsdb in the cadence it now runs even without a metrics dir —
    # the not-yet-time check must cost a read and a compare, not a
    # lock acquisition (double-checked under the lock before firing)
    if now - _flush_state["last"] < interval:
        return False
    with _flush_lock:
        if now - _flush_state["last"] < interval:
            return False
        _flush_state["last"] = now
    if _metrics_dir() is None:
        _tsdb_sample()
        return False
    flush(force=False)  # flush() samples the tsdb too
    return True


def flush(force: bool = True):
    """Write every exporter now: the tsdb sample, Prometheus textfile,
    heartbeat, and the span ring as ``trace.json``.  ``force=True``
    also resets the cadence clock (used at run end:
    TrainGuard.close/finalize, Executor.close)."""
    if not enabled():
        return
    _tsdb_sample()
    # flight-recorder cadence: metric-snapshot ring + rolling dump
    if _blackbox_flush_tap is not None:
        _blackbox_flush_tap()
    d = _metrics_dir()
    if d is None:
        return
    if force:
        with _flush_lock:
            _flush_state["last"] = time.monotonic()
    write_prometheus()
    write_heartbeat()
    export_chrome_trace(os.path.join(d, "trace.json"))
