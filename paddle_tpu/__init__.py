"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle Fluid (~1.8/2.0-beta), built on jax/XLA/pallas/pjit.

Architecture (vs. the reference, see SURVEY.md):
  * Program/Block/Operator IR mirrors fluid's ProgramDesc, but execution
    lowers whole blocks to single XLA computations (no op interpreter).
  * Collectives are sharding annotations + XLA collectives over ICI,
    not NCCL ops.
  * The imperative mode shares the same op lowerings via an eager tracer.
"""
# the lock-order sanitizer must patch threading BEFORE any module
# constructs its locks, so this hook runs first (no-op unless
# FLAGS_debug_lock_order is set in the environment)
from . import locksan as _locksan  # noqa: E402
_locksan.install_from_flag()

from . import ops  # registers the operator library
from .framework.core import (Program, Variable, Parameter, OpRole,  # noqa
                             default_main_program, default_startup_program,
                             program_guard, unique_name, in_dygraph_mode,
                             convert_dtype, grad_var_name, device_guard)
from .framework.executor import (AsyncRunResult, Executor,  # noqa
                                 FetchHandle, Scope, global_scope,
                                 scope_guard)
from .framework.backward import append_backward, gradients  # noqa
from .framework.layer_helper import ParamAttr, WeightNormParamAttr  # noqa
from .framework import initializer  # noqa
from .framework import ir  # noqa
from . import layers  # noqa
from . import optimizer  # noqa
from . import regularizer  # noqa
from . import clip  # noqa
from .layers.tensor import data  # noqa
from . import dygraph  # noqa
from .dygraph import jit  # noqa  (paddle.jit 2.0 namespace)
from .framework.compiler import (CompiledProgram, BuildStrategy,  # noqa
                                 ExecutionStrategy, ParallelExecutor)
from . import distributed  # noqa
from . import contrib  # noqa
from . import io  # noqa
from . import checkpoint  # noqa
from . import reader  # noqa
from .reader import DataLoader, DataFeeder, batch  # noqa
from . import inference  # noqa
from . import serving  # noqa  (dynamic-batching inference engine + HTTP)
from . import profiler  # noqa
from .flags import get_flags, set_flags  # noqa
from . import fault  # noqa  (deterministic fault injection)
from .train_guard import TrainGuard, TrainingInterrupted  # noqa
from . import memory  # noqa
from . import tensor  # noqa  (paddle.tensor 2.0 namespace)
from . import monitor  # noqa  (StatRegistry + graphviz dumps)
from . import telemetry  # noqa  (spans, typed metrics, exporters)
from . import amp  # noqa  (paddle.amp 2.0 namespace)
from . import errors  # noqa
from .errors import EnforceNotMet, enforce  # noqa
from . import vision  # noqa
from . import text  # noqa
from . import metrics  # noqa
from . import dataset  # noqa
from .dataset import DatasetFactory  # noqa
from . import transpiler  # noqa
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig  # noqa
from . import metric  # noqa
from . import nn  # noqa
from . import static  # noqa
from . import hapi  # noqa
from .hapi import Model  # noqa

__version__ = "0.1.0"


# -- device places (API parity; jax owns actual placement) -------------------
class CPUPlace:
    def __repr__(self):
        return "CPUPlace"


class TPUPlace:
    """The TPU device place — the reference's CUDAPlace analog."""

    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def __repr__(self):
        return f"TPUPlace({self.device_id})"


CUDAPlace = TPUPlace  # scripts written for the reference keep working


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    import jax
    return any(d.platform in ("tpu", "axon") for d in jax.devices())


def device_count() -> int:
    import jax
    return jax.device_count()


# fluid-compat namespace: `import paddle_tpu.fluid as fluid`
from . import fluid  # noqa  (must come after the symbols above exist)
