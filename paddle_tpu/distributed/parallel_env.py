"""Process-level distributed environment.

Reference: python/paddle/distributed/parallel.py:57 (init_parallel_env —
TCP exchange of ncclUniqueId, imperative/nccl_context.cc). TPU-native:
multi-host rendezvous is jax.distributed.initialize; within one host, all
chips belong to this process and rank/world refer to *hosts*.
"""
from __future__ import annotations

import os

from ..dygraph.parallel import ParallelEnv  # re-export


def get_rank() -> int:
    import jax
    try:
        return jax.process_index()
    except RuntimeError:
        return int(os.getenv("PADDLE_TRAINER_ID", "0"))


def get_world_size() -> int:
    import jax
    try:
        return jax.process_count()
    except RuntimeError:
        return int(os.getenv("PADDLE_TRAINERS_NUM", "1"))


def init_parallel_env():
    """Bootstrap multi-host jax.distributed from PADDLE_* / coordinator
    env vars; no-op single-host."""
    import jax
    coord = os.getenv("PADDLE_COORDINATOR", os.getenv("JAX_COORDINATOR"))
    nprocs = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
    pid = int(os.getenv("PADDLE_TRAINER_ID", "0"))
    if coord and nprocs > 1:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nprocs, process_id=pid)
    return ParallelEnv()
