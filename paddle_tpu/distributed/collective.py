"""Host-level cross-process collectives.

Reference: the NCCL/gloo eager collectives behind
paddle.distributed.{all_reduce,all_gather,broadcast,barrier}
(collective.py + imperative/nccl_context.cc).  TPU-native: there is no
eager cross-host primitive — a collective is a tiny jitted program over a
one-device-per-process mesh; XLA lowers it onto ICI/DCN.  These helpers
serve the *host-loop* uses (dygraph DataParallel gradient sync, metric
reduction, rendezvous); inside compiled steps, collectives are the c_*
ops / GSPMD shardings.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["all_reduce", "all_gather", "broadcast", "barrier",
           "ReduceOp"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


_MESH_CACHE = {}
_JIT_CACHE = {}


def _world_mesh():
    """One device per process, in process order (cached — the process
    topology is fixed for the life of the runtime)."""
    import jax
    from jax.sharding import Mesh

    if "mesh" not in _MESH_CACHE:
        per_proc = {}
        for d in jax.devices():
            per_proc.setdefault(d.process_index, d)
        nproc = jax.process_count()
        devs = np.array([per_proc[i] for i in range(nproc)])
        _MESH_CACHE["mesh"] = (Mesh(devs, ("w",)),
                               per_proc[jax.process_index()], nproc)
    return _MESH_CACHE["mesh"]


def _sum0(a):
    return a.sum(0)


def _max0(a):
    return a.max(0)


def _min0(a):
    return a.min(0)


def _prod0(a):
    return a.prod(0)


def _ident(a):
    return a


def _take(src):
    def f(a):
        return a[src]
    return f


def _jitted(key, fn, mesh):
    """jit cache keyed by op — a fresh lambda per call would force a
    retrace+recompile on every collective."""
    import jax

    if key not in _JIT_CACHE:
        _JIT_CACHE[key] = jax.jit(fn, out_shardings=_replicated(mesh))
    return _JIT_CACHE[key]


def _global_stack(x, mesh, my_dev, nproc):
    """Stack each process's local array into a [world, ...] global."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = np.asarray(x)
    sh = NamedSharding(mesh, P("w"))
    local = jax.device_put(x[None], my_dev)
    return jax.make_array_from_single_device_arrays(
        (nproc,) + x.shape, sh, [local])


def _replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


def all_reduce(x, op: str = ReduceOp.SUM):
    """Cross-process allreduce of a host array; returns the reduced
    value (identical on every process)."""
    import jax

    x = np.asarray(x)
    mesh, my_dev, nproc = _world_mesh()
    if nproc == 1:
        return x
    garr = _global_stack(x, mesh, my_dev, nproc)
    red = {"sum": _sum0, "max": _max0, "min": _min0, "prod": _prod0}[op]
    out = _jitted(("reduce", op), red, mesh)(garr)
    return np.asarray(out.addressable_shards[0].data)


def all_gather(x):
    """[world, ...] stack of every process's array, on every process."""
    import jax

    x = np.asarray(x)
    mesh, my_dev, nproc = _world_mesh()
    if nproc == 1:
        return x[None]
    garr = _global_stack(x, mesh, my_dev, nproc)
    out = _jitted(("gather",), _ident, mesh)(garr)
    return np.asarray(out.addressable_shards[0].data)


def broadcast(x, src: int = 0):
    import jax

    x = np.asarray(x)
    mesh, my_dev, nproc = _world_mesh()
    if nproc == 1:
        return x
    garr = _global_stack(x, mesh, my_dev, nproc)
    out = _jitted(("broadcast", src), _take(src), mesh)(garr)
    return np.asarray(out.addressable_shards[0].data)


def barrier():
    all_reduce(np.zeros((1,), "float32"))
