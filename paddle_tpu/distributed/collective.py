"""Host-level cross-process collectives.

Reference: the NCCL/gloo eager collectives behind
paddle.distributed.{all_reduce,all_gather,broadcast,barrier}
(collective.py + imperative/nccl_context.cc).  TPU-native: there is no
eager cross-host primitive — a collective is a tiny jitted program over a
one-device-per-process mesh; XLA lowers it onto ICI/DCN.  These helpers
serve the *host-loop* uses (dygraph DataParallel gradient sync, metric
reduction, rendezvous); inside compiled steps, collectives are the c_*
ops / GSPMD shardings.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["all_reduce", "all_gather", "broadcast", "barrier",
           "ReduceOp"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


def _world_mesh():
    """One device per process, in process order."""
    import jax
    from jax.sharding import Mesh

    per_proc = {}
    for d in jax.devices():
        per_proc.setdefault(d.process_index, d)
    nproc = jax.process_count()
    devs = np.array([per_proc[i] for i in range(nproc)])
    return Mesh(devs, ("w",)), per_proc[jax.process_index()], nproc


def _global_stack(x, mesh, my_dev, nproc):
    """Stack each process's local array into a [world, ...] global."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = np.asarray(x)
    sh = NamedSharding(mesh, P("w"))
    local = jax.device_put(x[None], my_dev)
    return jax.make_array_from_single_device_arrays(
        (nproc,) + x.shape, sh, [local])


def _replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


def all_reduce(x, op: str = ReduceOp.SUM):
    """Cross-process allreduce of a host array; returns the reduced
    value (identical on every process)."""
    import jax

    x = np.asarray(x)
    mesh, my_dev, nproc = _world_mesh()
    if nproc == 1:
        return x
    garr = _global_stack(x, mesh, my_dev, nproc)
    red = {"sum": lambda a: a.sum(0), "max": lambda a: a.max(0),
           "min": lambda a: a.min(0), "prod": lambda a: a.prod(0)}[op]
    out = jax.jit(red, out_shardings=_replicated(mesh))(garr)
    return np.asarray(out.addressable_shards[0].data)


def all_gather(x):
    """[world, ...] stack of every process's array, on every process."""
    import jax

    x = np.asarray(x)
    mesh, my_dev, nproc = _world_mesh()
    if nproc == 1:
        return x[None]
    garr = _global_stack(x, mesh, my_dev, nproc)
    out = jax.jit(lambda a: a, out_shardings=_replicated(mesh))(garr)
    return np.asarray(out.addressable_shards[0].data)


def broadcast(x, src: int = 0):
    import jax

    x = np.asarray(x)
    mesh, my_dev, nproc = _world_mesh()
    if nproc == 1:
        return x
    garr = _global_stack(x, mesh, my_dev, nproc)
    out = jax.jit(lambda a: a[src],
                  out_shardings=_replicated(mesh))(garr)
    return np.asarray(out.addressable_shards[0].data)


def barrier():
    all_reduce(np.zeros((1,), "float32"))
