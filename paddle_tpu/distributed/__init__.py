"""Distributed training (reference python/paddle/distributed/).

fleet          -- collective/PS training orchestration (fleet 2.0 API)
launch         -- process launcher (python -m paddle_tpu.distributed.launch)
collective fns -- all_reduce/all_gather/broadcast for dygraph/static
"""
from . import fleet  # noqa
from .parallel_env import (init_parallel_env, get_rank, get_world_size,  # noqa
                           ParallelEnv)
