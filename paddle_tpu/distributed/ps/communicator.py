"""Trainer-side communicator: sync / async / geo gradient traffic.

Reference: operators/distributed/communicator.h — AsyncCommunicator
(:195 send queue + merge thread), HalfAsyncCommunicator (:268 barrier'd
k-step merge), SyncCommunicator (:340), GeoCommunicator (:383 delta
push / pull of touched rows).  Python/launch surface:
fleet.init_worker() starts it, fleet.stop_worker() flushes and stops.

The communicator sits between the PSTrainer (which fetches gradients
from the XLA step) and a client (LocalClient / RPCClient /
ShardedClient).  Modes:

  * sync:   push immediately, server applies optimizer, pull fresh next
            step; a server barrier fences every trainer per step.
  * async:  pushes enqueue; a background thread merges duplicate ids and
            sends; pulls read whatever the server has (HogWild-style
            staleness, the reference's default CTR mode).
  * geo:    trainers train *locally* (local sparse optimizer applies the
            update) and every k steps exchange parameter deltas with the
            server, which accumulates them; then the trainer adopts the
            server value.  Dense params follow the same delta protocol.
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .table import SparseTable, TableConfig, merge_sparse_grad

__all__ = ["Communicator", "AsyncCommunicator", "HalfAsyncCommunicator",
           "GeoCommunicator", "make_communicator"]


class Communicator:
    """Sync mode: every push applied before the call returns."""

    mode = "sync"

    def __init__(self, client):
        self.client = client
        self.running = False

    def start(self):
        self.running = True

    def stop(self):
        self.flush()
        self.running = False

    def flush(self):
        pass

    def step_done(self):
        """Called by the trainer once per training step (geo keys its
        k_steps interval on this, not on push counts)."""

    # -- sparse -------------------------------------------------------------
    def pull_sparse(self, table: str, ids: np.ndarray) -> np.ndarray:
        return self.client.pull_sparse(table, ids)

    def push_sparse(self, table: str, ids: np.ndarray, grads: np.ndarray,
                    lr_scale: float = 1.0):
        self.client.push_sparse(table, ids, grads, lr_scale)

    # -- dense --------------------------------------------------------------
    def pull_dense(self, name: str) -> np.ndarray:
        return self.client.pull_dense(name)

    def push_dense(self, name: str, grad: np.ndarray,
                   lr_scale: float = 1.0):
        self.client.push_dense(name, grad, lr_scale)

    def barrier(self):
        self.client.barrier()


class AsyncCommunicator(Communicator):
    """Async mode: a send thread drains a bounded queue, merging rows of
    duplicate ids before sending (communicator.h:195 MergeVars +
    send_threadpool)."""

    mode = "async"

    def __init__(self, client, send_queue_size: int = 64,
                 merge_steps: int = 1):
        super().__init__(client)
        self._q: "queue.Queue[Optional[Tuple]]" = queue.Queue(
            maxsize=send_queue_size)
        self._thread: Optional[threading.Thread] = None
        self.merge_steps = max(1, merge_steps)
        self._err: Optional[BaseException] = None

    def start(self):
        self.running = True
        self._thread = threading.Thread(target=self._send_loop, daemon=True)
        self._thread.start()

    def stop(self):
        if self.running:
            self.running = False
            self._q.put(None)
            if self._thread is not None:
                self._thread.join(timeout=30)
        if self._err is not None:
            raise self._err

    def flush(self):
        self._q.join()

    def push_sparse(self, table, ids, grads, lr_scale=1.0):
        self._q.put(("sparse", table, np.asarray(ids, np.int64).ravel(),
                     np.asarray(grads, np.float32), lr_scale))

    def push_dense(self, name, grad, lr_scale=1.0):
        self._q.put(("dense", name, None, np.asarray(grad, np.float32),
                     lr_scale))

    def _send_loop(self):
        pending: List[Tuple] = []
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                break
            pending.append(item)
            # opportunistically batch whatever is queued, up to merge_steps
            while len(pending) < self.merge_steps:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._q.task_done()
                    self._drain(pending)
                    return
                pending.append(nxt)
            self._drain(pending)
            pending = []

    def _drain(self, items: List[Tuple]):
        # merge per destination before sending (MergeVars); merged sends
        # use the latest lr_scale seen for that destination
        sparse: Dict[str, List[Tuple[np.ndarray, np.ndarray]]] = {}
        dense: Dict[str, List[np.ndarray]] = {}
        scales: Dict[str, float] = {}
        for kind, name, ids, g, lr_scale in items:
            scales[name] = lr_scale
            if kind == "sparse":
                sparse.setdefault(name, []).append((ids, g))
            else:
                dense.setdefault(name, []).append(g)
        try:
            for name, parts in sparse.items():
                ids = np.concatenate([p[0] for p in parts])
                grads = np.concatenate(
                    [p[1].reshape(len(p[0]), -1) for p in parts])
                uids, merged = merge_sparse_grad(ids, grads)
                self.client.push_sparse(name, uids, merged,
                                        lr_scale=scales[name])
            for name, gs in dense.items():
                g = gs[0] if len(gs) == 1 else np.sum(gs, axis=0)
                self.client.push_dense(name, g, lr_scale=scales[name])
        except BaseException as e:  # surfaced on stop()
            self._err = e
        finally:
            for _ in items:
                self._q.task_done()


class HalfAsyncCommunicator(Communicator):
    """Barrier'd k-step batch (reference communicator.h:340
    HalfAsyncCommunicator): pushes buffer locally; every ``k_steps``
    step_done() merges duplicate ids, sends the whole batch, and fences
    all trainers on the server barrier. Staleness is bounded by the
    window (unlike async) without sync's per-step server round trip.
    Pulls read the server state directly — within a window they see
    values at most k steps old, the defining half-async contract."""

    mode = "half_async"

    def __init__(self, client, k_steps: int = 10):
        super().__init__(client)
        self.k_steps = max(1, k_steps)
        self._pending: List[Tuple] = []
        self._step_count = 0
        self._lock = threading.Lock()

    def push_sparse(self, table, ids, grads, lr_scale=1.0):
        with self._lock:
            self._pending.append(
                ("sparse", table, np.asarray(ids, np.int64).ravel(),
                 np.asarray(grads, np.float32), lr_scale))

    def push_dense(self, name, grad, lr_scale=1.0):
        with self._lock:
            self._pending.append(
                ("dense", name, None, np.asarray(grad, np.float32),
                 lr_scale))

    def step_done(self):
        with self._lock:
            self._step_count += 1
            fence = self._step_count % self.k_steps == 0
            if fence:
                self._send_locked()
        if fence:
            # barrier OUTSIDE the lock: it blocks on other trainers
            self.client.barrier()

    def flush(self):
        with self._lock:
            self._send_locked()

    def _send_locked(self):
        sparse: Dict[str, List[Tuple[np.ndarray, np.ndarray]]] = {}
        dense: Dict[str, List[np.ndarray]] = {}
        scales: Dict[str, float] = {}
        for kind, name, ids, g, lr_scale in self._pending:
            scales[name] = lr_scale
            if kind == "sparse":
                sparse.setdefault(name, []).append((ids, g))
            else:
                dense.setdefault(name, []).append(g)
        self._pending = []
        for name, parts in sparse.items():
            ids = np.concatenate([p[0] for p in parts])
            grads = np.concatenate(
                [p[1].reshape(len(p[0]), -1) for p in parts])
            uids, merged = merge_sparse_grad(ids, grads)
            self.client.push_sparse(name, uids, merged,
                                    lr_scale=scales[name])
        for name, gs in dense.items():
            g = gs[0] if len(gs) == 1 else np.sum(gs, axis=0)
            self.client.push_dense(name, g, lr_scale=scales[name])


class GeoCommunicator(Communicator):
    """Geo-SGD: local training + k-step delta exchange.

    The trainer holds a local mirror of each sparse table (same config +
    seed, so lazily-materialized rows match the server's deterministic
    init) and a *base* snapshot of every row it has touched.  Updates are
    applied locally; every ``k_steps`` pushes, the delta
    ``local - base`` for touched ids goes to the server (which adds it),
    then the trainer adopts the server's value as the new local + base —
    communicator.h:383 GeoCommunicator / geo_sgd_transpiler semantics.
    """

    mode = "geo"

    def __init__(self, client, sparse_configs: Sequence[TableConfig],
                 k_steps: int = 100):
        super().__init__(client)
        self.k_steps = max(1, k_steps)
        self.local: Dict[str, SparseTable] = {
            c.name: SparseTable(c) for c in sparse_configs}
        self.base: Dict[str, SparseTable] = {
            c.name: SparseTable(c) for c in sparse_configs}
        self._touched: Dict[str, set] = {c.name: set()
                                         for c in sparse_configs}
        self._dense_local: Dict[str, np.ndarray] = {}
        self._dense_base: Dict[str, np.ndarray] = {}
        self._dense_lr: Dict[str, float] = {}
        self._step_count = 0
        self._lock = threading.Lock()

    # dense params in geo mode are trainer-optimized locally; the trainer
    # registers its local view so deltas can be computed.
    def register_dense(self, name: str, value: np.ndarray, lr: float):
        self._dense_local[name] = np.array(value, "float32")
        self._dense_base[name] = np.array(value, "float32")
        self._dense_lr[name] = lr

    def pull_sparse(self, table, ids):
        return self.local[table].pull(ids)

    def push_sparse(self, table, ids, grads, lr_scale=1.0):
        with self._lock:
            ids = np.asarray(ids, np.int64).ravel()
            # snapshot base rows for ids never seen before the update
            tbl, base = self.local[table], self.base[table]
            new = [i for i in np.unique(ids) if int(i)
                   not in self._touched[table]]
            if new:
                base.load(np.asarray(new, np.int64),
                          tbl.pull(np.asarray(new, np.int64)))
                self._touched[table].update(int(i) for i in new)
            tbl.push(ids, grads, lr_scale=lr_scale)

    def pull_dense(self, name):
        return self._dense_local[name].copy()

    def push_dense(self, name, grad, lr_scale=1.0):
        with self._lock:
            g = np.asarray(grad, "float32").reshape(
                self._dense_local[name].shape)
            self._dense_local[name] -= self._dense_lr[name] * lr_scale * g

    def step_done(self):
        with self._lock:
            self._step_count += 1
            if self._step_count % self.k_steps == 0:
                self._sync_locked()

    def flush(self):
        with self._lock:
            self._sync_locked()

    def _sync_locked(self):
        for name, tbl in self.local.items():
            touched = self._touched[name]
            if touched:
                ids = np.fromiter(touched, np.int64, len(touched))
                delta = tbl.pull(ids) - self.base[name].pull(ids)
                self.client.push_sparse_delta(name, ids, delta)
                fresh = self.client.pull_sparse(name, ids)
                tbl.load(ids, fresh)
                self.base[name].load(ids, fresh)
                touched.clear()
        for name, local in self._dense_local.items():
            delta = local - self._dense_base[name]
            if np.any(delta):
                self.client.push_dense_delta(name, delta)
                fresh = self.client.pull_dense(name).reshape(local.shape)
                self._dense_local[name] = fresh.copy()
                self._dense_base[name] = fresh.copy()


def make_communicator(mode: str, client, sparse_configs=(),
                      k_steps: int = 100, **kw):
    """Factory keyed by DistributedStrategy: a_sync=False -> sync,
    a_sync=True -> async, a_sync + k_steps>0 -> geo (reference
    fleet/base/distributed_strategy.py a_sync_configs)."""
    if mode == "sync":
        return Communicator(client)
    if mode == "async":
        return AsyncCommunicator(client, **kw)
    if mode == "half_async":
        return HalfAsyncCommunicator(client, k_steps=max(1, k_steps),
                                     **kw)
    if mode == "geo":
        return GeoCommunicator(client, sparse_configs, k_steps=k_steps)
    raise ValueError(f"unknown communicator mode {mode!r}")
