"""Host-resident large-scale sparse embedding tables.

TPU-native replacement for the reference parameter-server sparse storage
(operators/distributed/large_scale_kv.h — server-side hash table of
feature-id -> embedding row + optimizer slots) and the sparse optimizer
kernels (operators/optimizers/*_op.* SelectedRows paths).

Design: feature ids index a *hash table*, not a dense array — capacity is
host RAM (and, sharded over pservers, the cluster), not device HBM.  Rows
are materialized lazily on first touch with a deterministic per-id
initializer, so a table declared as [2**40, dim] costs nothing until ids
are actually seen (the reference's "10^11 features / 10^12 parameters"
capability, README.md:52).  The dense XLA step never sees the table: the
trainer *pulls* the rows for the current batch (gather -> dense [n, dim]
feed), computes on device, and *pushes* the gradient rows back, where the
sparse optimizer (sgd / adagrad / adam, each with its own slots) applies
the update — the DownpourWorker pull/compute/push cycle
(framework/device_worker.h:268, framework/fleet/fleet_wrapper.h:66,111).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["TableConfig", "SparseShard", "SparseTable", "DenseTable"]

_GROW = 1024  # arena growth granularity (rows)


class TableConfig:
    """Declarative config for one sparse table (reference
    large_scale_kv.h ValueDesc / distributed_strategy sparse_table_configs).
    """

    def __init__(self, name: str, dim: int, dtype: str = "float32",
                 initializer: Tuple = ("uniform", -0.05, 0.05),
                 optimizer: str = "sgd", lr: float = 0.01,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, momentum: float = 0.9,
                 seed: int = 0):
        self.name = name
        self.dim = int(dim)
        self.dtype = dtype
        self.initializer = tuple(initializer)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.momentum = momentum
        self.seed = int(seed)

    def to_dict(self):
        return dict(name=self.name, dim=self.dim, dtype=self.dtype,
                    initializer=list(self.initializer),
                    optimizer=self.optimizer, lr=self.lr, beta1=self.beta1,
                    beta2=self.beta2, epsilon=self.epsilon,
                    momentum=self.momentum, seed=self.seed)

    @staticmethod
    def from_dict(d):
        d = dict(d)
        d["initializer"] = tuple(d.get("initializer", ("uniform", -.05, .05)))
        return TableConfig(**d)

    # number of extra slot vectors the optimizer needs per row
    def n_slots(self) -> int:
        return {"sgd": 0, "momentum": 1, "adagrad": 1, "adam": 2}[
            self.optimizer]


def _init_rows(cfg: TableConfig, ids: np.ndarray) -> np.ndarray:
    """Deterministic per-id row init: the same id always materializes the
    same row, on any shard/server — this is what makes geo-sync and
    restart-from-scratch reproducible without coordination."""
    kind = cfg.initializer[0]
    if kind == "constant":
        return np.full((len(ids), cfg.dim), cfg.initializer[1],
                       dtype=cfg.dtype)
    if kind == "uniform":
        low, high = cfg.initializer[1], cfg.initializer[2]
        out = np.empty((len(ids), cfg.dim), dtype=cfg.dtype)
        for i, fid in enumerate(ids):
            # counter-based per-id stream: Philox keyed by (table seed, id)
            g = np.random.Generator(
                np.random.Philox(key=(cfg.seed & 0xFFFFFFFF, int(fid))))
            out[i] = g.uniform(low, high, cfg.dim).astype(cfg.dtype)
        return out
    raise ValueError(f"unknown sparse initializer {cfg.initializer!r}")


class SparseShard:
    """One shard: id -> arena row index; value + optimizer slot arenas.

    Mirrors large_scale_kv.h ValueBlock (rows in flat arenas, free-list
    — here append-only growable numpy arenas).
    """

    def __init__(self, cfg: TableConfig):
        self.cfg = cfg
        self._index: Dict[int, int] = {}
        self._n = 0
        self._value = np.empty((0, cfg.dim), dtype=cfg.dtype)
        self._slots = [np.empty((0, cfg.dim), dtype="float32")
                       for _ in range(cfg.n_slots())]
        self._counts = np.empty((0,), dtype="int64")  # per-row step count
        self._lock = threading.Lock()

    def __len__(self):
        return self._n

    def _ensure_capacity(self, need: int):
        cap = self._value.shape[0]
        if need <= cap:
            return
        new_cap = max(need, cap + max(_GROW, cap // 2))
        self._value = np.resize(self._value, (new_cap, self.cfg.dim))
        self._slots = [np.resize(s, (new_cap, self.cfg.dim))
                       for s in self._slots]
        self._counts = np.resize(self._counts, (new_cap,))

    def _rows_for(self, ids: np.ndarray, create: bool) -> np.ndarray:
        """id array -> arena row indices, materializing missing rows."""
        idx = np.empty(len(ids), dtype=np.int64)
        missing: List[int] = []
        mpos: List[int] = []
        for i, fid in enumerate(ids):
            r = self._index.get(int(fid), -1)
            if r < 0:
                if not create:
                    r = -1
                else:
                    missing.append(int(fid))
                    mpos.append(i)
                    continue
            idx[i] = r
        if missing:
            self._ensure_capacity(self._n + len(missing))
            fresh = _init_rows(self.cfg, np.asarray(missing))
            for j, fid in enumerate(missing):
                r = self._index.get(fid, -1)
                if r < 0:  # dedupe within this batch of missing ids
                    r = self._n
                    self._index[fid] = r
                    self._n += 1
                    self._value[r] = fresh[j]
                    for s in self._slots:
                        s[r] = 0.0
                    self._counts[r] = 0
                idx[mpos[j]] = r
        return idx

    def pull(self, ids: np.ndarray, create: bool = True) -> np.ndarray:
        with self._lock:
            idx = self._rows_for(ids, create=create)
            out = self._value[idx].copy()
            if not create:
                out[idx < 0] = 0.0
            return out

    def push(self, ids: np.ndarray, grads: np.ndarray,
             lr_scale: float = 1.0):
        """Apply merged (unique-id) gradient rows with the table optimizer.

        Caller must have merged duplicates already (SparseTable.push does);
        reference: MergeAdd in operators/math/selected_rows_functor.*.
        """
        cfg = self.cfg
        lr = cfg.lr * lr_scale
        with self._lock:
            idx = self._rows_for(ids, create=True)
            g = grads.astype("float32", copy=False)
            if cfg.optimizer == "sgd":
                self._value[idx] -= (lr * g).astype(cfg.dtype)
            elif cfg.optimizer == "momentum":
                vel = self._slots[0]
                vel[idx] = cfg.momentum * vel[idx] + g
                self._value[idx] -= (lr * vel[idx]).astype(cfg.dtype)
            elif cfg.optimizer == "adagrad":
                acc = self._slots[0]
                acc[idx] += g * g
                self._value[idx] -= (
                    lr * g / (np.sqrt(acc[idx]) + cfg.epsilon)
                ).astype(cfg.dtype)
            elif cfg.optimizer == "adam":
                m, v = self._slots
                self._counts[idx] += 1
                t = self._counts[idx].astype("float32")[:, None]
                m[idx] = cfg.beta1 * m[idx] + (1 - cfg.beta1) * g
                v[idx] = cfg.beta2 * v[idx] + (1 - cfg.beta2) * g * g
                mhat = m[idx] / (1 - cfg.beta1 ** t)
                vhat = v[idx] / (1 - cfg.beta2 ** t)
                self._value[idx] -= (
                    lr * mhat / (np.sqrt(vhat) + cfg.epsilon)
                ).astype(cfg.dtype)
            else:
                raise ValueError(f"unknown sparse optimizer "
                                 f"{cfg.optimizer!r}")

    def push_delta(self, ids: np.ndarray, deltas: np.ndarray):
        """Geo-SGD: server adds the trainer's parameter delta directly
        (reference GeoCommunicator / geo_sgd_transpiler semantics)."""
        with self._lock:
            idx = self._rows_for(ids, create=True)
            self._value[idx] += deltas.astype(self.cfg.dtype)

    def export(self) -> Tuple[np.ndarray, np.ndarray]:
        """(ids, values) snapshot of every materialized row."""
        with self._lock:
            ids = np.fromiter(self._index.keys(), dtype=np.int64,
                              count=len(self._index))
            idx = np.fromiter(self._index.values(), dtype=np.int64,
                              count=len(self._index))
            return ids, self._value[idx].copy()

    def load(self, ids: np.ndarray, values: np.ndarray):
        with self._lock:
            idx = self._rows_for(np.asarray(ids, dtype=np.int64),
                                 create=True)
            self._value[idx] = values.astype(self.cfg.dtype)


def merge_sparse_grad(ids: np.ndarray, grads: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Sum gradient rows of duplicate ids (SelectedRows MergeAdd,
    operators/math/selected_rows_functor.h)."""
    ids = np.asarray(ids, dtype=np.int64).ravel()
    grads = np.asarray(grads)
    grads = grads.reshape(len(ids), -1)
    uids, inv = np.unique(ids, return_inverse=True)
    merged = np.zeros((len(uids), grads.shape[1]), dtype=grads.dtype)
    np.add.at(merged, inv, grads)
    return uids, merged


class DenseTable:
    """Server-side dense parameter + optimizer state.

    The PS-mode trainer program carries forward/backward only; dense
    optimizer updates run here, mirroring the reference's scheme of moving
    optimize ops onto the pserver program
    (transpiler/distribute_transpiler.py:256 get_pserver_program).  One
    DenseTable per parameter; multi-server deployments split the flat
    vector into contiguous blocks per server.
    """

    def __init__(self, name: str, init_value: np.ndarray,
                 optimizer: str = "sgd", lr: float = 0.01,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, momentum: float = 0.9):
        self.name = name
        self.value = np.array(init_value, dtype="float32")
        self.optimizer = optimizer
        self.lr, self.beta1, self.beta2 = lr, beta1, beta2
        self.epsilon, self.momentum = epsilon, momentum
        self._t = 0
        n_slots = {"sgd": 0, "momentum": 1, "adagrad": 1, "adam": 2}[optimizer]
        self.slots = [np.zeros_like(self.value) for _ in range(n_slots)]
        self._lock = threading.Lock()

    def pull(self) -> np.ndarray:
        with self._lock:
            return self.value.copy()

    def push(self, grad: np.ndarray, lr_scale: float = 1.0):
        g = np.asarray(grad, dtype="float32").reshape(self.value.shape)
        lr = self.lr * lr_scale
        with self._lock:
            if self.optimizer == "sgd":
                self.value -= lr * g
            elif self.optimizer == "momentum":
                vel = self.slots[0]
                vel *= self.momentum
                vel += g
                self.value -= lr * vel
            elif self.optimizer == "adagrad":
                acc = self.slots[0]
                acc += g * g
                self.value -= lr * g / (np.sqrt(acc) + self.epsilon)
            elif self.optimizer == "adam":
                m, v = self.slots
                self._t += 1
                m *= self.beta1
                m += (1 - self.beta1) * g
                v *= self.beta2
                v += (1 - self.beta2) * g * g
                mhat = m / (1 - self.beta1 ** self._t)
                vhat = v / (1 - self.beta2 ** self._t)
                self.value -= lr * mhat / (np.sqrt(vhat) + self.epsilon)
            else:
                raise ValueError(f"unknown optimizer {self.optimizer!r}")

    def push_delta(self, delta: np.ndarray):
        with self._lock:
            self.value += np.asarray(delta, "float32").reshape(
                self.value.shape)

    def set(self, value: np.ndarray):
        with self._lock:
            self.value = np.array(value, dtype="float32")


class SparseTable:
    """A sharded sparse table (in one process).  Multi-server deployments
    hold one SparseTable per server, each owning the ids whose
    ``hash(id) % n_servers`` equals its server index — routing done by the
    TableClient (rpc.py), mirroring DistributeTranspiler's id-sharding
    (transpiler/distribute_transpiler.py:256).
    """

    def __init__(self, cfg: TableConfig, n_shards: int = 8):
        self.cfg = cfg
        self.n_shards = int(n_shards)
        self.shards = [SparseShard(cfg) for _ in range(self.n_shards)]

    def _route(self, ids: np.ndarray):
        shard_of = ids % self.n_shards
        return shard_of

    def size(self) -> int:
        return sum(len(s) for s in self.shards)

    def pull(self, ids, create: bool = True) -> np.ndarray:
        ids = np.asarray(ids, dtype=np.int64).ravel()
        out = np.empty((len(ids), self.cfg.dim), dtype=self.cfg.dtype)
        shard_of = self._route(ids)
        for k in range(self.n_shards):
            m = shard_of == k
            if m.any():
                out[m] = self.shards[k].pull(ids[m], create=create)
        return out

    def push(self, ids, grads, lr_scale: float = 1.0):
        uids, merged = merge_sparse_grad(ids, grads)
        shard_of = self._route(uids)
        for k in range(self.n_shards):
            m = shard_of == k
            if m.any():
                self.shards[k].push(uids[m], merged[m], lr_scale=lr_scale)

    def push_delta(self, ids, deltas):
        ids = np.asarray(ids, dtype=np.int64).ravel()
        shard_of = self._route(ids)
        for k in range(self.n_shards):
            m = shard_of == k
            if m.any():
                self.shards[k].push_delta(ids[m], deltas[m])

    def export(self):
        parts = [s.export() for s in self.shards]
        ids = np.concatenate([p[0] for p in parts])
        vals = np.concatenate([p[1] for p in parts])
        return ids, vals

    def load(self, ids, values):
        ids = np.asarray(ids, dtype=np.int64).ravel()
        values = np.asarray(values).reshape(len(ids), self.cfg.dim)
        shard_of = self._route(ids)
        for k in range(self.n_shards):
            m = shard_of == k
            if m.any():
                self.shards[k].load(ids[m], values[m])

    def save(self, path: str):
        ids, vals = self.export()
        np.savez(path, ids=ids, values=vals,
                 meta=np.frombuffer(
                     repr(self.cfg.to_dict()).encode(), dtype=np.uint8))

    @staticmethod
    def restore(path: str, cfg: Optional[TableConfig] = None,
                n_shards: int = 8) -> "SparseTable":
        import ast
        z = np.load(path if path.endswith(".npz") else path + ".npz")
        if cfg is None:
            cfg = TableConfig.from_dict(
                ast.literal_eval(bytes(z["meta"]).decode()))
        t = SparseTable(cfg, n_shards=n_shards)
        t.load(z["ids"], z["values"])
        return t
