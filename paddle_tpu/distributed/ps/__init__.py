"""Parameter-server subsystem: host-resident sparse tables, the
pull/compute/push trainer, and sync/async/geo communicators.

Reference: paddle/fluid/operators/distributed/ (communicator, grpc/brpc
transport, large_scale_kv), framework/fleet/fleet_wrapper.h, and
transpiler/distribute_transpiler.py — re-architected so the XLA-compiled
dense step stays pure and static-shape while the unbounded sparse state
lives on the host/servers.
"""
from .table import DenseTable, SparseTable, TableConfig, merge_sparse_grad  # noqa
from .rpc import (LocalClient, PServer, PSService, RPCClient,  # noqa
                  ShardedClient)
from .communicator import (AsyncCommunicator, Communicator,  # noqa
                           GeoCommunicator, make_communicator)
from .worker import (PSContext, PSTrainer, SparseSection,  # noqa
                     build_service, transpile_to_ps)
