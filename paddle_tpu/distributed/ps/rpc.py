"""Parameter-server service + transport.

TPU-native replacement for the reference's gRPC parameter-server data
plane (operators/distributed/grpc/*, listen_and_serv_op.cc,
brpc_server.*).  The service semantics are the same — pull/push sparse
rows, pull/push dense blocks, barrier — but the stack is inverted: the
reference interleaves send/recv *ops inside the graph* per variable; here
the XLA-compiled step is a pure dense function and the transport runs
around it at the host level (pull -> feed, fetch -> push), so device
execution never blocks on the network mid-step.

Three client/server flavors share one duck-typed API:

  * ``PSService``      — the in-process service object (tables + dispatch).
  * ``LocalClient``    — direct method calls (single-process deployments,
                         also the backend reached after RPC decode).
  * ``PServer``/``RPCClient`` — length-prefixed binary protocol over TCP
                         sockets, threaded server; multi-server routing by
                         ``id % n_servers`` is done in ``ShardedClient``.

Wire format: 4-byte big-endian length + payload.  Payload = 1-byte
method id + msgpack-free manual encoding (numpy buffers are sent raw with
a small header) — no pickle on the data plane.
"""
from __future__ import annotations

import socket
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Sequence

import numpy as np

from .table import DenseTable, SparseTable, TableConfig
from ...monitor import monitor as _monitor

_RPC_STAT = _monitor.get("ps_rpc_requests")

__all__ = ["PSService", "LocalClient", "PServer", "RPCClient",
           "ShardedClient", "PSError", "BarrierError",
           "HeartBeatMonitor", "start_heartbeat"]


class PSError(RuntimeError):
    """Server-side failure surfaced to the client (error RPC frame)."""


class BarrierError(PSError):
    """Barrier released abnormally: dead trainers evicted or timeout."""


class HeartBeatMonitor:
    """Trainer liveness (reference
    operators/distributed/heart_beat_monitor.cc): trainers ping
    periodically; one that has pinged before and then goes silent past
    `timeout` is declared dead. Eviction is evaluated lazily on
    alive_count() — no dedicated sweep thread needed, the barrier path
    polls it."""

    def __init__(self, n_workers: int, timeout: float = 10.0):
        self._time = time.monotonic
        self.n_workers = n_workers
        self.timeout = timeout
        self._lock = threading.Lock()
        self._last_seen: Dict[int, float] = {}
        self._dead: set = set()

    def beat(self, trainer_id: int):
        with self._lock:
            self._last_seen[trainer_id] = self._time()
            self._dead.discard(trainer_id)   # rejoin after a blip

    def dead_trainers(self):
        now = self._time()
        with self._lock:
            for tid, t in self._last_seen.items():
                if tid not in self._dead and now - t > self.timeout:
                    self._dead.add(tid)
            return sorted(self._dead)

    def alive_count(self) -> int:
        return self.n_workers - len(self.dead_trainers())


# ---------------------------------------------------------------------------
# Service: the tables + operations (server-side brain)
# ---------------------------------------------------------------------------
class PSService:
    """Holds sparse + dense tables; every client flavor dispatches here."""

    def __init__(self):
        self.sparse: Dict[str, SparseTable] = {}
        self.dense: Dict[str, DenseTable] = {}
        self._barrier_lock = threading.Lock()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition(self._barrier_lock)

    # -- table management ---------------------------------------------------
    def create_sparse_table(self, cfg: TableConfig, n_shards: int = 8):
        if cfg.name not in self.sparse:
            self.sparse[cfg.name] = SparseTable(cfg, n_shards=n_shards)

    def create_dense_table(self, name: str, init_value, optimizer="sgd",
                           lr=0.01, **kw):
        if name not in self.dense:
            self.dense[name] = DenseTable(name, init_value,
                                          optimizer=optimizer, lr=lr, **kw)

    # -- sparse -------------------------------------------------------------
    def pull_sparse(self, table: str, ids: np.ndarray) -> np.ndarray:
        return self.sparse[table].pull(ids)

    def push_sparse(self, table: str, ids: np.ndarray, grads: np.ndarray,
                    lr_scale: float = 1.0):
        self.sparse[table].push(ids, grads, lr_scale=lr_scale)

    def push_sparse_delta(self, table: str, ids: np.ndarray,
                          deltas: np.ndarray):
        self.sparse[table].push_delta(ids, deltas)

    # -- dense --------------------------------------------------------------
    def pull_dense(self, name: str) -> np.ndarray:
        return self.dense[name].pull()

    def push_dense(self, name: str, grad: np.ndarray, lr_scale: float = 1.0):
        self.dense[name].push(grad, lr_scale=lr_scale)

    def push_dense_delta(self, name: str, delta: np.ndarray):
        self.dense[name].push_delta(delta)

    def set_dense(self, name: str, value: np.ndarray):
        self.dense[name].set(value)

    # -- checkpoint (reference checkpoint_notify_op.cc: the trainer
    # notifies, the SERVER writes/reads its own disk) -----------------------
    def save_checkpoint(self, dirname: str):
        """Write every table under dirname. Sparse tables persist
        (ids, values) — parameter state, like the reference's
        save_persistables over PS tables; dense tables persist value +
        optimizer slots + step so a restored server resumes exactly."""
        import os
        os.makedirs(dirname, exist_ok=True)
        for name, t in self.sparse.items():
            t.save(os.path.join(dirname, f"sparse_{name}"))
        for name, d in self.dense.items():
            with d._lock:
                np.savez(os.path.join(dirname, f"dense_{name}"),
                         value=d.value, t=np.int64(d._t),
                         **{f"slot_{i}": s
                            for i, s in enumerate(d.slots)})

    def restore_checkpoint(self, dirname: str):
        """Load tables saved by save_checkpoint into the EXISTING table
        objects (configs/optimizers come from the program, exactly like
        the reference's init-then-load flow)."""
        import os
        for name, t in self.sparse.items():
            path = os.path.join(dirname, f"sparse_{name}.npz")
            z = np.load(path)
            t.load(z["ids"], z["values"])
        for name, d in self.dense.items():
            z = np.load(os.path.join(dirname, f"dense_{name}.npz"))
            with d._lock:
                d.value[...] = z["value"]
                d._t = int(z["t"])
                for i in range(len(d.slots)):
                    d.slots[i][...] = z[f"slot_{i}"]

    # -- coordination -------------------------------------------------------
    def barrier(self, n_workers: int, monitor: "HeartBeatMonitor" = None,
                timeout: float = 120.0):
        """Block until the expected number of callers arrive (sync-mode
        step fence; reference fetch_barrier/send_barrier ops).

        Robustness (r3 weak #3 — a hung trainer used to stall this
        forever): the expected count shrinks as the HeartBeatMonitor
        declares trainers dead, and when the barrier releases because of
        an eviction (or exceeds `timeout`) every waiter gets a LOUD
        BarrierError instead of silently proceeding under-synced."""
        deadline = time.monotonic() + timeout
        with self._barrier_cv:
            gen = self._barrier_gen
            self._barrier_count += 1
            while True:
                expected = (monitor.alive_count() if monitor is not None
                            else n_workers)
                if self._barrier_count >= expected:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    dead = (monitor.dead_trainers()
                            if monitor is not None else [])
                    self._barrier_dead = dead
                    self._barrier_cv.notify_all()
                    if dead:
                        raise BarrierError(
                            f"barrier released after evicting dead "
                            f"trainers {dead}")
                    return
                if gen != self._barrier_gen:
                    dead = getattr(self, "_barrier_dead", [])
                    if dead:
                        raise BarrierError(
                            f"barrier released after evicting dead "
                            f"trainers {dead}")
                    return
                if time.monotonic() > deadline:
                    self._barrier_count -= 1
                    raise BarrierError(
                        f"barrier timed out after {timeout}s "
                        f"({self._barrier_count + 1} of "
                        f"{expected} arrived)")
                self._barrier_cv.wait(timeout=0.2)


class LocalClient:
    """In-process client: direct dispatch to a PSService."""

    def __init__(self, service: PSService, n_workers: int = 1):
        self.service = service
        self.n_workers = n_workers

    def pull_sparse(self, table, ids):
        return self.service.pull_sparse(table, np.asarray(ids, np.int64))

    def push_sparse(self, table, ids, grads, lr_scale=1.0):
        self.service.push_sparse(table, ids, grads, lr_scale)

    def push_sparse_delta(self, table, ids, deltas):
        self.service.push_sparse_delta(table, ids, deltas)

    def pull_dense(self, name):
        return self.service.pull_dense(name)

    def push_dense(self, name, grad, lr_scale=1.0):
        self.service.push_dense(name, grad, lr_scale)

    def push_dense_delta(self, name, delta):
        self.service.push_dense_delta(name, delta)

    def set_dense(self, name, value):
        self.service.set_dense(name, value)

    def barrier(self):
        self.service.barrier(self.n_workers)

    def heartbeat(self, trainer_id: int):
        pass  # in-process: liveness is trivial

    def save_checkpoint(self, dirname: str):
        self.service.save_checkpoint(dirname)

    def restore_checkpoint(self, dirname: str):
        self.service.restore_checkpoint(dirname)

    def close(self):
        pass


# ---------------------------------------------------------------------------
# Wire protocol
# ---------------------------------------------------------------------------
# method ids
_PULL_SPARSE, _PUSH_SPARSE, _PUSH_SPARSE_DELTA = 1, 2, 3
_PULL_DENSE, _PUSH_DENSE, _SET_DENSE = 4, 5, 6
_BARRIER, _STOP, _PUSH_DENSE_DELTA = 7, 8, 9
_HEARTBEAT = 10
_SAVE_CKPT, _RESTORE_CKPT = 11, 12

# response status framing (first byte): 0 = OK, 1 = server error string
_OK, _ERR = b"\x00", b"\x01"

_HDR = struct.Struct("!I")


def _pack_array(a: np.ndarray) -> bytes:
    a = np.ascontiguousarray(a)
    dt = a.dtype.str.encode()
    shape = np.asarray(a.shape, dtype=np.int64).tobytes()
    return (struct.pack("!BB", len(dt), a.ndim) + dt + shape + a.tobytes())


def _pack_array_parts(a: np.ndarray):
    """(header, body) with body a zero-copy view of the array buffer."""
    a = np.ascontiguousarray(a)
    dt = a.dtype.str.encode()
    shape = np.asarray(a.shape, dtype=np.int64).tobytes()
    return (struct.pack("!BB", len(dt), a.ndim) + dt + shape,
            memoryview(a).cast("B"))


def _unpack_array(buf: memoryview, off: int):
    ndt, ndim = struct.unpack_from("!BB", buf, off)
    off += 2
    dt = bytes(buf[off:off + ndt]).decode()
    off += ndt
    shape = np.frombuffer(buf, dtype=np.int64, count=ndim, offset=off)
    off += 8 * ndim
    n = int(np.prod(shape)) if ndim else 1
    a = np.frombuffer(buf, dtype=np.dtype(dt), count=n, offset=off)
    off += a.nbytes
    return a.reshape(shape), off


def _pack_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack("!H", len(b)) + b


def _unpack_str(buf: memoryview, off: int):
    (n,) = struct.unpack_from("!H", buf, off)
    off += 2
    return bytes(buf[off:off + n]).decode(), off + n


def _send_msg(sock: socket.socket, payload: bytes):
    sock.sendall(_HDR.pack(len(payload)) + payload)


def _send_msg_parts(sock: socket.socket, *parts):
    """Scatter-gather send: header + parts via one sendmsg — the array
    body goes out straight from the numpy buffer, no concat copies (the
    pull path moves tens of MB per call on big dense tables)."""
    total = sum(len(p) for p in parts)
    bufs = [_HDR.pack(total)] + [memoryview(p) for p in parts]
    sent = sock.sendmsg(bufs)
    expect = 4 + total
    if sent < expect:
        # kernel took a partial write: flatten the rest and sendall
        rest = b"".join(bytes(b) for b in bufs)[sent:]
        sock.sendall(rest)


def _recv_msg(sock: socket.socket) -> Optional[memoryview]:
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = _HDR.unpack(hdr)
    body = _recv_exact(sock, n)
    return memoryview(body) if body is not None else None


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            return None
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------
class _StopServing(Exception):
    pass


class PServer:
    """Threaded TCP parameter server fronting a PSService.

    Reference: listen_and_serv_op.cc (blocking RPC loop embedded as a
    graph op) — here a plain host service, started by
    ``fleet.run_server()`` on server-role processes.
    """

    def __init__(self, service: PSService, endpoint: str = "127.0.0.1:0",
                 n_workers: int = 1, heartbeat_timeout: float = 10.0,
                 barrier_timeout: float = 120.0, max_conns: int = 64):
        self.service = service
        self.n_workers = n_workers
        self.monitor = HeartBeatMonitor(n_workers,
                                        timeout=heartbeat_timeout)
        self.barrier_timeout = barrier_timeout
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.create_server((host, int(port)))
        self.endpoint = "%s:%d" % self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._accept_thread: Optional[threading.Thread] = None
        # bounded connection pool (r3 weak #3: one unbounded thread per
        # connection). Each trainer holds a data connection (which a
        # sync barrier parks) PLUS a dedicated heartbeat connection
        # (start_heartbeat), so the floor is 2*n_workers + slack.
        self._conn_slots = threading.BoundedSemaphore(
            max(max_conns, 2 * n_workers + 4))

    def start(self):
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self):
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            if not self._conn_slots.acquire(blocking=False) and \
                    not self._conn_slots.acquire(timeout=0.1):
                # pool exhausted: refuse WITHOUT blocking the accept
                # loop (a 5s park here would head-of-line-block every
                # pending connect, including heartbeats)
                try:
                    conn.settimeout(0.5)
                    _send_msg(conn, _ERR + b"server connection pool "
                              b"exhausted")
                except OSError:
                    pass  # ok: best-effort refusal; peer already gone
                finally:
                    try:
                        conn.close()
                    except OSError:
                        pass  # ok: peer already closed the socket
                continue
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            # prune finished threads so connection churn (reconnecting
            # retry clients, heartbeats) doesn't grow the list unboundedly
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket):
        with self._conns_lock:
            self._conns.add(conn)
        try:
            while not self._stop.is_set():
                msg = _recv_msg(conn)
                if msg is None:
                    return
                try:
                    resp = self._dispatch(conn, msg)
                except _StopServing:
                    return
                except Exception as e:  # error frame, connection lives on
                    resp = _ERR + f"{type(e).__name__}: {e}".encode()
                if isinstance(resp, tuple):
                    _send_msg_parts(conn, *resp)
                else:
                    _send_msg(conn, resp)
        except (ConnectionError, OSError):
            return
        finally:
            try:
                self._conn_slots.release()
            except ValueError:
                pass  # ok: slot was already released on the refusal path
            with self._conns_lock:
                self._conns.discard(conn)
            conn.close()

    def _dispatch(self, conn: socket.socket, msg: memoryview) -> bytes:
        _RPC_STAT.increase()
        svc = self.service
        method = msg[0]
        off = 1
        if method == _PULL_SPARSE:
            table, off = _unpack_str(msg, off)
            ids, off = _unpack_array(msg, off)
            hdr, body = _pack_array_parts(svc.pull_sparse(table, ids))
            return (_OK + hdr, body)
        if method == _PUSH_SPARSE:
            table, off = _unpack_str(msg, off)
            (scale,) = struct.unpack_from("!f", msg, off)
            off += 4
            ids, off = _unpack_array(msg, off)
            grads, off = _unpack_array(msg, off)
            svc.push_sparse(table, ids, grads, lr_scale=scale)
            return _OK
        if method == _PUSH_SPARSE_DELTA:
            table, off = _unpack_str(msg, off)
            ids, off = _unpack_array(msg, off)
            deltas, off = _unpack_array(msg, off)
            svc.push_sparse_delta(table, ids, deltas)
            return _OK
        if method == _PULL_DENSE:
            name, off = _unpack_str(msg, off)
            hdr, body = _pack_array_parts(svc.pull_dense(name))
            return (_OK + hdr, body)
        if method == _PUSH_DENSE:
            name, off = _unpack_str(msg, off)
            (scale,) = struct.unpack_from("!f", msg, off)
            off += 4
            grad, off = _unpack_array(msg, off)
            svc.push_dense(name, grad, lr_scale=scale)
            return _OK
        if method == _PUSH_DENSE_DELTA:
            name, off = _unpack_str(msg, off)
            delta, off = _unpack_array(msg, off)
            svc.push_dense_delta(name, delta)
            return _OK
        if method == _SET_DENSE:
            name, off = _unpack_str(msg, off)
            value, off = _unpack_array(msg, off)
            svc.set_dense(name, value)
            return _OK
        if method == _HEARTBEAT:
            (tid,) = struct.unpack_from("!i", msg, off)
            self.monitor.beat(tid)
            return _OK
        if method == _SAVE_CKPT:
            dirname, off = _unpack_str(msg, off)
            svc.save_checkpoint(dirname)
            return _OK
        if method == _RESTORE_CKPT:
            dirname, off = _unpack_str(msg, off)
            svc.restore_checkpoint(dirname)
            return _OK
        if method == _BARRIER:
            svc.barrier(self.n_workers, monitor=self.monitor,
                        timeout=self.barrier_timeout)
            return _OK
        if method == _STOP:
            _send_msg(conn, _OK)
            self.stop()
            raise _StopServing
        raise PSError(f"bad PS method {method}")

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass  # ok: listener socket already dead during shutdown
        # close live connections too: a serve thread parked in recv would
        # otherwise answer one more request after stop
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # ok: connection already torn down by the peer
            try:
                c.close()
            except OSError:
                pass  # ok: connection already closed

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until stop() (e.g. a client's stop_server) — the
        pserver main loop (reference listen_and_serv RunSyncLoop)."""
        return self._stop.wait(timeout)


class RPCClient:
    """Client for one PServer endpoint (one persistent connection,
    serialized by a lock — matches per-variable ordered gRPC channels in
    the reference grpc_client.cc).

    Robustness (reference grpc_client.cc deadlines/retry): every call
    carries a timeout; on timeout or a broken connection the client
    reconnects and retries up to `retries` times with backoff, then
    raises loudly. Barriers get their own longer `barrier_timeout` and
    are NOT retried (re-entering a barrier would double-count the
    arrival). Server-side failures arrive as error frames and raise
    PSError with the server's message.

    NOTE push retries can double-apply a gradient if the first request
    was executed but its ack was lost — the async-SGD tolerance the
    reference also accepts; sync jobs fence with the barrier anyway.
    """

    def __init__(self, endpoint: str, timeout: float = 30.0,
                 retries: int = 2, retry_backoff: float = 0.5,
                 barrier_timeout: float = 150.0):
        self.endpoint = endpoint
        self.timeout = timeout
        self.retries = retries
        self.retry_backoff = retry_backoff
        self.barrier_timeout = barrier_timeout
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._connect()

    def _connect(self):
        host, port = self.endpoint.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=self.timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _call_once(self, payload: bytes, timeout: float) -> memoryview:
        self._sock.settimeout(timeout)
        _send_msg(self._sock, payload)
        resp = _recv_msg(self._sock)
        if resp is None:
            raise ConnectionError("pserver closed connection")
        if resp[0] == 1:
            msg = bytes(resp[1:]).decode(errors="replace")
            if msg.startswith("BarrierError:"):
                raise BarrierError(msg)   # catchable type across RPC
            raise PSError(msg)
        return memoryview(resp)[1:]

    def _call(self, payload: bytes, timeout: Optional[float] = None,
              retry: bool = True) -> memoryview:
        timeout = self.timeout if timeout is None else timeout
        attempts = (self.retries + 1) if retry else 1
        last = None
        with self._lock:
            for i in range(attempts):
                try:
                    if self._sock is None:
                        # previous hard failure closed the socket —
                        # reconnect even when retries are exhausted, so a
                        # retries=0 client (heartbeat pingers) recovers
                        # on its NEXT call instead of dying forever on
                        # EBADF
                        self._connect()
                    return self._call_once(payload, timeout)
                except PSError:
                    raise                      # server answered: no retry
                except (socket.timeout, TimeoutError, ConnectionError,
                        OSError) as e:
                    last = e
                    try:
                        if self._sock is not None:
                            self._sock.close()
                    except OSError:
                        pass  # ok: closing a dead socket before retry
                    self._sock = None
                    if i + 1 < attempts:
                        time.sleep(self.retry_backoff * (2 ** i))
        raise ConnectionError(
            f"pserver {self.endpoint} unreachable after {attempts} "
            f"attempt(s) (timeout {timeout}s): {last}")

    def pull_sparse(self, table, ids):
        ids = np.asarray(ids, np.int64)
        resp = self._call(bytes([_PULL_SPARSE]) + _pack_str(table)
                          + _pack_array(ids))
        arr, _ = _unpack_array(resp, 0)
        return arr.copy()

    def push_sparse(self, table, ids, grads, lr_scale=1.0):
        self._call(bytes([_PUSH_SPARSE]) + _pack_str(table)
                   + struct.pack("!f", lr_scale)
                   + _pack_array(np.asarray(ids, np.int64))
                   + _pack_array(np.asarray(grads, np.float32)))

    def push_sparse_delta(self, table, ids, deltas):
        self._call(bytes([_PUSH_SPARSE_DELTA]) + _pack_str(table)
                   + _pack_array(np.asarray(ids, np.int64))
                   + _pack_array(np.asarray(deltas, np.float32)))

    def pull_dense(self, name):
        resp = self._call(bytes([_PULL_DENSE]) + _pack_str(name))
        arr, _ = _unpack_array(resp, 0)
        return arr.copy()

    def push_dense(self, name, grad, lr_scale=1.0):
        self._call(bytes([_PUSH_DENSE]) + _pack_str(name)
                   + struct.pack("!f", lr_scale)
                   + _pack_array(np.asarray(grad, np.float32)))

    def push_dense_delta(self, name, delta):
        self._call(bytes([_PUSH_DENSE_DELTA]) + _pack_str(name)
                   + _pack_array(np.asarray(delta, np.float32)))

    def set_dense(self, name, value):
        self._call(bytes([_SET_DENSE]) + _pack_str(name)
                   + _pack_array(np.asarray(value, np.float32)))

    def barrier(self):
        # not retried: a retry would re-enter and double-count
        self._call(bytes([_BARRIER]), timeout=self.barrier_timeout,
                   retry=False)

    def heartbeat(self, trainer_id: int):
        self._call(bytes([_HEARTBEAT]) + struct.pack("!i", trainer_id))

    def save_checkpoint(self, dirname: str):
        """checkpoint_notify: the server saves to ITS disk at dirname."""
        self._call(bytes([_SAVE_CKPT]) + _pack_str(dirname))

    def restore_checkpoint(self, dirname: str):
        self._call(bytes([_RESTORE_CKPT]) + _pack_str(dirname))

    def stop_server(self):
        try:
            self._call(bytes([_STOP]))
        except ConnectionError:
            pass  # ok: server exits before answering its own stop

    def close(self):
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass  # ok: socket already closed
        self._sock = None


class ShardedClient:
    """Routes sparse ids over multiple servers by ``id % n_servers`` and
    dense tables by round-robin of name hash — DistributeTranspiler's
    placement policy (transpiler/distribute_transpiler.py:256
    slice_variable / id-mod routing)."""

    def __init__(self, clients: Sequence):
        self.clients = list(clients)
        self.n = len(self.clients)

    def _dense_owner(self, name: str):
        # crc32, not hash(): every process must route a parameter to the
        # same server regardless of PYTHONHASHSEED salting
        return self.clients[zlib.crc32(name.encode()) % self.n]

    def pull_sparse(self, table, ids):
        ids = np.asarray(ids, np.int64).ravel()
        out = None
        owner = ids % self.n
        for k, c in enumerate(self.clients):
            m = owner == k
            if not m.any():
                continue
            rows = c.pull_sparse(table, ids[m])
            if out is None:
                out = np.empty((len(ids), rows.shape[1]), rows.dtype)
            out[m] = rows
        if out is None:  # empty batch
            out = np.empty((0, 1), np.float32)
        return out

    def push_sparse(self, table, ids, grads, lr_scale=1.0):
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads).reshape(len(ids), -1)
        owner = ids % self.n
        for k, c in enumerate(self.clients):
            m = owner == k
            if m.any():
                c.push_sparse(table, ids[m], grads[m], lr_scale)

    def push_sparse_delta(self, table, ids, deltas):
        ids = np.asarray(ids, np.int64).ravel()
        deltas = np.asarray(deltas).reshape(len(ids), -1)
        owner = ids % self.n
        for k, c in enumerate(self.clients):
            m = owner == k
            if m.any():
                c.push_sparse_delta(table, ids[m], deltas[m])

    def pull_dense(self, name):
        return self._dense_owner(name).pull_dense(name)

    def push_dense(self, name, grad, lr_scale=1.0):
        self._dense_owner(name).push_dense(name, grad, lr_scale)

    def push_dense_delta(self, name, delta):
        self._dense_owner(name).push_dense_delta(name, delta)

    def set_dense(self, name, value):
        self._dense_owner(name).set_dense(name, value)

    def barrier(self):
        self.clients[0].barrier()

    def save_checkpoint(self, dirname: str):
        # per-shard subdir: shard servers sharing a filesystem must not
        # clobber each other's identically-named tables
        import os
        for i, c in enumerate(self.clients):
            c.save_checkpoint(os.path.join(dirname, f"shard_{i}"))

    def restore_checkpoint(self, dirname: str):
        import os
        for i, c in enumerate(self.clients):
            c.restore_checkpoint(os.path.join(dirname, f"shard_{i}"))

    # NOTE deliberately no heartbeat() here: pinging over the
    # data-plane connections would queue behind a blocked sync barrier
    # and self-evict the waiting trainer — use start_heartbeat(), which
    # opens dedicated connections.

    def close(self):
        for c in self.clients:
            c.close()


def start_heartbeat(client, trainer_id: int, interval: float = 2.0):
    """Background liveness pinger for a trainer (reference: the trainer
    send thread feeding HeartBeatMonitor over its own channel).

    Opens DEDICATED connections: an RPCClient serializes calls on one
    socket, so a heartbeat sharing the data-plane connection would queue
    behind a blocked sync barrier and the waiting trainer would evict
    ITSELF. Returns a stop() callable (also closes the dedicated
    connections); ping failures are swallowed — a dead server surfaces
    on the next real RPC with a clear ConnectionError."""
    if hasattr(client, "clients"):           # ShardedClient
        endpoints = [c.endpoint for c in client.clients
                     if hasattr(c, "endpoint")]
    elif hasattr(client, "endpoint"):        # RPCClient
        endpoints = [client.endpoint]
    else:                                    # LocalClient: nothing to ping
        return lambda: None
    hb = [RPCClient(ep, timeout=5.0, retries=0) for ep in endpoints]
    stop = threading.Event()

    def loop():
        while not stop.wait(interval):
            for c in hb:
                try:
                    c.heartbeat(trainer_id)
                except Exception:
                    from ...monitor import stat_add
                    stat_add("ps_heartbeat_send_errors")

    t = threading.Thread(target=loop, daemon=True)
    t.start()

    def stopper():
        stop.set()
        for c in hb:
            c.close()

    return stopper
