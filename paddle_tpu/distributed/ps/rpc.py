"""Parameter-server service + transport.

TPU-native replacement for the reference's gRPC parameter-server data
plane (operators/distributed/grpc/*, listen_and_serv_op.cc,
brpc_server.*).  The service semantics are the same — pull/push sparse
rows, pull/push dense blocks, barrier — but the stack is inverted: the
reference interleaves send/recv *ops inside the graph* per variable; here
the XLA-compiled step is a pure dense function and the transport runs
around it at the host level (pull -> feed, fetch -> push), so device
execution never blocks on the network mid-step.

Three client/server flavors share one duck-typed API:

  * ``PSService``      — the in-process service object (tables + dispatch).
  * ``LocalClient``    — direct method calls (single-process deployments,
                         also the backend reached after RPC decode).
  * ``PServer``/``RPCClient`` — length-prefixed binary protocol over TCP
                         sockets, threaded server; multi-server routing by
                         ``id % n_servers`` is done in ``ShardedClient``.

Wire format: 4-byte big-endian length + payload.  Payload = 1-byte
method id + msgpack-free manual encoding (numpy buffers are sent raw with
a small header) — no pickle on the data plane.
"""
from __future__ import annotations

import socket
import struct
import threading
import zlib
from typing import Dict, List, Optional, Sequence

import numpy as np

from .table import DenseTable, SparseTable, TableConfig

__all__ = ["PSService", "LocalClient", "PServer", "RPCClient",
           "ShardedClient"]


# ---------------------------------------------------------------------------
# Service: the tables + operations (server-side brain)
# ---------------------------------------------------------------------------
class PSService:
    """Holds sparse + dense tables; every client flavor dispatches here."""

    def __init__(self):
        self.sparse: Dict[str, SparseTable] = {}
        self.dense: Dict[str, DenseTable] = {}
        self._barrier_lock = threading.Lock()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition(self._barrier_lock)

    # -- table management ---------------------------------------------------
    def create_sparse_table(self, cfg: TableConfig, n_shards: int = 8):
        if cfg.name not in self.sparse:
            self.sparse[cfg.name] = SparseTable(cfg, n_shards=n_shards)

    def create_dense_table(self, name: str, init_value, optimizer="sgd",
                           lr=0.01, **kw):
        if name not in self.dense:
            self.dense[name] = DenseTable(name, init_value,
                                          optimizer=optimizer, lr=lr, **kw)

    # -- sparse -------------------------------------------------------------
    def pull_sparse(self, table: str, ids: np.ndarray) -> np.ndarray:
        return self.sparse[table].pull(ids)

    def push_sparse(self, table: str, ids: np.ndarray, grads: np.ndarray,
                    lr_scale: float = 1.0):
        self.sparse[table].push(ids, grads, lr_scale=lr_scale)

    def push_sparse_delta(self, table: str, ids: np.ndarray,
                          deltas: np.ndarray):
        self.sparse[table].push_delta(ids, deltas)

    # -- dense --------------------------------------------------------------
    def pull_dense(self, name: str) -> np.ndarray:
        return self.dense[name].pull()

    def push_dense(self, name: str, grad: np.ndarray, lr_scale: float = 1.0):
        self.dense[name].push(grad, lr_scale=lr_scale)

    def push_dense_delta(self, name: str, delta: np.ndarray):
        self.dense[name].push_delta(delta)

    def set_dense(self, name: str, value: np.ndarray):
        self.dense[name].set(value)

    # -- coordination -------------------------------------------------------
    def barrier(self, n_workers: int):
        """Block until n_workers callers arrive (sync-mode step fence;
        reference: fetch_barrier/send_barrier ops)."""
        with self._barrier_cv:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count >= n_workers:
                self._barrier_count = 0
                self._barrier_gen += 1
                self._barrier_cv.notify_all()
            else:
                while gen == self._barrier_gen:
                    self._barrier_cv.wait(timeout=30)


class LocalClient:
    """In-process client: direct dispatch to a PSService."""

    def __init__(self, service: PSService, n_workers: int = 1):
        self.service = service
        self.n_workers = n_workers

    def pull_sparse(self, table, ids):
        return self.service.pull_sparse(table, np.asarray(ids, np.int64))

    def push_sparse(self, table, ids, grads, lr_scale=1.0):
        self.service.push_sparse(table, ids, grads, lr_scale)

    def push_sparse_delta(self, table, ids, deltas):
        self.service.push_sparse_delta(table, ids, deltas)

    def pull_dense(self, name):
        return self.service.pull_dense(name)

    def push_dense(self, name, grad, lr_scale=1.0):
        self.service.push_dense(name, grad, lr_scale)

    def push_dense_delta(self, name, delta):
        self.service.push_dense_delta(name, delta)

    def set_dense(self, name, value):
        self.service.set_dense(name, value)

    def barrier(self):
        self.service.barrier(self.n_workers)

    def close(self):
        pass


# ---------------------------------------------------------------------------
# Wire protocol
# ---------------------------------------------------------------------------
# method ids
_PULL_SPARSE, _PUSH_SPARSE, _PUSH_SPARSE_DELTA = 1, 2, 3
_PULL_DENSE, _PUSH_DENSE, _SET_DENSE = 4, 5, 6
_BARRIER, _STOP, _PUSH_DENSE_DELTA = 7, 8, 9

_HDR = struct.Struct("!I")


def _pack_array(a: np.ndarray) -> bytes:
    a = np.ascontiguousarray(a)
    dt = a.dtype.str.encode()
    shape = np.asarray(a.shape, dtype=np.int64).tobytes()
    return (struct.pack("!BB", len(dt), a.ndim) + dt + shape + a.tobytes())


def _unpack_array(buf: memoryview, off: int):
    ndt, ndim = struct.unpack_from("!BB", buf, off)
    off += 2
    dt = bytes(buf[off:off + ndt]).decode()
    off += ndt
    shape = np.frombuffer(buf, dtype=np.int64, count=ndim, offset=off)
    off += 8 * ndim
    n = int(np.prod(shape)) if ndim else 1
    a = np.frombuffer(buf, dtype=np.dtype(dt), count=n, offset=off)
    off += a.nbytes
    return a.reshape(shape), off


def _pack_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack("!H", len(b)) + b


def _unpack_str(buf: memoryview, off: int):
    (n,) = struct.unpack_from("!H", buf, off)
    off += 2
    return bytes(buf[off:off + n]).decode(), off + n


def _send_msg(sock: socket.socket, payload: bytes):
    sock.sendall(_HDR.pack(len(payload)) + payload)


def _recv_msg(sock: socket.socket) -> Optional[memoryview]:
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = _HDR.unpack(hdr)
    body = _recv_exact(sock, n)
    return memoryview(body) if body is not None else None


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            return None
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------
class PServer:
    """Threaded TCP parameter server fronting a PSService.

    Reference: listen_and_serv_op.cc (blocking RPC loop embedded as a
    graph op) — here a plain host service, started by
    ``fleet.run_server()`` on server-role processes.
    """

    def __init__(self, service: PSService, endpoint: str = "127.0.0.1:0",
                 n_workers: int = 1):
        self.service = service
        self.n_workers = n_workers
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.create_server((host, int(port)))
        self.endpoint = "%s:%d" % self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._accept_thread: Optional[threading.Thread] = None

    def start(self):
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self):
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket):
        svc = self.service
        try:
            while not self._stop.is_set():
                msg = _recv_msg(conn)
                if msg is None:
                    return
                method = msg[0]
                off = 1
                if method == _PULL_SPARSE:
                    table, off = _unpack_str(msg, off)
                    ids, off = _unpack_array(msg, off)
                    _send_msg(conn, _pack_array(svc.pull_sparse(table, ids)))
                elif method == _PUSH_SPARSE:
                    table, off = _unpack_str(msg, off)
                    (scale,) = struct.unpack_from("!f", msg, off)
                    off += 4
                    ids, off = _unpack_array(msg, off)
                    grads, off = _unpack_array(msg, off)
                    svc.push_sparse(table, ids, grads, lr_scale=scale)
                    _send_msg(conn, b"\x00")
                elif method == _PUSH_SPARSE_DELTA:
                    table, off = _unpack_str(msg, off)
                    ids, off = _unpack_array(msg, off)
                    deltas, off = _unpack_array(msg, off)
                    svc.push_sparse_delta(table, ids, deltas)
                    _send_msg(conn, b"\x00")
                elif method == _PULL_DENSE:
                    name, off = _unpack_str(msg, off)
                    _send_msg(conn, _pack_array(svc.pull_dense(name)))
                elif method == _PUSH_DENSE:
                    name, off = _unpack_str(msg, off)
                    (scale,) = struct.unpack_from("!f", msg, off)
                    off += 4
                    grad, off = _unpack_array(msg, off)
                    svc.push_dense(name, grad, lr_scale=scale)
                    _send_msg(conn, b"\x00")
                elif method == _PUSH_DENSE_DELTA:
                    name, off = _unpack_str(msg, off)
                    delta, off = _unpack_array(msg, off)
                    svc.push_dense_delta(name, delta)
                    _send_msg(conn, b"\x00")
                elif method == _SET_DENSE:
                    name, off = _unpack_str(msg, off)
                    value, off = _unpack_array(msg, off)
                    svc.set_dense(name, value)
                    _send_msg(conn, b"\x00")
                elif method == _BARRIER:
                    svc.barrier(self.n_workers)
                    _send_msg(conn, b"\x00")
                elif method == _STOP:
                    _send_msg(conn, b"\x00")
                    self.stop()
                    return
                else:
                    raise RuntimeError(f"bad PS method {method}")
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until stop() (e.g. a client's stop_server) — the
        pserver main loop (reference listen_and_serv RunSyncLoop)."""
        return self._stop.wait(timeout)


class RPCClient:
    """Client for one PServer endpoint (one persistent connection,
    serialized by a lock — matches per-variable ordered gRPC channels in
    the reference grpc_client.cc)."""

    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=30)
        # blocking calls (barrier on a straggler, large-table seeding) may
        # legitimately exceed the connect timeout
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def _call(self, payload: bytes) -> memoryview:
        with self._lock:
            _send_msg(self._sock, payload)
            resp = _recv_msg(self._sock)
        if resp is None:
            raise ConnectionError("pserver closed connection")
        return resp

    def pull_sparse(self, table, ids):
        ids = np.asarray(ids, np.int64)
        resp = self._call(bytes([_PULL_SPARSE]) + _pack_str(table)
                          + _pack_array(ids))
        arr, _ = _unpack_array(resp, 0)
        return arr.copy()

    def push_sparse(self, table, ids, grads, lr_scale=1.0):
        self._call(bytes([_PUSH_SPARSE]) + _pack_str(table)
                   + struct.pack("!f", lr_scale)
                   + _pack_array(np.asarray(ids, np.int64))
                   + _pack_array(np.asarray(grads, np.float32)))

    def push_sparse_delta(self, table, ids, deltas):
        self._call(bytes([_PUSH_SPARSE_DELTA]) + _pack_str(table)
                   + _pack_array(np.asarray(ids, np.int64))
                   + _pack_array(np.asarray(deltas, np.float32)))

    def pull_dense(self, name):
        resp = self._call(bytes([_PULL_DENSE]) + _pack_str(name))
        arr, _ = _unpack_array(resp, 0)
        return arr.copy()

    def push_dense(self, name, grad, lr_scale=1.0):
        self._call(bytes([_PUSH_DENSE]) + _pack_str(name)
                   + struct.pack("!f", lr_scale)
                   + _pack_array(np.asarray(grad, np.float32)))

    def push_dense_delta(self, name, delta):
        self._call(bytes([_PUSH_DENSE_DELTA]) + _pack_str(name)
                   + _pack_array(np.asarray(delta, np.float32)))

    def set_dense(self, name, value):
        self._call(bytes([_SET_DENSE]) + _pack_str(name)
                   + _pack_array(np.asarray(value, np.float32)))

    def barrier(self):
        self._call(bytes([_BARRIER]))

    def stop_server(self):
        try:
            self._call(bytes([_STOP]))
        except ConnectionError:
            pass

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class ShardedClient:
    """Routes sparse ids over multiple servers by ``id % n_servers`` and
    dense tables by round-robin of name hash — DistributeTranspiler's
    placement policy (transpiler/distribute_transpiler.py:256
    slice_variable / id-mod routing)."""

    def __init__(self, clients: Sequence):
        self.clients = list(clients)
        self.n = len(self.clients)

    def _dense_owner(self, name: str):
        # crc32, not hash(): every process must route a parameter to the
        # same server regardless of PYTHONHASHSEED salting
        return self.clients[zlib.crc32(name.encode()) % self.n]

    def pull_sparse(self, table, ids):
        ids = np.asarray(ids, np.int64).ravel()
        out = None
        owner = ids % self.n
        for k, c in enumerate(self.clients):
            m = owner == k
            if not m.any():
                continue
            rows = c.pull_sparse(table, ids[m])
            if out is None:
                out = np.empty((len(ids), rows.shape[1]), rows.dtype)
            out[m] = rows
        if out is None:  # empty batch
            out = np.empty((0, 1), np.float32)
        return out

    def push_sparse(self, table, ids, grads, lr_scale=1.0):
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads).reshape(len(ids), -1)
        owner = ids % self.n
        for k, c in enumerate(self.clients):
            m = owner == k
            if m.any():
                c.push_sparse(table, ids[m], grads[m], lr_scale)

    def push_sparse_delta(self, table, ids, deltas):
        ids = np.asarray(ids, np.int64).ravel()
        deltas = np.asarray(deltas).reshape(len(ids), -1)
        owner = ids % self.n
        for k, c in enumerate(self.clients):
            m = owner == k
            if m.any():
                c.push_sparse_delta(table, ids[m], deltas[m])

    def pull_dense(self, name):
        return self._dense_owner(name).pull_dense(name)

    def push_dense(self, name, grad, lr_scale=1.0):
        self._dense_owner(name).push_dense(name, grad, lr_scale)

    def push_dense_delta(self, name, delta):
        self._dense_owner(name).push_dense_delta(name, delta)

    def set_dense(self, name, value):
        self._dense_owner(name).set_dense(name, value)

    def barrier(self):
        self.clients[0].barrier()

    def close(self):
        for c in self.clients:
            c.close()
