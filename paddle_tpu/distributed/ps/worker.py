"""PS-mode program transpilation + the trainer pull/compute/push loop.

TPU-native counterpart of the reference DistributeTranspiler
(python/paddle/fluid/transpiler/distribute_transpiler.py:256) +
DownpourWorker/HogwildWorker (framework/device_worker.h:268,
framework/downpour_worker.cc): the trainer program is rewritten so that
sparse ``lookup_table`` ops read a *fed* dense row block instead of a
device-resident table, and dense parameters lose their optimizer ops
(the server applies updates).  Every step the PSTrainer:

  1. pulls the embedding rows for the batch's feature ids (and the
     current dense params) from the server,
  2. runs the XLA-compiled dense step — which stays a pure, static-shape
     function; the table never touches HBM,
  3. fetches the row gradients and pushes them back.

This is the inversion that makes the trillion-parameter sparse claim
(reference README.md:52) TPU-native: device memory holds only the rows
the current batch touches.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...framework.core import Program, Variable, grad_var_name
from .communicator import (AsyncCommunicator, Communicator, GeoCommunicator)
from .rpc import LocalClient, PSService
from .table import TableConfig

__all__ = ["SparseSection", "PSContext", "transpile_to_ps",
           "build_service", "PSTrainer"]

# vocab sizes at or above this never materialize densely; rows lazy-init
# on the server (reference large_scale_kv.h path)
LARGE_VOCAB = 1 << 30


@dataclass
class SparseSection:
    """One rewritten sparse lookup."""
    table_name: str          # original W parameter name == server table
    ids_name: str            # feed var holding feature ids
    pulled_name: str         # new feed var: gathered rows [*, dim]
    out_name: str            # original lookup output
    dim: int
    padding_idx: int = -1
    version: int = 2         # lookup_table (1: ids [N,1]) vs _v2
    vocab: int = 0
    lazy_init: bool = False  # True: never densely initialized anywhere

    @property
    def grad_name(self) -> str:
        return grad_var_name(self.pulled_name)


@dataclass
class PSContext:
    """Everything the runtime needs, attached to the trainer program."""
    sections: List[SparseSection]
    dense_params: List[Tuple[str, str, tuple]]  # (name, grad_name, shape)
    optimizer: str = "sgd"
    lr: float = 0.01
    opt_kwargs: dict = field(default_factory=dict)
    mode: str = "sync"       # sync | half_async | async | geo
    k_steps: int = 100       # geo sync interval

    def table_configs(self) -> List[TableConfig]:
        return [TableConfig(s.table_name, s.dim, optimizer=self.optimizer,
                            lr=self.lr, **self.opt_kwargs)
                for s in self.sections]


def transpile_to_ps(program: Program) -> List[SparseSection]:
    """Rewrite sparse lookups in-place; call BEFORE append_backward so
    gradients flow to the pulled rows.

    Each ``lookup_table(_v2)`` with ``is_sparse``/``is_distributed``
    becomes ``assign(Out <- W@PULLED)`` where ``W@PULLED`` is a feed var;
    W leaves the parameter list (the server owns it).  Startup
    initialization of W is kept for normal vocabs — ``PSTrainer.
    init_worker`` seeds the server from it, preserving exact parity with
    a dense baseline — and stripped for LARGE_VOCAB/is_distributed
    tables, which lazy-init server-side.
    """
    block = program.global_block()
    sections: List[SparseSection] = []
    server_owned = set()
    for op in list(block.ops):
        if op.type not in ("lookup_table", "lookup_table_v2"):
            continue
        if not (op.attrs.get("is_sparse") or op.attrs.get("is_distributed")):
            continue
        w_name = op.single_input("W")
        ids_name = op.single_input("Ids")
        out_name = op.single_output("Out")
        w = block.var(w_name)
        out = block.var(out_name)
        vocab, dim = int(w.shape[0]), int(w.shape[-1])
        lazy = bool(op.attrs.get("is_distributed")) or vocab >= LARGE_VOCAB
        padding_idx = int(op.attrs.get("padding_idx", -1))
        version = 1 if op.type == "lookup_table" else 2
        # keyed by the *output* so a table shared by several lookups
        # (tied embeddings) gets one pulled var per lookup site
        pulled_name = out_name + "@PULLED"
        block.create_var(name=pulled_name, shape=out.shape, dtype=w.dtype,
                         is_data=True, stop_gradient=False, trainable=False)
        # rewrite in place (keeps op position and the Out consumers)
        op.type = "assign"
        op.inputs = {"X": [pulled_name]}
        op.outputs = {"Out": [out_name]}
        op.attrs = {k: v for k, v in op.attrs.items() if k == "op_role"}
        sections.append(SparseSection(
            table_name=w_name, ids_name=ids_name, pulled_name=pulled_name,
            out_name=out_name, dim=dim, padding_idx=padding_idx,
            version=version, vocab=vocab, lazy_init=lazy))
        server_owned.add(w_name)
    for w_name in server_owned:  # the W parameters are now server-owned
        block.vars.pop(w_name, None)
    return sections


def _strip_startup_init(startup: Program, names: Sequence[str]):
    """Remove init ops (and vars) for server-lazy tables from the startup
    program so a 2^40-row table never materializes host- or device-side."""
    block = startup.global_block()
    names = set(names)
    for i in reversed(range(len(block.ops))):
        op = block.ops[i]
        if set(op.output_arg_names()) & names:
            block._remove_op(i) if hasattr(block, "_remove_op") else \
                block.ops.pop(i)
    for n in names:
        block.vars.pop(n, None)


def build_service(ctx: PSContext, scope=None,
                  dense_init: Optional[Dict[str, np.ndarray]] = None
                  ) -> PSService:
    """Construct the server-side service for a PSContext.

    Sparse tables are created empty (rows lazy-init or seeded by
    ``PSTrainer.init_worker``).  Dense tables are created from
    ``dense_init``/scope values when available, else zeros — the first
    worker's init push overwrites them (reference: trainer0 sends initial
    params to pservers).
    """
    svc = PSService()
    for cfg in ctx.table_configs():
        svc.create_sparse_table(cfg)
    for name, _g, shape in ctx.dense_params:
        init = None
        if dense_init and name in dense_init:
            init = dense_init[name]
        elif scope is not None and scope.find_var(name) is not None:
            init = np.asarray(scope.find_var(name))
        if init is None:
            init = np.zeros(shape, "float32")
        svc.create_dense_table(name, init, optimizer=ctx.optimizer,
                               lr=ctx.lr, **ctx.opt_kwargs)
    return svc


class PSTrainer:
    """Runs one worker's pull/compute/push loop around an Executor.

    ``init_worker()`` must run after the startup program: it seeds the
    server's sparse tables from any densely-initialized W still in scope
    (non-lazy tables), pushes initial dense params (worker 0), and drops
    the dense W copy from the trainer (reference
    fleet.init_worker / communicator start).
    """

    def __init__(self, program: Program, ctx: PSContext,
                 communicator: Communicator, executor=None, scope=None,
                 worker_index: int = 0, n_workers: int = 1):
        from ...framework.executor import Executor, global_scope
        self.program = program
        self.ctx = ctx
        self.comm = communicator
        self.exe = executor or Executor()
        self.scope = scope or global_scope()
        self.worker_index = worker_index
        self.n_workers = n_workers
        # per-step LR multiplier (host-side LR schedules in PS mode: the
        # server applies base_lr * lr_scale)
        self.lr_scale = 1.0
        self._dense_names = [d[0] for d in ctx.dense_params]
        self._dense_grads = [d[1] for d in ctx.dense_params]
        self._dense_shapes = {d[0]: tuple(d[2]) for d in ctx.dense_params}

    # -- lifecycle ----------------------------------------------------------
    def init_worker(self):
        client = self.comm.client
        if self.worker_index == 0:
            for sec in self.ctx.sections:
                if sec.lazy_init:
                    continue
                v = self.scope.find_var(sec.table_name)
                if v is not None:
                    w = np.asarray(v)
                    if isinstance(client, LocalClient):
                        client.service.sparse[sec.table_name].load(
                            np.arange(w.shape[0], dtype=np.int64), w)
                    else:
                        _rpc_seed_sparse(client, sec, w)
                    self.scope.erase([sec.table_name])
            for name in self._dense_names:
                v = self.scope.find_var(name)
                if v is not None:
                    client.set_dense(name, np.asarray(v, dtype="float32"))
        if isinstance(self.comm, GeoCommunicator):
            # geo trains dense locally: register local copies
            for name in self._dense_names:
                v = self.scope.find_var(name)
                init = (np.asarray(v) if v is not None
                        else self.comm.client.pull_dense(name).reshape(
                            self._dense_shapes[name]))
                self.comm.register_dense(name, np.asarray(init, "float32"),
                                         lr=self.ctx.lr)
            # local mirrors of seeded (non-lazy) tables must match the
            # server; lazy tables already agree via the shared TableConfig
            # seed + deterministic per-id init.
            for sec in self.ctx.sections:
                if sec.lazy_init:
                    continue
                ids = np.arange(sec.vocab, dtype=np.int64)
                vals = client.pull_sparse(sec.table_name, ids)
                self.comm.local[sec.table_name].load(ids, vals)
                self.comm.base[sec.table_name].load(ids, vals)
        self.comm.start()
        if self.n_workers > 1:
            # no worker may train until worker 0 finished seeding
            client.barrier()

    def stop_worker(self):
        self.comm.stop()

    # -- the per-step cycle --------------------------------------------------
    def run(self, feed: Dict[str, np.ndarray], fetch_list=None,
            return_numpy: bool = True):
        feed = dict(feed)
        fetch_list = list(fetch_list or [])
        user_fetch_n = len(fetch_list)

        # 1. pull dense params into scope (server-owned unless geo-local)
        for name in self._dense_names:
            val = self.comm.pull_dense(name).reshape(
                self._dense_shapes[name])
            self.scope.set_var(name, val)

        # 2. pull sparse rows -> feed
        masks = {}
        for sec in self.ctx.sections:
            ids = np.asarray(feed[sec.ids_name], np.int64)
            flat = ids.ravel()
            rows = np.asarray(
                self.comm.pull_sparse(sec.table_name, flat),
                dtype="float32").reshape(len(flat), sec.dim)
            if sec.padding_idx >= 0:
                pad = flat == sec.padding_idx
                rows[pad] = 0.0
                masks[sec.pulled_name] = pad
            if sec.version == 1:
                out_shape = (ids.shape[0], sec.dim)
            else:
                out_shape = tuple(ids.shape) + (sec.dim,)
            feed[sec.pulled_name] = rows.reshape(out_shape)

        # 3. run the compiled dense step, fetching user targets + grads
        grad_names = [sec.grad_name for sec in self.ctx.sections] + \
            self._dense_grads
        outs = self.exe.run(self.program, feed=feed,
                            fetch_list=fetch_list + grad_names,
                            scope=self.scope, return_numpy=True)
        user_outs, grads = outs[:user_fetch_n], outs[user_fetch_n:]

        # 4. push gradients
        for sec, g in zip(self.ctx.sections, grads):
            ids = np.asarray(feed[sec.ids_name], np.int64).ravel()
            g = np.asarray(g, "float32").reshape(len(ids), sec.dim)
            pad = masks.get(sec.pulled_name)
            if pad is not None and pad.any():
                keep = ~pad
                ids, g = ids[keep], g[keep]
            self.comm.push_sparse(sec.table_name, ids, g,
                                  lr_scale=self.lr_scale)
        for name, g in zip(self._dense_names,
                           grads[len(self.ctx.sections):]):
            self.comm.push_dense(name, np.asarray(g, "float32"),
                                 lr_scale=self.lr_scale)

        self.comm.step_done()
        if self.ctx.mode == "sync" and self.n_workers > 1:
            self.comm.barrier()
        return user_outs


def _rpc_seed_sparse(client, sec: SparseSection, w: np.ndarray,
                     chunk: int = 65536):
    """Seed a server table over RPC: rows start at deterministic init, so
    send (value - init) as a delta in chunks."""
    n = w.shape[0]
    for lo in range(0, n, chunk):
        ids = np.arange(lo, min(lo + chunk, n), dtype=np.int64)
        cur = client.pull_sparse(sec.table_name, ids)  # materializes init
        client.push_sparse_delta(sec.table_name, ids,
                                 np.asarray(w[lo:lo + len(ids)]) - cur)
