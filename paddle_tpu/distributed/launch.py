"""Process launcher: ``python -m paddle_tpu.distributed.launch [opts]
train.py args...``.

Reference: python/paddle/distributed/fleet/launch.py:196 launch_collective —
one subprocess per GPU with PADDLE_TRAINER_ID/ENDPOINTS env.

TPU-native: one process per *host* (all local chips belong to it). For
single-host (the common case) this execs the script directly; for
multi-host it sets the jax.distributed coordinator env consumed by
init_parallel_env().
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys


def _parse():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes on this host (TPU: 1 — chips are "
                        "driven by the mesh, not by processes)")
    p.add_argument("--ips", type=str, default="127.0.0.1",
                   help="comma-separated host ips")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--coordinator_port", type=int, default=12355)
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("--max_restarts", type=int, default=0,
                   help="elastic: restart a failed worker up to N times "
                        "(reference fleet launch watch loop)")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def spawn_process(cmd, env_overrides=None, log_path=None,
                  restart_count=0):
    """Spawn one supervised worker process: current env + overrides,
    ``PADDLE_TPU_RESTART_COUNT`` accounting (which life this worker is
    on; 0 = first — a restarted worker can tell a fresh launch from an
    elastic respawn, e.g. to insist on finding an auto-checkpoint),
    stdout+stderr appended to ``log_path`` when given.

    Shared machinery: the training watch loop below and the serving
    fleet supervisor (:mod:`paddle_tpu.serving.fleet`) spawn through
    this one helper so restart accounting and log capture cannot
    drift apart."""
    env = dict(os.environ)
    env.update({k: str(v) for k, v in (env_overrides or {}).items()})
    env["PADDLE_TPU_RESTART_COUNT"] = str(restart_count)
    stdout = None
    if log_path:
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        stdout = open(log_path, "a")
    try:
        return subprocess.Popen(cmd, env=env, stdout=stdout,
                                stderr=subprocess.STDOUT
                                if stdout else None)
    finally:
        if stdout is not None:
            stdout.close()  # the child holds its own descriptor


def _spawn(args, hosts, nnodes, local_rank, restart_count=0):
    rank = args.node_rank * args.nproc_per_node + local_rank
    world = nnodes * args.nproc_per_node
    env = {
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(
            f"{h}:{args.coordinator_port + i}"
            for h in hosts for i in range(args.nproc_per_node)),
        "PADDLE_CURRENT_ENDPOINT":
            f"{hosts[min(args.node_rank, nnodes - 1)]}:"
            f"{args.coordinator_port + local_rank}",
    }
    if world > 1:
        env["PADDLE_COORDINATOR"] = f"{hosts[0]}:{args.coordinator_port}"
    cmd = [sys.executable, "-u", args.training_script,
           *args.training_script_args]
    log_path = (os.path.join(args.log_dir, f"worker.{rank}.log")
                if args.log_dir else None)
    return spawn_process(cmd, env, log_path, restart_count)


def main():
    import time

    args = _parse()
    hosts = [h for h in args.ips.split(",") if h]
    nnodes = max(1, len(hosts))
    procs = {lr: _spawn(args, hosts, nnodes, lr)
             for lr in range(args.nproc_per_node)}
    restarts = {lr: 0 for lr in procs}

    # watch loop (reference fleet/launch.py watch_local_trainers): poll
    # workers; restart crashed ones up to --max_restarts (they resume
    # from their auto-checkpoint), give up past the budget.
    rc = 0
    while procs:
        time.sleep(0.2)
        for lr, p in list(procs.items()):
            ret = p.poll()
            if ret is None:
                continue
            if ret == 0:
                del procs[lr]
            elif restarts[lr] < args.max_restarts:
                restarts[lr] += 1
                print(f"[launch] worker {lr} exited rc={ret}; restart "
                      f"{restarts[lr]}/{args.max_restarts}",
                      file=sys.stderr)
                procs[lr] = _spawn(args, hosts, nnodes, lr, restarts[lr])
            else:
                rc = rc or ret
                del procs[lr]
    sys.exit(rc)


if __name__ == "__main__":
    main()
