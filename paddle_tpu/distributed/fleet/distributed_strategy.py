"""DistributedStrategy: the strategy-flag surface.

Reference: python/paddle/distributed/fleet/base/distributed_strategy.py:101
backed by framework/distributed_strategy.proto:77-101. Here plain Python
attributes + per-strategy config dicts (same keys as the proto messages).
"""
from __future__ import annotations

import copy


class DistributedStrategy:
    def __init__(self):
        # collective execution
        self.auto = False
        self.a_sync = False                 # parameter-server async mode
        self.a_sync_configs = {"k_steps": -1, "batch_merge_repeat": 1}

        # mixed precision (proto AMPConfig)
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0,
            "incr_every_n_steps": 1000,
            "decr_every_n_nan_or_inf": 2,
            "incr_ratio": 2.0,
            "decr_ratio": 0.5,
            "use_dynamic_loss_scaling": True,
            "use_pure_bf16": False,
            "custom_white_list": [],
            "custom_black_list": [],
        }

        # activation recompute (proto RecomputeConfig)
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}

        # pipeline (proto PipelineConfig)
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1,
                                 "schedule_mode": "F-then-B"}

        # gradient merge (proto GradientMergeConfig)
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}

        # ZeRO-style sharding (proto ShardingConfig)
        self.sharding = False
        self.sharding_configs = {"sharding_degree": 8,
                                 "segment_broadcast_MB": 32.0}

        # localsgd / dgc / large-batch optimizers
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1, "begin_step": 1}
        self.adaptive_localsgd = False
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "rampup_step": 1,
                            "sparsity": [0.999]}
        self.lamb = False
        self.lamb_configs = {"lamb_weight_decay": 0.01,
                             "exclude_from_weight_decay": []}
        self.lars = False
        self.lars_configs = {"lars_coeff": 0.001, "lars_weight_decay": 0.0005,
                             "epsilon": 0.0,
                             "exclude_from_weight_decay": []}
        self.fp16_allreduce = False

        # tensor/sequence parallel (new capability; absent in reference)
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        self.sequence_parallel = False
        self.sequence_parallel_configs = {"sequence_parallel_degree": 1,
                                          "mode": "ring"}

        # execution
        self.nccl_comm_num = 1
        self.sync_nccl_allreduce = True
        self.fuse_grad_size_in_MB = 32
        self.fuse_all_reduce_ops = True
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 1

        self.build_strategy = None
        self.execution_strategy = None

    def copy(self) -> "DistributedStrategy":
        return copy.deepcopy(self)

    def __repr__(self):
        on = [k for k, v in self.__dict__.items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(enabled={on})"
