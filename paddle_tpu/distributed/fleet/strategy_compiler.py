"""Reference: distributed/fleet/base/strategy_compiler.py:112,168 —
pick applicable meta optimizers and stack them inner-to-outer."""
from __future__ import annotations

from .meta_optimizers import META_OPTIMIZER_CLASSES


class StrategyCompiler:
    def generate_optimizer(self, loss, role_maker, optimizer,
                           user_defined_strategy):
        applied = []
        current = optimizer
        valid_strategy = user_defined_strategy.copy()
        for cls in META_OPTIMIZER_CLASSES:
            meta = cls(current)
            meta._set_basic_info(loss, role_maker, optimizer,
                                 valid_strategy)
            if meta._can_apply():
                applied.append(cls.__name__)
                current = meta
            else:
                meta._disable_strategy(valid_strategy)
        return current, applied, valid_strategy
