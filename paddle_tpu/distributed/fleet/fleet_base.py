"""Fleet: the distributed-training facade.

Reference: python/paddle/distributed/fleet/base/fleet_base.py (init:125,
distributed_optimizer:554, minimize:946 with meta-optimizer ranking at
:1019-1061).
"""
from __future__ import annotations

import logging

from typing import Optional

from .distributed_strategy import DistributedStrategy
from .role_maker import PaddleCloudRoleMaker, RoleMakerBase
from .strategy_compiler import StrategyCompiler


class Fleet:
    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._is_collective = False
        self._user_defined_strategy: Optional[DistributedStrategy] = None
        self._user_defined_optimizer = None
        self._final_strategy = None
        self._applied_meta_optimizers = []
        self._origin_main_program = None
        self._origin_startup_program = None

    # -- lifecycle ----------------------------------------------------------
    def init(self, role_maker: Optional[RoleMakerBase] = None,
             is_collective: bool = False, strategy=None):
        self._is_collective = is_collective or role_maker is None
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=self._is_collective)
        # a fresh init is a fresh deployment: shut down and drop any PS
        # runtime left from a previous one (communicator threads, bound
        # server sockets)
        t = getattr(self, "_ps_trainer", None)
        if t is not None:
            try:
                t.stop_worker()
            except Exception as e:
                from ...monitor import stat_add
                stat_add("fleet_stale_worker_stop_errors")
                logging.getLogger("paddle_tpu.fleet").warning(
                    "stopping stale PS trainer failed: %s", e)
        s = getattr(self, "_ps_server", None)
        if s is not None:
            s.stop()
        for attr in ("_ps_service", "_ps_trainer", "_ps_server"):
            if hasattr(self, attr):
                delattr(self, attr)
        if strategy is not None:
            self._user_defined_strategy = strategy
        return self

    # -- cluster queries ----------------------------------------------------
    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_worker(self):
        return self._role_maker.is_worker()

    def worker_endpoints(self, to_string=False):
        eps = self._role_maker.get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def server_num(self):
        return self._role_maker.server_num()

    def server_index(self):
        return self._role_maker.server_index()

    def server_endpoints(self, to_string=False):
        eps = self._role_maker.get_pserver_endpoints()
        return ",".join(eps) if to_string else eps

    def is_server(self):
        return self._role_maker.is_server()

    def barrier_worker(self):
        # collective mode: XLA orders everything within the single SPMD
        # program.  PS mode: fence through the server.
        t = getattr(self, "_ps_trainer", None)
        if t is not None and t.n_workers > 1:
            t.comm.barrier()

    # -- optimizer ----------------------------------------------------------
    def distributed_optimizer(self, optimizer,
                              strategy: Optional[DistributedStrategy] = None
                              ) -> "Fleet":
        self._user_defined_optimizer = optimizer
        self._user_defined_strategy = (strategy or
                                       self._user_defined_strategy or
                                       DistributedStrategy())
        return self

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if self._role_maker is None:
            raise RuntimeError("fleet.init() must be called before minimize")
        from ...framework.core import (default_main_program,
                                       default_startup_program)
        self._origin_main_program = loss.block.program
        self._origin_startup_program = (startup_program or
                                        default_startup_program())
        compiler = StrategyCompiler()
        final_opt, applied, valid = compiler.generate_optimizer(
            loss, self._role_maker, self._user_defined_optimizer,
            self._user_defined_strategy)
        self._applied_meta_optimizers = applied
        self._final_strategy = valid
        return final_opt.minimize(loss, self._origin_startup_program,
                                  parameter_list, no_grad_set)

    # -- program accessors --------------------------------------------------
    def main_program(self):
        return self._origin_main_program

    def startup_program(self):
        return self._origin_startup_program

    # -- io passthroughs (wired to paddle_tpu.io) ---------------------------
    def save_persistables(self, executor, dirname, main_program=None):
        from ... import io
        return io.save_persistables(executor, dirname, main_program)

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None, **kw):
        from ... import io
        return io.save_inference_model(dirname, feeded_var_names,
                                       target_vars, executor,
                                       main_program=main_program)

    # -- parameter-server runtime ------------------------------------------
    # Reference: fleet.init_server/run_server/init_worker/stop_worker
    # (distributed/fleet/base/fleet_base.py + the pslib runtime).  Two
    # deployments share the code path: in-process (no server endpoints —
    # a LocalClient fronting an embedded PSService, the single-node dev
    # mode) and RPC (PServer processes at get_pserver_endpoints).

    def _ps_ctx(self):
        ctx = getattr(self._origin_main_program, "_ps_ctx", None)
        if ctx is None:
            raise RuntimeError(
                "no PS context: fleet.minimize must run with a "
                "non-collective role or strategy.a_sync first")
        return ctx

    def init_server(self, *args, **kwargs):
        from ..ps import build_service
        from ...framework.executor import global_scope
        self._ps_service = build_service(self._ps_ctx(),
                                         scope=global_scope())

    def run_server(self, block: bool = True):
        """Serve on this role's endpoint (RPC deployments).  Blocks until
        a worker sends stop (reference fleet.run_server / the pserver
        listen_and_serv loop); ``block=False`` returns the running
        server.  Needs server endpoints: in-process mode (no endpoints)
        has no server process — init_worker builds the embedded service
        there."""
        from ..ps import PServer
        eps = self._role_maker.get_pserver_endpoints()
        if not eps:
            raise RuntimeError(
                "run_server: no pserver endpoints configured — in the "
                "in-process deployment there is no server process; "
                "workers use the embedded service via init_worker()")
        me = eps[self.server_index()]
        server = PServer(self._ps_service, endpoint=me,
                         n_workers=self.worker_num())
        server.start()
        self._ps_server = server
        if block:
            server.wait()
        return server

    def init_worker(self):
        from ..ps import (LocalClient, PSTrainer, RPCClient, ShardedClient,
                          build_service, make_communicator)
        ctx = self._ps_ctx()
        eps = self._role_maker.get_pserver_endpoints()
        if eps:
            client = ShardedClient([RPCClient(ep) for ep in eps])
        else:
            if not hasattr(self, "_ps_service"):
                self.init_server()
            client = LocalClient(self._ps_service,
                                 n_workers=max(1, self.worker_num()))
        comm = make_communicator(ctx.mode, client,
                                 sparse_configs=ctx.table_configs(),
                                 k_steps=ctx.k_steps)
        self._ps_trainer = PSTrainer(
            self._origin_main_program, ctx, comm,
            worker_index=self.worker_index(),
            n_workers=max(1, self.worker_num()))
        self._ps_trainer.init_worker()
        return self._ps_trainer

    def ps_trainer(self):
        return self._ps_trainer

    def stop_worker(self):
        t = getattr(self, "_ps_trainer", None)
        if t is not None:
            t.stop_worker()
