"""fleet 2.0-style module API: ``from paddle_tpu.distributed import fleet;
fleet.init(is_collective=True)`` (reference distributed/fleet/__init__.py
binds the Fleet singleton's methods at module level)."""
from .distributed_strategy import DistributedStrategy  # noqa
from .fleet_base import Fleet  # noqa
from .role_maker import (PaddleCloudRoleMaker, Role, RoleMakerBase,  # noqa
                         UserDefinedRoleMaker)

_fleet_singleton = Fleet()

init = _fleet_singleton.init
is_first_worker = _fleet_singleton.is_first_worker
worker_index = _fleet_singleton.worker_index
worker_num = _fleet_singleton.worker_num
is_worker = _fleet_singleton.is_worker
worker_endpoints = _fleet_singleton.worker_endpoints
server_num = _fleet_singleton.server_num
server_index = _fleet_singleton.server_index
server_endpoints = _fleet_singleton.server_endpoints
is_server = _fleet_singleton.is_server
barrier_worker = _fleet_singleton.barrier_worker
distributed_optimizer = _fleet_singleton.distributed_optimizer
minimize = _fleet_singleton.minimize
save_persistables = _fleet_singleton.save_persistables
save_inference_model = _fleet_singleton.save_inference_model
stop_worker = _fleet_singleton.stop_worker
init_server = _fleet_singleton.init_server
run_server = _fleet_singleton.run_server
init_worker = _fleet_singleton.init_worker
ps_trainer = _fleet_singleton.ps_trainer
main_program = _fleet_singleton.main_program
startup_program = _fleet_singleton.startup_program


def fleet_instance() -> Fleet:
    return _fleet_singleton
