"""Reference: distributed/fleet/meta_optimizers/amp_optimizer.py — apply
mixed precision per strategy.amp_configs."""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase


class AMPOptimizer(MetaOptimizerBase):
    strategy_flag = "amp"

    # expose backward/apply_gradients so outer meta optimizers (gradient
    # merge, localsgd) compose with the decorated optimizer
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._decorated().backward(loss, startup_program,
                                          parameter_list, no_grad_set,
                                          callbacks)

    def apply_gradients(self, params_grads):
        return self._decorated().apply_gradients(params_grads)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        return self._decorated().minimize(loss, startup_program,
                                          parameter_list, no_grad_set)

    def _decorated(self):
        cached = getattr(self, "_dec", None)
        if cached is not None:
            return cached
        from ....contrib.mixed_precision import (AutoMixedPrecisionLists,
                                                 decorate)
        cfg = self.user_defined_strategy.amp_configs
        lists = AutoMixedPrecisionLists(
            custom_white_list=cfg.get("custom_white_list"),
            custom_black_list=cfg.get("custom_black_list"))
        # TPU default is bf16; float16 engages dynamic loss scaling
        dtype = "float16" if cfg.get("use_fp16", False) else "bfloat16"
        dec = decorate(
            self.inner_opt, lists,
            init_loss_scaling=cfg.get("init_loss_scaling", 2.0 ** 15),
            incr_every_n_steps=cfg.get("incr_every_n_steps", 1000),
            decr_every_n_nan_or_inf=cfg.get("decr_every_n_nan_or_inf", 2),
            incr_ratio=cfg.get("incr_ratio", 2.0),
            decr_ratio=cfg.get("decr_ratio", 0.5),
            use_dynamic_loss_scaling=cfg.get("use_dynamic_loss_scaling",
                                             True),
            dtype=dtype)
        self._dec = dec
        return dec
