"""Reference: distributed/fleet/meta_optimizers/lars_optimizer.py —
swap Momentum for LARS-Momentum when strategy.lars is on."""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase


class LarsOptimizer(MetaOptimizerBase):
    strategy_flag = "lars"

    def _can_apply(self):
        from ....optimizer import MomentumOptimizer
        return bool(self.user_defined_strategy.lars) and \
            isinstance(self.user_defined_optimizer, MomentumOptimizer)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....optimizer import LarsMomentumOptimizer
        cfg = self.user_defined_strategy.lars_configs
        inner = self.user_defined_optimizer
        lars = LarsMomentumOptimizer(
            learning_rate=inner._learning_rate,
            momentum=getattr(inner, "_momentum", 0.9),
            lars_coeff=cfg.get("lars_coeff", 0.001),
            lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
            parameter_list=inner._parameter_list,
            regularization=inner.regularization,
            grad_clip=inner._grad_clip)
        return lars.minimize(loss, startup_program, parameter_list,
                             no_grad_set)
