"""Reference: distributed/fleet/meta_optimizers/dgc_optimizer.py — swap
Momentum for DGCMomentum when strategy.dgc is on."""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase


class DGCOptimizer(MetaOptimizerBase):
    strategy_flag = "dgc"

    def _can_apply(self):
        from ....optimizer import MomentumOptimizer
        return bool(self.user_defined_strategy.dgc) and \
            isinstance(self.user_defined_optimizer, MomentumOptimizer)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....optimizer import DGCMomentumOptimizer
        cfg = self.user_defined_strategy.dgc_configs
        inner = self.user_defined_optimizer
        dgc = DGCMomentumOptimizer(
            learning_rate=inner._learning_rate,
            momentum=getattr(inner, "_momentum", 0.9),
            rampup_begin_step=cfg.get("rampup_begin_step", 0),
            rampup_step=cfg.get("rampup_step", 1),
            sparsity=cfg.get("sparsity", [0.999]),
            use_nesterov=getattr(inner, "_use_nesterov", False),
            num_trainers=self.role_maker.worker_num(),
            parameter_list=inner._parameter_list,
            regularization=inner.regularization,
            grad_clip=inner._grad_clip)
        return dgc.minimize(loss, startup_program, parameter_list,
                            no_grad_set)
