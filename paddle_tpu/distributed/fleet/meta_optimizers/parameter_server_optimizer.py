"""ParameterServerOptimizer: fleet's PS-mode program rewrite.

Reference: distributed/fleet/meta_optimizers/parameter_server_optimizer.py
(+ the fluid DistributeTranspiler it drives).  Applies when the role
maker is non-collective (a PS cluster) or ``strategy.a_sync`` is set.
``minimize`` rewrites sparse lookups to the pulled-row form, appends
backward only (dense optimizer updates run on the server), and attaches a
``PSContext`` to the program that ``fleet.init_server / init_worker`` and
the ``PSTrainer`` consume.
"""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase

# inner-optimizer class name -> server-side table optimizer
_OPT_MAP = {
    "SGDOptimizer": "sgd",
    "MomentumOptimizer": "momentum",
    "AdagradOptimizer": "adagrad",
    "AdamOptimizer": "adam",
}


class ParameterServerOptimizer(MetaOptimizerBase):
    strategy_flag = "a_sync"

    def _can_apply(self) -> bool:
        rm = self.role_maker
        non_collective = rm is not None and not getattr(
            rm, "_is_collective", True)
        return bool(getattr(self.user_defined_strategy, "a_sync", False)
                    or non_collective)

    def _disable_strategy(self, strategy):
        strategy.a_sync = False

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....framework.backward import append_backward
        from ....framework.core import grad_var_name
        from ....distributed.ps.worker import (PSContext, _strip_startup_init,
                                               transpile_to_ps)

        # PS replaces the whole update path; composing it with other meta
        # optimizers (gradient merge, recompute, ...) would silently drop
        # them — the reference treats PS as exclusive, so do we, loudly.
        if self.inner_opt is not self.user_defined_optimizer:
            raise ValueError(
                "parameter-server mode cannot stack with other meta "
                "optimizers; disable the extra strategy flags")

        program = loss.block.program
        sections = transpile_to_ps(program)
        lazy = [s.table_name for s in sections if s.lazy_init]
        if lazy and startup_program is not None:
            _strip_startup_init(startup_program, lazy)

        params_grads = append_backward(loss, parameter_list, no_grad_set)

        inner = self.user_defined_optimizer
        opt_name = _OPT_MAP.get(type(inner).__name__)
        if opt_name is None:
            raise NotImplementedError(
                f"PS mode supports {sorted(_OPT_MAP)}; got "
                f"{type(inner).__name__}")
        opt_kwargs = {}
        if opt_name == "adam":
            opt_kwargs = {"beta1": getattr(inner, "_beta1", 0.9),
                          "beta2": getattr(inner, "_beta2", 0.999),
                          "epsilon": getattr(inner, "_epsilon", 1e-8)}
        elif opt_name == "momentum":
            opt_kwargs = {"momentum": getattr(inner, "_momentum", 0.9)}
        elif opt_name == "adagrad":
            opt_kwargs = {"epsilon": getattr(inner, "_epsilon", 1e-6)}
        if not isinstance(getattr(inner, "_learning_rate", 0.01),
                          (int, float)):
            import warnings
            warnings.warn(
                "PS mode freezes the learning rate at its current value; "
                "server-side LR schedules are not applied. Scale per-step "
                "via PSTrainer.lr_scale instead.")

        strategy = self.user_defined_strategy
        k_steps = int(strategy.a_sync_configs.get("k_steps", -1))
        if not getattr(strategy, "a_sync", False):
            mode = "sync"
        elif strategy.a_sync_configs.get("half_async", False):
            # barrier'd k-step batch (reference HalfAsyncCommunicator,
            # communicator.h:340)
            mode = "half_async"
        elif k_steps > 0:
            mode = "geo"
        else:
            mode = "async"
        if mode == "geo" and opt_name != "sgd":
            # geo-SGD is SGD by construction (local updates exchanged as
            # parameter deltas); the reference geo transpiler is SGD-only
            raise NotImplementedError(
                f"geo mode supports SGD only, got {type(inner).__name__}")

        dense = [(p.name, grad_var_name(p.name), tuple(p.shape))
                 for p, _g in params_grads]
        program._ps_ctx = PSContext(
            sections=sections, dense_params=dense, optimizer=opt_name,
            lr=float(inner.current_step_lr()), opt_kwargs=opt_kwargs,
            mode=mode, k_steps=max(k_steps, 1))
        # no optimize ops on the trainer: the server applies updates
        return [], params_grads
