"""Meta-optimizer stack (reference distributed/fleet/meta_optimizers/).

Each meta optimizer wraps an inner optimizer and rewrites the program (or
the lowering) to implement one distributed-training strategy; the strategy
compiler stacks the applicable ones (fleet_base.py:1019-1061 ranking).
"""
from .meta_optimizer_base import MetaOptimizerBase  # noqa
from .graph_execution_optimizer import GraphExecutionOptimizer  # noqa
from .lamb_optimizer import LambOptimizer  # noqa
from .lars_optimizer import LarsOptimizer  # noqa

META_OPTIMIZER_CLASSES = [
    # inner-most applied first; order mirrors the reference ranking
    LambOptimizer,
    LarsOptimizer,
    GraphExecutionOptimizer,
]


def register_meta_optimizer(cls, index=None):
    """Extension point used by amp/recompute/... as they land."""
    if index is None:
        META_OPTIMIZER_CLASSES.append(cls)
    else:
        META_OPTIMIZER_CLASSES.insert(index, cls)
    return cls
