"""Meta-optimizer stack (reference distributed/fleet/meta_optimizers/).

Each meta optimizer wraps an inner optimizer and rewrites the program (or
the lowering) to implement one distributed-training strategy; the strategy
compiler stacks the applicable ones (fleet_base.py:1019-1061 ranking).
"""
from .meta_optimizer_base import MetaOptimizerBase  # noqa
from .graph_execution_optimizer import GraphExecutionOptimizer  # noqa
from .lamb_optimizer import LambOptimizer  # noqa
from .lars_optimizer import LarsOptimizer  # noqa
from .amp_optimizer import AMPOptimizer  # noqa
from .dgc_optimizer import DGCOptimizer  # noqa
from .recompute_optimizer import RecomputeOptimizer  # noqa
from .gradient_merge_optimizer import GradientMergeOptimizer  # noqa
from .localsgd_optimizer import LocalSGDOptimizer  # noqa
from .sharding_optimizer import ShardingOptimizer  # noqa
from .pipeline_optimizer import PipelineOptimizer  # noqa
from .parameter_server_optimizer import ParameterServerOptimizer  # noqa

META_OPTIMIZER_CLASSES = [
    # inner-most applied first; order mirrors the reference ranking
    # (fleet_base.py:1019-1061): optimizer swaps, then backward-shaping
    # (amp/recompute), then update-shaping (gradient merge / localsgd),
    # then communication (dgc/sharding/graph execution)
    LambOptimizer,
    LarsOptimizer,
    DGCOptimizer,
    AMPOptimizer,
    RecomputeOptimizer,
    GradientMergeOptimizer,
    LocalSGDOptimizer,
    PipelineOptimizer,
    ShardingOptimizer,
    GraphExecutionOptimizer,
    # outermost: PS mode replaces the whole update path (server-side
    # optimize); reference ranks it exclusive with collective metas
    ParameterServerOptimizer,
]


def register_meta_optimizer(cls, index=None):
    """Extension point used by amp/recompute/... as they land."""
    if index is None:
        META_OPTIMIZER_CLASSES.append(cls)
    else:
        META_OPTIMIZER_CLASSES.insert(index, cls)
    return cls
