"""Reference: distributed/fleet/meta_optimizers/gradient_merge_optimizer.py."""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase


class GradientMergeOptimizer(MetaOptimizerBase):
    strategy_flag = "gradient_merge"

    def _can_apply(self):
        return bool(self.user_defined_strategy.gradient_merge) and \
            self.user_defined_strategy.gradient_merge_configs.get(
                "k_steps", 1) > 1

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....optimizer import GradientMergeOptimizer as GM
        cfg = self.user_defined_strategy.gradient_merge_configs
        gm = GM(self.inner_opt, k_steps=cfg.get("k_steps", 1),
                avg=cfg.get("avg", True))
        return gm.minimize(loss, startup_program, parameter_list,
                           no_grad_set)
