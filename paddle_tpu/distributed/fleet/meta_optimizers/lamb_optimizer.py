"""Reference: distributed/fleet/meta_optimizers/lamb_optimizer.py —
swap the inner optimizer for LAMB when strategy.lamb is on."""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase


class LambOptimizer(MetaOptimizerBase):
    strategy_flag = "lamb"

    def _can_apply(self):
        from ....optimizer import AdamOptimizer
        return bool(self.user_defined_strategy.lamb) and \
            isinstance(self.user_defined_optimizer, AdamOptimizer)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....optimizer import LambOptimizer as Lamb
        cfg = self.user_defined_strategy.lamb_configs
        inner = self.user_defined_optimizer
        exclude = set(cfg.get("exclude_from_weight_decay", []))

        def _exclude_fn(pname):
            return any(e in pname for e in exclude)

        lamb = Lamb(
            learning_rate=inner._learning_rate,
            lamb_weight_decay=cfg.get("lamb_weight_decay", 0.01),
            beta1=getattr(inner, "_beta1", 0.9),
            beta2=getattr(inner, "_beta2", 0.999),
            epsilon=getattr(inner, "_epsilon", 1e-6),
            exclude_from_weight_decay_fn=_exclude_fn if exclude else None,
            parameter_list=inner._parameter_list,
            regularization=inner.regularization,
            grad_clip=inner._grad_clip)
        return lamb.minimize(loss, startup_program, parameter_list,
                             no_grad_set)
