"""Reference: distributed/fleet/meta_optimizers/meta_optimizer_base.py."""
from __future__ import annotations


class MetaOptimizerBase:
    # strategy attribute that switches this optimizer on
    strategy_flag: str = ""

    def __init__(self, optimizer):
        self.inner_opt = optimizer
        self.role_maker = None
        self.user_defined_strategy = None

    def _set_basic_info(self, loss, role_maker, user_defined_optimizer,
                        user_defined_strategy):
        self.loss = loss
        self.role_maker = role_maker
        self.user_defined_optimizer = user_defined_optimizer
        self.user_defined_strategy = user_defined_strategy

    def _can_apply(self) -> bool:
        return bool(getattr(self.user_defined_strategy, self.strategy_flag,
                            False))

    def _disable_strategy(self, strategy):
        setattr(strategy, self.strategy_flag, False)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        return self.inner_opt.minimize(loss, startup_program,
                                       parameter_list, no_grad_set)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self.minimize_impl(loss, startup_program, parameter_list,
                                  no_grad_set)

    # pass through attributes optimizers expose
    def __getattr__(self, item):
        return getattr(self.__dict__["inner_opt"], item)
