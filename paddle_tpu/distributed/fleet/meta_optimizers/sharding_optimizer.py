"""ZeRO-style sharding.

Reference: distributed/fleet/meta_optimizers/sharding_optimizer.py:33 —
shards params + optimizer state across ranks by *rewriting the program*
into broadcast/allreduce segments with pruned non-owned vars
(minimize_impl:67: _split_program, _add_broadcast_allreduce,
_prune_main_program).

TPU-native: ZeRO is a *placement decision*, not a program rewrite. The
program is untouched; the CompiledProgram GSPMD path splits the device
axis into ("dp", "zero") with |zero| = sharding_degree, shards the batch
over both, shards every parameter and optimizer-state array over "zero"
(dim-0, when divisible), and XLA inserts exactly the ZeRO collectives:
all-gather of params before use, reduce-scatter of grads, sharded
optimizer update replicated across the dp groups.
"""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase

ZERO_AXIS = "zero"


class ShardingOptimizer(MetaOptimizerBase):
    strategy_flag = "sharding"

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        res = self.inner_opt.minimize(loss, startup_program,
                                      parameter_list, no_grad_set)
        cfg = self.user_defined_strategy.sharding_configs
        main = loss.block.program
        main._zero_sharding = {
            "degree": int(cfg.get("sharding_degree", 8)),
        }
        main.bump()
        return res


def zero_mesh(n_devices: int, degree: int):
    """(mesh, batch_axes) for ZeRO at `degree` over `n_devices`.

    Mirrors the reference's world-size check (sharding_optimizer.py
    degree asserts): a degree that doesn't divide the device count is an
    error, not a silent clamp."""
    from ....parallel.mesh import DP_AXIS, make_mesh

    degree = int(degree)
    if degree < 1 or degree > n_devices or n_devices % degree:
        raise ValueError(
            f"sharding_degree={degree} must divide the device count "
            f"{n_devices}")
    mesh = make_mesh({DP_AXIS: n_devices // degree, ZERO_AXIS: degree})
    return mesh, (DP_AXIS, ZERO_AXIS)


def zero_sharding_rules(mesh, axis: str = ZERO_AXIS):
    """Shard dim 0 of every sharding-eligible state array over `axis`.

    Covers parameters AND their optimizer moments (same shapes); scalars
    (lr, beta pows, loss-scale) and indivisible dims stay replicated."""
    from jax.sharding import PartitionSpec as P
    from ....parallel.sharded import ShardingRules

    size = dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)

    def fn(name, shape):
        if size <= 1 or not shape:
            return None
        if shape[0] % size == 0 and shape[0] >= size:
            return P(*([axis] + [None] * (len(shape) - 1)))
        return None

    return ShardingRules(fn)
