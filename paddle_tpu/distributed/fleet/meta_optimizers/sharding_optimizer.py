"""ZeRO-style sharding.

Reference: distributed/fleet/meta_optimizers/sharding_optimizer.py:33 —
shards params + optimizer state across ranks by *rewriting the program*
into broadcast/allreduce segments with pruned non-owned vars
(minimize_impl:67: _split_program, _add_broadcast_allreduce,
_prune_main_program).

TPU-native: ZeRO is a *placement decision*, not a program rewrite. The
program is untouched; the CompiledProgram GSPMD path shards every
parameter and optimizer-state array over the dp axis (dim-0, when
divisible) and XLA inserts exactly the ZeRO collectives: all-gather of
params before use, reduce-scatter of grads, sharded optimizer update.
"""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase


class ShardingOptimizer(MetaOptimizerBase):
    strategy_flag = "sharding"

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        res = self.inner_opt.minimize(loss, startup_program,
                                      parameter_list, no_grad_set)
        cfg = self.user_defined_strategy.sharding_configs
        main = loss.block.program
        main._zero_sharding = {
            "degree": int(cfg.get("sharding_degree", 8)),
        }
        main.bump()
        return res


def zero_sharding_rules(mesh, axis: str = "dp"):
    """Shard dim 0 of every sharding-eligible state array over `axis`."""
    from jax.sharding import PartitionSpec as P
    from ....parallel.sharded import ShardingRules

    size = dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)

    def fn(name, shape):
        if size <= 1 or not shape:
            return None
        if shape[0] % size == 0 and shape[0] >= size:
            return P(*([axis] + [None] * (len(shape) - 1)))
        return None

    return ShardingRules(fn)
