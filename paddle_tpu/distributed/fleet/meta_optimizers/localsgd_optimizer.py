"""LocalSGD: k local steps, then average parameters across workers.

Reference: distributed/fleet/meta_optimizers/localsgd_optimizer.py and
transpiler/collective.py:270 (LocalSGD transpiler) — no per-step gradient
allreduce; every k steps the params are synchronized. Here the periodic
sync is a conditional block whose c_allreduce_sum+scale lower to one
lax.cond-guarded psum over the dp axis.
"""
from __future__ import annotations

from ....framework.core import OpRole, unique_name
from ....framework.layer_helper import LayerHelper
from .meta_optimizer_base import MetaOptimizerBase


class LocalSGDOptimizer(MetaOptimizerBase):
    strategy_flag = "localsgd"

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....layers import tensor as T
        opt_ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        cfg = self.user_defined_strategy.localsgd_configs
        k = int(cfg.get("k_steps", 1))
        nranks = self.role_maker.worker_num()
        main = loss.block.program
        block = main.global_block()
        helper = LayerHelper("localsgd")

        step = T.create_global_var([1], 0.0, "float32", persistable=True,
                                   name=unique_name("localsgd_step"))
        T.increment(step, 1.0)
        mod = T.elementwise_mod(step, T.fill_constant([1], "float32",
                                                      float(k)))
        cond_var = T.equal(mod, T.fill_constant([1], "float32", 0.0))

        sub = main._create_block()
        params = [p for p, _ in params_grads]
        for p in params:
            helper.append_op("c_allreduce_sum", inputs={"X": [p]},
                             outputs={"Out": [p]},
                             attrs={"ring_id": 0,
                                    "op_role": OpRole.Optimize})
            helper.append_op("scale", inputs={"X": [p]},
                             outputs={"Out": [p]},
                             attrs={"scale": 1.0 / nranks,
                                    "op_role": OpRole.Optimize})
        main._rollback()
        block.append_op("conditional_block",
                        inputs={"Cond": [cond_var]},
                        outputs={"Out": params},
                        attrs={"sub_block": sub.idx,
                               "op_role": OpRole.Optimize},
                        infer_shape=False)
        main.bump()
        return opt_ops, params_grads
