"""Collective data-parallel rewrite.

Reference: the transpiler inserts, after the backward pass, a
`scale + c_allreduce_sum (+ c_sync_*)` per gradient and comm bootstrap ops
into the startup program (transpiler/collective.py:178 GradAllReduce,
fleet meta_optimizers/graph_execution_optimizer.py).

Same rewrite here — and because the program executes as one shard_map'd
SPMD computation (parallel/spmd.py), each inserted c_allreduce_sum lowers
to one lax.psum over the dp mesh axis (ICI), with XLA free to fuse/overlap
them (the reference needed fuse_all_reduce_op_pass + stream juggling for
that).
"""
from __future__ import annotations

from ....framework.core import OpRole
from .meta_optimizer_base import MetaOptimizerBase


class GraphExecutionOptimizer(MetaOptimizerBase):
    strategy_flag = "_collective_dp"  # applied by default in collective mode

    def _can_apply(self):
        s = self.user_defined_strategy
        # strategies that own their own communication pattern
        if s.localsgd or s.sharding or s.dgc or s.a_sync:
            return False
        return self.role_maker is not None and \
            self.role_maker.worker_num() > 1

    def _disable_strategy(self, strategy):
        pass

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        opt_ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        nranks = self.role_maker.worker_num()
        main = loss.block.program
        fp16_ar = bool(self.user_defined_strategy.fp16_allreduce)
        self._insert_allreduce(main, params_grads, nranks,
                               fp16_allreduce=fp16_ar)
        self._init_communicator(startup_program)
        main.bump()
        return opt_ops, params_grads

    def _init_communicator(self, startup_program):
        from ....framework.core import default_startup_program
        startup = startup_program or default_startup_program()
        block = startup.global_block()
        nccl_id = block.create_var(name="nccl_id_0", shape=(1,),
                                   dtype="int32", persistable=True)
        block.append_op("c_gen_nccl_id", outputs={"Out": [nccl_id]},
                        attrs={"ring_id": 0})
        block.append_op("c_comm_init", inputs={"X": [nccl_id]},
                        attrs={"ring_id": 0})

    @staticmethod
    def _insert_allreduce(main, params_grads, nranks,
                          fp16_allreduce=False):
        """fp16_allreduce (reference fp16_allreduce_optimizer.py):
        compress the wire format of the allreduce — here a bf16 cast pair
        around the collective (bf16 is the TPU-native half type)."""
        block = main.global_block()
        grad_names = {g.name for _, g in params_grads if g is not None}
        # first optimize-role op = end of backward
        insert_at = len(block.ops)
        for i, op in enumerate(block.ops):
            if op.attr("op_role") == OpRole.Optimize:
                insert_at = i
                break
        for _, g in params_grads:
            if g is None:
                continue
            block._insert_op(
                insert_at, "scale", inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"scale": 1.0 / nranks, "op_role": OpRole.Backward})
            insert_at += 1
            if fp16_allreduce:
                block._insert_op(
                    insert_at, "cast", inputs={"X": [g]},
                    outputs={"Out": [g]},
                    attrs={"out_dtype": "bfloat16",
                           "op_role": OpRole.Backward}, infer_shape=False)
                insert_at += 1
            block._insert_op(
                insert_at, "c_allreduce_sum",
                inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"ring_id": 0, "op_role": OpRole.Backward})
            insert_at += 1
            if fp16_allreduce:
                block._insert_op(
                    insert_at, "cast", inputs={"X": [g]},
                    outputs={"Out": [g]},
                    attrs={"out_dtype": "float32",
                           "op_role": OpRole.Backward}, infer_shape=False)
                insert_at += 1
        return grad_names
