"""Collective data-parallel rewrite.

Reference: the transpiler inserts, after the backward pass, a
`scale + c_allreduce_sum (+ c_sync_*)` per gradient and comm bootstrap ops
into the startup program (transpiler/collective.py:178 GradAllReduce,
fleet meta_optimizers/graph_execution_optimizer.py).

Same rewrite here — and because the program executes as one shard_map'd
SPMD computation (parallel/spmd.py), each inserted c_allreduce_sum lowers
to one lax.psum over the dp mesh axis (ICI), with XLA free to fuse/overlap
them (the reference needed fuse_all_reduce_op_pass + stream juggling for
that).
"""
from __future__ import annotations

from ....framework.core import OpRole
from .meta_optimizer_base import MetaOptimizerBase


class GraphExecutionOptimizer(MetaOptimizerBase):
    strategy_flag = "_collective_dp"  # applied by default in collective mode

    def _can_apply(self):
        return self.role_maker is not None and \
            self.role_maker.worker_num() > 1

    def _disable_strategy(self, strategy):
        pass

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        opt_ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        nranks = self.role_maker.worker_num()
        main = loss.block.program
        self._insert_allreduce(main, params_grads, nranks)
        self._init_communicator(startup_program)
        main.bump()
        return opt_ops, params_grads

    def _init_communicator(self, startup_program):
        from ....framework.core import default_startup_program
        startup = startup_program or default_startup_program()
        block = startup.global_block()
        nccl_id = block.create_var(name="nccl_id_0", shape=(1,),
                                   dtype="int32", persistable=True)
        block.append_op("c_gen_nccl_id", outputs={"Out": [nccl_id]},
                        attrs={"ring_id": 0})
        block.append_op("c_comm_init", inputs={"X": [nccl_id]},
                        attrs={"ring_id": 0})

    @staticmethod
    def _insert_allreduce(main, params_grads, nranks):
        block = main.global_block()
        grad_names = {g.name for _, g in params_grads if g is not None}
        # first optimize-role op = end of backward
        insert_at = len(block.ops)
        for i, op in enumerate(block.ops):
            if op.attr("op_role") == OpRole.Optimize:
                insert_at = i
                break
        for _, g in params_grads:
            if g is None:
                continue
            block._insert_op(
                insert_at, "scale", inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"scale": 1.0 / nranks, "op_role": OpRole.Backward})
            block._insert_op(
                insert_at + 1, "c_allreduce_sum",
                inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"ring_id": 0, "op_role": OpRole.Backward})
            insert_at += 2
        return grad_names
