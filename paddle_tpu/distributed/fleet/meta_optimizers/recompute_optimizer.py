"""Reference: distributed/fleet/meta_optimizers/recompute_optimizer.py."""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase


class RecomputeOptimizer(MetaOptimizerBase):
    strategy_flag = "recompute"

    def _can_apply(self):
        return bool(self.user_defined_strategy.recompute) and \
            bool(self.user_defined_strategy.recompute_configs.get(
                "checkpoints"))

    def _wrapped(self):
        from ....optimizer import RecomputeOptimizer as Recompute
        cfg = self.user_defined_strategy.recompute_configs
        rec = Recompute(self.inner_opt)
        rec._set_checkpoints(list(cfg["checkpoints"]))
        return rec

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._wrapped().backward(loss, startup_program,
                                        parameter_list, no_grad_set,
                                        callbacks)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        return self._wrapped().minimize(loss, startup_program,
                                        parameter_list, no_grad_set)
