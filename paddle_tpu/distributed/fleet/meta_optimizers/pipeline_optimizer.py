"""Reference: distributed/fleet/meta_optimizers/pipeline_optimizer.py —
wrap with the fluid PipelineOptimizer per strategy.pipeline_configs."""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase


class PipelineOptimizer(MetaOptimizerBase):
    strategy_flag = "pipeline"

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....optimizer import PipelineOptimizer as Pipe
        cfg = self.user_defined_strategy.pipeline_configs
        pipe = Pipe(self.inner_opt,
                    num_microbatches=cfg.get("accumulate_steps", 1))
        return pipe.minimize(loss, startup_program, parameter_list,
                             no_grad_set)
