"""Role makers: who am I in the cluster.

Reference: python/paddle/distributed/fleet/base/role_maker.py (Role enum:33,
PaddleCloudRoleMaker:535 parsing PADDLE_* env, Gloo rendezvous:364).
TPU-native: rendezvous is jax.distributed; in the common single-host case
"workers" are the local mesh devices.
"""
from __future__ import annotations

import os


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self):
        self._worker_endpoints = []
        self._server_endpoints = []
        self._role = Role.WORKER
        self._current_id = 0

    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER

    def is_first_worker(self) -> bool:
        return self.is_worker() and self.worker_index() == 0

    def worker_index(self) -> int:
        return self._current_id

    def server_index(self) -> int:
        return self._current_id

    def worker_num(self) -> int:
        return max(1, len(self._worker_endpoints))

    def server_num(self) -> int:
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints

    # reference underscore-aliases used throughout fleet
    _is_worker = is_worker
    _is_server = is_server
    _is_first_worker = is_first_worker
    _worker_index = worker_index
    _server_index = server_index
    _worker_num = worker_num
    _server_num = server_num


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-var cluster spec (reference role_maker.py:535). With no env set
    and is_collective, the local device mesh is the cluster."""

    def __init__(self, is_collective: bool = False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        self._generate_role()

    def _generate_role(self):
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = [e for e in eps.split(",") if e]
        self._server_endpoints = [
            e for e in os.getenv("PADDLE_PSERVERS_IP_PORT_LIST",
                                 "").split(",") if e]
        role = os.getenv("TRAINING_ROLE", "TRAINER").upper()
        self._role = Role.SERVER if role == "PSERVER" else Role.WORKER
        if self._role == Role.SERVER:
            self._current_id = int(os.getenv("PADDLE_PSERVER_ID", "0"))
        else:
            self._current_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        if not self._worker_endpoints and self._is_collective:
            # single host: each local device is a data-parallel participant
            import jax
            self._worker_endpoints = [
                f"local:{i}" for i in range(jax.device_count())]

    def worker_num(self) -> int:
        n = os.getenv("PADDLE_TRAINERS_NUM")
        if n is not None:
            return int(n)
        return max(1, len(self._worker_endpoints))

    _worker_num = worker_num


class UserDefinedRoleMaker(RoleMakerBase):
    """Explicit cluster spec (reference fleet 1.x UserDefinedRoleMaker)."""

    def __init__(self, current_id=0, role=Role.WORKER, worker_num=0,
                 server_endpoints=None, worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._is_collective = False  # PS-style cluster spec
        self._server_endpoints = server_endpoints or []
        self._worker_endpoints = worker_endpoints or \
            [f"w:{i}" for i in range(worker_num)]
