"""Profiler: device traces + host event annotation.

Reference: platform/profiler.h:127 (RecordEvent, EnableProfiler /
DisableProfiler, profiler.py:start_profiler/stop_profiler) +
platform/device_tracer.h:43 (CUPTI device tracer).  TPU-native: the
device tracer IS jax.profiler (XLA/TPU runtime events, HLO timelines,
memory viewer); this module gives it the reference's API shape and adds
a host-side summary so ``stop_profiler('total')`` can print a table
without TensorBoard.
"""
from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import time
from collections import Counter
from typing import Optional

__all__ = ["profiler", "start_profiler", "stop_profiler", "RecordEvent",
           "load_trace", "summarize_trace"]

_active_dir: Optional[str] = None


def start_profiler(state: str = "All", tracer_option: str = "Default",
                   trace_dir: Optional[str] = None):
    """reference fluid.profiler.start_profiler; `state` is advisory (the
    XLA trace always captures host+device)."""
    import jax

    global _active_dir
    if _active_dir is not None:
        raise RuntimeError("profiler already running")
    target = trace_dir or os.path.join(
        os.getcwd(), f"paddle_tpu_profile_{int(time.time())}")
    jax.profiler.start_trace(target)
    _active_dir = target  # only after start succeeded
    return _active_dir


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: Optional[str] = None) -> Optional[str]:
    """Stop tracing; optionally print the reference-style op table
    (sorted_key in {'total', 'calls', 'ave'}) and return the trace dir."""
    import jax

    global _active_dir
    if _active_dir is None:
        return None
    trace_dir = _active_dir
    try:
        jax.profiler.stop_trace()
    finally:
        # even a failed stop tears down the session state: leaving
        # _active_dir set would wedge start_profiler ("profiler already
        # running") for the rest of the process
        _active_dir = None
    if sorted_key:
        table = summarize_trace(trace_dir, sorted_key)
        print(table)
        if profile_path:
            with open(profile_path, "w") as f:
                f.write(table)
    return trace_dir


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = None,
             profile_path: Optional[str] = None,
             trace_dir: Optional[str] = None):
    """reference fluid.profiler.profiler context manager."""
    start_profiler(state, trace_dir=trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class RecordEvent:
    """Annotate a host region; shows on the trace timeline (reference
    platform/profiler.h RecordEvent -> jax.profiler.TraceAnnotation)."""

    def __init__(self, name: str):
        import jax

        self.name = name
        self._ann = jax.profiler.TraceAnnotation(name)

    def __enter__(self):
        self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        return self._ann.__exit__(*exc)


def load_trace(trace_dir: str) -> dict:
    """Load the captured trace's event JSON (the .trace.json.gz the XLA
    profiler writes; also what TensorBoard reads)."""
    files = sorted(glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True))
    if not files:
        raise FileNotFoundError(f"no trace found under {trace_dir}")
    with gzip.open(files[-1]) as f:
        return json.load(f)


def summarize_trace(trace_dir: str, sorted_key: str = "total",
                    top: int = 30) -> str:
    """Reference-style event table (profiler.cc PrintProfiler analog)."""
    trace = load_trace(trace_dir)
    dur, calls = Counter(), Counter()
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "X" and "dur" in e and e.get("name"):
            dur[e["name"]] += e["dur"]
            calls[e["name"]] += 1
    rows = [(n, dur[n] / 1e3, calls[n], dur[n] / 1e3 / calls[n])
            for n in dur]
    key = {"total": lambda r: -r[1], "calls": lambda r: -r[2],
           "ave": lambda r: -r[3]}.get(sorted_key, lambda r: -r[1])
    rows.sort(key=key)
    lines = [f"{'Event':60s} {'Total(ms)':>12s} {'Calls':>8s} "
             f"{'Ave(ms)':>10s}"]
    for n, tot, c, ave in rows[:top]:
        lines.append(f"{n[:60]:60s} {tot:12.3f} {c:8d} {ave:10.4f}")
    return "\n".join(lines)
