"""Data pipeline: Dataset / BatchSampler / DataLoader with background
prefetch and host->device double buffering.

Reference surface: python/paddle/fluid/reader.py:147 (DataLoader,
from_generator:418), fluid/dataloader/{dataset.py:26,batch_sampler.py:27},
and the C++ double-buffered device prefetch
(operators/reader/buffered_reader.cc).  TPU-first inversions:

  * Worker pool is a *thread* pool by default: collate is numpy (GIL
    released) and the consumer is an XLA step that runs seconds per
    batch, so processes (the reference's default, needed for Python-heavy
    GPU-era augmentation) buy nothing but fork cost.  ``num_workers``
    still sizes the pool; ``use_process=True`` opts into a
    multiprocessing pool for CPU-heavy user ``__getitem__``.
  * Device double buffering = ``jax.device_put`` of batch N+1 issued
    while batch N computes (dispatch is async), replacing
    buffered_reader.cc's cudaMemcpyAsync ping-pong.  The executor then
    sees device-resident arrays and skips its own H2D copy.
  * Everything yields dicts keyed by feed name (or tuples), matching
    ``Executor.run(feed=...)`` — no LoDTensor conversion layer.

Also provides the classic decorator readers (``paddle.batch``-style
``batch``/``shuffle``/``chain``) and ``DataFeeder`` for API parity.

Telemetry (paddle_tpu/telemetry.py): ``reader_prefetch_depth`` gauge —
staged-batch occupancy of the ``device_prefetch`` ring as each batch is
yielded (pinned at 0/1 while the full ``depth`` was requested means the
host pipeline, not the device, is the bottleneck).  The executor's own
2-deep feed ring reports as ``feed_ring_occupancy``.
"""
from __future__ import annotations

import itertools
import threading
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Sequence)

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "BatchSampler",
           "RandomSampler", "SequenceSampler", "DataLoader", "DataFeeder",
           "batch", "shuffle", "chain", "device_prefetch",
           "stage_to_device"]


# ---------------------------------------------------------------------------
# datasets
# ---------------------------------------------------------------------------
class Dataset:
    """Map-style dataset (reference fluid/dataloader/dataset.py:26)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    """Stream-style dataset: define __iter__ instead of __getitem__."""

    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset has no random access")

    def __len__(self):
        raise TypeError("IterableDataset has no length")


class TensorDataset(Dataset):
    """Wrap aligned arrays: sample i = tuple(arr[i] for arr in arrays)."""

    def __init__(self, *arrays):
        n = len(arrays[0])
        assert all(len(a) == n for a in arrays), "length mismatch"
        self.arrays = [np.asarray(a) for a in arrays]

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.arrays)

    def __len__(self):
        return len(self.arrays[0])


# ---------------------------------------------------------------------------
# samplers
# ---------------------------------------------------------------------------
class SequenceSampler:
    def __init__(self, n: int):
        self.n = n

    def __iter__(self):
        return iter(range(self.n))

    def __len__(self):
        return self.n


class RandomSampler:
    def __init__(self, n: int, seed: Optional[int] = None):
        self.n = n
        self.seed = seed
        self._epoch = 0

    def __iter__(self):
        rng = np.random.RandomState(
            None if self.seed is None else self.seed + self._epoch)
        self._epoch += 1
        return iter(rng.permutation(self.n).tolist())

    def __len__(self):
        return self.n


class BatchSampler:
    """Yields lists of indices (reference dataloader/batch_sampler.py:27)."""

    def __init__(self, dataset=None, sampler=None, shuffle: bool = False,
                 batch_size: int = 1, drop_last: bool = False,
                 seed: Optional[int] = None):
        if sampler is None:
            n = len(dataset)
            sampler = RandomSampler(n, seed) if shuffle \
                else SequenceSampler(n)
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.drop_last = drop_last

    def __iter__(self):
        buf: List[int] = []
        for idx in self.sampler:
            buf.append(idx)
            if len(buf) == self.batch_size:
                yield buf
                buf = []
        if buf and not self.drop_last:
            yield buf

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else \
            -(-n // self.batch_size)


# ---------------------------------------------------------------------------
# collate
# ---------------------------------------------------------------------------
def default_collate(samples: Sequence) -> Any:
    """Stack a list of samples into batch arrays (tuple/dict aware)."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate(col)
                           for col in zip(*samples))
    return np.stack([np.asarray(s) for s in samples])


# ---------------------------------------------------------------------------
# device double buffering
# ---------------------------------------------------------------------------
def stage_to_device(batch, device=None):
    """``device_put`` one batch (dict / tuple / array) — the building
    block of both ``device_prefetch``'s ping-pong staging and the
    Executor's double-buffered feed ring.  ``device_put`` dispatches
    asynchronously, so the H2D DMA overlaps whatever step is already
    running on the device; values that are already device arrays pass
    through untouched (no host round-trip)."""
    import jax

    def put(v):
        if hasattr(v, "devices") and device is None:
            return v  # already device-resident; leave its placement alone
        return jax.device_put(v, device)

    if isinstance(batch, dict):
        return {k: put(v) for k, v in batch.items()}
    if isinstance(batch, (tuple, list)):
        return type(batch)(put(v) for v in batch)
    return put(batch)


def device_prefetch(it: Iterable, depth: int = 2, device=None):
    """Stage batches onto the device ahead of consumption.

    jax dispatch is asynchronous: ``device_put`` returns immediately and
    the DMA overlaps the running step — the TPU analog of
    buffered_reader.cc's ping-pong staging buffers.  ``depth`` bounds
    device memory spent on staged batches.
    """

    from . import telemetry

    def put(b):
        return stage_to_device(b, device)

    it = iter(it)
    staged: List[Any] = []
    try:
        for _ in range(depth):
            staged.append(put(next(it)))
    except StopIteration:
        pass  # ok: prefetch window larger than the dataset
    while staged:
        out = staged.pop(0)
        try:
            staged.append(put(next(it)))
        except StopIteration:
            pass  # ok: source exhausted; drain the staged batches
        # occupancy at yield time: < depth means the consumer outruns
        # the host pipeline (the feed, not the chip, is the bottleneck)
        telemetry.gauge_set("reader_prefetch_depth", len(staged))
        yield out


# ---------------------------------------------------------------------------
# DataLoader
# ---------------------------------------------------------------------------
_END = object()


class DataLoader:
    """Iterates a dataset by batches with background workers + device
    staging.  Mirrors reference DataLoader (fluid/reader.py:147) minus the
    LoDTensor plumbing; see module docstring for the TPU inversions.

    feed_list: optional list of Variables (or names) — batches then yield
    as feed dicts ready for ``Executor.run``.
    """

    def __init__(self, dataset: Dataset, feed_list=None,
                 batch_size: int = 1, shuffle: bool = False,
                 batch_sampler: Optional[BatchSampler] = None,
                 num_workers: int = 0, collate_fn: Optional[Callable] = None,
                 drop_last: bool = False, prefetch_factor: int = 2,
                 use_double_buffer: bool = True, seed: Optional[int] = None,
                 use_process: bool = False, return_list: bool = False):
        self.dataset = dataset
        self.feed_names = [getattr(v, "name", v) for v in feed_list or []]
        self.return_list = return_list or not self.feed_names
        self.collate_fn = collate_fn or default_collate
        self.num_workers = int(num_workers)
        self.prefetch_factor = max(1, int(prefetch_factor))
        self.use_double_buffer = use_double_buffer
        self.use_process = use_process
        self._iterable_ds = isinstance(dataset, IterableDataset)
        if self._iterable_ds:
            self.batch_sampler = None
            self.batch_size = int(batch_size)
            self.drop_last = drop_last
        else:
            self.batch_sampler = batch_sampler or BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last, seed=seed)

    def __len__(self):
        if self._iterable_ds:
            raise TypeError("IterableDataset pipeline has no length")
        return len(self.batch_sampler)

    # -- batch production ----------------------------------------------------
    def _batches_sync(self) -> Iterator:
        if self._iterable_ds:
            buf = []
            for sample in self.dataset:
                buf.append(sample)
                if len(buf) == self.batch_size:
                    yield self.collate_fn(buf)
                    buf = []
            if buf and not self.drop_last:
                yield self.collate_fn(buf)
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def _batches_threaded(self) -> Iterator:
        """num_workers threads collate index-batches concurrently;
        delivery is in sampler order with bounded read-ahead (reference
        _DataLoaderIterMultiProcess reordering + outstanding cap)."""
        batches = list(self.batch_sampler)
        results: Dict[int, Any] = {}
        cond = threading.Condition()
        cursor = [0]    # next batch index to claim
        consumed = [0]  # next batch index the consumer wants
        err: List[BaseException] = []
        max_ahead = max(self.num_workers * self.prefetch_factor, 1)

        def worker():
            while True:
                with cond:
                    i = cursor[0]
                    if i >= len(batches) or err:
                        return
                    cursor[0] = i + 1
                try:
                    out = self.collate_fn(
                        [self.dataset[j] for j in batches[i]])
                except BaseException as e:
                    with cond:
                        err.append(e)
                        cond.notify_all()
                    return
                with cond:
                    while i - consumed[0] >= max_ahead and not err:
                        cond.wait(0.1)  # backpressure
                    results[i] = out
                    cond.notify_all()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            for i in range(len(batches)):
                with cond:
                    while i not in results and not err:
                        cond.wait(0.1)
                    if err:
                        raise err[0]
                    out = results.pop(i)
                    consumed[0] = i + 1
                    cond.notify_all()
                yield out
        finally:
            with cond:
                cursor[0] = len(batches)  # stop stragglers
                err.append(GeneratorExit())
                cond.notify_all()
            for t in threads:
                t.join(timeout=5)
            with cond:
                if err and isinstance(err[0], GeneratorExit):
                    err.clear()

    def _batches_process(self) -> Iterator:
        """Opt-in multiprocessing pool for CPU-bound __getitem__."""
        import multiprocessing as mp
        batches = list(self.batch_sampler)
        with mp.get_context("fork").Pool(self.num_workers) as pool:
            for out in pool.imap(_CollateJob(self.dataset, self.collate_fn),
                                 batches):
                yield out

    def __iter__(self) -> Iterator:
        if self.num_workers > 0 and not self._iterable_ds:
            src = (self._batches_process() if self.use_process
                   else self._batches_threaded())
        else:
            if self.num_workers > 0:
                import warnings
                warnings.warn(
                    "DataLoader: num_workers has no effect on an "
                    "IterableDataset (a stream has no index space to "
                    "partition); reading single-threaded")
            src = self._batches_sync()
        if self.feed_names and not self.return_list:
            src = (dict(zip(self.feed_names,
                            b if isinstance(b, (tuple, list)) else (b,)))
                   for b in src)
        if self.use_double_buffer:
            src = device_prefetch(src, depth=self.prefetch_factor)
        return src

    # -- reference compat constructors ---------------------------------------
    @staticmethod
    def from_generator(feed_list=None, capacity: int = 2,
                       use_double_buffer: bool = True, iterable: bool = True,
                       return_list: bool = False, drop_last: bool = True):
        """reference fluid/reader.py:418 — returns a loader whose
        ``set_batch_generator(fn)`` installs a python generator of
        ready-made batches."""
        return _GeneratorLoader(feed_list, capacity, use_double_buffer,
                                return_list=return_list,
                                drop_last=drop_last)


class _CollateJob:
    """Picklable worker job for the process pool."""

    def __init__(self, dataset, collate_fn):
        self.dataset = dataset
        self.collate_fn = collate_fn

    def __call__(self, idxs):
        return self.collate_fn([self.dataset[i] for i in idxs])


class _GeneratorLoader:
    """from_generator flavor: user supplies batch/sample generators."""

    def __init__(self, feed_list, capacity, use_double_buffer,
                 return_list=False, drop_last=True):
        self.feed_names = [getattr(v, "name", v) for v in feed_list or []]
        self.capacity = capacity
        self.use_double_buffer = use_double_buffer
        self.return_list = return_list
        self.drop_last = drop_last
        self._gen = None
        self._mode = "batch"

    def set_batch_generator(self, fn, places=None):
        self._gen = fn
        self._mode = "batch"
        return self

    def set_sample_list_generator(self, fn, places=None):
        self._gen = fn
        self._mode = "sample_list"
        return self

    def set_sample_generator(self, fn, batch_size, drop_last=None,
                             places=None):
        self._gen = fn
        self._mode = "sample"
        self._batch_size = batch_size
        if drop_last is not None:  # explicit arg wins over constructor
            self.drop_last = drop_last
        return self

    def __iter__(self):
        if self._gen is None:
            raise RuntimeError("set_*_generator was never called")
        if self._mode == "batch":
            src = self._gen()
        elif self._mode == "sample_list":
            src = (default_collate(s) for s in self._gen())
        else:
            src = (default_collate(s) for s in
                   batch(self._gen, self._batch_size, self.drop_last)())
        if self.feed_names and not self.return_list:
            src = (dict(zip(self.feed_names,
                            b if isinstance(b, (tuple, list)) else (b,)))
                   for b in src)
        if self.use_double_buffer:
            src = device_prefetch(src, depth=self.capacity)
        return iter(src)


# ---------------------------------------------------------------------------
# classic decorator readers (paddle.batch / paddle.reader.*)
# ---------------------------------------------------------------------------
def batch(reader: Callable, batch_size: int, drop_last: bool = False):
    """reference python/paddle/batch.py: sample reader -> batch reader."""

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader


def shuffle(reader: Callable, buf_size: int, seed: Optional[int] = None):
    """reference python/paddle/reader/decorator.py shuffle."""

    def shuffled():
        rng = np.random.RandomState(seed)
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        rng.shuffle(buf)
        yield from buf

    return shuffled


def chain(*readers: Callable):
    def chained():
        return itertools.chain(*[r() for r in readers])

    return chained


class DataFeeder:
    """reference fluid/data_feeder.py: list-of-samples -> feed dict."""

    def __init__(self, feed_list, place=None, program=None):
        self.feed_names = [getattr(v, "name", v) for v in feed_list]

    def feed(self, iterable) -> Dict[str, np.ndarray]:
        cols = list(zip(*iterable))
        assert len(cols) == len(self.feed_names), \
            f"sample arity {len(cols)} != feed arity {len(self.feed_names)}"
        return {n: default_collate(c)
                for n, c in zip(self.feed_names, cols)}
