"""Detection op family — TPU-native rebuild of operators/detection/.

Reference: paddle/fluid/operators/detection/{iou_similarity_op.h:20,
box_coder_op.h:41,118, prior_box_op.h:95-170, anchor_generator_op.h:43,
yolo_box_op.h:29-151, bipartite_match_op.cc:71, multiclass_nms_op.cc:139,
box_clip_op.h + bbox_util.h:157} and operators/roi_{align,pool}_op.h.

Design inversion for TPU: the reference kernels are scalar loops with
data-dependent control flow (skip-if-below-threshold, variable-length
LoD outputs). Here every op is a fixed-shape dense computation:

  * threshold "skips" become masks (yolo_box zeroes suppressed entries —
    exactly what the reference's memset-0-then-skip produces);
  * variable-length NMS outputs become padded [K, ...] tensors plus an
    explicit count (the multiclass_nms3-style Index/NmsRoisNum outputs),
    the same masked-replacement convention as sequence_ops;
  * greedy NMS / bipartite match run a fixed number of argmax-suppress
    iterations under lax.fori_loop (K iterations of an O(M) vector step
    instead of data-dependent list surgery);
  * roi_align requires a static sampling_ratio >= 1 (the reference's
    adaptive ceil(roi_h/ph) grid is data-dependent and cannot be a
    static XLA shape).
"""
from __future__ import annotations

import math

import numpy as np

from ..errors import InvalidArgumentError, UnimplementedError
from .registry import in_var, register_op, set_out


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# iou_similarity
# ---------------------------------------------------------------------------

def _iou_matrix(jnp, x, y, normalized, eps=1e-10):
    """x [N,4], y [M,4] -> [N,M] (reference iou_similarity_op.h:20)."""
    off = 0.0 if normalized else 1.0
    ax = (x[:, 2] - x[:, 0] + off) * (x[:, 3] - x[:, 1] + off)
    ay = (y[:, 2] - y[:, 0] + off) * (y[:, 3] - y[:, 1] + off)
    ix0 = jnp.maximum(x[:, None, 0], y[None, :, 0])
    iy0 = jnp.maximum(x[:, None, 1], y[None, :, 1])
    ix1 = jnp.minimum(x[:, None, 2], y[None, :, 2])
    iy1 = jnp.minimum(x[:, None, 3], y[None, :, 3])
    iw = jnp.maximum(ix1 - ix0 + off, 0.0)
    ih = jnp.maximum(iy1 - iy0 + off, 0.0)
    inter = iw * ih
    return inter / (ax[:, None] + ay[None, :] - inter + eps)


def _iou_sim_infer(op, block):
    x = in_var(op, block, "X")
    y = in_var(op, block, "Y")
    set_out(op, block, "Out", (x.shape[0], y.shape[0]), x.dtype)


@register_op("iou_similarity", infer=_iou_sim_infer, grad="auto")
def _iou_similarity(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    y = ctx.get_input(op, "Y")
    normalized = op.attr("box_normalized", True)
    ctx.set_output(op, "Out", _iou_matrix(jnp, x, y, normalized))


# ---------------------------------------------------------------------------
# box_coder
# ---------------------------------------------------------------------------

def _box_coder_infer(op, block):
    t = in_var(op, block, "TargetBox")
    p = in_var(op, block, "PriorBox")
    code_type = op.attr("code_type", "encode_center_size")
    if code_type == "encode_center_size":
        out = (t.shape[0], p.shape[0], 4)
    else:
        out = tuple(t.shape)
    set_out(op, block, "OutputBox", out, t.dtype)


@register_op("box_coder", infer=_box_coder_infer, grad="auto")
def _box_coder(ctx, op):
    """reference box_coder_op.h:41 (EncodeCenterSize) / :118 (Decode)."""
    jnp = _jnp()
    t = ctx.get_input(op, "TargetBox")
    p = ctx.get_input(op, "PriorBox")
    pvar = (ctx.get_input(op, "PriorBoxVar")
            if op.single_input("PriorBoxVar") else None)
    code_type = op.attr("code_type", "encode_center_size")
    normalized = op.attr("box_normalized", True)
    variance = op.attr("variance", []) or []
    axis = op.attr("axis", 0)
    off = 0.0 if normalized else 1.0

    pw = p[:, 2] - p[:, 0] + off
    ph = p[:, 3] - p[:, 1] + off
    pcx = p[:, 0] + pw / 2
    pcy = p[:, 1] + ph / 2

    if code_type == "encode_center_size":
        tw = t[:, 2] - t[:, 0] + off           # [N]
        th = t[:, 3] - t[:, 1] + off
        tcx = (t[:, 0] + t[:, 2]) / 2
        tcy = (t[:, 1] + t[:, 3]) / 2
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([ox, oy, ow, oh], axis=-1)  # [N,M,4]
        if pvar is not None:
            out = out / pvar[None, :, :]
        elif variance:
            out = out / jnp.asarray(variance, out.dtype)
    elif code_type == "decode_center_size":
        # t: [N,M,4] deltas; prior per column (axis=0) or per row (axis=1)
        expand = (lambda a: a[None, :]) if axis == 0 else \
            (lambda a: a[:, None])
        if pvar is not None:
            var = pvar[None, :, :] if axis == 0 else pvar[:, None, :]
        elif variance:
            var = jnp.asarray(variance, t.dtype)[None, None, :]
        else:
            var = jnp.ones((1, 1, 4), t.dtype)
        tcx = var[..., 0] * t[..., 0] * expand(pw) + expand(pcx)
        tcy = var[..., 1] * t[..., 1] * expand(ph) + expand(pcy)
        tw = jnp.exp(var[..., 2] * t[..., 2]) * expand(pw)
        th = jnp.exp(var[..., 3] * t[..., 3]) * expand(ph)
        out = jnp.stack([tcx - tw / 2, tcy - th / 2,
                         tcx + tw / 2 - off, tcy + th / 2 - off], axis=-1)
    else:
        raise InvalidArgumentError(
            f"box_coder: unknown code_type {code_type!r}")
    ctx.set_output(op, "OutputBox", out)


# ---------------------------------------------------------------------------
# prior_box / anchor_generator  (static generators: attrs + static shapes
# fully determine the output — XLA constant-folds the whole computation)
# ---------------------------------------------------------------------------

def _emit_boxes_vars(ctx, op, boxes, dtype, clip, flatten=False):
    """Shared tail of the prior generators: clip, broadcast the
    variance attr, optionally flatten to [H*W*n, 4], emit outputs."""
    jnp = _jnp()
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    variances = op.attr("variances", [0.1, 0.1, 0.2, 0.2])
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          boxes.shape).copy()
    if flatten:
        boxes = boxes.reshape(-1, 4)
        var = var.reshape(-1, 4)
    ctx.set_output(op, "Boxes", jnp.asarray(boxes, dtype))
    ctx.set_output(op, "Variances", jnp.asarray(var, dtype))


def _expand_aspect_ratios(aspect_ratios, flip):
    """reference prior_box_op.h:28 ExpandAspectRatios."""
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(ar)
        if flip:
            out.append(1.0 / ar)
    return out


def _prior_box_count(op):
    ars = _expand_aspect_ratios(op.attr("aspect_ratios", [1.0]),
                                op.attr("flip", False))
    n = len(ars) * len(op.attr("min_sizes", []))
    n += len(op.attr("max_sizes", []) or [])
    return n


def _prior_box_infer(op, block):
    x = in_var(op, block, "Input")
    h, w = x.shape[2], x.shape[3]
    n = _prior_box_count(op)
    set_out(op, block, "Boxes", (h, w, n, 4), x.dtype)
    set_out(op, block, "Variances", (h, w, n, 4), x.dtype)


@register_op("prior_box", infer=_prior_box_infer)
def _prior_box(ctx, op):
    """reference prior_box_op.h:95-170 — SSD prior boxes, computed in
    numpy at trace time (pure function of static shapes + attrs)."""
    feat = ctx.get_input(op, "Input")
    image = ctx.get_input(op, "Image")
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in op.attr("min_sizes", [])]
    max_sizes = [float(s) for s in (op.attr("max_sizes", []) or [])]
    ars = _expand_aspect_ratios(op.attr("aspect_ratios", [1.0]),
                                op.attr("flip", False))
    variances = op.attr("variances", [0.1, 0.1, 0.2, 0.2])
    clip = op.attr("clip", False)
    step_w = op.attr("step_w", 0.0) or iw / fw
    step_h = op.attr("step_h", 0.0) or ih / fh
    offset = op.attr("offset", 0.5)
    mm_order = op.attr("min_max_aspect_ratios_order", False)
    if max_sizes and len(max_sizes) != len(min_sizes):
        raise InvalidArgumentError(
            f"prior_box: len(max_sizes)={len(max_sizes)} must equal "
            f"len(min_sizes)={len(min_sizes)}")

    boxes = np.zeros((fh, fw, _prior_box_count(op), 4), np.float32)
    cx = (np.arange(fw) + offset) * step_w          # [fw]
    cy = (np.arange(fh) + offset) * step_h          # [fh]
    cxg, cyg = np.meshgrid(cx, cy)                  # [fh,fw]

    def put(idx, bw, bh):
        boxes[:, :, idx, 0] = (cxg - bw) / iw
        boxes[:, :, idx, 1] = (cyg - bh) / ih
        boxes[:, :, idx, 2] = (cxg + bw) / iw
        boxes[:, :, idx, 3] = (cyg + bh) / ih

    idx = 0
    for s, ms in enumerate(min_sizes):
        if mm_order:
            put(idx, ms / 2.0, ms / 2.0)
            idx += 1
            if max_sizes:
                sq = math.sqrt(ms * max_sizes[s]) / 2.0
                put(idx, sq, sq)
                idx += 1
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                put(idx, ms * math.sqrt(ar) / 2.0,
                    ms / math.sqrt(ar) / 2.0)
                idx += 1
        else:
            for ar in ars:
                put(idx, ms * math.sqrt(ar) / 2.0,
                    ms / math.sqrt(ar) / 2.0)
                idx += 1
            if max_sizes:
                sq = math.sqrt(ms * max_sizes[s]) / 2.0
                put(idx, sq, sq)
                idx += 1
    _emit_boxes_vars(ctx, op, boxes, feat.dtype, clip)


def _anchor_gen_infer(op, block):
    x = in_var(op, block, "Input")
    h, w = x.shape[2], x.shape[3]
    n = len(op.attr("aspect_ratios", [])) * len(op.attr("anchor_sizes", []))
    set_out(op, block, "Anchors", (h, w, n, 4), x.dtype)
    set_out(op, block, "Variances", (h, w, n, 4), x.dtype)


@register_op("anchor_generator", infer=_anchor_gen_infer)
def _anchor_generator(ctx, op):
    """reference anchor_generator_op.h:43-85 (RCNN-style anchors)."""
    feat = ctx.get_input(op, "Input")
    fh, fw = feat.shape[2], feat.shape[3]
    sizes = [float(s) for s in op.attr("anchor_sizes", [])]
    ars = [float(a) for a in op.attr("aspect_ratios", [])]
    variances = op.attr("variances", [0.1, 0.1, 0.2, 0.2])
    stride = op.attr("stride", [16.0, 16.0])
    offset = op.attr("offset", 0.5)
    sw, sh = float(stride[0]), float(stride[1])

    n = len(ars) * len(sizes)
    anchors = np.zeros((fh, fw, n, 4), np.float32)
    xc = np.arange(fw) * sw + offset * (sw - 1)
    yc = np.arange(fh) * sh + offset * (sh - 1)
    xg, yg = np.meshgrid(xc, yc)
    idx = 0
    for ar in ars:
        for size in sizes:
            area = sw * sh
            base_w = round(math.sqrt(area / ar))
            base_h = round(base_w * ar)
            aw = (size / sw) * base_w
            ah = (size / sh) * base_h
            anchors[:, :, idx, 0] = xg - 0.5 * (aw - 1)
            anchors[:, :, idx, 1] = yg - 0.5 * (ah - 1)
            anchors[:, :, idx, 2] = xg + 0.5 * (aw - 1)
            anchors[:, :, idx, 3] = yg + 0.5 * (ah - 1)
            idx += 1
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          anchors.shape).copy()
    jnp = _jnp()
    # (anchor_generator's output slot is "Anchors", not "Boxes" — the
    # shared tail does not apply)
    ctx.set_output(op, "Anchors", jnp.asarray(anchors, feat.dtype))
    ctx.set_output(op, "Variances", jnp.asarray(var, feat.dtype))


# ---------------------------------------------------------------------------
# yolo_box
# ---------------------------------------------------------------------------

def _yolo_box_infer(op, block):
    x = in_var(op, block, "X")
    an_num = len(op.attr("anchors", [])) // 2
    class_num = op.attr("class_num", 1)
    h, w = x.shape[2], x.shape[3]
    box_num = an_num * h * w
    set_out(op, block, "Boxes", (x.shape[0], box_num, 4), x.dtype)
    set_out(op, block, "Scores", (x.shape[0], box_num, class_num), x.dtype)


@register_op("yolo_box", infer=_yolo_box_infer)
def _yolo_box(ctx, op):
    """reference yolo_box_op.h:82-151. The reference's skip-if-below-
    conf_thresh writes zeros (output memset); here the same zeros come
    from a mask."""
    import jax

    jnp = _jnp()
    x = ctx.get_input(op, "X")                     # [N, an*(5+C), H, W]
    imgsize = ctx.get_input(op, "ImgSize")         # [N, 2] (h, w)
    anchors = np.asarray(op.attr("anchors", []), np.float32)
    an_num = anchors.size // 2
    C = op.attr("class_num", 1)
    conf_thresh = op.attr("conf_thresh", 0.01)
    downsample = op.attr("downsample_ratio", 32)
    clip_bbox = op.attr("clip_bbox", True)
    scale = op.attr("scale_x_y", 1.0)
    bias = -0.5 * (scale - 1.0)

    N, _, H, W = x.shape
    in_h, in_w = downsample * H, downsample * W
    x = x.reshape(N, an_num, 5 + C, H, W)
    img_h = imgsize[:, 0].astype(x.dtype)[:, None, None, None]
    img_w = imgsize[:, 1].astype(x.dtype)[:, None, None, None]

    grid_x = jnp.arange(W, dtype=x.dtype)[None, None, None, :]
    grid_y = jnp.arange(H, dtype=x.dtype)[None, None, :, None]
    aw = jnp.asarray(anchors[0::2], x.dtype)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], x.dtype)[None, :, None, None]

    sig = jax.nn.sigmoid
    bx = (grid_x + sig(x[:, :, 0]) * scale + bias) * img_w / W
    by = (grid_y + sig(x[:, :, 1]) * scale + bias) * img_h / H
    bw = jnp.exp(x[:, :, 2]) * aw * img_w / in_w
    bh = jnp.exp(x[:, :, 3]) * ah * img_h / in_h
    conf = sig(x[:, :, 4])                        # [N,an,H,W]
    keep = conf >= conf_thresh

    x0, y0 = bx - bw / 2, by - bh / 2
    x1, y1 = bx + bw / 2, by + bh / 2
    if clip_bbox:
        x0 = jnp.maximum(x0, 0.0)
        y0 = jnp.maximum(y0, 0.0)
        x1 = jnp.minimum(x1, img_w - 1)
        y1 = jnp.minimum(y1, img_h - 1)
    boxes = jnp.stack([x0, y0, x1, y1], axis=-1)   # [N,an,H,W,4]
    boxes = jnp.where(keep[..., None], boxes, 0.0)
    scores = conf[..., None] * sig(
        jnp.moveaxis(x[:, :, 5:], 2, -1))          # [N,an,H,W,C]
    scores = jnp.where(keep[..., None], scores, 0.0)
    ctx.set_output(op, "Boxes", boxes.reshape(N, an_num * H * W, 4))
    ctx.set_output(op, "Scores", scores.reshape(N, an_num * H * W, C))


# ---------------------------------------------------------------------------
# box_clip
# ---------------------------------------------------------------------------

def _box_clip_infer(op, block):
    x = in_var(op, block, "Input")
    set_out(op, block, "Output", x.shape, x.dtype)


@register_op("box_clip", infer=_box_clip_infer, grad="auto")
def _box_clip(ctx, op):
    """reference bbox_util.h:157 ClipTiledBoxes (is_scale=true)."""
    jnp = _jnp()
    boxes = ctx.get_input(op, "Input")             # [B, N, 4] or [N, 4]
    im_info = ctx.get_input(op, "ImInfo")          # [B, 3] (h, w, scale)
    squeeze = boxes.ndim == 2
    if squeeze:
        boxes = boxes[None]
    im_h = jnp.round(im_info[:, 0] / im_info[:, 2])[:, None]
    im_w = jnp.round(im_info[:, 1] / im_info[:, 2])[:, None]
    out = jnp.stack([
        jnp.clip(boxes[..., 0], 0.0, im_w - 1),
        jnp.clip(boxes[..., 1], 0.0, im_h - 1),
        jnp.clip(boxes[..., 2], 0.0, im_w - 1),
        jnp.clip(boxes[..., 3], 0.0, im_h - 1),
    ], axis=-1)
    if squeeze:
        out = out[0]
    ctx.set_output(op, "Output", out)


# ---------------------------------------------------------------------------
# bipartite_match
# ---------------------------------------------------------------------------

def _bipartite_infer(op, block):
    d = in_var(op, block, "DistMat")
    set_out(op, block, "ColToRowMatchIndices", (1, d.shape[1]), "int32")
    set_out(op, block, "ColToRowMatchDist", (1, d.shape[1]), d.dtype)


@register_op("bipartite_match", infer=_bipartite_infer)
def _bipartite_match(ctx, op):
    """reference bipartite_match_op.cc:71 — greedy global-argmax
    matching as min(R,C) fixed argmax-and-mask iterations."""
    from jax import lax

    jnp = _jnp()
    dist = ctx.get_input(op, "DistMat")            # [R, C]
    R, C = dist.shape
    match_type = op.attr("match_type", "bipartite")
    overlap_thresh = op.attr("dist_threshold", 0.5)

    NEG = jnp.asarray(-1.0, dist.dtype)

    def body(_, state):
        midx, mdist, row_used, col_used = state
        masked = jnp.where(row_used[:, None] | col_used[None, :],
                           NEG, dist)
        flat = jnp.argmax(masked)
        r, c = flat // C, flat % C
        v = masked[r, c]
        ok = v > 0
        midx = midx.at[c].set(jnp.where(ok, r.astype(jnp.int32),
                                        midx[c]))
        mdist = mdist.at[c].set(jnp.where(ok, v, mdist[c]))
        row_used = row_used.at[r].set(row_used[r] | ok)
        col_used = col_used.at[c].set(col_used[c] | ok)
        return midx, mdist, row_used, col_used

    init = (jnp.full((C,), -1, jnp.int32),
            jnp.zeros((C,), dist.dtype),
            jnp.zeros((R,), bool), jnp.zeros((C,), bool))
    midx, mdist, _, _ = lax.fori_loop(0, min(R, C), body, init)

    if match_type == "per_prediction":
        # reference ArgMaxMatch: unmatched cols with max-dist >= thresh
        # match their argmax row
        col_max = dist.max(axis=0)
        col_arg = dist.argmax(axis=0).astype(jnp.int32)
        fill = (midx < 0) & (col_max >= overlap_thresh)
        midx = jnp.where(fill, col_arg, midx)
        mdist = jnp.where(fill, col_max, mdist)
    ctx.set_output(op, "ColToRowMatchIndices", midx[None, :])
    ctx.set_output(op, "ColToRowMatchDist", mdist[None, :])


# ---------------------------------------------------------------------------
# roi_align / roi_pool
# ---------------------------------------------------------------------------

def _rois_batch_ids(jnp, ctx, op, B, R):
    """Batch id per roi [R] from the RoisNum input (replaces the
    reference's LoD offsets, roi_align_op.h:210-215). Without RoisNum,
    only a single-image batch is unambiguous."""
    if op.single_input("RoisNum"):
        ends = jnp.cumsum(ctx.get_input(op, "RoisNum"))
        return (jnp.arange(R)[:, None] >= ends[None, :]).sum(axis=1)
    if B == 1:
        return jnp.zeros((R,), jnp.int32)
    raise InvalidArgumentError(
        f"{op.type}: feature batch is {B} but no RoisNum input maps "
        "rois to images (the reference carries this via the ROIs LoD; "
        "the dense port needs RoisNum)")


def _roi_align_infer(op, block):
    x = in_var(op, block, "X")
    rois = in_var(op, block, "ROIs")
    ph = op.attr("pooled_height", 1)
    pw = op.attr("pooled_width", 1)
    set_out(op, block, "Out", (rois.shape[0], x.shape[1], ph, pw), x.dtype)


@register_op("roi_align", infer=_roi_align_infer, grad="auto")
def _roi_align(ctx, op):
    """reference roi_align_op.h:218-275. Static sampling grid
    (sampling_ratio >= 1) — the adaptive ceil(roi_h/ph) grid is
    data-dependent and has no static-shape equivalent."""
    import jax

    jnp = _jnp()
    x = ctx.get_input(op, "X")                     # [B, C, H, W]
    rois = ctx.get_input(op, "ROIs")               # [R, 4]
    ph = op.attr("pooled_height", 1)
    pw = op.attr("pooled_width", 1)
    scale = op.attr("spatial_scale", 1.0)
    ratio = op.attr("sampling_ratio", -1)
    if ratio < 1:
        raise UnimplementedError(
            "roi_align on TPU requires a static sampling_ratio >= 1; "
            "the reference's adaptive grid (sampling_ratio=-1, "
            "roi_align_op.h:231) is data-dependent shape")
    B, Cc, H, W = x.shape
    R = rois.shape[0]
    batch_ids = _rois_batch_ids(jnp, ctx, op, B, R)

    xmin = rois[:, 0] * scale
    ymin = rois[:, 1] * scale
    roi_w = jnp.maximum(rois[:, 2] * scale - xmin, 1.0)
    roi_h = jnp.maximum(rois[:, 3] * scale - ymin, 1.0)
    bin_w = roi_w / pw
    bin_h = roi_h / ph

    # sample coords: [R, ph*ratio] x [R, pw*ratio]
    iy = jnp.arange(ph * ratio)
    ix = jnp.arange(pw * ratio)
    ys = ymin[:, None] + bin_h[:, None] / ratio * (
        iy[None, :] % ratio + 0.5) + (iy[None, :] // ratio) * bin_h[:, None]
    xs = xmin[:, None] + bin_w[:, None] / ratio * (
        ix[None, :] % ratio + 0.5) + (ix[None, :] // ratio) * bin_w[:, None]

    def bilinear(img, ys, xs):
        """img [C,H,W]; ys [Sy], xs [Sx] -> [C,Sy,Sx] (reference
        bilinear_interpolate: out-of-range samples contribute 0)."""
        valid_y = (ys >= -1.0) & (ys <= H * 1.0)
        valid_x = (xs >= -1.0) & (xs <= W * 1.0)
        y = jnp.clip(ys, 0.0, None)
        xx = jnp.clip(xs, 0.0, None)
        y0 = jnp.clip(jnp.floor(y).astype(jnp.int32), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
        y1 = jnp.clip(y0 + 1, 0, H - 1)
        x1 = jnp.clip(x0 + 1, 0, W - 1)
        ly = jnp.clip(y - y0, 0.0, 1.0)
        lx = jnp.clip(xx - x0, 0.0, 1.0)
        hy, hx = 1.0 - ly, 1.0 - lx
        g = lambda yi, xi: img[:, yi][:, :, xi]    # [C,Sy,Sx]
        val = (g(y0, x0) * (hy[:, None] * hx[None, :])
               + g(y0, x1) * (hy[:, None] * lx[None, :])
               + g(y1, x0) * (ly[:, None] * hx[None, :])
               + g(y1, x1) * (ly[:, None] * lx[None, :]))
        return val * (valid_y[:, None] & valid_x[None, :])

    def per_roi(bid, ys_r, xs_r):
        img = x[bid]                               # [C,H,W]
        samples = bilinear(img, ys_r, xs_r)        # [C, ph*r, pw*r]
        s = samples.reshape(Cc, ph, ratio, pw, ratio)
        return s.mean(axis=(2, 4))                 # [C, ph, pw]

    out = jax.vmap(per_roi)(batch_ids, ys, xs)
    ctx.set_output(op, "Out", out.astype(x.dtype))


def _roi_pool_infer(op, block):
    x = in_var(op, block, "X")
    rois = in_var(op, block, "ROIs")
    ph = op.attr("pooled_height", 1)
    pw = op.attr("pooled_width", 1)
    set_out(op, block, "Out", (rois.shape[0], x.shape[1], ph, pw), x.dtype)


@register_op("roi_pool", infer=_roi_pool_infer, grad="auto")
def _roi_pool(ctx, op):
    """reference roi_pool_op.h:95-160 — quantized-bin max pooling.
    Dynamic [hstart,hend) ranges become masks over the (static) H x W
    grid; empty bins produce 0 like the reference."""
    import jax

    jnp = _jnp()
    x = ctx.get_input(op, "X")
    rois = ctx.get_input(op, "ROIs")
    ph = op.attr("pooled_height", 1)
    pw = op.attr("pooled_width", 1)
    scale = op.attr("spatial_scale", 1.0)
    B, Cc, H, W = x.shape
    R = rois.shape[0]
    batch_ids = _rois_batch_ids(jnp, ctx, op, B, R)

    x0 = jnp.round(rois[:, 0] * scale).astype(jnp.int32)
    y0 = jnp.round(rois[:, 1] * scale).astype(jnp.int32)
    x1 = jnp.round(rois[:, 2] * scale).astype(jnp.int32)
    y1 = jnp.round(rois[:, 3] * scale).astype(jnp.int32)
    roi_h = jnp.maximum(y1 - y0 + 1, 1)
    roi_w = jnp.maximum(x1 - x0 + 1, 1)

    def per_roi(bid, x0r, y0r, hr, wr):
        img = x[bid]                               # [C,H,W]
        bh = hr.astype(jnp.float32) / ph
        bw = wr.astype(jnp.float32) / pw
        pidx_h = jnp.arange(ph)
        pidx_w = jnp.arange(pw)
        hs = jnp.clip(jnp.floor(pidx_h * bh).astype(jnp.int32) + y0r, 0, H)
        he = jnp.clip(jnp.ceil((pidx_h + 1) * bh).astype(jnp.int32) + y0r,
                      0, H)
        ws = jnp.clip(jnp.floor(pidx_w * bw).astype(jnp.int32) + x0r, 0, W)
        we = jnp.clip(jnp.ceil((pidx_w + 1) * bw).astype(jnp.int32) + x0r,
                      0, W)
        hh = jnp.arange(H)
        ww = jnp.arange(W)
        hmask = (hh[None, :] >= hs[:, None]) & (hh[None, :] < he[:, None])
        wmask = (ww[None, :] >= ws[:, None]) & (ww[None, :] < we[:, None])
        m = hmask[:, None, :, None] & wmask[None, :, None, :]  # [ph,pw,H,W]
        empty = ~m.any(axis=(2, 3))                            # [ph,pw]
        vals = jnp.where(m[None], img[:, None, None, :, :],
                         -jnp.inf)                 # [C,ph,pw,H,W]
        pooled = vals.max(axis=(3, 4))
        return jnp.where(empty[None], 0.0, pooled)  # [C,ph,pw]

    out = jax.vmap(per_roi)(batch_ids, x0, y0, roi_h, roi_w)
    ctx.set_output(op, "Out", out.astype(x.dtype))


# ---------------------------------------------------------------------------
# multiclass_nms (padded multiclass_nms3-style outputs)
# ---------------------------------------------------------------------------

def _greedy_nms(jnp, lax, iou, scores, n_out, nms_thresh, eta):
    """Fixed-iteration greedy NMS shared by multiclass_nms and
    generate_proposals.

    Suppression is evaluated lazily each iteration against the kept set
    under the CURRENT adaptive threshold — the reference visits
    candidates in score order and tests each against the threshold at
    that candidate's turn (thr only shrinks on a keep), which this
    reproduces: every iteration picks the highest-scoring candidate
    whose max-IoU vs kept <= thr.

    scores: [M] with ineligible candidates already at -inf. Returns
    (sel [n_out] int32 with -1 padding, valid [n_out] bool,
    sel_scores [n_out])."""
    NEG = jnp.asarray(-jnp.inf, scores.dtype)

    def body(i, state):
        sel, val, scr, kept, thr = state
        supp = ((iou > thr) & kept[:, None]).any(axis=0)
        s_ok = jnp.where(supp | kept, NEG, scores)
        j = jnp.argmax(s_ok)
        ok = s_ok[j] > NEG
        sel = sel.at[i].set(jnp.where(ok, j.astype(jnp.int32), -1))
        val = val.at[i].set(ok)
        scr = scr.at[i].set(jnp.where(ok, s_ok[j], 0.0))
        kept = kept.at[j].set(kept[j] | ok)
        thr = jnp.where(ok & (eta < 1.0) & (thr > 0.5), thr * eta, thr)
        return sel, val, scr, kept, thr

    init = (jnp.full((n_out,), -1, jnp.int32),
            jnp.zeros((n_out,), bool),
            jnp.zeros((n_out,), scores.dtype),
            jnp.zeros(scores.shape, bool),
            jnp.asarray(nms_thresh, scores.dtype))
    sel, val, scr, _, _ = lax.fori_loop(0, n_out, body, init)
    return sel, val, scr




def _mc_nms_keep(op):
    keep_top_k = op.attr("keep_top_k", -1)
    nms_top_k = op.attr("nms_top_k", -1)
    return keep_top_k, nms_top_k


def _mc_nms_out_k(keep_top_k, nms_top_k, M, C):
    per_class = min(nms_top_k, M) if nms_top_k > 0 else M
    # the per-class stage can emit at most C*per_class rows — a larger
    # keep_top_k cannot be filled, so the static K caps there
    K = min(keep_top_k, C * per_class) if keep_top_k > 0 \
        else C * per_class
    return K, per_class


def _multiclass_nms_infer(op, block):
    b = in_var(op, block, "BBoxes")                # [B, M, 4]
    s = in_var(op, block, "Scores")                # [B, C, M]
    B, M = b.shape[0], b.shape[1]
    C = s.shape[1]
    keep_top_k, nms_top_k = _mc_nms_keep(op)
    K, _ = _mc_nms_out_k(keep_top_k, nms_top_k, M, C)
    set_out(op, block, "Out", (B, K, 6), b.dtype)
    if op.output("Index"):
        set_out(op, block, "Index", (B, K), "int32")
    if op.output("NmsRoisNum"):
        set_out(op, block, "NmsRoisNum", (B,), "int32")


@register_op("multiclass_nms", infer=_multiclass_nms_infer)
def _multiclass_nms(ctx, op):
    """reference multiclass_nms_op.cc:139 (NMSFast) + :194. LoD output
    [No, 6] becomes padded [B, K, 6] with label -1 in unused slots, an
    Index into the per-image box rows, and NmsRoisNum counts (the
    multiclass_nms3 output contract)."""
    import jax
    from jax import lax

    jnp = _jnp()
    bboxes = ctx.get_input(op, "BBoxes")           # [B, M, 4]
    scores = ctx.get_input(op, "Scores")           # [B, C, M]
    B, M = bboxes.shape[0], bboxes.shape[1]
    C = scores.shape[1]
    background = op.attr("background_label", 0)
    score_thresh = op.attr("score_threshold", 0.0)
    nms_thresh = op.attr("nms_threshold", 0.3)
    nms_eta = op.attr("nms_eta", 1.0)
    normalized = op.attr("normalized", True)
    keep_top_k, nms_top_k = _mc_nms_keep(op)
    K, per_class = _mc_nms_out_k(keep_top_k, nms_top_k, M, C)

    NEG = jnp.asarray(-jnp.inf, scores.dtype)

    def nms_one_class(boxes_m, scores_m):
        """greedy NMS -> (idx [per_class], valid [per_class])."""
        s = jnp.where(scores_m > score_thresh, scores_m, NEG)
        if per_class < M:
            # reference GetMaxScoreIndex keeps only the top nms_top_k
            # candidates before NMS; index-based mask (top_k breaks ties
            # by lower index, like the reference's stable_sort)
            _, topi = lax.top_k(s, per_class)
            cand = jnp.zeros((M,), bool).at[topi].set(True)
            s = jnp.where(cand, s, NEG)
        iou = _iou_matrix(jnp, boxes_m, boxes_m, normalized)
        sel, val, _ = _greedy_nms(jnp, lax, iou, s, per_class,
                                  nms_thresh, nms_eta)
        return sel, val

    def per_image(boxes_m, scores_cm):
        sel, val = jax.vmap(
            lambda s_m: nms_one_class(boxes_m, s_m))(scores_cm)
        # mask out the background class entirely
        if 0 <= background < C:
            val = val.at[background].set(
                jnp.zeros((per_class,), bool))
        flat_idx = sel.reshape(-1)                 # [C*per_class]
        flat_val = val.reshape(-1)
        cls = jnp.repeat(jnp.arange(C), per_class)
        flat_score = jnp.where(
            flat_val,
            scores_cm[cls, jnp.clip(flat_idx, 0, M - 1)], NEG)
        # keep_top_k across classes
        order = jnp.argsort(-flat_score)[:K]
        kept_score = flat_score[order]
        kept_valid = kept_score > NEG
        kept_idx = jnp.where(kept_valid, flat_idx[order], -1)
        kept_cls = jnp.where(kept_valid, cls[order], -1)
        kept_boxes = boxes_m[jnp.clip(kept_idx, 0, M - 1)]
        out = jnp.concatenate([
            kept_cls.astype(boxes_m.dtype)[:, None],
            jnp.where(kept_valid, kept_score, 0.0)[:, None],
            jnp.where(kept_valid[:, None], kept_boxes, 0.0)], axis=1)
        return out, kept_idx, kept_valid.sum().astype(jnp.int32)

    out, index, nums = jax.vmap(per_image)(bboxes, scores)
    ctx.set_output(op, "Out", out)
    if op.output("Index"):
        ctx.set_output(op, "Index", index)
    if op.output("NmsRoisNum"):
        ctx.set_output(op, "NmsRoisNum", nums)


# ---------------------------------------------------------------------------
# SSD training ops: density_prior_box / target_assign / mine_hard_examples
# ---------------------------------------------------------------------------

def _density_prior_count(op):
    dens = op.attr("densities", [])
    return len(op.attr("fixed_ratios", [])) * sum(d * d for d in dens)


def _density_prior_infer(op, block):
    x = in_var(op, block, "Input")
    h, w = x.shape[2], x.shape[3]
    n = _density_prior_count(op)
    if op.attr("flatten_to_2d", False):
        set_out(op, block, "Boxes", (h * w * n, 4), x.dtype)
        set_out(op, block, "Variances", (h * w * n, 4), x.dtype)
    else:
        set_out(op, block, "Boxes", (h, w, n, 4), x.dtype)
        set_out(op, block, "Variances", (h, w, n, 4), x.dtype)


@register_op("density_prior_box", infer=_density_prior_infer)
def _density_prior_box(ctx, op):
    """reference density_prior_box_op.h:59-130 — density-sampled SSD
    priors; static numpy generation like prior_box."""
    feat = ctx.get_input(op, "Input")
    image = ctx.get_input(op, "Image")
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    fixed_sizes = [float(s) for s in op.attr("fixed_sizes", [])]
    fixed_ratios = [float(r) for r in op.attr("fixed_ratios", [])]
    densities = [int(d) for d in op.attr("densities", [])]
    variances = op.attr("variances", [0.1, 0.1, 0.2, 0.2])
    clip = op.attr("clip", False)
    step_w = op.attr("step_w", 0.0) or iw / fw
    step_h = op.attr("step_h", 0.0) or ih / fh
    offset = op.attr("offset", 0.5)
    if len(fixed_sizes) != len(densities):
        raise InvalidArgumentError(
            "density_prior_box: len(fixed_sizes) must equal "
            "len(densities)")

    n = _density_prior_count(op)
    boxes = np.zeros((fh, fw, n, 4), np.float32)
    step_avg = int((step_w + step_h) * 0.5)
    cx = (np.arange(fw) + offset) * step_w
    cy = (np.arange(fh) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)
    idx = 0
    for size, density in zip(fixed_sizes, densities):
        shift = step_avg // density
        for r in fixed_ratios:
            bw = size * math.sqrt(r)
            bh = size / math.sqrt(r)
            dcx = cxg - step_avg / 2.0 + shift / 2.0
            dcy = cyg - step_avg / 2.0 + shift / 2.0
            for di in range(density):
                for dj in range(density):
                    ctx_x = dcx + dj * shift
                    ctx_y = dcy + di * shift
                    boxes[:, :, idx, 0] = np.maximum(
                        (ctx_x - bw / 2.0) / iw, 0.0)
                    boxes[:, :, idx, 1] = np.maximum(
                        (ctx_y - bh / 2.0) / ih, 0.0)
                    boxes[:, :, idx, 2] = np.minimum(
                        (ctx_x + bw / 2.0) / iw, 1.0)
                    boxes[:, :, idx, 3] = np.minimum(
                        (ctx_y + bh / 2.0) / ih, 1.0)
                    idx += 1
    _emit_boxes_vars(ctx, op, boxes, feat.dtype, clip,
                     flatten=op.attr("flatten_to_2d", False))


def _target_assign_infer(op, block):
    x = in_var(op, block, "X")
    mi = in_var(op, block, "MatchIndices")
    B, P = mi.shape[0], mi.shape[1]
    K = x.shape[-1]
    set_out(op, block, "Out", (B, P, K), x.dtype)
    if op.output("OutWeight"):
        set_out(op, block, "OutWeight", (B, P, 1), "float32")


@register_op("target_assign", infer=_target_assign_infer, grad="auto")
def _target_assign(ctx, op):
    """reference target_assign_op.h:50-73, dense form: X carries the
    per-image candidate targets ([B, G, K] per-gt values, or
    [B, G, P, K] per-(gt, prior) values such as box_coder encodings);
    matched priors gather row match[b, p], unmatched get
    mismatch_value with weight 0."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    mi = ctx.get_input(op, "MatchIndices")          # [B, P] int
    mismatch = op.attr("mismatch_value", 0)
    B, P = mi.shape
    ids = jnp.clip(mi, 0, x.shape[1] - 1)
    if x.ndim == 3:                                 # [B, G, K]
        picked = jnp.take_along_axis(
            x, ids[:, :, None], axis=1)             # [B, P, K]
    elif x.ndim == 4:                               # [B, G, P, K]
        # combined gather: out[b, p] = x[b, ids[b, p], p] — O(P)
        # output without the [B, P, P, K] intermediate (P can be 8732)
        picked = x[jnp.arange(B)[:, None], ids,
                   jnp.arange(P)[None, :]]          # [B, P, K]
    else:
        raise InvalidArgumentError(
            f"target_assign: X must be rank 3 or 4, got {x.ndim}")
    matched = (mi > -1)[:, :, None]
    out = jnp.where(matched, picked,
                    jnp.asarray(mismatch, picked.dtype))
    weight = matched.astype(jnp.float32)
    if op.single_input("NegMask"):
        # reference target_assign NegIndices (LoD) -> dense NegMask
        # [B, P]: mined negatives keep mismatch_value targets but
        # re-enter the loss with weight 1
        neg = ctx.get_input(op, "NegMask")[:, :, None] > 0
        out = jnp.where(neg & ~matched,
                        jnp.asarray(mismatch, out.dtype), out)
        weight = jnp.maximum(weight, neg.astype(jnp.float32))
    ctx.set_output(op, "Out", out)
    if op.output("OutWeight"):
        ctx.set_output(op, "OutWeight", weight)


def _mine_hard_infer(op, block):
    mi = in_var(op, block, "MatchIndices")
    set_out(op, block, "NegMask", mi.shape, "float32")
    set_out(op, block, "UpdatedMatchIndices", mi.shape, "int32")


@register_op("mine_hard_examples", infer=_mine_hard_infer, grad=None)
def _mine_hard_examples(ctx, op):
    """reference mine_hard_examples_op.cc:40-100 (max_negative mining).
    The LoD NegIndices output becomes a fixed-shape NegMask [B, P]:
    eligible negatives (unmatched, dist below threshold) ranked by
    classification loss, the top num_pos * neg_pos_ratio per image
    selected."""
    jnp = _jnp()
    cls_loss = ctx.get_input(op, "ClsLoss")         # [B, P]
    mi = ctx.get_input(op, "MatchIndices")          # [B, P]
    dist = ctx.get_input(op, "MatchDist")
    mining = op.attr("mining_type", "max_negative")
    if mining != "max_negative":
        raise UnimplementedError(
            "mine_hard_examples: only max_negative mining (the SSD "
            "default) has a fixed-shape equivalent; hard_example "
            "rewrites match indices data-dependently")
    ratio = op.attr("neg_pos_ratio", 3.0)
    thresh = op.attr("neg_dist_threshold", 0.5)
    # max_negative ranks by classification loss ALONE; the reference
    # only adds LocLoss under hard_example mining
    # (mine_hard_examples_op.cc:46-49)
    loss = cls_loss
    eligible = (mi == -1) & (dist < thresh)
    num_pos = (mi != -1).sum(axis=1)                # [B]
    neg_sel = jnp.minimum(
        (num_pos * ratio).astype(jnp.int32),
        eligible.sum(axis=1).astype(jnp.int32))     # [B]
    NEG = jnp.asarray(-jnp.inf, loss.dtype)
    ranked = jnp.where(eligible, loss, NEG)
    order = jnp.argsort(-ranked, axis=1)
    rank = jnp.argsort(order, axis=1)               # rank of each prior
    mask = (rank < neg_sel[:, None]) & eligible
    ctx.set_output(op, "NegMask", mask.astype(jnp.float32))
    ctx.set_output(op, "UpdatedMatchIndices", mi.astype(jnp.int32))


# ---------------------------------------------------------------------------
# generate_proposals (RPN)
# ---------------------------------------------------------------------------

def _gen_proposals_infer(op, block):
    scores = in_var(op, block, "Scores")            # [N, A, H, W]
    N = scores.shape[0]
    post = op.attr("post_nms_topN", 1000)
    set_out(op, block, "RpnRois", (N, post, 4), scores.dtype)
    set_out(op, block, "RpnRoiProbs", (N, post, 1), scores.dtype)
    if op.output("RpnRoisNum"):
        set_out(op, block, "RpnRoisNum", (N,), "int32")


@register_op("generate_proposals", infer=_gen_proposals_infer, grad=None)
def _generate_proposals(ctx, op):
    """reference generate_proposals_op.cc:85-240 — RPN proposal
    generation: top-pre_nms scores -> delta decode (+1 box widths, exp
    clipped at log(1000/16)) -> clip to image -> min-size filter ->
    greedy NMS -> top post_nms. The LoD RpnRois output becomes padded
    [N, post_nms_topN, 4] + RpnRoisNum counts."""
    import jax
    from jax import lax

    jnp = _jnp()
    scores = ctx.get_input(op, "Scores")            # [N, A, H, W]
    deltas = ctx.get_input(op, "BboxDeltas")        # [N, 4A, H, W]
    im_info = ctx.get_input(op, "ImInfo")           # [N, 3]
    anchors = ctx.get_input(op, "Anchors").reshape(-1, 4)
    variances = ctx.get_input(op, "Variances").reshape(-1, 4)
    pre = op.attr("pre_nms_topN", 6000)
    post = op.attr("post_nms_topN", 1000)
    nms_thresh = op.attr("nms_thresh", 0.5)
    min_size = max(op.attr("min_size", 0.1), 1.0)
    eta = op.attr("eta", 1.0)

    N, A, H, W = scores.shape
    K = A * H * W
    # layout: [A, H, W] -> [H, W, A] flat, matching the reference's
    # NHWA transpose so flat index i pairs with anchors[h, w, a]
    sc = jnp.transpose(scores, (0, 2, 3, 1)).reshape(N, K)
    dl = jnp.transpose(deltas.reshape(N, A, 4, H, W),
                       (0, 3, 4, 1, 2)).reshape(N, K, 4)
    T1 = min(pre, K) if pre > 0 else K
    clip_d = float(np.log(1000.0 / 16.0))
    NEG = jnp.asarray(-jnp.inf, sc.dtype)

    def one_image(s, d, info):
        topv, topi = lax.top_k(s, T1)
        an = anchors[topi]
        var = variances[topi]
        dd = d[topi]
        # decode (reference bbox_util.h:216 BoxCoder, +1 widths)
        aw = an[:, 2] - an[:, 0] + 1.0
        ah = an[:, 3] - an[:, 1] + 1.0
        acx = an[:, 0] + 0.5 * aw
        acy = an[:, 1] + 0.5 * ah
        cx = var[:, 0] * dd[:, 0] * aw + acx
        cy = var[:, 1] * dd[:, 1] * ah + acy
        w = jnp.exp(jnp.minimum(var[:, 2] * dd[:, 2], clip_d)) * aw
        h = jnp.exp(jnp.minimum(var[:, 3] * dd[:, 3], clip_d)) * ah
        x0, y0 = cx - 0.5 * w, cy - 0.5 * h
        x1, y1 = cx + 0.5 * w - 1.0, cy + 0.5 * h - 1.0
        # clip to raw image bounds (ClipTiledBoxes is_scale=false)
        im_h, im_w, im_s = info[0], info[1], info[2]
        x0 = jnp.clip(x0, 0.0, im_w - 1.0)
        y0 = jnp.clip(y0, 0.0, im_h - 1.0)
        x1 = jnp.clip(x1, 0.0, im_w - 1.0)
        y1 = jnp.clip(y1, 0.0, im_h - 1.0)
        boxes = jnp.stack([x0, y0, x1, y1], axis=1)
        # min-size filter in ORIGINAL image scale (FilterBoxes
        # is_scale=true) + center inside image
        ws = (x1 - x0) / im_s + 1.0
        hs = (y1 - y0) / im_s + 1.0
        keep = ((ws >= min_size) & (hs >= min_size)
                & (x0 + 0.5 * (x1 - x0 + 1.0) <= im_w)
                & (y0 + 0.5 * (y1 - y0 + 1.0) <= im_h))
        s_f = jnp.where(keep, topv, NEG)
        iou = _iou_matrix(jnp, boxes, boxes, False)  # +1 areas, like NMS<T>
        sel, val, scr = _greedy_nms(jnp, lax, iou, s_f, post,
                                    nms_thresh, eta)
        rois = jnp.where(val[:, None],
                         boxes[jnp.clip(sel, 0, T1 - 1)], 0.0)
        return rois, scr[:, None], val.sum().astype(jnp.int32)

    rois, probs, nums = jax.vmap(one_image)(sc, dl, im_info)
    ctx.set_output(op, "RpnRois", rois)
    ctx.set_output(op, "RpnRoiProbs", probs)
    if op.output("RpnRoisNum"):
        ctx.set_output(op, "RpnRoisNum", nums)


# ---------------------------------------------------------------------------
# matrix_nms / FPN proposal plumbing
# ---------------------------------------------------------------------------

def _matrix_nms_infer(op, block):
    b = in_var(op, block, "BBoxes")                 # [B, M, 4]
    s = in_var(op, block, "Scores")                 # [B, C, M]
    B, M, C = b.shape[0], b.shape[1], s.shape[1]
    keep_top_k = op.attr("keep_top_k", -1)
    nms_top_k = op.attr("nms_top_k", -1)
    K, _ = _mc_nms_out_k(keep_top_k, nms_top_k, M, C)
    set_out(op, block, "Out", (B, K, 6), b.dtype)
    if op.output("Index"):
        set_out(op, block, "Index", (B, K), "int32")
    if op.output("RoisNum"):
        set_out(op, block, "RoisNum", (B,), "int32")


@register_op("matrix_nms", infer=_matrix_nms_infer, grad=None)
def _matrix_nms(ctx, op):
    """reference matrix_nms_op.cc:81-167 — soft-NMS by decay matrix
    (PP-YOLO/SOLOv2): no sequential suppression loop at all, so the
    whole op is dense linear algebra — the one NMS variant that is
    natively TPU-shaped. decay[i] = min_j<i fn(iou_ij, iou_max[j]);
    candidates keep score*decay and survive post_threshold."""
    import jax

    jnp = _jnp()
    bboxes = ctx.get_input(op, "BBoxes")            # [B, M, 4]
    scores = ctx.get_input(op, "Scores")            # [B, C, M]
    B, M = bboxes.shape[0], bboxes.shape[1]
    C = scores.shape[1]
    background = op.attr("background_label", 0)
    score_thresh = op.attr("score_threshold", 0.0)
    post_thresh = op.attr("post_threshold", 0.0)
    use_gaussian = op.attr("use_gaussian", False)
    sigma = op.attr("gaussian_sigma", 2.0)
    normalized = op.attr("normalized", True)
    keep_top_k = op.attr("keep_top_k", -1)
    nms_top_k = op.attr("nms_top_k", -1)
    K, per_class = _mc_nms_out_k(keep_top_k, nms_top_k, M, C)
    NEG = jnp.asarray(-jnp.inf, scores.dtype)

    def nms_one_class(boxes_m, scores_m):
        s = jnp.where(scores_m > score_thresh, scores_m, NEG)
        order = jnp.argsort(-s)[:per_class]         # score-desc cands
        sv = s[order]
        bv = boxes_m[order]
        iou = _iou_matrix(jnp, bv, bv, normalized)  # [T, T]
        tri = jnp.tril(jnp.ones((per_class, per_class), bool), k=-1)
        iou_lower = jnp.where(tri, iou, 0.0)
        # iou_max[j] = max_{k<j} iou[j, k]
        iou_max = iou_lower.max(axis=1)
        if use_gaussian:
            decay_m = jnp.exp((iou_max[None, :] ** 2 - iou ** 2)
                              * sigma)
        else:
            decay_m = (1.0 - iou) / (1.0 - iou_max[None, :])
        decay = jnp.where(tri, decay_m, 1.0).min(axis=1)
        ds = jnp.where(jnp.isfinite(sv), decay * sv, NEG)
        valid = ds > post_thresh
        return order.astype(jnp.int32), ds, valid

    def per_image(boxes_m, scores_cm):
        sel, ds, val = jax.vmap(
            lambda s_m: nms_one_class(boxes_m, s_m))(scores_cm)
        if 0 <= background < C:
            val = val.at[background].set(
                jnp.zeros((per_class,), bool))
        flat_idx = sel.reshape(-1)
        flat_val = val.reshape(-1)
        flat_ds = jnp.where(flat_val, ds.reshape(-1), NEG)
        cls = jnp.repeat(jnp.arange(C), per_class)
        order = jnp.argsort(-flat_ds)[:K]
        kept_score = flat_ds[order]
        kept_valid = kept_score > NEG
        kept_idx = jnp.where(kept_valid, flat_idx[order], -1)
        kept_cls = jnp.where(kept_valid, cls[order], -1)
        kept_boxes = boxes_m[jnp.clip(kept_idx, 0, M - 1)]
        out = jnp.concatenate([
            kept_cls.astype(boxes_m.dtype)[:, None],
            jnp.where(kept_valid, kept_score, 0.0)[:, None],
            jnp.where(kept_valid[:, None], kept_boxes, 0.0)], axis=1)
        return out, kept_idx, kept_valid.sum().astype(jnp.int32)

    out, index, nums = jax.vmap(per_image)(bboxes, scores)
    ctx.set_output(op, "Out", out)
    if op.output("Index"):
        ctx.set_output(op, "Index", index)
    if op.output("RoisNum"):
        ctx.set_output(op, "RoisNum", nums)


def _distribute_fpn_infer(op, block):
    rois = in_var(op, block, "FpnRois")             # [R, 4]
    R = rois.shape[0]
    # set_out applies the shape to every var in a multi-var slot
    set_out(op, block, "MultiFpnRois", (R, 4), rois.dtype)
    set_out(op, block, "RestoreIndex", (R, 1), "int32")
    if op.output("MultiLevelRoIsNum"):
        set_out(op, block, "MultiLevelRoIsNum", (1,), "int32")


@register_op("distribute_fpn_proposals", infer=_distribute_fpn_infer,
             grad=None)
def _distribute_fpn_proposals(ctx, op):
    """reference distribute_fpn_proposals_op.h:100-150: assign each roi
    to level floor(log2(sqrt(area)/refer_scale) + refer_level). The
    variable-length per-level splits become full-size padded tensors
    (invalid rows zeroed) + per-level counts; rois pack to the front of
    their level in original order, matching the reference's stable
    per-level scatter. RestoreIndex maps level-concatenated order back
    to the input order."""
    jnp = _jnp()
    if op.input("RoisNum"):
        raise UnimplementedError(
            "distribute_fpn_proposals: batched RoisNum input is not "
            "supported yet — split per image and distribute each "
            "image's rois separately")
    rois = ctx.get_input(op, "FpnRois")             # [R, 4]
    lo = op.attr("min_level", 2)
    hi = op.attr("max_level", 5)
    refer_level = op.attr("refer_level", 4)
    refer_scale = op.attr("refer_scale", 224)
    n_level = hi - lo + 1
    R = rois.shape[0]

    ws = rois[:, 2] - rois[:, 0] + 1.0
    hs = rois[:, 3] - rois[:, 1] + 1.0
    scale = jnp.sqrt(ws * hs)
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6) + refer_level)
    lvl = jnp.clip(lvl, lo, hi).astype(jnp.int32)   # [R]

    outs, counts, restore_src = [], [], []
    offset = jnp.zeros((), jnp.int32)
    positions = jnp.zeros((R,), jnp.int32)
    for li in range(n_level):
        mask = lvl == (lo + li)
        cnt = mask.sum().astype(jnp.int32)
        # stable pack-to-front: rank within the level by original index
        rank = jnp.cumsum(mask) - 1                 # [R]
        padded = jnp.zeros((R, 4), rois.dtype)
        padded = padded.at[jnp.where(mask, rank, R)].set(
            rois, mode="drop")
        outs.append(padded)
        counts.append(cnt.reshape(1))
        positions = jnp.where(mask, offset + rank, positions)
        offset = offset + cnt
    # reference restore_index[original_idx] = position in the
    # level-concatenated order (distribute_fpn_proposals_op.h:160-162)
    ctx.set_outputs(op, "MultiFpnRois", outs)
    ctx.set_output(op, "RestoreIndex", positions[:, None])
    if op.output("MultiLevelRoIsNum"):
        ctx.set_outputs(op, "MultiLevelRoIsNum", counts)


def _collect_fpn_infer(op, block):
    rois0 = in_var(op, block, "MultiLevelRois")
    post = op.attr("post_nms_topN", 100)
    set_out(op, block, "FpnRois", (post, 4), rois0.dtype)
    if op.output("RoisNum"):
        set_out(op, block, "RoisNum", (1,), "int32")


@register_op("collect_fpn_proposals", infer=_collect_fpn_infer,
             grad=None)
def _collect_fpn_proposals(ctx, op):
    """reference collect_fpn_proposals_op.h: concat per-level rois +
    scores, keep the global top post_nms_topN by score. Padded-input
    convention: each level i supplies rois [Ri, 4], scores [Ri, 1] and
    (optionally) MultiLevelRoIsNum counts masking the padding."""
    jnp = _jnp()
    rois_list = ctx.get_inputs(op, "MultiLevelRois")
    score_list = ctx.get_inputs(op, "MultiLevelScores")
    post = op.attr("post_nms_topN", 100)
    NEG = jnp.asarray(-jnp.inf, score_list[0].dtype)
    if op.input("MultiLevelRoIsNum"):
        nums = ctx.get_inputs(op, "MultiLevelRoIsNum")
        masked = []
        for s, n in zip(score_list, nums):
            idx = jnp.arange(s.shape[0])
            masked.append(jnp.where(idx < n[0], s[:, 0], NEG))
        scores = jnp.concatenate(masked)
    else:
        scores = jnp.concatenate([s[:, 0] for s in score_list])
    from jax import lax

    rois = jnp.concatenate(rois_list, axis=0)
    k = min(post, scores.shape[0])
    topv, topi = lax.top_k(scores, k)
    valid = topv > NEG
    out = jnp.zeros((post, 4), rois.dtype)
    out = out.at[jnp.arange(k)].set(
        jnp.where(valid[:, None], rois[topi], 0.0))
    ctx.set_output(op, "FpnRois", out)
    if op.output("RoisNum"):
        ctx.set_output(op, "RoisNum",
                       valid.sum().astype(jnp.int32).reshape(1))


# ---------------------------------------------------------------------------
# yolov3_loss
# ---------------------------------------------------------------------------

def _yolov3_loss_infer(op, block):
    x = in_var(op, block, "X")
    gt = in_var(op, block, "GTBox")
    N, H, W = x.shape[0], x.shape[2], x.shape[3]
    M = len(op.attr("anchor_mask", []))
    set_out(op, block, "Loss", (N,), x.dtype)
    if op.output("ObjectnessMask"):
        set_out(op, block, "ObjectnessMask", (N, M, H, W), x.dtype)
    if op.output("GTMatchMask"):
        set_out(op, block, "GTMatchMask", (N, gt.shape[1]), "int32")


@register_op("yolov3_loss", infer=_yolov3_loss_infer, grad="auto")
def _yolov3_loss(ctx, op):
    """reference yolov3_loss_op.h:28-250 — YOLOv3 training loss.

    Per image: every predicted box whose best IoU against a valid gt
    exceeds ignore_thresh drops out of the objectness loss (mask -1);
    every gt matches its best anchor by origin-centered IoU, and if
    that anchor belongs to this head's anchor_mask, the gt's cell pays
    sigmoid-CE x/y + L1 w/h location loss scaled by (2 - w*h)*score,
    per-class sigmoid-CE label loss, and positive objectness. The
    match/ignore decisions are stop_gradient (the reference grad kernel
    treats ObjectnessMask/GTMatchMask as constants)."""
    import jax
    from jax import lax

    jnp = _jnp()
    x = ctx.get_input(op, "X")                      # [N, M*(5+C), H, W]
    gt_box = ctx.get_input(op, "GTBox")             # [N, B, 4] xywh
    gt_label = ctx.get_input(op, "GTLabel")         # [N, B] int
    gt_score = (ctx.get_input(op, "GTScore")
                if op.single_input("GTScore") else None)
    anchors = np.asarray(op.attr("anchors", []), np.float32)
    anchor_mask = [int(a) for a in op.attr("anchor_mask", [])]
    C = op.attr("class_num", 1)
    ignore_thresh = op.attr("ignore_thresh", 0.7)
    downsample = op.attr("downsample_ratio", 32)
    use_smooth = op.attr("use_label_smooth", True)
    scale_xy = op.attr("scale_x_y", 1.0)
    bias_xy = -0.5 * (scale_xy - 1.0)

    N, _, H, W = x.shape
    M = len(anchor_mask)
    B = gt_box.shape[1]
    an_num = anchors.size // 2
    input_size = downsample * H
    label_pos, label_neg = 1.0, 0.0
    if use_smooth:
        sw = min(1.0 / C, 1.0 / 40)
        label_pos, label_neg = 1.0 - sw, sw

    xr = x.reshape(N, M, 5 + C, H, W)
    if gt_score is None:
        gt_score = jnp.ones((N, B), x.dtype)

    def sce(logit, label):
        # reference SigmoidCrossEntropy: max(x,0) - x*z + log1p(e^-|x|)
        return (jnp.maximum(logit, 0.0) - logit * label
                + jnp.log1p(jnp.exp(-jnp.abs(logit))))

    def center_iou(b1, b2):
        """center-form IoU; b* = (..., 4) broadcastable."""
        ov = lambda c1, w1, c2, w2: (
            jnp.minimum(c1 + w1 / 2, c2 + w2 / 2)
            - jnp.maximum(c1 - w1 / 2, c2 - w2 / 2))
        w = ov(b1[..., 0], b1[..., 2], b2[..., 0], b2[..., 2])
        h = ov(b1[..., 1], b1[..., 3], b2[..., 1], b2[..., 3])
        inter = jnp.where((w < 0) | (h < 0), 0.0, w * h)
        union = (b1[..., 2] * b1[..., 3] + b2[..., 2] * b2[..., 3]
                 - inter)
        return inter / jnp.maximum(union, 1e-10)

    aw = jnp.asarray(anchors[0::2], x.dtype)
    ah = jnp.asarray(anchors[1::2], x.dtype)
    mask_arr = jnp.asarray(anchor_mask, jnp.int32)

    def one_image(xi, gts, glabels, gscores):
        valid = (gts[:, 2] * gts[:, 3]) > 1e-6     # [B]
        # --- ignore mask: best pred-gt IoU > thresh -> -1 ------------
        gx = jnp.arange(W, dtype=x.dtype)[None, None, :]
        gy = jnp.arange(H, dtype=x.dtype)[None, :, None]
        sig = jax.nn.sigmoid
        px = (gx + sig(xi[:, 0]) * scale_xy + bias_xy) / W
        py = (gy + sig(xi[:, 1]) * scale_xy + bias_xy) / H
        pw = jnp.exp(xi[:, 2]) * aw[mask_arr][:, None, None] / input_size
        ph = jnp.exp(xi[:, 3]) * ah[mask_arr][:, None, None] / input_size
        pred = jnp.stack([px, py, pw, ph], axis=-1)  # [M,H,W,4]
        iou = center_iou(pred[:, :, :, None, :],
                         gts[None, None, None, :, :])  # [M,H,W,B]
        iou = jnp.where(valid[None, None, None, :], iou, 0.0)
        best = iou.max(axis=-1)
        obj_mask0 = jnp.where(best > ignore_thresh,
                              jnp.asarray(-1.0, x.dtype), 0.0)

        # --- gt -> anchor matching -----------------------------------
        an_boxes = jnp.stack([jnp.zeros_like(aw), jnp.zeros_like(ah),
                              aw / input_size, ah / input_size], axis=1)
        gt_shift = gts.at[:, 0:2].set(0.0)
        a_iou = center_iou(an_boxes[None, :, :], gt_shift[:, None, :])
        best_n = jnp.argmax(a_iou, axis=1).astype(jnp.int32)   # [B]
        in_mask = (mask_arr[None, :] == best_n[:, None])
        mask_idx = jnp.where(in_mask.any(axis=1),
                             jnp.argmax(in_mask, axis=1), -1)
        mask_idx = jnp.where(valid, mask_idx, -1)              # [B]
        gi = jnp.clip((gts[:, 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gts[:, 1] * H).astype(jnp.int32), 0, H - 1)
        matched = mask_idx >= 0

        # positive objectness overrides ignore, in gt order (reference
        # writes sequentially; later gts win the cell)
        def write(t, m):
            return lax.cond(
                matched[t],
                lambda mm: mm.at[mask_idx[t], gj[t], gi[t]].set(
                    gscores[t]),
                lambda mm: mm, m)
        obj_mask = lax.fori_loop(0, B, write, obj_mask0)
        obj_mask = lax.stop_gradient(obj_mask)

        # --- location + label loss (sum over matched gts) ------------
        midx = jnp.clip(mask_idx, 0, M - 1)
        cell = (midx, gj, gi)
        tx = gts[:, 0] * W - gi
        ty = gts[:, 1] * H - gj
        tw = jnp.log(jnp.maximum(
            gts[:, 2] * input_size / aw[jnp.clip(best_n, 0, an_num - 1)],
            1e-9))
        th = jnp.log(jnp.maximum(
            gts[:, 3] * input_size / ah[jnp.clip(best_n, 0, an_num - 1)],
            1e-9))
        wscale = (2.0 - gts[:, 2] * gts[:, 3]) * gscores
        loc = (sce(xi[cell[0], 0, cell[1], cell[2]], tx)
               + sce(xi[cell[0], 1, cell[1], cell[2]], ty)
               + jnp.abs(xi[cell[0], 2, cell[1], cell[2]] - tw)
               + jnp.abs(xi[cell[0], 3, cell[1], cell[2]] - th)) * wscale
        cls_logits = xi[cell[0], 5:, cell[1], cell[2]]         # [B, C]
        onehot = (jnp.arange(C)[None, :]
                  == jnp.clip(glabels, 0, C - 1)[:, None])
        cls_tgt = jnp.where(onehot, label_pos, label_neg)
        lbl = sce(cls_logits, cls_tgt).sum(axis=1) * gscores
        loss_pos = jnp.where(matched, loc + lbl, 0.0).sum()

        # --- objectness loss -----------------------------------------
        obj_logit = xi[:, 4]                                   # [M,H,W]
        pos = obj_mask > 1e-5
        neg = (obj_mask <= 1e-5) & (obj_mask > -0.5)
        obj_loss = (jnp.where(pos, sce(obj_logit, 1.0) * obj_mask, 0.0)
                    + jnp.where(neg, sce(obj_logit, 0.0), 0.0)).sum()
        # mask_idx is already -1 for invalid gts
        return loss_pos + obj_loss, obj_mask, mask_idx.astype(jnp.int32)

    loss, obj_mask, match = jax.vmap(one_image)(
        xr, gt_box.astype(x.dtype), gt_label, gt_score.astype(x.dtype))
    ctx.set_output(op, "Loss", loss)
    if op.output("ObjectnessMask"):
        ctx.set_output(op, "ObjectnessMask", obj_mask)
    if op.output("GTMatchMask"):
        ctx.set_output(op, "GTMatchMask", match)
