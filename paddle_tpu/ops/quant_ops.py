"""Fake-quantization ops (quantization-aware training + PTQ).

Reference: operators/fake_quantize_op.* — simulate int-k inference
inside the float graph: out = round(clip(x) / scale * qmax) * scale /
qmax, with the scale tracked per tensor (abs_max / moving average) or
per output channel (weights).  Gradients are straight-through
(identity), the standard QAT estimator the reference uses.
"""
from __future__ import annotations

import numpy as np

from ..framework.core import grad_var_name
from .registry import in_var, register_op, same_as_input, set_out


def _jnp():
    import jax.numpy as jnp
    return jnp


def _ste_grad_maker(fwd_op, block, helper):
    """Straight-through estimator: d(out)/d(x) = 1."""
    return [dict(type="assign",
                 inputs={"X": [grad_var_name(fwd_op.single_output("Out"))]},
                 outputs={"Out": [grad_var_name(
                     fwd_op.single_input("X"))]},
                 attrs={})]


def _qdq(jnp, x, scale, bits):
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)
    return q * scale / qmax


def _quant_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)
    if op.output("OutScale"):
        # persistable only when OutScale IS the moving-average state
        # (QAT aliases InScale==OutScale); the PTQ flavor writes a fresh
        # per-run scale var that must not join the saved persistables
        aliased = (bool(op.input("InScale"))
                   and op.single_input("InScale")
                   == op.single_output("OutScale"))
        set_out(op, block, "OutScale", (1,), "float32",
                persistable=aliased)


@register_op("fake_quantize_dequantize_abs_max", infer=_quant_infer,
             grad=_ste_grad_maker)
def _fq_abs_max(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    bits = op.attr("bit_length", 8)
    scale = jnp.max(jnp.abs(x))
    ctx.set_output(op, "Out", _qdq(jnp, x, scale, bits).astype(x.dtype))
    ctx.set_output(op, "OutScale", jnp.reshape(scale, (1,)))


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             infer=_quant_infer, grad=_ste_grad_maker,
             stateful_outputs=("OutScale",))
def _fq_moving(ctx, op):
    """Activations: scale = EMA of batch abs-max (reference
    fake_quantize_op.cc moving_average_abs_max).  In test mode the
    stored scale is used unchanged."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    in_scale = ctx.get_input(op, "InScale")
    bits = op.attr("bit_length", 8)
    rate = op.attr("moving_rate", 0.9)
    if ctx.is_test or op.attr("is_test", False):
        scale = jnp.reshape(in_scale, ())
        new_scale = in_scale
    else:
        cur = jnp.max(jnp.abs(x))
        prev = jnp.reshape(in_scale, ())
        # first batch adopts the observed scale (stored init 0)
        scale = jnp.where(prev > 0, rate * prev + (1 - rate) * cur, cur)
        new_scale = jnp.reshape(scale, (1,))
    ctx.set_output(op, "Out", _qdq(jnp, x, scale, bits).astype(x.dtype))
    ctx.set_output(op, "OutScale", new_scale)


def _cw_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)
    axis = op.attrs.get("quant_axis", 0)
    set_out(op, block, "OutScale", (x.shape[axis],), "float32")


@register_op("fake_channel_wise_quantize_dequantize_abs_max",
             infer=_cw_infer, grad=_ste_grad_maker)
def _fq_channel(ctx, op):
    """Weights: one scale per output channel (reference
    fake_channel_wise_quantize_*)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    bits = op.attr("bit_length", 8)
    axis = op.attr("quant_axis", 0)
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    out = _qdq(jnp, x, scale, bits)
    ctx.set_output(op, "Out", out.astype(x.dtype))
    ctx.set_output(op, "OutScale", jnp.reshape(scale, (-1,)))


# ---------------------------------------------------------------------------
# real (non-fake) quant ops — the mkldnn INT8 surface (reference
# operators/quantize_op.cc, dequantize_op.cc, requantize_op.cc); on TPU
# the integer tensors are ordinary int8 arrays XLA computes with.
# ---------------------------------------------------------------------------
def _q_same_shape(dtype):
    def infer(op, block):
        x = in_var(op, block, "Input")
        set_out(op, block, "Output", x.shape, dtype)
    return infer


@register_op("quantize", infer=_q_same_shape("int8"), grad=None)
def _quantize(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    scale = op.attr("Scale", 1.0)
    shift = op.attr("Shift", 0.0)
    q = jnp.round(x.astype("float32") * scale + shift)
    ctx.set_output(op, "Output",
                   jnp.clip(q, -128, 127).astype("int8"))


@register_op("dequantize", infer=_q_same_shape("float32"), grad=None)
def _dequantize(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    scale = op.attr("Scale", 1.0)
    shift = op.attr("Shift", 0.0)
    ctx.set_output(op, "Output",
                   (x.astype("float32") - shift) / scale)


@register_op("requantize", infer=_q_same_shape("int8"), grad=None)
def _requantize(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    s_in = op.attr("Scale_in", 1.0)
    s_out = op.attr("Scale_out", 1.0)
    q = jnp.round(x.astype("float32") * (s_out / s_in))
    ctx.set_output(op, "Output",
                   jnp.clip(q, -128, 127).astype("int8"))
