"""Fused single-op RNN surfaces: lstm / lstmp / gru / rnn (+cudnn_lstm).

Reference: paddle/fluid/operators/lstm_op.cc, lstmp_op.cc, gru_op.cc
(gate math in operators/math/detail/lstm_kernel.h — gate layout
[candidate, input, forget, output] — and gru_kernel.h:76 for
origin_mode), and cudnn_lstm_op.cc / the 2.0 `rnn` op (multi-layer,
bidirectional, mode attr).

TPU-first design: the reference's LoD batch-reorder machinery
(sequence2batch.h) and cuDNN descriptors collapse to one lax.scan per
layer/direction whose per-step math is a fused [H,kH] matmul on the MXU;
variable lengths use the repo-wide padded [B,T,...] + Lengths masking
convention (state freezes past each row's end). The x-projection
(Input @ Wx) is kept OUTSIDE lstm/lstmp/gru, exactly like the reference
(callers feed the projected [B,T,4H] stream) — so XLA fuses it into one
big [B*T, D]x[D, 4H] matmul instead of T small ones. The `rnn` op takes
raw input + a WeightList of (w_ih, w_hh, b_ih, b_hh) per layer*dir.
"""
from __future__ import annotations

from .registry import in_var, register_op, set_out


def _jnp():
    import jax.numpy as jnp
    return jnp


def _mask_step(x, lengths, t, new, old):
    jnp = _jnp()
    alive = (t < lengths)[:, None].astype(new.dtype)
    return alive * new + (1 - alive) * old


def _lstm_scan(xs, lengths, w, h0, c0, *, peep=None, reverse=False):
    """xs [T,B,4H] projected gates; w [H,4H]; returns (hs, h_T, c_T).

    Reference gate layout (math/detail/lstm_kernel.h):
    [candidate, input, forget, output]; peepholes (wi, wf) read c_prev,
    wo reads c_new.
    """
    import jax
    jnp = _jnp()
    H = w.shape[0]
    if reverse:
        xs = xs[::-1]
    T = xs.shape[0]
    # original time index per scan position (reverse runs T-1..0) —
    # a step is alive iff its original index < length
    idxs = jnp.arange(T - 1, -1, -1) if reverse else jnp.arange(T)

    def step(carry, inp):
        xt, t = inp
        h, c = carry
        z = xt + h @ w
        g, i, f, o = (z[:, :H], z[:, H:2 * H], z[:, 2 * H:3 * H],
                      z[:, 3 * H:])
        if peep is not None:
            wi, wf, wo = peep
            i = i + wi * c
            f = f + wf * c
        i, f = jax.nn.sigmoid(i), jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        if peep is not None:
            o = o + wo * c_new
        o = jax.nn.sigmoid(o)
        h_new = o * jnp.tanh(c_new)
        alive = (t < lengths)[:, None].astype(h_new.dtype)
        h_c = alive * h_new + (1 - alive) * h
        c_c = alive * c_new + (1 - alive) * c
        # per-step outputs zero past each row's end (repo-wide padded
        # convention, matches sequence_pad); carry freezes instead
        return (h_c, c_c), (alive * h_new, alive * c_new)

    (h_l, c_l), (hs, cs) = jax.lax.scan(step, (h0, c0), (xs, idxs))
    if reverse:
        hs, cs = hs[::-1], cs[::-1]
    return hs, cs, h_l, c_l


def _lstm_io(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "Input")          # [B, T, 4H] projected
    w = ctx.get_input(op, "Weight")         # [H, 4H]
    bias = ctx.get_input(op, "Bias")        # [4H] or [7H] w/ peepholes
    lengths = ctx.get_input(op, "Lengths")
    H = w.shape[0]
    peep = None
    if op.attr("use_peepholes", False):
        b, pw = bias[..., :4 * H], bias[..., 4 * H:]
        pw = pw.reshape(-1)
        peep = (pw[:H], pw[H:2 * H], pw[2 * H:])
    else:
        b = bias
    xs = jnp.swapaxes(x + b.reshape(1, 1, -1), 0, 1)
    B = x.shape[0]
    h0 = (ctx.get_input(op, "H0") if op.input("H0")
          else jnp.zeros((B, H), x.dtype))
    c0 = (ctx.get_input(op, "C0") if op.input("C0")
          else jnp.zeros((B, H), x.dtype))
    return xs, lengths, w, h0, c0, peep


def _lstm_infer(op, block):
    x = in_var(op, block, "Input")
    H = in_var(op, block, "Weight").shape[0]
    set_out(op, block, "Hidden", (x.shape[0], x.shape[1], H), x.dtype)
    set_out(op, block, "Cell", (x.shape[0], x.shape[1], H), x.dtype)


@register_op("lstm", infer=_lstm_infer)
def _lstm(ctx, op):
    jnp = _jnp()
    xs, lengths, w, h0, c0, peep = _lstm_io(ctx, op)
    hs, cs, _, _ = _lstm_scan(xs, lengths, w, h0, c0, peep=peep,
                              reverse=bool(op.attr("is_reverse", False)))
    ctx.set_output(op, "Hidden", jnp.swapaxes(hs, 0, 1))
    ctx.set_output(op, "Cell", jnp.swapaxes(cs, 0, 1))


def _lstmp_infer(op, block):
    x = in_var(op, block, "Input")
    # lstmp Weight is [P,4H]; ProjWeight [H,P] carries both dims
    H, P = in_var(op, block, "ProjWeight").shape
    set_out(op, block, "Projection", (x.shape[0], x.shape[1], P),
            x.dtype)
    set_out(op, block, "Cell", (x.shape[0], x.shape[1], H), x.dtype)


@register_op("lstmp", infer=_lstmp_infer)
def _lstmp(ctx, op):
    """LSTM with recurrent projection (reference lstmp_op.cc): the
    recurrent state is r = act(h @ ProjWeight) [B,P]; Weight is [P,4H]."""
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    w = ctx.get_input(op, "Weight")          # [P, 4H]
    wp = ctx.get_input(op, "ProjWeight")     # [H, P]
    bias = ctx.get_input(op, "Bias")
    lengths = ctx.get_input(op, "Lengths")
    H, P = wp.shape
    peep = None
    if op.attr("use_peepholes", False):
        b, pw = bias[..., :4 * H], bias[..., 4 * H:].reshape(-1)
        peep = (pw[:H], pw[H:2 * H], pw[2 * H:])
    else:
        b = bias
    xs = jnp.swapaxes(x + b.reshape(1, 1, -1), 0, 1)
    B = x.shape[0]
    r0 = jnp.zeros((B, P), x.dtype)
    c0 = jnp.zeros((B, H), x.dtype)
    reverse = bool(op.attr("is_reverse", False))
    if reverse:
        xs = xs[::-1]
    T = xs.shape[0]
    idxs = (jnp.arange(T - 1, -1, -1) if reverse else jnp.arange(T))

    def step(carry, inp):
        xt, t = inp
        r, c = carry
        z = xt + r @ w
        g, i, f, o = (z[:, :H], z[:, H:2 * H], z[:, 2 * H:3 * H],
                      z[:, 3 * H:])
        if peep is not None:
            i = i + peep[0] * c
            f = f + peep[1] * c
        i, f = jax.nn.sigmoid(i), jax.nn.sigmoid(f)
        c_new = f * c + i * jnp.tanh(g)
        if peep is not None:
            o = o + peep[2] * c_new
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        r_new = h_new @ wp
        act = op.attr("proj_activation", "tanh")
        if act == "tanh":
            r_new = jnp.tanh(r_new)
        alive = (t < lengths)[:, None].astype(r_new.dtype)
        r_c = alive * r_new + (1 - alive) * r
        c_c = alive * c_new + (1 - alive) * c
        return (r_c, c_c), (alive * r_new, alive * c_new)

    _, (rs, cs) = jax.lax.scan(step, (r0, c0), (xs, idxs))
    if reverse:
        rs, cs = rs[::-1], cs[::-1]
    ctx.set_output(op, "Projection", jnp.swapaxes(rs, 0, 1))
    ctx.set_output(op, "Cell", jnp.swapaxes(cs, 0, 1))


def _gru_infer(op, block):
    x = in_var(op, block, "Input")
    H = in_var(op, block, "Weight").shape[0]
    set_out(op, block, "Hidden", (x.shape[0], x.shape[1], H), x.dtype)


@register_op("gru", infer=_gru_infer)
def _gru(ctx, op):
    """Fused GRU (reference gru_op.cc): Input [B,T,3H] projected;
    Weight [H,3H] packs (update, reset) then candidate; origin_mode per
    gru_kernel.h:76."""
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    w = ctx.get_input(op, "Weight")
    lengths = ctx.get_input(op, "Lengths")
    H = w.shape[0]
    B = x.shape[0]
    if op.input("Bias"):
        x = x + ctx.get_input(op, "Bias").reshape(1, 1, -1)
    h0 = (ctx.get_input(op, "H0") if op.input("H0")
          else jnp.zeros((B, H), x.dtype))
    origin = bool(op.attr("origin_mode", False))
    reverse = bool(op.attr("is_reverse", False))
    xs = jnp.swapaxes(x, 0, 1)
    if reverse:
        xs = xs[::-1]
    T = xs.shape[0]
    idxs = (jnp.arange(T - 1, -1, -1) if reverse else jnp.arange(T))
    w_ur, w_c = w[:, :2 * H], w[:, 2 * H:]

    def step(h, inp):
        xt, t = inp
        g = xt[:, :2 * H] + h @ w_ur
        u = jax.nn.sigmoid(g[:, :H])
        r = jax.nn.sigmoid(g[:, H:])
        c = jnp.tanh(xt[:, 2 * H:] + (r * h) @ w_c)
        if origin:
            h_new = u * h + (1 - u) * c
        else:
            h_new = (1 - u) * h + u * c
        alive = (t < lengths)[:, None].astype(h_new.dtype)
        h_c = alive * h_new + (1 - alive) * h
        return h_c, alive * h_new

    _, hs = jax.lax.scan(step, h0, (xs, idxs))
    if reverse:
        hs = hs[::-1]
    ctx.set_output(op, "Hidden", jnp.swapaxes(hs, 0, 1))


# ---------------------------------------------------------------------------
# unified multi-layer rnn (reference 2.0 rnn op / cudnn_lstm_op.cc)
# ---------------------------------------------------------------------------
def _rnn_op_infer(op, block):
    x = in_var(op, block, "Input")
    H = int(op.attr("hidden_size"))
    nd = 2 if op.attr("is_bidirec", False) else 1
    L = int(op.attr("num_layers", 1))
    set_out(op, block, "Out", (x.shape[0], x.shape[1], H * nd), x.dtype)
    set_out(op, block, "LastH", (L * nd, x.shape[0], H), x.dtype)
    if op.output("LastC"):
        set_out(op, block, "LastC", (L * nd, x.shape[0], H), x.dtype)


def _rnn_op_lower(ctx, op):
    """Multi-layer (optionally bidirectional) LSTM/GRU/RNN. WeightList
    holds (w_ih [Din,kH], w_hh [H,kH], b_ih [kH], b_hh [kH]) per
    layer*direction, forward direction first."""
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    lengths = ctx.get_input(op, "Lengths")
    weights = ctx.get_inputs(op, "WeightList")
    mode = op.attr("mode", "LSTM")
    H = int(op.attr("hidden_size"))
    L = int(op.attr("num_layers", 1))
    ndir = 2 if op.attr("is_bidirec", False) else 1
    B = x.shape[0]
    lasth, lastc = [], []
    out = x
    wi = 0
    for layer in range(L):
        dirs = []
        for d in range(ndir):
            w_ih, w_hh, b_ih, b_hh = weights[wi:wi + 4]
            wi += 4
            proj = out @ w_ih + (b_ih + b_hh)
            xs = jnp.swapaxes(proj, 0, 1)
            rev = d == 1
            if mode == "LSTM":
                hs, cs, h_l, c_l = _lstm_scan(
                    xs, lengths, w_hh,
                    jnp.zeros((B, H), x.dtype),
                    jnp.zeros((B, H), x.dtype), reverse=rev)
                lastc.append(c_l)
                lasth.append(h_l)
                dirs.append(jnp.swapaxes(hs, 0, 1))
            elif mode == "GRU":
                if rev:
                    xs = xs[::-1]
                T = xs.shape[0]
                idxs = (jnp.arange(T - 1, -1, -1) if rev
                        else jnp.arange(T))
                w_ur, w_c = w_hh[:, :2 * H], w_hh[:, 2 * H:]

                def gstep(h, inp):
                    xt, t = inp
                    g = xt[:, :2 * H] + h @ w_ur
                    u = jax.nn.sigmoid(g[:, :H])
                    r = jax.nn.sigmoid(g[:, H:])
                    c = jnp.tanh(xt[:, 2 * H:] + (r * h) @ w_c)
                    h_new = (1 - u) * h + u * c
                    alive = (t < lengths)[:, None].astype(h_new.dtype)
                    h_c = alive * h_new + (1 - alive) * h
                    return h_c, alive * h_new

                h_l, hs = jax.lax.scan(
                    gstep, jnp.zeros((B, H), x.dtype), (xs, idxs))
                if rev:
                    hs = hs[::-1]
                lasth.append(h_l)
                dirs.append(jnp.swapaxes(hs, 0, 1))
            else:  # RNN_TANH / RNN_RELU
                act = (jnp.tanh if mode == "RNN_TANH"
                       else lambda v: jnp.maximum(v, 0))
                if rev:
                    xs = xs[::-1]
                T = xs.shape[0]
                idxs = (jnp.arange(T - 1, -1, -1) if rev
                        else jnp.arange(T))

                def rstep(h, inp):
                    xt, t = inp
                    h_new = act(xt + h @ w_hh)
                    alive = (t < lengths)[:, None].astype(h_new.dtype)
                    h_c = alive * h_new + (1 - alive) * h
                    return h_c, alive * h_new

                h_l, hs = jax.lax.scan(
                    rstep, jnp.zeros((B, H), x.dtype), (xs, idxs))
                if rev:
                    hs = hs[::-1]
                lasth.append(h_l)
                dirs.append(jnp.swapaxes(hs, 0, 1))
        out = jnp.concatenate(dirs, -1) if ndir > 1 else dirs[0]
        drop = op.attr("dropout_prob", 0.0)
        if drop and layer < L - 1:
            import jax as _jax
            keep = _jax.random.bernoulli(ctx.rng(op), 1.0 - drop,
                                         out.shape)
            out = jnp.where(keep, out / (1.0 - drop), 0)
    ctx.set_output(op, "Out", out)
    ctx.set_output(op, "LastH", jnp.stack(lasth))
    if op.output("LastC") and lastc:
        ctx.set_output(op, "LastC", jnp.stack(lastc))


register_op("rnn", infer=_rnn_op_infer, lower=_rnn_op_lower)
# cudnn_lstm is the pre-2.0 surface of the same kernel
register_op("cudnn_lstm", infer=_rnn_op_infer, lower=_rnn_op_lower)
