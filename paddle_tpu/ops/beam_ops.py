"""Beam-search decoding ops: beam_search, beam_search_decode, gather_tree.

Reference analogs: operators/beam_search_op.cc, beam_search_decode_op.cc,
gather_tree_op.cc. The reference implements hypothesis pruning with LoD
shrinking (finished hypotheses leave the batch); that is scalar-loop,
dynamic-shape machinery XLA cannot compile. Here the TPU-native
formulation: FIXED [batch, beam] shapes end-to-end, finished hypotheses
stay in the beam as end-token self-continuations with frozen scores
(the standard fixed-shape beam search of flax/t5x), and the whole decode
step is dense topk over [batch, beam*width] — one MXU/VPU-friendly
reduction instead of per-sentence queues.
"""
from __future__ import annotations

import numpy as np

from .registry import in_var, register_op, set_out

NEG_INF = -1e9


def _beam_search_infer(op, block):
    scores = in_var(op, block, "Scores")       # [B, K, W] accumulated
    B, K = scores.shape[0], op.attr("beam_size")
    set_out(op, block, "SelectedIds", (B, K), "int64")
    set_out(op, block, "SelectedScores", (B, K), scores.dtype)
    set_out(op, block, "ParentIdx", (B, K), "int64")


@register_op("beam_search", infer=_beam_search_infer, grad=None)
def _beam_search(ctx, op):
    """One fixed-shape beam step.

    Inputs:
      PreIds    [B, K] int64   — last selected token per hypothesis
      PreScores [B, K] float   — accumulated log-prob per hypothesis
      Ids       [B, K, W] int64 — candidate token ids per hypothesis
                 (typically a topk over the vocab; W = candidate width)
      Scores    [B, K, W] float — ACCUMULATED log-probs of candidates
                 (pre_score + step log-prob, reference accu_scores)
    Attrs: beam_size K, end_id.
    Outputs: SelectedIds/SelectedScores [B, K], ParentIdx [B, K] (which
    source hypothesis each selected candidate extends).

    Finished semantics (replaces reference LoD pruning,
    beam_search_op.cc:42 `PruneEndBeams`): a hypothesis whose PreIds is
    end_id contributes exactly one candidate — end_id again, at its
    frozen PreScores — so it persists in the beam without spawning
    continuations.
    """
    import jax.numpy as jnp
    import jax

    pre_ids = ctx.get_input(op, "PreIds")
    pre_scores = ctx.get_input(op, "PreScores")
    # Ids optional: absent means candidate slot w IS token id w (the
    # full-vocab case — avoids materializing a [B,K,V] int64 id tensor)
    ids = ctx.get_input(op, "Ids") if op.single_input("Ids") else None
    scores = ctx.get_input(op, "Scores")
    K = op.attr("beam_size")
    end_id = op.attr("end_id")
    B, K_in, W = scores.shape

    finished = (pre_ids == end_id)                       # [B, K]
    # finished rows: candidate 0 -> (end_id, frozen score), rest masked
    slot = jnp.arange(W)[None, None, :] == 0             # [1,1,W]
    cand_scores = jnp.where(
        finished[:, :, None],
        jnp.where(slot, pre_scores[:, :, None],
                  jnp.asarray(NEG_INF, scores.dtype)),
        scores)
    flat_scores = cand_scores.reshape(B, K_in * W)
    top_scores, top_idx = jax.lax.top_k(flat_scores, K)  # [B, K]
    parent = (top_idx // W).astype("int64")
    if ids is None:
        tok = (top_idx % W).astype("int64")
        # a selected candidate extending a finished parent is its end_id
        # self-continuation (slot 0), not token 0
        sel_ids = jnp.where(jnp.take_along_axis(finished, parent, axis=1),
                            jnp.asarray(end_id, "int64"), tok)
    else:
        cand_ids = jnp.where(finished[:, :, None],
                             jnp.asarray(end_id, ids.dtype), ids)
        sel_ids = jnp.take_along_axis(
            cand_ids.reshape(B, K_in * W), top_idx, axis=1).astype("int64")
    ctx.set_output(op, "SelectedIds", sel_ids)
    ctx.set_output(op, "SelectedScores", top_scores)
    ctx.set_output(op, "ParentIdx", parent)


def _gather_tree_infer(op, block):
    ids = in_var(op, block, "Ids")
    set_out(op, block, "Out", ids.shape, ids.dtype)


def _backtrack(ids, parents):
    """Reverse-scan beam backtrack (reference gather_tree_op.h:27).

    Ids/Parents: [T, B, K] -> [T, B, K]. Out[t, b, k] follows the parent
    chain from (T-1, b, k) down to step t. The reference walks each
    (b, k) chain with a scalar loop; here one reverse lax.scan carries
    the live parent row [B, K] and gathers whole [B, K] slices per step.
    """
    import jax
    import jax.numpy as jnp

    T = ids.shape[0]
    if T == 1:
        return ids

    def body(parent, xs):
        ids_t, par_t = xs
        out_t = jnp.take_along_axis(ids_t, parent, axis=1)
        parent = jnp.take_along_axis(par_t, parent, axis=1)
        return parent, out_t

    _, rows = jax.lax.scan(body, parents[T - 1],
                           (ids[:T - 1], parents[:T - 1]), reverse=True)
    return jnp.concatenate([rows, ids[T - 1:]], axis=0)


@register_op("gather_tree", infer=_gather_tree_infer, grad=None)
def _gather_tree(ctx, op):
    ctx.set_output(op, "Out",
                   _backtrack(ctx.get_input(op, "Ids"),
                              ctx.get_input(op, "Parents")))


def _bsd_infer(op, block):
    ids = in_var(op, block, "Ids")             # [T, B, K]
    T, B, K = ids.shape
    set_out(op, block, "SentenceIds", (B, K, T), "int64")
    set_out(op, block, "SentenceScores", (B, K),
            in_var(op, block, "Scores").dtype)
    set_out(op, block, "SentenceLengths", (B, K), "int64")


@register_op("beam_search_decode", infer=_bsd_infer, grad=None)
def _beam_search_decode(ctx, op):
    """Assemble final hypotheses from per-step beam outputs.

    Inputs: Ids/Parents [T, B, K] (per-step selected tokens + parent
    indices), Scores [B, K] (final accumulated log-probs). Outputs:
    SentenceIds [B, K, T] (end_id-padded past each hypothesis' end),
    SentenceScores [B, K], SentenceLengths [B, K] (tokens up to and
    including the first end_id, or T if never finished).

    Reference beam_search_decode_op.cc assembles LoD sentences on the
    host; this stays on device with dense padded output.
    """
    import jax.numpy as jnp

    ids = ctx.get_input(op, "Ids")
    parents = ctx.get_input(op, "Parents")
    scores = ctx.get_input(op, "Scores")
    end_id = op.attr("end_id")
    T, B, K = ids.shape

    full = _backtrack(ids, parents)                          # [T, B, K]
    sent = jnp.moveaxis(full, 0, 2).astype("int64")          # [B, K, T]
    is_end = sent == end_id
    # length = index of first end_id + 1, or T
    first_end = jnp.where(is_end.any(-1), is_end.argmax(-1) + 1, T)
    # pad everything past the first end with end_id
    t_idx = jnp.arange(T)[None, None, :]
    sent = jnp.where(t_idx < first_end[..., None], sent, end_id)
    ctx.set_output(op, "SentenceIds", sent)
    ctx.set_output(op, "SentenceScores", scores)
    ctx.set_output(op, "SentenceLengths", first_end.astype("int64"))
