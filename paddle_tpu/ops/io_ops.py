"""save / load ops.

Reference: operators/save_op.cc, load_op.cc, save_combine_op.cc,
load_combine_op.cc — checkpointing as *graph ops* run by the Executor, so
it composes with distributed execution.

TPU note: a save inside a jitted computation would force a device->host
sync, so these ops run as host callbacks via jax.experimental.io_callback
(ordered) — the XLA-native equivalent of the reference's synchronous
file-writing kernels.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .registry import register_op


def _save_arrays(path, names, arrays):
    path = str(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = {str(n): np.asarray(a) for n, a in zip(names, arrays)}
    with open(path, "wb") as f:
        pickle.dump(payload, f, protocol=2)
    return np.int32(0)


@register_op("save", infer=lambda op, block: None, grad=None)
def _save(ctx, op):
    import jax
    x = ctx.get_input(op, "X")
    path = op.attr("file_path")
    name = op.single_input("X")
    jax.experimental.io_callback(
        lambda a: _save_arrays(path, [name], [a]),
        jax.ShapeDtypeStruct((), np.int32), x, ordered=True)


@register_op("save_combine", infer=lambda op, block: None, grad=None)
def _save_combine(ctx, op):
    import jax
    xs = ctx.get_inputs(op, "X")
    names = op.input("X")
    path = op.attr("file_path")
    jax.experimental.io_callback(
        lambda *arrs: _save_arrays(path, names, arrs),
        jax.ShapeDtypeStruct((), np.int32), *xs, ordered=True)


def _load_infer(op, block):
    # target var must already carry shape/dtype metadata (reference load_op
    # reads them from the serialized tensor; we require declared vars)
    pass


@register_op("load", infer=_load_infer, grad=None,
             stateful_outputs=("Out",))
def _load(ctx, op):
    import jax
    from ..framework.core import dtype_to_np
    path = op.attr("file_path")
    name = op.single_output("Out")
    v = ctx.block._find_var_recursive(name)
    if v is None or v.shape is None:
        raise ValueError(f"load op: target var {name} needs declared "
                         f"shape/dtype")

    def _read():
        with open(path, "rb") as f:
            payload = pickle.load(f)
        key = name if name in payload else list(payload)[0]
        return np.asarray(payload[key], dtype=dtype_to_np(v.dtype))

    out = jax.experimental.io_callback(
        _read, jax.ShapeDtypeStruct(tuple(v.shape), dtype_to_np(v.dtype)),
        ordered=True)
    ctx.set_output(op, "Out", out)


@register_op("load_combine", infer=lambda op, block: None, grad=None,
             stateful_outputs=("Out",))
def _load_combine(ctx, op):
    import jax
    from ..framework.core import dtype_to_np
    path = op.attr("file_path")
    names = op.output("Out")
    metas = []
    for n in names:
        v = ctx.block._find_var_recursive(n)
        if v is None or v.shape is None:
            raise ValueError(f"load_combine: target var {n} needs "
                             f"declared shape/dtype")
        metas.append((tuple(v.shape), dtype_to_np(v.dtype)))

    def _read():
        with open(path, "rb") as f:
            payload = pickle.load(f)
        return tuple(np.asarray(payload[n], dtype=dt)
                     for n, (sh, dt) in zip(names, metas))

    outs = jax.experimental.io_callback(
        _read, tuple(jax.ShapeDtypeStruct(sh, dt) for sh, dt in metas),
        ordered=True)
    ctx.set_outputs(op, "Out", list(outs))


# ---------------------------------------------------------------------------
# py_func — user Python inside the graph (reference py_func_op.cc:44)
# ---------------------------------------------------------------------------
_PY_FUNCS = []


def register_py_func(fn) -> int:
    """Register a Python callable; returns its id (the reference keeps
    the same global registry on the Python side of py_func_op)."""
    _PY_FUNCS.append(fn)
    return len(_PY_FUNCS) - 1


def get_py_func(fid):
    return _PY_FUNCS[int(fid)]


def _py_func_infer(op, block):
    mirror = op.attr("__mirror_inputs__", None)
    if mirror is not None:
        dtypes = op.attr("__out_dtypes__", None)
        xs = op.input("X")
        for name, i, dt in zip(op.output("Out"), mirror, dtypes):
            src = block._find_var_recursive(xs[i])
            v = block._find_var_recursive(name)
            if v is None:
                v = block.create_var(name=name)
            v.shape, v.dtype = tuple(src.shape), dt
    # else: the layer front-end pre-declared the out vars with shapes


def _py_func_grad_maker(fwd_op, block, helper):
    """Backward = another py_func running the user's backward_func on
    (x..., out..., dout...) -> dx... (reference py_func_op.cc grad
    maker)."""
    from ..framework.core import grad_var_name
    bid = fwd_op.attr("backward_callable_id", -1)
    if bid is None or bid < 0:
        return []
    xs = list(fwd_op.input("X"))
    outs = list(fwd_op.output("Out"))
    douts = [grad_var_name(n) for n in outs]
    gxs, mirror, dtypes = [], [], []
    for i, n in enumerate(xs):
        v = block._find_var_recursive(n)
        if (v is not None and not v.stop_gradient
                and n not in helper.no_grad_set
                and str(v.dtype).startswith(("float", "bfloat"))):
            gxs.append(grad_var_name(n))
            mirror.append(i)  # dx_i has x_i's (runtime) shape
            dtypes.append(v.dtype)
    if not gxs:
        return []
    return [dict(type="py_func",
                 inputs={"X": xs + outs + douts},
                 outputs={"Out": gxs},
                 attrs={"forward_callable_id": bid,
                        "backward_callable_id": -1,
                        "__mirror_inputs__": mirror,
                        "__out_dtypes__": dtypes})]


@register_op("py_func", infer=_py_func_infer, grad=_py_func_grad_maker)
def _py_func(ctx, op):
    """Host callback via io_callback: the callable sees real numpy
    arrays, its results are shipped back to the device. Inside jit this
    is an ordered host round-trip — the documented cost of py_func on
    an accelerator (the reference pays a GPU sync the same way)."""
    import jax

    fn = get_py_func(op.attr("forward_callable_id"))
    xs = ctx.get_inputs(op, "X")
    out_names = op.output("Out")
    mirror = op.attr("__mirror_inputs__", None)
    if mirror is not None:
        # grad form: dx_i mirrors x_i's runtime shape (static var shapes
        # can carry -1 batch dims)
        dtypes = op.attr("__out_dtypes__")
        specs = [jax.ShapeDtypeStruct(tuple(xs[i].shape), dt)
                 for i, dt in zip(mirror, dtypes)]
    else:
        specs = [jax.ShapeDtypeStruct(tuple(ctx.var_shape(n)),
                                      ctx.var_dtype(n))
                 for n in out_names]

    def host(*arrays):
        res = fn(*[np.asarray(a) for a in arrays])
        res = list(res) if isinstance(res, (list, tuple)) else [res]
        return [np.asarray(r, s.dtype).reshape(s.shape)
                for r, s in zip(res, specs)]

    outs = jax.experimental.io_callback(host, specs, *xs)
    ctx.set_outputs(op, "Out", outs)


# ---------------------------------------------------------------------------
# distributed_lookup_table (reference distributed_ops/
# distributed_lookup_table_op.cc): sparse-table lookup. The PS-backed
# path lives in distributed/ps (communicator pulls); inside a compiled
# graph the op gathers from the locally-materialized table slice — the
# transpiled PS program feeds W from the pulled parameter.
# ---------------------------------------------------------------------------
def _dlt_infer(op, block):
    w = block.var(op.input("W")[0])
    for name, src in zip(op.output("Outputs"), op.input("Ids")):
        ids = block.var(src)
        v = block._find_var_recursive(name)
        if v is None:
            v = block.create_var(name=name)
        v.shape = tuple(ids.shape[:-1]) + (w.shape[-1],)
        v.dtype = w.dtype


@register_op("distributed_lookup_table", infer=_dlt_infer)
def _distributed_lookup_table(ctx, op):
    w = ctx.get_input(op, "W")
    outs = []
    for ids in ctx.get_inputs(op, "Ids"):
        idx = ids.reshape(ids.shape[:-1]).astype("int32")
        outs.append(w[idx])
    ctx.set_outputs(op, "Outputs", outs)
