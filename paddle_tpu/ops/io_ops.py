"""save / load ops.

Reference: operators/save_op.cc, load_op.cc, save_combine_op.cc,
load_combine_op.cc — checkpointing as *graph ops* run by the Executor, so
it composes with distributed execution.

TPU note: a save inside a jitted computation would force a device->host
sync, so these ops run as host callbacks via jax.experimental.io_callback
(ordered) — the XLA-native equivalent of the reference's synchronous
file-writing kernels.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .registry import register_op


def _save_arrays(path, names, arrays):
    path = str(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = {str(n): np.asarray(a) for n, a in zip(names, arrays)}
    with open(path, "wb") as f:
        pickle.dump(payload, f, protocol=2)
    return np.int32(0)


@register_op("save", infer=lambda op, block: None, grad=None)
def _save(ctx, op):
    import jax
    x = ctx.get_input(op, "X")
    path = op.attr("file_path")
    name = op.single_input("X")
    jax.experimental.io_callback(
        lambda a: _save_arrays(path, [name], [a]),
        jax.ShapeDtypeStruct((), np.int32), x, ordered=True)


@register_op("save_combine", infer=lambda op, block: None, grad=None)
def _save_combine(ctx, op):
    import jax
    xs = ctx.get_inputs(op, "X")
    names = op.input("X")
    path = op.attr("file_path")
    jax.experimental.io_callback(
        lambda *arrs: _save_arrays(path, names, arrs),
        jax.ShapeDtypeStruct((), np.int32), *xs, ordered=True)


def _load_infer(op, block):
    # target var must already carry shape/dtype metadata (reference load_op
    # reads them from the serialized tensor; we require declared vars)
    pass


@register_op("load", infer=_load_infer, grad=None,
             stateful_outputs=("Out",))
def _load(ctx, op):
    import jax
    from ..framework.core import dtype_to_np
    path = op.attr("file_path")
    name = op.single_output("Out")
    v = ctx.block._find_var_recursive(name)
    if v is None or v.shape is None:
        raise ValueError(f"load op: target var {name} needs declared "
                         f"shape/dtype")

    def _read():
        with open(path, "rb") as f:
            payload = pickle.load(f)
        key = name if name in payload else list(payload)[0]
        return np.asarray(payload[key], dtype=dtype_to_np(v.dtype))

    out = jax.experimental.io_callback(
        _read, jax.ShapeDtypeStruct(tuple(v.shape), dtype_to_np(v.dtype)),
        ordered=True)
    ctx.set_output(op, "Out", out)


@register_op("load_combine", infer=lambda op, block: None, grad=None,
             stateful_outputs=("Out",))
def _load_combine(ctx, op):
    import jax
    from ..framework.core import dtype_to_np
    path = op.attr("file_path")
    names = op.output("Out")
    metas = []
    for n in names:
        v = ctx.block._find_var_recursive(n)
        if v is None or v.shape is None:
            raise ValueError(f"load_combine: target var {n} needs "
                             f"declared shape/dtype")
        metas.append((tuple(v.shape), dtype_to_np(v.dtype)))

    def _read():
        with open(path, "rb") as f:
            payload = pickle.load(f)
        return tuple(np.asarray(payload[n], dtype=dt)
                     for n, (sh, dt) in zip(names, metas))

    outs = jax.experimental.io_callback(
        _read, tuple(jax.ShapeDtypeStruct(sh, dt) for sh, dt in metas),
        ordered=True)
    ctx.set_outputs(op, "Out", list(outs))
