"""Debug ops: print.

Reference: operators/print_op.cc (pass-through op that logs tensor
stats/values at run time; layers.Print builds it).  Lowering uses
jax.debug.print, which survives jit (host callback) — the TPU analog of
the reference's CPU-side LogTensor.
"""
from __future__ import annotations

import numpy as np

from ..framework.core import grad_var_name
from .registry import in_var, register_op, set_out


def _print_infer(op, block):
    x = in_var(op, block, "In")
    set_out(op, block, "Out", x.shape, x.dtype)


def _print_grad_maker(fwd_op, block, helper):
    """Backward: print the gradient when print_phase asks for it
    (reference print_op is_forward=false instance), else identity."""
    out_g = grad_var_name(fwd_op.single_output("Out"))
    in_g = grad_var_name(fwd_op.single_input("In"))
    phase = fwd_op.attrs.get("print_phase", "both")
    if phase in ("backward", "both"):
        attrs = {k: v for k, v in fwd_op.attrs.items()
                 if k in ("first_n", "message", "summarize")}
        attrs["message"] = (attrs.get("message") or "") + "@GRAD"
        attrs["print_phase"] = "forward"  # grad-of-grad stays silent
        return [dict(type="print", inputs={"In": [out_g]},
                     outputs={"Out": [in_g]}, attrs=attrs)]
    return [dict(type="assign", inputs={"X": [out_g]},
                 outputs={"Out": [in_g]}, attrs={})]


def _emit(message, shape, dtype, first_n, counter, head):
    if first_n > 0:
        if counter["n"] >= first_n:
            return
        counter["n"] += 1
    print(f"{message} shape={shape} dtype={dtype} data={head}",
          flush=True)


@register_op("print", infer=_print_infer, grad=_print_grad_maker)
def _print(ctx, op):
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "In")
    message = op.attr("message", "") or ""
    summarize = op.attr("summarize", 20)
    first_n = int(op.attr("first_n", -1) or -1)
    phase = op.attr("print_phase", "both")
    if phase in ("forward", "both"):
        flat = jnp.ravel(x)
        n = int(np.prod(jnp.shape(x))) if jnp.shape(x) else 1
        head = flat[:max(0, min(summarize if summarize > 0 else n, n))]
        counter = {"n": 0}  # first_n: host-side per-op-instance count
        shape, dtype = tuple(jnp.shape(x)), str(x.dtype)

        def cb(vals):
            _emit(message, shape, dtype, first_n, counter,
                  np.asarray(vals))

        jax.debug.callback(cb, head)
    ctx.set_output(op, "Out", x)
