"""Long-tail math / loss / tensor ops.

Reference analogs: one .cc each under paddle/fluid/operators/ (addmm_op,
allclose_op, mv_op, minus_op, l1_norm_op, squared_l2_distance_op,
hinge_loss_op, modified_huber_loss_op, margin_rank_loss_op, rank_loss_op,
bpr_loss_op, teacher_student_sigmoid_loss_op, nll_loss_op, selu_op,
size_op, shard_index_op, multiplex_op, unbind_op, reverse_op, cos_sim_op,
log_loss_op, sampling_id_op, fill_constant_batch_size_like_op,
uniform/gaussian_random_batch_size_like_op, mean_iou_op, edit_distance_op,
add_position_encoding_op, center_loss_op, empty_op, is_empty_op, fill_op,
unique_with_counts_op, conv_shift_op, cvm_op, where_index analog).
Each is a direct jnp/lax lowering — the reference's per-op CUDA kernels
and Eigen functors collapse to XLA-fused expressions.
"""
from __future__ import annotations

import numpy as np

from .registry import in_var, register_op, same_as_input, set_out


def _jnp():
    import jax.numpy as jnp
    return jnp


def _first_out(shape, dtype="float32"):
    def infer(op, block):
        set_out(op, block, "Out", shape, dtype)
    return infer


# ---------------------------------------------------------------------------
# math
# ---------------------------------------------------------------------------

@register_op("addmm", infer=lambda op, block: set_out(
    op, block, "Out",
    (in_var(op, block, "X").shape[0], in_var(op, block, "Y").shape[1]),
    in_var(op, block, "X").dtype))
def _addmm(ctx, op):
    jnp = _jnp()
    inp = ctx.get_input(op, "Input")
    x, y = ctx.get_input(op, "X"), ctx.get_input(op, "Y")
    ctx.set_output(op, "Out", op.attr("Beta", 1.0) * inp
                   + op.attr("Alpha", 1.0) * (x @ y))


@register_op("mv", infer=lambda op, block: set_out(
    op, block, "Out", (in_var(op, block, "X").shape[0],),
    in_var(op, block, "X").dtype))
def _mv(ctx, op):
    ctx.set_output(op, "Out",
                   ctx.get_input(op, "X") @ ctx.get_input(op, "Vec"))


@register_op("minus", infer=same_as_input())
def _minus(ctx, op):
    ctx.set_output(op, "Out",
                   ctx.get_input(op, "X") - ctx.get_input(op, "Y"))


@register_op("allclose", infer=lambda op, block: set_out(
    op, block, "Out", (), "bool"), grad=None)
def _allclose(ctx, op):
    jnp = _jnp()
    ctx.set_output(op, "Out", jnp.allclose(
        ctx.get_input(op, "Input"), ctx.get_input(op, "Other"),
        rtol=float(op.attr("rtol", 1e-5)),
        atol=float(op.attr("atol", 1e-8)),
        equal_nan=op.attr("equal_nan", False)))


@register_op("l1_norm", infer=lambda op, block: set_out(
    op, block, "Out", (), in_var(op, block, "X").dtype))
def _l1_norm(ctx, op):
    jnp = _jnp()
    ctx.set_output(op, "Out", jnp.abs(ctx.get_input(op, "X")).sum())


@register_op("squared_l2_distance", infer=lambda op, block: (
    set_out(op, block, "sub_result", in_var(op, block, "X").shape,
            in_var(op, block, "X").dtype),
    set_out(op, block, "Out", (in_var(op, block, "X").shape[0], 1),
            in_var(op, block, "X").dtype)))
def _squared_l2_distance(ctx, op):
    jnp = _jnp()
    d = ctx.get_input(op, "X") - ctx.get_input(op, "Y")
    ctx.set_output(op, "sub_result", d)
    ctx.set_output(op, "Out",
                   (d * d).reshape(d.shape[0], -1).sum(1, keepdims=True))


@register_op("size", infer=lambda op, block: set_out(
    op, block, "Out", (), "int64"), grad=None)
def _size(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    ctx.set_output(op, "Out", jnp.asarray(int(np.prod(x.shape)) if x.ndim
                                          else 1, "int64"))


@register_op("shard_index", infer=same_as_input(), grad=None)
def _shard_index(ctx, op):
    """id -> id % shard_size if it lands in this shard else ignore_value
    (reference shard_index_op.cc, PS sharded embedding lookup)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    index_num = op.attr("index_num")
    nshards = op.attr("nshards")
    shard_id = op.attr("shard_id")
    ignore = op.attr("ignore_value", -1)
    size = (index_num + nshards - 1) // nshards
    ctx.set_output(op, "Out", jnp.where(x // size == shard_id, x % size,
                                        ignore))


@register_op("multiplex", infer=lambda op, block: set_out(
    op, block, "Out", in_var(op, block, "X").shape,
    in_var(op, block, "X").dtype))
def _multiplex(ctx, op):
    """Row-wise select among candidate tensors by index
    (reference multiplex_op.cc)."""
    jnp = _jnp()
    xs = ctx.get_inputs(op, "X")
    ids = ctx.get_input(op, "Ids").reshape(-1).astype("int32")
    stacked = jnp.stack(xs, axis=0)            # [C, B, ...]
    ctx.set_output(op, "Out", stacked[ids, jnp.arange(stacked.shape[1])])


def _unbind_infer(op, block):
    x = in_var(op, block, "X")
    axis = op.attr("axis", 0)
    shape = list(x.shape)
    del shape[axis]
    set_out(op, block, "Out", shape, x.dtype)


@register_op("unbind", infer=_unbind_infer)
def _unbind(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    axis = op.attr("axis", 0)
    outs = [jnp.squeeze(s, axis) for s in
            jnp.split(x, x.shape[axis], axis=axis)]
    ctx.set_outputs(op, "Out", outs)


@register_op("reverse", infer=same_as_input())
def _reverse(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    ctx.set_output(op, "Out", jnp.flip(x, axis=tuple(op.attr("axis"))))


@register_op("cos_sim", infer=lambda op, block: set_out(
    op, block, "Out", (in_var(op, block, "X").shape[0], 1),
    in_var(op, block, "X").dtype))
def _cos_sim(ctx, op):
    jnp = _jnp()
    x, y = ctx.get_input(op, "X"), ctx.get_input(op, "Y")
    xn = jnp.sqrt((x * x).sum(-1, keepdims=True) + 1e-12)
    yn = jnp.sqrt((y * y).sum(-1, keepdims=True) + 1e-12)
    ctx.set_output(op, "Out", (x * y).sum(-1, keepdims=True) / (xn * yn))


@register_op("log_loss", infer=same_as_input("Predicted", "Loss"))
def _log_loss(ctx, op):
    jnp = _jnp()
    p = ctx.get_input(op, "Predicted")
    y = ctx.get_input(op, "Labels")
    eps = op.attr("epsilon", 1e-4)
    ctx.set_output(op, "Loss", -y * jnp.log(p + eps)
                   - (1 - y) * jnp.log(1 - p + eps))


@register_op("selu", infer=same_as_input())
def _selu(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    scale = op.attr("scale", 1.0507009873554805)
    alpha = op.attr("alpha", 1.6732632423543772)
    ctx.set_output(op, "Out", scale * jnp.where(
        x > 0, x, alpha * (jnp.exp(x) - 1)))


@register_op("conv_shift", infer=same_as_input())
def _conv_shift(ctx, op):
    """Circular convolution (reference conv_shift_op.cc): x [B, M],
    y [B, N] (N odd, N<=M); out[b,i] = sum_j x[b,(i+j-N//2) % M]*y[b,j]."""
    jnp = _jnp()
    x, y = ctx.get_input(op, "X"), ctx.get_input(op, "Y")
    B, M = x.shape
    N = y.shape[1]
    half = N // 2
    idx = (jnp.arange(M)[:, None] + jnp.arange(N)[None, :] - half) % M
    ctx.set_output(op, "Out",
                   jnp.einsum("bmn,bn->bm", x[:, idx], y))


@register_op("add_position_encoding", infer=same_as_input())
def _add_position_encoding(ctx, op):
    """Sinusoidal position encoding added in-place
    (reference add_position_encoding_op.cc)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # [B, T, D]
    alpha = op.attr("alpha", 1.0)
    beta = op.attr("beta", 1.0)
    B, T, D = x.shape
    half = D // 2
    pos = jnp.arange(T, dtype=jnp.float32)[:, None]
    div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) / half)
    enc = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], axis=1)
    ctx.set_output(op, "Out", alpha * x + beta * enc[None].astype(x.dtype))


def _cvm_infer(op, block):
    x = in_var(op, block, "X")
    cols = x.shape[1] if op.attr("use_cvm", True) else x.shape[1] - 2
    set_out(op, block, "Y", (x.shape[0], cols), x.dtype)


@register_op("cvm", infer=_cvm_infer)
def _cvm(ctx, op):
    """Continuous-value model feature transform (reference cvm_op.cc):
    the leading two columns (show, click) become [log(show+1),
    log(click+1) - log(show+1)]; use_cvm=False drops them."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    use_cvm = op.attr("use_cvm", True)
    show = jnp.log(x[:, :1] + 1)
    click = jnp.log(x[:, 1:2] + 1) - show
    if use_cvm:
        out = jnp.concatenate([show, click, x[:, 2:]], axis=1)
    else:
        out = x[:, 2:]
    ctx.set_output(op, "Y", out)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

@register_op("hinge_loss", infer=same_as_input("Logits", "Loss"))
def _hinge_loss(ctx, op):
    jnp = _jnp()
    logits = ctx.get_input(op, "Logits")
    labels = ctx.get_input(op, "Labels")
    ctx.set_output(op, "Loss", jnp.maximum(
        0.0, 1.0 - (2.0 * labels - 1.0) * logits))


@register_op("modified_huber_loss", infer=lambda op, block: (
    set_out(op, block, "IntermediateVal", in_var(op, block, "X").shape,
            in_var(op, block, "X").dtype),
    set_out(op, block, "Out", in_var(op, block, "X").shape,
            in_var(op, block, "X").dtype)))
def _modified_huber_loss(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    y = ctx.get_input(op, "Y")
    z = (2.0 * y - 1.0) * x
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, (1.0 - z) ** 2, 0.0))
    ctx.set_output(op, "IntermediateVal", z)
    ctx.set_output(op, "Out", loss)


@register_op("margin_rank_loss", infer=lambda op, block: (
    set_out(op, block, "Activated", in_var(op, block, "X1").shape,
            in_var(op, block, "X1").dtype),
    set_out(op, block, "Out", in_var(op, block, "X1").shape,
            in_var(op, block, "X1").dtype)))
def _margin_rank_loss(ctx, op):
    jnp = _jnp()
    x1, x2 = ctx.get_input(op, "X1"), ctx.get_input(op, "X2")
    label = ctx.get_input(op, "Label")
    margin = op.attr("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    ctx.set_output(op, "Activated", (out > 0).astype(x1.dtype))
    ctx.set_output(op, "Out", out)


@register_op("rank_loss", infer=lambda op, block: set_out(
    op, block, "Out", in_var(op, block, "Left").shape,
    in_var(op, block, "Left").dtype))
def _rank_loss(ctx, op):
    import jax
    left = ctx.get_input(op, "Left")
    right = ctx.get_input(op, "Right")
    label = ctx.get_input(op, "Label")
    d = left - right
    ctx.set_output(op, "Out",
                   jax.nn.softplus(d) - label * d)


@register_op("bpr_loss", infer=lambda op, block: set_out(
    op, block, "Y", (in_var(op, block, "X").shape[0], 1),
    in_var(op, block, "X").dtype))
def _bpr_loss(ctx, op):
    """Bayesian personalized ranking (reference bpr_loss_op.cc):
    -mean_j log sigmoid(x_label - x_j), j != label."""
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # [B, C]
    label = ctx.get_input(op, "Label").reshape(-1).astype("int32")
    B, C = x.shape
    pos = jnp.take_along_axis(x, label[:, None], axis=1)    # [B, 1]
    mask = jnp.arange(C)[None, :] != label[:, None]
    losses = jax.nn.softplus(-(pos - x)) * mask
    ctx.set_output(op, "Y", losses.sum(1, keepdims=True) / (C - 1))


@register_op("teacher_student_sigmoid_loss", infer=lambda op, block:
             set_out(op, block, "Y",
                     (in_var(op, block, "X").shape[0], 1),
                     in_var(op, block, "X").dtype))
def _ts_sigmoid_loss(ctx, op):
    """reference teacher_student_sigmoid_loss_op.cc: CTR distillation —
    label < -1 pure teacher, -1<=label<0 binary, else mixed."""
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X").reshape(-1)
    label = ctx.get_input(op, "Label").reshape(-1)
    sp = jax.nn.softplus
    teacher = label + 2.0
    binary = jnp.where(label < -0.5, 0.0, 1.0)
    out = jnp.where(
        label < -1.0, sp(x) - x * teacher,
        jnp.where(label < 0.0, sp(x) - x * binary,
                  sp(x) - x * jnp.clip(label, 0.0, 1.0)
                  + sp(x) - x * jnp.where(label > 0, 1.0, 0.0)))
    ctx.set_output(op, "Y", out[:, None])


@register_op("nll_loss", infer=lambda op, block: (
    set_out(op, block, "Out",
            () if op.attr("reduction", "mean") != "none"
            else (in_var(op, block, "X").shape[0],),
            in_var(op, block, "X").dtype),
    set_out(op, block, "Total_weight", (),
            in_var(op, block, "X").dtype)))
def _nll_loss(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # [B, C] log-probs
    label = ctx.get_input(op, "Label").reshape(-1).astype("int32")
    w = ctx.get_input(op, "Weight") if op.single_input("Weight") else None
    ignore = op.attr("ignore_index", -100)
    reduction = op.attr("reduction", "mean")
    picked = -jnp.take_along_axis(x, label[:, None], axis=1)[:, 0]
    wt = w[label] if w is not None else jnp.ones_like(picked)
    keep = (label != ignore)
    picked = jnp.where(keep, picked * wt, 0.0)
    total_w = jnp.where(keep, wt, 0.0).sum()
    if reduction == "none":
        out = picked
    elif reduction == "sum":
        out = picked.sum()
    else:
        out = picked.sum() / jnp.maximum(total_w, 1e-12)
    ctx.set_output(op, "Out", out)
    ctx.set_output(op, "Total_weight", total_w)


@register_op("center_loss", infer=lambda op, block: (
    set_out(op, block, "Loss", (in_var(op, block, "X").shape[0], 1),
            in_var(op, block, "X").dtype),
    set_out(op, block, "SampleCenterDiff", in_var(op, block, "X").shape,
            in_var(op, block, "X").dtype),
    set_out(op, block, "CentersOut",
            in_var(op, block, "Centers").shape,
            in_var(op, block, "Centers").dtype)),
    stateful_outputs=("CentersOut",))
def _center_loss(ctx, op):
    """reference center_loss_op.cc: pull features toward class centers;
    centers update by averaged per-class diffs (update=True)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # [B, D]
    label = ctx.get_input(op, "Label").reshape(-1).astype("int32")
    centers = ctx.get_input(op, "Centers")     # [C, D]
    lr = ctx.get_input(op, "CenterUpdateRate").reshape(())
    diff = x - centers[label]
    ctx.set_output(op, "SampleCenterDiff", diff)
    ctx.set_output(op, "Loss", 0.5 * (diff * diff).sum(1, keepdims=True))
    if op.attr("need_update", True):
        import jax
        counts = jnp.zeros((centers.shape[0],)).at[label].add(1.0)
        sums = jnp.zeros_like(centers).at[label].add(diff)
        upd = sums / (1.0 + counts)[:, None]
        ctx.set_output(op, "CentersOut", centers + lr * upd)
    else:
        ctx.set_output(op, "CentersOut", centers)


# ---------------------------------------------------------------------------
# tensor creation / shape-like
# ---------------------------------------------------------------------------

def _batch_size_like_infer(out_slot):
    def infer(op, block):
        x = in_var(op, block, "Input")
        shape = list(op.attr("shape"))
        in_idx = op.attr("input_dim_idx", 0)
        out_idx = op.attr("output_dim_idx", 0)
        shape[out_idx] = x.shape[in_idx]
        set_out(op, block, out_slot, shape, _creation_dtype(op))
    return infer


def _creation_dtype(op):
    """Creation-op dtype attr: the repo convention is "dtype"
    (fill_constant/range/linspace); "dtype_str" accepted as an alias."""
    return op.attr("dtype", None) or op.attr("dtype_str", None) \
        or "float32"


@register_op("fill_constant_batch_size_like",
             infer=_batch_size_like_infer("Out"), grad=None)
def _fill_constant_bsl(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    shape = list(op.attr("shape"))
    shape[op.attr("output_dim_idx", 0)] = x.shape[op.attr("input_dim_idx",
                                                          0)]
    ctx.set_output(op, "Out",
                   jnp.full(shape, op.attr("value", 0.0),
                            _creation_dtype(op)))


@register_op("uniform_random_batch_size_like",
             infer=_batch_size_like_infer("Out"), grad=None)
def _uniform_random_bsl(ctx, op):
    import jax
    x = ctx.get_input(op, "Input")
    shape = list(op.attr("shape"))
    shape[op.attr("output_dim_idx", 0)] = x.shape[op.attr("input_dim_idx",
                                                          0)]
    ctx.set_output(op, "Out", jax.random.uniform(
        ctx.rng(op), shape, minval=op.attr("min", -1.0),
        maxval=op.attr("max", 1.0)))


@register_op("gaussian_random_batch_size_like",
             infer=_batch_size_like_infer("Out"), grad=None)
def _gaussian_random_bsl(ctx, op):
    import jax
    x = ctx.get_input(op, "Input")
    shape = list(op.attr("shape"))
    shape[op.attr("output_dim_idx", 0)] = x.shape[op.attr("input_dim_idx",
                                                          0)]
    ctx.set_output(op, "Out", op.attr("mean", 0.0)
                   + op.attr("std", 1.0)
                   * jax.random.normal(ctx.rng(op), shape))


@register_op("empty", infer=lambda op, block: set_out(
    op, block, "Out", op.attr("shape"), _creation_dtype(op)),
    grad=None)
def _empty(ctx, op):
    jnp = _jnp()
    ctx.set_output(op, "Out", jnp.zeros(
        op.attr("shape"), _creation_dtype(op)))


@register_op("fill", infer=lambda op, block: set_out(
    op, block, "Out", op.attr("shape"), _creation_dtype(op)),
    grad=None)
def _fill(ctx, op):
    jnp = _jnp()
    ctx.set_output(op, "Out", jnp.asarray(
        np.array(op.attr("value"), dtype="float64").reshape(
            op.attr("shape")), _creation_dtype(op)))


@register_op("is_empty", infer=lambda op, block: set_out(
    op, block, "Out", (), "bool"), grad=None)
def _is_empty(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    ctx.set_output(op, "Out", jnp.asarray(int(np.prod(x.shape)) == 0))


@register_op("sampling_id", infer=lambda op, block: set_out(
    op, block, "Out", (in_var(op, block, "X").shape[0],), "int64"),
    grad=None)
def _sampling_id(ctx, op):
    """Sample one class index per row from a probability matrix
    (reference sampling_id_op.cc)."""
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    ids = jax.random.categorical(ctx.rng(op), jnp.log(x + 1e-20), axis=1)
    ctx.set_output(op, "Out", ids.astype("int64"))


# ---------------------------------------------------------------------------
# metrics-adjacent
# ---------------------------------------------------------------------------

@register_op("mean_iou", infer=lambda op, block: (
    set_out(op, block, "OutMeanIou", (), "float32"),
    set_out(op, block, "OutWrong", (op.attr("num_classes"),), "int32"),
    set_out(op, block, "OutCorrect", (op.attr("num_classes"),), "int32")),
    grad=None)
def _mean_iou(ctx, op):
    jnp = _jnp()
    pred = ctx.get_input(op, "Predictions").reshape(-1).astype("int32")
    label = ctx.get_input(op, "Labels").reshape(-1).astype("int32")
    C = op.attr("num_classes")
    correct = jnp.zeros((C,), "int32").at[jnp.where(
        pred == label, pred, C - 1)].add(
        (pred == label).astype("int32"))
    # wrong counts: union minus intersection per class
    pred_c = jnp.zeros((C,), "int32").at[pred].add(1)
    label_c = jnp.zeros((C,), "int32").at[label].add(1)
    inter = correct
    union = pred_c + label_c - inter
    iou = jnp.where(union > 0, inter / jnp.maximum(union, 1), 0.0)
    present = (union > 0).sum()
    ctx.set_output(op, "OutMeanIou",
                   (iou.sum() / jnp.maximum(present, 1)).astype("float32"))
    ctx.set_output(op, "OutWrong", (union - inter).astype("int32"))
    ctx.set_output(op, "OutCorrect", inter.astype("int32"))


@register_op("edit_distance", infer=lambda op, block: (
    set_out(op, block, "Out",
            (in_var(op, block, "Hyps").shape[0], 1), "float32"),
    set_out(op, block, "SequenceNum", (), "int64")), grad=None)
def _edit_distance(ctx, op):
    """Levenshtein distance per row (reference edit_distance_op.cc),
    padded [B, L] + lengths; DP over a lax.scan on the shorter axis."""
    import jax
    jnp = _jnp()
    hyp = ctx.get_input(op, "Hyps").astype("int32")
    ref = ctx.get_input(op, "Refs").astype("int32")
    hyp_len = ctx.get_input(op, "HypsLength").reshape(-1)
    ref_len = ctx.get_input(op, "RefsLength").reshape(-1)
    B, H = hyp.shape
    Rl = ref.shape[1]

    # row[j] = distance(hyp[:i], ref[:j]); scan over hyp positions
    init = jnp.broadcast_to(jnp.arange(Rl + 1, dtype=jnp.float32),
                            (B, Rl + 1))

    def body(row, i):
        h_i = hyp[:, i]                                     # [B]
        sub_cost = (ref != h_i[:, None]).astype(jnp.float32)  # [B, Rl]

        def inner(carry, j):
            prev_row_jm1 = row[:, j]
            prev_row_j = row[:, j + 1]
            left = carry
            val = jnp.minimum(jnp.minimum(prev_row_j + 1, left + 1),
                              prev_row_jm1 + sub_cost[:, j])
            return val, val

        first = row[:, 0] + 1
        _, rest = jax.lax.scan(inner, first, jnp.arange(Rl))
        new_row = jnp.concatenate([first[None], rest], axis=0).T
        # positions past hyp_len keep the old row
        alive = (i < hyp_len)
        return jnp.where(alive[:, None], new_row, row), None

    row, _ = jax.lax.scan(body, init, jnp.arange(H))
    d = jnp.take_along_axis(row, ref_len[:, None].astype("int32"),
                            axis=1)[:, 0]
    if op.attr("normalized", False):
        d = d / jnp.maximum(ref_len.astype(jnp.float32), 1.0)
    ctx.set_output(op, "Out", d[:, None].astype("float32"))
    ctx.set_output(op, "SequenceNum", jnp.asarray(B, "int64"))


@register_op("unique_with_counts", infer=lambda op, block: (
    set_out(op, block, "Out", in_var(op, block, "X").shape,
            in_var(op, block, "X").dtype),
    set_out(op, block, "Index", in_var(op, block, "X").shape, "int64"),
    set_out(op, block, "Count", in_var(op, block, "X").shape, "int64")),
    grad=None)
def _unique_with_counts(ctx, op):
    """Fixed-shape unique (XLA static-shape contract, like the repo's
    `unique`): Out is padded with the first unique value; Index maps
    each input element to its slot in Out; Count is per-slot."""
    jnp = _jnp()
    x = ctx.get_input(op, "X").reshape(-1)
    uniq, idx, counts = (
        jnp.unique(x, return_inverse=True, return_counts=True,
                   size=x.shape[0]))
    ctx.set_output(op, "Out", uniq)
    ctx.set_output(op, "Index", idx.reshape(-1).astype("int64"))
    ctx.set_output(op, "Count", counts.astype("int64"))
