"""Operator library: importing this package registers all op lowerings.

TPU-native equivalent of the reference's operator library
(paddle/fluid/operators/ — see SURVEY.md §2.3); ops here are JAX lowering
rules compiled by XLA instead of per-op CUDA kernels.
"""
from . import math_ops  # noqa: F401
from . import tensor_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import collective_ops  # noqa: F401
from . import amp_ops  # noqa: F401
from . import control_flow_ops  # noqa: F401
from . import dgc_ops  # noqa: F401
from . import attention_ops  # noqa: F401
from . import rope_ops  # noqa: F401
from . import decode_ops  # noqa: F401
from . import io_ops  # noqa: F401
from . import debug_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import quant_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import beam_ops  # noqa: F401
from . import crf_ctc_ops  # noqa: F401
from . import misc_ops  # noqa: F401
from . import nn_extra_ops  # noqa: F401
from . import linalg_ops  # noqa: F401
from . import interp_extra_ops  # noqa: F401
from . import pool_extra_ops  # noqa: F401
from . import misc2_ops  # noqa: F401
from . import rnn_fused_ops  # noqa: F401
from . import catalog_seq_ops  # noqa: F401
from . import catalog_ctr_ops  # noqa: F401
from . import moe_ops  # noqa: F401
from .registry import (LowerContext, all_registered_ops, get_op_def,  # noqa
                       has_op, register_op)
