"""Neural-network op lowerings: conv / pool / norm / softmax / losses.

Replaces the reference's cuDNN-backed kernels (operators/conv_op.*,
conv_cudnn_op.cu, pool_op.*, batch_norm_op.*, layer_norm_op.*,
softmax_op.*, softmax_with_cross_entropy_op.*, cross_entropy_op.*,
dropout_op.*, operators/math/softmax.*) with lax/jnp lowerings: convs map
onto the MXU via lax.conv_general_dilated, pooling via lax.reduce_window,
and XLA fuses the pointwise epilogues.
"""
from __future__ import annotations

import functools

import numpy as np

from ..framework.core import Block, Operator, dtype_to_np
from .registry import (LowerContext, in_var, register_op, same_as_input,
                       set_out)


def _jnp():
    import jax.numpy as jnp
    return jnp


def _lax():
    import jax.lax as lax
    return lax


def _conv_precision(dtype):
    """f32 convs at full precision on TPU (DEFAULT would truncate operands
    to bf16 on the MXU); CPU's DEFAULT is already full f32."""
    import jax
    import jax.numpy as jnp
    if dtype in (jnp.bfloat16, np.float16):
        return None
    if jax.default_backend() == "cpu":
        return None
    return jax.lax.Precision.HIGHEST


# ---------------------------------------------------------------------------
# softmax & friends
# ---------------------------------------------------------------------------

@register_op("softmax", infer=same_as_input())
def _softmax(ctx, op):
    import jax
    ctx.set_output(op, "Out",
                   jax.nn.softmax(ctx.get_input(op, "X"),
                                  axis=op.attr("axis", -1)))


@register_op("log_softmax", infer=same_as_input())
def _log_softmax(ctx, op):
    import jax
    ctx.set_output(op, "Out",
                   jax.nn.log_softmax(ctx.get_input(op, "X"),
                                      axis=op.attr("axis", -1)))


def _ce_infer(op: Operator, block: Block):
    x = in_var(op, block, "X")
    label = in_var(op, block, "Label")
    soft = op.attr("soft_label", False)
    out = list(label.shape if not soft else x.shape[:-1] + (1,))
    if not soft and (not out or out[-1] != 1):
        out = list(x.shape[:-1]) + [1]
    set_out(op, block, "Y", out, x.dtype)


@register_op("cross_entropy", infer=_ce_infer)
def _cross_entropy(ctx: LowerContext, op: Operator):
    """-log(p[label]); input X is already a probability distribution
    (reference operators/cross_entropy_op.h)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    label = ctx.get_input(op, "Label")
    eps = 1e-12
    if op.attr("soft_label", False):
        y = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        if jnp.ndim(label) == jnp.ndim(x):
            label = jnp.squeeze(label, -1)
        p = jnp.take_along_axis(x, label[..., None].astype("int32"), axis=-1)
        ignore = op.attr("ignore_index", -100)
        y = -jnp.log(p + eps)
        if ignore >= 0:
            y = jnp.where(label[..., None] == ignore, 0.0, y)
    ctx.set_output(op, "Y", y)


def _swce_infer(op, block):
    x = in_var(op, block, "Logits")
    label = in_var(op, block, "Label")
    axis = op.attr("axis", -1) % len(x.shape)
    loss = list(x.shape)
    loss[axis] = 1
    set_out(op, block, "Softmax", x.shape, x.dtype)
    set_out(op, block, "Loss", loss, x.dtype)


@register_op("softmax_with_cross_entropy", infer=_swce_infer)
def _softmax_with_cross_entropy(ctx, op):
    """Logsumexp formulation: loss = lse(logits) - logit[label].

    Deliberately NOT log_softmax-then-gather — that materializes the
    full [N, V] log-prob tensor in HBM (297 MB for the BERT MLM head at
    batch 128, V=30522; profiled at ~5% of the train step as
    'data formatting' copies). Here the forward writes only [N, 1]
    reductions; the Softmax output is a pure elementwise of logits that
    XLA fuses into its consumer or DCEs when unused, and the vjp's
    softmax-minus-onehot recomputes from logits inside the backward
    matmul fusion."""
    import jax

    jnp = _jnp()
    logits = ctx.get_input(op, "Logits")
    label = ctx.get_input(op, "Label")
    axis = op.attr("axis", -1) % jnp.ndim(logits)
    m = jax.lax.stop_gradient(jnp.max(logits, axis=axis, keepdims=True))
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m), axis=axis,
                              keepdims=True))
    softmax = jnp.exp(logits - lse)
    if op.attr("soft_label", False):
        # sum(label * (lse - logits)) — no [N,V] log-prob intermediate
        loss = jnp.sum(label * (lse - logits), axis=axis, keepdims=True)
    else:
        lab = label
        if jnp.ndim(lab) == jnp.ndim(logits):
            lab = jnp.squeeze(lab, axis)
        picked = jnp.take_along_axis(
            logits, jnp.expand_dims(lab.astype("int32"), axis),
            axis=axis)
        loss = lse - picked
        ignore = op.attr("ignore_index", -100)
        if ignore >= 0:
            loss = jnp.where(
                jnp.expand_dims(lab, axis) == ignore, 0.0, loss)
    ctx.set_output(op, "Softmax", softmax)
    ctx.set_output(op, "Loss", loss)


@register_op("sigmoid_cross_entropy_with_logits", infer=same_as_input())
def _sigmoid_ce(ctx, op):
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    label = ctx.get_input(op, "Label")
    loss = jnp.maximum(x, 0) - x * label + jax.nn.softplus(-jnp.abs(x))
    ignore = op.attr("ignore_index", -100)
    if ignore >= 0:
        loss = jnp.where(label == ignore, 0.0, loss)
    if op.attr("normalize", False):
        n = jnp.maximum(jnp.sum((label != ignore).astype(x.dtype)), 1.0)
        loss = loss / n
    ctx.set_output(op, "Out", loss)


@register_op("bce_loss", infer=same_as_input())
def _bce_loss(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    label = ctx.get_input(op, "Label")
    eps = 1e-12
    out = -(label * jnp.log(x + eps) + (1 - label) * jnp.log(1 - x + eps))
    ctx.set_output(op, "Out", out)


def _loss_reduce_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", [], x.dtype)


@register_op("squared_l2_norm", infer=_loss_reduce_infer)
def _squared_l2_norm(ctx, op):
    x = ctx.get_input(op, "X")
    ctx.set_output(op, "Out", _jnp().sum(x * x))


@register_op("huber_loss", infer=lambda op, block: (
    set_out(op, block, "Out", in_var(op, block, "X").shape,
            in_var(op, block, "X").dtype),
    set_out(op, block, "Residual", in_var(op, block, "X").shape,
            in_var(op, block, "X").dtype)))
def _huber_loss(ctx, op):
    jnp = _jnp()
    x, y = ctx.get_input(op, "X"), ctx.get_input(op, "Y")
    d = op.attr("delta", 1.0)
    r = y - x
    out = jnp.where(jnp.abs(r) <= d, 0.5 * r * r,
                    d * (jnp.abs(r) - 0.5 * d))
    ctx.set_output(op, "Residual", r)
    ctx.set_output(op, "Out", out)


@register_op("smooth_l1_loss", infer=lambda op, block: (
    set_out(op, block, "Diff", in_var(op, block, "X").shape,
            in_var(op, block, "X").dtype),
    set_out(op, block, "Out",
            list(in_var(op, block, "X").shape[:1]) + [1],
            in_var(op, block, "X").dtype)))
def _smooth_l1(ctx, op):
    jnp = _jnp()
    x, y = ctx.get_input(op, "X"), ctx.get_input(op, "Y")
    sigma = op.attr("sigma", 1.0)
    s2 = sigma * sigma
    d = x - y
    val = jnp.where(jnp.abs(d) < 1.0 / s2, 0.5 * d * d * s2,
                    jnp.abs(d) - 0.5 / s2)
    ctx.set_output(op, "Diff", d)
    ctx.set_output(op, "Out",
                   jnp.sum(val.reshape(val.shape[0], -1), -1, keepdims=True))


@register_op("mse_loss", infer=same_as_input())
def _mse(ctx, op):
    x, y = ctx.get_input(op, "X"), ctx.get_input(op, "Y")
    ctx.set_output(op, "Out", (x - y) ** 2)


def _kldiv_infer(op, block):
    x = in_var(op, block, "X")
    red = op.attrs.get("reduction", "mean")
    shape = x.shape if red == "none" else ()
    set_out(op, block, "Loss", shape, x.dtype)


@register_op("kldiv_loss", infer=_kldiv_infer)
def _kldiv(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    target = ctx.get_input(op, "Target")
    loss = jnp.where(target > 0, target * (jnp.log(target) - x), 0.0)
    red = op.attr("reduction", "mean")
    if red == "mean":
        loss = jnp.mean(loss)
    elif red == "sum":
        loss = jnp.sum(loss)
    elif red == "batchmean":
        loss = jnp.sum(loss) / jnp.shape(x)[0]
    ctx.set_output(op, "Loss", loss)


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------

def _dropout_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)
    if op.output("Mask"):
        set_out(op, block, "Mask", x.shape, "uint8")


def _dropout_keep(key, shape, thresh):
    import jax
    jnp = _jnp()
    return jax.random.bits(key, shape, "uint8") >= jnp.uint8(thresh)


_REMAT_DROPOUT = None


def _remat_dropout():
    """Dropout whose backward REGENERATES the keep mask from the
    stateless key instead of saving it as a residual.

    The saved state is just the key (a few bytes) — the [*x.shape] mask
    never round-trips HBM between forward and backward, and the forward
    select stays free to fuse into its producer (the mask residual was
    pinning a materialization per site; 25 sites x ~13 MB at the BERT
    flagship config). rbg bit generation is cheap enough to pay twice.

    Built lazily on first dropout lowering so module import stays
    jax-free (the ops package convention).
    """
    global _REMAT_DROPOUT
    if _REMAT_DROPOUT is None:
        import jax
        jnp = _jnp()

        @functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
        def fn(x, key, thresh, scale):
            keep = _dropout_keep(key, jnp.shape(x), thresh)
            return jnp.where(keep, x * scale if scale != 1.0 else x,
                             0.0).astype(x.dtype)

        def fwd(x, key, thresh, scale):
            return fn(x, key, thresh, scale), key

        def bwd(thresh, scale, key, g):
            keep = _dropout_keep(key, jnp.shape(g), thresh)
            dx = jnp.where(keep, g * scale if scale != 1.0 else g, 0.0)
            return dx.astype(g.dtype), None

        fn.defvjp(fwd, bwd)
        _REMAT_DROPOUT = fn
    return _REMAT_DROPOUT


@register_op("dropout", infer=_dropout_infer)
def _dropout(ctx: LowerContext, op: Operator):
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    p = op.attr("dropout_prob", 0.5)
    impl = op.attr("dropout_implementation", "downgrade_in_infer")
    is_test = op.attr("is_test", False) or ctx.is_test
    if is_test:
        out = x if impl == "upscale_in_train" else x * (1.0 - p)
        ctx.set_output(op, "Out", out)
        if op.output("Mask"):
            ctx.set_output(op, "Mask",
                           jnp.ones(jnp.shape(x), dtype="uint8"))
        return
    # NOTE(perf): a pallas fused-dropout kernel with in-kernel hardware
    # PRNG (pltpu.prng_random_bits) was built and measured on v5e:
    # 775 samples/s vs 847 for this XLA path on the BERT flagship — the
    # pallas_call boundary costs more fusion than the in-kernel bits
    # save in HBM traffic. XLA already fuses bernoulli+select into the
    # surrounding elementwise chains; keep the XLA path.
    scale = (0.0 if p >= 1.0 else 1.0 / (1.0 - p)) \
        if impl == "upscale_in_train" else 1.0
    # raw-bits threshold instead of bernoulli: same keep distribution
    # (uniform bits >= p*2^n has probability ~1-p) without bernoulli's
    # bits->float _uniform conversion pass (profiled ~1.4% of the BERT
    # step across 37 dropout sites).  uint8 bits: 4x less rng HBM
    # traffic than u32 (the [B,h,S,S] prob-dropout bits tensor alone is
    # 100 MB at seq-128); keep-probability granularity 1/256 (p quantized
    # by <0.4%, irrelevant for regularization)
    if p >= 255.5 / 256.0:  # not representable in u8 granularity: drop all
        keep = jnp.zeros(jnp.shape(x), bool)
        out = jnp.where(keep, x, 0.0).astype(x.dtype)
        ctx.set_output(op, "Out", out)
        if op.output("Mask"):
            ctx.set_output(op, "Mask", keep.astype("uint8"))
        return
    thresh = round(max(p, 0.0) * 256.0)
    if op.output("Mask"):
        # mask requested (reference-compat Mask output): materialize it
        bits = jax.random.bits(ctx.rng(op), jnp.shape(x), "uint8")
        keep = bits >= jnp.uint8(thresh)
        out = jnp.where(keep, x * scale if scale != 1.0 else x,
                        0.0).astype(x.dtype)
        ctx.set_output(op, "Out", out)
        ctx.set_output(op, "Mask", keep.astype("uint8"))
        return
    ctx.set_output(op, "Out",
                   _remat_dropout()(x, ctx.rng(op), thresh, scale))


# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------

def _conv_out_dim(i, k, pad0, pad1, stride, dil):
    if i == -1:
        return -1
    ke = (k - 1) * dil + 1
    return (i + pad0 + pad1 - ke) // stride + 1


def _resolve_padding(op, spatial, ksize, strides, dils):
    pad = op.attr("paddings", [0] * len(spatial))
    algo = op.attr("padding_algorithm", "EXPLICIT")
    n = len(spatial)
    if algo == "VALID":
        return [(0, 0)] * n
    if algo == "SAME":
        pairs = []
        for i in range(n):
            out = -(-spatial[i] // strides[i]) if spatial[i] != -1 else 1
            ke = (ksize[i] - 1) * dils[i] + 1
            total = max((out - 1) * strides[i] + ke - spatial[i], 0)
            pairs.append((total // 2, total - total // 2))
        return pairs
    if len(pad) == n:
        return [(p, p) for p in pad]
    if len(pad) == 2 * n:
        return [(pad[2 * i], pad[2 * i + 1]) for i in range(n)]
    return [(0, 0)] * n


def _conv2d_infer(op: Operator, block: Block):
    x = in_var(op, block, "Input")
    w = in_var(op, block, "Filter")
    fmt = op.attr("data_format", "NCHW")
    strides = op.attr("strides", [1, 1])
    dils = op.attr("dilations", [1, 1])
    if fmt in ("NCHW", "AnyLayout"):
        n, c, h, wd = x.shape
    else:
        n, h, wd, c = x.shape
    kh, kw = w.shape[2], w.shape[3]
    pads = _resolve_padding(op, [h, wd], [kh, kw], strides, dils)
    oh = _conv_out_dim(h, kh, pads[0][0], pads[0][1], strides[0], dils[0])
    ow = _conv_out_dim(wd, kw, pads[1][0], pads[1][1], strides[1], dils[1])
    oc = w.shape[0]
    out = [n, oc, oh, ow] if fmt in ("NCHW", "AnyLayout") else [n, oh, ow, oc]
    set_out(op, block, "Output", out, x.dtype)


def _conv2d_lower(ctx: LowerContext, op: Operator):
    lax = _lax()
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    w = ctx.get_input(op, "Filter")  # OIHW, as in the reference
    fmt = op.attr("data_format", "NCHW")
    if fmt == "AnyLayout":
        fmt = "NCHW"
    strides = tuple(op.attr("strides", [1, 1]))
    dils = tuple(op.attr("dilations", [1, 1]))
    groups = op.attr("groups", 1)
    if fmt == "NCHW":
        spatial = jnp.shape(x)[2:]
        dn = lax.conv_dimension_numbers(jnp.shape(x), jnp.shape(w),
                                        ("NCHW", "OIHW", "NCHW"))
    else:
        spatial = jnp.shape(x)[1:3]
        dn = lax.conv_dimension_numbers(jnp.shape(x), jnp.shape(w),
                                        ("NHWC", "OIHW", "NHWC"))
    pads = _resolve_padding(op, list(spatial),
                            [jnp.shape(w)[2], jnp.shape(w)[3]], strides, dils)
    # no preferred_element_type=f32 here: the result was rounded straight
    # back to x.dtype anyway (numerically identical — XLA's TPU conv
    # accumulates low-precision operands in f32 internally), and jax
    # 0.4.x's conv transpose rule can't mix an f32 cotangent with bf16
    # primals (lax.conv requires same dtypes), which broke conv2d_grad
    # under bf16 AMP
    out = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pads, rhs_dilation=dils,
        dimension_numbers=dn, feature_group_count=groups,
        precision=_conv_precision(x.dtype))
    ctx.set_output(op, "Output", out.astype(x.dtype))


register_op("conv2d", infer=_conv2d_infer, lower=_conv2d_lower)
register_op("depthwise_conv2d", infer=_conv2d_infer, lower=_conv2d_lower)


def _conv2d_transpose_infer(op, block):
    x = in_var(op, block, "Input")
    w = in_var(op, block, "Filter")  # [in_c, out_c/groups, kh, kw]
    strides = op.attr("strides", [1, 1])
    dils = op.attr("dilations", [1, 1])
    pad = op.attr("paddings", [0, 0])
    groups = op.attr("groups", 1)
    fmt = op.attr("data_format", "NCHW")
    n, c, h, wd = x.shape if fmt == "NCHW" else (
        x.shape[0], x.shape[3], x.shape[1], x.shape[2])
    kh, kw = w.shape[2], w.shape[3]
    pads = _resolve_padding(op, [h, wd], [kh, kw], strides, dils)
    oh = (h - 1) * strides[0] - pads[0][0] - pads[0][1] + (kh - 1) * dils[0] + 1
    ow = (wd - 1) * strides[1] - pads[1][0] - pads[1][1] + (kw - 1) * dils[1] + 1
    oc = w.shape[1] * groups
    out_size = op.attr("output_size", [])
    if out_size:
        oh, ow = out_size
    out = [n, oc, oh, ow] if fmt == "NCHW" else [n, oh, ow, oc]
    set_out(op, block, "Output", out, x.dtype)


# depthwise flavor shares the lowering: groups come from the attr
# (reference conv_transpose_op.cc registers both names over one kernel)
@register_op("depthwise_conv2d_transpose",
             infer=_conv2d_transpose_infer)
@register_op("conv2d_transpose", infer=_conv2d_transpose_infer)
def _conv2d_transpose_lower(ctx, op):
    """Gradient-of-conv formulation (same as conv3d_transpose): dilate
    the input by the stride, flip the kernel, pad with k_eff-1-p per
    side. Round-5 fix: the previous lax.conv_transpose call passed the
    FORWARD pads as literal pads on the dilated input, which silently
    shrank outputs for stride>1 or p != (k-1)/2 (stride-1 SAME-style
    configs happened to coincide, which is why it survived). Groups
    (incl. depthwise_conv2d_transpose) via feature_group_count."""
    lax = _lax()
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    w = ctx.get_input(op, "Filter")  # IOHW [Cin, Cout/g, kh, kw]
    strides = tuple(op.attr("strides", [1, 1]))
    dils = tuple(op.attr("dilations", [1, 1]))
    fmt = op.attr("data_format", "NCHW")
    g = int(op.attr("groups", 1))
    ch_axis = 1 if fmt == "NCHW" else 3
    spatial = (jnp.shape(x)[2:] if fmt == "NCHW"
               else jnp.shape(x)[1:3])
    kh, kw = jnp.shape(w)[2], jnp.shape(w)[3]
    pads_f = _resolve_padding(op, list(spatial), [kh, kw], strides,
                              dils)
    ke = [(k - 1) * d + 1 for k, d in zip((kh, kw), dils)]
    default_out = [
        (spatial[i] - 1) * strides[i] - pads_f[i][0] - pads_f[i][1]
        + ke[i] for i in range(2)]
    out_size = op.attr("output_size", []) or default_out
    pads = [(ke[i] - 1 - pads_f[i][0],
             ke[i] - 1 - pads_f[i][1]
             + int(out_size[i]) - default_out[i]) for i in range(2)]
    cin = jnp.shape(x)[ch_axis]
    wt = jnp.flip(w, axis=(2, 3))
    # IOHW -> OIHW with group-major output channels (paddle layout)
    wt = wt.reshape(g, cin // g, -1, kh, kw)
    wt = wt.transpose(0, 2, 1, 3, 4).reshape(-1, cin // g, kh, kw)
    dn = (("NCHW", "OIHW", "NCHW") if fmt == "NCHW"
          else ("NHWC", "OIHW", "NHWC"))
    out = lax.conv_general_dilated(
        x, wt.astype(x.dtype), window_strides=(1, 1), padding=pads,
        lhs_dilation=strides, rhs_dilation=dils,
        dimension_numbers=dn, feature_group_count=g,
        precision=_conv_precision(x.dtype))
    ctx.set_output(op, "Output", out.astype(x.dtype))


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------

def _pool2d_infer(op: Operator, block: Block):
    x = in_var(op, block, "X")
    fmt = op.attr("data_format", "NCHW")
    n, c, h, w = x.shape if fmt == "NCHW" else (
        x.shape[0], x.shape[3], x.shape[1], x.shape[2])
    if op.attr("global_pooling", False):
        oh = ow = 1
    elif op.attr("adaptive", False):
        oh, ow = op.attr("ksize", [1, 1])
    else:
        ks = op.attr("ksize", [1, 1])
        strides = op.attr("strides", [1, 1])
        pads = _resolve_padding(op, [h, w], ks, strides, [1, 1])
        ceil = op.attr("ceil_mode", False)
        def _od(i, k, p0, p1, s):
            if i == -1:
                return -1
            num = i + p0 + p1 - k
            return (num + s - 1) // s + 1 if ceil else num // s + 1
        oh = _od(h, ks[0], pads[0][0], pads[0][1], strides[0])
        ow = _od(w, ks[1], pads[1][0], pads[1][1], strides[1])
    out = [n, c, oh, ow] if fmt == "NCHW" else [n, oh, ow, c]
    set_out(op, block, "Out", out, x.dtype)


@register_op("pool2d", infer=_pool2d_infer)
def _pool2d(ctx: LowerContext, op: Operator):
    lax = _lax()
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    fmt = op.attr("data_format", "NCHW")
    ptype = op.attr("pooling_type", "max")
    sdims = (2, 3) if fmt == "NCHW" else (1, 2)
    shape = jnp.shape(x)
    if op.attr("global_pooling", False) or (
            op.attr("adaptive", False) and op.attr("ksize") == [1, 1]):
        red = jnp.max if ptype == "max" else jnp.mean
        ctx.set_output(op, "Out", red(x, axis=sdims, keepdims=True))
        return
    if op.attr("adaptive", False):
        oh, ow = op.attr("ksize")
        h, w = shape[sdims[0]], shape[sdims[1]]
        assert h % oh == 0 and w % ow == 0, \
            "adaptive pool needs divisible sizes under static shapes"
        ks = [h // oh, w // ow]
        strides = ks
        pads = [(0, 0), (0, 0)]
    else:
        ks = op.attr("ksize", [1, 1])
        strides = op.attr("strides", [1, 1])
        pads = _resolve_padding(op, [shape[sdims[0]], shape[sdims[1]]],
                                ks, strides, [1, 1])
    window = [1] * len(shape)
    wstrides = [1] * len(shape)
    padding = [(0, 0)] * len(shape)
    for i, d in enumerate(sdims):
        window[d] = ks[i]
        wstrides[d] = strides[i]
        padding[d] = pads[i]
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        out = lax.reduce_window(x, init, lax.max, window, wstrides, padding)
    else:
        summed = lax.reduce_window(x, 0.0, lax.add,
                                   window, wstrides, padding)
        if op.attr("exclusive", True) and any(p != (0, 0) for p in padding):
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add,
                                       window, wstrides, padding)
            out = summed / counts
        else:
            out = summed / float(np.prod(ks))
    ctx.set_output(op, "Out", out.astype(x.dtype))


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

def _bn_infer(op: Operator, block: Block):
    x = in_var(op, block, "X")
    c_axis = 1 if op.attr("data_layout", "NCHW") == "NCHW" else len(x.shape) - 1
    c = x.shape[c_axis]
    set_out(op, block, "Y", x.shape, x.dtype)
    for slot in ("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"):
        if op.output(slot):
            set_out(op, block, slot, [c], "float32")


def _bn_lower(ctx: LowerContext, op: Operator):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    scale = ctx.get_input(op, "Scale")
    bias = ctx.get_input(op, "Bias")
    mean = ctx.get_input(op, "Mean")
    var = ctx.get_input(op, "Variance")
    eps = op.attr("epsilon", 1e-5)
    momentum = op.attr("momentum", 0.9)
    layout = op.attr("data_layout", "NCHW")
    is_test = op.attr("is_test", False) or ctx.is_test
    use_global = op.attr("use_global_stats", False) or is_test

    nd = jnp.ndim(x)
    c_axis = 1 if layout == "NCHW" else nd - 1
    red_axes = tuple(i for i in range(nd) if i != c_axis)
    bshape = [1] * nd
    bshape[c_axis] = jnp.shape(x)[c_axis]

    xf = x.astype("float32")
    if use_global:
        use_mean, use_var = mean, var
        new_mean, new_var = mean, var
        saved_mean = mean
        saved_var = 1.0 / jnp.sqrt(var + eps)
    else:
        bmean = jnp.mean(xf, axis=red_axes)
        bvar = jnp.mean((xf - bmean.reshape(bshape)) ** 2, axis=red_axes)
        use_mean, use_var = bmean, bvar
        new_mean = momentum * mean + (1 - momentum) * bmean
        new_var = momentum * var + (1 - momentum) * bvar
        saved_mean = bmean
        saved_var = 1.0 / jnp.sqrt(bvar + eps)

    inv = 1.0 / jnp.sqrt(use_var + eps)
    y = (xf - use_mean.reshape(bshape)) * inv.reshape(bshape)
    y = y * scale.reshape(bshape) + bias.reshape(bshape)
    ctx.set_output(op, "Y", y.astype(x.dtype))
    ctx.set_output(op, "MeanOut", new_mean)
    ctx.set_output(op, "VarianceOut", new_var)
    ctx.set_output(op, "SavedMean", saved_mean)
    ctx.set_output(op, "SavedVariance", saved_var)


def _bn_grad_maker(fwd_op, block, helper):
    """batch_norm Y depends on X/Scale/Bias only (stats are derived), so the
    auto-vjp grad is correct -- but MeanOut/VarianceOut alias their inputs
    and must be excluded from re-lowering state.  We keep auto grads and let
    the executor's SSA env ordering handle aliasing (grad ops are emitted
    before any later state write)."""
    from .registry import build_auto_grad_specs
    specs = build_auto_grad_specs(fwd_op, block, helper.no_grad_set)
    for s in specs:
        # Mean/Variance inputs are running stats: never differentiable.
        s["outputs"].pop("Mean@GRAD", None)
        s["outputs"].pop("Variance@GRAD", None)
    return specs


register_op("batch_norm", infer=_bn_infer, lower=_bn_lower,
            grad=_bn_grad_maker,
            stateful_outputs=("MeanOut", "VarianceOut"))


def _sync_bn_lower(ctx: LowerContext, op: Operator):
    """Cross-replica batch norm (reference sync_batch_norm_op.cu:31:
    NCCL allreduce of per-device sum/sum-of-squares). On TPU the stats
    ride one lax.pmean pair over the dp axis inside shard_map — cheap
    on ICI — and the grad falls out of the auto-vjp (pmean has a
    defined transpose). Without a bound axis it degrades to plain BN
    (single participant), matching the reference's 1-GPU behavior."""
    import jax.lax as lax
    jnp = _jnp()
    from .collective_ops import _axis_name
    axis = _axis_name(ctx, op)
    x = ctx.get_input(op, "X")
    scale = ctx.get_input(op, "Scale")
    bias = ctx.get_input(op, "Bias")
    mean = ctx.get_input(op, "Mean")
    var = ctx.get_input(op, "Variance")
    eps = op.attr("epsilon", 1e-5)
    momentum = op.attr("momentum", 0.9)
    layout = op.attr("data_layout", "NCHW")
    is_test = op.attr("is_test", False) or ctx.is_test
    use_global = op.attr("use_global_stats", False) or is_test

    nd = jnp.ndim(x)
    c_axis = 1 if layout == "NCHW" else nd - 1
    red_axes = tuple(i for i in range(nd) if i != c_axis)
    bshape = [1] * nd
    bshape[c_axis] = jnp.shape(x)[c_axis]

    xf = x.astype("float32")
    if use_global:
        use_mean, use_var = mean, var
        new_mean, new_var = mean, var
        saved_mean = mean
        saved_var = 1.0 / jnp.sqrt(var + eps)
    else:
        m1 = jnp.mean(xf, axis=red_axes)
        m2 = jnp.mean(xf * xf, axis=red_axes)
        if axis is not None:
            m1 = lax.pmean(m1, axis)
            m2 = lax.pmean(m2, axis)
        bmean = m1
        bvar = jnp.maximum(m2 - m1 * m1, 0.0)
        use_mean, use_var = bmean, bvar
        new_mean = momentum * mean + (1 - momentum) * bmean
        new_var = momentum * var + (1 - momentum) * bvar
        saved_mean = bmean
        saved_var = 1.0 / jnp.sqrt(bvar + eps)

    inv = 1.0 / jnp.sqrt(use_var + eps)
    y = (xf - use_mean.reshape(bshape)) * inv.reshape(bshape)
    y = y * scale.reshape(bshape) + bias.reshape(bshape)
    ctx.set_output(op, "Y", y.astype(x.dtype))
    ctx.set_output(op, "MeanOut", new_mean)
    ctx.set_output(op, "VarianceOut", new_var)
    ctx.set_output(op, "SavedMean", saved_mean)
    ctx.set_output(op, "SavedVariance", saved_var)


register_op("sync_batch_norm", infer=_bn_infer, lower=_sync_bn_lower,
            grad=_bn_grad_maker,
            stateful_outputs=("MeanOut", "VarianceOut"))


def _ln_infer(op, block):
    x = in_var(op, block, "X")
    axis = op.attr("begin_norm_axis", 1)
    rows = int(np.prod([s for s in x.shape[:axis]])) \
        if -1 not in x.shape[:axis] else -1
    set_out(op, block, "Y", x.shape, x.dtype)
    if op.output("Mean"):
        set_out(op, block, "Mean", [rows], "float32")
    if op.output("Variance"):
        set_out(op, block, "Variance", [rows], "float32")


@register_op("layer_norm", infer=_ln_infer)
def _layer_norm(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    scale = ctx.get_input(op, "Scale")
    bias = ctx.get_input(op, "Bias")
    eps = op.attr("epsilon", 1e-5)
    axis = op.attr("begin_norm_axis", 1)
    shape = jnp.shape(x)
    red = tuple(range(axis, len(shape)))
    xf = x.astype("float32")
    mean = jnp.mean(xf, axis=red, keepdims=True)
    var = jnp.mean((xf - mean) ** 2, axis=red, keepdims=True)
    y = (xf - mean) / jnp.sqrt(var + eps)
    norm_shape = (1,) * axis + shape[axis:]
    if scale is not None:
        y = y * scale.reshape(norm_shape)
    if bias is not None:
        y = y + bias.reshape(norm_shape)
    ctx.set_output(op, "Y", y.astype(x.dtype))
    ctx.set_output(op, "Mean", mean.reshape(-1))
    ctx.set_output(op, "Variance", var.reshape(-1))


@register_op("rms_norm", infer=lambda op, block: set_out(
    op, block, "Y", in_var(op, block, "X").shape,
    in_var(op, block, "X").dtype))
def _rms_norm(ctx, op):
    """RMSNorm (new capability for the LLM configs; no reference analog)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    scale = ctx.get_input(op, "Scale")
    eps = op.attr("epsilon", 1e-6)
    xf = x.astype("float32")
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf / jnp.sqrt(ms + eps)
    if scale is not None:
        y = y * scale
    ctx.set_output(op, "Y", y.astype(x.dtype))


def _gn_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Y", x.shape, x.dtype)
    g = op.attr("groups", 1)
    set_out(op, block, "Mean", [x.shape[0], g], "float32")
    set_out(op, block, "Variance", [x.shape[0], g], "float32")


@register_op("group_norm", infer=_gn_infer)
def _group_norm(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    scale, bias = ctx.get_input(op, "Scale"), ctx.get_input(op, "Bias")
    g = op.attr("groups", 1)
    eps = op.attr("epsilon", 1e-5)
    layout = op.attr("data_layout", "NCHW")
    if layout != "NCHW":
        x = jnp.moveaxis(x, -1, 1)
    n, c = jnp.shape(x)[:2]
    spatial = jnp.shape(x)[2:]
    xg = x.reshape((n, g, c // g) + spatial).astype("float32")
    red = tuple(range(2, jnp.ndim(xg)))
    mean = jnp.mean(xg, axis=red, keepdims=True)
    var = jnp.mean((xg - mean) ** 2, axis=red, keepdims=True)
    y = ((xg - mean) / jnp.sqrt(var + eps)).reshape(jnp.shape(x))
    cshape = (1, c) + (1,) * len(spatial)
    if scale is not None:
        y = y * scale.reshape(cshape)
    if bias is not None:
        y = y + bias.reshape(cshape)
    if layout != "NCHW":
        y = jnp.moveaxis(y, 1, -1)
    ctx.set_output(op, "Y", y.astype(ctx.get_input(op, "X").dtype))
    ctx.set_output(op, "Mean", mean.reshape(n, g))
    ctx.set_output(op, "Variance", var.reshape(n, g))


@register_op("instance_norm", infer=lambda op, block: (
    set_out(op, block, "Y", in_var(op, block, "X").shape,
            in_var(op, block, "X").dtype),
    set_out(op, block, "SavedMean",
            [in_var(op, block, "X").shape[0] *
             in_var(op, block, "X").shape[1]], "float32"),
    set_out(op, block, "SavedVariance",
            [in_var(op, block, "X").shape[0] *
             in_var(op, block, "X").shape[1]], "float32")))
def _instance_norm(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    scale, bias = ctx.get_input(op, "Scale"), ctx.get_input(op, "Bias")
    eps = op.attr("epsilon", 1e-5)
    red = tuple(range(2, jnp.ndim(x)))
    xf = x.astype("float32")
    mean = jnp.mean(xf, axis=red, keepdims=True)
    var = jnp.mean((xf - mean) ** 2, axis=red, keepdims=True)
    y = (xf - mean) / jnp.sqrt(var + eps)
    c = jnp.shape(x)[1]
    cshape = (1, c) + (1,) * (jnp.ndim(x) - 2)
    if scale is not None:
        y = y * scale.reshape(cshape)
    if bias is not None:
        y = y + bias.reshape(cshape)
    ctx.set_output(op, "Y", y.astype(x.dtype))
    ctx.set_output(op, "SavedMean", mean.reshape(-1))
    ctx.set_output(op, "SavedVariance",
                   (1.0 / jnp.sqrt(var + eps)).reshape(-1))


def _norm_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)
    # Norm keeps a size-1 reduced axis (reference norm_op.cc InferShape:
    # xdim[axis] = 1) — caught by the round-5 infer-vs-runtime gate
    axis = op.attrs.get("axis", 1) % len(x.shape)
    nshape = list(x.shape)
    nshape[axis] = 1
    set_out(op, block, "Norm", nshape, x.dtype)


@register_op("norm", infer=_norm_infer)
def _l2norm(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    axis = op.attr("axis", 1)
    eps = op.attr("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    ctx.set_output(op, "Out", x / norm)
    ctx.set_output(op, "Norm", norm)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def _acc_infer(op, block):
    set_out(op, block, "Accuracy", [], "float32")
    if op.output("Correct"):
        set_out(op, block, "Correct", [], "int32")
    if op.output("Total"):
        set_out(op, block, "Total", [], "int32")


@register_op("accuracy", infer=_acc_infer, grad=None)
def _accuracy(ctx, op):
    jnp = _jnp()
    idx = ctx.get_input(op, "Indices")
    label = ctx.get_input(op, "Label")
    if jnp.ndim(label) == 2:
        label = jnp.squeeze(label, -1)
    correct = jnp.any(idx == label[:, None], axis=1)
    n = jnp.shape(idx)[0]
    num_correct = jnp.sum(correct.astype("int32"))
    ctx.set_output(op, "Accuracy",
                   num_correct.astype("float32") / float(n))
    ctx.set_output(op, "Correct", num_correct)
    ctx.set_output(op, "Total", jnp.asarray(n, dtype="int32"))


# ---------------------------------------------------------------------------
# misc nn
# ---------------------------------------------------------------------------

@register_op("label_smooth", infer=same_as_input())
def _label_smooth(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    eps = op.attr("epsilon", 0.1)
    dist = ctx.get_input(op, "PriorDist")
    k = jnp.shape(x)[-1]
    if dist is not None:
        out = (1 - eps) * x + eps * dist
    else:
        out = (1 - eps) * x + eps / k
    ctx.set_output(op, "Out", out)


@register_op("prelu", infer=same_as_input())
def _prelu(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    alpha = ctx.get_input(op, "Alpha")
    mode = op.attr("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (jnp.ndim(x) - 2))
    ctx.set_output(op, "Out", jnp.where(x >= 0, x, alpha * x))


@register_op("softshrink", infer=same_as_input())
def _softshrink(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    lam = op.attr("lambda", 0.5)
    ctx.set_output(op, "Out",
                   jnp.where(x > lam, x - lam,
                             jnp.where(x < -lam, x + lam, 0.0)))


@register_op("maxout", infer=lambda op, block: set_out(
    op, block, "Out",
    [in_var(op, block, "X").shape[0],
     in_var(op, block, "X").shape[1] // op.attr("groups", 1)] +
    list(in_var(op, block, "X").shape[2:]),
    in_var(op, block, "X").dtype))
def _maxout(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    g = op.attr("groups", 1)
    n, c = jnp.shape(x)[:2]
    rest = jnp.shape(x)[2:]
    ctx.set_output(op, "Out",
                   jnp.max(x.reshape((n, c // g, g) + rest), axis=2))
