"""Linalg + misc tensor ops.

Reference: operators/{cholesky,inverse,kron,trace,diag,diag_embed,
diag_v2,cross,dist,affine_channel,affine_grid,grid_sampler,histogram,
index_sample,multinomial,unfold}_op.* — each a hand-written CPU/CUDA
kernel (cuSOLVER for the factorizations); here jnp/lax lowerings on the
MXU/XLA with 'auto' vjp grads where the reference registers a grad op.
"""
from __future__ import annotations

import numpy as np

from ..errors import InvalidArgumentError
from .registry import in_var, register_op, same_as_input, set_out


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# factorizations / inverses
# ---------------------------------------------------------------------------

@register_op("cholesky", infer=same_as_input(), grad="auto")
def _cholesky(ctx, op):
    """reference cholesky_op.h (cuSOLVER potrf); upper=True returns the
    upper-triangular factor (transpose of the lower one)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    low = jnp.linalg.cholesky(x)
    if op.attr("upper", False):
        low = jnp.swapaxes(low, -1, -2)
    ctx.set_output(op, "Out", low)


def _inverse_infer(op, block):
    x = in_var(op, block, "Input")
    set_out(op, block, "Output", x.shape, x.dtype)


@register_op("inverse", infer=_inverse_infer, grad="auto")
def _inverse(ctx, op):
    ctx.set_output(op, "Output",
                   _jnp().linalg.inv(ctx.get_input(op, "Input")))


# ---------------------------------------------------------------------------
# products / reductions
# ---------------------------------------------------------------------------

def _kron_infer(op, block):
    x, y = in_var(op, block, "X"), in_var(op, block, "Y")
    xs, ys = list(x.shape), list(y.shape)
    while len(xs) < len(ys):
        xs.insert(0, 1)
    while len(ys) < len(xs):
        ys.insert(0, 1)
    set_out(op, block, "Out", [a * b for a, b in zip(xs, ys)], x.dtype)


@register_op("kron", infer=_kron_infer, grad="auto")
def _kron(ctx, op):
    """reference kron_op.h: out[i] = prod of dims (np.kron semantics
    with rank padding)."""
    jnp = _jnp()
    ctx.set_output(op, "Out", jnp.kron(ctx.get_input(op, "X"),
                                       ctx.get_input(op, "Y")))


def _trace_infer(op, block):
    x = in_var(op, block, "Input")
    ax1 = op.attr("axis1", 0) % len(x.shape)
    ax2 = op.attr("axis2", 1) % len(x.shape)
    shape = [s for i, s in enumerate(x.shape) if i not in (ax1, ax2)]
    set_out(op, block, "Out", shape or [1], x.dtype)


@register_op("trace", infer=_trace_infer, grad="auto")
def _trace(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    out = jnp.trace(x, offset=op.attr("offset", 0),
                    axis1=op.attr("axis1", 0), axis2=op.attr("axis2", 1))
    if out.ndim == 0:
        out = out.reshape(1)
    ctx.set_output(op, "Out", out)


def _cross_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)


@register_op("cross", infer=_cross_infer, grad="auto")
def _cross(ctx, op):
    """reference cross_op.h: axis defaults to the first dim of size 3."""
    jnp = _jnp()
    x, y = ctx.get_input(op, "X"), ctx.get_input(op, "Y")
    dim = op.attr("dim", None)
    if dim is None or dim == -100:  # DefaultDim sentinel
        dim = next((i for i, s in enumerate(x.shape) if s == 3), None)
        if dim is None:
            raise InvalidArgumentError("cross: no dimension of size 3")
    ctx.set_output(op, "Out", jnp.cross(x, y, axis=int(dim)))


def _dist_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", [1], x.dtype)


@register_op("dist", infer=_dist_infer, grad="auto")
def _dist(ctx, op):
    """reference dist_op.h: p-norm of the broadcast difference."""
    jnp = _jnp()
    d = jnp.abs(ctx.get_input(op, "X") - ctx.get_input(op, "Y"))
    p = op.attr("p", 2.0)
    if p == float("inf"):
        out = d.max()
    elif p == float("-inf"):
        out = d.min()
    elif p == 0:
        out = (d != 0).sum().astype(d.dtype)
    else:
        out = (d ** p).sum() ** (1.0 / p)
    ctx.set_output(op, "Out", out.reshape(1))


# ---------------------------------------------------------------------------
# diag family
# ---------------------------------------------------------------------------

def _diag_infer(op, block):
    x = in_var(op, block, "Diagonal")
    n = x.shape[0]
    set_out(op, block, "Out", (n, n), x.dtype)


@register_op("diag", infer=_diag_infer, grad="auto")
def _diag(ctx, op):
    """reference diag_op.cc (v1): 1-D diagonal -> square matrix."""
    ctx.set_output(op, "Out", _jnp().diag(ctx.get_input(op, "Diagonal")))


def _diag_v2_infer(op, block):
    x = in_var(op, block, "X")
    off = abs(op.attr("offset", 0))
    if len(x.shape) == 1:
        n = x.shape[0] + off
        set_out(op, block, "Out", (n, n), x.dtype)
    else:
        n = max(0, min(x.shape[0], x.shape[1] - op.attr("offset", 0),
                       x.shape[1], x.shape[0] + op.attr("offset", 0)))
        set_out(op, block, "Out", (n,), x.dtype)


@register_op("diag_v2", infer=_diag_v2_infer, grad="auto")
def _diag_v2(ctx, op):
    """reference diag_v2_op.cc: np.diag with offset + padding_value."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    offset = op.attr("offset", 0)
    pad = op.attr("padding_value", 0.0)
    out = jnp.diag(x, k=offset)
    if x.ndim == 1 and pad:
        n = out.shape[0]
        mask = jnp.eye(n, k=offset, dtype=bool)
        out = jnp.where(mask, out, jnp.asarray(pad, out.dtype))
    ctx.set_output(op, "Out", out)


def _diag_embed_infer(op, block):
    x = in_var(op, block, "Input")
    n = x.shape[-1] + abs(op.attr("offset", 0))
    shape = list(x.shape[:-1]) + [n, n]
    nd = len(shape)
    d1 = op.attr("dim1", -2) % nd
    d2 = op.attr("dim2", -1) % nd
    if (d1, d2) != (nd - 2, nd - 1):
        # mirror the lowering's moveaxis of the two diagonal plane axes
        rest = [s for i, s in enumerate(shape) if i < nd - 2]
        out = [None] * nd
        out[d1], out[d2] = n, n
        it = iter(rest)
        for i in range(nd):
            if out[i] is None:
                out[i] = next(it)
        shape = out
    set_out(op, block, "Out", shape, x.dtype)


@register_op("diag_embed", infer=_diag_embed_infer, grad="auto")
def _diag_embed(ctx, op):
    """reference diag_embed_op.h: batched last-dim -> diagonal planes
    (dim1/dim2 default -2/-1)."""
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    offset = op.attr("offset", 0)
    dim1 = op.attr("dim1", -2)
    dim2 = op.attr("dim2", -1)
    n = x.shape[-1] + abs(offset)
    planes = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + max(0, -offset)
    c = idx + max(0, offset)
    out = planes.at[..., r, c].set(x)
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
    ctx.set_output(op, "Out", out)


# ---------------------------------------------------------------------------
# sampling / selection
# ---------------------------------------------------------------------------

def _index_sample_infer(op, block):
    x = in_var(op, block, "X")
    idx = in_var(op, block, "Index")
    set_out(op, block, "Out", idx.shape, x.dtype)


@register_op("index_sample", infer=_index_sample_infer, grad="auto")
def _index_sample(ctx, op):
    """reference index_sample_op.h: per-row gather x[i, index[i, j]]."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    idx = ctx.get_input(op, "Index")
    ctx.set_output(op, "Out",
                   jnp.take_along_axis(x, idx.astype(jnp.int32), axis=1))


def _multinomial_infer(op, block):
    x = in_var(op, block, "X")
    n = op.attr("num_samples", 1)
    shape = (x.shape[0], n) if len(x.shape) == 2 else (n,)
    set_out(op, block, "Out", shape, "int64")


@register_op("multinomial", infer=_multinomial_infer, grad=None)
def _multinomial(ctx, op):
    """reference multinomial_op.h: sample category ids from unnormalized
    probabilities; without replacement via Gumbel top-k."""
    import jax

    jnp = _jnp()
    x = ctx.get_input(op, "X")
    n = op.attr("num_samples", 1)
    repl = op.attr("replacement", False)
    squeeze = x.ndim == 1
    probs = x[None] if squeeze else x
    logp = jnp.log(jnp.clip(probs, 1e-30, None))
    key = ctx.rng(op)
    if repl:
        out = jax.random.categorical(key, logp, axis=-1,
                                     shape=(n, probs.shape[0])).T
    else:
        if n > probs.shape[-1]:
            raise InvalidArgumentError(
                "multinomial without replacement: num_samples exceeds "
                "category count")
        # zero-probability categories must never be drawn (reference
        # multinomial_op errors when nonzero categories < num_samples;
        # the count is data-dependent here, so the invalid case is
        # marked in the output instead of raised)
        g = jax.random.gumbel(key, logp.shape)
        masked = jnp.where(probs > 0, logp + g, -jnp.inf)
        score, out = jax.lax.top_k(masked, n)
        # a -inf selection means fewer than n nonzero categories: make
        # the result recognizably invalid (-1) rather than silently
        # sampling a zero-probability id
        out = jnp.where(jnp.isneginf(score), -1, out)
    ctx.set_output(op, "Out", out[0] if squeeze else out)


def _histogram_infer(op, block):
    lo, hi = op.attr("min", 0), op.attr("max", 0)
    if lo > hi:
        raise InvalidArgumentError(
            f"histogram: min ({lo}) must be <= max ({hi})")
    set_out(op, block, "Out", (op.attr("bins", 100),), "int64")


@register_op("histogram", infer=_histogram_infer, grad=None)
def _histogram(ctx, op):
    """reference histogram_op.h: fixed-bin counts; min==max==0 takes
    the data range."""
    jnp = _jnp()
    x = ctx.get_input(op, "X").reshape(-1)
    bins = op.attr("bins", 100)
    lo = op.attr("min", 0)
    hi = op.attr("max", 0)
    if lo == hi and lo != 0:
        # reference histogram_op widens an equal explicit range
        lo, hi = lo - 1, hi + 1
    if lo == 0 and hi == 0:
        lo_v, hi_v = x.min(), x.max()
        same = lo_v == hi_v
        lo_v = jnp.where(same, lo_v - 1, lo_v)
        hi_v = jnp.where(same, hi_v + 1, hi_v)
    else:
        lo_v = jnp.asarray(lo, x.dtype)
        hi_v = jnp.asarray(hi, x.dtype)
    xf = x.astype(jnp.float32)
    width = (hi_v - lo_v).astype(jnp.float32)
    b = jnp.floor((xf - lo_v) * bins / width).astype(jnp.int32)
    b = jnp.where(xf == hi_v, bins - 1, b)  # right edge inclusive
    valid = (xf >= lo_v) & (xf <= hi_v)
    # int32 accumulators; x64 is disabled jax-wide in this runtime and
    # the declared int64 output narrows like every other integer op
    counts = jnp.zeros((bins,), jnp.int32).at[
        jnp.where(valid, b, bins)].add(1, mode="drop")
    ctx.set_output(op, "Out", counts)


# ---------------------------------------------------------------------------
# geometry: affine_channel / affine_grid / grid_sampler / unfold
# ---------------------------------------------------------------------------

@register_op("affine_channel", infer=same_as_input(), grad="auto")
def _affine_channel(ctx, op):
    """reference affine_channel_op.cc: per-channel scale+bias."""
    x = ctx.get_input(op, "X")
    scale = ctx.get_input(op, "Scale")
    bias = ctx.get_input(op, "Bias")
    if op.attr("data_layout", "NCHW") == "NCHW":
        shape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        shape = (1,) * (x.ndim - 1) + (-1,)
    ctx.set_output(op, "Out",
                   x * scale.reshape(shape) + bias.reshape(shape))


def _affine_grid_infer(op, block):
    theta = in_var(op, block, "Theta")
    h, w = op.attr("output_shape", [0, 0, 0, 0])[2:4]
    set_out(op, block, "Output", (theta.shape[0], h, w, 2), theta.dtype)


@register_op("affine_grid", infer=_affine_grid_infer, grad="auto")
def _affine_grid(ctx, op):
    """reference affine_grid_op.h: grid = [x_norm, y_norm, 1] @ theta^T
    over normalized [-1, 1] coords (align_corners=True semantics of the
    vintage)."""
    jnp = _jnp()
    theta = ctx.get_input(op, "Theta")              # [N, 2, 3]
    _, _, h, w = op.attr("output_shape")
    align = op.attr("align_corners", True)
    if align:
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
    else:
        ys = (jnp.arange(h) * 2 + 1) / h - 1.0
        xs = (jnp.arange(w) * 2 + 1) / w - 1.0
    xg, yg = jnp.meshgrid(xs, ys)                   # [h, w]
    base = jnp.stack([xg, yg, jnp.ones_like(xg)], axis=-1)  # [h,w,3]
    out = jnp.einsum("hwk,njk->nhwj", base.astype(theta.dtype), theta)
    ctx.set_output(op, "Output", out)


def _grid_sampler_infer(op, block):
    x = in_var(op, block, "X")
    grid = in_var(op, block, "Grid")
    set_out(op, block, "Output",
            (x.shape[0], x.shape[1], grid.shape[1], grid.shape[2]),
            x.dtype)


@register_op("grid_sampler", infer=_grid_sampler_infer, grad="auto")
def _grid_sampler(ctx, op):
    """reference grid_sampler_op.h: sample x at normalized grid coords;
    bilinear/nearest x zeros/border/reflection."""
    import jax

    jnp = _jnp()
    x = ctx.get_input(op, "X")                      # [N, C, H, W]
    grid = ctx.get_input(op, "Grid")                # [N, Hg, Wg, 2]
    mode = op.attr("mode", "bilinear")
    padding = op.attr("padding_mode", "zeros")
    align = op.attr("align_corners", True)
    N, C, H, W = x.shape

    def unnorm(c, size):
        if align:
            return (c + 1.0) / 2.0 * (size - 1)
        return ((c + 1.0) * size - 1.0) / 2.0

    gx = unnorm(grid[..., 0], W)                    # [N, Hg, Wg]
    gy = unnorm(grid[..., 1], H)

    def reflect(v, lo, hi):
        rng = hi - lo
        v = jnp.abs((v - lo) % (2 * rng) - rng) + lo
        return v

    if padding == "reflection":
        if align:
            gx = reflect(gx, 0.0, W - 1.0)
            gy = reflect(gy, 0.0, H - 1.0)
        else:
            gx = jnp.clip(reflect(gx, -0.5, W - 0.5), 0, W - 1)
            gy = jnp.clip(reflect(gy, -0.5, H - 0.5), 0, H - 1)
    elif padding == "border":
        gx = jnp.clip(gx, 0.0, W - 1.0)
        gy = jnp.clip(gy, 0.0, H - 1.0)

    def gather(img, yi, xi):
        """img [C,H,W]; yi/xi int [Hg,Wg] -> [C,Hg,Wg]; OOB -> 0."""
        inb = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        yc = jnp.clip(yi, 0, H - 1)
        xc = jnp.clip(xi, 0, W - 1)
        v = img[:, yc, xc]
        return v * inb[None]

    def sample_one(img, gx1, gy1):
        if mode == "nearest":
            return gather(img, jnp.round(gy1).astype(jnp.int32),
                          jnp.round(gx1).astype(jnp.int32))
        x0 = jnp.floor(gx1).astype(jnp.int32)
        y0 = jnp.floor(gy1).astype(jnp.int32)
        lx = (gx1 - x0).astype(x.dtype)
        ly = (gy1 - y0).astype(x.dtype)
        return (gather(img, y0, x0) * (1 - ly) * (1 - lx)
                + gather(img, y0, x0 + 1) * (1 - ly) * lx
                + gather(img, y0 + 1, x0) * ly * (1 - lx)
                + gather(img, y0 + 1, x0 + 1) * ly * lx)

    out = jax.vmap(sample_one)(x, gx, gy)
    ctx.set_output(op, "Output", out)


def _unfold_infer(op, block):
    x = in_var(op, block, "X")
    k = op.attr("kernel_sizes")
    s = op.attr("strides", [1, 1])
    p = op.attr("paddings", [0, 0, 0, 0])
    d = op.attr("dilations", [1, 1])
    N, C, H, W = x.shape
    oh = (H + p[0] + p[2] - d[0] * (k[0] - 1) - 1) // s[0] + 1
    ow = (W + p[1] + p[3] - d[1] * (k[1] - 1) - 1) // s[1] + 1
    set_out(op, block, "Y", (N, C * k[0] * k[1], oh * ow), x.dtype)


@register_op("unfold", infer=_unfold_infer, grad="auto")
def _unfold(ctx, op):
    """reference unfold_op.h (im2col): patches flattened to
    [N, C*kh*kw, L] via lax patch extraction."""
    import jax

    jnp = _jnp()
    x = ctx.get_input(op, "X")
    k = op.attr("kernel_sizes")
    s = op.attr("strides", [1, 1])
    p = op.attr("paddings", [0, 0, 0, 0])
    d = op.attr("dilations", [1, 1])
    N, C = x.shape[0], x.shape[1]
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=tuple(k), window_strides=tuple(s),
        padding=((p[0], p[2]), (p[1], p[3])),
        rhs_dilation=tuple(d))                      # [N, C*kh*kw, oh, ow]
    ctx.set_output(op, "Y",
                   patches.reshape(N, C * k[0] * k[1], -1))
