"""Collective communication ops.

Reference: paddle/fluid/operators/collective/ — the NCCL op set
(c_allreduce_op.h:109-158 CAllReduceOpCUDAKernel -> ncclAllReduce,
c_allgather_op.cu.cc, c_reducescatter_op.cu.cc, c_broadcast_op.cu.cc,
send_v2/recv_v2, comm bootstrap c_gen_nccl_id/c_comm_init, stream syncs).

TPU-native redesign: a ring_id is a *named mesh axis*; kernels are XLA
collectives (lax.psum/all_gather/psum_scatter/ppermute) that compile to ICI
transfers. Ops only have collective meaning when lowered inside shard_map
with mesh axes bound (parallel/spmd.py); lowered outside any mesh they take
their single-participant meaning (allreduce = identity, allgather = expand
with group size 1), which is also the reference behavior with one rank.
Bootstrap/stream ops (c_gen_nccl_id, c_comm_init, c_sync_*_stream,
barrier) are structural no-ops: XLA owns scheduling and jax.distributed
owns rendezvous.
"""
from __future__ import annotations

import numpy as np

from .registry import (LowerContext, in_var, register_op, same_as_input,
                       set_out)


def _jnp():
    import jax.numpy as jnp
    return jnp


def _axis_name(ctx: LowerContext, op):
    """Resolve the mesh axis for this op's ring_id.

    Priority: explicit 'axis_name' attr; then the ring table installed by
    the SPMD lowering context (ring_id -> axis); None when no axes bound
    (single participant).
    """
    name = op.attr("axis_name", None)
    axes = getattr(ctx, "axis_names", None) or ()
    if name:
        return name if name in axes else None
    ring = op.attr("ring_id", 0)
    table = getattr(ctx, "ring_table", None) or {}
    if ring in table and table[ring] in axes:
        return table[ring]
    return axes[0] if axes else None


def _group_size(ctx, op):
    import jax
    name = _axis_name(ctx, op)
    if name is None:
        return 1
    mesh = ctx.mesh
    return dict(zip(mesh.axis_names, mesh.devices.shape))[name]


# -- allreduce family -------------------------------------------------------

def _make_allreduce(suffix, reducer):
    op_type = f"c_allreduce_{suffix}"

    @register_op(op_type, infer=same_as_input("X", "Out"), grad="auto")
    def _lower(ctx, op, _reducer=reducer):
        import jax.lax as lax
        x = ctx.get_input(op, "X")
        axis = _axis_name(ctx, op)
        if axis is None:
            ctx.set_output(op, "Out", x)
            return
        ctx.set_output(op, "Out", _reducer(x, axis))
    return _lower


def _psum(x, a):
    import jax.lax as lax
    return lax.psum(x, a)


def _pmax(x, a):
    import jax.lax as lax
    return lax.pmax(x, a)


def _pmin(x, a):
    import jax.lax as lax
    return lax.pmin(x, a)


def _pprod(x, a):
    import jax.lax as lax
    import jax.numpy as jnp
    return jnp.prod(lax.all_gather(x, a), axis=0)


_make_allreduce("sum", _psum)
_make_allreduce("max", _pmax)
_make_allreduce("min", _pmin)
_make_allreduce("prod", _pprod)

# c_reduce_*: result only meaningful on root; SPMD model keeps it on all
# participants (superset of reference semantics)
for _s, _r in (("sum", _psum), ("max", _pmax), ("min", _pmin)):
    register_op(f"c_reduce_{_s}", infer=same_as_input("X", "Out"),
                lower=(lambda ctx, op, _r=_r: ctx.set_output(
                    op, "Out",
                    ctx.get_input(op, "X") if _axis_name(ctx, op) is None
                    else _r(ctx.get_input(op, "X"), _axis_name(ctx, op)))),
                grad="auto")


@register_op("c_broadcast", infer=same_as_input("X", "Out"), grad="auto")
def _c_broadcast(ctx, op):
    """Root's value to all: implemented as select(root)+psum so it stays a
    single ICI collective."""
    import jax.lax as lax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    axis = _axis_name(ctx, op)
    if axis is None:
        ctx.set_output(op, "Out", x)
        return
    root = op.attr("root", 0)
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    ctx.set_output(op, "Out", lax.psum(masked, axis))


def _allgather_infer(op, block):
    x = in_var(op, block, "X")
    n = op.attr("nranks", 1)
    shape = list(x.shape)
    shape[0] = shape[0] * n if shape[0] != -1 else -1
    set_out(op, block, "Out", shape, x.dtype)


@register_op("c_allgather", infer=_allgather_infer, grad="auto")
def _c_allgather(ctx, op):
    import jax.lax as lax
    x = ctx.get_input(op, "X")
    axis = _axis_name(ctx, op)
    if axis is None:
        ctx.set_output(op, "Out", x)
        return
    out = lax.all_gather(x, axis, tiled=True)
    ctx.set_output(op, "Out", out)


def _reducescatter_infer(op, block):
    x = in_var(op, block, "X")
    n = op.attr("nranks", 1)
    shape = list(x.shape)
    if shape[0] != -1:
        assert shape[0] % n == 0, \
            f"c_reducescatter: dim0 {shape[0]} not divisible by {n}"
        shape[0] //= n
    set_out(op, block, "Out", shape, x.dtype)


@register_op("c_reducescatter", infer=_reducescatter_infer, grad="auto")
def _c_reducescatter(ctx, op):
    import jax.lax as lax
    x = ctx.get_input(op, "X")
    axis = _axis_name(ctx, op)
    if axis is None:
        ctx.set_output(op, "Out", x)
        return
    ctx.set_output(op, "Out", lax.psum_scatter(x, axis, tiled=True))


def _c_concat_infer(op, block):
    x = in_var(op, block, "X")
    shape = list(x.shape)
    if shape and shape[-1] != -1:
        shape[-1] *= op.attr("nranks", 1)
    set_out(op, block, "Out", shape, x.dtype)


@register_op("c_concat", infer=_c_concat_infer, grad="auto")
def _c_concat(ctx, op):
    """Gather along the last dim (model-parallel activation gather)."""
    import jax.lax as lax
    x = ctx.get_input(op, "X")
    axis = _axis_name(ctx, op)
    if axis is None:
        ctx.set_output(op, "Out", x)
        return
    ndim = x.ndim
    ctx.set_output(op, "Out",
                   lax.all_gather(x, axis, axis=ndim - 1, tiled=True))


def _c_split_infer(op, block):
    x = in_var(op, block, "X")
    n = op.attr("nranks", 1)
    shape = list(x.shape)
    if shape[-1] != -1:
        shape[-1] //= n
    set_out(op, block, "Out", shape, x.dtype)


@register_op("c_split", infer=_c_split_infer, grad="auto")
def _c_split(ctx, op):
    """Keep this rank's last-dim slice (model-parallel activation split)."""
    import jax.lax as lax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    axis = _axis_name(ctx, op)
    if axis is None:
        ctx.set_output(op, "Out", x)
        return
    n = _group_size(ctx, op)
    idx = lax.axis_index(axis)
    piece = x.shape[-1] // n
    out = lax.dynamic_slice_in_dim(x, idx * piece, piece, axis=x.ndim - 1)
    ctx.set_output(op, "Out", out)


@register_op("c_identity", infer=same_as_input("X", "Out"), grad="auto")
def _c_identity(ctx, op):
    ctx.set_output(op, "Out", ctx.get_input(op, "X"))


@register_op("send_v2", infer=lambda op, block: None, grad=None)
def _send_v2(ctx, op):
    """Point-to-point send: paired with recv_v2 as a ppermute in the SPMD
    program (pipeline stage boundary). The SPMD lowering fuses matched
    send/recv pairs; a lone send lowers to nothing.

    Stash is keyed by ring_id only (reference pairs send_v2/recv_v2 per
    ring, send_v2_op.cc / recv_v2_op.cc); the send's `peer` attr (the
    destination rank of the logical edge) rides along so recv can derive
    the actual src->dst shift for the ppermute.
    """
    x = ctx.get_input(op, "X")
    ctx.env[f"__p2p_{op.attr('ring_id', 0)}"] = (x, op.attr("peer", 0))


def _recv_v2_infer(op, block):
    shape = op.attr("out_shape", [1])
    set_out(op, block, "Out", shape, op.attr("dtype", "float32"))


@register_op("recv_v2", infer=_recv_v2_infer, grad=None)
def _recv_v2(ctx, op):
    import jax.lax as lax
    axis = _axis_name(ctx, op)
    key = f"__p2p_{op.attr('ring_id', 0)}"
    if key not in ctx.env:
        raise RuntimeError(
            f"recv_v2(ring_id={op.attr('ring_id', 0)}): no paired send_v2 "
            "lowered before this recv in the program; a lone recv would "
            "silently compute on zeros")
    x, send_peer = ctx.env.pop(key)  # consume: one send pairs one recv
    if axis is not None:
        n = _group_size(ctx, op)
        # One logical edge encodes (dst=send.peer, src=recv.peer); the
        # SPMD shift is their difference, e.g. stage s -> s+1 gives 1.
        shift = (send_peer - op.attr("peer", 0)) % n
        if shift:
            x = lax.ppermute(x, axis,
                             [(i, (i + shift) % n) for i in range(n)])
    ctx.set_output(op, "Out", x)


# -- bootstrap / sync ops: structural no-ops under XLA ----------------------

def _noop_infer(op, block):
    for slot in list(op.outputs):
        for name in op.output(slot):
            v = block._find_var_recursive(name)
            if v is not None and v.shape is None:
                v.shape, v.dtype = (1,), "int32"


def _register_noop(op_type):
    @register_op(op_type, infer=_noop_infer, grad=None)
    def _lower(ctx, op):
        jnp = _jnp()
        for slot in list(op.outputs):
            for name in op.output(slot):
                if name and name not in ctx.env:
                    ctx.env[name] = jnp.zeros((1,), "int32")


for _t in ("c_gen_nccl_id", "c_comm_init", "c_comm_init_all",
           "c_sync_calc_stream", "c_sync_comm_stream", "barrier",
           "c_wait_comm", "c_wait_compute"):
    _register_noop(_t)


# ---------------------------------------------------------------------------
# legacy dense collective surfaces (reference operators/collective/
# allreduce_op.cc, broadcast_op.cc, c_scatter_op.cc, c_allreduce_prod)
# ---------------------------------------------------------------------------
register_op("allreduce", infer=same_as_input("X", "Out"),
            lower=(lambda ctx, op: ctx.set_output(
                op, "Out",
                ctx.get_input(op, "X") if _axis_name(ctx, op) is None
                else _psum(ctx.get_input(op, "X"), _axis_name(ctx, op)))),
            grad="auto")

register_op("c_reduce_prod", infer=same_as_input("X", "Out"),
            lower=(lambda ctx, op: ctx.set_output(
                op, "Out",
                ctx.get_input(op, "X") if _axis_name(ctx, op) is None
                else _pprod(ctx.get_input(op, "X"),
                            _axis_name(ctx, op)))),
            grad="auto")


@register_op("broadcast", infer=same_as_input("X", "Out"), grad="auto")
def _broadcast_legacy(ctx, op):
    """Dense broadcast from root (reference collective/broadcast_op.cc)
    — same select(root)+psum single-collective trick as c_broadcast."""
    import jax.lax as lax
    import jax.numpy as jnp
    x = ctx.get_input(op, "X")
    axis = _axis_name(ctx, op)
    if axis is None:
        ctx.set_output(op, "Out", x)
        return
    root = int(op.attr("root", op.attr("root_id", 0)))
    me = lax.axis_index(axis)
    ctx.set_output(op, "Out",
                   lax.psum(jnp.where(me == root, x, 0), axis))


def _c_scatter_infer(op, block):
    x = in_var(op, block, "X")
    n = int(op.attrs.get("nranks", 1))
    shape = list(x.shape)
    shape[0] //= max(n, 1)
    set_out(op, block, "Out", shape, x.dtype)


@register_op("c_scatter", infer=_c_scatter_infer, grad="auto")
def _c_scatter(ctx, op):
    """Root's [nranks*chunk, ...] scattered along dim 0: each rank takes
    its chunk of the broadcast value (reference c_scatter_op.cc)."""
    import jax.lax as lax
    import jax.numpy as jnp
    x = ctx.get_input(op, "X")
    axis = _axis_name(ctx, op)
    if axis is None:
        ctx.set_output(op, "Out", x)
        return
    root = int(op.attr("root", 0))
    n = int(op.attr("nranks", 1))
    me = lax.axis_index(axis)
    x_root = lax.psum(jnp.where(me == root, x, 0), axis)
    chunk = x.shape[0] // max(n, 1)
    ctx.set_output(op, "Out",
                   lax.dynamic_slice_in_dim(x_root, me * chunk, chunk,
                                            axis=0))
