"""Rotary position embedding op (new capability for the LLM configs;
no reference analog — the reference vintage predates RoPE adoption)."""
from __future__ import annotations

import numpy as np

from .registry import in_var, register_op, set_out


def _rope_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)


@register_op("rope", infer=_rope_infer, grad="auto")
def _rope(ctx, op):
    """X: [B, H, S, D] (D even). Rotates pairs (x[..., :D/2], x[..., D/2:])
    by position-dependent angles — the 'rotate_half' convention.

    Optional input ``Offset`` [B] (int): per-row dynamic position
    offset for cached decode — row b's positions are
    ``offset[b] .. offset[b]+S-1``.  The angle math is identical to the
    static path (``pos * inv_freq``), so a token rotated at decode step
    p is bit-equal to the same token rotated at position p of a full
    forward."""
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    base = op.attr("base", 10000.0)
    pos_offset = op.attr("position_offset", 0)
    B, H, S, D = x.shape
    half = D // 2

    inv_freq = 1.0 / (base ** (np.arange(0, half) / half))
    offset = ctx.get_input(op, "Offset") if op.single_input("Offset") \
        else None
    if offset is None:
        pos = jnp.arange(pos_offset, pos_offset + S, dtype=jnp.float32)
        freqs = jnp.outer(pos, inv_freq)          # [S, half]
        cos = jnp.cos(freqs)[None, None]          # [1,1,S,half]
        sin = jnp.sin(freqs)[None, None]
    else:
        pos = offset.astype(jnp.float32)[:, None] \
            + jnp.arange(S, dtype=jnp.float32)[None, :]      # [B, S]
        freqs = pos[..., None] * jnp.asarray(inv_freq,
                                             jnp.float32)    # [B,S,half]
        cos = jnp.cos(freqs)[:, None]             # [B,1,S,half]
        sin = jnp.sin(freqs)[:, None]

    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x2 * cos + x1 * sin], axis=-1)
    ctx.set_output(op, "Out", out.astype(x.dtype))
