"""Tensor creation / manipulation op lowerings.

Replaces reference fill/random/reshape/transpose/concat/split/slice/
gather/scatter/embedding kernels (operators/fill_constant_op.cc,
gaussian_random_op.*, uniform_random_op.*, reshape_op.cc, transpose_op.*,
concat_op.*, split_op.*, slice_op.*, gather_op.*, lookup_table_v2_op.*,
one_hot_v2_op.*, expand_v2_op.*, …).  Randomness is stateless
counter-based jax.random keyed per-op — the TPU-native replacement for
cuRAND generators.
"""
from __future__ import annotations

import numpy as np

from ..framework.core import Block, Operator, convert_dtype, dtype_to_np
from .registry import (LowerContext, in_var, register_op, same_as_input,
                       set_out)


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# creation ops
# ---------------------------------------------------------------------------

def _fill_infer(op: Operator, block: Block):
    set_out(op, block, "Out", op.attr("shape", []),
            op.attr("dtype", "float32"))


@register_op("fill_constant", infer=_fill_infer, grad=None)
def _fill_constant(ctx: LowerContext, op: Operator):
    jnp = _jnp()
    dtype = dtype_to_np(op.attr("dtype", "float32"))
    value = op.attr("value", 0.0)
    if op.attr("str_value", ""):
        value = float(op.attr("str_value"))
    shape = tuple(op.attr("shape", []))
    if op.single_input("ValueTensor"):
        value = ctx.get_input(op, "ValueTensor")
    ctx.set_output(op, "Out", jnp.full(shape, value, dtype=dtype))


def _fill_like_infer(op, block):
    x = in_var(op, block, "X")
    dt = op.attr("dtype", -1)
    dtype = x.dtype if dt in (-1, None, "") else dt
    set_out(op, block, "Out", x.shape, dtype)


@register_op("fill_any_like", infer=_fill_like_infer, grad=None)
def _fill_any_like(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    dt = op.attr("dtype", -1)
    dtype = x.dtype if dt in (-1, None, "") else dtype_to_np(dt)
    ctx.set_output(op, "Out", jnp.full(jnp.shape(x), op.attr("value", 0.0),
                                       dtype=dtype))


@register_op("fill_zeros_like", infer=same_as_input(), grad=None)
def _fill_zeros_like(ctx, op):
    ctx.set_output(op, "Out", _jnp().zeros_like(ctx.get_input(op, "X")))


@register_op("assign_value", infer=_fill_infer, grad=None)
def _assign_value(ctx, op):
    jnp = _jnp()
    dtype = dtype_to_np(op.attr("dtype", "float32"))
    shape = tuple(op.attr("shape", []))
    for key in ("fp32_values", "int32_values", "int64_values", "bool_values",
                "values"):
        vals = op.attr(key)
        if vals is not None and (not isinstance(vals, list) or vals):
            if isinstance(vals, dict) and "__ndarray__" in vals:
                vals = np.asarray(vals["__ndarray__"], dtype=vals["dtype"])
            arr = jnp.asarray(np.asarray(vals).reshape(shape), dtype=dtype)
            ctx.set_output(op, "Out", arr)
            return
    ctx.set_output(op, "Out", jnp.zeros(shape, dtype=dtype))


def _range_infer(op, block):
    # shape only known statically when start/end/step are attrs
    try:
        n = int(np.ceil((op.attr("end") - op.attr("start")) / op.attr("step")))
    except TypeError:
        n = -1
    set_out(op, block, "Out", [n], op.attr("dtype", "float32"))


@register_op("range", infer=_range_infer, grad=None)
def _range(ctx, op):
    jnp = _jnp()
    dtype = dtype_to_np(op.attr("dtype", "float32"))
    ctx.set_output(op, "Out", jnp.arange(op.attr("start"), op.attr("end"),
                                         op.attr("step"), dtype=dtype))


@register_op("linspace", infer=lambda op, block: set_out(
    op, block, "Out", [op.attr("num", 0)], op.attr("dtype", "float32")),
    grad=None)
def _linspace(ctx, op):
    jnp = _jnp()
    ctx.set_output(op, "Out", jnp.linspace(
        op.attr("start"), op.attr("stop"), op.attr("num"),
        dtype=dtype_to_np(op.attr("dtype", "float32"))))


@register_op("eye", infer=lambda op, block: set_out(
    op, block, "Out",
    [op.attr("num_rows"), op.attr("num_columns", op.attr("num_rows"))],
    op.attr("dtype", "float32")), grad=None)
def _eye(ctx, op):
    jnp = _jnp()
    ctx.set_output(op, "Out", jnp.eye(
        op.attr("num_rows"), op.attr("num_columns", op.attr("num_rows")),
        dtype=dtype_to_np(op.attr("dtype", "float32"))))


# ---------------------------------------------------------------------------
# random ops (stateless, per-op folded keys)
# ---------------------------------------------------------------------------

@register_op("gaussian_random", infer=_fill_infer, grad=None)
def _gaussian_random(ctx: LowerContext, op: Operator):
    import jax
    dtype = dtype_to_np(op.attr("dtype", "float32"))
    shape = tuple(op.attr("shape", []))
    mean, std = op.attr("mean", 0.0), op.attr("std", 1.0)
    out = jax.random.normal(ctx.rng(op), shape, dtype="float32") * std + mean
    ctx.set_output(op, "Out", out.astype(dtype))


@register_op("uniform_random", infer=_fill_infer, grad=None)
def _uniform_random(ctx, op):
    import jax
    dtype = dtype_to_np(op.attr("dtype", "float32"))
    shape = tuple(op.attr("shape", []))
    out = jax.random.uniform(ctx.rng(op), shape, dtype="float32",
                             minval=op.attr("min", -1.0),
                             maxval=op.attr("max", 1.0))
    ctx.set_output(op, "Out", out.astype(dtype))


@register_op("truncated_gaussian_random", infer=_fill_infer, grad=None)
def _truncated_gaussian_random(ctx, op):
    import jax
    dtype = dtype_to_np(op.attr("dtype", "float32"))
    shape = tuple(op.attr("shape", []))
    mean, std = op.attr("mean", 0.0), op.attr("std", 1.0)
    out = jax.random.truncated_normal(ctx.rng(op), -2.0, 2.0, shape,
                                      dtype="float32") * std + mean
    ctx.set_output(op, "Out", out.astype(dtype))


@register_op("randint", infer=_fill_infer, grad=None)
def _randint(ctx, op):
    import jax
    shape = tuple(op.attr("shape", []))
    out = jax.random.randint(ctx.rng(op), shape, op.attr("low", 0),
                             op.attr("high", 100))
    ctx.set_output(op, "Out",
                   out.astype(dtype_to_np(op.attr("dtype", "int64"))))


@register_op("randperm", infer=lambda op, block: set_out(
    op, block, "Out", [op.attr("n")], op.attr("dtype", "int64")), grad=None)
def _randperm(ctx, op):
    import jax
    out = jax.random.permutation(ctx.rng(op), op.attr("n"))
    ctx.set_output(op, "Out",
                   out.astype(dtype_to_np(op.attr("dtype", "int64"))))


@register_op("bernoulli", infer=same_as_input(), grad=None)
def _bernoulli(ctx, op):
    import jax
    x = ctx.get_input(op, "X")
    out = jax.random.bernoulli(ctx.rng(op), x)
    ctx.set_output(op, "Out", out.astype(x.dtype))


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------

def _infer_reshape_shape(in_shape, target):
    target = list(target)
    if -1 in target:
        known = 1
        for s in target:
            if s not in (-1, 0):
                known *= s
        for i, s in enumerate(target):
            if s == 0:
                target[i] = in_shape[i]
                known *= in_shape[i]
        total = int(np.prod([s for s in in_shape]))
        target[target.index(-1)] = (total // known) if known else -1
    else:
        for i, s in enumerate(target):
            if s == 0:
                target[i] = in_shape[i]
    return target


def _reshape_infer(op: Operator, block: Block):
    x = in_var(op, block, "X")
    shape = op.attr("shape", [])
    if -1 in (x.shape or ()):  # dynamic batch flows through
        out = list(shape)
        for i, s in enumerate(out):
            if s == 0:
                out[i] = x.shape[i]
    else:
        out = _infer_reshape_shape(list(x.shape), shape)
    set_out(op, block, "Out", out, x.dtype)
    if op.output("XShape"):
        set_out(op, block, "XShape", [0] + list(x.shape), x.dtype)


def _reshape_lower(ctx: LowerContext, op: Operator):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    if op.single_input("Shape"):
        shape = list(np.asarray(ctx.get_input(op, "Shape")))
    else:
        shape = list(op.attr("shape", []))
    shape = _infer_reshape_shape(list(jnp.shape(x)), shape)
    ctx.set_output(op, "Out", jnp.reshape(x, shape))
    if op.output("XShape"):
        ctx.set_output(op, "XShape", jnp.zeros((0,), dtype=x.dtype))


register_op("reshape", infer=_reshape_infer, lower=_reshape_lower)
register_op("reshape2", infer=_reshape_infer, lower=_reshape_lower)


def _transpose_infer(op, block):
    x = in_var(op, block, "X")
    axis = op.attr("axis", [])
    set_out(op, block, "Out", [x.shape[a] for a in axis], x.dtype)
    if op.output("XShape"):
        set_out(op, block, "XShape", [0] + list(x.shape), x.dtype)


def _transpose_lower(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    ctx.set_output(op, "Out", jnp.transpose(x, op.attr("axis", [])))
    if op.output("XShape"):
        ctx.set_output(op, "XShape", jnp.zeros((0,), dtype=x.dtype))


register_op("transpose", infer=_transpose_infer, lower=_transpose_lower)
register_op("transpose2", infer=_transpose_infer, lower=_transpose_lower)


def _flatten_infer(op, block):
    x = in_var(op, block, "X")
    start = op.attr("start_axis", op.attr("axis", 1))
    stop = op.attr("stop_axis", -1)
    nd = len(x.shape)
    if op.type == "flatten_contiguous_range":
        start, stop = start % nd, stop % nd
        mid = int(np.prod(x.shape[start:stop + 1]))
        out = list(x.shape[:start]) + [mid] + list(x.shape[stop + 1:])
    else:  # reference flatten/flatten2: 2-D at `axis`
        out = [int(np.prod(x.shape[:start])) if start else 1,
               int(np.prod(x.shape[start:]))]
    set_out(op, block, "Out", out, x.dtype)
    if op.output("XShape"):
        set_out(op, block, "XShape", [0] + list(x.shape), x.dtype)


def _flatten_lower(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    nd = jnp.ndim(x)
    if op.type == "flatten_contiguous_range":
        start = op.attr("start_axis", 1) % nd
        stop = op.attr("stop_axis", -1) % nd
        shape = jnp.shape(x)
        out = jnp.reshape(x, shape[:start] + (-1,) + shape[stop + 1:])
    else:
        axis = op.attr("axis", 1)
        out = jnp.reshape(x, (int(np.prod(jnp.shape(x)[:axis])) if axis else 1,
                              -1))
    ctx.set_output(op, "Out", out)
    if op.output("XShape"):
        ctx.set_output(op, "XShape", jnp.zeros((0,), dtype=x.dtype))


for _t in ("flatten", "flatten2", "flatten_contiguous_range"):
    register_op(_t, infer=_flatten_infer, lower=_flatten_lower)


def _sq_axes(op, shape):
    axes = op.attr("axes", [])
    if not axes:
        return [i for i, s in enumerate(shape) if s == 1]
    return [a % len(shape) for a in axes]


def _squeeze_infer(op, block):
    x = in_var(op, block, "X")
    axes = _sq_axes(op, x.shape)
    out = [s for i, s in enumerate(x.shape) if i not in axes]
    set_out(op, block, "Out", out, x.dtype)
    if op.output("XShape"):
        set_out(op, block, "XShape", [0] + list(x.shape), x.dtype)


def _squeeze_lower(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    axes = _sq_axes(op, jnp.shape(x))
    ctx.set_output(op, "Out", jnp.squeeze(x, axis=tuple(axes)))
    if op.output("XShape"):
        ctx.set_output(op, "XShape", jnp.zeros((0,), dtype=x.dtype))


def _unsqueeze_infer(op, block):
    x = in_var(op, block, "X")
    out = list(x.shape)
    for a in op.attr("axes", []):
        out.insert(a if a >= 0 else a + len(out) + 1, 1)
    set_out(op, block, "Out", out, x.dtype)
    if op.output("XShape"):
        set_out(op, block, "XShape", [0] + list(x.shape), x.dtype)


def _unsqueeze_lower(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    for a in op.attr("axes", []):
        x = jnp.expand_dims(x, a)
    ctx.set_output(op, "Out", x)
    if op.output("XShape"):
        ctx.set_output(op, "XShape", jnp.zeros((0,), dtype=x.dtype))


for _t in ("squeeze", "squeeze2"):
    register_op(_t, infer=_squeeze_infer, lower=_squeeze_lower)
for _t in ("unsqueeze", "unsqueeze2"):
    register_op(_t, infer=_unsqueeze_infer, lower=_unsqueeze_lower)


def _concat_infer(op, block):
    xs = [block.var(n) for n in op.input("X")]
    axis = op.attr("axis", 0) % len(xs[0].shape)
    out = list(xs[0].shape)
    out[axis] = sum(v.shape[axis] for v in xs)
    set_out(op, block, "Out", out, xs[0].dtype)


@register_op("concat", infer=_concat_infer)
def _concat(ctx, op):
    jnp = _jnp()
    xs = ctx.get_inputs(op, "X")
    axis = op.attr("axis", 0)
    if op.single_input("AxisTensor"):
        axis = int(np.asarray(ctx.get_input(op, "AxisTensor")))
    ctx.set_output(op, "Out", jnp.concatenate(xs, axis=axis))


def _split_infer(op, block):
    x = in_var(op, block, "X")
    axis = op.attr("axis", 0) % len(x.shape)
    sections = op.attr("sections", [])
    num = op.attr("num", 0)
    outs = op.output("Out")
    if sections:
        sizes = sections
    else:
        n = num or len(outs)
        sizes = [x.shape[axis] // n] * n
    for name, size in zip(outs, sizes):
        v = block._find_var_recursive(name)
        if v is None:  # `or` would trip Variable.__bool__'s trace guard
            v = block.create_var(name=name)
        shape = list(x.shape)
        shape[axis] = size
        v.shape, v.dtype = tuple(shape), x.dtype


@register_op("split", infer=_split_infer)
def _split(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    axis = op.attr("axis", 0)
    sections = op.attr("sections", [])
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        n = op.attr("num", 0) or len(op.output("Out"))
        outs = jnp.split(x, n, axis=axis)
    ctx.set_outputs(op, "Out", outs)


def _stack_infer(op, block):
    xs = [block.var(n) for n in op.input("X")]
    axis = op.attr("axis", 0)
    out = list(xs[0].shape)
    out.insert(axis if axis >= 0 else axis + len(out) + 1, len(xs))
    set_out(op, block, "Y", out, xs[0].dtype)


@register_op("stack", infer=_stack_infer)
def _stack(ctx, op):
    xs = ctx.get_inputs(op, "X")
    ctx.set_output(op, "Y", _jnp().stack(xs, axis=op.attr("axis", 0)))


@register_op("unstack", infer=lambda op, block: _unstack_infer(op, block))
def _unstack(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    axis = op.attr("axis", 0)
    outs = [jnp.squeeze(s, axis) for s in
            jnp.split(x, jnp.shape(x)[axis], axis=axis)]
    ctx.set_outputs(op, "Y", outs)


def _unstack_infer(op, block):
    x = in_var(op, block, "X")
    axis = op.attr("axis", 0) % len(x.shape)
    shape = [s for i, s in enumerate(x.shape) if i != axis]
    for name in op.output("Y"):
        v = block._find_var_recursive(name)
        if v is None:
            v = block.create_var(name=name)
        v.shape, v.dtype = tuple(shape), x.dtype


def _slice_infer(op, block):
    x = in_var(op, block, "Input")
    axes = op.attr("axes", [])
    starts, ends = op.attr("starts", []), op.attr("ends", [])
    out = list(x.shape)
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        if dim == -1:
            continue
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        out[a] = max(e - s, 0)
    for a in sorted(op.attr("decrease_axis", []), reverse=True):
        out.pop(a)
    set_out(op, block, "Out", out, x.dtype)


@register_op("slice", infer=_slice_infer)
def _slice(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    axes = op.attr("axes", [])
    starts, ends = list(op.attr("starts", [])), list(op.attr("ends", []))
    idx = [slice(None)] * jnp.ndim(x)
    for a, s, e in zip(axes, starts, ends):
        idx[a] = slice(s, e if e < np.iinfo(np.int32).max else None)
    out = x[tuple(idx)]
    dec = op.attr("decrease_axis", [])
    if dec:
        out = jnp.squeeze(out, axis=tuple(dec))
    ctx.set_output(op, "Out", out)


def _strided_slice_infer(op, block):
    x = in_var(op, block, "Input")
    out = list(x.shape)
    for a, s, e, st in zip(op.attr("axes", []), op.attr("starts", []),
                           op.attr("ends", []), op.attr("strides", [])):
        dim = x.shape[a]
        if dim == -1:
            continue
        r = len(range(*slice(s, e, st).indices(dim)))
        out[a] = r
    set_out(op, block, "Out", out, x.dtype)


@register_op("strided_slice", infer=_strided_slice_infer)
def _strided_slice(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    idx = [slice(None)] * jnp.ndim(x)
    for a, s, e, st in zip(op.attr("axes", []), op.attr("starts", []),
                           op.attr("ends", []), op.attr("strides", [])):
        idx[a] = slice(s, e, st)
    ctx.set_output(op, "Out", x[tuple(idx)])


def _expand_infer(op, block):
    x = in_var(op, block, "X")
    shape = op.attr("shape", op.attr("expand_shape", []))
    if op.type == "expand":  # v1: expand_times multiplies dims
        times = op.attr("expand_times", [])
        out = [s * t for s, t in zip(x.shape, times)]
    else:
        out = list(shape)
        xs = [1] * (len(out) - len(x.shape)) + list(x.shape)
        out = [xs[i] if o == -1 else o for i, o in enumerate(out)]
    set_out(op, block, "Out", out, x.dtype)


@register_op("expand_v2", infer=_expand_infer)
def _expand_v2(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    shape = list(op.attr("shape", []))
    xs = [1] * (len(shape) - jnp.ndim(x)) + list(jnp.shape(x))
    shape = [xs[i] if s == -1 else s for i, s in enumerate(shape)]
    ctx.set_output(op, "Out", jnp.broadcast_to(jnp.reshape(x, xs), shape))


@register_op("expand", infer=_expand_infer)
def _expand(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    ctx.set_output(op, "Out", jnp.tile(x, op.attr("expand_times", [])))


@register_op("tile", infer=lambda op, block: set_out(
    op, block, "Out",
    [s * t for s, t in zip(
        [1] * (len(op.attr("repeat_times", [])) -
               len(in_var(op, block, "X").shape)) +
        list(in_var(op, block, "X").shape),
        op.attr("repeat_times", []))] or in_var(op, block, "X").shape,
    in_var(op, block, "X").dtype))
def _tile(ctx, op):
    ctx.set_output(op, "Out",
                   _jnp().tile(ctx.get_input(op, "X"),
                               op.attr("repeat_times", [])))


@register_op("shape", infer=lambda op, block: set_out(
    op, block, "Out", [len(in_var(op, block, "Input").shape)], "int32"),
    grad=None)
def _shape(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    ctx.set_output(op, "Out", jnp.asarray(jnp.shape(x), dtype="int32"))


# ---------------------------------------------------------------------------
# indexing: gather / scatter / embedding / one-hot
# ---------------------------------------------------------------------------

def _gather_infer(op, block):
    x, idx = in_var(op, block, "X"), in_var(op, block, "Index")
    axis = op.attr("axis", 0)
    out = list(x.shape)
    if len(idx.shape) == 0:
        out.pop(axis)
    else:
        out[axis] = idx.shape[0]
    set_out(op, block, "Out", out, x.dtype)


@register_op("gather", infer=_gather_infer)
def _gather(ctx, op):
    jnp = _jnp()
    x, idx = ctx.get_input(op, "X"), ctx.get_input(op, "Index")
    axis = op.attr("axis", 0)
    if op.single_input("Axis"):
        axis = int(np.asarray(ctx.get_input(op, "Axis")))
    ctx.set_output(op, "Out", jnp.take(x, idx, axis=axis))


def _gather_nd_infer(op, block):
    x, idx = in_var(op, block, "X"), in_var(op, block, "Index")
    out = list(idx.shape[:-1]) + list(x.shape[idx.shape[-1]:])
    set_out(op, block, "Out", out, x.dtype)


@register_op("gather_nd", infer=_gather_nd_infer)
def _gather_nd(ctx, op):
    jnp = _jnp()
    x, idx = ctx.get_input(op, "X"), ctx.get_input(op, "Index")
    k = jnp.shape(idx)[-1]
    out = x[tuple(jnp.moveaxis(idx, -1, 0))] if k == jnp.ndim(x) else \
        x[tuple(jnp.moveaxis(idx, -1, 0))]
    ctx.set_output(op, "Out", out)


@register_op("scatter", infer=same_as_input())
def _scatter(ctx, op):
    x = ctx.get_input(op, "X")
    idx = ctx.get_input(op, "Ids")
    upd = ctx.get_input(op, "Updates")
    if op.attr("overwrite", True):
        out = x.at[idx].set(upd)
    else:
        out = x.at[idx].add(upd)
    ctx.set_output(op, "Out", out)


@register_op("scatter_nd_add", infer=same_as_input())
def _scatter_nd_add(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    idx = ctx.get_input(op, "Index")
    upd = ctx.get_input(op, "Updates")
    ctx.set_output(op, "Out", x.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd))


@register_op("index_select", infer=_gather_infer)
def _index_select(ctx, op):
    jnp = _jnp()
    x, idx = ctx.get_input(op, "X"), ctx.get_input(op, "Index")
    ctx.set_output(op, "Out", jnp.take(x, idx, axis=op.attr("dim", 0)))


def _lookup_infer(op, block):
    w, ids = in_var(op, block, "W"), in_var(op, block, "Ids")
    ids_shape = list(ids.shape)
    if op.type == "lookup_table" and ids_shape and ids_shape[-1] == 1:
        ids_shape = ids_shape[:-1]  # v1 keeps a trailing 1-dim
        out = ids_shape + [1, w.shape[-1]] if False else ids_shape + [w.shape[-1]]
    else:
        out = ids_shape + [w.shape[-1]]
    set_out(op, block, "Out", out, w.dtype)


def _lookup_lower(ctx: LowerContext, op: Operator):
    jnp = _jnp()
    w, ids = ctx.get_input(op, "W"), ctx.get_input(op, "Ids")
    if op.type == "lookup_table" and jnp.shape(ids)[-1] == 1:
        ids = jnp.squeeze(ids, -1)
    padding_idx = op.attr("padding_idx", -1)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    ctx.set_output(op, "Out", out)


def _lookup_grad_maker(fwd_op, block, helper):
    """is_sparse=True routes to the SelectedRows grad (reference
    lookup_table_op.cc LookupTableGradOp: grad var type switches to
    SELECTED_ROWS when is_sparse); dense keeps the auto vjp."""
    from ..framework.core import grad_var_name
    from .registry import build_auto_grad_specs

    if not fwd_op.attr("is_sparse", False):
        return build_auto_grad_specs(fwd_op, block, helper.no_grad_set)
    w_name = fwd_op.single_input("W")
    v = block._find_var_recursive(w_name)
    if v is None or v.stop_gradient or w_name in helper.no_grad_set:
        return []
    return [dict(
        type="lookup_table_sparse_grad",
        inputs={"W": [w_name], "Ids": list(fwd_op.input("Ids")),
                "Out@GRAD": [grad_var_name(fwd_op.single_output("Out"))]},
        outputs={"W@GRAD": [grad_var_name(w_name)]},
        attrs={"padding_idx": fwd_op.attr("padding_idx", -1),
               "__lookup_type__": fwd_op.type})]


def _lookup_sparse_grad_infer(op, block):
    from ..framework.core import VarType

    w = in_var(op, block, "W")
    set_out(op, block, "W@GRAD", w.shape, w.dtype,
            type=VarType.SELECTED_ROWS)


@register_op("lookup_table_sparse_grad", infer=_lookup_sparse_grad_infer,
             grad=None)
def _lookup_sparse_grad(ctx, op):
    """W@GRAD as SelectedRows{rows=flat ids, values=flat out-grad rows}
    — no [V,H] dense scatter materializes (reference
    lookup_table_op.h is_sparse branch)."""
    from ..framework.selected_rows import SelectedRowsValue

    jnp = _jnp()
    w = ctx.get_input(op, "W")
    ids = ctx.get_input(op, "Ids")
    og = ctx.get_input(op, "Out@GRAD")
    if op.attr("__lookup_type__") == "lookup_table" \
            and jnp.shape(ids)[-1] == 1:
        ids = jnp.squeeze(ids, -1)
    height, cols = w.shape[0], w.shape[-1]
    rows = ids.reshape(-1).astype(jnp.int32)
    vals = og.reshape(-1, cols).astype(w.dtype)
    pad = op.attr("padding_idx", -1)
    if pad is not None and pad >= 0:
        # padding rows contribute no gradient (forward masked them)
        vals = vals * (rows != pad)[:, None].astype(vals.dtype)
    ctx.set_output(op, "W@GRAD", SelectedRowsValue(rows, vals, height))


register_op("lookup_table", infer=_lookup_infer, lower=_lookup_lower,
            grad=_lookup_grad_maker)
register_op("lookup_table_v2", infer=_lookup_infer, lower=_lookup_lower,
            grad=_lookup_grad_maker)
register_op("embedding", infer=_lookup_infer, lower=_lookup_lower,
            grad=_lookup_grad_maker)


def _one_hot_infer(op, block):
    x = in_var(op, block, "X")
    depth = op.attr("depth", 0)
    shape = list(x.shape)
    if op.type == "one_hot" and shape and shape[-1] == 1:
        shape = shape[:-1]
    set_out(op, block, "Out", shape + [depth], "float32")


def _one_hot_lower(ctx, op):
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    if op.type == "one_hot" and jnp.shape(x)[-1] == 1:
        x = jnp.squeeze(x, -1)
    ctx.set_output(op, "Out",
                   jax.nn.one_hot(x, op.attr("depth", 0), dtype="float32"))


register_op("one_hot", infer=_one_hot_infer, lower=_one_hot_lower, grad=None)
register_op("one_hot_v2", infer=_one_hot_infer, lower=_one_hot_lower,
            grad=None)


# ---------------------------------------------------------------------------
# selection / search
# ---------------------------------------------------------------------------

def _where_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)


@register_op("where", infer=_where_infer)
def _where(ctx, op):
    jnp = _jnp()
    cond = ctx.get_input(op, "Condition")
    x, y = ctx.get_input(op, "X"), ctx.get_input(op, "Y")
    ctx.set_output(op, "Out", jnp.where(cond, x, y))


def _argminmax_infer(op, block):
    x = in_var(op, block, "X")
    axis = op.attr("axis", -1)
    keep = op.attr("keepdims", False)
    if op.attr("flatten", False):
        shape = []
    else:
        axis = axis % len(x.shape)
        shape = [(1 if i == axis else s) for i, s in enumerate(x.shape)
                 if keep or i != axis]
    set_out(op, block, "Out", shape, op.attr("dtype", "int64"))


def _make_argminmax(op_type, fn):
    def lower(ctx, op):
        jnp = _jnp()
        x = ctx.get_input(op, "X")
        if op.attr("flatten", False):
            out = fn(jnp.ravel(x), 0, False)
        else:
            out = fn(x, op.attr("axis", -1), op.attr("keepdims", False))
        ctx.set_output(op, "Out",
                       out.astype(dtype_to_np(op.attr("dtype", "int64"))))
    register_op(op_type, infer=_argminmax_infer, lower=lower, grad=None)


_make_argminmax("arg_max",
                lambda x, a, k: _jnp().argmax(x, axis=a, keepdims=k))
_make_argminmax("arg_min",
                lambda x, a, k: _jnp().argmin(x, axis=a, keepdims=k))


def _topk_infer(op, block):
    x = in_var(op, block, "X")
    k = op.attr("k", 1)
    axis = op.attr("axis", -1) % len(x.shape)
    shape = list(x.shape)
    shape[axis] = k
    set_out(op, block, "Out", shape, x.dtype)
    set_out(op, block, "Indices", shape, "int64")


def _topk_lower(ctx, op):
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    k = op.attr("k", 1)
    if op.single_input("K"):
        k = int(np.asarray(ctx.get_input(op, "K")))
    axis = op.attr("axis", -1) % jnp.ndim(x)
    xm = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(xm, k)
    if op.attr("largest", True) is False:
        vals, idx = jax.lax.top_k(-xm, k)
        vals = -vals
    ctx.set_output(op, "Out", jnp.moveaxis(vals, -1, axis))
    ctx.set_output(op, "Indices",
                   jnp.moveaxis(idx, -1, axis).astype("int64"))


register_op("top_k", infer=_topk_infer, lower=_topk_lower, grad=None)
register_op("top_k_v2", infer=_topk_infer, lower=_topk_lower, grad=None)


def _argsort_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)
    set_out(op, block, "Indices", x.shape, "int64")


@register_op("argsort", infer=_argsort_infer, grad=None)
def _argsort(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    axis = op.attr("axis", -1)
    desc = op.attr("descending", False)
    key = -x if desc else x
    idx = jnp.argsort(key, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    ctx.set_output(op, "Out", out)
    ctx.set_output(op, "Indices", idx.astype("int64"))


@register_op("unique", infer=lambda op, block: set_out(
    op, block, "Out", in_var(op, block, "X").shape,
    in_var(op, block, "X").dtype), grad=None)
def _unique(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    # static-shape variant: sorted unique with padding (size= required by XLA)
    out = jnp.unique(jnp.ravel(x), size=jnp.size(x), fill_value=0)
    ctx.set_output(op, "Out", out)


@register_op("masked_select", infer=same_as_input())
def _masked_select(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    mask = ctx.get_input(op, "Mask")
    # static-shape: zero-out unselected (dynamic gather unsupported under jit)
    ctx.set_output(op, "Out", jnp.where(mask, x, 0))


@register_op("take_along_axis", infer=lambda op, block: set_out(
    op, block, "Result", in_var(op, block, "Index").shape,
    in_var(op, block, "Input").dtype))
def _take_along_axis(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    idx = ctx.get_input(op, "Index")
    ctx.set_output(op, "Result",
                   jnp.take_along_axis(x, idx, axis=op.attr("Axis", 0)))


@register_op("flip", infer=same_as_input())
def _flip(ctx, op):
    ctx.set_output(op, "Out", _jnp().flip(ctx.get_input(op, "X"),
                                          axis=op.attr("axis", [0])))


@register_op("roll", infer=same_as_input())
def _roll(ctx, op):
    jnp = _jnp()
    ctx.set_output(op, "Out", jnp.roll(
        ctx.get_input(op, "X"), op.attr("shifts", [0]),
        axis=op.attr("axis", None) or None))


@register_op("pad", infer=lambda op, block: _pad_infer(op, block))
def _pad(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    pads = op.attr("paddings", [])
    pairs = [(pads[2 * i], pads[2 * i + 1]) for i in range(jnp.ndim(x))]
    mode = op.attr("mode", "constant")
    if mode == "constant":
        out = jnp.pad(x, pairs,
                      constant_values=op.attr("pad_value", 0.0))
    elif mode in ("reflect", "edge"):
        out = jnp.pad(x, pairs, mode=mode)
    else:
        raise ValueError(f"pad: unsupported mode {mode!r}")
    ctx.set_output(op, "Out", out)


def _pad_infer(op, block):
    x = in_var(op, block, "X")
    pads = op.attr("paddings", [])
    out = [s + pads[2 * i] + pads[2 * i + 1] if s != -1 else -1
           for i, s in enumerate(x.shape)]
    set_out(op, block, "Out", out, x.dtype)


def _pad3d_infer(op, block):
    x = in_var(op, block, "X")
    p = op.attr("paddings", [0] * 6)
    fmt = op.attr("data_format", "NCDHW")
    out = list(x.shape)
    if fmt == "NCDHW":
        out[4] += p[0] + p[1]
        out[3] += p[2] + p[3]
        out[2] += p[4] + p[5]
    else:
        out[3] += p[0] + p[1]
        out[2] += p[2] + p[3]
        out[1] += p[4] + p[5]
    set_out(op, block, "Out", out, x.dtype)


# ---------------------------------------------------------------------------
# image / structural ops (reference operators/interpolate_op.*,
# tril_triu_op.*, meshgrid_op.*, cumprod_op.*, pixel_shuffle_op.*)
# ---------------------------------------------------------------------------
def _interp_infer(op, block):
    x = in_var(op, block, "X")  # NCHW
    oh = op.attrs.get("out_h", -1)
    ow = op.attrs.get("out_w", -1)
    scale = op.attrs.get("scale", 0.0)
    if (oh <= 0 or ow <= 0) and scale > 0 and x.shape[2] > 0:
        oh, ow = int(x.shape[2] * scale), int(x.shape[3] * scale)
    set_out(op, block, "Out", (x.shape[0], x.shape[1], oh, ow), x.dtype)


def _axis_coords(jnp, size, out_size, align_corners):
    """Source sampling coordinates for one spatial axis (reference
    interpolate_op.h: align_corners picks corner-aligned vs half-pixel
    sampling)."""
    if align_corners:
        if out_size <= 1:
            return jnp.zeros((out_size,))  # corner mapping: pixel 0
        return jnp.linspace(0.0, size - 1.0, out_size)
    c = (jnp.arange(out_size) + 0.5) * (size / out_size) - 0.5
    return jnp.clip(c, 0.0, size - 1.0)


def _interp_lower(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    n, c, h, w = x.shape
    oh = op.attr("out_h", -1)
    ow = op.attr("out_w", -1)
    scale = op.attr("scale", 0.0)
    align = bool(op.attr("align_corners", True))
    if (oh is None or oh <= 0) and scale:
        oh, ow = int(h * scale), int(w * scale)
    xf = x.astype("float32")
    if op.type.startswith("nearest"):
        if align:
            ys = jnp.round(jnp.arange(oh) * ((h - 1) / max(oh - 1, 1)))
            xs = jnp.round(jnp.arange(ow) * ((w - 1) / max(ow - 1, 1)))
        else:
            ys = jnp.floor(jnp.arange(oh) * (h / oh))
            xs = jnp.floor(jnp.arange(ow) * (w / ow))
        out = xf[:, :, ys.astype("int32"), :][:, :, :, xs.astype("int32")]
    else:  # bilinear: gather the 4 corners and lerp
        ys = _axis_coords(jnp, h, oh, align)
        xs = _axis_coords(jnp, w, ow, align)
        y0 = jnp.floor(ys).astype("int32")
        x0 = jnp.floor(xs).astype("int32")
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[None, None, :, None]
        wx = (xs - x0)[None, None, None, :]
        g = lambda yi, xi: xf[:, :, yi, :][:, :, :, xi]
        out = (g(y0, x0) * (1 - wy) * (1 - wx) +
               g(y1, x0) * wy * (1 - wx) +
               g(y0, x1) * (1 - wy) * wx +
               g(y1, x1) * wy * wx)
    ctx.set_output(op, "Out", out.astype(x.dtype))


register_op("bilinear_interp", infer=_interp_infer, lower=_interp_lower)
register_op("bilinear_interp_v2", infer=_interp_infer, lower=_interp_lower)
register_op("nearest_interp", infer=_interp_infer, lower=_interp_lower)
register_op("nearest_interp_v2", infer=_interp_infer, lower=_interp_lower)


@register_op("tril_triu", infer=same_as_input())
def _tril_triu(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    diag = op.attr("diagonal", 0)
    if op.attr("lower", True):
        ctx.set_output(op, "Out", jnp.tril(x, k=diag))
    else:
        ctx.set_output(op, "Out", jnp.triu(x, k=diag))


def _meshgrid_infer(op, block):
    xs = [block.var(n) for n in op.input("X")]
    shape = tuple(v.shape[0] for v in xs)
    for n in op.output("Out"):
        v = block._find_var_recursive(n)
        if v is None:
            v = block.create_var(name=n)
        v.shape, v.dtype = shape, xs[0].dtype


@register_op("meshgrid", infer=_meshgrid_infer)
def _meshgrid(ctx, op):
    jnp = _jnp()
    xs = ctx.get_inputs(op, "X")
    outs = jnp.meshgrid(*xs, indexing="ij")
    ctx.set_outputs(op, "Out", outs)


@register_op("cumprod", infer=same_as_input())
def _cumprod(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    ctx.set_output(op, "Out", jnp.cumprod(x, axis=op.attr("dim", -1)))


def _pixel_shuffle_infer(op, block):
    x = in_var(op, block, "X")  # NCHW
    r = op.attrs.get("upscale_factor", 1)
    n, c, h, w = x.shape
    set_out(op, block, "Out", (n, c // (r * r), h * r, w * r), x.dtype)


@register_op("pixel_shuffle", infer=_pixel_shuffle_infer)
def _pixel_shuffle(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    r = op.attr("upscale_factor", 1)
    n, c, h, w = x.shape
    co = c // (r * r)
    out = x.reshape(n, co, r, r, h, w)
    out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
    ctx.set_output(op, "Out", out.reshape(n, co, h * r, w * r))
