"""CTR / text-matching / detection long-tail ops.

Reference analogs (paddle/fluid/operators/): batch_fc_op.cu,
rank_attention.cu.h:28 (expand kernels), tree_conv_op.cc +
math/tree2col.h:35 (eta formulas) / tree2col.cc:23 (patch DFS),
var_conv_2d_op.cc, pyramid_hash_op.cc, filter_by_instag_op.h,
detection/prroi_pool_op.h (integral of bilinear basis),
correlation_op.cu (FlowNet cost volume), metrics/chunk_eval_op.h.

TPU-first notes:
  * rank_attention's two CUDA expand kernels + batched GEMM collapse to
    gathers + one einsum.
  * tree_conv's per-node DFS patch construction becomes an all-pairs
    bounded-depth reachability built with B boolean matmuls (trees are
    runtime data, so the structure tensors are computed on device with
    static [N,N] shapes); eta_{t,l,r} follow tree2col.h exactly.
  * prroi_pool is computed in closed form: the integral of the bilinear
    interpolant over a bin is separable into per-axis integrals of the
    hat basis, giving an [outW,W]x[outH,H] pair of weight matrices per
    ROI — one einsum per ROI under vmap, no sampling-grid approximation.
  * correlation's displacement loop is a static python loop over the
    (2d+1)^2 shifts — each iteration is a fused multiply-reduce.
  * chunk_eval's chunk walk is vectorized: per-position begin/end masks
    from the scheme rules, first-end-at-or-after-start via a reverse
    cummin, segment equality per start position.
  * filter_by_instag / pyramid_hash keep static shapes (zeroed rows /
    per-position n-gram embeddings); pyramid_hash uses the splitmix-
    style mix from misc2_ops.hash instead of XXH64 (documented).
"""
from __future__ import annotations

import numpy as np

from .registry import in_var, register_op, set_out


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# batch_fc
# ---------------------------------------------------------------------------
def _batch_fc_infer(op, block):
    x = in_var(op, block, "Input")      # [slot, ins, in_dim]
    w = in_var(op, block, "W")          # [slot, in_dim, out_dim]
    set_out(op, block, "Out", (x.shape[0], x.shape[1], w.shape[2]),
            x.dtype)


@register_op("batch_fc", infer=_batch_fc_infer)
def _batch_fc(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    w = ctx.get_input(op, "W")
    b = ctx.get_input(op, "Bias")       # [slot, 1, out_dim]
    ctx.set_output(op, "Out", jnp.einsum("sid,sdo->sio", x, w) + b)


# ---------------------------------------------------------------------------
# rank_attention
# ---------------------------------------------------------------------------
def _rank_attn_infer(op, block):
    x = in_var(op, block, "X")
    p = in_var(op, block, "RankParam")
    set_out(op, block, "Out", (x.shape[0], p.shape[1]), x.dtype)
    if op.output("InputHelp"):
        mr = int(op.attr("MaxRank", 3))
        set_out(op, block, "InputHelp", (x.shape[0], mr * x.shape[1]),
                x.dtype)
    if op.output("InsRank"):
        set_out(op, block, "InsRank", (x.shape[0], 1), x.dtype)


@register_op("rank_attention", infer=_rank_attn_infer)
def _rank_attention(ctx, op):
    """RankOffset row: [own_rank, (faster_rank_k, index_k) x MaxRank]
    (1-based ranks, 0 = invalid). Expanded input block k = X[index_k];
    expanded param block (k, :) = RankParam[(own-1)*R + faster_k - 1]
    viewed [R*R, in_dim, out_dim]; Out = per-instance GEMM of the two
    (rank_attention.cu.h:28,66)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    ro = ctx.get_input(op, "RankOffset").astype("int32")
    param = ctx.get_input(op, "RankParam")
    R = int(op.attr("MaxRank", 3))
    n, d = x.shape
    pcol = param.shape[1]
    lower = ro[:, 0] - 1                      # [N]
    faster = ro[:, 1::2][:, :R] - 1           # [N, R]
    index = ro[:, 2::2][:, :R]                # [N, R]
    valid = (lower[:, None] >= 0) & (faster >= 0)
    xin = jnp.where(valid[..., None], x[jnp.clip(index, 0, n - 1)], 0)
    start = jnp.clip(lower[:, None] * R + faster, 0, R * R - 1)
    pr = param.reshape(R * R, d, pcol)
    pw = jnp.where(valid[..., None, None], pr[start], 0)
    ctx.set_output(op, "Out", jnp.einsum("nrd,nrdp->np", xin, pw))
    if op.output("InputHelp"):
        ctx.set_output(op, "InputHelp", xin.reshape(n, R * d))
    if op.output("InsRank"):
        ctx.set_output(op, "InsRank",
                       ro[:, :1].astype(x.dtype))


# ---------------------------------------------------------------------------
# tree_conv (TBCNN)
# ---------------------------------------------------------------------------
def _tree_conv_infer(op, block):
    x = in_var(op, block, "NodesVector")    # [B, N, F]
    w = in_var(op, block, "Filter")         # [F, 3, G, M]
    set_out(op, block, "Out",
            (x.shape[0], x.shape[1], w.shape[2], w.shape[3]), x.dtype)


@register_op("tree_conv", infer=_tree_conv_infer)
def _tree_conv(ctx, op):
    """Continuous binary tree conv. EdgeSet [B, E, 2] (parent, child)
    1-based, 0-padded. Per patch node at depth dep (root 0), sibling
    index i (1-based) of pclen children (tree2col.h:35):
      eta_t = (D - dep)/D
      eta_l = (1-eta_t) * (0.5 if pclen==1 else (i-1)/(pclen-1))
      eta_r = (1-eta_t) * (1 - eta_l)
    Patch = nodes within depth D-1; reachability via D-1 boolean
    matmuls of the child adjacency."""
    jnp = _jnp()
    x = ctx.get_input(op, "NodesVector")
    edges = ctx.get_input(op, "EdgeSet").astype("int32")
    w = ctx.get_input(op, "Filter")
    D = int(op.attr("max_depth", 2))
    B, N, F = x.shape

    def one(feat, edge):
        p = edge[:, 0] - 1
        c = edge[:, 1] - 1
        ev = (edge[:, 0] > 0) & (edge[:, 1] > 0)
        pc = jnp.clip(p, 0, N - 1)
        cc = jnp.clip(c, 0, N - 1)
        adj = jnp.zeros((N, N), "float32").at[pc, cc].add(
            ev.astype("float32"))
        adj = (adj > 0).astype("float32")
        # sibling order: position of the edge among same-parent edges
        E = edge.shape[0]
        same_p = (p[None, :] == p[:, None]) & ev[None, :] & ev[:, None]
        earlier = jnp.tril(jnp.ones((E, E), bool), -1)
        sib_idx = (same_p & earlier.T).sum(0) + 1       # 1-based
        pclen_e = same_p.sum(1)
        node_idx = jnp.ones((N,), "float32").at[cc].max(
            jnp.where(ev, sib_idx.astype("float32"), 1.0))
        node_pclen = jnp.ones((N,), "float32").at[cc].max(
            jnp.where(ev, pclen_e.astype("float32"), 1.0))
        # dist[u,v] = tree distance if reachable within D-1 else INF
        INF = np.float32(1e9)
        dist = jnp.where(jnp.eye(N, dtype=bool), 0.0, INF)
        frontier = jnp.eye(N, dtype="float32")
        for k in range(1, D):
            frontier = (frontier @ adj > 0).astype("float32")
            dist = jnp.where((frontier > 0) & (dist >= INF),
                             float(k), dist)
        member = dist < INF
        eta_t = jnp.where(member, (D - dist) / D, 0.0)
        temp = jnp.where(node_pclen > 1,
                         (node_idx - 1.0)
                         / jnp.maximum(node_pclen - 1.0, 1.0),
                         0.5)[None, :]
        # patch ROOT uses index=1, pclen=1 -> temp 0.5 regardless of the
        # node's own sibling position (tree2col.cc:29)
        temp = jnp.where(jnp.eye(N, dtype=bool), 0.5, temp)
        eta_l = jnp.where(member, (1 - eta_t) * temp, 0.0)
        eta_r = jnp.where(member, (1 - eta_t) * (1 - eta_l), 0.0)
        coeff = jnp.stack([eta_l, eta_r, eta_t], -1)    # [U, V, 3]
        return jnp.einsum("uvr,vf,frgm->ugm", coeff, feat, w)

    import jax
    ctx.set_output(op, "Out", jax.vmap(one)(x, edges))


# ---------------------------------------------------------------------------
# var_conv_2d — masked variable-size conv (text matching)
# ---------------------------------------------------------------------------
def _var_conv_infer(op, block):
    x = in_var(op, block, "X")      # [B, Cin, H, W] padded
    w = in_var(op, block, "W")      # [Cout, Cin*kh*kw]
    out_ch = int(op.attr("OutputChannel"))
    sh, sw = int(op.attr("StrideH", 1)), int(op.attr("StrideW", 1))
    set_out(op, block, "Out",
            (x.shape[0], out_ch, x.shape[2] // sh, x.shape[3] // sw),
            x.dtype)


@register_op("var_conv_2d", infer=_var_conv_infer)
def _var_conv_2d(ctx, op):
    """Per-row variable-extent conv (reference var_conv_2d_op.cc walks
    LoD extents; padded form: same-padding conv + per-row output mask
    from RowLengths/ColLengths)."""
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    w = ctx.get_input(op, "W")
    rows = ctx.get_input(op, "RowLengths")
    cols = ctx.get_input(op, "ColLengths")
    kh, kw = int(op.attr("KernelH")), int(op.attr("KernelW"))
    sh, sw = int(op.attr("StrideH", 1)), int(op.attr("StrideW", 1))
    out_ch = int(op.attr("OutputChannel"))
    b, cin, H, W = x.shape
    wk = w.reshape(out_ch, cin, kh, kw)
    out = jax.lax.conv_general_dilated(
        x.astype("float32"), wk.astype("float32"),
        window_strides=(sh, sw),
        padding=[((kh - 1) // 2, kh // 2), ((kw - 1) // 2, kw // 2)])
    oh, ow = out.shape[2], out.shape[3]
    # valid extent per row: ceil(len/stride)
    rvalid = (jnp.arange(oh)[None, :]
              < jnp.ceil(rows[:, None] / sh)).astype(out.dtype)
    cvalid = (jnp.arange(ow)[None, :]
              < jnp.ceil(cols[:, None] / sw)).astype(out.dtype)
    mask = rvalid[:, None, :, None] * cvalid[:, None, None, :]
    ctx.set_output(op, "Out", (out * mask).astype(x.dtype))


# ---------------------------------------------------------------------------
# pyramid_hash
# ---------------------------------------------------------------------------
def _pyramid_hash_infer(op, block):
    x = in_var(op, block, "X")          # [B, T] ids
    num_emb = int(op.attr("num_emb"))
    set_out(op, block, "Out", (x.shape[0], x.shape[1], num_emb),
            x.dtype if x.dtype.startswith("float") else "float32")


@register_op("pyramid_hash", infer=_pyramid_hash_infer)
def _pyramid_hash(ctx, op):
    """PyramidDNN n-gram hash embedding (reference pyramid_hash_op.cc):
    out[b,t] = sum over n-gram lengths 2..pyramid_layer+1 of the hashed
    embedding of ids[b, t:t+n] (alive n-grams only). Each n-gram hashes
    to num_emb/rand_len buckets of W [space_len, rand_len]
    (splitmix-style mix instead of the reference's XXH64)."""
    jnp = _jnp()
    ids = ctx.get_input(op, "X").astype("uint32")
    W = ctx.get_input(op, "W")          # [space_len, rand_len]
    lengths = ctx.get_input(op, "Lengths")
    num_emb = int(op.attr("num_emb"))
    rand_len = int(op.attr("rand_len", 16))
    space = W.shape[0]
    levels = int(op.attr("pyramid_layer", 2))
    b, t = ids.shape
    n_seed = num_emb // rand_len
    out = jnp.zeros((b, t, num_emb), "float32")
    alive = jnp.arange(t)[None, :] < lengths[:, None]
    for n in range(2, levels + 2):
        if n > t:
            break
        key = jnp.zeros((b, t - n + 1), "uint32")
        for j in range(n):
            key = key * jnp.uint32(1000003) + ids[:, j:t - n + 1 + j]
        ok = alive[:, n - 1:]           # whole n-gram in range
        chunks = []
        for s in range(n_seed):
            z = key + jnp.uint32(0x9E3779B9) * jnp.uint32(s + 1)
            z = (z ^ (z >> 16)) * jnp.uint32(0x85EBCA6B)
            z = (z ^ (z >> 13)) * jnp.uint32(0xC2B2AE35)
            bucket = ((z ^ (z >> 16)) % jnp.uint32(space)).astype("int32")
            chunks.append(W[bucket])    # [b, t-n+1, rand_len]
        emb = jnp.concatenate(chunks, -1) * ok[..., None]
        out = out.at[:, :t - n + 1].add(emb.astype("float32"))
    ctx.set_output(op, "Out", out)


# ---------------------------------------------------------------------------
# filter_by_instag
# ---------------------------------------------------------------------------
def _instag_infer(op, block):
    x = in_var(op, block, "Ins")
    set_out(op, block, "Out", x.shape, x.dtype)
    set_out(op, block, "LossWeight", (x.shape[0], 1), "float32")
    if op.output("IndexMap"):
        set_out(op, block, "IndexMap", (x.shape[0], 2), "int64")


@register_op("filter_by_instag", infer=_instag_infer)
def _filter_by_instag(ctx, op):
    """Keep rows whose tag list intersects Filter_tag (reference
    filter_by_instag_op.h). Static shapes: dropped rows are zeroed and
    get LossWeight 0 (reference out_val_if_empty analog)."""
    jnp = _jnp()
    ins = ctx.get_input(op, "Ins")
    tags = ctx.get_input(op, "Ins_tag")        # [N, Ttag], -1 padded
    want = ctx.get_input(op, "Filter_tag")     # [K]
    hit = ((tags[:, :, None] == want[None, None, :])
           & (tags[:, :, None] >= 0)).any((1, 2))
    m = hit.reshape((-1,) + (1,) * (ins.ndim - 1))
    ctx.set_output(op, "Out", jnp.where(m, ins, 0))
    ctx.set_output(op, "LossWeight",
                   hit.astype("float32")[:, None])
    if op.output("IndexMap"):
        n = ins.shape[0]
        idx = jnp.arange(n, dtype="int64")
        ctx.set_output(op, "IndexMap", jnp.stack([idx, idx], 1))


# ---------------------------------------------------------------------------
# prroi_pool — closed-form integral of the bilinear interpolant
# ---------------------------------------------------------------------------
def _prroi_infer(op, block):
    rois = in_var(op, block, "ROIs")
    x = in_var(op, block, "X")
    ph = int(op.attr("pooled_height"))
    pw = int(op.attr("pooled_width"))
    set_out(op, block, "Out", (rois.shape[0], x.shape[1], ph, pw),
            x.dtype)


def _hat_integral(jnp, a, b, centers):
    """∫_a^b hat(t - c) dt for each center c; hat(d)=max(0,1-|d|).
    Antiderivative H(d) = d - d|d|/2 on [-1,1], clamped outside."""
    def H(d):
        d = jnp.clip(d, -1.0, 1.0)
        return d - d * jnp.abs(d) / 2.0
    return H(b[..., None] - centers) - H(a[..., None] - centers)


@register_op("prroi_pool", infer=_prroi_infer)
def _prroi_pool(ctx, op):
    """Precise ROI pooling (reference detection/prroi_pool_op.h): the
    average of the continuous bilinear interpolant over each bin,
    computed exactly — the 2-D integral separates into per-axis
    integrals of the hat basis, so each ROI is two small weight
    matrices and one einsum. Fully differentiable in both X and ROIs
    (the reference ships a hand-written coordinate backward; here the
    closed form autodiffs)."""
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    rois = ctx.get_input(op, "ROIs")    # [R, 4] (x1,y1,x2,y2)
    batch_idx = (ctx.get_input(op, "BatchRoINums")
                 if op.input("BatchRoINums") else None)
    scale = float(op.attr("spatial_scale", 1.0))
    ph = int(op.attr("pooled_height"))
    pw = int(op.attr("pooled_width"))
    N, C, H, W = x.shape
    if batch_idx is None:
        bidx = jnp.zeros((rois.shape[0],), "int32")
    else:
        # BatchRoINums [N]: rois per image, in order
        counts = batch_idx.astype("int32")
        bidx = jnp.searchsorted(jnp.cumsum(counts),
                                jnp.arange(rois.shape[0]),
                                side="right").astype("int32")

    cy = jnp.arange(H, dtype="float32")
    cx = jnp.arange(W, dtype="float32")

    def one(roi, bi):
        x1, y1, x2, y2 = roi * scale
        bw = jnp.maximum((x2 - x1) / pw, 1e-9)
        bh = jnp.maximum((y2 - y1) / ph, 1e-9)
        ax = x1 + jnp.arange(pw) * bw
        ay = y1 + jnp.arange(ph) * bh
        wx = _hat_integral(jnp, ax, ax + bw, cx)      # [pw, W]
        wy = _hat_integral(jnp, ay, ay + bh, cy)      # [ph, H]
        feat = x[bi].astype("float32")
        s = jnp.einsum("ph,qw,chw->cpq", wy, wx, feat)
        return s / (bw * bh)

    ctx.set_output(op, "Out",
                   jax.vmap(one)(rois.astype("float32"), bidx)
                   .astype(x.dtype))


# ---------------------------------------------------------------------------
# correlation (FlowNet cost volume)
# ---------------------------------------------------------------------------
def _corr_infer(op, block):
    x = in_var(op, block, "Input1")
    d = int(op.attr("max_displacement"))
    s2 = int(op.attr("stride2", 1))
    rad = d // s2
    k = 2 * rad + 1
    set_out(op, block, "Out",
            (x.shape[0], k * k, x.shape[2], x.shape[3]), x.dtype)


@register_op("correlation", infer=_corr_infer)
def _correlation(ctx, op):
    """out[:, d, :, :] = mean_c x1[c, h, w] * x2[c, h+dy, w+dx] for the
    (2r+1)^2 displacement grid (reference correlation_op.cu); stride1/
    kernel_size=1 form, zero padding at borders."""
    jnp = _jnp()
    x1 = ctx.get_input(op, "Input1").astype("float32")
    x2 = ctx.get_input(op, "Input2").astype("float32")
    d = int(op.attr("max_displacement"))
    s2 = int(op.attr("stride2", 1))
    rad = d // s2
    n, c, h, w = x1.shape
    pad = rad * s2
    x2p = jnp.pad(x2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    outs = []
    for dy in range(-rad, rad + 1):
        for dx in range(-rad, rad + 1):
            oy, ox = pad + dy * s2, pad + dx * s2
            shifted = x2p[:, :, oy:oy + h, ox:ox + w]
            outs.append((x1 * shifted).mean(1))
    ctx.set_output(op, "Out",
                   jnp.stack(outs, 1).astype(x1.dtype))


# ---------------------------------------------------------------------------
# chunk_eval
# ---------------------------------------------------------------------------
def _chunk_eval_infer(op, block):
    for slot in ("Precision", "Recall", "F1-Score"):
        set_out(op, block, slot, (1,), "float32")
    for slot in ("NumInferChunks", "NumLabelChunks",
                 "NumCorrectChunks"):
        if op.output(slot):
            set_out(op, block, slot, (1,), "int64")


def _chunk_masks(jnp, tags, lengths, scheme, n_types):
    """(begin, end, type) masks per position for one [B,T] tag batch.

    Tag encoding (reference chunk_eval_op.h): tag = type * n_pos + pos;
    anything >= n_types * n_pos (or < 0) is Outside.
    """
    n_pos = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]
    B, T = tags.shape
    alive = jnp.arange(T)[None, :] < lengths[:, None]
    inside = alive & (tags >= 0) & (tags < n_types * n_pos)
    typ = jnp.where(inside, tags // n_pos, -1)
    pos = jnp.where(inside, tags % n_pos, -1)
    # neighbours (Outside beyond the sequence)
    prev_t = jnp.pad(typ, ((0, 0), (1, 0)), constant_values=-1)[:, :-1]
    prev_p = jnp.pad(pos, ((0, 0), (1, 0)), constant_values=-1)[:, :-1]
    next_t = jnp.pad(typ, ((0, 0), (0, 1)), constant_values=-1)[:, 1:]
    next_p = jnp.pad(pos, ((0, 0), (0, 1)), constant_values=-1)[:, 1:]
    last = alive & ~jnp.pad(alive, ((0, 0), (0, 1)))[:, 1:]
    next_t = jnp.where(last, -1, next_t)
    next_p = jnp.where(last, -1, next_p)

    if scheme == "plain":
        begin = inside & (prev_t != typ)
        end = inside & (next_t != typ)
    elif scheme == "IOB":        # pos: B=0, I=1
        begin = inside & ((pos == 0)
                          | ((pos == 1) & (prev_t != typ)))
        end = inside & ((next_t != typ) | (next_p == 0))
    elif scheme == "IOE":        # pos: I=0, E=1
        begin = inside & ((prev_t != typ) | (prev_p == 1))
        end = inside & ((pos == 1) | (next_t != typ))
    else:                        # IOBES: B=0, I=1, E=2, S=3
        begin = inside & ((pos == 0) | (pos == 3))
        end = inside & ((pos == 2) | (pos == 3))
    return begin, end, typ


@register_op("chunk_eval", infer=_chunk_eval_infer, grad=None)
def _chunk_eval(ctx, op):
    """Chunk-level precision/recall/F1 (reference metrics/chunk_eval
    _op.h). A predicted chunk is correct iff a label chunk starts at
    the same position with the same type and ends at the same place;
    ends are matched with a reverse cummin (first end >= start)."""
    jnp = _jnp()
    inf = ctx.get_input(op, "Inference").reshape(
        ctx.get_input(op, "Inference").shape[:2])
    lab = ctx.get_input(op, "Label").reshape(inf.shape)
    lengths = ctx.get_input(op, "Lengths")
    scheme = op.attr("chunk_scheme", "IOB")
    n_types = int(op.attr("num_chunk_types"))
    ib, ie, it = _chunk_masks(jnp, inf.astype("int32"), lengths,
                              scheme, n_types)
    lb, le, lt = _chunk_masks(jnp, lab.astype("int32"), lengths,
                              scheme, n_types)
    B, T = inf.shape
    pos = jnp.arange(T)[None, :]
    BIG = T + 1

    def first_end_at_or_after(endmask):
        import jax.lax as lax
        v = jnp.where(endmask, pos, BIG)
        # reverse cummin: for each t, min over t' >= t
        return lax.cummin(v, axis=1, reverse=True)

    i_end = first_end_at_or_after(ie)
    l_end = first_end_at_or_after(le)
    both = ib & lb & (it == lt) & (i_end == l_end) & (i_end < BIG)
    tp = both.sum()
    n_inf = ib.sum()
    n_lab = lb.sum()
    p = tp / jnp.maximum(n_inf, 1)
    r = tp / jnp.maximum(n_lab, 1)
    f1 = 2 * p * r / jnp.maximum(p + r, 1e-9)
    ctx.set_output(op, "Precision", p.astype("float32").reshape(1))
    ctx.set_output(op, "Recall", r.astype("float32").reshape(1))
    ctx.set_output(op, "F1-Score", f1.astype("float32").reshape(1))
    for slot, v in (("NumInferChunks", n_inf),
                    ("NumLabelChunks", n_lab),
                    ("NumCorrectChunks", tp)):
        if op.output(slot):
            ctx.set_output(op, slot, v.astype("int64").reshape(1))


# ---------------------------------------------------------------------------
# attention_lstm (reference attention_lstm_op.cc:350 compute loop)
# ---------------------------------------------------------------------------
def _attn_lstm_infer(op, block):
    x = in_var(op, block, "X")              # [B, T, M]
    D = in_var(op, block, "C0").shape[-1]
    set_out(op, block, "Hidden", (x.shape[0], x.shape[1], D), x.dtype)
    set_out(op, block, "Cell", (x.shape[0], x.shape[1], D), x.dtype)


@register_op("attention_lstm", infer=_attn_lstm_infer)
def _attention_lstm(ctx, op):
    """Fused attention-LSTM. Per step: attention logits over the row's
    positions = relu(X@aw[:M] + ab + dot(c_prev, aw[M:])), optional
    scalar relu(s*logit + sb), masked softmax, context = probs @ X;
    LSTM gates = [h_prev, ctx] @ lstm_w + lstm_b with layout
    [forget, input, output, candidate] (attention_lstm_op.cc:405
    "concat[forget, input, output, tilde]"; lstm_w rows = hidden part
    then x part). Padded [B,T,M] + Lengths replaces the LoD walk."""
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X").astype("float32")
    c0 = ctx.get_input(op, "C0").astype("float32")
    lengths = ctx.get_input(op, "Lengths")
    aw = ctx.get_input(op, "AttentionWeight").astype("float32")
    ab = (ctx.get_input(op, "AttentionBias").astype("float32")
          if op.input("AttentionBias") else 0.0)
    a_s = (ctx.get_input(op, "AttentionScalar").astype("float32")
           if op.input("AttentionScalar") else None)
    a_sb = (ctx.get_input(op, "AttentionScalarBias").astype("float32")
            if op.input("AttentionScalarBias") else 0.0)
    lw = ctx.get_input(op, "LSTMWeight").astype("float32")
    lb = ctx.get_input(op, "LSTMBias").astype("float32").reshape(-1)
    B, T, M = x.shape
    D = c0.shape[-1]
    h0 = (ctx.get_input(op, "H0").astype("float32")
          if op.input("H0") else jnp.zeros((B, D), "float32"))

    atted = jnp.einsum("btm,m->bt", x, aw[:M, 0]) + jnp.reshape(ab, ())
    alive = jnp.arange(T)[None, :] < lengths[:, None]
    NEG = -3.0e38

    def step(carry, t):
        h, c = carry
        logit = jnp.maximum(atted + (c @ aw[M:, 0])[:, None], 0.0)
        if a_s is not None:
            logit = jnp.maximum(
                jnp.reshape(a_s, ()) * logit + jnp.reshape(a_sb, ()),
                0.0)
        probs = jax.nn.softmax(jnp.where(alive, logit, NEG), axis=1)
        ctx_vec = jnp.einsum("bt,btm->bm", probs, x)
        gates = h @ lw[:D] + ctx_vec @ lw[D:] + lb
        f = jax.nn.sigmoid(gates[:, :D])
        i = jax.nn.sigmoid(gates[:, D:2 * D])
        o = jax.nn.sigmoid(gates[:, 2 * D:3 * D])
        cand = jnp.tanh(gates[:, 3 * D:])
        c_new = f * c + i * cand
        h_new = jnp.tanh(c_new) * o
        live = alive[:, t][:, None].astype("float32")
        h_c = live * h_new + (1 - live) * h
        c_c = live * c_new + (1 - live) * c
        return (h_c, c_c), (live * h_new, live * c_new)

    _, (hs, cs) = jax.lax.scan(step, (h0, c0), jnp.arange(T))
    dt = ctx.get_input(op, "X").dtype
    ctx.set_output(op, "Hidden", jnp.swapaxes(hs, 0, 1).astype(dt))
    ctx.set_output(op, "Cell", jnp.swapaxes(cs, 0, 1).astype(dt))


# ---------------------------------------------------------------------------
# bilateral_slice (HDRNet; reference bilateral_slice_op.cu:60)
# ---------------------------------------------------------------------------
def _bilateral_infer(op, block):
    x = in_var(op, block, "X")              # [B, Cin, H, W]
    grid = in_var(op, block, "Grid")        # [B, Cg, D, Hg, Wg]
    cs = x.shape[1] + (1 if op.attr("has_offset", False) else 0)
    set_out(op, block, "Out",
            (x.shape[0], grid.shape[1] // cs, x.shape[2], x.shape[3]),
            x.dtype)


@register_op("bilateral_slice", infer=_bilateral_infer)
def _bilateral_slice(ctx, op):
    """Slice the bilateral grid at (x, y, guide) with tent weights and
    apply the sampled per-pixel affine coeffs (reference
    bilateral_slice_op.cu:60-121; z weight uses the smoothed |.| with
    eps=1e-8 exactly as WeightZ). The 2x2x2 corner walk is a static
    8-term python loop of fused gathers."""
    jnp = _jnp()
    x = ctx.get_input(op, "X").astype("float32")
    grid = ctx.get_input(op, "Grid").astype("float32")
    guide = ctx.get_input(op, "Guide").astype("float32")
    has_offset = bool(op.attr("has_offset", False))
    B, Cin, H, W = x.shape
    _, Cg, D, Hg, Wg = grid.shape
    cs = Cin + (1 if has_offset else 0)
    Cout = Cg // cs

    gx = (jnp.arange(W) + 0.5) * Wg / W                  # [W]
    gy = (jnp.arange(H) + 0.5) * Hg / H                  # [H]
    gz = guide.reshape(B, H, W) * D                      # [B, H, W]
    fx = jnp.floor(gx - 0.5).astype("int32")
    fy = jnp.floor(gy - 0.5).astype("int32")
    fz = jnp.floor(gz - 0.5).astype("int32")

    coeff = jnp.zeros((B, Cg, H, W), "float32")
    for dx in range(2):
        xx = fx + dx
        x_ = jnp.clip(xx, 0, Wg - 1)
        wx = jnp.maximum(1.0 - jnp.abs(xx + 0.5 - gx), 0.0)   # [W]
        for dy in range(2):
            yy = fy + dy
            y_ = jnp.clip(yy, 0, Hg - 1)
            wy = jnp.maximum(1.0 - jnp.abs(yy + 0.5 - gy), 0.0)
            for dz in range(2):
                zz = fz + dz
                z_ = jnp.clip(zz, 0, D - 1)                   # [B,H,W]
                diff = zz + 0.5 - gz
                wz = jnp.maximum(
                    1.0 - jnp.sqrt(diff * diff + 1e-8), 0.0)
                # gather grid[b, :, z_, y_, x_] -> [B, Cg, H, W]
                g = grid[jnp.arange(B)[:, None, None], :,
                         z_, y_[None, :, None], x_[None, None, :]]
                g = jnp.moveaxis(g, -1, 1)
                w8 = (wz * wy[None, :, None]
                      * wx[None, None, :])[:, None]
                coeff = coeff + g * w8
    coeff = coeff.reshape(B, Cout, cs, H, W)
    out = jnp.einsum("bochw,bchw->bohw", coeff[:, :, :Cin], x)
    if has_offset:
        out = out + coeff[:, :, Cin]
    ctx.set_output(op, "Out", out.astype(ctx.get_input(op, "X").dtype))
