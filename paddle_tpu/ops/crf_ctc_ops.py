"""Classic-NLP loss ops: linear_chain_crf, crf_decoding, warpctc, nce,
hierarchical_sigmoid.

Reference analogs: operators/linear_chain_crf_op.h, crf_decoding_op.h,
warpctc_op.cc, nce_op.h, hierarchical_sigmoid_op.cc. The reference
implements these as per-sequence scalar CPU loops (CRF/decoding), a
vendored warp-ctc CUDA library, and Eigen sample loops (NCE/hsigmoid).
Here each is a batched log-space lax.scan / gather formulation — the
whole batch advances one time step per scan step, everything stays on
device, and jax.vjp differentiates the forward directly (no hand-written
backward kernels).

Shared conventions (repo-wide LoD replacement): padded [B, T, ...]
tensors + explicit Length vectors.
"""
from __future__ import annotations

import numpy as np

from .registry import in_var, register_op, set_out

NEG_INF = -1e30


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# linear-chain CRF (forward algorithm) + viterbi decoding
# ---------------------------------------------------------------------------
#
# Transition layout (reference linear_chain_crf_op.h:185): row 0 = start
# weights, row 1 = stop weights, rows 2..N+1 = pairwise [from, to].

def _crf_infer(op, block):
    em = in_var(op, block, "Emission")         # [B, T, N]
    B = em.shape[0]
    set_out(op, block, "LogLikelihood", (B, 1), em.dtype)


@register_op("linear_chain_crf", infer=_crf_infer)
def _linear_chain_crf(ctx, op):
    """Per-sequence negative log-likelihood -(score(path) - log Z).

    Emission [B, T, N], Transition [N+2, N], Label [B, T] (or [B,T,1])
    int64, Length [B] int64. The reference normalizes alpha rows in
    probability space to dodge under/overflow; the log-space logsumexp
    scan needs no normalization.
    """
    import jax
    jnp = _jnp()
    em_in = ctx.get_input(op, "Emission")
    out_dtype = em_in.dtype
    em = em_in.astype(jnp.float32)
    trans = ctx.get_input(op, "Transition").astype(jnp.float32)
    label = ctx.get_input(op, "Label")
    length = ctx.get_input(op, "Length")
    if label.ndim == 3:
        label = label[..., 0]
    label = label.astype("int32")
    B, T, N = em.shape
    start_w, stop_w, pair = trans[0], trans[1], trans[2:]   # [N],[N],[N,N]

    t_idx = jnp.arange(T)
    valid = t_idx[None, :] < length[:, None]                # [B, T]

    # ---- log Z by forward scan -------------------------------------
    alpha0 = start_w[None, :] + em[:, 0]                    # [B, N]

    def body(alpha, xs):
        em_t, valid_t = xs                                  # [B,N], [B]
        nxt = jax.nn.logsumexp(
            alpha[:, :, None] + pair[None], axis=1) + em_t
        alpha = jnp.where(valid_t[:, None], nxt, alpha)
        return alpha, None

    alpha, _ = jax.lax.scan(
        body, alpha0, (jnp.moveaxis(em[:, 1:], 1, 0),
                       jnp.moveaxis(valid[:, 1:], 1, 0)))
    logz = jax.nn.logsumexp(alpha + stop_w[None, :], axis=1)  # [B]

    # ---- gold path score -------------------------------------------
    em_score = jnp.where(
        valid, jnp.take_along_axis(em, label[..., None],
                                   axis=2)[..., 0], 0.0).sum(1)
    prev, cur = label[:, :-1], label[:, 1:]
    pair_scores = pair[prev, cur]                           # [B, T-1]
    pair_score = jnp.where(valid[:, 1:], pair_scores, 0.0).sum(1)
    last = jnp.take_along_axis(
        label, (length[:, None] - 1).astype("int32"), axis=1)[:, 0]
    path = start_w[label[:, 0]] + em_score + pair_score + stop_w[last]
    nll = (logz - path)[:, None]
    ctx.set_output(op, "LogLikelihood", nll.astype(out_dtype))


def _crf_decoding_infer(op, block):
    em = in_var(op, block, "Emission")
    set_out(op, block, "ViterbiPath", em.shape[:2], "int64")


@register_op("crf_decoding", infer=_crf_decoding_infer, grad=None)
def _crf_decoding(ctx, op):
    """Viterbi decode (reference crf_decoding_op.h): max-product forward
    scan storing argmax backpointers, then a reverse scan backtracks.
    Positions past Length are 0. When Label is also fed, the reference
    emits a correctness mask instead; we keep the path output and leave
    comparison to the caller (layers.crf_decoding handles it)."""
    import jax
    jnp = _jnp()
    em = ctx.get_input(op, "Emission").astype(jnp.float32)
    trans = ctx.get_input(op, "Transition").astype(jnp.float32)
    length = ctx.get_input(op, "Length")
    B, T, N = em.shape
    start_w, stop_w, pair = trans[0], trans[1], trans[2:]

    t_idx = jnp.arange(T)
    valid = t_idx[None, :] < length[:, None]

    alpha0 = start_w[None, :] + em[:, 0]

    def fwd(alpha, xs):
        em_t, valid_t, t = xs
        scores = alpha[:, :, None] + pair[None]             # [B, N, N]
        best_prev = jnp.argmax(scores, axis=1)              # [B, N]
        nxt = jnp.max(scores, axis=1) + em_t
        alpha_new = jnp.where(valid_t[:, None], nxt, alpha)
        return alpha_new, best_prev

    alpha, bp = jax.lax.scan(
        fwd, alpha0, (jnp.moveaxis(em[:, 1:], 1, 0),
                      jnp.moveaxis(valid[:, 1:], 1, 0),
                      jnp.arange(1, T)))
    # bp: [T-1, B, N] backpointers for steps 1..T-1
    final = alpha + stop_w[None, :]
    last_tag = jnp.argmax(final, axis=1)                    # [B]

    def bwd(tag, xs):
        bp_t, t = xs                                        # [B, N]
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        # only follow the pointer while step t is inside the sequence
        inside = t < length
        prev = jnp.where(inside, prev, tag)
        return prev, prev

    # walk t = T-1 .. 1; tags emitted are for positions t-1
    _, prevs = jax.lax.scan(bwd, last_tag,
                            (bp, jnp.arange(1, T)), reverse=True)
    # prevs[t-1] is the tag at position t-1 (the frozen carry makes
    # prevs[length-1] == last_tag exactly); append last_tag for T-1
    path = jnp.concatenate([jnp.moveaxis(prevs, 0, 1),
                            last_tag[:, None]], axis=1)     # [B, T]
    path = jnp.where(valid, path, 0)
    ctx.set_output(op, "ViterbiPath", path.astype("int64"))


# ---------------------------------------------------------------------------
# CTC loss
# ---------------------------------------------------------------------------

def _warpctc_infer(op, block):
    logits = in_var(op, block, "Logits")       # [B, T, C]
    set_out(op, block, "Loss", (logits.shape[0], 1), logits.dtype)


@register_op("warpctc", infer=_warpctc_infer)
def _warpctc(ctx, op):
    """CTC loss (reference warpctc_op.cc wraps the warp-ctc CUDA lib).

    Logits [B, T, C] (unnormalized), Label [B, L] int labels (no
    blanks), LogitsLength [B], LabelLength [B]; attr blank. Log-space
    alpha recursion over the blank-interleaved extended sequence
    l' = [b, l1, b, l2, ..., b] (|l'| = 2L+1), one lax.scan over time
    for the whole batch. Loss = -logsumexp(alpha_T[last, last-1]).
    """
    import jax
    jnp = _jnp()
    logits_in = ctx.get_input(op, "Logits")
    out_dtype = logits_in.dtype
    logits = logits_in.astype(jnp.float32)
    label = ctx.get_input(op, "Label").astype("int32")
    in_len = ctx.get_input(op, "LogitsLength")
    lab_len = ctx.get_input(op, "LabelLength")
    blank = op.attr("blank", 0)
    B, T, C = logits.shape
    L = label.shape[1]
    S = 2 * L + 1

    logp = jax.nn.log_softmax(logits, axis=-1)              # [B, T, C]
    # extended sequence tokens: even slots blank, odd slots labels
    ext = jnp.full((B, S), blank, "int32")
    ext = ext.at[:, 1::2].set(label)
    ext_len = 2 * lab_len + 1                               # [B]

    # can we skip from s-2 to s? only onto label slots whose token
    # differs from the token two back
    tok = ext
    tok_m2 = jnp.concatenate(
        [jnp.full((B, 2), -1, "int32"), ext[:, :-2]], axis=1)
    can_skip = (tok != blank) & (tok != tok_m2)             # [B, S]

    a0 = jnp.full((B, S), NEG_INF, jnp.float32)
    a0 = a0.at[:, 0].set(logp[:, 0, blank])
    a0 = a0.at[:, 1].set(
        jnp.where(lab_len > 0,
                  jnp.take_along_axis(logp[:, 0], label[:, :1],
                                      axis=1)[:, 0], NEG_INF))

    def lse2(a, b):
        return jnp.logaddexp(a, b)

    def body(alpha, xs):
        logp_t, t = xs                                      # [B, C]
        stay = alpha
        prev1 = jnp.concatenate(
            [jnp.full((B, 1), NEG_INF), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate(
            [jnp.full((B, 2), NEG_INF), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(can_skip, prev2, NEG_INF)
        tot = lse2(lse2(stay, prev1), prev2)
        emit = jnp.take_along_axis(logp_t, tok, axis=1)     # [B, S]
        new = tot + emit
        alive = t < in_len                                  # [B]
        return jnp.where(alive[:, None], new, alpha), None

    alpha, _ = jax.lax.scan(
        body, a0, (jnp.moveaxis(logp[:, 1:], 1, 0), jnp.arange(1, T)))
    last = jnp.take_along_axis(alpha, (ext_len[:, None] - 1).astype(
        "int32"), axis=1)[:, 0]
    second = jnp.take_along_axis(alpha, (ext_len[:, None] - 2).astype(
        "int32"), axis=1)[:, 0]
    second = jnp.where(lab_len > 0, second, NEG_INF)
    loss = -lse2(last, second)
    ctx.set_output(op, "Loss", loss[:, None].astype(out_dtype))


# ---------------------------------------------------------------------------
# NCE
# ---------------------------------------------------------------------------

def _nce_infer(op, block):
    x = in_var(op, block, "Input")             # [B, D]
    set_out(op, block, "Cost", (x.shape[0], 1), x.dtype)


@register_op("nce", infer=_nce_infer)
def _nce(ctx, op):
    """Noise-contrastive estimation (reference nce_op.h:87).

    Input [B, D], Weight [num_classes, D], Bias [num_classes] (opt),
    Label [B, num_true]. attrs: num_neg_samples, num_total_classes,
    sampler (0 uniform / 1 log-uniform), seed.

    Per sample: cost = -sum_true log h(s_t) - sum_neg log(1 - h(s_n))
    with h(s) = sigmoid(s - log(k * q(class))), q the sampler density —
    the reference's binary-logistic NCE objective. Negatives are drawn
    fresh per step from the op's stateless RNG and not differentiated.
    """
    import jax
    jnp = _jnp()
    x_in = ctx.get_input(op, "Input")
    out_dtype = x_in.dtype
    x = x_in.astype(jnp.float32)
    w = ctx.get_input(op, "Weight").astype(jnp.float32)
    bias = ctx.get_input(op, "Bias") if op.single_input("Bias") else None
    label = ctx.get_input(op, "Label").astype("int32")
    k = op.attr("num_neg_samples", 10)
    num_classes = op.attr("num_total_classes")
    sampler = op.attr("sampler", 0)
    B, D = x.shape
    num_true = label.shape[1]

    key = ctx.rng(op)
    if sampler == 1:
        # log-uniform (Zipf): P(c) = log((c+2)/(c+1)) / log(V+1)
        u = jax.random.uniform(key, (B, k))
        neg = (jnp.exp(u * jnp.log(num_classes + 1.0)) - 1.0).astype(
            "int32")
        neg = jnp.clip(neg, 0, num_classes - 1)
        def q(c):
            c = c.astype(jnp.float32)
            return (jnp.log((c + 2.0) / (c + 1.0))
                    / jnp.log(num_classes + 1.0))
    else:
        neg = jax.random.randint(key, (B, k), 0, num_classes, "int32")
        def q(c):
            return jnp.full(c.shape, 1.0 / num_classes, jnp.float32)
    neg = jax.lax.stop_gradient(neg)

    def score(cls):                                         # [B, M]
        s = jnp.einsum("bd,bmd->bm", x, w[cls])
        if bias is not None:
            s = s + bias[cls]
        return s

    log_kq_true = jnp.log(k * q(label) + 1e-20)
    log_kq_neg = jnp.log(k * q(neg) + 1e-20)
    s_true = score(label) - log_kq_true                     # [B, num_true]
    s_neg = score(neg) - log_kq_neg                         # [B, k]
    # -log sigmoid(s_true) = softplus(-s), -log(1-sigmoid(s)) = softplus(s)
    cost = (jax.nn.softplus(-s_true).sum(1)
            + jax.nn.softplus(s_neg).sum(1)) / num_true
    ctx.set_output(op, "Cost", cost[:, None].astype(out_dtype))


# ---------------------------------------------------------------------------
# hierarchical sigmoid
# ---------------------------------------------------------------------------

def _hsig_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", (x.shape[0], 1), x.dtype)


@register_op("hierarchical_sigmoid", infer=_hsig_infer)
def _hierarchical_sigmoid(ctx, op):
    """Hierarchical softmax over a complete binary tree (reference
    hierarchical_sigmoid_op.cc; custom Huffman paths via
    PathTable/PathCode also supported).

    X [B, D], W [num_classes-1, D] (one row per internal node), Bias
    [num_classes-1] (opt), Label [B] or [B,1]. Default tree: class c's
    path is the binary representation of node index (c + num_classes-1)
    walked up to the root — the classic complete-tree hsigmoid.
    loss = sum_path softplus((1 - 2*bit) * (x·w_node + b_node)).
    """
    import jax
    jnp = _jnp()
    x_in = ctx.get_input(op, "X")
    out_dtype = x_in.dtype
    x = x_in.astype(jnp.float32)
    w = ctx.get_input(op, "W").astype(jnp.float32)
    bias = ctx.get_input(op, "Bias") if op.single_input("Bias") else None
    label = ctx.get_input(op, "Label")
    if label.ndim == 2:
        label = label[:, 0]
    label = label.astype("int32")
    num_classes = op.attr("num_classes")
    B, D = x.shape

    if op.single_input("PathTable"):
        table = ctx.get_input(op, "PathTable").astype("int32")  # [B, P]
        code = ctx.get_input(op, "PathCode").astype(jnp.float32)
        mask = (table >= 0).astype(jnp.float32)
        nodes = jnp.maximum(table, 0)
    else:
        # complete binary tree: leaf index = label + (num_classes - 1);
        # parent(i) = (i-1)//2; bit = 1 if i was a right child
        depth = max(1, int(np.ceil(np.log2(max(num_classes, 2)))))
        idx = label + (num_classes - 1)
        nodes_l, code_l, mask_l = [], [], []
        cur = idx
        for _ in range(depth):
            parent = (cur - 1) // 2
            is_right = (cur % 2 == 0).astype(jnp.float32)
            live = (cur > 0).astype(jnp.float32)
            nodes_l.append(jnp.maximum(parent, 0))
            code_l.append(is_right)
            mask_l.append(live)
            cur = jnp.maximum(parent, 0)
        nodes = jnp.stack(nodes_l, axis=1)                  # [B, depth]
        code = jnp.stack(code_l, axis=1)
        mask = jnp.stack(mask_l, axis=1)

    s = jnp.einsum("bd,bpd->bp", x, w[nodes])               # [B, P]
    if bias is not None:
        s = s + bias[nodes]
    # bit 1 -> -log sigmoid(-s)? convention: code bit selects the branch
    # probability sigmoid(s) (bit 0) vs 1-sigmoid(s) (bit 1)
    sign = 1.0 - 2.0 * code
    loss = (jax.nn.softplus(-sign * s) * mask).sum(1)
    ctx.set_output(op, "Out", loss[:, None].astype(out_dtype))
