"""Interpolation variants: linear / bicubic / trilinear (+_v2 aliases).

Reference: paddle/fluid/operators/interpolate_op.h — LinearInterpolation
(:118), TrilinearInterpolation (:312), BicubicInterpolation (:487) with
get_cubic_upsample_coefficients (:460, A=-0.75) — and interpolate_op.cc
(:558-612) for the op surfaces. nearest/bilinear live in tensor_ops.py.

TPU-first design: every mode is a *separable* weighted gather — per
spatial axis we precompute (taps [out,k] int32, weights [out,k] f32) on
the host (shapes are static under jit) and contract one axis at a time
with jnp.take + a broadcasted weighted sum. XLA fuses the k-tap
contraction into a single gather-multiply-reduce per axis; grads fall
out of the auto-vjp (a scatter-add, also fused). No data-dependent
control flow, no dynamic shapes.

Semantics mirrored from the reference kernels:
  * ratio = (in-1)/(out-1) if align_corners else in/out
  * linear family: x_w = trunc(align_flag ? ratio*(l+.5)-.5 : ratio*l),
    clamped at 0; right tap min(x_w+1, in-1); fractional part from the
    clamped source coordinate (align_flag = align_mode==0 and not
    align_corners).
  * bicubic: src = align_corners ? ratio*l : ratio*(l+.5)-.5, 4 taps at
    clip(floor(src)-1+o), Keys kernel A=-0.75.
"""
from __future__ import annotations

import numpy as np

from .registry import in_var, register_op, set_out


def _jnp():
    import jax.numpy as jnp
    return jnp


def _ratio(in_size: int, out_size: int, align_corners: bool) -> float:
    if align_corners:
        return (in_size - 1.0) / (out_size - 1.0) if out_size > 1 else 0.0
    return in_size / float(out_size)


def _linear_taps(in_size, out_size, align_corners, align_mode):
    """(idx [out,2] int32, w [out,2] f32) for one linear-family axis."""
    r = _ratio(in_size, out_size, align_corners)
    l = np.arange(out_size, dtype=np.float64)
    align_flag = (align_mode == 0) and not align_corners
    src = r * (l + 0.5) - 0.5 if align_flag else r * l
    x_w = np.maximum(np.trunc(src), 0.0).astype(np.int64)
    x_e = np.minimum(x_w + 1, in_size - 1)
    d = (np.maximum(src, 0.0) - x_w) if align_flag else (r * l - x_w)
    idx = np.stack([x_w, x_e], 1).astype(np.int32)
    w = np.stack([1.0 - d, d], 1).astype(np.float32)
    return idx, w


def _cubic_taps(in_size, out_size, align_corners):
    """(idx [out,4] int32, w [out,4] f32): Keys cubic kernel, A=-0.75."""
    A = -0.75
    r = _ratio(in_size, out_size, align_corners)
    l = np.arange(out_size, dtype=np.float64)
    src = r * l if align_corners else r * (l + 0.5) - 0.5
    base = np.floor(src)
    t = src - base

    def conv1(x):  # |x| <= 1
        return ((A + 2) * x - (A + 3)) * x * x + 1

    def conv2(x):  # 1 < |x| < 2
        return ((A * x - 5 * A) * x + 8 * A) * x - 4 * A

    w = np.stack([conv2(t + 1.0), conv1(t), conv1(1.0 - t),
                  conv2(2.0 - t)], 1).astype(np.float32)
    idx = np.clip(base[:, None] + np.arange(-1, 3)[None, :],
                  0, in_size - 1).astype(np.int32)
    return idx, w


def _contract_axis(jnp, x, axis, idx, w):
    """Weighted k-tap gather along one axis: x[..., idx, ...] @ w."""
    out, k = idx.shape
    g = jnp.take(x, jnp.asarray(idx.reshape(-1)), axis=axis)
    g = g.reshape(x.shape[:axis] + (out, k) + x.shape[axis + 1:])
    wshape = (1,) * axis + (out, k) + (1,) * (x.ndim - axis - 1)
    return (g * jnp.asarray(w).reshape(wshape)).sum(axis=axis + 1)


def _out_sizes(op, in_spatial, names):
    """Resolve output spatial sizes from out_* attrs or scale."""
    sizes = [op.attr(n, -1) or -1 for n in names]
    scale = op.attr("scale", 0.0)
    if any(s is None or s <= 0 for s in sizes):
        if isinstance(scale, (list, tuple)) and scale:
            sizes = [int(d * s) for d, s in zip(in_spatial, scale)]
        elif scale and scale > 0:
            sizes = [int(d * scale) for d in in_spatial]
    return sizes


def _interp_nd_infer(names):
    def infer(op, block):
        x = in_var(op, block, "X")
        sizes = _out_sizes(op, x.shape[2:], names)
        set_out(op, block, "Out", tuple(x.shape[:2]) + tuple(sizes),
                x.dtype)
    return infer


def _interp_nd_lower(names, cubic):
    def lower(ctx, op):
        jnp = _jnp()
        x = ctx.get_input(op, "X")
        sizes = _out_sizes(op, x.shape[2:], names)
        align = bool(op.attr("align_corners", True))
        mode = int(op.attr("align_mode", 1))
        out = x.astype("float32")
        for i, (in_sz, out_sz) in enumerate(zip(x.shape[2:], sizes)):
            idx, w = (_cubic_taps(in_sz, out_sz, align) if cubic
                      else _linear_taps(in_sz, out_sz, align, mode))
            out = _contract_axis(jnp, out, 2 + i, idx, w)
        ctx.set_output(op, "Out", out.astype(x.dtype))
    return lower


for _name, _axes, _cubic in [
        ("linear_interp", ("out_w",), False),
        ("trilinear_interp", ("out_d", "out_h", "out_w"), False),
        ("bicubic_interp", ("out_h", "out_w"), True)]:
    for _suffix in ("", "_v2"):
        register_op(_name + _suffix, infer=_interp_nd_infer(_axes),
                    lower=_interp_nd_lower(_axes, _cubic))
