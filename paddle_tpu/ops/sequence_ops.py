"""Masked/dense sequence ops + TensorArray ops.

TPU-native replacement for the reference's LoD machinery
(operators/sequence_ops/, 6,158 LoC; framework LoDTensor ragged rows):
a "sequence batch" here is a dense [B, T, ...] tensor + an int lengths
vector [B] — the bucketed/masked representation (SURVEY.md §7 hard part
(a)).  Every sequence_* op takes the lengths through a second input slot
and masks accordingly; XLA sees only static shapes.

TensorArray (framework.proto LOD_TENSOR_ARRAY + operators/
tensor_array_read_write ops): a fixed-capacity ring of slots backed by
one dense buffer [cap, *item] so writes/reads are dynamic_update_slice /
dynamic_index — scan/while-carry compatible and differentiable.
"""
from __future__ import annotations

import numpy as np

from .registry import (LowerContext, in_var, register_op, same_as_input,
                       set_out)


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# sequence mask / pool / softmax / reverse / expand / concat
# ---------------------------------------------------------------------------
def _seq_mask_infer(op, block):
    x = in_var(op, block, "X")
    maxlen = op.attrs.get("maxlen", -1)
    b = x.shape[0] if x.shape else -1
    set_out(op, block, "Y", (b, maxlen if maxlen > 0 else -1),
            op.attrs.get("out_dtype", "float32"))


@register_op("sequence_mask", infer=_seq_mask_infer, grad=None)
def _sequence_mask(ctx, op):
    jnp = _jnp()
    lengths = ctx.get_input(op, "X")
    maxlen = op.attr("maxlen", -1)
    if maxlen <= 0:
        raise ValueError("sequence_mask needs a static maxlen on TPU")
    dtype = op.attr("out_dtype", "float32")
    mask = jnp.arange(maxlen)[None, :] < lengths[:, None]
    ctx.set_output(op, "Y", mask.astype(dtype))


def _pool_infer(op, block):
    x = in_var(op, block, "X")  # [B, T, ...]
    set_out(op, block, "Out", (x.shape[0],) + tuple(x.shape[2:]), x.dtype)


@register_op("sequence_pool", infer=_pool_infer)
def _sequence_pool(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")          # [B, T, ...]
    lengths = ctx.get_input(op, "Lengths")
    T = x.shape[1]
    mask = (jnp.arange(T)[None, :] < lengths[:, None])
    mshape = mask.shape + (1,) * (x.ndim - 2)
    m = mask.reshape(mshape).astype(x.dtype)
    pool = op.attr("pool_type", "average").lower()
    if pool in ("average", "avg", "mean"):
        denom = jnp.maximum(lengths.astype(x.dtype), 1).reshape(
            (-1,) + (1,) * (x.ndim - 2))
        out = (x * m).sum(axis=1) / denom
    elif pool == "sum":
        out = (x * m).sum(axis=1)
    elif pool == "sqrt":
        denom = jnp.sqrt(jnp.maximum(lengths.astype(x.dtype), 1)).reshape(
            (-1,) + (1,) * (x.ndim - 2))
        out = (x * m).sum(axis=1) / denom
    elif pool == "max":
        neg = jnp.finfo(x.dtype).min if jnp.issubdtype(
            x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = jnp.where(m > 0, x, neg).max(axis=1)
    elif pool == "last":
        idx = jnp.maximum(lengths - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)).astype("int32"),
            axis=1).squeeze(1)
    elif pool == "first":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pool_type {pool!r}")
    ctx.set_output(op, "Out", out)


@register_op("sequence_softmax", infer=same_as_input())
def _sequence_softmax(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")          # [B, T]
    lengths = ctx.get_input(op, "Lengths")
    mask = jnp.arange(x.shape[1])[None, :] < lengths[:, None]
    neg = jnp.asarray(-1e30, x.dtype)
    z = jnp.where(mask, x, neg)
    z = z - z.max(axis=1, keepdims=True)
    e = jnp.exp(z) * mask.astype(x.dtype)
    ctx.set_output(op, "Out",
                   e / jnp.maximum(e.sum(axis=1, keepdims=True), 1e-30))


@register_op("sequence_reverse", infer=same_as_input())
def _sequence_reverse(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")          # [B, T, ...]
    lengths = ctx.get_input(op, "Lengths")
    T = x.shape[1]
    pos = jnp.arange(T)[None, :]
    # position i maps to (len-1-i) inside the sequence; padding stays
    src = jnp.where(pos < lengths[:, None],
                    lengths[:, None] - 1 - pos, pos)
    out = jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)).astype("int32"),
        axis=1)
    ctx.set_output(op, "Out", out)


def _seq_expand_infer(op, block):
    x = in_var(op, block, "X")          # [B, ...]
    times = op.attrs.get("maxlen", -1)
    set_out(op, block, "Out", (x.shape[0], times) + tuple(x.shape[1:]),
            x.dtype)


@register_op("sequence_expand_as", infer=_seq_expand_infer)
def _sequence_expand_as(ctx, op):
    """Broadcast a per-sequence vector across its (masked) time steps."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")          # [B, ...]
    lengths = ctx.get_input(op, "Lengths")
    maxlen = op.attr("maxlen")
    mask = (jnp.arange(maxlen)[None, :] < lengths[:, None])
    out = jnp.broadcast_to(x[:, None], (x.shape[0], maxlen) + x.shape[1:])
    m = mask.reshape(mask.shape + (1,) * (x.ndim - 1)).astype(x.dtype)
    ctx.set_output(op, "Out", out * m)


# ---------------------------------------------------------------------------
# TensorArray
# ---------------------------------------------------------------------------
def _wta_infer(op, block):
    arr = in_var(op, block, "Array")
    set_out(op, block, "Out", arr.shape, arr.dtype)


@register_op("write_to_array", infer=_wta_infer)
def _write_to_array(ctx, op):
    import jax
    jnp = _jnp()
    arr = ctx.get_input(op, "Array")    # [cap, *item]
    x = ctx.get_input(op, "X")
    i = ctx.get_input(op, "I")
    i = jnp.reshape(i, ()).astype("int32")
    ctx.set_output(op, "Out", jax.lax.dynamic_update_index_in_dim(
        arr, x.astype(arr.dtype), i, 0))


def _rfa_infer(op, block):
    arr = in_var(op, block, "Array")
    set_out(op, block, "Out", tuple(arr.shape[1:]), arr.dtype)


@register_op("read_from_array", infer=_rfa_infer)
def _read_from_array(ctx, op):
    import jax
    jnp = _jnp()
    arr = ctx.get_input(op, "Array")
    i = jnp.reshape(ctx.get_input(op, "I"), ()).astype("int32")
    ctx.set_output(op, "Out",
                   jax.lax.dynamic_index_in_dim(arr, i, 0,
                                                keepdims=False))


# ---------------------------------------------------------------------------
# recurrent cells: lstm / gru over time (lax.scan)
# ---------------------------------------------------------------------------
def _rnn_infer(op, block):
    x = in_var(op, block, "X")          # [B, T, D]
    hid = op.attrs["hidden_size"]
    set_out(op, block, "Out", (x.shape[0], x.shape[1], hid), x.dtype)
    set_out(op, block, "LastH", (x.shape[0], hid), x.dtype)
    if op.output("LastC"):
        set_out(op, block, "LastC", (x.shape[0], hid), x.dtype)


@register_op("lstm_rnn", infer=_rnn_infer)
def _lstm_rnn(ctx, op):
    """Single-layer LSTM over [B,T,D]; lengths mask freezes state past
    each sequence's end.  Reference: cudnn_lstm_op / layers/rnn.py —
    here one lax.scan whose per-step math is a fused [D+H, 4H] matmul.
    """
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    w = ctx.get_input(op, "W")          # [D+H, 4H]
    b = ctx.get_input(op, "B")          # [4H]
    lengths = ctx.get_input(op, "Lengths")
    H = op.attr("hidden_size")
    B = x.shape[0]
    h0 = jnp.zeros((B, H), x.dtype)
    c0 = jnp.zeros((B, H), x.dtype)
    xs = jnp.swapaxes(x, 0, 1)          # [T, B, D]

    def step(carry, inp):
        h, c, t = carry
        xt = inp
        z = jnp.concatenate([xt, h], axis=-1) @ w + b
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        alive = (t < lengths)[:, None].astype(x.dtype)
        h_new = alive * h_new + (1 - alive) * h
        c_new = alive * c_new + (1 - alive) * c
        return (h_new, c_new, t + 1), h_new

    (h_last, c_last, _), hs = jax.lax.scan(step, (h0, c0, 0), xs)
    ctx.set_output(op, "Out", jnp.swapaxes(hs, 0, 1))
    ctx.set_output(op, "LastH", h_last)
    ctx.set_output(op, "LastC", c_last)


@register_op("gru_rnn", infer=_rnn_infer)
def _gru_rnn(ctx, op):
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    w = ctx.get_input(op, "W")          # [D+H, 3H]
    b = ctx.get_input(op, "B")          # [3H]
    lengths = ctx.get_input(op, "Lengths")
    H = op.attr("hidden_size")
    B = x.shape[0]
    h0 = jnp.zeros((B, H), x.dtype)
    xs = jnp.swapaxes(x, 0, 1)
    w_rz, w_h = w[:, :2 * H], w[:, 2 * H:]
    b_rz, b_h = b[:2 * H], b[2 * H:]

    def step(carry, xt):
        h, t = carry
        rz = jax.nn.sigmoid(jnp.concatenate([xt, h], -1) @ w_rz + b_rz)
        r, z = jnp.split(rz, 2, axis=-1)
        hbar = jnp.tanh(jnp.concatenate([xt, r * h], -1) @ w_h + b_h)
        h_new = (1 - z) * h + z * hbar
        alive = (t < lengths)[:, None].astype(x.dtype)
        h_new = alive * h_new + (1 - alive) * h
        return (h_new, t + 1), h_new

    (h_last, _), hs = jax.lax.scan(step, (h0, 0), xs)
    ctx.set_output(op, "Out", jnp.swapaxes(hs, 0, 1))
    ctx.set_output(op, "LastH", h_last)


# ---------------------------------------------------------------------------
# sequence_ops long tail (reference operators/sequence_ops/*) — padded
# [B, T, ...] + Lengths convention throughout
# ---------------------------------------------------------------------------

def _seq_conv_infer(op, block):
    x = in_var(op, block, "X")                 # [B, T, D]
    w = in_var(op, block, "Filter")            # [ctx_len*D, M]
    set_out(op, block, "Out", (x.shape[0], x.shape[1], w.shape[1]),
            x.dtype)


@register_op("sequence_conv", infer=_seq_conv_infer)
def _sequence_conv(ctx, op):
    """Context-window conv over time (reference sequence_conv_op.cc):
    each step's feature is the flattened [context_length, D] window
    starting at t + context_start, matmul'd against Filter. Steps past
    Lengths are zeroed; the window never crosses a row's end (the
    reference's per-sequence im2col becomes a padded gather)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    w = ctx.get_input(op, "Filter")
    lengths = ctx.get_input(op, "Lengths")
    start = op.attr("context_start", 0)
    clen = op.attr("context_length")
    B, T, D = x.shape
    t_idx = jnp.arange(T)
    cols = []
    for j in range(clen):
        pos = t_idx + start + j                # source position per step
        valid = (pos >= 0) & (pos[None, :] < lengths[:, None])
        g = x[:, jnp.clip(pos, 0, T - 1)]      # [B, T, D]
        cols.append(jnp.where(valid[..., None], g, 0.0))
    win = jnp.concatenate(cols, axis=2)        # [B, T, clen*D]
    out = win @ w                              # [B, T, M]
    mask = (t_idx[None, :] < lengths[:, None])[..., None]
    ctx.set_output(op, "Out", out * mask.astype(out.dtype))


def _seq_expand_infer(op2, block):
    x = in_var(op2, block, "X")
    y = in_var(op2, block, "Y")
    set_out(op2, block, "Out", (x.shape[0], y.shape[1]) + tuple(
        x.shape[2:]), x.dtype)


@register_op("sequence_expand", infer=_seq_expand_infer)
def _sequence_expand(ctx, op):
    """reference sequence_expand_op.cc with ref_level=0, padded form:
    each row's single step (or [T=1] slice) is broadcast across the
    companion Y's valid steps."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # [B, Tx, ...]
    lengths = ctx.get_input(op, "YLengths")    # [B]
    maxlen = ctx.get_input(op, "Y").shape[1]
    first = x[:, 0]                            # [B, ...]
    out = jnp.broadcast_to(first[:, None],
                           (x.shape[0], maxlen) + first.shape[1:])
    mask = (jnp.arange(maxlen)[None, :] < lengths[:, None])
    m = mask.reshape(mask.shape + (1,) * (first.ndim - 1))
    ctx.set_output(op, "Out", out * m.astype(x.dtype))


def _seq_pad_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)
    set_out(op, block, "Length", (x.shape[0],), "int64")


@register_op("sequence_pad", infer=_seq_pad_infer)
def _sequence_pad(ctx, op):
    """reference sequence_pad_op.cc: under the repo's padded convention
    the data is already dense — the op re-pads the tail with PadValue
    and reports lengths (identity + mask, kept for API parity)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    lengths = ctx.get_input(op, "Lengths")
    pad_value = ctx.get_input(op, "PadValue").reshape(())
    T = x.shape[1]
    mask = (jnp.arange(T)[None, :] < lengths[:, None])
    m = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    ctx.set_output(op, "Out", jnp.where(m, x, pad_value.astype(x.dtype)))
    ctx.set_output(op, "Length", lengths.astype("int64"))


@register_op("sequence_unpad", infer=same_as_input())
def _sequence_unpad(ctx, op):
    """reference sequence_unpad_op.cc: inverse of sequence_pad. Fixed
    shapes mean the padding slots stay (zeroed) — downstream masked ops
    ignore them."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    lengths = ctx.get_input(op, "Lengths")
    T = x.shape[1]
    mask = (jnp.arange(T)[None, :] < lengths[:, None])
    m = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    ctx.set_output(op, "Out", x * m.astype(x.dtype))


def _seq_concat_infer(op, block):
    xs = [block.var(n) for n in op.input("X")]
    T = sum(x.shape[1] for x in xs)
    set_out(op, block, "Out", (xs[0].shape[0], T) + tuple(
        xs[0].shape[2:]), xs[0].dtype)


@register_op("sequence_concat", infer=_seq_concat_infer)
def _sequence_concat(ctx, op):
    """reference sequence_concat_op.cc: per-row concatenation of the
    VALID prefixes of each input, left-compacted into the output.
    One argsort-based stable compaction replaces the reference's
    per-sequence memcpy loop."""
    jnp = _jnp()
    xs = ctx.get_inputs(op, "X")
    lens = ctx.get_inputs(op, "Lengths")
    B = xs[0].shape[0]
    cat = jnp.concatenate(xs, axis=1)          # [B, sumT, ...]
    valid = jnp.concatenate(
        [jnp.arange(x.shape[1])[None, :] < l[:, None]
         for x, l in zip(xs, lens)], axis=1)   # [B, sumT]
    # stable sort: valid slots (0) before padding (1) preserves order
    order = jnp.argsort(jnp.where(valid, 0, 1), axis=1, stable=True)
    idx = order.reshape(order.shape + (1,) * (cat.ndim - 2))
    out = jnp.take_along_axis(cat, idx, axis=1)
    total = sum(l for l in lens)
    mask = (jnp.arange(cat.shape[1])[None, :] < total[:, None])
    m = mask.reshape(mask.shape + (1,) * (cat.ndim - 2))
    ctx.set_output(op, "Out", out * m.astype(out.dtype))


@register_op("sequence_slice", infer=same_as_input())
def _sequence_slice(ctx, op):
    """reference sequence_slice_op.cc: per-row [offset, offset+length)
    slice, left-aligned into the output with zero padding."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # [B, T, ...]
    offset = ctx.get_input(op, "Offset").reshape(-1)
    length = ctx.get_input(op, "Length").reshape(-1)
    T = x.shape[1]
    t_idx = jnp.arange(T)[None, :]
    src = jnp.clip(offset[:, None] + t_idx, 0, T - 1)
    g = jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)
    mask = t_idx < length[:, None]
    m = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    ctx.set_output(op, "Out", g * m.astype(x.dtype))


def _seq_erase_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)
    if op.output("OutLengths"):
        set_out(op, block, "OutLengths", (x.shape[0],), "int64")


@register_op("sequence_erase", infer=_seq_erase_infer, grad=None)
def _sequence_erase(ctx, op):
    """reference sequence_erase_op.cc: drop listed tokens, compact left,
    pad with zeros; emits updated lengths."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # [B, T] int
    lengths = ctx.get_input(op, "Lengths")
    tokens = op.attr("tokens", [])
    T = x.shape[1]
    t_idx = jnp.arange(T)[None, :]
    valid = t_idx < lengths[:, None]
    keep = valid
    for t in tokens:
        keep = keep & (x != t)
    order = jnp.argsort(jnp.where(keep, 0, 1), axis=1, stable=True)
    out = jnp.take_along_axis(x, order, axis=1)
    new_len = keep.sum(1)
    mask = t_idx < new_len[:, None]
    ctx.set_output(op, "Out", jnp.where(mask, out, 0))
    if op.output("OutLengths"):
        ctx.set_output(op, "OutLengths", new_len.astype("int64"))


def _seq_enum_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out",
            (x.shape[0], x.shape[1], op.attr("win_size")), x.dtype)


@register_op("sequence_enumerate", infer=_seq_enum_infer, grad=None)
def _sequence_enumerate(ctx, op):
    """reference sequence_enumerate_op.cc: sliding win_size windows per
    step, pad_value past each row's end."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # [B, T] int
    lengths = ctx.get_input(op, "Lengths")
    win = op.attr("win_size")
    pad = op.attr("pad_value", 0)
    T = x.shape[1]
    t_idx = jnp.arange(T)
    pos = t_idx[:, None] + jnp.arange(win)[None, :]      # [T, win]
    g = x[:, jnp.clip(pos, 0, T - 1)]                    # [B, T, win]
    valid = pos[None] < lengths[:, None, None]
    ctx.set_output(op, "Out", jnp.where(valid, g, pad))
