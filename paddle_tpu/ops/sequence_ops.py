"""Masked/dense sequence ops + TensorArray ops.

TPU-native replacement for the reference's LoD machinery
(operators/sequence_ops/, 6,158 LoC; framework LoDTensor ragged rows):
a "sequence batch" here is a dense [B, T, ...] tensor + an int lengths
vector [B] — the bucketed/masked representation (SURVEY.md §7 hard part
(a)).  Every sequence_* op takes the lengths through a second input slot
and masks accordingly; XLA sees only static shapes.

TensorArray (framework.proto LOD_TENSOR_ARRAY + operators/
tensor_array_read_write ops): a fixed-capacity ring of slots backed by
one dense buffer [cap, *item] so writes/reads are dynamic_update_slice /
dynamic_index — scan/while-carry compatible and differentiable.
"""
from __future__ import annotations

import numpy as np

from .registry import (LowerContext, in_var, register_op, same_as_input,
                       set_out)


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# sequence mask / pool / softmax / reverse / expand / concat
# ---------------------------------------------------------------------------
def _seq_mask_infer(op, block):
    x = in_var(op, block, "X")
    maxlen = op.attrs.get("maxlen", -1)
    b = x.shape[0] if x.shape else -1
    set_out(op, block, "Y", (b, maxlen if maxlen > 0 else -1),
            op.attrs.get("out_dtype", "float32"))


@register_op("sequence_mask", infer=_seq_mask_infer, grad=None)
def _sequence_mask(ctx, op):
    jnp = _jnp()
    lengths = ctx.get_input(op, "X")
    maxlen = op.attr("maxlen", -1)
    if maxlen <= 0:
        raise ValueError("sequence_mask needs a static maxlen on TPU")
    dtype = op.attr("out_dtype", "float32")
    mask = jnp.arange(maxlen)[None, :] < lengths[:, None]
    ctx.set_output(op, "Y", mask.astype(dtype))


def _pool_infer(op, block):
    x = in_var(op, block, "X")  # [B, T, ...]
    set_out(op, block, "Out", (x.shape[0],) + tuple(x.shape[2:]), x.dtype)


@register_op("sequence_pool", infer=_pool_infer)
def _sequence_pool(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")          # [B, T, ...]
    lengths = ctx.get_input(op, "Lengths")
    T = x.shape[1]
    mask = (jnp.arange(T)[None, :] < lengths[:, None])
    mshape = mask.shape + (1,) * (x.ndim - 2)
    m = mask.reshape(mshape).astype(x.dtype)
    pool = op.attr("pool_type", "average").lower()
    if pool in ("average", "avg", "mean"):
        denom = jnp.maximum(lengths.astype(x.dtype), 1).reshape(
            (-1,) + (1,) * (x.ndim - 2))
        out = (x * m).sum(axis=1) / denom
    elif pool == "sum":
        out = (x * m).sum(axis=1)
    elif pool == "sqrt":
        denom = jnp.sqrt(jnp.maximum(lengths.astype(x.dtype), 1)).reshape(
            (-1,) + (1,) * (x.ndim - 2))
        out = (x * m).sum(axis=1) / denom
    elif pool == "max":
        neg = jnp.finfo(x.dtype).min if jnp.issubdtype(
            x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = jnp.where(m > 0, x, neg).max(axis=1)
    elif pool == "last":
        idx = jnp.maximum(lengths - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)).astype("int32"),
            axis=1).squeeze(1)
    elif pool == "first":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pool_type {pool!r}")
    ctx.set_output(op, "Out", out)


@register_op("sequence_softmax", infer=same_as_input())
def _sequence_softmax(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")          # [B, T]
    lengths = ctx.get_input(op, "Lengths")
    mask = jnp.arange(x.shape[1])[None, :] < lengths[:, None]
    neg = jnp.asarray(-1e30, x.dtype)
    z = jnp.where(mask, x, neg)
    z = z - z.max(axis=1, keepdims=True)
    e = jnp.exp(z) * mask.astype(x.dtype)
    ctx.set_output(op, "Out",
                   e / jnp.maximum(e.sum(axis=1, keepdims=True), 1e-30))


@register_op("sequence_reverse", infer=same_as_input())
def _sequence_reverse(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")          # [B, T, ...]
    lengths = ctx.get_input(op, "Lengths")
    T = x.shape[1]
    pos = jnp.arange(T)[None, :]
    # position i maps to (len-1-i) inside the sequence; padding stays
    src = jnp.where(pos < lengths[:, None],
                    lengths[:, None] - 1 - pos, pos)
    out = jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)).astype("int32"),
        axis=1)
    ctx.set_output(op, "Out", out)


def _seq_expand_infer(op, block):
    x = in_var(op, block, "X")          # [B, ...]
    times = op.attrs.get("maxlen", -1)
    set_out(op, block, "Out", (x.shape[0], times) + tuple(x.shape[1:]),
            x.dtype)


@register_op("sequence_expand_as", infer=_seq_expand_infer)
def _sequence_expand_as(ctx, op):
    """Broadcast a per-sequence vector across its (masked) time steps."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")          # [B, ...]
    lengths = ctx.get_input(op, "Lengths")
    maxlen = op.attr("maxlen")
    mask = (jnp.arange(maxlen)[None, :] < lengths[:, None])
    out = jnp.broadcast_to(x[:, None], (x.shape[0], maxlen) + x.shape[1:])
    m = mask.reshape(mask.shape + (1,) * (x.ndim - 1)).astype(x.dtype)
    ctx.set_output(op, "Out", out * m)


# ---------------------------------------------------------------------------
# TensorArray
# ---------------------------------------------------------------------------
def _wta_infer(op, block):
    arr = in_var(op, block, "Array")
    set_out(op, block, "Out", arr.shape, arr.dtype)


@register_op("write_to_array", infer=_wta_infer)
def _write_to_array(ctx, op):
    import jax
    jnp = _jnp()
    arr = ctx.get_input(op, "Array")    # [cap, *item]
    x = ctx.get_input(op, "X")
    i = ctx.get_input(op, "I")
    i = jnp.reshape(i, ()).astype("int32")
    ctx.set_output(op, "Out", jax.lax.dynamic_update_index_in_dim(
        arr, x.astype(arr.dtype), i, 0))


def _rfa_infer(op, block):
    arr = in_var(op, block, "Array")
    set_out(op, block, "Out", tuple(arr.shape[1:]), arr.dtype)


@register_op("read_from_array", infer=_rfa_infer)
def _read_from_array(ctx, op):
    import jax
    jnp = _jnp()
    arr = ctx.get_input(op, "Array")
    i = jnp.reshape(ctx.get_input(op, "I"), ()).astype("int32")
    ctx.set_output(op, "Out",
                   jax.lax.dynamic_index_in_dim(arr, i, 0,
                                                keepdims=False))


# ---------------------------------------------------------------------------
# recurrent cells: lstm / gru over time (lax.scan)
# ---------------------------------------------------------------------------
def _rnn_infer(op, block):
    x = in_var(op, block, "X")          # [B, T, D]
    hid = op.attrs["hidden_size"]
    set_out(op, block, "Out", (x.shape[0], x.shape[1], hid), x.dtype)
    set_out(op, block, "LastH", (x.shape[0], hid), x.dtype)
    if op.output("LastC"):
        set_out(op, block, "LastC", (x.shape[0], hid), x.dtype)


@register_op("lstm_rnn", infer=_rnn_infer)
def _lstm_rnn(ctx, op):
    """Single-layer LSTM over [B,T,D]; lengths mask freezes state past
    each sequence's end.  Reference: cudnn_lstm_op / layers/rnn.py —
    here one lax.scan whose per-step math is a fused [D+H, 4H] matmul.
    """
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    w = ctx.get_input(op, "W")          # [D+H, 4H]
    b = ctx.get_input(op, "B")          # [4H]
    lengths = ctx.get_input(op, "Lengths")
    H = op.attr("hidden_size")
    B = x.shape[0]
    h0 = jnp.zeros((B, H), x.dtype)
    c0 = jnp.zeros((B, H), x.dtype)
    xs = jnp.swapaxes(x, 0, 1)          # [T, B, D]

    def step(carry, inp):
        h, c, t = carry
        xt = inp
        z = jnp.concatenate([xt, h], axis=-1) @ w + b
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        alive = (t < lengths)[:, None].astype(x.dtype)
        h_new = alive * h_new + (1 - alive) * h
        c_new = alive * c_new + (1 - alive) * c
        return (h_new, c_new, t + 1), h_new

    (h_last, c_last, _), hs = jax.lax.scan(step, (h0, c0, 0), xs)
    ctx.set_output(op, "Out", jnp.swapaxes(hs, 0, 1))
    ctx.set_output(op, "LastH", h_last)
    ctx.set_output(op, "LastC", c_last)


@register_op("gru_rnn", infer=_rnn_infer)
def _gru_rnn(ctx, op):
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    w = ctx.get_input(op, "W")          # [D+H, 3H]
    b = ctx.get_input(op, "B")          # [3H]
    lengths = ctx.get_input(op, "Lengths")
    H = op.attr("hidden_size")
    B = x.shape[0]
    h0 = jnp.zeros((B, H), x.dtype)
    xs = jnp.swapaxes(x, 0, 1)
    w_rz, w_h = w[:, :2 * H], w[:, 2 * H:]
    b_rz, b_h = b[:2 * H], b[2 * H:]

    def step(carry, xt):
        h, t = carry
        rz = jax.nn.sigmoid(jnp.concatenate([xt, h], -1) @ w_rz + b_rz)
        r, z = jnp.split(rz, 2, axis=-1)
        hbar = jnp.tanh(jnp.concatenate([xt, r * h], -1) @ w_h + b_h)
        h_new = (1 - z) * h + z * hbar
        alive = (t < lengths)[:, None].astype(x.dtype)
        h_new = alive * h_new + (1 - alive) * h
        return (h_new, t + 1), h_new

    (h_last, _), hs = jax.lax.scan(step, (h0, 0), xs)
    ctx.set_output(op, "Out", jnp.swapaxes(hs, 0, 1))
    ctx.set_output(op, "LastH", h_last)
