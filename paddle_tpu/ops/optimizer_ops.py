"""Optimizer op lowerings: sgd/momentum/adam/adamw/adagrad/rmsprop/lamb/...

Replaces the reference optimizer kernels (operators/optimizers/*.cc/.cu:
sgd_op, momentum_op, adam_op, adamax_op, adagrad_op, adadelta_op,
rmsprop_op, ftrl_op, lamb_op, lars_momentum_op, dgc_momentum_op).  Each is
a pure update function over (param, grad, state) -> (param', state'); the
Executor threads the state through the single compiled step function, so
"in-place param update" becomes a donated-buffer rebind, which XLA turns
into a true in-place update on TPU HBM.

All are registered grad=None (optimize-role ops are never differentiated)
and declare their aliased outputs via `stateful_outputs`.
"""
from __future__ import annotations

import numpy as np

from ..framework.core import Block, Operator
from .registry import LowerContext, in_var, register_op, set_out


def _jnp():
    import jax.numpy as jnp
    return jnp


def _opt_infer(*alias_pairs):
    """Outputs mirror the shape/dtype of the aliased input slot."""
    def infer(op: Operator, block: Block):
        for out_slot, in_slot in alias_pairs:
            if not op.output(out_slot):
                continue
            src = in_var(op, block, in_slot)
            set_out(op, block, out_slot, src.shape, src.dtype)
    return infer


def _reg_opt(op_type, alias_pairs, lower):
    register_op(op_type, infer=_opt_infer(*alias_pairs), lower=lower,
                grad=None,
                stateful_outputs=tuple(p[0] for p in alias_pairs))


# ---------------------------------------------------------------------------

def _sgd(ctx: LowerContext, op: Operator):
    from ..framework.selected_rows import is_selected_rows

    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad")
    lr = ctx.get_input(op, "LearningRate")
    if is_selected_rows(g):
        # reference sgd_op.h:73 SelectedRows branch: scatter-update the
        # touched rows only — O(K*cols), no [height, cols] grad exists
        m = g.merge()
        upd = (lr * m.values.astype(p.dtype))
        ctx.set_output(op, "ParamOut",
                       p.at[m.rows].add(-upd, mode="drop"))
        return
    ctx.set_output(op, "ParamOut", p - lr * g.astype(p.dtype))


_reg_opt("sgd", [("ParamOut", "Param")], _sgd)


def _momentum(ctx, op):
    from ..framework.selected_rows import is_selected_rows

    jnp = _jnp()
    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad")
    if is_selected_rows(g):
        # reference momentum_op.h:287 SparseMomentumFunctor is
        # dense-equivalent: every row updates with g=0 for untouched
        # rows (velocity decays everywhere) — densify is exact
        g = g.to_dense()
    g = g.astype(p.dtype)
    v = ctx.get_input(op, "Velocity")
    lr = ctx.get_input(op, "LearningRate")
    mu = op.attr("mu", 0.9)
    decay = op.attr("regularization_coeff", 0.0)
    if op.attr("regularization_method", "") == "l2_decay":
        g = g + decay * p
    v_new = mu * v + g
    if op.attr("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    ctx.set_output(op, "ParamOut", p_new)
    ctx.set_output(op, "VelocityOut", v_new)


_reg_opt("momentum", [("ParamOut", "Param"), ("VelocityOut", "Velocity")],
         _momentum)


def _adam_infer(op, block):
    _opt_infer(("ParamOut", "Param"), ("Moment1Out", "Moment1"),
               ("Moment2Out", "Moment2"), ("Beta1PowOut", "Beta1Pow"),
               ("Beta2PowOut", "Beta2Pow"))(op, block)


def _adam(ctx: LowerContext, op: Operator):
    from ..framework.selected_rows import is_selected_rows

    jnp = _jnp()
    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad")
    if is_selected_rows(g):
        if op.attr("lazy_mode", False):
            return _adam_sparse_lazy(ctx, op, g)
        # reference adam_op.h:269 lazy_mode=false: dense-equivalent
        # (every row updates, g=0 for untouched rows)
        g = g.to_dense()
    g = g.astype("float32")
    m1 = ctx.get_input(op, "Moment1")
    m2 = ctx.get_input(op, "Moment2")
    b1p = ctx.get_input(op, "Beta1Pow")
    b2p = ctx.get_input(op, "Beta2Pow")
    lr = ctx.get_input(op, "LearningRate")
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    if op.single_input("Beta1Tensor"):
        b1 = ctx.get_input(op, "Beta1Tensor")
    if op.single_input("Beta2Tensor"):
        b2 = ctx.get_input(op, "Beta2Tensor")
    eps = op.attr("epsilon", 1e-8)

    if op.type == "adamw":
        # decoupled weight decay (AdamW): param scaled before update
        coeff = op.attr("coeff", 0.01)
        if not op.attr("with_decay", True):
            coeff = 0.0
        p = p * (1.0 - lr * coeff)

    pf = p.astype("float32")
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * g * g
    # reference adam_op.h: lr_t = lr * sqrt(1-b2^t) / (1-b1^t)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    pf = pf - lr_t * m1n / (jnp.sqrt(m2n) + eps * jnp.sqrt(1 - b2p))
    ctx.set_output(op, "ParamOut", pf.astype(p.dtype))
    ctx.set_output(op, "Moment1Out", m1n)
    ctx.set_output(op, "Moment2Out", m2n)
    ctx.set_output(op, "Beta1PowOut", b1p * b1)
    ctx.set_output(op, "Beta2PowOut", b2p * b2)


def _adam_sparse_lazy(ctx: LowerContext, op: Operator, sr):
    """reference adam_op.h:269 lazy_mode=true: only touched rows update
    param AND moments — O(K*cols) gather/update/scatter."""
    jnp = _jnp()
    p = ctx.get_input(op, "Param")
    m1 = ctx.get_input(op, "Moment1")
    m2 = ctx.get_input(op, "Moment2")
    b1p = ctx.get_input(op, "Beta1Pow")
    b2p = ctx.get_input(op, "Beta2Pow")
    lr = ctx.get_input(op, "LearningRate")
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    if op.single_input("Beta1Tensor"):
        b1 = ctx.get_input(op, "Beta1Tensor")
    if op.single_input("Beta2Tensor"):
        b2 = ctx.get_input(op, "Beta2Tensor")
    eps = op.attr("epsilon", 1e-8)
    if op.type == "adamw":
        coeff = op.attr("coeff", 0.01)
        if not op.attr("with_decay", True):
            coeff = 0.0
        # decoupled decay is a dense param scale — sparse rows only
        # would silently skip decay on untouched rows
        p = p * (1.0 - lr * coeff)

    m = sr.merge()
    rows = m.rows
    g = m.values.astype("float32")
    # duplicate-merged sentinel rows carry zero values; their gathered
    # row updates are no-ops numerically and 'drop' discards them
    m1r = m1.at[rows].get(mode="fill", fill_value=0.0)
    m2r = m2.at[rows].get(mode="fill", fill_value=0.0)
    pr = p.at[rows].get(mode="fill", fill_value=0.0).astype("float32")
    m1n = b1 * m1r + (1 - b1) * g
    m2n = b2 * m2r + (1 - b2) * g * g
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    prn = pr - lr_t * m1n / (jnp.sqrt(m2n) + eps * jnp.sqrt(1 - b2p))
    # post-merge rows are unique; sentinel (out-of-range) slots are
    # dropped by the scatter, so the writes below touch K real rows
    ctx.set_output(op, "ParamOut",
                   p.at[rows].set(prn.astype(p.dtype), mode="drop"))
    ctx.set_output(op, "Moment1Out", m1.at[rows].set(m1n, mode="drop"))
    ctx.set_output(op, "Moment2Out", m2.at[rows].set(m2n, mode="drop"))
    ctx.set_output(op, "Beta1PowOut", b1p * b1)
    ctx.set_output(op, "Beta2PowOut", b2p * b2)


for _t in ("adam", "adamw"):
    register_op(_t, infer=_adam_infer, lower=_adam, grad=None,
                stateful_outputs=("ParamOut", "Moment1Out", "Moment2Out",
                                  "Beta1PowOut", "Beta2PowOut"))


def _adamax(ctx, op):
    jnp = _jnp()
    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad").astype("float32")
    m = ctx.get_input(op, "Moment")
    inf_norm = ctx.get_input(op, "InfNorm")
    b1p = ctx.get_input(op, "Beta1Pow")
    lr = ctx.get_input(op, "LearningRate")
    b1, b2 = op.attr("beta1", 0.9), op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-8)
    mn = b1 * m + (1 - b1) * g
    inf_n = jnp.maximum(b2 * inf_norm, jnp.abs(g))
    p_new = p.astype("float32") - (lr / (1 - b1p)) * (mn / (inf_n + eps))
    ctx.set_output(op, "ParamOut", p_new.astype(p.dtype))
    ctx.set_output(op, "MomentOut", mn)
    ctx.set_output(op, "InfNormOut", inf_n)


_reg_opt("adamax", [("ParamOut", "Param"), ("MomentOut", "Moment"),
                    ("InfNormOut", "InfNorm")], _adamax)


def _adagrad(ctx, op):
    from ..framework.selected_rows import is_selected_rows

    jnp = _jnp()
    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad")
    m = ctx.get_input(op, "Moment")
    lr = ctx.get_input(op, "LearningRate")
    eps = op.attr("epsilon", 1e-6)
    if is_selected_rows(g):
        # reference adagrad_op.h SelectedRows branch: merge, then update
        # moment+param on the touched rows only
        mg = g.merge()
        rows, gv = mg.rows, mg.values.astype("float32")
        mr = m.at[rows].get(mode="fill", fill_value=0.0)
        pr = p.at[rows].get(mode="fill", fill_value=0.0).astype("float32")
        mn = mr + gv * gv
        prn = pr - lr * gv / (jnp.sqrt(mn) + eps)
        ctx.set_output(op, "ParamOut",
                       p.at[rows].set(prn.astype(p.dtype), mode="drop"))
        ctx.set_output(op, "MomentOut",
                       m.at[rows].set(mn, mode="drop"))
        return
    g = g.astype("float32")
    mn = m + g * g
    p_new = p.astype("float32") - lr * g / (jnp.sqrt(mn) + eps)
    ctx.set_output(op, "ParamOut", p_new.astype(p.dtype))
    ctx.set_output(op, "MomentOut", mn)


_reg_opt("adagrad", [("ParamOut", "Param"), ("MomentOut", "Moment")],
         _adagrad)


def _adadelta(ctx, op):
    jnp = _jnp()
    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad").astype("float32")
    avg_sq = ctx.get_input(op, "AvgSquaredGrad")
    avg_upd = ctx.get_input(op, "AvgSquaredUpdate")
    rho = op.attr("rho", 0.95)
    eps = op.attr("epsilon", 1e-6)
    avg_sq_n = rho * avg_sq + (1 - rho) * g * g
    upd = -jnp.sqrt((avg_upd + eps) / (avg_sq_n + eps)) * g
    avg_upd_n = rho * avg_upd + (1 - rho) * upd * upd
    ctx.set_output(op, "ParamOut", (p.astype("float32") + upd).astype(p.dtype))
    ctx.set_output(op, "AvgSquaredGradOut", avg_sq_n)
    ctx.set_output(op, "AvgSquaredUpdateOut", avg_upd_n)


_reg_opt("adadelta", [("ParamOut", "Param"),
                      ("AvgSquaredGradOut", "AvgSquaredGrad"),
                      ("AvgSquaredUpdateOut", "AvgSquaredUpdate")], _adadelta)


def _rmsprop(ctx, op):
    jnp = _jnp()
    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad").astype("float32")
    ms = ctx.get_input(op, "MeanSquare")
    mom = ctx.get_input(op, "Moment")
    lr = ctx.get_input(op, "LearningRate")
    rho = op.attr("decay", 0.9)
    eps = op.attr("epsilon", 1e-10)
    momentum = op.attr("momentum", 0.0)
    centered = op.attr("centered", False)
    ms_n = rho * ms + (1 - rho) * g * g
    if centered:
        mg = ctx.get_input(op, "MeanGrad")
        mg_n = rho * mg + (1 - rho) * g
        denom = ms_n - mg_n * mg_n + eps
        ctx.set_output(op, "MeanGradOut", mg_n)
    else:
        denom = ms_n + eps
    mom_n = momentum * mom + lr * g / jnp.sqrt(denom)
    ctx.set_output(op, "ParamOut",
                   (p.astype("float32") - mom_n).astype(p.dtype))
    ctx.set_output(op, "MeanSquareOut", ms_n)
    ctx.set_output(op, "MomentOut", mom_n)


_reg_opt("rmsprop", [("ParamOut", "Param"), ("MeanSquareOut", "MeanSquare"),
                     ("MomentOut", "Moment"), ("MeanGradOut", "MeanGrad")],
         _rmsprop)


def _lamb(ctx, op):
    jnp = _jnp()
    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad").astype("float32")
    m1 = ctx.get_input(op, "Moment1")
    m2 = ctx.get_input(op, "Moment2")
    b1p = ctx.get_input(op, "Beta1Pow")
    b2p = ctx.get_input(op, "Beta2Pow")
    lr = ctx.get_input(op, "LearningRate")
    b1, b2 = op.attr("beta1", 0.9), op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-6)
    wd = op.attr("weight_decay", 0.01)
    pf = p.astype("float32")
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * g * g
    m1h = m1n / (1 - b1p)
    m2h = m2n / (1 - b2p)
    r = m1h / (jnp.sqrt(m2h) + eps) + wd * pf
    p_norm = jnp.linalg.norm(pf)
    r_norm = jnp.linalg.norm(r)
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    pf = pf - lr * trust * r
    ctx.set_output(op, "ParamOut", pf.astype(p.dtype))
    ctx.set_output(op, "Moment1Out", m1n)
    ctx.set_output(op, "Moment2Out", m2n)
    ctx.set_output(op, "Beta1PowOut", b1p * b1)
    ctx.set_output(op, "Beta2PowOut", b2p * b2)


_reg_opt("lamb", [("ParamOut", "Param"), ("Moment1Out", "Moment1"),
                  ("Moment2Out", "Moment2"), ("Beta1PowOut", "Beta1Pow"),
                  ("Beta2PowOut", "Beta2Pow")], _lamb)


def _lars_momentum(ctx, op):
    jnp = _jnp()
    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad").astype("float32")
    v = ctx.get_input(op, "Velocity")
    lr = ctx.get_input(op, "LearningRate")
    mu = op.attr("mu", 0.9)
    coeff = op.attr("lars_coeff", 0.001)
    decay = op.attr("lars_weight_decay", 0.0005)
    eps = op.attr("epsilon", 0.0)
    pf = p.astype("float32")
    p_norm = jnp.linalg.norm(pf)
    g_norm = jnp.linalg.norm(g)
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * coeff * p_norm / (g_norm + decay * p_norm + eps), lr)
    v_new = mu * v + local_lr * (g + decay * pf)
    ctx.set_output(op, "ParamOut", (pf - v_new).astype(p.dtype))
    ctx.set_output(op, "VelocityOut", v_new)


_reg_opt("lars_momentum", [("ParamOut", "Param"),
                           ("VelocityOut", "Velocity")], _lars_momentum)


def _ftrl(ctx, op):
    jnp = _jnp()
    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad").astype("float32")
    sq = ctx.get_input(op, "SquaredAccumulator")
    lin = ctx.get_input(op, "LinearAccumulator")
    lr = ctx.get_input(op, "LearningRate")
    l1 = op.attr("l1", 0.0)
    l2 = op.attr("l2", 0.0)
    power = op.attr("lr_power", -0.5)
    pf = p.astype("float32")
    new_sq = sq + g * g
    sigma = (jnp.power(new_sq, -power) - jnp.power(sq, -power)) / lr
    new_lin = lin + g - sigma * pf
    x = jnp.clip(new_lin, -l1, l1) - new_lin
    y = jnp.power(new_sq, -power) / lr + 2 * l2
    p_new = jnp.where(jnp.abs(new_lin) > l1, x / y, 0.0)
    ctx.set_output(op, "ParamOut", p_new.astype(p.dtype))
    ctx.set_output(op, "SquaredAccumOut", new_sq)
    ctx.set_output(op, "LinearAccumOut", new_lin)


_reg_opt("ftrl", [("ParamOut", "Param"),
                  ("SquaredAccumOut", "SquaredAccumulator"),
                  ("LinearAccumOut", "LinearAccumulator")], _ftrl)


def _dpsgd(ctx, op):
    """Differentially-private SGD (reference operators/optimizers/dpsgd_op.h):
    clip grad to clip-norm, add gaussian noise scaled by sigma."""
    import jax
    jnp = _jnp()
    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad").astype("float32")
    lr = ctx.get_input(op, "LearningRate")
    clip = op.attr("clip", 10.0)
    sigma = op.attr("sigma", 1.0)
    batch_size = op.attr("batch_size", 16.0)
    g_norm = jnp.linalg.norm(g)
    scale = jnp.minimum(1.0, clip / (g_norm + 1e-12))
    noise = jax.random.normal(ctx.rng(op), jnp.shape(g)) * sigma * clip
    g_priv = (g * scale + noise) / batch_size
    ctx.set_output(op, "ParamOut",
                   (p.astype("float32") - lr * g_priv).astype(p.dtype))


_reg_opt("dpsgd", [("ParamOut", "Param")], _dpsgd)


def _proximal_gd(ctx, op):
    jnp = _jnp()
    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad").astype("float32")
    lr = ctx.get_input(op, "LearningRate")
    l1, l2 = op.attr("l1", 0.0), op.attr("l2", 0.0)
    prox = p.astype("float32") - lr * g
    p_new = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
             / (1.0 + lr * l2))
    ctx.set_output(op, "ParamOut", p_new.astype(p.dtype))


_reg_opt("proximal_gd", [("ParamOut", "Param")], _proximal_gd)


def _decayed_adagrad(ctx, op):
    jnp = _jnp()
    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad").astype("float32")
    m = ctx.get_input(op, "Moment")
    lr = ctx.get_input(op, "LearningRate")
    decay = op.attr("decay", 0.95)
    eps = op.attr("epsilon", 1e-6)
    mn = decay * m + (1 - decay) * g * g
    p_new = p.astype("float32") - lr * g / (jnp.sqrt(mn) + eps)
    ctx.set_output(op, "ParamOut", p_new.astype(p.dtype))
    ctx.set_output(op, "MomentOut", mn)


_reg_opt("decayed_adagrad", [("ParamOut", "Param"), ("MomentOut", "Moment")],
         _decayed_adagrad)


def _average_accumulates(ctx, op):
    """Sliding-window parameter accumulation for ModelAverage.

    Reference semantics (operators/average_accumulates_op.h, driven by
    fluid/optimizer.py:3134 ModelAverage):
        num_updates += 1; num_accumulates += 1; sum_1 += param
        if num_updates % max_acc == 0: sum_2 += sum_1; sum_1 = 0
        if num_accumulates >= max_average_window
           or num_accumulates >= num_updates * average_window_rate (once
           past min_average_window):
            sum_3 = sum_1 + sum_2; sum_1 = sum_2 = 0
            old_num_accumulates = num_accumulates; num_accumulates = 0
    The scalar branches become jnp.where selects — fully fused by XLA.
    """
    jnp = _jnp()
    p = ctx.get_input(op, "Param").astype("float32")
    s1 = ctx.get_input(op, "InSum1")
    s2 = ctx.get_input(op, "InSum2")
    s3 = ctx.get_input(op, "InSum3")
    n_acc = ctx.get_input(op, "InNumAccumulates")
    old_n = ctx.get_input(op, "InOldNumAccumulates")
    n_upd = ctx.get_input(op, "InNumUpdates")

    avg_rate = op.attr("average_window", 0.0)
    max_win = op.attr("max_average_window", 2 ** 31 - 1)
    min_win = op.attr("min_average_window", 10000)
    max_acc = 16384  # kMaxNumAccumulates in the reference kernel

    n_upd = n_upd + 1
    n_acc = n_acc + 1
    s1 = s1 + p

    spill = (n_upd % max_acc) == 0
    s2 = jnp.where(spill, s2 + s1, s2)
    s1 = jnp.where(spill, jnp.zeros_like(s1), s1)

    window = jnp.maximum(
        jnp.minimum(jnp.asarray(float(max_win), "float32"),
                    n_upd.astype("float32") * avg_rate),
        float(min_win))
    rotate = n_acc.astype("float32") >= window
    s3 = jnp.where(rotate, s1 + s2, s3)
    s1 = jnp.where(rotate, jnp.zeros_like(s1), s1)
    s2 = jnp.where(rotate, jnp.zeros_like(s2), s2)
    old_n = jnp.where(rotate, n_acc, old_n)
    n_acc = jnp.where(rotate, jnp.zeros_like(n_acc), n_acc)

    ctx.set_output(op, "OutSum1", s1)
    ctx.set_output(op, "OutSum2", s2)
    ctx.set_output(op, "OutSum3", s3)
    ctx.set_output(op, "OutNumAccumulates", n_acc)
    ctx.set_output(op, "OutOldNumAccumulates", old_n)
    ctx.set_output(op, "OutNumUpdates", n_upd)


_reg_opt("average_accumulates",
         [("OutSum1", "InSum1"), ("OutSum2", "InSum2"),
          ("OutSum3", "InSum3"),
          ("OutNumAccumulates", "InNumAccumulates"),
          ("OutOldNumAccumulates", "InOldNumAccumulates"),
          ("OutNumUpdates", "InNumUpdates")],
         _average_accumulates)


def _proximal_adagrad(ctx, op):
    """Reference operators/optimizers/proximal_adagrad_op.h: adagrad
    moment accumulation then the proximal l1/l2 shrink step."""
    jnp = _jnp()
    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad").astype("float32")
    m = ctx.get_input(op, "Moment")
    lr = ctx.get_input(op, "LearningRate")
    l1, l2 = op.attr("l1", 0.0), op.attr("l2", 0.0)
    m_new = m + g * g
    lr_eff = lr / jnp.sqrt(m_new)
    prox = p.astype("float32") - lr_eff * g
    p_new = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr_eff * l1,
                                          0.0)
             / (1.0 + lr_eff * l2))
    ctx.set_output(op, "ParamOut", p_new.astype(p.dtype))
    ctx.set_output(op, "MomentOut", m_new)


_reg_opt("proximal_adagrad", [("ParamOut", "Param"),
                              ("MomentOut", "Moment")],
         _proximal_adagrad)
