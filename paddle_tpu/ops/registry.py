"""Operator registry: shape inference + JAX lowering + grad derivation.

TPU-native replacement for the reference's op registry / kernel-dispatch
machinery (framework/op_registry.h:101,256; framework/operator.cc:1017,1141).

Architectural inversion: the reference keeps a global (op, place, dtype,
layout) -> kernel map consulted at *every step* per op.  Here each op type
registers:

  * ``infer``  -- compile-time shape/dtype inference (reference InferShape),
                  run at op-append time so graphs carry static shapes.
  * ``lower``  -- a pure function from a LowerContext (name->traced jax value
                  environment) to output values.  The Executor composes the
                  lowerings of a whole block into ONE function traced by JAX
                  and compiled by XLA; kernel selection / data transfer /
                  per-op dispatch all disappear into the compiler.
  * ``grad``   -- how to build the backward ops for framework.backward:
                  'auto' (default) emits a generic ``<type>_grad`` op whose
                  lowering computes jax.vjp of the forward lowering (XLA CSE
                  removes the recomputation); a callable builds custom grad
                  op descs (used where semantics demand it, e.g. ops whose
                  grad must reuse a saved random mask).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..errors import (NotFoundError, UnimplementedError,
                      op_error_context)
from ..framework.core import (Block, Operator, Variable, convert_dtype,
                              dtype_to_np, grad_var_name)

__all__ = [
    "OpDef", "register_op", "get_op_def", "infer_op_shape", "LowerContext",
    "lower_op", "all_registered_ops",
]


class LowerContext:
    """Environment for lowering a block: var name -> traced JAX value.

    Also carries the PRNG base key (TPU-first randomness: stateless
    counter-based keys folded per-op, replacing the reference's cuRAND
    stateful generators) and the mesh/test-mode flags.
    """

    def __init__(self, block: Block, env: Dict[str, Any], base_key=None,
                 is_test: bool = False, mesh=None, amp=None):
        self.block = block
        self.env = env
        self.base_key = base_key
        self.is_test = is_test
        self.mesh = mesh
        # amp: None or {"dtype": "bfloat16", "white": set, "black": set} —
        # lowering-level autocast (see _lower_with_amp). The reference
        # rewrites the ProgramDesc to fp16 (contrib/mixed_precision/
        # fp16_utils.py:193 rewrite_program); casting at lowering time is
        # equivalent under XLA (casts fuse into the matmul/conv kernels)
        # and keeps fp32 master params in the scope for free.
        self.amp = amp

    def get(self, name: str):
        if name not in self.env:
            raise KeyError(
                f"variable {name!r} has no value during lowering; "
                f"known: {sorted(self.env)[:20]}...")
        return self.env[name]

    def get_input(self, op: Operator, slot: str):
        name = op.single_input(slot)
        return None if name is None else self.get(name)

    def get_inputs(self, op: Operator, slot: str) -> List[Any]:
        return [self.get(n) for n in op.input(slot)]

    def set(self, name: str, value):
        self.env[name] = value

    def set_output(self, op: Operator, slot: str, value):
        name = op.single_output(slot)
        if name is not None:
            self.env[name] = value

    def set_outputs(self, op: Operator, slot: str, values: Sequence[Any]):
        for n, v in zip(op.output(slot), values):
            self.env[n] = v

    def rng(self, op: Operator):
        """Deterministic per-op PRNG key.

        Folds the op's build-time seed id into the step key so that
        re-lowering the same op (e.g. inside its auto-derived grad's vjp
        recomputation) yields the *same* randomness -- this is what makes
        'auto' grads of stochastic ops (dropout) correct.
        """
        import jax
        if self.base_key is None:
            raise RuntimeError("no PRNG key available in this context")
        return jax.random.fold_in(self.base_key, op.attr("__op_seed__", 0))

    def var_shape(self, name: str):
        return self.block.var(name).shape

    def var_dtype(self, name: str):
        return self.block.var(name).dtype


class OpDef:
    def __init__(self, type: str,
                 infer: Optional[Callable[[Operator, Block], None]] = None,
                 lower: Optional[Callable[[LowerContext, Operator], None]] = None,
                 grad=None,
                 stateful_outputs: Sequence[str] = ()):
        self.type = type
        self.infer = infer
        self.lower = lower
        # grad: None = non-differentiable; 'auto' = vjp of forward lowering;
        # callable(fwd_op, block, helper) -> list of grad op specs.
        self.grad = grad
        # output slots aliasing an input (in-place update semantics, e.g.
        # optimizer ParamOut); informs executors which vars are state.
        self.stateful_outputs = tuple(stateful_outputs)


_REGISTRY: Dict[str, OpDef] = {}


def register_op(type: str, *, infer=None, lower=None, grad="auto",
                stateful_outputs=()):
    """Register an op type.  Usable directly or as a decorator on `lower`."""
    if lower is None:
        def deco(fn):
            register_op(type, infer=infer, lower=fn, grad=grad,
                        stateful_outputs=stateful_outputs)
            return fn
        return deco
    _REGISTRY[type] = OpDef(type, infer=infer, lower=lower, grad=grad,
                            stateful_outputs=stateful_outputs)
    return _REGISTRY[type]


def get_op_def(type: str) -> OpDef:
    if type not in _REGISTRY:
        raise KeyError(f"op type {type!r} is not registered "
                       f"({len(_REGISTRY)} ops known)")
    return _REGISTRY[type]


def has_op(type: str) -> bool:
    return type in _REGISTRY


def all_registered_ops() -> List[str]:
    return sorted(_REGISTRY)


# global monotonically increasing op seed for stateless per-op randomness
_OP_SEED = [0]


def reset_op_seed(value: int = 0):
    """Reset the per-op randomness counter (test isolation / building two
    programs that must draw identical init randomness)."""
    _OP_SEED[0] = value


def infer_op_shape(op: Operator, block: Block):
    _OP_SEED[0] += 1
    op.attrs.setdefault("__op_seed__", _OP_SEED[0])
    opdef = _REGISTRY.get(op.type)
    if opdef is None:
        raise NotFoundError(f"cannot append unregistered op {op.type!r}")
    if opdef.infer is not None:
        with op_error_context(op, block, phase="shape inference"):
            opdef.infer(op, block)


_AMP_CASTABLE = ("float16", "bfloat16", "float32")


def _lower_with_amp(ctx: LowerContext, opdef: "OpDef", op: Operator):
    """Autocast wrapper: white-list ops see low-precision float inputs,
    black-list ops see float32; env bindings are restored afterwards so
    other consumers keep the original precision."""
    amp = ctx.amp
    target = None
    if amp is not None:
        # grad ops autocast like their forward: without this the whole
        # backward (2/3 of training FLOPs) runs f32 matmuls off the f32
        # master weights — measured 0.21 -> 0.35+ MFU on the bf16 BERT
        # bench when the backward joined the white list
        base = op.type[:-5] if op.type.endswith("_grad") else op.type
        if base in amp["white"]:
            target = amp["dtype"]
        elif base in amp["black"]:
            target = "float32"
    if target is None:
        opdef.lower(ctx, op)
        return
    saved = {}
    for name in op.input_arg_names():
        v = ctx.env.get(name)
        dt = str(getattr(v, "dtype", ""))
        if v is not None and dt in _AMP_CASTABLE and dt != target:
            saved[name] = v
            ctx.env[name] = v.astype(target)
    opdef.lower(ctx, op)
    for n, v in saved.items():
        ctx.env[n] = v


def lower_op(ctx: LowerContext, op: Operator):
    opdef = _REGISTRY.get(op.type)
    if opdef is None or opdef.lower is None:
        raise UnimplementedError(f"no lowering for op {op.type!r}")
    with op_error_context(op, getattr(ctx, "block", None),
                          phase="lowering"):
        _lower_with_amp(ctx, opdef, op)


# ---------------------------------------------------------------------------
# Shared infer-shape helpers
# ---------------------------------------------------------------------------

def set_out(op: Operator, block: Block, slot: str, shape, dtype,
            **var_kwargs):
    """Create/refresh the output var's shape+dtype in the block."""
    for name in op.output(slot):
        v = block._find_var_recursive(name)
        if v is None:
            v = block.create_var(name=name)
        v.shape = tuple(int(s) for s in shape) if shape is not None else None
        v.dtype = convert_dtype(dtype)
        for k, val in var_kwargs.items():
            setattr(v, k, val)


def in_var(op: Operator, block: Block, slot: str) -> Variable:
    return block.var(op.single_input(slot))


def same_as_input(input_slot="X", output_slot="Out"):
    def infer(op: Operator, block: Block):
        x = in_var(op, block, input_slot)
        set_out(op, block, output_slot, x.shape, x.dtype)
    return infer


def broadcast_shapes(s1, s2, axis=-1):
    """Paddle-style broadcast: y's dims align to x starting at `axis`
    (reference operators/elementwise/elementwise_op_function.h); -1 means
    trailing alignment (numpy rule)."""
    s1, s2 = list(s1), list(s2)
    if len(s2) > len(s1):
        s1, s2 = s2, s1
    if axis == -1:
        axis = len(s1) - len(s2)
    padded = [1] * axis + s2 + [1] * (len(s1) - axis - len(s2))
    out = []
    for a, b in zip(s1, padded):
        if a == -1 or b == -1:
            out.append(-1)
        elif a == 1:
            out.append(b)
        elif b == 1 or a == b:
            out.append(a)
        else:
            raise ValueError(f"cannot broadcast shapes {s1} vs {s2}")
    return tuple(out)


# ---------------------------------------------------------------------------
# Auto-grad ("vjp of the forward lowering") machinery
# ---------------------------------------------------------------------------

def build_auto_grad_specs(fwd_op: Operator, block: Block,
                          no_grad_set: set) -> List[dict]:
    """Emit the generic ``<type>_grad`` op desc for `fwd_op`.

    Inputs: every forward input slot and output slot under its own name,
    plus ``<slot>@GRAD`` for each forward output.  Outputs: ``<slot>@GRAD``
    for each differentiable forward input.  Mirrors the reference's
    DefaultGradOpMaker (framework/grad_op_desc_maker.h).
    """
    inputs: Dict[str, List[str]] = {}
    for slot, names in fwd_op.inputs.items():
        inputs[slot] = list(names)
    for slot, names in fwd_op.outputs.items():
        inputs[slot] = list(names)
        inputs[slot + "@GRAD"] = [grad_var_name(n) for n in names]
    outputs: Dict[str, List[str]] = {}
    for slot, names in fwd_op.inputs.items():
        grads = []
        for n in names:
            v = block._find_var_recursive(n)
            differentiable = (
                v is not None and not v.stop_gradient and n not in no_grad_set
                and convert_dtype(v.dtype).startswith(("float", "bfloat")))
            grads.append(grad_var_name(n) if differentiable else "")
        if any(grads):
            outputs[slot + "@GRAD"] = grads
    if not outputs:
        return []
    attrs = dict(fwd_op.attrs)
    attrs["__fwd_type__"] = fwd_op.type
    attrs["__fwd_inputs__"] = {k: list(v) for k, v in fwd_op.inputs.items()}
    attrs["__fwd_outputs__"] = {k: list(v) for k, v in fwd_op.outputs.items()}
    # full-fidelity nested desc, ONLY when differentiating a grad op
    # (double backward): its own __fwd_* attrs would be clobbered by the
    # flat keys above.  Plain first-order grads skip the duplication.
    if fwd_op.type.endswith("_grad"):
        attrs["__fwd_desc__"] = dict(
            type=fwd_op.type,
            inputs={k: list(v) for k, v in fwd_op.inputs.items()},
            outputs={k: list(v) for k, v in fwd_op.outputs.items()},
            attrs=dict(fwd_op.attrs))
    return [dict(type=fwd_op.type + "_grad", inputs=inputs, outputs=outputs,
                 attrs=attrs)]


def _lower_auto_grad(ctx: LowerContext, gop: Operator):
    """Lowering for auto-derived ``<type>_grad`` ops: jax.vjp of fwd lower."""
    import jax
    import jax.numpy as jnp

    fwd_type = gop.attr("__fwd_type__")
    fwd_inputs: Dict[str, List[str]] = gop.attr("__fwd_inputs__")
    fwd_outputs: Dict[str, List[str]] = gop.attr("__fwd_outputs__")
    opdef = get_op_def(fwd_type)

    # Which (slot, idx) need grads, in a stable order.
    wanted: List[tuple] = []
    for gslot, gnames in gop.outputs.items():
        slot = gslot[:-len("@GRAD")]
        for i, gname in enumerate(gnames):
            if gname:
                wanted.append((slot, i, gname))

    diff_names: List[str] = []
    seen = set()
    for slot, i, _ in wanted:
        n = fwd_inputs[slot][i]
        if n not in seen:
            seen.add(n)
            diff_names.append(n)

    # Forward output order for cotangents.
    out_order: List[str] = []
    for slot, names in fwd_outputs.items():
        for n in names:
            if n not in out_order:
                out_order.append(n)

    const_env = {n: ctx.get(n)
                 for ns in fwd_inputs.values() for n in ns
                 if n not in seen}

    # Reconstruct a forward op object for re-lowering (pure; attrs carry the
    # original __op_seed__ so stochastic ops replay identically).  The
    # nested desc preserves a grad op's own __fwd_* attrs, which double
    # backward needs (grad-of-grad re-lowers the inner grad op).
    desc = gop.attr("__fwd_desc__")
    if desc is not None:
        fwd_attrs = dict(desc["attrs"])
    else:
        fwd_attrs = {k: v for k, v in gop.attrs.items()
                     if not k.startswith("__fwd_")}
    fwd_op = Operator(ctx.block, fwd_type, fwd_inputs, fwd_outputs, fwd_attrs)

    def fwd_fn(*diff_vals):
        env = dict(const_env)
        env.update(zip(diff_names, diff_vals))
        sub = LowerContext(ctx.block, env, base_key=ctx.base_key,
                           is_test=ctx.is_test, mesh=ctx.mesh, amp=ctx.amp)
        sub.axis_names = getattr(ctx, "axis_names", ())
        sub.ring_table = getattr(ctx, "ring_table", {})
        _lower_with_amp(sub, opdef, fwd_op)
        return tuple(env[n] for n in out_order)

    primals = tuple(ctx.get(n) for n in diff_names)
    out_vals, vjp_fn = jax.vjp(fwd_fn, *primals)

    # cotangent names were recorded in the op's <slot>@GRAD inputs at
    # build time — use them, not grad_var_name(), which reads the
    # *current* grad suffix (higher-order passes build under @GRAD2, ...)
    cot_name = {}
    for slot, names in fwd_outputs.items():
        for i, n in enumerate(names):
            gnames = gop.inputs.get(slot + "@GRAD", [])
            if i < len(gnames) and gnames[i]:
                cot_name[n] = gnames[i]
    cotangents = []
    for n, ov in zip(out_order, out_vals):
        g = ctx.env.get(cot_name.get(n, grad_var_name(n)))
        if g is None:
            g = jnp.zeros_like(ov)
        else:
            g = jnp.asarray(g, dtype=ov.dtype).reshape(jnp.shape(ov))
        cotangents.append(g)
    in_grads = vjp_fn(tuple(cotangents))
    grad_by_name = dict(zip(diff_names, in_grads))

    written = set()
    for slot, i, gname in wanted:
        src = fwd_inputs[slot][i]
        val = grad_by_name[src]
        if gname in written:
            # same fwd var feeds multiple slots of THIS op (e.g. x*x):
            # jax.vjp already summed all paths into grad_by_name[src] —
            # writing again would double-count
            continue
        # accumulate across DIFFERENT consumers of the fwd var
        if gname in ctx.env and gop.attr("__accumulate__", False):
            val = ctx.env[gname] + val
        ctx.env[gname] = val
        written.add(gname)


def infer_auto_grad(gop: Operator, block: Block):
    """Grad vars mirror the shape/dtype of their forward vars."""
    fwd_inputs: Dict[str, List[str]] = gop.attr("__fwd_inputs__")
    for gslot, gnames in gop.outputs.items():
        slot = gslot[:-len("@GRAD")]
        for i, gname in enumerate(gnames):
            if not gname:
                continue
            src = block.var(fwd_inputs[slot][i])
            v = block._find_var_recursive(gname)
            if v is None:
                v = block.create_var(name=gname)
            v.shape, v.dtype = src.shape, src.dtype


class _AutoGradDef(OpDef):
    pass


def ensure_grad_op_registered(fwd_type: str):
    gtype = fwd_type + "_grad"
    if gtype not in _REGISTRY:
        # grad='auto': a grad op is itself differentiable (vjp of its
        # vjp), which is what double backward walks through
        _REGISTRY[gtype] = _AutoGradDef(
            gtype, infer=infer_auto_grad, lower=_lower_auto_grad,
            grad="auto")
    return gtype
