"""moe_ffn graph op: Switch-style expert-parallel FFN.

New capability (SURVEY.md §2.6 — completes the TP/EP/CP/SP quartet; the
reference vintage has no MoE op). Lowering picks the TPU execution per
context, the same pattern as flash_attention:
  * `ep` axis bound (shard_map / build_spmd_step) -> all_to_all token
    dispatch over ICI (parallel/moe.py)
  * otherwise (single device or GSPMD build_sharded_step) -> dense
    einsum math; under GSPMD the expert weights are physically sharded
    by parallel.moe.moe_rules and XLA inserts the collectives.
"""
from __future__ import annotations

from .registry import in_var, register_op, set_out


def _moe_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)
    set_out(op, block, "AuxLoss", (), "float32")
    if op.output("ExpertCount"):
        e = in_var(op, block, "GateW").shape[1]
        set_out(op, block, "ExpertCount", (e,), "float32")


@register_op("moe_ffn", infer=_moe_infer, grad="auto")
def _moe_ffn(ctx, op):
    from ..parallel.mesh import EP_AXIS
    from ..parallel.moe import moe_ffn_tokens

    x = ctx.get_input(op, "X")
    gate_w = ctx.get_input(op, "GateW")
    w1, b1 = ctx.get_input(op, "W1"), ctx.get_input(op, "B1")
    w2, b2 = ctx.get_input(op, "W2"), ctx.get_input(op, "B2")
    axes = getattr(ctx, "axis_names", ()) or ()
    axis = EP_AXIS if EP_AXIS in axes else None
    shape = x.shape
    flat = x.reshape(-1, shape[-1])
    out, aux, counts = moe_ffn_tokens(
        flat, gate_w, w1, b1, w2, b2,
        capacity_factor=float(op.attr("capacity_factor", 1.25)),
        axis_name=axis,
        activation=op.attr("activation", "gelu"))
    ctx.set_output(op, "Out", out.reshape(shape))
    ctx.set_output(op, "AuxLoss", aux)
    if op.output("ExpertCount"):
        ctx.set_output(op, "ExpertCount", counts)
