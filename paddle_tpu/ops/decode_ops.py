"""Autoregressive-decode ops: per-slot KV-cache write + cached attention.

New capability for the generation serving path (no reference analog —
the reference vintage predates KV-cached LLM serving).  Two ops that
make a decoder block's attention O(1) per step instead of O(n²) over
the prefix:

* ``kv_cache_write`` — scatter the step's fresh K/V rows into a
  persistent per-slot cache at per-row dynamic offsets
  (``jax.lax.dynamic_update_slice`` vmapped over the slot dim).  The
  output aliases the cache *variable name*, so the executor classifies
  the cache as mutated persistable state → donated buffer → XLA updates
  it in place in HBM (no [slots, H, S_max, D] copy per token).
* ``cached_attention`` — one query step attends over the full cache
  with a per-row validity mask (``j <= position[b] + t``).  The
  formulation mirrors ``flash_attention impl='xla'`` exactly (same
  einsum contractions, same ``-1e30`` mask constant, same
  ``jax.nn.softmax``), which is what makes cached decode logits
  **bit-exact** against the uncached full forward on CPU — masked cache
  columns contribute exact zeros, and reduction prefixes are preserved
  across lengths (asserted in ``tests/test_generation.py``).

Both are inference-only (``grad=None``): the decode path never trains.
"""
from __future__ import annotations

from .registry import in_var, register_op, set_out


def _kv_write_infer(op, block):
    c = in_var(op, block, "Cache")
    set_out(op, block, "Out", c.shape, c.dtype)


@register_op("kv_cache_write", infer=_kv_write_infer, grad=None,
             stateful_outputs=("Out",))
def _kv_cache_write(ctx, op):
    """Cache [B, Hkv, S_max, D], New [B, Hkv, T, D], Positions [B] int —
    write row b's T fresh rows at seq offset ``positions[b]``."""
    import jax
    import jax.numpy as jnp

    cache = ctx.get_input(op, "Cache")
    new = ctx.get_input(op, "New")
    pos = ctx.get_input(op, "Positions")

    def write_row(c, n, p):
        return jax.lax.dynamic_update_slice(
            c, n.astype(c.dtype), (jnp.int32(0), p, jnp.int32(0)))

    out = jax.vmap(write_row)(cache, new, pos.astype(jnp.int32))
    ctx.set_output(op, "Out", out)


@register_op("kv_cache_insert", infer=_kv_write_infer, grad=None,
             stateful_outputs=("Out",))
def _kv_cache_insert(ctx, op):
    """Prefill insert: Cache [slots, Hkv, S_max, D] gets New
    [1, Hkv, S_b, D] at slot ``Slot[0]`` (seq offset 0) — the one-shot
    cache population after a prompt's causal forward, in-graph so the
    prefill step donates the cache buffer like the decode step does
    (no per-layer K/V fetch + host-side reinsert)."""
    import jax
    import jax.numpy as jnp

    cache = ctx.get_input(op, "Cache")
    new = ctx.get_input(op, "New")
    slot = ctx.get_input(op, "Slot").astype(jnp.int32)
    z = jnp.int32(0)
    out = jax.lax.dynamic_update_slice(
        cache, new.astype(cache.dtype), (slot.reshape(()), z, z, z))
    ctx.set_output(op, "Out", out)


def _cached_attn_infer(op, block):
    q = in_var(op, block, "Q")
    set_out(op, block, "Out", q.shape, q.dtype)


@register_op("cached_attention", infer=_cached_attn_infer, grad=None)
def _cached_attention(ctx, op):
    """Q [B, H, T, D] over caches K/V [B, Hkv, S_max, D]; Positions [B]
    is the pre-step sequence length (row b's query t sits at absolute
    position ``positions[b] + t`` and attends columns ``j`` with
    ``j <= positions[b] + t``).  GQA caches (Hkv < H) expand
    repeat-interleave style, matching the uncached block's ``expand_kv``
    values exactly."""
    import jax
    import jax.numpy as jnp

    q = ctx.get_input(op, "Q")
    k = ctx.get_input(op, "K")
    v = ctx.get_input(op, "V")
    pos = ctx.get_input(op, "Positions").astype(jnp.int32)
    B, H, T, D = q.shape
    Hkv, S = k.shape[1], k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        # repeat_interleave [k1,k1,..,k2,k2,..]: query-head group g maps
        # to kv head g//rep (same convention as llama_block's expand_kv)
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale = op.attr("scale", None)
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    if T == 1:
        # a Q=1 scores dot lowers to a GEMV-style rewrite whose
        # accumulation order over D differs from the generic GEMM the
        # uncached forward uses (measured on CPU: ~1e-6 logit drift,
        # breaking the bit-exactness contract).  Duplicating the query
        # row keeps the generic row-consistent GEMM path; the clone's
        # scores are sliced away before the softmax.
        s = jnp.einsum("bhqd,bhkd->bhqk",
                       jnp.concatenate([q, q], axis=2), k)[:, :, :1]
        s = s * scale
    else:
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    # validity mask: same -1e30 constant as flash_attention impl="xla";
    # exp underflows to exact 0 for masked columns, so softmax sums and
    # the PV contraction are bit-identical to the shorter uncached row
    j = jnp.arange(S, dtype=jnp.int32)[None, None, None, :]
    t = jnp.arange(T, dtype=jnp.int32)[None, None, :, None]
    limit = pos[:, None, None, None] + t
    s = jnp.where(j <= limit, s, jnp.asarray(-1e30, s.dtype))
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    ctx.set_output(op, "Out", out.astype(q.dtype))
