"""Autoregressive-decode ops: per-slot KV-cache write + cached attention.

New capability for the generation serving path (no reference analog —
the reference vintage predates KV-cached LLM serving).  Two ops that
make a decoder block's attention O(1) per step instead of O(n²) over
the prefix:

* ``kv_cache_write`` — scatter the step's fresh K/V rows into a
  persistent per-slot cache at per-row dynamic offsets
  (``jax.lax.dynamic_update_slice`` vmapped over the slot dim).  The
  output aliases the cache *variable name*, so the executor classifies
  the cache as mutated persistable state → donated buffer → XLA updates
  it in place in HBM (no [slots, H, S_max, D] copy per token).
* ``cached_attention`` — one query step attends over the full cache
  with a per-row validity mask (``j <= position[b] + t``).  The
  formulation mirrors ``flash_attention impl='xla'`` exactly (same
  einsum contractions, same ``-1e30`` mask constant, same
  ``jax.nn.softmax``), which is what makes cached decode logits
  **bit-exact** against the uncached full forward on CPU — masked cache
  columns contribute exact zeros, and reduction prefixes are preserved
  across lengths (asserted in ``tests/test_generation.py``).

Both are inference-only (``grad=None``): the decode path never trains.

Paged variants (``kv_pool_write`` / ``kv_pool_gather``) back the
block-paged cache (PagedAttention, Kwon et al., SOSP '23): a flat
per-layer pool ``[num_pages, n_kv, page_tokens, D]`` replaces the dense
per-slot reservation, and a per-slot block table maps logical page
index -> physical page.  ``kv_pool_gather`` reconstructs a slot's
logical ``[B, n_kv, NP*page_tokens, D]`` cache view from its pages, so
``cached_attention`` runs the *identical* einsum at the *identical*
contraction length as the dense path — which is what keeps paged
decode bit-exact against dense (columns beyond the live length differ
only in garbage the ``-1e30`` mask turns into exact zeros either way).
Physical page 0 is the reserved **trash page**: rows a write must
discard (idle slots, pad-tail rows of a chunk) are redirected there
instead of branching, so the scatter stays a single fused op.
"""
from __future__ import annotations

from .registry import in_var, register_op, set_out


def _kv_write_infer(op, block):
    c = in_var(op, block, "Cache")
    set_out(op, block, "Out", c.shape, c.dtype)


@register_op("kv_cache_write", infer=_kv_write_infer, grad=None,
             stateful_outputs=("Out",))
def _kv_cache_write(ctx, op):
    """Cache [B, Hkv, S_max, D], New [B, Hkv, T, D], Positions [B] int —
    write row b's T fresh rows at seq offset ``positions[b]``."""
    import jax
    import jax.numpy as jnp

    cache = ctx.get_input(op, "Cache")
    new = ctx.get_input(op, "New")
    pos = ctx.get_input(op, "Positions")

    def write_row(c, n, p):
        return jax.lax.dynamic_update_slice(
            c, n.astype(c.dtype), (jnp.int32(0), p, jnp.int32(0)))

    out = jax.vmap(write_row)(cache, new, pos.astype(jnp.int32))
    ctx.set_output(op, "Out", out)


@register_op("kv_cache_insert", infer=_kv_write_infer, grad=None,
             stateful_outputs=("Out",))
def _kv_cache_insert(ctx, op):
    """Prefill insert: Cache [slots, Hkv, S_max, D] gets New
    [1, Hkv, S_b, D] at slot ``Slot[0]`` (seq offset 0) — the one-shot
    cache population after a prompt's causal forward, in-graph so the
    prefill step donates the cache buffer like the decode step does
    (no per-layer K/V fetch + host-side reinsert)."""
    import jax
    import jax.numpy as jnp

    cache = ctx.get_input(op, "Cache")
    new = ctx.get_input(op, "New")
    slot = ctx.get_input(op, "Slot").astype(jnp.int32)
    z = jnp.int32(0)
    out = jax.lax.dynamic_update_slice(
        cache, new.astype(cache.dtype), (slot.reshape(()), z, z, z))
    ctx.set_output(op, "Out", out)


def _kv_pool_write_infer(op, block):
    p = in_var(op, block, "Pool")
    set_out(op, block, "Out", p.shape, p.dtype)


@register_op("kv_pool_write", infer=_kv_pool_write_infer, grad=None,
             stateful_outputs=("Out",))
def _kv_pool_write(ctx, op):
    """Paged cache write: Pool [P, Hkv, pt, D], New [B, Hkv, T, D],
    Positions [B] int (logical base position per row), BlockTable
    [B, NP] int (logical page -> physical page), Lengths [B] int
    (valid rows per batch row).  Row (b, t) of New lands at logical
    position ``positions[b] + t``, i.e. physical page
    ``block_table[b, (positions[b]+t) // pt]`` at in-page offset
    ``(positions[b]+t) % pt``.  Rows with ``t >= lengths[b]`` (idle
    slots, the pad tail of a bucketed prefill chunk) are redirected to
    the reserved trash page 0 — one scatter, no branches.  The output
    aliases the pool variable name, so the executor donates the buffer
    exactly like the dense ``kv_cache_write`` (in-place HBM update)."""
    import jax.numpy as jnp

    pool = ctx.get_input(op, "Pool")
    new = ctx.get_input(op, "New")
    pos = ctx.get_input(op, "Positions").astype(jnp.int32)
    bt = ctx.get_input(op, "BlockTable").astype(jnp.int32)
    length = ctx.get_input(op, "Lengths").astype(jnp.int32)
    P, Hkv, pt, D = pool.shape
    B, _, T, _ = new.shape
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    logical = pos[:, None] + t                        # [B, T]
    page_idx = jnp.clip(logical // pt, 0, bt.shape[1] - 1)
    phys = jnp.take_along_axis(bt, page_idx, axis=1,  # [B, T]
                               mode="clip")
    off = logical % pt
    valid = t < length[:, None]
    # invalid rows all collapse onto trash slot (0, 0): duplicate
    # scatter indices there are fine — the trash page is never read
    # unmasked
    phys = jnp.where(valid, phys, 0)
    off = jnp.where(valid, off, 0)
    rows = jnp.transpose(new, (0, 2, 1, 3)).reshape(B * T, Hkv, D)
    out = pool.at[phys.reshape(-1), :, off.reshape(-1), :].set(
        rows.astype(pool.dtype))
    ctx.set_output(op, "Out", out)


def _kv_pool_gather_infer(op, block):
    pool = in_var(op, block, "Pool")
    bt = in_var(op, block, "BlockTable")
    P, hkv, pt, d = pool.shape
    b, np_ = bt.shape
    set_out(op, block, "Out", (b, hkv, np_ * pt, d), pool.dtype)


@register_op("kv_pool_gather", infer=_kv_pool_gather_infer, grad=None)
def _kv_pool_gather(ctx, op):
    """Reassemble a slot's logical cache view from its pages: Pool
    [P, Hkv, pt, D] gathered through BlockTable [B, NP] ->
    [B, Hkv, NP*pt, D].  Column j of the output is logical position j
    of slot b — the exact dense-cache layout, so the downstream
    ``cached_attention`` einsum (and therefore its XLA reduction
    tiling) is byte-identical to the dense path's.  Unmapped block-
    table entries read the trash page; those columns sit beyond the
    slot's validity limit and mask to exact zeros."""
    import jax.numpy as jnp

    pool = ctx.get_input(op, "Pool")
    bt = ctx.get_input(op, "BlockTable").astype(jnp.int32)
    P, Hkv, pt, D = pool.shape
    B, NP = bt.shape
    pages = jnp.take(pool, bt.reshape(-1), axis=0, mode="clip")
    out = jnp.transpose(pages.reshape(B, NP, Hkv, pt, D),
                        (0, 2, 1, 3, 4)).reshape(B, Hkv, NP * pt, D)
    ctx.set_output(op, "Out", out)


def _cached_attn_infer(op, block):
    q = in_var(op, block, "Q")
    set_out(op, block, "Out", q.shape, q.dtype)


@register_op("cached_attention", infer=_cached_attn_infer, grad=None)
def _cached_attention(ctx, op):
    """Q [B, H, T, D] over caches K/V [B, Hkv, S_max, D]; Positions [B]
    is the pre-step sequence length (row b's query t sits at absolute
    position ``positions[b] + t`` and attends columns ``j`` with
    ``j <= positions[b] + t``).  GQA caches (Hkv < H) expand
    repeat-interleave style, matching the uncached block's ``expand_kv``
    values exactly."""
    import jax
    import jax.numpy as jnp

    q = ctx.get_input(op, "Q")
    k = ctx.get_input(op, "K")
    v = ctx.get_input(op, "V")
    pos = ctx.get_input(op, "Positions").astype(jnp.int32)
    B, H, T, D = q.shape
    Hkv, S = k.shape[1], k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        # repeat_interleave [k1,k1,..,k2,k2,..]: query-head group g maps
        # to kv head g//rep (same convention as llama_block's expand_kv)
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale = op.attr("scale", None)
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    if T == 1:
        # a Q=1 scores dot lowers to a GEMV-style rewrite whose
        # accumulation order over D differs from the generic GEMM the
        # uncached forward uses (measured on CPU: ~1e-6 logit drift,
        # breaking the bit-exactness contract).  Duplicating the query
        # row keeps the generic row-consistent GEMM path; the clone's
        # scores are sliced away before the softmax.
        s = jnp.einsum("bhqd,bhkd->bhqk",
                       jnp.concatenate([q, q], axis=2), k)[:, :, :1]
        s = s * scale
    else:
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    # validity mask: same -1e30 constant as flash_attention impl="xla";
    # exp underflows to exact 0 for masked columns, so softmax sums and
    # the PV contraction are bit-identical to the shorter uncached row
    j = jnp.arange(S, dtype=jnp.int32)[None, None, None, :]
    t = jnp.arange(T, dtype=jnp.int32)[None, None, :, None]
    limit = pos[:, None, None, None] + t
    s = jnp.where(j <= limit, s, jnp.asarray(-1e30, s.dtype))
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    ctx.set_output(op, "Out", out.astype(q.dtype))
