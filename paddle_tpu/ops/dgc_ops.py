"""Deep Gradient Compression op.

Reference: paddle/fluid/operators/dgc_op.cc + dgc_momentum momentum
correction and framework/details/sparse_all_reduce_op_handle.h:41 (encoded
ncclAllGather). DGC (Lin et al.): momentum-corrected gradient accumulation,
top-k sparsification with error feedback, communicate only the top-k.

TPU-native: the sparsification/error-feedback math is identical; the
communication lowers to a dense psum over the mesh axis when axes are
bound — ICI is fast enough that sparse encoding buys nothing, but the
*training dynamics* (what DGC actually changes) are preserved.
"""
from __future__ import annotations

import numpy as np

from .collective_ops import _axis_name
from .registry import in_var, register_op, set_out


def _dgc_infer(op, block):
    for slot_in, slot_out in (("Param", "ParamOut"), ("U", "UOut"),
                              ("V", "VOut")):
        xn, on = op.single_input(slot_in), op.single_output(slot_out)
        if xn and on:
            xv, ov = block.var(xn), block.var(on)
            ov.shape, ov.dtype = xv.shape, xv.dtype


@register_op("dgc_momentum", infer=_dgc_infer, grad=None,
             stateful_outputs=("ParamOut", "UOut", "VOut"))
def _dgc_momentum(ctx, op):
    import jax
    import jax.numpy as jnp
    import jax.lax as lax

    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad").astype("float32")
    u = ctx.get_input(op, "U")          # momentum-corrected velocity
    v = ctx.get_input(op, "V")          # local error accumulation
    lr = ctx.get_input(op, "LearningRate")
    step = ctx.get_input(op, "CurrentStep")
    m = op.attr("m", 0.9)
    sparsity = op.attr("sparsity", 0.999)
    rampup_begin = op.attr("rampup_begin_step", 0.0)
    nranks = op.attr("nranks", 1)

    # momentum correction: accumulate velocity locally, then error-feedback
    u_new = m * u + g
    v_new = v + u_new

    flat = v_new.reshape(-1)
    numel = flat.shape[0]
    k = max(1, int(np.ceil(numel * (1.0 - sparsity))))
    topk_vals, _ = lax.top_k(jnp.abs(flat), k)
    thresh = topk_vals[-1]
    mask = (jnp.abs(v_new) >= thresh).astype(v_new.dtype)

    in_rampup = jnp.reshape(step, ()) < rampup_begin
    mask = jnp.where(in_rampup, jnp.ones_like(mask), mask)

    encoded = v_new * mask
    v_out = v_new * (1.0 - mask)

    axis = _axis_name(ctx, op)
    if axis is not None:
        encoded = jax.lax.psum(encoded, axis) / nranks

    pf = p.astype("float32") - lr * encoded
    ctx.set_output(op, "ParamOut", pf.astype(p.dtype))
    ctx.set_output(op, "UOut", u_new)
    ctx.set_output(op, "VOut", v_out)


def _dgc_op_infer(op, block):
    for slot_in, slot_out in (("U", "U_out"), ("V", "V_out"),
                              ("Grad", "Grad_out"),
                              ("Grad", "EncodeGrad")):
        xn = op.single_input(slot_in)
        for on in op.output(slot_out):
            xv = block.var(xn)
            ov = block._find_var_recursive(on)
            if ov is None:
                ov = block.create_var(name=on)
            ov.shape, ov.dtype = xv.shape, xv.dtype


@register_op("dgc", infer=_dgc_op_infer, grad=None,
             stateful_outputs=("U_out", "V_out"))
def _dgc(ctx, op):
    """Standalone DGC sparsify (reference dgc_op.h): momentum
    correction u/v accumulation, top-k threshold mask with error
    feedback; EncodeGrad carries the sparsified gradient (dense tensor
    with zeros — ICI psum replaces the reference's encoded allgather),
    Grad_out the residual."""
    import jax.lax as lax
    import jax.numpy as jnp

    g = ctx.get_input(op, "Grad").astype("float32")
    u = ctx.get_input(op, "U")
    v = ctx.get_input(op, "V")
    step = ctx.get_input(op, "current_step")
    m = op.attr("m", 0.9)
    use_nesterov = op.attr("use_nesterov", False)
    ratios = op.attr("sparsity", [0.999])
    rampup_begin = op.attr("rampup_begin_step", 0.0)
    rampup = max(1.0, op.attr("rampup_step", 1.0))
    # rampup sparsity schedule: pick the period's ratio
    s = jnp.reshape(step, ()) - rampup_begin
    seg = jnp.clip((s * len(ratios) / rampup).astype("int32"),
                   0, len(ratios) - 1)
    ratio = jnp.asarray(np.asarray(ratios, "float32"))[seg]

    u_new = m * u + g
    if use_nesterov:
        acc = m * (u_new + v) + g + v
    else:
        acc = u_new + v
    flat = acc.reshape(-1)
    numel = flat.shape[0]
    # static top-k bound at the max ratio; runtime threshold from the
    # scheduled ratio via the sorted prefix
    k_max = max(1, int(np.ceil(numel * (1.0 - min(ratios)))))
    top_vals = lax.top_k(jnp.abs(flat), k_max)[0]
    k_run = jnp.clip((numel * (1.0 - ratio)).astype("int32"),
                     1, k_max)
    thresh = top_vals[k_run - 1]
    mask = (jnp.abs(acc) >= thresh).astype("float32")
    in_rampup = jnp.reshape(step, ()) < rampup_begin
    mask = jnp.where(in_rampup, jnp.ones_like(mask), mask)
    encoded = acc * mask
    ctx.set_output(op, "U_out", u_new)
    ctx.set_output(op, "V_out", acc * (1.0 - mask))
    ctx.set_output(op, "EncodeGrad", encoded)
    ctx.set_output(op, "Grad_out", encoded)
    if op.output("k"):
        ctx.set_output(op, "k", k_run.astype("float32").reshape(1))


def _dgc_clip_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)


@register_op("dgc_clip_by_norm", infer=_dgc_clip_infer, grad=None)
def _dgc_clip_by_norm(ctx, op):
    """clip_by_norm gated on the DGC rampup step (reference
    dgc_clip_by_norm_op.cc: no clipping before rampup_begin_step)."""
    import jax.numpy as jnp
    x = ctx.get_input(op, "X").astype("float32")
    step = ctx.get_input(op, "current_step")
    max_norm = op.attr("max_norm", 1.0)
    rampup_begin = op.attr("rampup_begin_step", -1.0)
    norm = jnp.sqrt((x * x).sum())
    clipped = x * jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    out = jnp.where(jnp.reshape(step, ()) < rampup_begin, x, clipped)
    ctx.set_output(op, "Out", out)
