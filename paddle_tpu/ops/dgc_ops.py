"""Deep Gradient Compression op.

Reference: paddle/fluid/operators/dgc_op.cc + dgc_momentum momentum
correction and framework/details/sparse_all_reduce_op_handle.h:41 (encoded
ncclAllGather). DGC (Lin et al.): momentum-corrected gradient accumulation,
top-k sparsification with error feedback, communicate only the top-k.

TPU-native: the sparsification/error-feedback math is identical; the
communication lowers to a dense psum over the mesh axis when axes are
bound — ICI is fast enough that sparse encoding buys nothing, but the
*training dynamics* (what DGC actually changes) are preserved.
"""
from __future__ import annotations

import numpy as np

from .collective_ops import _axis_name
from .registry import register_op


def _dgc_infer(op, block):
    for slot_in, slot_out in (("Param", "ParamOut"), ("U", "UOut"),
                              ("V", "VOut")):
        xn, on = op.single_input(slot_in), op.single_output(slot_out)
        if xn and on:
            xv, ov = block.var(xn), block.var(on)
            ov.shape, ov.dtype = xv.shape, xv.dtype


@register_op("dgc_momentum", infer=_dgc_infer, grad=None,
             stateful_outputs=("ParamOut", "UOut", "VOut"))
def _dgc_momentum(ctx, op):
    import jax
    import jax.numpy as jnp
    import jax.lax as lax

    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad").astype("float32")
    u = ctx.get_input(op, "U")          # momentum-corrected velocity
    v = ctx.get_input(op, "V")          # local error accumulation
    lr = ctx.get_input(op, "LearningRate")
    step = ctx.get_input(op, "CurrentStep")
    m = op.attr("m", 0.9)
    sparsity = op.attr("sparsity", 0.999)
    rampup_begin = op.attr("rampup_begin_step", 0.0)
    nranks = op.attr("nranks", 1)

    # momentum correction: accumulate velocity locally, then error-feedback
    u_new = m * u + g
    v_new = v + u_new

    flat = v_new.reshape(-1)
    numel = flat.shape[0]
    k = max(1, int(np.ceil(numel * (1.0 - sparsity))))
    topk_vals, _ = lax.top_k(jnp.abs(flat), k)
    thresh = topk_vals[-1]
    mask = (jnp.abs(v_new) >= thresh).astype(v_new.dtype)

    in_rampup = jnp.reshape(step, ()) < rampup_begin
    mask = jnp.where(in_rampup, jnp.ones_like(mask), mask)

    encoded = v_new * mask
    v_out = v_new * (1.0 - mask)

    axis = _axis_name(ctx, op)
    if axis is not None:
        encoded = jax.lax.psum(encoded, axis) / nranks

    pf = p.astype("float32") - lr * encoded
    ctx.set_output(op, "ParamOut", pf.astype(p.dtype))
    ctx.set_output(op, "UOut", u_new)
    ctx.set_output(op, "VOut", v_out)
