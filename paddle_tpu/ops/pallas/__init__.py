"""Hand-written TPU kernels (pallas).

The reference's analog is its hand-CUDA operator set
(operators/fused/multihead_matmul_op.cu, math/bert_encoder_functor.cu);
here the hot ops are Mosaic kernels tiled for MXU/VMEM.
"""
from .flash_attention import flash_attention, blockwise_attention  # noqa
