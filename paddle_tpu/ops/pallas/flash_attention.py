"""Flash attention for TPU.

Forward: a pallas kernel — one grid cell per (batch*head, q-block), online
softmax over kv-blocks held in VMEM, fp32 accumulation on the MXU.
Backward: jax.vjp of the blockwise (lax.scan) formulation — XLA compiles
it to the standard recompute-based flash backward; activations per step
are one kv block, not the S×S score matrix.

Reference analog: the fused attention precursors
(operators/fused/multihead_matmul_op.cu, bert_encoder_functor.cu) — those
fuse QK^T+softmax+PV at fixed small S; this kernel is the long-sequence
capability the reference vintage lacks (SURVEY.md §5 long-context).
"""
from __future__ import annotations

import functools

import jax
import numpy as np

# tuned on TPU v5e (seq 2048, d 64): bq 256 / bk 512 beats both 128/128
# and the unfused XLA attention by ~1.5-4x wall clock
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


# ---------------------------------------------------------------------------
# blockwise reference formulation (differentiable; also the bwd path)
# ---------------------------------------------------------------------------

def blockwise_attention(q, k, v, causal=False, sm_scale=None,
                        block_k=DEFAULT_BLOCK_K, kv_offset=0, bias=None):
    """Online-softmax attention, scanning kv blocks.

    q: [B, H, Sq, D], k/v: [B, H, Sk, D]. kv_offset shifts the global kv
    position for causal masking (ring attention passes the rotating
    shard's offset). bias: optional [B, Sk] additive score bias
    (padding mask: 0 attend / -1e4 pad), broadcast over heads and q.
    Returns (out, (m, l)): out [B,H,Sq,D], m/l the softmax running stats
    [B,H,Sq] (used by ring accumulation).
    """
    import jax
    import jax.numpy as jnp

    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)
    bk = min(block_k, Sk)
    while Sk % bk:
        bk //= 2
    nblocks = Sk // bk

    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32).reshape(B, H, nblocks, bk, D)
    vf = v.astype(jnp.float32).reshape(B, H, nblocks, bk, D)
    kf = jnp.moveaxis(kf, 2, 0)  # [n, B, H, bk, D]
    vf = jnp.moveaxis(vf, 2, 0)
    if bias is not None:
        bf = bias.astype(jnp.float32).reshape(B, nblocks, bk)
        bf = jnp.moveaxis(bf, 1, 0)  # [n, B, bk]
        xs = (kf, vf, bf)
    else:
        xs = (kf, vf)

    q_pos = jnp.arange(Sq)[:, None]

    def body(carry, blk):
        m, l, acc, j = carry
        kb, vb = blk[:2]
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb)  # [B,H,Sq,bk]
        if len(blk) == 3:
            s = s + blk[2][:, None, None, :]
        if causal:
            k_pos = j * bk + jnp.arange(bk)[None, :] + kv_offset
            mask = q_pos >= k_pos
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        # guards: a fully-masked block/row keeps m at NEG_INF — exp(0)=1
        # must not leak in (ring attention hits this when a whole rotated
        # shard is causally masked)
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new[..., None]))
        corr = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        l_new = l * corr + p.sum(-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vb)
        return (m_new, l_new, acc_new, j + 1), None

    # derive initializers from qf so they inherit any shard_map
    # varying-axes type (plain zeros would mismatch the scan carry)
    m0 = qf[..., 0] * 0 + NEG_INF
    l0 = qf[..., 0] * 0
    acc0 = qf * 0
    (m, l, acc, _), _ = jax.lax.scan(body, (m0, l0, acc0, 0), xs)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype), (m, l)


# ---------------------------------------------------------------------------
# pallas forward kernel
# ---------------------------------------------------------------------------

def _fa_kernel(q_ref, k_ref, v_ref, *rest, block_k, causal, scale,
               seq_k, has_bias=False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if has_bias:
        b_ref, o_ref = rest
    else:
        (o_ref,) = rest
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [Bq, D]
    bq, d = q.shape
    nk = seq_k // block_k

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [Bq, Bk]
        if has_bias:
            bb = b_ref[0, 0, pl.ds(j * block_k, block_k)].astype(
                jnp.float32)
            s = s + bb[None, :]
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(-1, keepdims=True)
        acc_new = acc * corr + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    if causal:
        # kv blocks past this q block's last row are fully masked
        upper = jnp.minimum(nk, ((qi + 1) * bq + block_k - 1) // block_k)
    else:
        upper = nk
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret,
                   bias=None):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)
    bq = min(block_q, Sq)
    while Sq % bq:
        bq //= 2
    bk = min(block_k, Sk)
    while Sk % bk:
        bk //= 2

    qr = q.reshape(B * H, Sq, D)
    kr = k.reshape(B * H, Sk, D)
    vr = v.reshape(B * H, Sk, D)

    kernel = functools.partial(_fa_kernel, block_k=bk, causal=causal,
                               scale=scale, seq_k=Sk,
                               has_bias=bias is not None)
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
    ]
    args = [qr, kr, vr]
    if bias is not None:
        # one bias row per batch, shared across the H heads in the grid;
        # [B, 1, Sk] so the block's trailing dims (1, Sk) match the array
        # (Mosaic tiling requires 8/128-divisible or full-dim blocks)
        in_specs.append(
            pl.BlockSpec((1, 1, Sk), lambda b, i: (b // H, 0, 0)))
        args.append(bias.reshape(B, 1, Sk))
    out = pl.pallas_call(
        kernel,
        grid=(B * H, Sq // bq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        interpret=interpret,
    )(*args)
    return out.reshape(B, H, Sq, D)


# ---------------------------------------------------------------------------
# public entry: pallas forward, blockwise-vjp backward
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, sm_scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=False):
    """Multi-head attention, q/k/v: [B, H, S, D] -> [B, H, Sq, D]."""
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)


def _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                         interpret)
    return out, (q, k, v)


def _fa_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    import jax
    q, k, v = res

    def ref(q, k, v):
        return blockwise_attention(q, k, v, causal=causal,
                                   sm_scale=sm_scale, block_k=block_k)[0]

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention_bias(q, k, v, bias, causal=False, sm_scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                         interpret=False):
    """flash_attention with an additive [B, Sk] score bias (padding
    mask). Separate entry so the unbiased path keeps its 3-arg vjp."""
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret, bias=bias)


def _fab_fwd(q, k, v, bias, causal, sm_scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                         interpret, bias=bias)
    return out, (q, k, v, bias)


def _fab_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    import jax
    q, k, v, bias = res

    def ref(q, k, v, bias):
        return blockwise_attention(q, k, v, causal=causal,
                                   sm_scale=sm_scale, block_k=block_k,
                                   bias=bias)[0]

    _, vjp = jax.vjp(ref, q, k, v, bias)
    return vjp(g)


flash_attention_bias.defvjp(_fab_fwd, _fab_bwd)
