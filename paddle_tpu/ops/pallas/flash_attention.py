"""Flash attention for TPU — pallas forward AND backward kernels.

Forward: one grid cell per (batch*head, q-block), online softmax over
kv-blocks held in VMEM, fp32 accumulation on the MXU; emits the softmax
LSE rows for the backward.

Backward (FlashAttention-2 style recompute, no S×S materialization):
  * delta = rowsum(dO ⊙ O) — one fused XLA reduce, [B,H,S].
  * dKV kernel: grid (B*H, kv-block); inner fori over q-blocks
    recomputes p = exp(q·kᵀ − lse), accumulates dV += pᵀ·dO and
    dK += dsᵀ·q with ds = p ⊙ (dO·vᵀ − delta).
  * dQ kernel: grid (B*H, q-block); inner fori over kv-blocks
    accumulates dQ += ds·k.
Both kernels stream blocks from VMEM and skip causally-dead blocks, so
backward memory is O(S) like the forward (round-3 verdict: the previous
jax.vjp-of-scan backward materialized per-block probabilities and lost
to unfused XLA at every length).

Reference analog: the fused attention precursors
(operators/fused/multihead_matmul_op.cu, bert_encoder_functor.cu) — those
fuse QK^T+softmax+PV at fixed small S; this kernel is the long-sequence
capability the reference vintage lacks (SURVEY.md §5 long-context).
"""
from __future__ import annotations

import functools

import jax
import numpy as np

# tuned on TPU v5e (tools/attn_microbench.py, fwd+bwd kernels, d 64,
# B=32 H=12): 512/512 is best or within 2% of best at S=512/1024/2048
# (e.g. S=2048: 35.3ms vs 119.3ms at 128/128 and 77.4ms unfused XLA);
# 2048-wide blocks fail to compile (VMEM)
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


def _fit_block(block, size):
    b = min(block, size)
    while size % b:
        b //= 2
    return b


# ---------------------------------------------------------------------------
# blockwise reference formulation (ring attention + GSPMD multi-device path)
# ---------------------------------------------------------------------------

def blockwise_attention(q, k, v, causal=False, sm_scale=None,
                        block_k=DEFAULT_BLOCK_K, kv_offset=0, bias=None):
    """Online-softmax attention, scanning kv blocks.

    q: [B, H, Sq, D], k/v: [B, H, Sk, D]. kv_offset shifts the global kv
    position for causal masking (ring attention passes the rotating
    shard's offset). bias: optional [B, Sk] additive score bias
    (padding mask: 0 attend / -1e4 pad), broadcast over heads and q.
    Returns (out, (m, l)): out [B,H,Sq,D], m/l the softmax running stats
    [B,H,Sq] (used by ring accumulation).
    """
    import jax
    import jax.numpy as jnp

    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)
    bk = _fit_block(block_k, Sk)
    nblocks = Sk // bk

    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32).reshape(B, H, nblocks, bk, D)
    vf = v.astype(jnp.float32).reshape(B, H, nblocks, bk, D)
    kf = jnp.moveaxis(kf, 2, 0)  # [n, B, H, bk, D]
    vf = jnp.moveaxis(vf, 2, 0)
    if bias is not None:
        bf = bias.astype(jnp.float32).reshape(B, nblocks, bk)
        bf = jnp.moveaxis(bf, 1, 0)  # [n, B, bk]
        xs = (kf, vf, bf)
    else:
        xs = (kf, vf)

    q_pos = jnp.arange(Sq)[:, None]

    def body(carry, blk):
        m, l, acc, j = carry
        kb, vb = blk[:2]
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb)  # [B,H,Sq,bk]
        if len(blk) == 3:
            s = s + blk[2][:, None, None, :]
        if causal:
            k_pos = j * bk + jnp.arange(bk)[None, :] + kv_offset
            mask = q_pos >= k_pos
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        # guards: a fully-masked block/row keeps m at NEG_INF — exp(0)=1
        # must not leak in (ring attention hits this when a whole rotated
        # shard is causally masked)
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new[..., None]))
        corr = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        l_new = l * corr + p.sum(-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vb)
        return (m_new, l_new, acc_new, j + 1), None

    # derive initializers from qf so they inherit any shard_map
    # varying-axes type (plain zeros would mismatch the scan carry)
    m0 = qf[..., 0] * 0 + NEG_INF
    l0 = qf[..., 0] * 0
    acc0 = qf * 0
    (m, l, acc, _), _ = jax.lax.scan(body, (m0, l0, acc0, 0), xs)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype), (m, l)


# ---------------------------------------------------------------------------
# pallas forward kernel (emits out + lse)
# ---------------------------------------------------------------------------

def _fa_fwd_kernel(q_ref, k_ref, v_ref, *rest, block_k, causal, scale,
                   seq_k, has_bias=False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if has_bias:
        b_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [Bq, D]
    bq, d = q.shape
    nk = seq_k // block_k

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [Bq, Bk]
        if has_bias:
            bb = b_ref[0, 0, pl.ds(j * block_k, block_k)].astype(
                jnp.float32)
            s = s + bb[None, :]
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(-1, keepdims=True)
        acc_new = acc * corr + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    if causal:
        # kv blocks past this q block's last row are fully masked
        upper = jnp.minimum(nk, ((qi + 1) * bq + block_k - 1) // block_k)
    else:
        upper = nk
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l_safe))[:, 0]


def _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret,
                   bias=None):
    """Returns (out [B,H,Sq,D], lse [B,H,Sq] f32)."""
    import jax
    from jax.experimental import pallas as pl

    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)
    bq = _fit_block(block_q, Sq)
    bk = _fit_block(block_k, Sk)

    qr = q.reshape(B * H, Sq, D)
    kr = k.reshape(B * H, Sk, D)
    vr = v.reshape(B * H, Sk, D)

    kernel = functools.partial(_fa_fwd_kernel, block_k=bk, causal=causal,
                               scale=scale, seq_k=Sk,
                               has_bias=bias is not None)
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
    ]
    args = [qr, kr, vr]
    if bias is not None:
        # one bias row per batch, shared across the H heads in the grid;
        # [B, 1, Sk] so the block's trailing dims (1, Sk) match the array
        # (Mosaic tiling requires 8/128-divisible or full-dim blocks)
        in_specs.append(
            pl.BlockSpec((1, 1, Sk), lambda b, i: (b // H, 0, 0)))
        args.append(bias.reshape(B, 1, Sk))
    # lse rides as [BH, 1, Sq]: Mosaic requires block last-two-dims to be
    # (8,128)-divisible or equal to the array dims — (1, bq) on a 2D
    # [BH, Sq] array violates the sublane rule, (1, 1, bq) on 3D is legal
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, Sq // bq),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i))],
        out_shape=[jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
                   jax.ShapeDtypeStruct((B * H, 1, Sq), np.float32)],
        interpret=interpret,
    )(*args)
    return out.reshape(B, H, Sq, D), lse.reshape(B, H, Sq)


# ---------------------------------------------------------------------------
# pallas backward kernels (FA2 recompute)
# ---------------------------------------------------------------------------

def _fa_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, *rest,
                   block_q, causal, scale, seq_q, has_bias=False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if has_bias:
        b_ref, dk_ref, dv_ref, db_ref = rest
    else:
        dk_ref, dv_ref = rest
    kj = pl.program_id(1)
    kb = k_ref[0].astype(jnp.float32)                  # [Bk, D]
    vb = v_ref[0].astype(jnp.float32)
    bk, d = kb.shape
    nq = seq_q // block_q

    def body(i, carry):
        dk, dv, db = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32) * scale                       # [Bq, D]
        dob = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)]  # [Bq]
        dlt = dl_ref[0, 0, pl.ds(i * block_q, block_q)]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [Bq, Bk]
        if has_bias:
            s = s + b_ref[0, 0, :].astype(jnp.float32)[None, :]
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            k_pos = kj * bk + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                  # [Bq, Bk]
        # dV += pᵀ·dO
        dv = dv + jax.lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [Bk, D]
        # dp = dO·vᵀ ; ds = p ⊙ (dp − delta)
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [Bq, Bk]
        ds = p * (dp - dlt[:, None])
        # dK += dsᵀ·(q·scale)  (qb already carries the scale)
        dk = dk + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [Bk, D]
        if has_bias:
            db = db + ds.sum(0)
        return dk, dv, db

    if causal:
        lower = (kj * bk) // block_q  # q blocks fully above diag are dead
    else:
        lower = 0
    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    db0 = jnp.zeros((bk,), jnp.float32)
    dk, dv, db = jax.lax.fori_loop(lower, nq, body, (dk0, dv0, db0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)
    if has_bias:
        db_ref[0, 0] = db


def _fa_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, *rest,
                  block_k, causal, scale, seq_k, has_bias=False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if has_bias:
        b_ref, dq_ref = rest
    else:
        (dq_ref,) = rest
    qi = pl.program_id(1)
    qb = q_ref[0].astype(jnp.float32) * scale          # [Bq, D]
    dob = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0]                                # [Bq]
    dlt = dl_ref[0, 0]
    bq, d = qb.shape
    nk = seq_k // block_k

    def body(j, acc):
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [Bq, Bk]
        if has_bias:
            bb = b_ref[0, 0, pl.ds(j * block_k, block_k)].astype(
                jnp.float32)
            s = s + bb[None, :]
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - dlt[:, None])
        return acc + jnp.dot(ds, kb, preferred_element_type=jnp.float32)

    if causal:
        upper = jnp.minimum(nk, ((qi + 1) * bq + block_k - 1) // block_k)
    else:
        upper = nk
    acc0 = jnp.zeros((bq, d), jnp.float32)
    acc = jax.lax.fori_loop(0, upper, body, acc0)
    dq_ref[0] = (acc * scale).astype(dq_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, sm_scale, block_q,
                    block_k, interpret, bias=None):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)
    bq = _fit_block(block_q, Sq)
    bk = _fit_block(block_k, Sk)

    # delta = rowsum(dO ⊙ O) — cheap fused XLA reduce
    delta = (g.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)

    qr = q.reshape(B * H, Sq, D)
    kr = k.reshape(B * H, Sk, D)
    vr = v.reshape(B * H, Sk, D)
    gr = g.reshape(B * H, Sq, D)
    lser = lse.reshape(B * H, 1, Sq)
    dltr = delta.reshape(B * H, 1, Sq)
    has_bias = bias is not None

    # ---- dK / dV (+ per-head db) -------------------------------------
    dkv_kernel = functools.partial(
        _fa_dkv_kernel, block_q=bq, causal=causal, scale=scale, seq_q=Sq,
        has_bias=has_bias)
    in_specs = [
        pl.BlockSpec((1, Sq, D), lambda b, j: (b, 0, 0)),   # q (full)
        pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),   # k block
        pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),   # v block
        pl.BlockSpec((1, Sq, D), lambda b, j: (b, 0, 0)),   # dO (full)
        pl.BlockSpec((1, 1, Sq), lambda b, j: (b, 0, 0)),   # lse
        pl.BlockSpec((1, 1, Sq), lambda b, j: (b, 0, 0)),   # delta
    ]
    args = [qr, kr, vr, gr, lser, dltr]
    out_specs = [pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
                 pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0))]
    out_shapes = [jax.ShapeDtypeStruct((B * H, Sk, D), k.dtype),
                  jax.ShapeDtypeStruct((B * H, Sk, D), v.dtype)]
    if has_bias:
        in_specs.append(
            pl.BlockSpec((1, 1, bk), lambda b, j: (b // H, 0, j)))
        args.append(bias.reshape(B, 1, Sk))
        out_specs.append(pl.BlockSpec((1, 1, bk), lambda b, j: (b, 0, j)))
        out_shapes.append(
            jax.ShapeDtypeStruct((B * H, 1, Sk), np.float32))
    res = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, Sk // bk),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(*args)
    dk, dv = res[0].reshape(B, H, Sk, D), res[1].reshape(B, H, Sk, D)
    db = None
    if has_bias:
        # bias rows broadcast over heads (and q) — reduce the per-head sums
        db = res[2].reshape(B, H, Sk).sum(1).astype(bias.dtype)

    # ---- dQ ----------------------------------------------------------
    dq_kernel = functools.partial(
        _fa_dq_kernel, block_k=bk, causal=causal, scale=scale, seq_k=Sk,
        has_bias=has_bias)
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),   # q block
        pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),   # k (full)
        pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),   # v (full)
        pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),   # dO block
        pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i)),   # lse block
        pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i)),   # delta block
    ]
    args = [qr, kr, vr, gr, lser, dltr]
    if has_bias:
        in_specs.append(
            pl.BlockSpec((1, 1, Sk), lambda b, i: (b // H, 0, 0)))
        args.append(bias.reshape(B, 1, Sk))
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B * H, Sq // bq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        interpret=interpret,
    )(*args)
    dq = dq.reshape(B, H, Sq, D)
    return dq, dk, dv, db


# ---------------------------------------------------------------------------
# public entries: pallas forward + pallas backward via custom_vjp
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, sm_scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=False):
    """Multi-head attention, q/k/v: [B, H, S, D] -> [B, H, Sq, D]."""
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)[0]


def _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                              interpret)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    dq, dk, dv, _ = _flash_backward(q, k, v, out, lse, g, causal, sm_scale,
                                    block_q, block_k, interpret)
    return dq, dk, dv


flash_attention.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention_bias(q, k, v, bias, causal=False, sm_scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                         interpret=False):
    """flash_attention with an additive [B, Sk] score bias (padding
    mask). Separate entry so the unbiased path keeps its 3-arg vjp."""
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret, bias=bias)[0]


def _fab_fwd(q, k, v, bias, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                              interpret, bias=bias)
    return out, (q, k, v, bias, out, lse)


def _fab_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, bias, out, lse = res
    dq, dk, dv, db = _flash_backward(q, k, v, out, lse, g, causal, sm_scale,
                                     block_q, block_k, interpret, bias=bias)
    return dq, dk, dv, db


flash_attention_bias.defvjp(_fab_fwd, _fab_bwd)


# ---------------------------------------------------------------------------
# packed-QKV kernels: transpose-free attention on [B, S, 3H]
# ---------------------------------------------------------------------------
#
# The standard path costs ~2.4 GB/step of pure layout movement on the
# seq-512 BERT bench (xprof: the [B,S,3H] -> [3,B,h,S,d] transpose, the
# q/k/v slices, the ctx transpose back, and all their grads).  These
# kernels consume the fused QKV projection output directly: the grid is
# (batch, 128-lane column chunk, row block) and each cell reads its
# head-pair's columns via BlockSpec index maps (768 = 6 x 128, so chunk
# boundaries are lane-aligned and Mosaic-legal).  head_dim 64 packs two
# heads per chunk (static halves inside the kernel); head_dim 128 maps
# one-to-one.  No transpose, slice, or concat ever materializes in HBM
# on the forward; the backward assembles d(qkv) with one cheap concat.

def _packed_dims(qkv_shape, num_heads):
    B, S, threeH = qkv_shape
    H = threeH // 3
    D = H // num_heads
    if threeH != 3 * H or H % 128 or D not in (64, 128):
        raise ValueError(
            f"flash_attention_packed needs hidden % 128 == 0 and head_dim "
            f"in (64, 128); got qkv {qkv_shape}, num_heads {num_heads}")
    return B, S, H, D, H // 128, 128 // D


def _fp_fwd_kernel(q_ref, k_ref, v_ref, *rest, block_k, causal, scale,
                   seq_k, head_dim, hpc, has_bias=False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if has_bias:
        b_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
    qi = pl.program_id(2)
    nk = seq_k // block_k
    outs = []
    for h in range(hpc):
        q = q_ref[0][:, h * head_dim:(h + 1) * head_dim].astype(
            jnp.float32) * scale                       # [Bq, D]
        bq = q.shape[0]

        def body(j, carry, q=q, h=h, bq=bq):
            m, l, acc = carry
            kb = k_ref[0, pl.ds(j * block_k, block_k), :][
                :, h * head_dim:(h + 1) * head_dim].astype(jnp.float32)
            vb = v_ref[0, pl.ds(j * block_k, block_k), :][
                :, h * head_dim:(h + 1) * head_dim].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)    # [Bq, Bk]
            if has_bias:
                s = s + b_ref[0, 0, pl.ds(j * block_k, block_k)].astype(
                    jnp.float32)[None, :]
            if causal:
                q_pos = qi * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, block_k), 0)
                k_pos = j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, block_k), 1)
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            m_new = jnp.maximum(m, s.max(-1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
            l_new = l * corr + p.sum(-1, keepdims=True)
            acc_new = acc * corr + jnp.dot(
                p, vb, preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        m0 = jnp.full((q.shape[0], 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((q.shape[0], 1), jnp.float32)
        acc0 = jnp.zeros((q.shape[0], head_dim), jnp.float32)
        if causal:
            upper = jnp.minimum(
                nk, ((qi + 1) * q.shape[0] + block_k - 1) // block_k)
        else:
            upper = nk
        m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
        l_safe = jnp.maximum(l, 1e-30)
        outs.append(acc / l_safe)
        lse_ref[0, 0, h] = (m + jnp.log(l_safe))[:, 0]
    o_ref[0] = jnp.concatenate(outs, axis=1).astype(o_ref.dtype)


def _packed_forward(qkv, num_heads, causal, sm_scale, block_q, block_k,
                    interpret, bias=None):
    """qkv [B, S, 3H] -> (out [B, S, H], lse [B, HP, hpc, S] f32)."""
    import jax
    from jax.experimental import pallas as pl

    B, S, H, D, HP, hpc = _packed_dims(qkv.shape, num_heads)
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)
    bq = _fit_block(block_q, S)
    bk = _fit_block(block_k, S)

    kernel = functools.partial(
        _fp_fwd_kernel, block_k=bk, causal=causal, scale=scale, seq_k=S,
        head_dim=D, hpc=hpc, has_bias=bias is not None)
    in_specs = [
        pl.BlockSpec((1, bq, 128), lambda b, hp, i: (b, i, hp)),
        pl.BlockSpec((1, S, 128), lambda b, hp, i: (b, 0, HP + hp)),
        pl.BlockSpec((1, S, 128), lambda b, hp, i: (b, 0, 2 * HP + hp)),
    ]
    args = [qkv, qkv, qkv]
    if bias is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, S), lambda b, hp, i: (b, 0, 0)))
        args.append(bias.reshape(B, 1, S))
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, HP, S // bq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, 128), lambda b, hp, i: (b, i, hp)),
            pl.BlockSpec((1, 1, hpc, bq), lambda b, hp, i: (b, hp, 0, i)),
        ],
        out_shape=[jax.ShapeDtypeStruct((B, S, H), qkv.dtype),
                   jax.ShapeDtypeStruct((B, HP, hpc, S), np.float32)],
        interpret=interpret,
    )(*args)
    return out, lse


def _fp_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, *rest,
                   block_q, causal, scale, seq_q, head_dim, hpc,
                   has_bias=False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if has_bias:
        b_ref, dk_ref, dv_ref, db_ref = rest
    else:
        dk_ref, dv_ref = rest
    kj = pl.program_id(2)
    nq = seq_q // block_q
    db_acc = None
    dk_parts, dv_parts = [], []
    for h in range(hpc):
        kb = k_ref[0][:, h * head_dim:(h + 1) * head_dim].astype(
            jnp.float32)                               # [Bk, D]
        vb = v_ref[0][:, h * head_dim:(h + 1) * head_dim].astype(
            jnp.float32)
        bk = kb.shape[0]

        def body(i, carry, kb=kb, vb=vb, h=h, bk=bk):
            dk, dv, db = carry
            qb = q_ref[0, pl.ds(i * block_q, block_q), :][
                :, h * head_dim:(h + 1) * head_dim].astype(
                jnp.float32) * scale                   # [Bq, D]
            dob = do_ref[0, pl.ds(i * block_q, block_q), :][
                :, h * head_dim:(h + 1) * head_dim].astype(jnp.float32)
            lse = lse_ref[0, 0, h, pl.ds(i * block_q, block_q)]
            dlt = dl_ref[0, 0, h, pl.ds(i * block_q, block_q)]
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)    # [Bq, Bk]
            if has_bias:
                s = s + b_ref[0, 0, pl.ds(kj * bk, bk)].astype(
                    jnp.float32)[None, :]
            if causal:
                q_pos = i * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, bk), 0)
                k_pos = kj * bk + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, bk), 1)
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            p = jnp.exp(s - lse[:, None])
            dv = dv + jax.lax.dot_general(
                p, dob, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                dob, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - dlt[:, None])
            dk = dk + jax.lax.dot_general(
                ds, qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            if has_bias:
                db = db + ds.sum(0)
            return dk, dv, db

        lower = (kj * bk) // block_q if causal else 0
        dk0 = jnp.zeros((bk, head_dim), jnp.float32)
        dv0 = jnp.zeros((bk, head_dim), jnp.float32)
        db0 = jnp.zeros((bk,), jnp.float32)
        dk, dv, db = jax.lax.fori_loop(lower, nq, body, (dk0, dv0, db0))
        dk_parts.append(dk)
        dv_parts.append(dv)
        db_acc = db if db_acc is None else db_acc + db
    dk_ref[0] = jnp.concatenate(dk_parts, axis=1).astype(dk_ref.dtype)
    dv_ref[0] = jnp.concatenate(dv_parts, axis=1).astype(dv_ref.dtype)
    if has_bias:
        # the db row block spans full S and is revisited across the kv
        # grid; each cell writes its own bk-wide chunk
        bk = dk_ref.shape[1]
        db_ref[0, 0, pl.ds(kj * bk, bk)] = db_acc


def _fp_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, *rest,
                  block_k, causal, scale, seq_k, head_dim, hpc,
                  has_bias=False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if has_bias:
        b_ref, dq_ref = rest
    else:
        (dq_ref,) = rest
    qi = pl.program_id(2)
    nk = seq_k // block_k
    dq_parts = []
    for h in range(hpc):
        qb = q_ref[0][:, h * head_dim:(h + 1) * head_dim].astype(
            jnp.float32) * scale
        dob = do_ref[0][:, h * head_dim:(h + 1) * head_dim].astype(
            jnp.float32)
        lse = lse_ref[0, 0, h]
        dlt = dl_ref[0, 0, h]
        bq = qb.shape[0]

        def body(j, acc, qb=qb, dob=dob, lse=lse, dlt=dlt, h=h, bq=bq):
            kb = k_ref[0, pl.ds(j * block_k, block_k), :][
                :, h * head_dim:(h + 1) * head_dim].astype(jnp.float32)
            vb = v_ref[0, pl.ds(j * block_k, block_k), :][
                :, h * head_dim:(h + 1) * head_dim].astype(jnp.float32)
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if has_bias:
                s = s + b_ref[0, 0, pl.ds(j * block_k, block_k)].astype(
                    jnp.float32)[None, :]
            if causal:
                q_pos = qi * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, block_k), 0)
                k_pos = j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, block_k), 1)
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            p = jnp.exp(s - lse[:, None])
            dp = jax.lax.dot_general(
                dob, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - dlt[:, None])
            return acc + jnp.dot(ds, kb,
                                 preferred_element_type=jnp.float32)

        if causal:
            upper = jnp.minimum(nk, ((qi + 1) * bq + block_k - 1)
                                // block_k)
        else:
            upper = nk
        acc0 = jnp.zeros((bq, head_dim), jnp.float32)
        acc = jax.lax.fori_loop(0, upper, body, acc0)
        dq_parts.append(acc * scale)
    dq_ref[0] = jnp.concatenate(dq_parts, axis=1).astype(dq_ref.dtype)


def _packed_backward(qkv, num_heads, out, lse, g, causal, sm_scale,
                     block_q, block_k, interpret, bias=None):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    B, S, H, D, HP, hpc = _packed_dims(qkv.shape, num_heads)
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)
    bq = _fit_block(block_q, S)
    bk = _fit_block(block_k, S)
    has_bias = bias is not None

    # delta = rowsum(dO ⊙ O) per head, laid out to match lse
    prod = (g.astype(jnp.float32) * out.astype(jnp.float32))
    delta = prod.reshape(B, S, HP, hpc, D).sum(-1)       # [B,S,HP,hpc]
    delta = jnp.moveaxis(delta, 1, 3)                    # [B,HP,hpc,S]

    common_specs = [
        pl.BlockSpec((1, S, 128), lambda b, hp, j: (b, 0, hp)),        # q
        pl.BlockSpec((1, S, 128), lambda b, hp, j: (b, 0, HP + hp)),   # k
        pl.BlockSpec((1, S, 128), lambda b, hp, j: (b, 0, 2 * HP + hp)),
        pl.BlockSpec((1, S, 128), lambda b, hp, j: (b, 0, hp)),        # dO
        pl.BlockSpec((1, 1, hpc, S), lambda b, hp, j: (b, hp, 0, 0)),  # lse
        pl.BlockSpec((1, 1, hpc, S), lambda b, hp, j: (b, hp, 0, 0)),  # dlt
    ]

    # ---- dK / dV ------------------------------------------------------
    dkv_kernel = functools.partial(
        _fp_dkv_kernel, block_q=bq, causal=causal, scale=scale, seq_q=S,
        head_dim=D, hpc=hpc, has_bias=has_bias)
    in_specs = list(common_specs)
    in_specs[1] = pl.BlockSpec((1, bk, 128),
                               lambda b, hp, j: (b, j, HP + hp))
    in_specs[2] = pl.BlockSpec((1, bk, 128),
                               lambda b, hp, j: (b, j, 2 * HP + hp))
    args = [qkv, qkv, qkv, g, lse, delta]
    out_specs = [pl.BlockSpec((1, bk, 128), lambda b, hp, j: (b, j, hp)),
                 pl.BlockSpec((1, bk, 128), lambda b, hp, j: (b, j, hp))]
    out_shapes = [jax.ShapeDtypeStruct((B, S, H), qkv.dtype),
                  jax.ShapeDtypeStruct((B, S, H), qkv.dtype)]
    if has_bias:
        in_specs.append(pl.BlockSpec((1, 1, S), lambda b, hp, j: (b, 0, 0)))
        args.append(bias.reshape(B, 1, S))
        out_specs.append(pl.BlockSpec(
            (1, 1, S), lambda b, hp, j: (b * HP + hp, 0, 0)))
        out_shapes.append(jax.ShapeDtypeStruct((B * HP, 1, S), np.float32))
    res = pl.pallas_call(
        dkv_kernel,
        grid=(B, HP, S // bk),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(*args)
    dk, dv = res[0], res[1]
    db = None
    if has_bias:
        db = res[2].reshape(B, HP, S).sum(1).astype(bias.dtype)

    # ---- dQ -----------------------------------------------------------
    dq_kernel = functools.partial(
        _fp_dq_kernel, block_k=bk, causal=causal, scale=scale, seq_k=S,
        head_dim=D, hpc=hpc, has_bias=has_bias)
    in_specs = list(common_specs)
    in_specs[0] = pl.BlockSpec((1, bq, 128), lambda b, hp, i: (b, i, hp))
    in_specs[3] = pl.BlockSpec((1, bq, 128), lambda b, hp, i: (b, i, hp))
    in_specs[4] = pl.BlockSpec((1, 1, hpc, bq),
                               lambda b, hp, i: (b, hp, 0, i))
    in_specs[5] = pl.BlockSpec((1, 1, hpc, bq),
                               lambda b, hp, i: (b, hp, 0, i))
    args = [qkv, qkv, qkv, g, lse, delta]
    if has_bias:
        in_specs.append(pl.BlockSpec((1, 1, S), lambda b, hp, i: (b, 0, 0)))
        args.append(bias.reshape(B, 1, S))
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B, HP, S // bq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, 128), lambda b, hp, i: (b, i, hp)),
        out_shape=jax.ShapeDtypeStruct((B, S, H), qkv.dtype),
        interpret=interpret,
    )(*args)

    dqkv = jnp.concatenate([dq, dk, dv], axis=-1)
    return dqkv, db


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def flash_attention_packed(qkv, num_heads, causal=False, sm_scale=None,
                           block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                           interpret=False):
    """Transpose-free attention on the fused projection: qkv [B, S, 3H]
    -> [B, S, H]. Requires H % 128 == 0 and head_dim in (64, 128)."""
    return _packed_forward(qkv, num_heads, causal, sm_scale, block_q,
                           block_k, interpret)[0]


def _fpk_fwd(qkv, num_heads, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _packed_forward(qkv, num_heads, causal, sm_scale, block_q,
                               block_k, interpret)
    return out, (qkv, out, lse)


def _fpk_bwd(num_heads, causal, sm_scale, block_q, block_k, interpret,
             res, g):
    qkv, out, lse = res
    dqkv, _ = _packed_backward(qkv, num_heads, out, lse, g, causal,
                               sm_scale, block_q, block_k, interpret)
    return (dqkv,)


flash_attention_packed.defvjp(_fpk_fwd, _fpk_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def flash_attention_packed_bias(qkv, bias, num_heads, causal=False,
                                sm_scale=None, block_q=DEFAULT_BLOCK_Q,
                                block_k=DEFAULT_BLOCK_K, interpret=False):
    """flash_attention_packed with an additive [B, S] score bias."""
    return _packed_forward(qkv, num_heads, causal, sm_scale, block_q,
                           block_k, interpret, bias=bias)[0]


def _fpkb_fwd(qkv, bias, num_heads, causal, sm_scale, block_q, block_k,
              interpret):
    out, lse = _packed_forward(qkv, num_heads, causal, sm_scale, block_q,
                               block_k, interpret, bias=bias)
    return out, (qkv, bias, out, lse)


def _fpkb_bwd(num_heads, causal, sm_scale, block_q, block_k, interpret,
              res, g):
    qkv, bias, out, lse = res
    dqkv, db = _packed_backward(qkv, num_heads, out, lse, g, causal,
                                sm_scale, block_q, block_k, interpret,
                                bias=bias)
    return dqkv, db


flash_attention_packed_bias.defvjp(_fpkb_fwd, _fpkb_bwd)
