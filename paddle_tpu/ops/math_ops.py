"""Math / elementwise / reduction / matmul op lowerings.

Replaces the reference's hand-written CPU/CUDA kernels for these ops
(operators/elementwise/*, operators/reduce_ops/*, operators/matmul_op.cc,
operators/activation_op.*, operators/scale_op.cc, operators/sum_op.cc,
operators/cast_op.cc, operators/clip_op.cc) with jax.numpy/lax lowerings
fused by XLA.  Broadcasting follows the reference's axis-aligned rule
(operators/elementwise/elementwise_op_function.h).
"""
from __future__ import annotations

import numpy as np

from ..errors import InvalidArgumentError
from ..framework.core import Block, Operator, convert_dtype, dtype_to_np
from .registry import (LowerContext, broadcast_shapes, in_var, register_op,
                       same_as_input, set_out)


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# elementwise binary ops (broadcast with paddle `axis` semantics)
# ---------------------------------------------------------------------------

def _ew_infer(op: Operator, block: Block):
    x = in_var(op, block, "X")
    y = in_var(op, block, "Y")
    axis = op.attr("axis", -1)
    shape = broadcast_shapes(list(x.shape), list(y.shape), axis)
    set_out(op, block, "Out", shape, x.dtype)


def _align_y(x, y, axis):
    jnp = _jnp()
    xr, yr = jnp.ndim(x), jnp.ndim(y)
    if yr < xr:
        if axis == -1:
            axis = xr - yr
        y = jnp.reshape(y, (1,) * axis + tuple(jnp.shape(y)) +
                        (1,) * (xr - axis - yr))
    elif xr < yr:
        if axis == -1:
            axis = yr - xr
        x = jnp.reshape(x, (1,) * axis + tuple(jnp.shape(x)) +
                        (1,) * (yr - axis - xr))
    return x, y


def _make_ew(op_type, fn):
    def lower(ctx: LowerContext, op: Operator):
        from ..framework.selected_rows import densify

        # SELECTED_ROWS operands densify here (grad-clip pipelines
        # square/scale grads elementwise); sparsity-preserving consumers
        # are sum/scale/optimizer ops
        x = densify(ctx.get_input(op, "X"))
        y = densify(ctx.get_input(op, "Y"))
        x, y = _align_y(x, y, op.attr("axis", -1))
        ctx.set_output(op, "Out", fn(x, y))
    register_op(op_type, infer=_ew_infer, lower=lower)


_make_ew("elementwise_add", lambda x, y: x + y)
_make_ew("elementwise_sub", lambda x, y: x - y)
_make_ew("elementwise_mul", lambda x, y: x * y)
_make_ew("elementwise_div", lambda x, y: x / y)
_make_ew("elementwise_min", lambda x, y: _jnp().minimum(x, y))
_make_ew("elementwise_max", lambda x, y: _jnp().maximum(x, y))
_make_ew("elementwise_pow", lambda x, y: _jnp().power(x, y))
_make_ew("elementwise_mod", lambda x, y: _jnp().mod(x, y))
_make_ew("elementwise_floordiv", lambda x, y: _jnp().floor_divide(x, y))


# ---------------------------------------------------------------------------
# comparison / logical (non-differentiable)
# ---------------------------------------------------------------------------

def _cmp_infer(op: Operator, block: Block):
    x = in_var(op, block, "X")
    y = in_var(op, block, "Y")
    shape = broadcast_shapes(list(x.shape), list(y.shape), op.attr("axis", -1))
    set_out(op, block, "Out", shape, "bool")


def _make_cmp(op_type, fn):
    def lower(ctx, op):
        x, y = ctx.get_input(op, "X"), ctx.get_input(op, "Y")
        ctx.set_output(op, "Out", fn(x, y))
    register_op(op_type, infer=_cmp_infer, lower=lower, grad=None)


_make_cmp("less_than", lambda x, y: x < y)
_make_cmp("less_equal", lambda x, y: x <= y)
_make_cmp("greater_than", lambda x, y: x > y)
_make_cmp("greater_equal", lambda x, y: x >= y)
_make_cmp("equal", lambda x, y: x == y)
_make_cmp("not_equal", lambda x, y: x != y)
_make_cmp("logical_and", lambda x, y: _jnp().logical_and(x, y))
_make_cmp("logical_or", lambda x, y: _jnp().logical_or(x, y))
_make_cmp("logical_xor", lambda x, y: _jnp().logical_xor(x, y))


@register_op("logical_not", infer=same_as_input(), grad=None)
def _logical_not(ctx, op):
    ctx.set_output(op, "Out", _jnp().logical_not(ctx.get_input(op, "X")))


@register_op("isfinite_v2", infer=same_as_input(), grad=None)
def _isfinite(ctx, op):
    ctx.set_output(op, "Out", _jnp().isfinite(ctx.get_input(op, "X")))


def _isfinite_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, "bool")


for _t in ("isfinite_v2", "isnan_v2", "isinf_v2"):
    pass  # shapes fixed below

register_op("isnan_v2", infer=_isfinite_infer, grad=None,
            lower=lambda ctx, op: ctx.set_output(
                op, "Out", _jnp().isnan(ctx.get_input(op, "X"))))
register_op("isinf_v2", infer=_isfinite_infer, grad=None,
            lower=lambda ctx, op: ctx.set_output(
                op, "Out", _jnp().isinf(ctx.get_input(op, "X"))))
_REG_FIX = True
# fix isfinite_v2 infer (bool output)
from .registry import _REGISTRY  # noqa: E402
_REGISTRY["isfinite_v2"].infer = _isfinite_infer


# ---------------------------------------------------------------------------
# unary activations & pointwise math
# ---------------------------------------------------------------------------

def _make_unary(op_type, fn, grad="auto"):
    def lower(ctx: LowerContext, op: Operator):
        ctx.set_output(op, "Out", fn(ctx.get_input(op, "X"), op))
    register_op(op_type, infer=same_as_input(), lower=lower, grad=grad)


def _jnn():
    import jax.nn
    return jax.nn


_make_unary("relu", lambda x, op: _jnp().maximum(x, 0))
_make_unary("relu6", lambda x, op: _jnp().clip(x, 0, op.attr("threshold", 6.0)))
_make_unary("sigmoid", lambda x, op: _jnn().sigmoid(x))
_make_unary("tanh", lambda x, op: _jnp().tanh(x))
_make_unary("exp", lambda x, op: _jnp().exp(x))
_make_unary("log", lambda x, op: _jnp().log(x))
_make_unary("log2", lambda x, op: _jnp().log2(x))
_make_unary("log10", lambda x, op: _jnp().log10(x))
_make_unary("log1p", lambda x, op: _jnp().log1p(x))
_make_unary("sqrt", lambda x, op: _jnp().sqrt(x))
_make_unary("rsqrt", lambda x, op: 1.0 / _jnp().sqrt(x))
_make_unary("square", lambda x, op: x * x)
_make_unary("abs", lambda x, op: _jnp().abs(x))
_make_unary("reciprocal", lambda x, op: 1.0 / x)
_make_unary("floor", lambda x, op: _jnp().floor(x))
_make_unary("ceil", lambda x, op: _jnp().ceil(x))
_make_unary("round", lambda x, op: _jnp().round(x))
_make_unary("sin", lambda x, op: _jnp().sin(x))
_make_unary("cos", lambda x, op: _jnp().cos(x))
_make_unary("tan", lambda x, op: _jnp().tan(x))
_make_unary("asin", lambda x, op: _jnp().arcsin(x))
_make_unary("acos", lambda x, op: _jnp().arccos(x))
_make_unary("atan", lambda x, op: _jnp().arctan(x))
_make_unary("sinh", lambda x, op: _jnp().sinh(x))
_make_unary("cosh", lambda x, op: _jnp().cosh(x))
_make_unary("erf", lambda x, op: __import__("jax").scipy.special.erf(x))
_make_unary("gelu", lambda x, op: _jnn().gelu(
    x, approximate=op.attr("approximate", False)))
_make_unary("softplus", lambda x, op: _jnn().softplus(x))
_make_unary("softsign", lambda x, op: _jnn().soft_sign(x))
_make_unary("silu", lambda x, op: _jnn().silu(x))
_make_unary("swish", lambda x, op: x * _jnn().sigmoid(
    op.attr("beta", 1.0) * x))
_make_unary("mish", lambda x, op: x * _jnp().tanh(_jnn().softplus(x)))
_make_unary("hard_sigmoid", lambda x, op: _jnp().clip(
    op.attr("slope", 0.2) * x + op.attr("offset", 0.5), 0, 1))
_make_unary("hard_swish", lambda x, op: x * _jnp().clip(
    x + op.attr("offset", 3.0), 0, op.attr("threshold", 6.0))
    / op.attr("scale", 6.0))
_make_unary("leaky_relu", lambda x, op: _jnn().leaky_relu(
    x, op.attr("alpha", 0.02)))
_make_unary("elu", lambda x, op: _jnn().elu(x, op.attr("alpha", 1.0)))
_make_unary("logsigmoid", lambda x, op: _jnn().log_sigmoid(x))
_make_unary("sign", lambda x, op: _jnp().sign(x), grad=None)


def _clip_value(x, op):
    """reference clip_op.h — the SelectedRows branch merges, then clips
    the values slab (untouched rows are implicitly 0, kept as-is)."""
    from ..framework.selected_rows import is_selected_rows

    lo = op.attr("min", float("-inf"))
    hi = op.attr("max", float("inf"))
    if is_selected_rows(x):
        m = x.merge()
        return type(m)(m.rows, _jnp().clip(m.values, lo, hi), m.height)
    return _jnp().clip(x, lo, hi)


_make_unary("clip", _clip_value)
_make_unary("assign", lambda x, op: x)
_make_unary("share_data", lambda x, op: x)


@register_op("scale", infer=same_as_input())
def _scale(ctx: LowerContext, op: Operator):
    from ..framework.selected_rows import is_selected_rows

    x = ctx.get_input(op, "X")
    scale = op.attr("scale", 1.0)
    if op.single_input("ScaleTensor"):
        scale = ctx.get_input(op, "ScaleTensor")
    bias = op.attr("bias", 0.0)
    if is_selected_rows(x):
        # sparsity-preserving (bias on a sparse grad would densify;
        # the framework only emits bias=0 scales on grads)
        if bias != 0.0:
            x = x.to_dense()
        else:
            ctx.set_output(op, "Out", x.scale(scale))
            return
    if op.attr("bias_after_scale", True):
        out = x * scale + bias
    else:
        out = (x + bias) * scale
    ctx.set_output(op, "Out", out)


@register_op("increment", infer=same_as_input())
def _increment(ctx: LowerContext, op: Operator):
    """Out = X + step, dtype-preserving (reference increment_op.cc) — used
    for int step counters, where a scale op would promote to float."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    step = op.attr("step", 1.0)
    ctx.set_output(op, "Out", x + jnp.asarray(step).astype(x.dtype))


@register_op("pow", infer=same_as_input())
def _pow(ctx, op):
    x = ctx.get_input(op, "X")
    factor = op.attr("factor", 1.0)
    if op.single_input("FactorTensor"):
        factor = ctx.get_input(op, "FactorTensor")
    ctx.set_output(op, "Out", _jnp().power(x, factor))


def _cast_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, op.attr("out_dtype", "float32"))


@register_op("cast", infer=_cast_infer)
def _cast(ctx, op):
    x = ctx.get_input(op, "X")
    ctx.set_output(op, "Out",
                   x.astype(dtype_to_np(op.attr("out_dtype", "float32"))))


# ---------------------------------------------------------------------------
# matmul family
# ---------------------------------------------------------------------------

def _matmul_shape(xs, ys, tx, ty):
    xs, ys = list(xs), list(ys)
    x1 = len(xs) == 1
    y1 = len(ys) == 1
    if x1:
        xs = [1, xs[0]]
    if y1:
        ys = [ys[0], 1]
    # transpose flags are ignored for 1-D operands, matching the
    # lowering's `ndim >= 2` condition
    if tx and not x1:
        xs = xs[:-2] + [xs[-1], xs[-2]]
    if ty and not y1:
        ys = ys[:-2] + [ys[-1], ys[-2]]
    if not (int(xs[-1]) == int(ys[-2]) or -1 in (int(xs[-1]),
                                                 int(ys[-2]))):
        raise InvalidArgumentError(
            f"matmul contraction mismatch: X{tuple(xs)} @ Y{tuple(ys)} "
            f"(K={xs[-1]} vs {ys[-2]})")
    batch = xs[:-2] if len(xs) >= len(ys) else ys[:-2]
    out = list(batch) + [xs[-2], ys[-1]]
    if x1:
        out.pop(-2)
    if y1:
        out.pop(-1)
    if not out:
        out = [1]
    return tuple(out)


def _matmul_infer(op: Operator, block: Block):
    x, y = in_var(op, block, "X"), in_var(op, block, "Y")
    tx = op.attr("trans_x", op.attr("transpose_X", False))
    ty = op.attr("trans_y", op.attr("transpose_Y", False))
    set_out(op, block, "Out", _matmul_shape(x.shape, y.shape, tx, ty), x.dtype)


def _matmul_lower(ctx: LowerContext, op: Operator):
    jnp = _jnp()
    x, y = ctx.get_input(op, "X"), ctx.get_input(op, "Y")
    tx = op.attr("trans_x", op.attr("transpose_X", False))
    ty = op.attr("trans_y", op.attr("transpose_Y", False))
    if tx and jnp.ndim(x) >= 2:
        x = jnp.swapaxes(x, -1, -2)
    if ty and jnp.ndim(y) >= 2:
        y = jnp.swapaxes(y, -1, -2)
    # On the MXU, accumulate matmuls in f32 even for bf16 operands.
    out = jnp.matmul(x, y, preferred_element_type=_acc_dtype(x.dtype),
                     precision=_mm_precision(x.dtype))
    out = out.astype(x.dtype)
    alpha = op.attr("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    ctx.set_output(op, "Out", out)


def _acc_dtype(dtype):
    jnp = _jnp()
    if dtype in (jnp.bfloat16, np.float16):
        return jnp.float32
    return dtype


def _mm_precision(dtype):
    """f32 operands compute at full precision (reference cuBLAS semantics);
    bf16/f16 operands ride the fast MXU path — speed is an explicit
    dtype/AMP choice, not a silent truncation.  On CPU, DEFAULT is already
    full f32 (and non-default precisions compile pathologically slowly)."""
    import jax
    jnp = _jnp()
    if dtype in (jnp.bfloat16, np.float16):
        return None
    if jax.default_backend() == "cpu":
        return None
    return jax.lax.Precision.HIGHEST


register_op("matmul_v2", infer=_matmul_infer, lower=_matmul_lower)
register_op("matmul", infer=_matmul_infer, lower=_matmul_lower)


def _mul_infer(op: Operator, block: Block):
    # reference `mul_op`: flatten x to 2-D at x_num_col_dims, y likewise.
    x, y = in_var(op, block, "X"), in_var(op, block, "Y")
    xd = op.attr("x_num_col_dims", 1)
    yd = op.attr("y_num_col_dims", 1)
    out = list(x.shape[:xd]) + list(y.shape[yd:])
    set_out(op, block, "Out", out, x.dtype)


@register_op("mul", infer=_mul_infer)
def _mul_lower(ctx: LowerContext, op: Operator):
    import jax
    jnp = _jnp()
    x, y = ctx.get_input(op, "X"), ctx.get_input(op, "Y")
    xd = op.attr("x_num_col_dims", 1)
    yd = op.attr("y_num_col_dims", 1)
    xs, ys = jnp.shape(x), jnp.shape(y)
    if list(xs[xd:]) == list(ys[:yd]):
        # contraction factorizations line up: contract directly with
        # dot_general, leading dims stay free. The reshape-to-2D-and-back
        # formulation costs real HBM copies when XLA's tiled layouts
        # differ across the reshape (profiled 3 GB/step of bf16
        # [B,S,I] copies on the seq-128 BERT flagship at batch 160 —
        # ~15% of device time as 'copy' ops)
        dn = ((tuple(range(xd, len(xs))), tuple(range(yd))), ((), ()))
        out = jax.lax.dot_general(
            x, y, dn, preferred_element_type=_acc_dtype(x.dtype),
            precision=_mm_precision(x.dtype))
        ctx.set_output(op, "Out", out.astype(x.dtype))
        return
    x2 = jnp.reshape(x, (int(np.prod(xs[:xd])), -1))
    y2 = jnp.reshape(y, (int(np.prod(ys[:yd])), -1))
    out = jnp.matmul(x2, y2, preferred_element_type=_acc_dtype(x2.dtype),
                     precision=_mm_precision(x2.dtype))
    out = out.astype(x2.dtype)
    ctx.set_output(op, "Out", jnp.reshape(out, xs[:xd] + ys[yd:]))


@register_op("dot", infer=lambda op, block: set_out(
    op, block, "Out", list(in_var(op, block, "X").shape[:-1]) or [1],
    in_var(op, block, "X").dtype))
def _dot(ctx, op):
    jnp = _jnp()
    x, y = ctx.get_input(op, "X"), ctx.get_input(op, "Y")
    ctx.set_output(op, "Out", jnp.sum(x * y, axis=-1))


@register_op("bmm", infer=_matmul_infer)
def _bmm(ctx, op):
    jnp = _jnp()
    x, y = ctx.get_input(op, "X"), ctx.get_input(op, "Y")
    out = jnp.matmul(x, y, preferred_element_type=_acc_dtype(x.dtype),
                     precision=_mm_precision(x.dtype))
    ctx.set_output(op, "Out", out.astype(x.dtype))


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

def _reduce_infer(op: Operator, block: Block):
    x = in_var(op, block, "X")
    dims = op.attr("dim", [0])
    keep = op.attr("keep_dim", False)
    if op.attr("reduce_all", False) or dims is None or dims == []:
        shape = [1] * len(x.shape) if keep else []
    else:
        dims = [d % len(x.shape) for d in
                (dims if isinstance(dims, (list, tuple)) else [dims])]
        shape = [(1 if i in dims else s) if keep else s
                 for i, s in enumerate(x.shape) if keep or i not in dims]
    if not shape:
        shape = []
    dtype = op.attr("out_dtype") or x.dtype
    set_out(op, block, "Out", shape, dtype)


def _make_reduce(op_type, fn, grad="auto"):
    def lower(ctx: LowerContext, op: Operator):
        jnp = _jnp()
        x = ctx.get_input(op, "X")
        keep = op.attr("keep_dim", False)
        if op.attr("reduce_all", False) or not op.attr("dim", [0]):
            axis = None
        else:
            dims = op.attr("dim", [0])
            dims = dims if isinstance(dims, (list, tuple)) else [dims]
            axis = tuple(d % jnp.ndim(x) for d in dims)
        out = fn(x, axis, keep)
        if op.attr("out_dtype"):
            out = out.astype(dtype_to_np(op.attr("out_dtype")))
        ctx.set_output(op, "Out", out)
    register_op(op_type, infer=_reduce_infer, lower=lower, grad=grad)


_make_reduce("reduce_sum", lambda x, a, k: _jnp().sum(x, axis=a, keepdims=k))
_make_reduce("reduce_mean", lambda x, a, k: _jnp().mean(x, axis=a, keepdims=k))
_make_reduce("reduce_max", lambda x, a, k: _jnp().max(x, axis=a, keepdims=k))
_make_reduce("reduce_min", lambda x, a, k: _jnp().min(x, axis=a, keepdims=k))
_make_reduce("reduce_prod", lambda x, a, k: _jnp().prod(x, axis=a, keepdims=k))
_make_reduce("reduce_any",
             lambda x, a, k: _jnp().any(x, axis=a, keepdims=k), grad=None)
_make_reduce("reduce_all",
             lambda x, a, k: _jnp().all(x, axis=a, keepdims=k), grad=None)
_make_reduce("logsumexp", lambda x, a, k: __import__("jax").scipy.special
             .logsumexp(x, axis=a, keepdims=k))


def _mean_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", [], x.dtype)


@register_op("mean", infer=_mean_infer)
def _mean(ctx, op):
    ctx.set_output(op, "Out", _jnp().mean(ctx.get_input(op, "X")))


def _sum_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)


@register_op("sum", infer=_sum_infer)
def _sum(ctx, op):
    """Add N tensors (reference sum_op, used for gradient accumulation).
    All-SelectedRows inputs concatenate (reference sum_op SelectedRows
    branch); mixed inputs densify."""
    from ..framework.selected_rows import (concat_selected_rows,
                                           is_selected_rows)

    xs = ctx.get_inputs(op, "X")
    if xs and all(is_selected_rows(x) for x in xs):
        out = xs[0] if len(xs) == 1 else concat_selected_rows(xs)
        ctx.set_output(op, "Out", out)
        return
    xs = [x.to_dense() if is_selected_rows(x) else x for x in xs]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    ctx.set_output(op, "Out", out)


@register_op("p_norm", infer=lambda op, block: _reduce_like_pnorm(op, block))
def _p_norm(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    porder = op.attr("porder", 2.0)
    axis = op.attr("axis", -1)
    keep = op.attr("keepdim", False)
    if op.attr("asvector", False):
        axis = None
    out = jnp.linalg.norm(x, ord=porder,
                          axis=axis if axis is None else int(axis),
                          keepdims=keep)
    ctx.set_output(op, "Out", out)


def _reduce_like_pnorm(op, block):
    x = in_var(op, block, "X")
    if op.attr("asvector", False):
        set_out(op, block, "Out", [], x.dtype)
        return
    axis = op.attr("axis", -1) % len(x.shape)
    keep = op.attr("keepdim", False)
    shape = [(1 if i == axis else s) for i, s in enumerate(x.shape)
             if keep or i != axis]
    set_out(op, block, "Out", shape, x.dtype)


# cumulative ops
@register_op("cumsum", infer=same_as_input())
def _cumsum(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    axis = op.attr("axis", -1)
    if op.attr("flatten", False):
        x = jnp.ravel(x)
        axis = 0
    out = jnp.cumsum(x, axis=axis)
    if op.attr("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    if op.attr("exclusive", False):
        out = out - x
    ctx.set_output(op, "Out", out)


@register_op("clip_by_norm", infer=same_as_input())
def _clip_by_norm(ctx, op):
    from ..framework.selected_rows import is_selected_rows

    jnp = _jnp()
    x = ctx.get_input(op, "X")
    max_norm = op.attr("max_norm", 1.0)
    if is_selected_rows(x):
        # reference clip_by_norm_op.h SelectedRows branch: MergeAdd,
        # then norm/scale the values slab (stays sparse)
        m = x.merge()
        norm = jnp.sqrt(jnp.sum(m.values * m.values))
        vals = jnp.where(norm > max_norm,
                         m.values * (max_norm / norm), m.values)
        ctx.set_output(op, "Out", type(m)(m.rows, vals, m.height))
        return
    norm = jnp.sqrt(jnp.sum(x * x))
    ctx.set_output(op, "Out",
                   jnp.where(norm > max_norm, x * (max_norm / norm), x))


@register_op("max", infer=_reduce_infer)
def _max(ctx, op):
    _REGISTRY["reduce_max"].lower(ctx, op)


@register_op("min", infer=_reduce_infer)
def _min(ctx, op):
    _REGISTRY["reduce_min"].lower(ctx, op)


def _global_norm_sq_infer(op, block):
    set_out(op, block, "Out", (), "float32")


@register_op("global_norm_sq", infer=_global_norm_sq_infer)
def _global_norm_sq(ctx, op):
    """sum_i ||x_i||^2 over ALL inputs in one concat+vdot fusion.

    Opt-in alternative (clip.py PT_FUSED_GLOBAL_CLIP=1) to the per-grad
    square+reduce chain — measured SLOWER on v5e BERT-base (the concat
    materializes the full gradient set), kept for param-count-heavy
    models where launch overhead dominates."""
    jnp = _jnp()
    from ..framework.selected_rows import densify
    xs = [densify(x) for x in ctx.get_inputs(op, "X")]
    flat = jnp.concatenate(
        [x.astype("float32").reshape(-1) for x in xs])
    ctx.set_output(op, "Out", jnp.vdot(flat, flat))
