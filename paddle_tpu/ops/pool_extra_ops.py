"""max_pool2d/3d_with_index and unpool.

Reference: paddle/fluid/operators/pool_with_index_op.cc (+ the
MaxPool2d/3dWithIndexFunctor in operators/math/pooling.cc — Mask holds
the flat h*W+w index of the max within the *input* feature map) and
operators/unpool_op.cc (max-unpooling: scatter by saved indices).

TPU-first design: pooling windows become a static k-tap gather per
spatial axis (same trick as interp_extra_ops) — taps and validity masks
are precomputed host-side, the patch tensor (N,C,out...,k...) is one
fused gather, and max/argmax reduce over the tap axes on the VPU. Both
uniform (stride/pad) and adaptive windows fit the same formulation
(adaptive start/end = floor/ceil divisions, padded to the max window
with invalid taps masked to -inf). No data-dependent shapes; grads via
auto-vjp (argmax is int-valued and naturally stop-gradient; Out grads
flow through the masked max). unpool is a batched scatter-add into the
zeroed output, exact inverse of the recorded argmax.
"""
from __future__ import annotations

import numpy as np

from .registry import in_var, register_op, set_out


def _jnp():
    import jax.numpy as jnp
    return jnp


NEG = -3.0e38  # -inf stand-in that survives f32 casts


def _axis_taps(in_size, out_size, k, stride, pad, adaptive):
    """(idx [out,kmax] int32, valid [out,kmax] bool, kmax) for one axis."""
    o = np.arange(out_size)
    if adaptive:
        start = (o * in_size) // out_size
        end = -((-(o + 1) * in_size) // out_size)  # ceil div
        kmax = int((end - start).max())
        j = np.arange(kmax)
        idx = start[:, None] + j[None, :]
        valid = j[None, :] < (end - start)[:, None]
    else:
        start = o * stride - pad
        kmax = k
        idx = start[:, None] + np.arange(k)[None, :]
        valid = (idx >= 0) & (idx < in_size)
    return (np.clip(idx, 0, in_size - 1).astype(np.int32),
            valid, kmax)


def _pool_out_size(in_size, k, stride, pad, adaptive, out_attr):
    if adaptive:
        return out_attr
    return (in_size - k + 2 * pad) // stride + 1


def _with_index_infer(nd):
    def infer(op, block):
        x = in_var(op, block, "X")
        ks = op.attr("ksize")
        st = op.attr("strides", [1] * nd)
        pd = op.attr("paddings", [0] * nd)
        adaptive = bool(op.attr("adaptive", False))
        if op.attr("global_pooling", False):
            out_sp = [1] * nd
        else:
            out_sp = [
                _pool_out_size(x.shape[2 + i], ks[i], st[i], pd[i],
                               adaptive, ks[i]) for i in range(nd)]
        shape = tuple(x.shape[:2]) + tuple(out_sp)
        set_out(op, block, "Out", shape, x.dtype)
        set_out(op, block, "Mask", shape, "int32")
    return infer


def _with_index_lower(nd):
    def lower(ctx, op):
        jnp = _jnp()
        x = ctx.get_input(op, "X")
        spatial = x.shape[2:]
        ks = list(op.attr("ksize"))
        st = list(op.attr("strides", [1] * nd))
        pd = list(op.attr("paddings", [0] * nd))
        adaptive = bool(op.attr("adaptive", False))
        if op.attr("global_pooling", False):
            ks, st, pd, adaptive = list(spatial), [1] * nd, [0] * nd, False

        # per-axis taps (adaptive: ksize attr is the target output size)
        taps = []
        for i in range(nd):
            out_sz = (ks[i] if adaptive else
                      _pool_out_size(spatial[i], ks[i], st[i], pd[i],
                                     False, None))
            taps.append(_axis_taps(spatial[i], out_sz, ks[i], st[i],
                                   pd[i], adaptive))
        idx_axes = [t[0] for t in taps]
        kmaxes = [t[2] for t in taps]
        out_spatial = tuple(t[0].shape[0] for t in taps)

        # gather axis-by-axis: after axis i the tap axis sits right after
        # its spatial axis, giving (N, C, o0, k0, o1, k1, ...)
        patch = x.astype("float32")
        for i in range(nd):
            axis = 2 + 2 * i
            idx, _, kmax = taps[i]
            g = jnp.take(patch, jnp.asarray(idx.reshape(-1)), axis=axis)
            patch = g.reshape(patch.shape[:axis]
                              + (out_spatial[i], kmax)
                              + patch.shape[axis + 1:])
        # move tap axes last: (N, C, o0..o{nd-1}, k0..k{nd-1})
        perm = ([0, 1] + [2 + 2 * i for i in range(nd)]
                + [3 + 2 * i for i in range(nd)])
        patch = patch.transpose(perm)

        # full validity mask, built host-side in the final layout
        valid_np = np.ones((1, 1) + out_spatial + tuple(kmaxes), bool)
        for i, (_, valid, _) in enumerate(taps):
            shape = [1] * (2 + 2 * nd)
            shape[2 + i] = out_spatial[i]
            shape[2 + nd + i] = kmaxes[i]
            valid_np = valid_np & valid.reshape(shape)

        flat = patch.reshape(patch.shape[:2 + nd] + (-1,))
        vflat = jnp.asarray(
            valid_np.reshape(valid_np.shape[:2 + nd] + (-1,)))
        masked = jnp.where(vflat, flat, NEG)
        out = masked.max(-1)
        am = masked.argmax(-1)  # flat tap index over (k0*k1*...)

        # decode tap -> global flat input index (row-major over spatial)
        out_spatial = patch.shape[2:2 + nd]
        rem, coords = am, []
        for i in reversed(range(nd)):
            tap = rem % kmaxes[i]
            rem = rem // kmaxes[i]
            # idx_axes[i][o_i, tap] with o_i broadcast over out position
            oshape = [1] * (2 + nd)
            oshape[2 + i] = out_spatial[i]
            o_i = jnp.arange(out_spatial[i]).reshape(oshape)
            coords.append(jnp.asarray(idx_axes[i])[o_i, tap])
        coords = coords[::-1]
        mask = coords[0]
        for i in range(1, nd):
            mask = mask * spatial[i] + coords[i]
        ctx.set_output(op, "Out", out.astype(x.dtype))
        ctx.set_output(op, "Mask", mask.astype("int32"))
    return lower


register_op("max_pool2d_with_index", infer=_with_index_infer(2),
            lower=_with_index_lower(2))
register_op("max_pool3d_with_index", infer=_with_index_infer(3),
            lower=_with_index_lower(3))


def _unpool_infer(op, block):
    x = in_var(op, block, "X")
    ks = op.attr("ksize")
    st = op.attr("strides", [2, 2])
    pd = op.attr("paddings", [0, 0])
    out_sp = op.attr("output_size", None)
    if not out_sp:
        out_sp = [(x.shape[2 + i] - 1) * st[i] - 2 * pd[i] + ks[i]
                  for i in range(2)]
    set_out(op, block, "Out",
            (x.shape[0], x.shape[1], out_sp[0], out_sp[1]), x.dtype)


@register_op("unpool", infer=_unpool_infer)
def _unpool(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    ind = ctx.get_input(op, "Indices")
    n, c, h, w = x.shape
    ks = op.attr("ksize")
    st = op.attr("strides", [2, 2])
    pd = op.attr("paddings", [0, 0])
    out_sp = op.attr("output_size", None)
    if not out_sp:
        out_sp = [(h - 1) * st[0] - 2 * pd[0] + ks[0],
                  (w - 1) * st[1] - 2 * pd[1] + ks[1]]
    oh, ow = out_sp
    flat_x = x.reshape(n * c, h * w)
    flat_i = ind.reshape(n * c, h * w).astype("int32")
    rows = jnp.arange(n * c)[:, None]
    out = jnp.zeros((n * c, oh * ow), flat_x.dtype)
    out = out.at[rows, flat_i].add(flat_x)
    ctx.set_output(op, "Out", out.reshape(n, c, oh, ow))
