"""Round-5 op-catalog batch: shape/structural/loss/sampling long tail.

Reference analogs (paddle/fluid/operators/): space_to_depth_op.h:25 (the
darknet-reorg index mapping), crop_op.cc, crop_tensor_op.cc,
pad_constant_like_op.cc, expand_as_op.cc, expand_as_v2_op.cc,
frobenius_norm_op.cc, cross_entropy_op.h:227 (CrossEntropyOpKernel2),
where_index_op.cc, coalesce_tensor_op.cc, inplace_abn_op.cc,
detection/sigmoid_focal_loss_op.cu:33, shuffle_batch_op.cc,
sample_logits_op.cc, positive_negative_pair_op.cc, hash_op.cc.

TPU-first notes:
  * space_to_depth's reorg permutation collapses to reshape+transpose+
    reshape — pure layout ops XLA folds into neighbouring fusions.
  * where_index (nonzero) has a data-dependent output size; under jit we
    keep the static shape (numel, rank) with valid rows sorted first and
    -1 padding (same documented convention as masked_select's zero-fill).
  * sample_logits uses the log-uniform inverse-CDF sampler drawn with
    replacement; Probabilities are the marginal log-uniform probs
    (deviation: the reference's unique-sampling num_tries adjustment is
    not applied — documented here, not hidden).
  * hash uses a multiply-xor integer mix (splitmix64) instead of XXH64:
    same contract (deterministic int -> bucket), different constants.
"""
from __future__ import annotations

import numpy as np

from .registry import in_var, register_op, same_as_input, set_out


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# space_to_depth (darknet reorg)
# ---------------------------------------------------------------------------
def _s2d_infer(op, block):
    x = in_var(op, block, "X")
    bs = int(op.attr("blocksize"))
    set_out(op, block, "Out", (x.shape[0], x.shape[1] * bs * bs,
                               x.shape[2] // bs, x.shape[3] // bs),
            x.dtype)


@register_op("space_to_depth", infer=_s2d_infer)
def _space_to_depth(ctx, op):
    x = ctx.get_input(op, "X")
    bs = int(op.attr("blocksize"))
    b, c, h, w = x.shape
    c2 = c // (bs * bs)
    # reference functor: k = (od*bs+ow)*c2 + cc writes to (b, cc,
    # j*bs+od, i*bs+ow) of a (b, c2, h*bs, w*bs) buffer, reinterpreted
    # as (b, c*bs*bs, h/bs, w/bs)
    y = x.reshape(b, bs, bs, c2, h, w).transpose(0, 3, 4, 1, 5, 2)
    ctx.set_output(op, "Out",
                   y.reshape(b, c * bs * bs, h // bs, w // bs))


# ---------------------------------------------------------------------------
# crop family
# ---------------------------------------------------------------------------
def _crop_infer(op, block):
    x = in_var(op, block, "X")
    shape = op.attr("shape", None) or list(in_var(op, block, "Y").shape)
    set_out(op, block, "Out", shape, x.dtype)


def _crop_lower(ctx, op):
    x = ctx.get_input(op, "X")
    shape = op.attr("shape", None)
    if not shape:
        shape = list(ctx.get_input(op, "Y").shape)
    offsets = op.attr("offsets", None) or [0] * len(shape)
    sl = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    ctx.set_output(op, "Out", x[sl])


register_op("crop", infer=_crop_infer, lower=_crop_lower)
register_op("crop_tensor", infer=_crop_infer, lower=_crop_lower)


def _pad_like_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, in_var(op, block, "Y").dtype)


@register_op("pad_constant_like", infer=_pad_like_infer)
def _pad_constant_like(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")  # the larger, shape-giving tensor
    y = ctx.get_input(op, "Y")
    val = op.attr("pad_value", 0.0)
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    ctx.set_output(op, "Out", jnp.pad(y, pads, constant_values=val))


# ---------------------------------------------------------------------------
# expand_as family
# ---------------------------------------------------------------------------
def _expand_as_infer(op, block):
    slot = "target_tensor" if op.input("target_tensor") else "Y"
    set_out(op, block, "Out", in_var(op, block, slot).shape,
            in_var(op, block, "X").dtype)


@register_op("expand_as", infer=_expand_as_infer)
def _expand_as(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    slot = "target_tensor" if op.input("target_tensor") else "Y"
    t = ctx.get_input(op, slot)
    reps = [ts // xs for ts, xs in zip(t.shape, x.shape)]
    ctx.set_output(op, "Out", jnp.tile(x, reps))


def _expand_as_v2_infer(op, block):
    shape = op.attr("target_shape", None)
    if not shape:
        shape = in_var(op, block, "Y").shape
    set_out(op, block, "Out", shape, in_var(op, block, "X").dtype)


@register_op("expand_as_v2", infer=_expand_as_v2_infer)
def _expand_as_v2(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    shape = op.attr("target_shape", None)
    if not shape:
        shape = ctx.get_input(op, "Y").shape
    ctx.set_output(op, "Out", jnp.broadcast_to(x, tuple(shape)))


# ---------------------------------------------------------------------------
# frobenius_norm
# ---------------------------------------------------------------------------
def _frob_infer(op, block):
    x = in_var(op, block, "X")
    dims = op.attr("dim", None)
    keep = op.attr("keep_dim", False)
    if op.attr("reduce_all", False) or not dims:
        dims = list(range(len(x.shape)))
    dims = [d % len(x.shape) for d in dims]
    if keep:
        shape = [1 if i in dims else s for i, s in enumerate(x.shape)]
    else:
        shape = [s for i, s in enumerate(x.shape) if i not in dims]
    set_out(op, block, "Out", shape, x.dtype)


@register_op("frobenius_norm", infer=_frob_infer)
def _frobenius_norm(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    dims = op.attr("dim", None)
    if op.attr("reduce_all", False) or not dims:
        dims = list(range(x.ndim))
    ctx.set_output(op, "Out", jnp.sqrt(
        (x.astype("float32") ** 2).sum(
            axis=tuple(d % x.ndim for d in dims),
            keepdims=bool(op.attr("keep_dim", False)))).astype(x.dtype))


# ---------------------------------------------------------------------------
# cross_entropy2 (hard label, keeps MatchX for the grad)
# ---------------------------------------------------------------------------
def _ce2_infer(op, block):
    x = in_var(op, block, "X")
    shape = list(x.shape[:-1]) + [1]
    set_out(op, block, "Y", shape, x.dtype)
    if op.output("MatchX"):
        set_out(op, block, "MatchX", shape, x.dtype)
    if op.output("XShape"):
        set_out(op, block, "XShape", [0] + list(x.shape), x.dtype)


@register_op("cross_entropy2", infer=_ce2_infer)
def _cross_entropy2(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    label = ctx.get_input(op, "Label")
    ignore = int(op.attr("ignore_index", -100))
    lab = label.reshape(label.shape[:x.ndim - 1]).astype("int32")
    safe = jnp.where(lab == ignore, 0, lab)
    match = jnp.take_along_axis(x, safe[..., None], axis=-1)
    tiny = jnp.asarray(np.finfo(np.float32).tiny, x.dtype)
    y = -jnp.log(jnp.maximum(match, tiny))
    valid = (lab != ignore)[..., None]
    ctx.set_output(op, "Y", jnp.where(valid, y, 0))
    ctx.set_output(op, "MatchX", match)
    if op.output("XShape"):
        ctx.set_output(op, "XShape", jnp.zeros((0,), x.dtype))


# ---------------------------------------------------------------------------
# where_index (nonzero) — static-shape convention
# ---------------------------------------------------------------------------
def _where_index_infer(op, block):
    x = in_var(op, block, "Condition")
    n = int(np.prod(x.shape)) if x.shape else 1
    set_out(op, block, "Out", (n, max(len(x.shape), 1)), "int64")


@register_op("where_index", infer=_where_index_infer, grad=None)
def _where_index(ctx, op):
    jnp = _jnp()
    cond = ctx.get_input(op, "Condition")
    flat = (cond != 0).reshape(-1)
    n = flat.shape[0]
    # stable order: true positions first, each in original order
    order = jnp.argsort(jnp.where(flat, 0, 1) * n + jnp.arange(n))
    count = flat.sum()
    coords = jnp.stack(
        jnp.unravel_index(order, cond.shape if cond.ndim else (1,)), 1)
    valid = (jnp.arange(n) < count)[:, None]
    ctx.set_output(op, "Out",
                   jnp.where(valid, coords, -1).astype("int64"))


# ---------------------------------------------------------------------------
# coalesce_tensor
# ---------------------------------------------------------------------------
def _coalesce_infer(op, block):
    def out_var(name):
        v = block._find_var_recursive(name)
        return v if v is not None else block.create_var(name=name)

    total = 0
    for name, src in zip(op.output("Output"), op.input("Input")):
        v = block.var(src)
        total += int(np.prod(v.shape)) if v.shape else 1
        out = out_var(name)
        out.shape, out.dtype = tuple(v.shape), v.dtype
    fused = out_var(op.output("FusedOutput")[0])
    fused.shape = (total,)
    fused.dtype = block.var(op.input("Input")[0]).dtype


@register_op("coalesce_tensor", infer=_coalesce_infer, grad=None)
def _coalesce_tensor(ctx, op):
    jnp = _jnp()
    ins = ctx.get_inputs(op, "Input")
    const = op.attr("set_constant", False)
    val = op.attr("constant", 0.0)
    outs = []
    for x in ins:
        outs.append(jnp.full_like(x, val) if const else x)
    ctx.set_outputs(op, "Output", outs)
    ctx.set_output(op, "FusedOutput",
                   jnp.concatenate([o.reshape(-1) for o in outs]))


# ---------------------------------------------------------------------------
# inplace_abn — activated batch norm (in-place is a no-op concept in XLA)
# ---------------------------------------------------------------------------
def _abn_infer(op, block):
    from .nn_ops import _bn_infer
    _bn_infer(op, block)


@register_op("inplace_abn", infer=_abn_infer)
def _inplace_abn(ctx, op):
    from .nn_ops import _bn_lower
    _bn_lower(ctx, op)
    act = op.attr("activation", "")
    if act:
        jnp = _jnp()
        y = ctx.get(op.output("Y")[0])
        if act == "relu":
            y = jnp.maximum(y, 0)
        elif act in ("leaky_relu", "leakyrelu"):
            alpha = op.attr("alpha", 0.01)
            y = jnp.where(y >= 0, y, alpha * y)
        elif act == "elu":
            alpha = op.attr("alpha", 1.0)
            y = jnp.where(y >= 0, y, alpha * (jnp.exp(y) - 1))
        else:
            raise NotImplementedError(f"inplace_abn activation {act!r}")
        ctx.set_output(op, "Y", y)


# ---------------------------------------------------------------------------
# sigmoid_focal_loss (reference sigmoid_focal_loss_op.cu:33)
# ---------------------------------------------------------------------------
def _sfl_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)


@register_op("sigmoid_focal_loss", infer=_sfl_infer)
def _sigmoid_focal_loss(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X").astype("float32")
    label = ctx.get_input(op, "Label").reshape(-1).astype("int32")
    fg = ctx.get_input(op, "FgNum").reshape(-1)[0]
    gamma = float(op.attr("gamma", 2.0))
    alpha = float(op.attr("alpha", 0.25))
    n, num_classes = x.shape
    d = jnp.arange(num_classes)[None, :]
    g = label[:, None]
    c_pos = (g == d + 1).astype("float32")
    c_neg = ((g != -1) & (g != d + 1)).astype("float32")
    fg_num = jnp.maximum(fg, 1).astype("float32")
    s_pos, s_neg = alpha / fg_num, (1.0 - alpha) / fg_num
    p = 1.0 / (1.0 + jnp.exp(-x))
    tiny = np.finfo(np.float32).tiny
    term_pos = (1.0 - p) ** gamma * jnp.log(jnp.maximum(p, tiny))
    # numerically-stable log(1-p) = -x*(x>=0) - log(1+exp(x-2x*(x>=0)))
    pos_mask = (x >= 0).astype("float32")
    log1mp = -x * pos_mask - jnp.log(1.0 + jnp.exp(x - 2.0 * x * pos_mask))
    term_neg = p ** gamma * log1mp
    out = -c_pos * term_pos * s_pos - c_neg * term_neg * s_neg
    ctx.set_output(op, "Out", out)


# ---------------------------------------------------------------------------
# shuffle_batch
# ---------------------------------------------------------------------------
def _shuffle_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)
    set_out(op, block, "ShuffleIdx", (x.shape[0],), "int64")
    if op.output("SeedOut"):
        set_out(op, block, "SeedOut", (1,), "int64")


@register_op("shuffle_batch", infer=_shuffle_infer)
def _shuffle_batch(ctx, op):
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    perm = jax.random.permutation(ctx.rng(op), x.shape[0])
    ctx.set_output(op, "Out", x[perm])
    ctx.set_output(op, "ShuffleIdx", perm.astype("int64"))
    if op.output("SeedOut"):
        ctx.set_output(op, "SeedOut",
                       jnp.zeros((1,), "int64"))


# ---------------------------------------------------------------------------
# sample_logits (sampled softmax; log-uniform with replacement)
# ---------------------------------------------------------------------------
def _sample_logits_infer(op, block):
    logits = in_var(op, block, "Logits")
    labels = in_var(op, block, "Labels")
    n, nt = labels.shape[0], labels.shape[1]
    s = int(op.attr("num_samples"))
    set_out(op, block, "Samples", (n, nt + s), "int64")
    set_out(op, block, "Probabilities", (n, nt + s), logits.dtype)
    set_out(op, block, "SampledLogits", (n, nt + s), logits.dtype)
    set_out(op, block, "SampledLabels", (n, nt), "int64")


@register_op("sample_logits", infer=_sample_logits_infer)
def _sample_logits(ctx, op):
    import jax
    jnp = _jnp()
    logits = ctx.get_input(op, "Logits")
    labels = ctx.get_input(op, "Labels").astype("int64")
    n, vocab = logits.shape
    nt = labels.shape[1]
    s = int(op.attr("num_samples"))

    if op.attr("use_customized_samples", False):
        samples_neg = ctx.get_input(op, "CustomizedSamples")
        probs_full = ctx.get_input(op, "CustomizedProbabilities")
        samples = samples_neg.astype("int64")
    else:
        # log-uniform inverse CDF: id = floor(exp(u*log(V+1))) - 1
        u = jax.random.uniform(ctx.rng(op), (n, s))
        neg = jnp.clip(
            jnp.exp(u * np.log(vocab + 1.0)) - 1.0, 0,
            vocab - 1).astype("int64")
        samples = jnp.concatenate([labels, neg], 1)
        # marginal log-uniform probability of each id
        ids = samples.astype("float32")
        probs_full = (jnp.log((ids + 2.0) / (ids + 1.0))
                      / np.log(vocab + 1.0))

    gathered = jnp.take_along_axis(logits, samples.astype("int32"), 1)
    sampled_logits = gathered - jnp.log(
        jnp.maximum(probs_full, np.finfo(np.float32).tiny))
    if op.attr("remove_accidental_hits", True):
        # a negative column that collides with any true label is masked
        neg_mask = jnp.concatenate(
            [jnp.zeros((n, nt), bool),
             (samples[:, nt:, None] == labels[:, None, :]).any(-1)], 1)
        sampled_logits = jnp.where(neg_mask,
                                   sampled_logits - 1e20, sampled_logits)
    ctx.set_output(op, "Samples", samples)
    ctx.set_output(op, "Probabilities",
                   probs_full.astype(logits.dtype))
    ctx.set_output(op, "SampledLogits",
                   sampled_logits.astype(logits.dtype))
    ctx.set_output(op, "SampledLabels",
                   jnp.tile(jnp.arange(nt, dtype="int64")[None, :],
                            (n, 1)))


# ---------------------------------------------------------------------------
# positive_negative_pair (query-grouped ranking pair counts)
# ---------------------------------------------------------------------------
def _pnp_infer(op, block):
    for slot in ("PositivePair", "NegativePair", "NeutralPair"):
        set_out(op, block, slot, (1,), "float32")


@register_op("positive_negative_pair", infer=_pnp_infer, grad=None)
def _positive_negative_pair(ctx, op):
    jnp = _jnp()
    score = ctx.get_input(op, "Score")
    label = ctx.get_input(op, "Label").reshape(-1)
    qid = ctx.get_input(op, "QueryID").reshape(-1)
    col = int(op.attr("column", -1))
    s = score[:, col].astype("float32")
    same_q = qid[:, None] == qid[None, :]
    # count each unordered pair once: i < j
    n = s.shape[0]
    upper = jnp.triu(jnp.ones((n, n), bool), 1)
    considered = same_q & upper & (label[:, None] != label[None, :])
    hi_first = jnp.where(label[:, None] > label[None, :],
                         s[:, None] - s[None, :],
                         s[None, :] - s[:, None])
    pos = (considered & (hi_first > 0)).sum()
    neg = (considered & (hi_first < 0)).sum()
    neu = (considered & (hi_first == 0)).sum()
    acc = [ctx.get_input(op, f"Accumulate{k}Pair")
           if op.input(f"Accumulate{k}Pair") else 0.0
           for k in ("Positive", "Negative", "Neutral")]
    ctx.set_output(op, "PositivePair",
                   (pos.astype("float32") + jnp.asarray(acc[0])).reshape(1))
    ctx.set_output(op, "NegativePair",
                   (neg.astype("float32") + jnp.asarray(acc[1])).reshape(1))
    ctx.set_output(op, "NeutralPair",
                   (neu.astype("float32") + jnp.asarray(acc[2])).reshape(1))


# ---------------------------------------------------------------------------
# hash (splitmix64 mix instead of XXH64 — same bucketing contract)
# ---------------------------------------------------------------------------
def _hash_infer(op, block):
    x = in_var(op, block, "X")
    n_hash = int(op.attr("num_hash", 1))
    set_out(op, block, "Out", (x.shape[0], n_hash, 1), "int64")


@register_op("hash", infer=_hash_infer, grad=None)
def _hash(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X").astype("uint32")
    n_hash = int(op.attr("num_hash", 1))
    mod_by = int(op.attr("mod_by", 100000000))
    # fold the feature dim into one key per row, then n_hash seeded mixes
    key = jnp.zeros((x.shape[0],), "uint32")
    for j in range(x.shape[1] if x.ndim > 1 else 1):
        col = x[:, j] if x.ndim > 1 else x
        key = key * jnp.uint32(1000003) + col
    outs = []
    for h in range(n_hash):
        z = key + jnp.uint32(0x9E3779B9) * jnp.uint32(h + 1)
        z = (z ^ (z >> 16)) * jnp.uint32(0x85EBCA6B)
        z = (z ^ (z >> 13)) * jnp.uint32(0xC2B2AE35)
        z = z ^ (z >> 16)
        outs.append(z.astype("int64") % mod_by)
    ctx.set_output(op, "Out", jnp.stack(outs, 1)[:, :, None])
