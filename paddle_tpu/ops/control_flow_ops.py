"""Control-flow ops.

Reference: paddle/fluid/operators/controlflow/ — conditional_block_op.cc
and while_op.cc run their sub-block with a *nested Executor* on a child
scope per iteration. TPU-native: the sub-block lowers into the SAME traced
computation under lax.cond / lax.while_loop — no nested interpreter, fixed
shapes, fully fused by XLA (the compiler-friendly control flow the MXU
needs).

Contract (matches the reference op defs):
  conditional_block: Cond (bool, scalar or [1]); attr sub_block (block
    idx); Out = vars the branch assigns that must be visible outside. The
    false path keeps each Out var's incoming value (it must already have
    one — same as the reference, where an unset conditional output is an
    error when read).
  while: Condition + X (loop carries); sub_block must re-assign Condition;
    Out = final carries.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from .registry import LowerContext, lower_op, register_op


def _sub_block(ctx: LowerContext, op):
    return ctx.block.program.block(op.attr("sub_block"))


def _external_reads(block, defined_outside) -> List[str]:
    """Names a sub-block reads before writing them (loop/branch inputs)."""
    written = set()
    reads: List[str] = []
    for o in block.ops:
        for n in o.input_arg_names():
            if n and n not in written and n not in reads:
                reads.append(n)
        for n in o.output_arg_names():
            written.add(n)
    return reads


def _lower_sub(ctx: LowerContext, block, env: Dict[str, object]):
    sub = LowerContext(block, env, base_key=ctx.base_key,
                       is_test=ctx.is_test, mesh=ctx.mesh, amp=ctx.amp)
    sub.axis_names = getattr(ctx, "axis_names", ())
    sub.ring_table = getattr(ctx, "ring_table", {})
    for o in block.ops:
        lower_op(sub, o)
    return env


def _cond_infer(op, block):
    # Out vars mirror their existing (outer) shapes; nothing to infer here —
    # the sub-block ops ran their own infer at append time.
    pass


@register_op("conditional_block", infer=_cond_infer, grad=None)
def _conditional_block(ctx, op):
    import jax
    import jax.numpy as jnp

    sub = _sub_block(ctx, op)
    out_names = [n for n in op.output("Out") if n]
    reads = [n for n in _external_reads(sub, None) if n in ctx.env]
    # carry = reads + current values of outs (for the unchanged branch)
    carry_names = list(dict.fromkeys(reads + out_names))
    for n in carry_names:
        if n not in ctx.env:
            raise KeyError(
                f"conditional_block: {n!r} has no value before the branch; "
                f"outputs must be initialized (reference semantics)")
    pred = ctx.get_input(op, "Cond")
    pred = jnp.reshape(pred, ()).astype(bool)

    def true_fn(carry):
        env = dict(zip(carry_names, carry))
        _lower_sub(ctx, sub, env)
        return tuple(env[n] for n in out_names)

    def false_fn(carry):
        env = dict(zip(carry_names, carry))
        return tuple(env[n] for n in out_names)

    carry = tuple(ctx.env[n] for n in carry_names)
    outs = jax.lax.cond(pred, true_fn, false_fn, carry)
    for n, v in zip(out_names, outs):
        ctx.env[n] = v


@register_op("cond2", infer=lambda op, block: None, grad=None)
def _cond2(ctx, op):
    """Two-branch functional conditional (layers.cond): one lax.cond.
    Branch side effects on outer vars are not propagated — only the
    declared branch outputs (reference cond has the same contract via
    select_input)."""
    import jax
    import jax.numpy as jnp

    tblk = ctx.block.program.block(op.attr("true_block"))
    fblk = ctx.block.program.block(op.attr("false_block"))
    t_outs = op.attr("true_outs")
    f_outs = op.attr("false_outs")
    out_names = [n for n in op.output("Out") if n]
    reads = [n for n in dict.fromkeys(_external_reads(tblk, None) +
                                      _external_reads(fblk, None))
             if n in ctx.env]
    cond_in = ctx.get_input(op, "Cond")
    if int(np.prod(jnp.shape(cond_in))) != 1:
        raise TypeError(
            f"cond: the condition must be a scalar (1-element) tensor, "
            f"got shape {tuple(jnp.shape(cond_in))} — reduce it first "
            "(e.g. reduce_any/reduce_all) or compare to a scalar")
    pred = jnp.reshape(cond_in, ()).astype(bool)

    def _branch(blk, outs):
        def fn(carry):
            env = dict(zip(reads, carry))
            _lower_sub(ctx, blk, env)
            return tuple(env[n] for n in outs)
        return fn

    carry = tuple(ctx.env[n] for n in reads)
    vals = jax.lax.cond(pred, _branch(tblk, t_outs),
                        _branch(fblk, f_outs), carry)
    for n, v in zip(out_names, vals):
        ctx.env[n] = v


@register_op("while", infer=lambda op, block: None, grad=None)
def _while(ctx, op):
    import jax
    import jax.numpy as jnp

    sub = _sub_block(ctx, op)
    cond_name = op.single_input("Condition")
    loop_names = [n for n in op.input("X") if n]
    out_names = [n for n in op.output("Out") if n] or loop_names
    reads = [n for n in _external_reads(sub, None) if n in ctx.env]
    carry_names = list(dict.fromkeys(loop_names + out_names + reads +
                                     [cond_name]))

    def cond_fn(carry):
        env = dict(zip(carry_names, carry))
        return jnp.reshape(env[cond_name], ()).astype(bool)

    def body_fn(carry):
        env = dict(zip(carry_names, carry))
        _lower_sub(ctx, sub, env)
        return tuple(env[n] for n in carry_names)

    carry = tuple(ctx.env[n] for n in carry_names)
    final = jax.lax.while_loop(cond_fn, body_fn, carry)
    env = dict(zip(carry_names, final))
    for n in carry_names:
        ctx.env[n] = env[n]


@register_op("increment", infer=lambda op, block: None, grad=None,
             stateful_outputs=("Out",))
def _increment(ctx, op):
    import jax.numpy as jnp
    x = ctx.get_input(op, "X")
    step = op.attr("step", 1.0)
    ctx.set_output(op, "Out", x + jnp.asarray(step, x.dtype))


def _run_program_infer(op, block):
    # out vars were shaped when the captured block's ops ran their infer
    pass


@register_op("run_program", infer=_run_program_infer, grad="auto")
def _run_program(ctx, op):
    """Execute a captured sub-program inline (reference run_program_op
    .cc — the dygraph-side container for to_static traces; there it
    spins a nested executor, here the sub-block lowers into the same
    traced computation and XLA fuses across the boundary)."""
    sub = _sub_block(ctx, op)
    env = dict()
    for n in op.input("X") + op.input("Params"):
        if n and n in ctx.env:
            env[n] = ctx.env[n]
    for n in _external_reads(sub, None):
        if n in ctx.env and n not in env:
            env[n] = ctx.env[n]
    _lower_sub(ctx, sub, env)
    for n in op.output("Out"):
        ctx.env[n] = env[n]
