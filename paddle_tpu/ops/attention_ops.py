"""Fused attention op.

Reference analog: operators/fused/multihead_matmul_op.cu (inference-only,
fixed layout). Here a first-class training op that picks the best TPU
execution per context:
  * `sp` mesh axis bound (shard_map)  -> ring attention over ICI
  * TPU backend                       -> pallas flash-attention kernel
  * CPU (tests/virtual mesh)          -> blockwise scan formulation
"""
from __future__ import annotations

from ..parallel.mesh import SP_AXIS
from .registry import in_var, register_op, set_out


def _attn_infer(op, block):
    q = in_var(op, block, "Q")
    set_out(op, block, "Out", q.shape, q.dtype)


@register_op("flash_attention", infer=_attn_infer, grad="auto")
def _flash_attention(ctx, op):
    import jax

    from .pallas.flash_attention import (blockwise_attention,
                                         flash_attention,
                                         flash_attention_bias)
    from ..parallel.ring import ring_attention, ulysses_attention

    q = ctx.get_input(op, "Q")
    k = ctx.get_input(op, "K")
    v = ctx.get_input(op, "V")
    bias = ctx.get_input(op, "Bias") if op.single_input("Bias") else None
    if bias is not None and bias.ndim != 2:
        # accept [B,1,1,S]-style additive masks; flatten to rows [B, S]
        bias = bias.reshape(bias.shape[0], bias.shape[-1])
    causal = op.attr("causal", False)
    sm_scale = op.attr("scale", None)
    mode = op.attr("seq_parallel_mode", "ring")

    if op.attr("impl", "auto") == "xla":
        if SP_AXIS in (getattr(ctx, "axis_names", ()) or ()):
            raise NotImplementedError(
                "flash_attention impl='xla' under sequence parallelism "
                "would attend over the local shard only; use impl='auto' "
                "(ring/Ulysses)")
        # einsum formulation: one op for the whole scores/softmax/PV
        # chain; layout "bshd" avoids materializing [B,h,S,d] transposes;
        # supports additive row bias, causal, and in-op probability
        # dropout (stateless key from the op's seed).  On v5e at S=128 it
        # measures within ~4% of the explicit-matmul build (763 vs 792
        # samples/s on the BERT bench) and well above the pallas kernel.
        import jax.numpy as jnp

        layout = op.attr("layout", "bhsd")
        d = q.shape[-1]
        scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
        eq = ("bqhd,bkhd->bhqk" if layout == "bshd"
              else "bhqd,bhkd->bhqk")
        s = jnp.einsum(eq, q, k) * scale
        if bias is not None:
            s = s + bias[:, None, None, :].astype(s.dtype)
        if causal:
            S = s.shape[-1]
            s = jnp.where(jnp.tril(jnp.ones((S, S), bool))[None, None],
                          s, jnp.asarray(-1e30, s.dtype))
        p = jax.nn.softmax(s, axis=-1)
        prob = op.attr("dropout_prob", 0.0)
        if prob and not (ctx.is_test or op.attr("is_test", False)):
            keep = jax.random.bernoulli(ctx.rng(op), 1.0 - prob, p.shape)
            p = jnp.where(keep, p / (1.0 - prob), 0.0).astype(p.dtype)
        eo = ("bhqk,bkhd->bqhd" if layout == "bshd"
              else "bhqk,bhkd->bhqd")
        out = jnp.einsum(eo, p, v)
        ctx.set_output(op, "Out", out)
        return

    axes = getattr(ctx, "axis_names", ()) or ()
    mesh = ctx.mesh
    multi_device = mesh is not None and mesh.devices.size > 1
    if SP_AXIS in axes:
        if bias is not None:
            raise NotImplementedError(
                "flash_attention: padding bias under sequence parallelism "
                "not supported yet — pad-free bucketing or causal only")
        fn = ring_attention if mode == "ring" else ulysses_attention
        out = fn(q, k, v, SP_AXIS, causal=causal, sm_scale=sm_scale)
    elif jax.default_backend() == "tpu" and not multi_device:
        if bias is not None:
            out = flash_attention_bias(q, k, v, bias, causal, sm_scale)
        else:
            out = flash_attention(q, k, v, causal, sm_scale)
    else:
        # multi-device GSPMD: the einsum formulation lets the partitioner
        # shard batch/head/seq dims freely (pallas_call pins the layout)
        out, _ = blockwise_attention(q, k, v, causal=causal,
                                     sm_scale=sm_scale, bias=bias)
    ctx.set_output(op, "Out", out)


def _attn_qkv_infer(op, block):
    qkv = in_var(op, block, "QKV")
    shape = list(qkv.shape)
    shape[-1] = shape[-1] // 3
    set_out(op, block, "Out", tuple(shape), qkv.dtype)


@register_op("flash_attention_qkv", infer=_attn_qkv_infer, grad="auto")
def _flash_attention_qkv(ctx, op):
    """Transpose-free fused attention on the packed QKV projection.

    QKV [B, S, 3H] -> Out [B, S, H].  On single-device TPU this lowers to
    the packed pallas kernels (ops/pallas/flash_attention.py:
    flash_attention_packed) whose grid reads 128-lane column chunks of
    the projection directly — none of the [B,S,3H] -> [3,B,h,S,d]
    transpose/slice traffic of the split-tensor path ever reaches HBM
    (measured ~2.4 GB/step of pure layout movement on the seq-512 BERT
    bench).  Elsewhere (CPU meshes, GSPMD) it lowers to an einsum
    formulation the partitioner can shard freely.

    Reference analog: operators/fused/multihead_matmul_op.cu takes the
    same packed [B, S, 3H] input (its "qkv weight" layout) — ours adds
    training (fwd+bwd) and long-sequence O(S) memory.
    """
    import jax
    import jax.numpy as jnp

    from .pallas.flash_attention import (flash_attention_packed,
                                         flash_attention_packed_bias)

    qkv = ctx.get_input(op, "QKV")
    bias = ctx.get_input(op, "Bias") if op.single_input("Bias") else None
    if bias is not None and bias.ndim != 2:
        bias = bias.reshape(bias.shape[0], bias.shape[-1])
    causal = op.attr("causal", False)
    sm_scale = op.attr("scale", None)
    nh = op.attr("num_heads")
    B, S, threeH = qkv.shape
    H = threeH // 3
    D = H // nh

    mesh = ctx.mesh
    multi_device = mesh is not None and mesh.devices.size > 1
    use_kernel = (jax.default_backend() == "tpu" and not multi_device
                  and H % 128 == 0 and D in (64, 128))
    if use_kernel:
        if bias is not None:
            out = flash_attention_packed_bias(qkv, bias, nh, causal,
                                              sm_scale)
        else:
            out = flash_attention_packed(qkv, nh, causal, sm_scale)
    else:
        # fallback (CPU / GSPMD meshes): blockwise online-softmax — keeps
        # O(S) attention memory so long-sequence mesh training doesn't
        # regress to an [B,h,S,S] materialization, and the einsum body is
        # layout-free for the partitioner
        from .pallas.flash_attention import blockwise_attention

        x = qkv.reshape(B, S, 3, nh, D)
        q = jnp.moveaxis(x[:, :, 0], 1, 2)               # [B,h,S,d]
        k = jnp.moveaxis(x[:, :, 1], 1, 2)
        v = jnp.moveaxis(x[:, :, 2], 1, 2)
        o, _ = blockwise_attention(q, k, v, causal=causal,
                                   sm_scale=sm_scale, bias=bias)
        out = jnp.moveaxis(o, 1, 2).reshape(B, S, H).astype(qkv.dtype)
    ctx.set_output(op, "Out", out)



# ---------------------------------------------------------------------------
# fused inference surfaces (reference operators/fused/) — on TPU these
# are plain compositions XLA fuses; the ops exist for API parity with
# the reference's pass-inserted fused kernels.
# ---------------------------------------------------------------------------
def _mm_infer(op, block):
    x = in_var(op, block, "Input")
    set_out(op, block, "Out", x.shape, x.dtype)


@register_op("multihead_matmul", infer=_mm_infer)
def _multihead_matmul(ctx, op):
    """Reference fused/multihead_matmul_op.cu: Input [B,S,D] projects to
    packed QKV via W [D,3,N,H] (+ Bias [3,N,H]), scaled dot-product
    attention with optional BiasQK added to the logits, heads merged
    back to [B,S,D]."""
    import jax
    import jax.numpy as jnp
    x = ctx.get_input(op, "Input")
    w = ctx.get_input(op, "W")
    bias = ctx.get_input(op, "Bias")
    n_head = int(op.attr("head_number"))
    alpha = float(op.attr("alpha", 1.0))
    B, S, D = x.shape
    H = D // n_head
    qkv = jnp.einsum("bsd,dknh->kbnsh", x.astype("float32"),
                     w.reshape(D, 3, n_head, H).astype("float32"))
    qkv = qkv + bias.reshape(3, 1, n_head, 1, H)
    q, k, v = qkv[0], qkv[1], qkv[2]
    logits = jnp.einsum("bnsh,bnth->bnst", q, k) * alpha
    if op.input("BiasQK"):
        logits = logits + ctx.get_input(op, "BiasQK").astype("float32")
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnst,bnth->bsnh", probs, v).reshape(B, S, D)
    ctx.set_output(op, "Out", out.astype(x.dtype))


def _skip_ln_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)


@register_op("skip_layernorm", infer=_skip_ln_infer)
def _skip_layernorm(ctx, op):
    """out = LayerNorm(X + Y) (reference fused/skip_layernorm_op.cc)."""
    import jax.numpy as jnp
    x = ctx.get_input(op, "X")
    y = ctx.get_input(op, "Y")
    scale = ctx.get_input(op, "Scale")
    bias = ctx.get_input(op, "Bias")
    eps = float(op.attr("epsilon", 1e-5))
    s = (x + y).astype("float32")
    mu = s.mean(-1, keepdims=True)
    var = ((s - mu) ** 2).mean(-1, keepdims=True)
    out = (s - mu) / jnp.sqrt(var + eps) * scale + bias
    ctx.set_output(op, "Out", out.astype(x.dtype))


def _feel_infer(op, block):
    ids0 = block.var(op.input("Ids")[0])
    emb0 = block.var(op.input("Embs")[0])
    set_out(op, block, "Out",
            (ids0.shape[0], ids0.shape[1], emb0.shape[1]), emb0.dtype)


@register_op("fused_embedding_eltwise_layernorm", infer=_feel_infer)
def _fused_embedding_eltwise_layernorm(ctx, op):
    """out = LayerNorm(sum_i Embs_i[Ids_i]) (reference
    fused/fused_embedding_eltwise_layernorm_op.cc)."""
    import jax.numpy as jnp
    ids = ctx.get_inputs(op, "Ids")
    embs = ctx.get_inputs(op, "Embs")
    scale = ctx.get_input(op, "Scale")
    bias = ctx.get_input(op, "Bias")
    eps = float(op.attr("epsilon", 1e-5))
    s = None
    for i, e in zip(ids, embs):
        idx = i.reshape(i.shape[:2]).astype("int32")
        g = e[idx].astype("float32")
        s = g if s is None else s + g
    mu = s.mean(-1, keepdims=True)
    var = ((s - mu) ** 2).mean(-1, keepdims=True)
    out = (s - mu) / jnp.sqrt(var + eps) * scale + bias
    ctx.set_output(op, "Out", out.astype(embs[0].dtype))
