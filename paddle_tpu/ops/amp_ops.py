"""AMP support ops.

Reference: paddle/fluid/operators/amp/ — check_finite_and_unscale_op
(gradient overflow detection + unscaling) and update_loss_scaling_op (the
dynamic loss-scale state machine: grow after incr_every_n_steps good
steps, shrink on decr_every_n_nan_or_inf bad ones).
"""
from __future__ import annotations

import numpy as np

from .registry import in_var, register_op, set_out


def _jnp():
    import jax.numpy as jnp
    return jnp


def _cfau_infer(op, block):
    for xn, on in zip(op.input("X"), op.output("Out")):
        xv = block.var(xn)
        ov = block.var(on)
        ov.shape, ov.dtype = xv.shape, xv.dtype
    fi = op.single_output("FoundInfinite")
    if fi:
        v = block.var(fi)
        v.shape, v.dtype = (1,), "bool"


@register_op("check_finite_and_unscale", infer=_cfau_infer, grad=None,
             stateful_outputs=("Out",))
def _check_finite_and_unscale(ctx, op):
    jnp = _jnp()
    scale = ctx.get_input(op, "Scale")
    found = jnp.zeros((1,), bool)
    outs = []
    for x in ctx.get_inputs(op, "X"):
        xf = x.astype("float32") / scale
        bad = ~jnp.all(jnp.isfinite(xf))
        found = found | bad
        outs.append(xf.astype(x.dtype))
    ctx.set_outputs(op, "Out", outs)
    ctx.set_output(op, "FoundInfinite", found)


def _uls_infer(op, block):
    for slot in ("Out",):
        for xn, on in zip(op.input("X"), op.output(slot)):
            xv, ov = block.var(xn), block.var(on)
            ov.shape, ov.dtype = xv.shape, xv.dtype
    for slot, dt in (("LossScaling", "float32"),
                     ("OutGoodSteps", "int32"), ("OutBadSteps", "int32")):
        n = op.single_output(slot)
        if n:
            v = block.var(n)
            v.shape, v.dtype = (1,), dt


@register_op("update_loss_scaling", infer=_uls_infer, grad=None,
             stateful_outputs=("Out", "LossScaling", "OutGoodSteps",
                               "OutBadSteps"))
def _update_loss_scaling(ctx, op):
    """reference update_loss_scaling_op.h UpdateLossScalingFunctor."""
    jnp = _jnp()
    found = ctx.get_input(op, "FoundInfinite").reshape(())
    scale = ctx.get_input(op, "PrevLossScaling")
    good = ctx.get_input(op, "InGoodSteps")
    bad = ctx.get_input(op, "InBadSteps")
    incr_n = op.attr("incr_every_n_steps", 1000)
    decr_n = op.attr("decr_every_n_nan_or_inf", 2)
    incr_ratio = op.attr("incr_ratio", 2.0)
    decr_ratio = op.attr("decr_ratio", 0.5)

    bad_n = jnp.where(found, bad + 1, jnp.zeros_like(bad))
    good_n = jnp.where(found, jnp.zeros_like(good), good + 1)
    shrink = bad_n >= decr_n
    grow = good_n >= incr_n
    new_scale = jnp.where(shrink, scale * decr_ratio,
                          jnp.where(grow, scale * incr_ratio, scale))
    new_scale = jnp.maximum(new_scale, 1e-8)
    bad_n = jnp.where(shrink, jnp.zeros_like(bad_n), bad_n)
    good_n = jnp.where(grow, jnp.zeros_like(good_n), good_n)

    # zero non-finite grads so the (unconditional) optimizer ops become
    # no-ops for this step (reference: conditional skip; see decorator.py)
    xs = ctx.get_inputs(op, "X")
    if op.attr("stop_update", False):
        outs = xs
    else:
        outs = [jnp.where(found, jnp.zeros_like(x), x) for x in xs]
    ctx.set_outputs(op, "Out", outs)
    ctx.set_output(op, "LossScaling", new_scale)
    ctx.set_output(op, "OutGoodSteps", good_n)
    ctx.set_output(op, "OutBadSteps", bad_n)
