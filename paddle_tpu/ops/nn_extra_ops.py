"""NN long-tail ops: 3D conv/pool, pads, lrn, data_norm, spectral_norm,
deformable conv, psroi_pool, and friends.

Reference analogs under paddle/fluid/operators/: conv_op.cc (3D),
conv_transpose_op.cc, pool_op.cc (3D), pad2d_op.cc, pad3d_op.cc,
lrn_op.cc, data_norm_op.cc, spectral_norm_op.cc, deformable_conv_op.cu,
deformable_psroi_pooling_op.cu, psroi_pool_op.cc, unpool_op.cc,
spp_op.cc, temporal_shift_op.cc, shuffle_channel_op.cc, row_conv_op.cc,
im2sequence_op.cc, bilinear_tensor_product_op.cc, fsp_op.cc,
partial_concat_op.cc, partial_sum_op.cc, gru_unit_op.cc,
lstm_unit_op.cc, segment_pool (incubate), metrics/auc_op.cc.
TPU-first: everything is a lax conv/reduce_window/gather formulation —
the reference's cuDNN descriptors and hand-rolled CUDA kernels
(deformable sampling loops, psroi bin loops) become batched bilinear
gathers the MXU/VPU consume directly.
"""
from __future__ import annotations

import numpy as np

from .registry import in_var, register_op, same_as_input, set_out


def _jnp():
    import jax.numpy as jnp
    return jnp


def _lax():
    import jax.lax as lax
    return lax


def _triple(v):
    return list(v) if isinstance(v, (list, tuple)) else [v] * 3


def _conv_out(i, k, p0, p1, s, d):
    return (i + p0 + p1 - (d * (k - 1) + 1)) // s + 1


# ---------------------------------------------------------------------------
# conv3d / conv3d_transpose / pool3d
# ---------------------------------------------------------------------------

def _conv3d_infer(op, block):
    x = in_var(op, block, "Input")             # [B, C, D, H, W]
    w = in_var(op, block, "Filter")            # [O, C/g, kd, kh, kw]
    s = _triple(op.attr("strides", [1, 1, 1]))
    p = _triple(op.attr("paddings", [0, 0, 0]))
    d = _triple(op.attr("dilations", [1, 1, 1]))
    out = [x.shape[0], w.shape[0]] + [
        _conv_out(x.shape[2 + i], w.shape[2 + i], p[i], p[i], s[i], d[i])
        for i in range(3)]
    set_out(op, block, "Output", out, x.dtype)


@register_op("conv3d", infer=_conv3d_infer)
def _conv3d(ctx, op):
    lax = _lax()
    x = ctx.get_input(op, "Input")
    w = ctx.get_input(op, "Filter")
    s = _triple(op.attr("strides", [1, 1, 1]))
    p = _triple(op.attr("paddings", [0, 0, 0]))
    d = _triple(op.attr("dilations", [1, 1, 1]))
    out = lax.conv_general_dilated(
        x, w, window_strides=s, padding=[(pi, pi) for pi in p],
        rhs_dilation=d, feature_group_count=op.attr("groups", 1),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    ctx.set_output(op, "Output", out)


def _conv3d_t_infer(op, block):
    x = in_var(op, block, "Input")
    w = in_var(op, block, "Filter")            # [C, O/g, kd, kh, kw]
    s = _triple(op.attr("strides", [1, 1, 1]))
    p = _triple(op.attr("paddings", [0, 0, 0]))
    g = op.attr("groups", 1)
    out = [x.shape[0], w.shape[1] * g] + [
        (x.shape[2 + i] - 1) * s[i] - 2 * p[i] + w.shape[2 + i]
        for i in range(3)]
    set_out(op, block, "Output", out, x.dtype)


@register_op("conv3d_transpose", infer=_conv3d_t_infer)
def _conv3d_transpose(ctx, op):
    lax = _lax()
    jnp = _jnp()
    x = ctx.get_input(op, "Input")
    w = ctx.get_input(op, "Filter")
    s = _triple(op.attr("strides", [1, 1, 1]))
    p = _triple(op.attr("paddings", [0, 0, 0]))
    k = w.shape[2:]
    g = int(op.attr("groups", 1))
    pads = [(k[i] - 1 - p[i], k[i] - 1 - p[i]) for i in range(3)]
    cin = w.shape[0]
    # IODHW -> OIDHW with group-major output channels, flipped spatial
    # (same formulation as the round-5 conv2d_transpose fix)
    wt = jnp.flip(w, axis=(2, 3, 4))
    wt = wt.reshape(g, cin // g, -1, *k)
    wt = wt.transpose(0, 2, 1, 3, 4, 5).reshape(-1, cin // g, *k)
    out = lax.conv_general_dilated(
        x, wt, window_strides=(1, 1, 1), padding=pads, lhs_dilation=s,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=g)
    ctx.set_output(op, "Output", out)


def _pool3d_infer(op, block):
    x = in_var(op, block, "X")
    if op.attr("global_pooling", False):
        set_out(op, block, "Out", list(x.shape[:2]) + [1, 1, 1], x.dtype)
        return
    k = _triple(op.attr("ksize"))
    s = _triple(op.attr("strides", [1, 1, 1]))
    p = _triple(op.attr("paddings", [0, 0, 0]))
    out = [x.shape[0], x.shape[1]] + [
        _conv_out(x.shape[2 + i], k[i], p[i], p[i], s[i], 1)
        for i in range(3)]
    set_out(op, block, "Out", out, x.dtype)


@register_op("pool3d", infer=_pool3d_infer)
def _pool3d(ctx, op):
    lax = _lax()
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    ptype = op.attr("pooling_type", "max")
    if op.attr("global_pooling", False):
        red = (jnp.max if ptype == "max" else jnp.mean)
        ctx.set_output(op, "Out", red(x, axis=(2, 3, 4), keepdims=True))
        return
    k = _triple(op.attr("ksize"))
    s = _triple(op.attr("strides", [1, 1, 1]))
    p = _triple(op.attr("paddings", [0, 0, 0]))
    dims = (1, 1) + tuple(k)
    strides = (1, 1) + tuple(s)
    pads = ((0, 0), (0, 0)) + tuple((pi, pi) for pi in p)
    if ptype == "max":
        out = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pads)
    else:
        out = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
        cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, dims,
                                strides, pads)
        out = out / (cnt if op.attr("exclusive", True)
                     else float(np.prod(k)))
    ctx.set_output(op, "Out", out)


# ---------------------------------------------------------------------------
# pad2d / pad3d
# ---------------------------------------------------------------------------

def _padnd_infer(op, block):
    x = in_var(op, block, "X")
    p = op.attr("paddings")
    shape = list(x.shape)
    nsp = len(p) // 2
    for i in range(nsp):
        # paddings are [d0_lo, d0_hi, d1_lo, d1_hi, ...] over spatial dims
        shape[2 + i] += p[2 * i] + p[2 * i + 1]
    set_out(op, block, "Out", shape, x.dtype)


def _pad_mode(x, pads, mode, value):
    jnp = _jnp()
    if mode == "constant":
        return jnp.pad(x, pads, constant_values=value)
    if mode == "reflect":
        return jnp.pad(x, pads, mode="reflect")
    if mode == "edge" or mode == "replicate":
        return jnp.pad(x, pads, mode="edge")
    if mode == "circular":
        return jnp.pad(x, pads, mode="wrap")
    raise ValueError(f"pad mode {mode!r}")


@register_op("pad2d", infer=_padnd_infer)
def _pad2d(ctx, op):
    x = ctx.get_input(op, "X")                 # NCHW
    p = op.attr("paddings")                    # [top, bottom, left, right]
    pads = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    ctx.set_output(op, "Out", _pad_mode(
        x, pads, op.attr("mode", "constant"),
        op.attr("pad_value", 0.0)))


@register_op("pad3d", infer=_padnd_infer)
def _pad3d(ctx, op):
    x = ctx.get_input(op, "X")                 # NCDHW
    p = op.attr("paddings")    # [front, back, top, bottom, left, right]
    pads = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3]), (p[4], p[5])]
    ctx.set_output(op, "Out", _pad_mode(
        x, pads, op.attr("mode", "constant"), op.attr("value", 0.0)))


# ---------------------------------------------------------------------------
# lrn / data_norm / spectral_norm
# ---------------------------------------------------------------------------

@register_op("lrn", infer=lambda op, block: (
    set_out(op, block, "Out", in_var(op, block, "X").shape,
            in_var(op, block, "X").dtype),
    set_out(op, block, "MidOut", in_var(op, block, "X").shape,
            in_var(op, block, "X").dtype)))
def _lrn(ctx, op):
    """Local response norm across channels (reference lrn_op.cc):
    mid = k + alpha * sum_{c-n/2..c+n/2} x^2; out = x / mid^beta."""
    lax = _lax()
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # NCHW
    n = op.attr("n", 5)
    k = op.attr("k", 2.0)
    alpha = op.attr("alpha", 1e-4)
    beta = op.attr("beta", 0.75)
    half = n // 2
    sq = x * x
    mid = k + alpha * lax.reduce_window(
        sq, 0.0, lax.add, (1, n, 1, 1), (1, 1, 1, 1),
        ((0, 0), (half, n - 1 - half), (0, 0), (0, 0)))
    ctx.set_output(op, "MidOut", mid)
    ctx.set_output(op, "Out", x / mid ** beta)


def _data_norm_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Y", x.shape, x.dtype)
    set_out(op, block, "Means", (x.shape[-1],), x.dtype)
    set_out(op, block, "Scales", (x.shape[-1],), x.dtype)


@register_op("data_norm", infer=_data_norm_infer)
def _data_norm(ctx, op):
    """reference data_norm_op.cc (CTR models): normalize by accumulated
    batch statistics carried as persistable BatchSize/BatchSum/
    BatchSquareSum tensors (the optimizer updates them via summary
    ops in the reference; here the stats are read-only inputs)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    bsz = ctx.get_input(op, "BatchSize")
    bsum = ctx.get_input(op, "BatchSum")
    bsq = ctx.get_input(op, "BatchSquareSum")
    mean = bsum / jnp.maximum(bsz, 1e-4)
    scale = jnp.sqrt(jnp.maximum(bsz, 1e-4)
                     / jnp.maximum(bsq - bsum * mean, 1e-4))
    ctx.set_output(op, "Means", mean)
    ctx.set_output(op, "Scales", scale)
    ctx.set_output(op, "Y", (x - mean) * scale)


@register_op("spectral_norm", infer=same_as_input("Weight", "Out"))
def _spectral_norm(ctx, op):
    """reference spectral_norm_op.cc: weight / sigma_max via power
    iteration on the [dim-first flattened] weight; U/V are persistable
    state fed in (updated by the layer's assign in the reference; we
    run power_iters fresh iterations from them, stop_gradient'd)."""
    import jax
    jnp = _jnp()
    w = ctx.get_input(op, "Weight")
    u = ctx.get_input(op, "U").reshape(-1)
    v = ctx.get_input(op, "V").reshape(-1)
    dim = op.attr("dim", 0)
    power_iters = op.attr("power_iters", 1)
    eps = op.attr("eps", 1e-12)
    perm = [dim] + [i for i in range(w.ndim) if i != dim]
    wm = jnp.transpose(w, perm).reshape(w.shape[dim], -1)   # [H, W]

    def norm(a):
        return a / (jnp.linalg.norm(a) + eps)

    for _ in range(max(1, power_iters)):
        v = norm(wm.T @ u)
        u = norm(wm @ v)
    u = jax.lax.stop_gradient(u)
    v = jax.lax.stop_gradient(v)
    sigma = u @ wm @ v
    ctx.set_output(op, "Out", w / sigma)


# ---------------------------------------------------------------------------
# shufflers / shifts / misc vision
# ---------------------------------------------------------------------------

@register_op("shuffle_channel", infer=same_as_input())
def _shuffle_channel(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # NCHW
    g = op.attr("group")
    B, C, H, W = x.shape
    out = x.reshape(B, g, C // g, H, W).swapaxes(1, 2).reshape(
        B, C, H, W)
    ctx.set_output(op, "Out", out)


@register_op("temporal_shift", infer=same_as_input())
def _temporal_shift(ctx, op):
    """reference temporal_shift_op.cc (TSM video models): shift 1/fold
    of channels one step back in time, 1/fold forward, rest stay."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # [N*T, C, H, W]
    seg_num = op.attr("seg_num")
    ratio = op.attr("shift_ratio", 0.25)
    NT, C, H, W = x.shape
    N = NT // seg_num
    xr = x.reshape(N, seg_num, C, H, W)
    c1 = int(C * ratio)
    c2 = int(C * 2 * ratio)
    pad_fwd = jnp.concatenate(
        [xr[:, 1:, :c1], jnp.zeros_like(xr[:, :1, :c1])], axis=1)
    pad_bwd = jnp.concatenate(
        [jnp.zeros_like(xr[:, :1, c1:c2]), xr[:, :-1, c1:c2]], axis=1)
    out = jnp.concatenate([pad_fwd, pad_bwd, xr[:, :, c2:]], axis=2)
    ctx.set_output(op, "Out", out.reshape(NT, C, H, W))


@register_op("row_conv", infer=same_as_input())
def _row_conv(ctx, op):
    """Lookahead row convolution (reference row_conv_op.cc, padded
    [B, T, D] convention): out[t] = sum_{j} x[t+j] * w[j]."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # [B, T, D]
    w = ctx.get_input(op, "Filter")            # [future_len, D]
    k = w.shape[0]
    B, T, D = x.shape
    xp = jnp.pad(x, [(0, 0), (0, k - 1), (0, 0)])
    out = sum(xp[:, j:j + T] * w[j] for j in range(k))
    ctx.set_output(op, "Out", out)


def _im2seq_infer(op, block):
    x = in_var(op, block, "X")                 # NCHW
    k = op.attr("kernels")
    s = op.attr("strides", [1, 1])
    p = op.attr("paddings", [0, 0, 0, 0])
    oh = (x.shape[2] + p[0] + p[2] - k[0]) // s[0] + 1
    ow = (x.shape[3] + p[1] + p[3] - k[1]) // s[1] + 1
    set_out(op, block, "Out",
            (x.shape[0], oh * ow, x.shape[1] * k[0] * k[1]), x.dtype)


@register_op("im2sequence", infer=_im2seq_infer)
def _im2sequence(ctx, op):
    """Patches -> sequence (reference im2sequence_op.cc), padded [B,
    oh*ow, C*kh*kw] instead of LoD rows."""
    lax = _lax()
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    k = op.attr("kernels")
    s = op.attr("strides", [1, 1])
    p = op.attr("paddings", [0, 0, 0, 0])
    B, C = x.shape[:2]
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=tuple(k), window_strides=tuple(s),
        padding=[(p[0], p[2]), (p[1], p[3])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))  # [B, C*kh*kw, oh, ow]
    Bp, CK, oh, ow = patches.shape
    out = patches.reshape(B, CK, oh * ow).swapaxes(1, 2)
    ctx.set_output(op, "Out", out)


@register_op("bilinear_tensor_product", infer=lambda op, block: set_out(
    op, block, "Out",
    (in_var(op, block, "X").shape[0],
     in_var(op, block, "Weight").shape[0]),
    in_var(op, block, "X").dtype))
def _bilinear_tensor_product(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # [B, M]
    y = ctx.get_input(op, "Y")                 # [B, N]
    w = ctx.get_input(op, "Weight")            # [S, M, N]
    out = jnp.einsum("bm,smn,bn->bs", x, w, y)
    if op.single_input("Bias"):
        out = out + ctx.get_input(op, "Bias")
    ctx.set_output(op, "Out", out)


@register_op("fsp", infer=lambda op, block: set_out(
    op, block, "Out",
    (in_var(op, block, "X").shape[0], in_var(op, block, "X").shape[1],
     in_var(op, block, "Y").shape[1]),
    in_var(op, block, "X").dtype))
def _fsp(ctx, op):
    """Flow-of-solution-procedure matrix (reference fsp_op.cc,
    distillation): Gram matrix between two feature maps."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # [B, C1, H, W]
    y = ctx.get_input(op, "Y")                 # [B, C2, H, W]
    h = x.shape[2] * x.shape[3]
    ctx.set_output(op, "Out",
                   jnp.einsum("bchw,bdhw->bcd", x, y) / h)


@register_op("partial_concat", infer=lambda op, block: set_out(
    op, block, "Out",
    (in_var(op, block, "X").shape[0],
     (op.attr("length", -1) if op.attr("length", -1) > 0
      else in_var(op, block, "X").shape[1] - op.attr("start_index", 0))
     * len(op.input("X"))),
    in_var(op, block, "X").dtype))
def _partial_concat(ctx, op):
    jnp = _jnp()
    xs = ctx.get_inputs(op, "X")
    start = op.attr("start_index", 0)
    length = op.attr("length", -1)
    end = None if length < 0 else start + length
    ctx.set_output(op, "Out",
                   jnp.concatenate([x[:, start:end] for x in xs], axis=1))


@register_op("partial_sum", infer=lambda op, block: set_out(
    op, block, "Out",
    (in_var(op, block, "X").shape[0],
     op.attr("length", -1) if op.attr("length", -1) > 0
     else in_var(op, block, "X").shape[1] - op.attr("start_index", 0)),
    in_var(op, block, "X").dtype))
def _partial_sum(ctx, op):
    xs = ctx.get_inputs(op, "X")
    start = op.attr("start_index", 0)
    length = op.attr("length", -1)
    end = None if length < 0 else start + length
    ctx.set_output(op, "Out", sum(x[:, start:end] for x in xs))


# ---------------------------------------------------------------------------
# roi family additions
# ---------------------------------------------------------------------------

def _psroi_infer(op, block):
    rois = in_var(op, block, "ROIs")
    oc = op.attr("output_channels")
    ph = op.attr("pooled_height")
    pw = op.attr("pooled_width")
    set_out(op, block, "Out", (rois.shape[0], oc, ph, pw),
            in_var(op, block, "X").dtype)


@register_op("psroi_pool", infer=_psroi_infer)
def _psroi_pool(ctx, op):
    """Position-sensitive ROI average pooling (reference
    psroi_pool_op.cc): output bin (c, i, j) averages input channel
    c*ph*pw + i*pw + j over the bin's region. The reference loops bins
    per ROI on GPU threads; here each bin gathers a fixed sample grid
    (bilinear-free integer coverage via rounded bin bounds is replaced
    by a dense sample average — fixed shapes, fully batched)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")                 # [B, C, H, W]
    rois = ctx.get_input(op, "ROIs")           # [R, 4] (x1,y1,x2,y2)
    batch_idx = (ctx.get_input(op, "RoisBatchIdx").reshape(-1).astype(
        "int32") if op.single_input("RoisBatchIdx")
        else jnp.zeros((rois.shape[0],), "int32"))
    scale = op.attr("spatial_scale", 1.0)
    oc = op.attr("output_channels")
    ph = op.attr("pooled_height")
    pw = op.attr("pooled_width")
    B, C, H, W = x.shape
    R = rois.shape[0]
    S = 4  # samples per bin side

    x1 = rois[:, 0] * scale
    y1 = rois[:, 1] * scale
    x2 = rois[:, 2] * scale
    y2 = rois[:, 3] * scale
    bin_h = (y2 - y1) / ph
    bin_w = (x2 - x1) / pw
    # sample grid per bin: [ph, S] fractional offsets
    off = (jnp.arange(S) + 0.5) / S
    ys = (y1[:, None, None]
          + (jnp.arange(ph)[None, :, None] + off[None, None, :])
          * bin_h[:, None, None])              # [R, ph, S]
    xs = (x1[:, None, None]
          + (jnp.arange(pw)[None, :, None] + off[None, None, :])
          * bin_w[:, None, None])              # [R, pw, S]
    yi = jnp.clip(ys, 0, H - 1).astype("int32")
    xi = jnp.clip(xs, 0, W - 1).astype("int32")
    # channel map for each output (c, i, j)
    cmap = (jnp.arange(oc)[:, None, None] * ph * pw
            + jnp.arange(ph)[None, :, None] * pw
            + jnp.arange(pw)[None, None, :])   # [oc, ph, pw]
    feat = x[batch_idx]                        # [R, C, H, W]
    # gather samples: out[r, c, i, j] = mean_{a,b} feat[r, cmap, yi, xi]
    samp = feat[jnp.arange(R)[:, None, None, None, None, None],
                cmap[None, :, :, :, None, None],
                yi[:, None, :, None, :, None],
                xi[:, None, None, :, None, :]]  # [R, oc, ph, pw, S, S]
    ctx.set_output(op, "Out", samp.mean(axis=(4, 5)))


def _deform_infer(op, block):
    x = in_var(op, block, "Input")
    w = in_var(op, block, "Filter")
    s = op.attr("strides", [1, 1])
    p = op.attr("paddings", [0, 0])
    d = op.attr("dilations", [1, 1])
    out = [x.shape[0], w.shape[0],
           _conv_out(x.shape[2], w.shape[2], p[0], p[0], s[0], d[0]),
           _conv_out(x.shape[3], w.shape[3], p[1], p[1], s[1], d[1])]
    set_out(op, block, "Output", out, x.dtype)


@register_op("deformable_conv", infer=_deform_infer)
def _deformable_conv(ctx, op):
    """Modulated deformable conv v2 (reference deformable_conv_op.cu —
    per-thread bilinear sampling loops). Here: build the full sampling
    grid [B, kh*kw, oh, ow] from offsets, bilinear-gather every tap,
    modulate by the mask, and contract taps x channels with one einsum
    on the MXU. v1 (deformable_conv_v1) is the same without the mask."""
    jnp = _jnp()
    x = ctx.get_input(op, "Input")             # [B, C, H, W]
    offset = ctx.get_input(op, "Offset")       # [B, 2*kh*kw, oh, ow]
    mask = ctx.get_input(op, "Mask") if op.single_input("Mask") else None
    w = ctx.get_input(op, "Filter")            # [O, C, kh, kw]
    s = op.attr("strides", [1, 1])
    p = op.attr("paddings", [0, 0])
    d = op.attr("dilations", [1, 1])
    B, C, H, W = x.shape
    O, _, kh, kw = w.shape
    oh = _conv_out(H, kh, p[0], p[0], s[0], d[0])
    ow = _conv_out(W, kw, p[1], p[1], s[1], d[1])
    K = kh * kw

    base_y = (jnp.arange(oh) * s[0] - p[0])[None, :, None]   # [1, oh, 1]
    base_x = (jnp.arange(ow) * s[1] - p[1])[None, None, :]
    ky = (jnp.arange(kh) * d[0]).repeat(kw).reshape(K, 1, 1)
    kx = jnp.tile(jnp.arange(kw) * d[1], kh).reshape(K, 1, 1)
    off = offset.reshape(B, K, 2, oh, ow)
    py = base_y + ky + off[:, :, 0]            # [B, K, oh, ow]
    px = base_x + kx + off[:, :, 1]

    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    wy = py - y0
    wx = px - x0

    def gather(yy, xx):
        yi = jnp.clip(yy, 0, H - 1).astype("int32")
        xi = jnp.clip(xx, 0, W - 1).astype("int32")
        valid = ((yy >= 0) & (yy <= H - 1) & (xx >= 0)
                 & (xx <= W - 1)).astype(x.dtype)
        g = x[jnp.arange(B)[:, None, None, None, None],
              jnp.arange(C)[None, :, None, None, None],
              yi[:, None], xi[:, None]]        # [B, C, K, oh, ow]
        return g * valid[:, None]

    v = (gather(y0, x0) * ((1 - wy) * (1 - wx))[:, None]
         + gather(y0, x0 + 1) * ((1 - wy) * wx)[:, None]
         + gather(y0 + 1, x0) * (wy * (1 - wx))[:, None]
         + gather(y0 + 1, x0 + 1) * (wy * wx)[:, None])
    if mask is not None:
        v = v * mask.reshape(B, 1, K, oh, ow)
    out = jnp.einsum("bckhw,ock->bohw",
                     v, w.reshape(O, C, K))
    ctx.set_output(op, "Output", out)


register_op("deformable_conv_v1", infer=_deform_infer,
            lower=_deformable_conv)


# ---------------------------------------------------------------------------
# segment pool / units
# ---------------------------------------------------------------------------

@register_op("segment_pool", infer=lambda op, block: (
    set_out(op, block, "Out",
            (op.attr("num_segments"),) + tuple(
                in_var(op, block, "X").shape[1:]),
            in_var(op, block, "X").dtype),
    set_out(op, block, "SummedIds", (op.attr("num_segments"), 1),
            in_var(op, block, "X").dtype)))
def _segment_pool(ctx, op):
    import jax
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    ids = ctx.get_input(op, "SegmentIds").reshape(-1).astype("int32")
    n = op.attr("num_segments")
    ptype = op.attr("pooltype", "SUM")
    counts = jax.ops.segment_sum(jnp.ones((x.shape[0],), x.dtype), ids, n)
    ctx.set_output(op, "SummedIds", counts[:, None])
    if ptype in ("SUM", "MEAN"):
        out = jax.ops.segment_sum(x, ids, n)
        if ptype == "MEAN":
            out = out / jnp.maximum(counts, 1.0).reshape(
                (-1,) + (1,) * (x.ndim - 1))
    elif ptype == "MAX":
        out = jax.ops.segment_max(x, ids, n)
        out = jnp.where(jnp.isfinite(out), out, 0.0)
    else:
        out = jax.ops.segment_min(x, ids, n)
        out = jnp.where(jnp.isfinite(out), out, 0.0)
    ctx.set_output(op, "Out", out)


def _gru_unit_infer(op, block):
    h = in_var(op, block, "HiddenPrev")
    set_out(op, block, "Gate", (h.shape[0], h.shape[1] * 3), h.dtype)
    set_out(op, block, "ResetHiddenPrev", h.shape, h.dtype)
    set_out(op, block, "Hidden", h.shape, h.dtype)


@register_op("gru_unit", infer=_gru_unit_infer)
def _gru_unit(ctx, op):
    """Single GRU step (reference gru_unit_op.cc). Input [B, 3H] is the
    precomputed x-projection; Weight [H, 3H] packs (update, reset) gates
    then the candidate projection."""
    import jax
    jnp = _jnp()
    xp = ctx.get_input(op, "Input")            # [B, 3H]
    h_prev = ctx.get_input(op, "HiddenPrev")   # [B, H]
    w = ctx.get_input(op, "Weight")            # [H, 3H]
    bias = ctx.get_input(op, "Bias") if op.single_input("Bias") else None
    H = h_prev.shape[1]
    if bias is not None:
        xp = xp + bias
    g_uh = h_prev @ w[:, :2 * H]
    u = jax.nn.sigmoid(xp[:, :H] + g_uh[:, :H])
    r = jax.nn.sigmoid(xp[:, H:2 * H] + g_uh[:, H:])
    rh = r * h_prev
    c = jnp.tanh(xp[:, 2 * H:] + rh @ w[:, 2 * H:])
    h = u * h_prev + (1 - u) * c
    ctx.set_output(op, "Gate",
                   jnp.concatenate([u, r, c], axis=1))
    ctx.set_output(op, "ResetHiddenPrev", rh)
    ctx.set_output(op, "Hidden", h)


def _lstm_unit_infer(op, block):
    c = in_var(op, block, "C_prev")
    set_out(op, block, "C", c.shape, c.dtype)
    set_out(op, block, "H", c.shape, c.dtype)


@register_op("lstm_unit", infer=_lstm_unit_infer)
def _lstm_unit(ctx, op):
    """Single LSTM step from the packed gate pre-activation
    (reference lstm_unit_op.cc): X [B, 4H] = (i, g, f, o)."""
    import jax
    jnp = _jnp()
    xg = ctx.get_input(op, "X")
    c_prev = ctx.get_input(op, "C_prev")
    H = c_prev.shape[1]
    fb = op.attr("forget_bias", 0.0)
    i = jax.nn.sigmoid(xg[:, :H])
    g = jnp.tanh(xg[:, H:2 * H])
    f = jax.nn.sigmoid(xg[:, 2 * H:3 * H] + fb)
    o = jax.nn.sigmoid(xg[:, 3 * H:])
    c = f * c_prev + i * g
    ctx.set_output(op, "C", c)
    ctx.set_output(op, "H", o * jnp.tanh(c))


# ---------------------------------------------------------------------------
# auc (stateful graph metric — reference metrics/auc_op.cc)
# ---------------------------------------------------------------------------

def _auc_infer(op, block):
    sp = in_var(op, block, "StatPos")
    set_out(op, block, "AUC", (), "float64")
    set_out(op, block, "StatPosOut", sp.shape, sp.dtype)
    set_out(op, block, "StatNegOut", sp.shape, sp.dtype)


@register_op("auc", infer=_auc_infer, grad=None,
             stateful_outputs=("StatPosOut", "StatNegOut"))
def _auc(ctx, op):
    """Streaming AUC (reference metrics/auc_op.cc): bucketed positive/
    negative counts accumulate across steps in persistable StatPos/
    StatNeg [num_thresholds+1] tensors; AUC is the trapezoid area over
    the bucket sweep."""
    jnp = _jnp()
    pred = ctx.get_input(op, "Predict")        # [B, 2] (prob of class 1)
    label = ctx.get_input(op, "Label").reshape(-1).astype("int32")
    stat_pos = ctx.get_input(op, "StatPos").astype("int64")
    stat_neg = ctx.get_input(op, "StatNeg").astype("int64")
    n_thresh = stat_pos.shape[0] - 1
    p1 = pred[:, -1]
    bucket = jnp.clip((p1 * n_thresh).astype("int32"), 0, n_thresh)
    pos_add = jnp.zeros_like(stat_pos).at[bucket].add(
        (label > 0).astype("int64"))
    neg_add = jnp.zeros_like(stat_neg).at[bucket].add(
        (label <= 0).astype("int64"))
    stat_pos = stat_pos + pos_add
    stat_neg = stat_neg + neg_add
    # sweep buckets high->low accumulating TP/FP; trapezoid area
    pos_flip = jnp.flip(stat_pos).astype("float64")
    neg_flip = jnp.flip(stat_neg).astype("float64")
    tp = jnp.cumsum(pos_flip)
    fp = jnp.cumsum(neg_flip)
    tp_prev = tp - pos_flip
    fp_prev = fp - neg_flip
    if op.attr("curve", "ROC") == "PR":
        # precision-recall area: x = recall = TP/P, y = precision
        p_total = jnp.maximum(tp[-1], 1.0)
        recall = tp / p_total
        recall_prev = tp_prev / p_total
        prec = tp / jnp.maximum(tp + fp, 1.0)
        prec_prev = tp_prev / jnp.maximum(tp_prev + fp_prev, 1.0)
        area = ((recall - recall_prev) * (prec + prec_prev) / 2.0).sum()
        auc = jnp.where(tp[-1] > 0, area, 0.0)
    else:
        area = ((fp - fp_prev) * (tp + tp_prev) / 2.0).sum()
        total = tp[-1] * fp[-1]
        auc = jnp.where(total > 0, area / jnp.maximum(total, 1.0), 0.0)
    ctx.set_output(op, "AUC", auc)
    ctx.set_output(op, "StatPosOut", stat_pos)
    ctx.set_output(op, "StatNegOut", stat_neg)
