"""Sequence/LoD machinery + SelectedRows/ids routing + select_input/output.

Reference analogs (paddle/fluid/operators/):
  sequence_ops/sequence_reshape_op.cc, sequence_ops/sequence_scatter_op
  .cc, lod_reset_op.cc, lod_tensor_to_array_op.cc,
  array_to_lod_tensor_op.cc, split_lod_tensor_op.cc,
  merge_lod_tensor_op.cc, shrink_rnn_memory_op.cc,
  merge_selected_rows_op.cc, split_selected_rows_op.cc,
  get_tensor_from_selected_rows_op.cc, distributed_ops/merge_ids_op.cc,
  distributed_ops/split_ids_op.cc, controlflow/select_input_output_op.cc.

TPU-first conventions (repo-wide, documented in README):
  * LoD tensors are padded [B, T, ...] + Lengths; ops that would change
    LoD emit the transformed padded tensor (and new lengths where the
    surface has a slot for them).
  * Ops whose reference output is data-dependently sized keep static
    shapes: routing ops (split_lod_tensor, split_ids, filter-style)
    zero/sentinel the non-selected slots instead of shrinking — the
    same convention as masked_select.
  * The tensor-array ops view a [B,T,...] batch time-major ([T,B,...]
    array items), replacing the reference's rank-table machinery.
"""
from __future__ import annotations

import numpy as np

from .registry import in_var, register_op, same_as_input, set_out


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# sequence_reshape / sequence_scatter
# ---------------------------------------------------------------------------
def _seq_reshape_infer(op, block):
    x = in_var(op, block, "X")          # [B, T, D]
    new_dim = int(op.attr("new_dim"))
    b, t, d = x.shape
    set_out(op, block, "Out", (b, t * d // new_dim, new_dim), x.dtype)
    if op.output("LengthsOut"):
        set_out(op, block, "LengthsOut", (b,), "int64")


@register_op("sequence_reshape", infer=_seq_reshape_infer)
def _sequence_reshape(ctx, op):
    """Each row's T_i*D payload re-chunked to new_dim columns
    (reference sequence_reshape_op.cc: out offset = offset*D/new_dim).
    Padded form: plain reshape + rescaled lengths (rows are
    left-justified so padding stays trailing)."""
    x = ctx.get_input(op, "X")
    new_dim = int(op.attr("new_dim"))
    b, t, d = x.shape
    ctx.set_output(op, "Out", x.reshape(b, t * d // new_dim, new_dim))
    if op.output("LengthsOut"):
        lengths = ctx.get_input(op, "Lengths")
        ctx.set_output(op, "LengthsOut",
                       (lengths * d) // new_dim)


def _seq_scatter_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)


@register_op("sequence_scatter", infer=_seq_scatter_infer)
def _sequence_scatter(ctx, op):
    """Out = X with Updates[b,t] added at (b, Ids[b,t]) for alive steps
    (reference sequence_scatter_op.cc over LoD rows)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    ids = ctx.get_input(op, "Ids").astype("int32")
    upd = ctx.get_input(op, "Updates")
    lengths = ctx.get_input(op, "Lengths")
    b, t = ids.shape[:2]
    alive = (jnp.arange(t)[None, :] < lengths[:, None])
    upd = jnp.where(alive, upd, 0)
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, t))
    ctx.set_output(op, "Out",
                   x.at[rows, ids].add(upd.astype(x.dtype)))


# ---------------------------------------------------------------------------
# lod_reset — data identity; lengths swap
# ---------------------------------------------------------------------------
def _lod_reset_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)
    if op.output("LengthsOut"):
        set_out(op, block, "LengthsOut", (x.shape[0],), "int64")


@register_op("lod_reset", infer=_lod_reset_infer)
def _lod_reset(ctx, op):
    """Reassign sequence structure (reference lod_reset_op.cc). Data
    passes through; the new lengths come from Y (if wired) or the
    target_lod attr converted to lengths."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    ctx.set_output(op, "Out", x)
    if op.output("LengthsOut"):
        if op.input("Y"):
            ctx.set_output(op, "LengthsOut",
                           ctx.get_input(op, "Y").astype("int64"))
        else:
            lod = list(op.attr("target_lod", []))
            lens = np.diff(np.asarray(lod, "int64"))
            ctx.set_output(op, "LengthsOut", jnp.asarray(lens))


# ---------------------------------------------------------------------------
# tensor-array bridges (time-major view of the padded batch)
# ---------------------------------------------------------------------------
def _l2a_infer(op, block):
    x = in_var(op, block, "X")          # [B, T, ...]
    set_out(op, block, "Out",
            (x.shape[1], x.shape[0]) + tuple(x.shape[2:]), x.dtype)


@register_op("lod_tensor_to_array", infer=_l2a_infer)
def _lod_tensor_to_array(ctx, op):
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    ctx.set_output(op, "Out", jnp.swapaxes(x, 0, 1))


def _a2l_infer(op, block):
    arr = in_var(op, block, "X")        # [T, B, ...]
    set_out(op, block, "Out",
            (arr.shape[1], arr.shape[0]) + tuple(arr.shape[2:]),
            arr.dtype)


@register_op("array_to_lod_tensor", infer=_a2l_infer)
def _array_to_lod_tensor(ctx, op):
    jnp = _jnp()
    arr = ctx.get_input(op, "X")
    ctx.set_output(op, "Out", jnp.swapaxes(arr, 0, 1))


def _split_lod_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "OutTrue", x.shape, x.dtype)
    set_out(op, block, "OutFalse", x.shape, x.dtype)


@register_op("split_lod_tensor", infer=_split_lod_infer)
def _split_lod_tensor(ctx, op):
    """Row routing by Mask (reference split_lod_tensor_op.cc). Static
    shapes: non-selected rows are zeroed, not removed."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    mask = ctx.get_input(op, "Mask").reshape(-1).astype(bool)
    shape = (-1,) + (1,) * (x.ndim - 1)
    m = mask.reshape(shape)
    ctx.set_output(op, "OutTrue", jnp.where(m, x, 0))
    ctx.set_output(op, "OutFalse", jnp.where(m, 0, x))


def _merge_lod_infer(op, block):
    x = in_var(op, block, "InTrue")
    set_out(op, block, "Out", x.shape, x.dtype)


@register_op("merge_lod_tensor", infer=_merge_lod_infer)
def _merge_lod_tensor(ctx, op):
    jnp = _jnp()
    t = ctx.get_input(op, "InTrue")
    f = ctx.get_input(op, "InFalse")
    mask = ctx.get_input(op, "Mask").reshape(-1).astype(bool)
    m = mask.reshape((-1,) + (1,) * (t.ndim - 1))
    ctx.set_output(op, "Out", jnp.where(m, t, f))


@register_op("shrink_rnn_memory", infer=same_as_input())
def _shrink_rnn_memory(ctx, op):
    """Keep state rows whose sequence is still alive at step I
    (reference shrink_rnn_memory_op.cc shrinks to the first K rows; the
    static-shape form zeroes dead rows instead)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    i = jnp.reshape(ctx.get_input(op, "I"), ()).astype("int32")
    lengths = ctx.get_input(op, "Lengths")
    alive = (i < lengths).reshape((-1,) + (1,) * (x.ndim - 1))
    ctx.set_output(op, "Out", jnp.where(alive, x, 0))


# ---------------------------------------------------------------------------
# SelectedRows utilities
# ---------------------------------------------------------------------------
def _sr_passthrough_infer(op, block):
    x = in_var(op, block, "X")
    set_out(op, block, "Out", x.shape, x.dtype)


@register_op("merge_selected_rows", infer=_sr_passthrough_infer,
             grad=None)
def _merge_selected_rows(ctx, op):
    """Deduplicate rows, summing values (reference math::scatter::
    MergeAdd via merge_selected_rows_op.cc)."""
    from ..framework.selected_rows import SelectedRowsValue, is_selected_rows
    x = ctx.get_input(op, "X")
    if is_selected_rows(x):
        ctx.set_output(op, "Out", x.merge())
    else:
        ctx.set_output(op, "Out", x)


@register_op("get_tensor_from_selected_rows",
             infer=_sr_passthrough_infer, grad=None)
def _get_tensor_from_selected_rows(ctx, op):
    from ..framework.selected_rows import is_selected_rows
    x = ctx.get_input(op, "X")
    ctx.set_output(op, "Out", x.values if is_selected_rows(x) else x)


def _split_sr_infer(op, block):
    x = in_var(op, block, "X")
    for name in op.output("Out"):
        v = block._find_var_recursive(name)
        if v is None:
            v = block.create_var(name=name)
        v.shape, v.dtype = x.shape, x.dtype


@register_op("split_selected_rows", infer=_split_sr_infer, grad=None)
def _split_selected_rows(ctx, op):
    """Split by height sections (reference split_selected_rows_op.cc).
    Static form: every shard keeps K slots; rows outside its section
    carry the empty sentinel (= height) with zeroed values."""
    jnp = _jnp()
    from ..framework.selected_rows import SelectedRowsValue, is_selected_rows
    x = ctx.get_input(op, "X")
    outs = op.output("Out")
    sections = op.attr("height_sections", None)
    if not sections:
        n = len(outs)
        base = x.height // n
        sections = [base + (1 if i < x.height % n else 0)
                    for i in range(n)]
    bounds = np.cumsum([0] + list(sections))
    vals = []
    for i in range(len(outs)):
        lo, hi = int(bounds[i]), int(bounds[i + 1])
        own = (x.rows >= lo) & (x.rows < hi)
        rows = jnp.where(own, x.rows - lo, sections[i])
        vshape = (-1,) + (1,) * (x.values.ndim - 1)
        v = jnp.where(own.reshape(vshape), x.values, 0)
        vals.append(SelectedRowsValue(rows.astype("int32"), v,
                                      int(sections[i])))
    ctx.set_outputs(op, "Out", vals)


# ---------------------------------------------------------------------------
# ids routing (PS sharding ops)
# ---------------------------------------------------------------------------
def _split_ids_infer(op, block):
    x = in_var(op, block, "Ids")
    for name in op.output("Out"):
        v = block._find_var_recursive(name)
        if v is None:
            v = block.create_var(name=name)
        v.shape, v.dtype = x.shape, x.dtype


@register_op("split_ids", infer=_split_ids_infer, grad=None)
def _split_ids(ctx, op):
    """Shard ids by id %% nshards (reference split_ids_op.cc). Static
    form: non-owned slots carry -1."""
    jnp = _jnp()
    ids = ctx.get_input(op, "Ids")
    outs = op.output("Out")
    n = len(outs)
    vals = [jnp.where(ids % n == k, ids, -1) for k in range(n)]
    ctx.set_outputs(op, "Out", vals)


def _merge_ids_infer(op, block):
    ids = in_var(op, block, "Ids")
    x0 = in_var(op, block, "X")
    set_out(op, block, "Out", (ids.shape[0], x0.shape[-1]), x0.dtype)


@register_op("merge_ids", infer=_merge_ids_infer, grad=None)
def _merge_ids(ctx, op):
    """Reassemble shard lookup results in original id order (reference
    distributed_ops/merge_ids_op.cc): for each queried id, take the
    value row whose shard id list matches (-1 slots never match)."""
    jnp = _jnp()
    ids = ctx.get_input(op, "Ids").reshape(-1)
    rows = [r.reshape(-1) for r in ctx.get_inputs(op, "Rows")]
    xs = ctx.get_inputs(op, "X")
    all_rows = jnp.concatenate(rows)
    all_vals = jnp.concatenate([x.reshape(x.shape[0], -1) for x in xs])
    # one-hot match (N_ids x N_rows) @ values — static-shape gather
    match = (ids[:, None] == all_rows[None, :]) & (all_rows[None, :] >= 0)
    first = (jnp.cumsum(match, 1) == 1) & match  # dedupe repeated rows
    out = first.astype(all_vals.dtype) @ all_vals
    ctx.set_output(op, "Out", out)


# ---------------------------------------------------------------------------
# select_input / select_output (controlflow/select_op family)
# ---------------------------------------------------------------------------
def _select_input_infer(op, block):
    x = block.var(op.input("X")[0])
    set_out(op, block, "Out", x.shape, x.dtype)


@register_op("select_input", infer=_select_input_infer)
def _select_input(ctx, op):
    import jax
    jnp = _jnp()
    xs = ctx.get_inputs(op, "X")
    mask = jnp.reshape(ctx.get_input(op, "Mask"), ()).astype("int32")
    out = xs[0]
    for i, x in enumerate(xs[1:], start=1):
        out = jnp.where(mask == i, x, out)
    ctx.set_output(op, "Out", out)


def _select_output_infer(op, block):
    x = in_var(op, block, "X")
    for name in op.output("Out"):
        v = block._find_var_recursive(name)
        if v is None:
            v = block.create_var(name=name)
        v.shape, v.dtype = x.shape, x.dtype


@register_op("select_output", infer=_select_output_infer)
def _select_output(ctx, op):
    """Route X to the Mask-selected output; the others carry zeros
    (static-shape form of controlflow/select_output)."""
    jnp = _jnp()
    x = ctx.get_input(op, "X")
    mask = jnp.reshape(ctx.get_input(op, "Mask"), ()).astype("int32")
    outs = [jnp.where(mask == i, x, jnp.zeros_like(x))
            for i in range(len(op.output("Out")))]
    ctx.set_outputs(op, "Out", outs)
