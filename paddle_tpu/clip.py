"""Gradient clipping (reference python/paddle/fluid/clip.py).

Clip strategies are applied by ``Optimizer.apply_gradients`` between
backward and the update ops (same seam as the reference's
``_append_clip_op`` / ``GradientClipBase._static_clip``).  The clip math is
graph ops, so it fuses into the one compiled XLA step; ByGlobalNorm's
norm-reduce + scale costs one fused reduction over the grads rather than
the reference's per-tensor kernel launches.

Dygraph mode clips eagerly on jax arrays (`_dygraph_clip`).
"""
from __future__ import annotations

from typing import List, Tuple

from .framework.core import OpRole, op_role_guard

__all__ = ["GradientClipBase", "GradientClipByValue", "GradientClipByNorm",
           "GradientClipByGlobalNorm", "set_gradient_clip", "ClipByValue",
           "ClipByNorm", "ClipByGlobalNorm"]


class GradientClipBase:
    def __call__(self, params_grads):
        from .framework.core import in_dygraph_mode
        if in_dygraph_mode():
            return self._dygraph_clip(params_grads)
        with op_role_guard(OpRole.Optimize):
            return self._static_clip(params_grads)

    def _static_clip(self, params_grads):
        raise NotImplementedError

    def _dygraph_clip(self, params_grads):
        raise NotImplementedError


class GradientClipByValue(GradientClipBase):
    """Clip each gradient elementwise into [min, max]
    (reference fluid/clip.py GradientClipByValue)."""

    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _static_clip(self, params_grads):
        from .layers import tensor as T
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "trainable", True):
                out.append((p, g))
                continue
            out.append((p, T.clip(g, self.min, self.max)))
        return out

    def _dygraph_clip(self, params_grads):
        import jax.numpy as jnp
        return [(p, None if g is None else jnp.clip(g, self.min, self.max))
                for p, g in params_grads]


class GradientClipByNorm(GradientClipBase):
    """Per-tensor L2-norm clip: g * clip_norm / max(norm(g), clip_norm)
    (reference fluid/clip.py GradientClipByNorm / clip_by_norm op)."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _static_clip(self, params_grads):
        from .layers import nn
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "trainable", True):
                out.append((p, g))
                continue
            out.append((p, nn.clip_by_norm(g, self.clip_norm)))
        return out

    def _dygraph_clip(self, params_grads):
        import jax.numpy as jnp
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(g * g))
            out.append((p, g * (self.clip_norm /
                                jnp.maximum(norm, self.clip_norm))))
        return out


class GradientClipByGlobalNorm(GradientClipBase):
    """Scale ALL gradients by clip_norm / max(global_norm, clip_norm)
    where global_norm = sqrt(sum_i ||g_i||^2)
    (reference fluid/clip.py:339 GradientClipByGlobalNorm)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _static_clip(self, params_grads):
        import os
        from .framework.layer_helper import LayerHelper
        from .layers import tensor as T
        grads = [g for p, g in params_grads
                 if g is not None and getattr(p, "trainable", True)]
        if not grads:
            return params_grads
        from .layers import nn
        if os.environ.get("PT_FUSED_GLOBAL_CLIP", "0") == "1":
            # single concat+vdot fusion (ops/math_ops.py global_norm_sq).
            # Measured SLOWER than per-grad on v5e BERT-base (1190 vs
            # 1205 samples/s, same-session A/B x2): the concat
            # materializes ~0.4 GB of gradient traffic, which costs more
            # than the ~200 small reduce fusions it replaces. Kept as an
            # opt-in for param-heavy models where launch overhead wins.
            helper = LayerHelper("global_norm")
            sq = helper.create_variable_for_type_inference("float32")
            helper.append_op("global_norm_sq",
                             inputs={"X": [g.name for g in grads]},
                             outputs={"Out": [sq.name]}, attrs={})
            helper_sqrt = nn.sqrt(sq)
        else:
            # per-grad square+reduce, summed (reference fluid/clip.py
            # formulation) — XLA pipelines the small reduces alongside
            # the backward matmuls, so no extra HBM pass is paid
            sq_sums = [T.reduce_sum(T.elementwise_mul(g, g))
                       for g in grads]
            helper_sqrt = nn.sqrt(T.sums(sq_sums))
        clip_var = T.fill_constant([1], "float32", self.clip_norm)
        scale_var = T.elementwise_div(
            clip_var, T.elementwise_max(helper_sqrt, clip_var))
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "trainable", True):
                out.append((p, g))
                continue
            out.append((p, T.elementwise_mul(g, scale_var)))
        return out

    def _dygraph_clip(self, params_grads):
        import jax.numpy as jnp
        sq = [jnp.sum(g * g) for _, g in params_grads if g is not None]
        if not sq:
            return params_grads
        gn = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        return [(p, None if g is None else g * scale)
                for p, g in params_grads]


def set_gradient_clip(clip, param_list=None, program=None):
    """Program-level default clip (reference fluid/clip.py set_gradient_clip);
    optimizers without an explicit grad_clip pick it up in
    apply_gradients."""
    from .framework.core import default_main_program
    if clip is not None and not isinstance(clip, GradientClipBase):
        raise TypeError("clip must be a GradientClipBase instance or None")
    program = program or default_main_program()
    program._gradient_clip = clip
    program._gradient_clip_params = (
        [p.name if hasattr(p, "name") else p for p in param_list]
        if param_list else None)


# reference exposes the strategies under both names
ClipByValue = GradientClipByValue
ClipByNorm = GradientClipByNorm
ClipByGlobalNorm = GradientClipByGlobalNorm
