"""paddle.amp 2.0 namespace (reference python/paddle/amp/__init__.py).

auto_cast is the dygraph autocast guard (dygraph/amp.py amp_guard);
GradScaler is the dynamic loss scaler; the static-graph decorator lives
in contrib.mixed_precision (also re-exported here as `decorate` when
used on an optimizer).
"""
from ..dygraph.amp import amp_guard as auto_cast  # noqa: F401
from ..dygraph.amp import GradScaler  # noqa: F401
from ..contrib.mixed_precision import decorate  # noqa: F401

__all__ = ["auto_cast", "GradScaler", "decorate"]
