"""Device-memory introspection and allocator configuration.

Reference analog: paddle/fluid/memory/allocation/allocator_facade.h:32
(AllocatorFacade + strategy selection), memory/allocation/
allocator_strategy.h:21 ({kNaiveBestFit, kAutoGrowth, kThreadLocal}),
and the STAT_ADD GPU-memory counters (platform/monitor.h:77,130).

On TPU the allocator itself belongs to PJRT/XLA: the runtime owns a BFC
arena per device and XLA's buffer assignment does the within-program
reuse the reference implements as ir memory_optimize passes.  What the
framework owes on top — and what this module provides — is

  * the *stats surface* the reference exposes through its monitor
    counters: live/peak bytes per device, pool reservation, and a
    framework-level peak tracker that can be reset between phases
    (`memory_stats`, `max_memory_allocated`, `reset_peak`);
  * the *strategy configuration* knob: PJRT's preallocation behaviour
    (arena vs on-demand) mirrors {kNaiveBestFit chunked growth vs
    kAutoGrowth}; it is env-driven and must be set before backend init,
    exactly like FLAGS_allocator_strategy must precede device init in
    the reference (`set_allocator_strategy`);
  * an allocation probe for tests and capacity planning
    (`device_memory_capacity`).

Stats come from PJRT's per-device allocator via
``jax.Device.memory_stats()`` when the backend provides it (TPU does;
CPU returns None — callers get zeros there, mirroring how the reference
reports 0 for platforms without the CUDA allocator compiled in).
"""
from __future__ import annotations

import os
from typing import Dict, Optional

__all__ = [
    "memory_stats",
    "memory_allocated",
    "max_memory_allocated",
    "memory_reserved",
    "device_memory_capacity",
    "reset_peak",
    "set_allocator_strategy",
    "get_allocator_strategy",
]

# reference memory/allocation/allocator_strategy.h:21; the backing
# flags (FLAGS_allocator_strategy, FLAGS_fraction_of_gpu_memory_to_use)
# are registered once in flags.py.
_STRATEGIES = ("naive_best_fit", "auto_growth", "thread_local")


def set_allocator_strategy(strategy: str,
                           memory_fraction: Optional[float] = None):
    """Configure the device allocator. Must run before first device use.

    naive_best_fit -> PJRT preallocates an arena of
    ``memory_fraction`` of HBM (XLA_PYTHON_CLIENT_PREALLOCATE=true);
    auto_growth / thread_local -> on-demand growth.  Mirrors
    FLAGS_allocator_strategy + FLAGS_fraction_of_gpu_memory_to_use
    (reference memory/allocation/allocator_facade.cc).
    """
    import jax

    if strategy not in _STRATEGIES:
        raise ValueError(
            f"unknown allocator strategy {strategy!r}; expected one of "
            f"{_STRATEGIES}")
    from . import flags as _flags

    _flags.set_flags({"FLAGS_allocator_strategy": strategy})
    if memory_fraction is not None:
        _flags.set_flags(
            {"FLAGS_fraction_of_gpu_memory_to_use": float(memory_fraction)})
    prealloc = strategy == "naive_best_fit"
    os.environ["XLA_PYTHON_CLIENT_PREALLOCATE"] = (
        "true" if prealloc else "false")
    if prealloc and memory_fraction is not None:
        os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] = str(memory_fraction)
    # if the backend is already initialized the env can no longer take
    # effect — surface that instead of silently configuring nothing
    # (reference enforces the same ordering via gflags-at-init).
    backends = getattr(getattr(jax._src, "xla_bridge", None),
                       "_backends", None)
    if backends:  # backend already up
        import warnings

        warnings.warn(
            "set_allocator_strategy called after device initialization; "
            "the strategy applies to the next process, not this one")


def get_allocator_strategy() -> str:
    from .flags import get_flags

    return get_flags(["FLAGS_allocator_strategy"])[
        "FLAGS_allocator_strategy"]


# framework-level peak tracking: PJRT's peak_bytes_in_use is
# process-lifetime; phase-scoped peaks (reference resets its STAT
# counters between epochs) need a local high-water mark.
_peak_baseline: Dict[int, int] = {}


def _raw_stats(device=None) -> Dict[str, int]:
    import jax

    dev = device if device is not None else jax.devices()[0]
    stats = None
    if hasattr(dev, "memory_stats"):
        try:
            stats = dev.memory_stats()
        except Exception:  # backend without allocator stats (CPU)
            stats = None
    return dict(stats or {})


def memory_stats(device=None) -> Dict[str, int]:
    """Full allocator stats for one device (bytes_in_use,
    peak_bytes_in_use, bytes_limit, num_allocs, ... as provided by
    PJRT). Empty dict on backends without stats (CPU)."""
    return _raw_stats(device)


def memory_allocated(device=None) -> int:
    """Live framework-visible bytes on the device (reference
    STAT gpu_mem counter, platform/monitor.h:130)."""
    return int(_raw_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """Peak bytes since process start or the last ``reset_peak``.

    PJRT's peak counter is process-monotonic; after a reset the window
    peak is the raw peak if it has grown past the reset snapshot, else
    the current live bytes (torch's reset_peak_memory_stats sets
    peak := current for the same reason)."""
    import jax

    dev = device if device is not None else jax.devices()[0]
    stats = _raw_stats(dev)
    peak = int(stats.get("peak_bytes_in_use", 0))
    live = int(stats.get("bytes_in_use", 0))
    baseline = _peak_baseline.get(dev.id)
    if baseline is None:
        return peak
    return peak if peak > baseline else live


def memory_reserved(device=None) -> int:
    """Bytes the allocator arena has reserved from the device
    (>= allocated under naive_best_fit preallocation)."""
    s = _raw_stats(device)
    return int(s.get("pool_bytes", s.get("bytes_reserved",
                                         s.get("bytes_in_use", 0))))


def device_memory_capacity(device=None) -> int:
    """Total HBM the allocator may use (bytes_limit)."""
    return int(_raw_stats(device).get("bytes_limit", 0))


def reset_peak(device=None):
    """Start a new peak-tracking window (reference resets its monitor
    STAT between profiling phases). PJRT's own peak counter is
    monotonic, so the framework keeps a baseline per device."""
    import jax

    dev = device if device is not None else jax.devices()[0]
    _peak_baseline[dev.id] = int(
        _raw_stats(dev).get("peak_bytes_in_use", 0))
