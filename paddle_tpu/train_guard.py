"""Robust training run-loop wrapper: non-finite skip-step, SIGTERM →
final checkpoint + clean exit, auto-resume from the newest valid
checkpoint.

Reference analog: the trainer failure-recovery contract around
auto-checkpoint + fleet elastic restart, plus ``FLAGS_check_nan_inf`` —
but where the reference's NaN gate is a debug mode that *aborts*, the
guard here is cheap enough to stay on in production: the executor
compiles the step so a non-finite loss selects the *old* state in-graph
(one extra scalar reduce; no host round-trip before the optimizer), so a
poisoned batch skips the update instead of corrupting the parameters.

Typical use::

    guard = TrainGuard(exe, loss, checkpoint_dir="ckpts",
                       interval_steps=500, keep_last_n=3)
    try:
        for batch in data:
            guard.step(batch, fetch_list=[loss])
    except TrainingInterrupted:
        pass   # SIGTERM: final checkpoint already written, exit 0

Telemetry (paddle_tpu/telemetry.py): a ``train_guard/resume`` span plus
``train_guard_resume_ms`` gauge time the construction-time restore,
``train_guard_restart_count`` gauge republishes
``PADDLE_TPU_RESTART_COUNT``, ``sigterm_to_exit_ms`` gauge records
SIGTERM-to-TrainingInterrupted latency, every step drives the periodic
exporter flush, and resume / guard-skip / SIGTERM / final-checkpoint
transitions land in the JSONL event log (events ``resume``,
``guard_skip``, ``sigterm``, ``final_checkpoint``).
"""
from __future__ import annotations

import logging
import os
import signal
import time
from typing import Callable, Optional

import numpy as np

from . import fault
from . import observatory
from . import telemetry
from .monitor import stat_add

__all__ = ["TrainGuard", "TrainingInterrupted"]

logger = logging.getLogger("paddle_tpu.train_guard")


class TrainingInterrupted(SystemExit):
    """Raised by TrainGuard.step after a SIGTERM once the final checkpoint
    is written.  Subclasses SystemExit with code 0, so an unhandled
    interrupt still exits the worker cleanly (no launcher restart)."""

    def __init__(self, step: int):
        super().__init__(0)
        self.step = step


def _poison_nonfinite(feed):
    """Injected 'loss: nan' fault: NaN out every float feed so the lowered
    loss goes non-finite in-graph (exercises the real skip-step path)."""
    out = {}
    for k, v in feed.items():
        arr = np.asarray(v)
        if np.issubdtype(arr.dtype, np.floating):
            arr = np.full_like(arr, np.nan)
        out[k] = arr
    return out


class TrainGuard:
    """Wraps an Executor's run loop with the fault-tolerance contract.

    * auto-resume: on construction, restore the newest *valid* checkpoint
      from `checkpoint_dir` (``resumed_step`` records it)
    * skip-step: compiles the step with the executor's non-finite guard on
      `loss`; skipped steps bump ``skipped_nonfinite_steps``, back off the
      AMP loss scale (``scaler.backoff_on_nonfinite``) and invoke
      `on_nonfinite(step)`
    * preemption: SIGTERM finishes the in-flight step, writes a final
      checkpoint, and raises :class:`TrainingInterrupted` (exit code 0)
    """

    def __init__(self, executor, loss, checkpoint_dir: Optional[str] = None,
                 program=None, interval_steps: int = 100,
                 keep_last_n: int = 3, scaler=None,
                 on_nonfinite: Optional[Callable[[int], None]] = None,
                 handle_sigterm: bool = True):
        from .framework.core import default_main_program

        self.exe = executor
        self.program = program or default_main_program()
        self.loss_name = loss if isinstance(loss, str) else loss.name
        self.scaler = scaler
        self.on_nonfinite = on_nonfinite
        self.skipped_steps = 0
        # dispatch-time watermark of the last scaler backoff: a skipped
        # step only compounds the backoff if it was DISPATCHED after the
        # previous backoff landed (i.e. it overflowed at the reduced
        # scale).  With the deferred guard, a whole batch of verdicts
        # from one overflow episode resolves at once — steps in flight
        # never saw the backoff, so they must not multiply it
        # (decr_ratio^interval would collapse the scale to ~0).
        self._backoff_watermark = -1
        self.resumed_step: Optional[int] = None
        self.stop_requested = False
        self._finalized = False
        self._ckpt_dir = checkpoint_dir
        self._keep_last_n = keep_last_n
        self._sigterm_at: Optional[float] = None
        restarts = int(os.environ.get("PADDLE_TPU_RESTART_COUNT", "0") or 0)
        telemetry.gauge_set("train_guard_restart_count", restarts)
        if checkpoint_dir:
            t0 = time.monotonic()
            with telemetry.trace_span("train_guard/resume",
                                      dir=checkpoint_dir):
                self.resumed_step = executor.enable_auto_checkpoint(
                    checkpoint_dir, interval_steps, program=self.program,
                    max_keep=keep_last_n)
            resume_ms = (time.monotonic() - t0) * 1e3
            telemetry.gauge_set("train_guard_resume_ms", resume_ms)
            telemetry.log_event("resume", step=self.resumed_step,
                                resume_ms=round(resume_ms, 3),
                                restart_count=restarts)
        executor.set_nonfinite_guard(self.loss_name,
                                     callback=self._skipped,
                                     program=self.program)
        self._sigterm_installed = False
        self._prev_handler = None
        if handle_sigterm:
            try:
                self._prev_handler = signal.signal(signal.SIGTERM,
                                                   self._on_sigterm)
                self._sigterm_installed = True
            except ValueError:
                # non-main thread can't install handlers; preemption then
                # falls back to the launcher's restart + auto-resume path
                stat_add("train_guard_no_sigterm")
        # device observatory: HBM timeline sampler for the run's
        # lifetime, and SIGUSR2 -> on-demand profiler capture
        # (FLAGS_profilez_sec seconds into FLAGS_metrics_dir/profiles,
        # without pausing the step loop)
        self._hbm_sampling = observatory.start_hbm_sampler()
        self._sigusr2_installed = False
        self._prev_usr2 = None
        if handle_sigterm and hasattr(signal, "SIGUSR2"):
            try:
                self._prev_usr2 = signal.signal(signal.SIGUSR2,
                                                self._on_sigusr2)
                self._sigusr2_installed = True
            except ValueError:
                # non-main thread: the SIGTERM try above already booked
                # train_guard_no_sigterm for this condition — captures
                # remain available via capture_profile()
                logger.debug("SIGUSR2 handler not installed "
                             "(non-main thread)")

    # -- run loop -----------------------------------------------------------
    def step(self, feed, fetch_list=None, scope=None):
        return self._step(feed, fetch_list, scope, run_async=False)

    def step_async(self, feed, fetch_list=None, scope=None):
        """Asynchronous flavor of :meth:`step`: returns the executor's
        :class:`AsyncRunResult` (lazy fetches + ``sync()`` fence) instead
        of blocking numpy arrays.  Skip-step protection is identical —
        the non-finite verdict stays on device and resolves lazily (fetch
        read / ``FLAGS_guard_resolve_interval`` / checkpoint / close),
        firing the scaler backoff with the original step id."""
        return self._step(feed, fetch_list, scope, run_async=True)

    def _step(self, feed, fetch_list, scope, run_async):
        if fault.fire("step") == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
        if fault.fire("loss") == "nan":
            feed = _poison_nonfinite(feed)
        # the guard keys on the block producing the loss, not on it being
        # fetched — the caller's fetch_list passes through untouched
        runner = self.exe.run_async if run_async else self.exe.run
        out = runner(self.program, feed=feed,
                     fetch_list=list(fetch_list or []) or None,
                     scope=scope)
        # periodic exporter flush rides the guarded loop even when the
        # caller bypasses Executor.run's epilogue (e.g. future runners)
        telemetry.maybe_flush()
        if self.stop_requested:
            self.finalize(scope=scope)
            exit_ms = None
            if self._sigterm_at is not None:
                exit_ms = (time.monotonic() - self._sigterm_at) * 1e3
                telemetry.gauge_set("sigterm_to_exit_ms", exit_ms)
            telemetry.log_event(
                "sigterm", step=self.exe._step,
                to_exit_ms=None if exit_ms is None else round(exit_ms, 3))
            telemetry.flush()
            raise TrainingInterrupted(self.exe._step)
        return out

    # -- callbacks ----------------------------------------------------------
    def _on_sigterm(self, signum, frame):
        self.stop_requested = True
        self._sigterm_at = time.monotonic()
        stat_add("sigterm_received")

    def _on_sigusr2(self, signum, frame):
        # a signal handler must not sleep for the capture window: the
        # capture runs on its own daemon thread while training continues
        self.capture_profile()

    def capture_profile(self, sec: Optional[float] = None):
        """Trigger an on-demand ``jax.profiler`` capture (default
        ``FLAGS_profilez_sec`` seconds) of the running training loop —
        the training analog of the serving ``GET /profilez``.  Returns
        the capture thread; the artifact lands under
        ``FLAGS_metrics_dir/profiles`` and is announced in the event
        log (``profile_capture``)."""
        telemetry.log_event("profile_capture_requested",
                            step=self.exe._step)
        return observatory.capture_profile_async(sec)

    def _skipped(self, step: int):
        # `step` is the ORIGINAL step id the verdict belongs to — with the
        # deferred guard, resolution may run many steps later
        self.skipped_steps += 1
        logger.warning("non-finite %r at step %d: update skipped",
                       self.loss_name, step)
        telemetry.log_event("guard_skip", step=step,
                            loss=self.loss_name,
                            resolved_at=self.exe._step)
        if self.scaler is not None and \
                hasattr(self.scaler, "backoff_on_nonfinite") and \
                step > self._backoff_watermark:
            # mark every step currently in flight as pre-backoff: their
            # verdicts belong to this same overflow episode
            self._backoff_watermark = self.exe._step
            self._backoff(step)
        if self.on_nonfinite is not None:
            self.on_nonfinite(step)

    def _backoff(self, step: int):
        import inspect
        try:
            params = inspect.signature(
                self.scaler.backoff_on_nonfinite).parameters
            takes_step = "step" in params
        except (TypeError, ValueError):
            takes_step = False  # builtins/C callables: play safe
        if takes_step:
            self.scaler.backoff_on_nonfinite(step=step)
        else:
            self.scaler.backoff_on_nonfinite()

    # -- shutdown -----------------------------------------------------------
    def finalize(self, scope=None):
        """Write the final checkpoint (best-effort: a dead store must not
        turn a clean preemption into a crash)."""
        if self._finalized:
            return
        self._finalized = True
        # end-of-run is a guard-resolution point: in-flight verdicts must
        # land (skip counters, scaler backoff) before the final snapshot
        if hasattr(self.exe, "resolve_nonfinite_guard"):
            self.exe.resolve_nonfinite_guard()
        if not self._ckpt_dir:
            return
        from . import checkpoint as ckpt
        try:
            ckpt.save_checkpoint(self._ckpt_dir, self.exe._step,
                                 program=self.program, scope=scope,
                                 keep_last_n=self._keep_last_n)
            stat_add("checkpoint_final")
            telemetry.log_event("final_checkpoint", step=self.exe._step,
                                dir=self._ckpt_dir)
        except OSError as e:
            stat_add("checkpoint_write_failures")
            logger.error("final checkpoint at step %d failed: %s",
                         self.exe._step, e)

    def close(self):
        """Undo everything the constructor installed on the executor."""
        if self._sigterm_installed:
            signal.signal(signal.SIGTERM,
                          self._prev_handler or signal.SIG_DFL)
            self._sigterm_installed = False
        if self._sigusr2_installed:
            signal.signal(signal.SIGUSR2,
                          self._prev_usr2 or signal.SIG_DFL)
            self._sigusr2_installed = False
        if self._hbm_sampling:
            self._hbm_sampling = False
            observatory.stop_hbm_sampler()
        self.exe.clear_nonfinite_guard()
        if self._ckpt_dir:
            self.exe.disable_auto_checkpoint()
        telemetry.flush()  # end-of-run exporter write (no-op without dir)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
