"""Robust training run-loop wrapper: non-finite skip-step, SIGTERM →
final checkpoint + clean exit, auto-resume from the newest valid
checkpoint.

Reference analog: the trainer failure-recovery contract around
auto-checkpoint + fleet elastic restart, plus ``FLAGS_check_nan_inf`` —
but where the reference's NaN gate is a debug mode that *aborts*, the
guard here is cheap enough to stay on in production: the executor
compiles the step so a non-finite loss selects the *old* state in-graph
(one extra scalar reduce; no host round-trip before the optimizer), so a
poisoned batch skips the update instead of corrupting the parameters.

Typical use::

    guard = TrainGuard(exe, loss, checkpoint_dir="ckpts",
                       interval_steps=500, keep_last_n=3)
    try:
        for batch in data:
            guard.step(batch, fetch_list=[loss])
    except TrainingInterrupted:
        pass   # SIGTERM: final checkpoint already written, exit 0
"""
from __future__ import annotations

import logging
import os
import signal
from typing import Callable, Optional

import numpy as np

from . import fault
from .monitor import stat_add

__all__ = ["TrainGuard", "TrainingInterrupted"]

logger = logging.getLogger("paddle_tpu.train_guard")


class TrainingInterrupted(SystemExit):
    """Raised by TrainGuard.step after a SIGTERM once the final checkpoint
    is written.  Subclasses SystemExit with code 0, so an unhandled
    interrupt still exits the worker cleanly (no launcher restart)."""

    def __init__(self, step: int):
        super().__init__(0)
        self.step = step


def _poison_nonfinite(feed):
    """Injected 'loss: nan' fault: NaN out every float feed so the lowered
    loss goes non-finite in-graph (exercises the real skip-step path)."""
    out = {}
    for k, v in feed.items():
        arr = np.asarray(v)
        if np.issubdtype(arr.dtype, np.floating):
            arr = np.full_like(arr, np.nan)
        out[k] = arr
    return out


class TrainGuard:
    """Wraps an Executor's run loop with the fault-tolerance contract.

    * auto-resume: on construction, restore the newest *valid* checkpoint
      from `checkpoint_dir` (``resumed_step`` records it)
    * skip-step: compiles the step with the executor's non-finite guard on
      `loss`; skipped steps bump ``skipped_nonfinite_steps``, back off the
      AMP loss scale (``scaler.backoff_on_nonfinite``) and invoke
      `on_nonfinite(step)`
    * preemption: SIGTERM finishes the in-flight step, writes a final
      checkpoint, and raises :class:`TrainingInterrupted` (exit code 0)
    """

    def __init__(self, executor, loss, checkpoint_dir: Optional[str] = None,
                 program=None, interval_steps: int = 100,
                 keep_last_n: int = 3, scaler=None,
                 on_nonfinite: Optional[Callable[[int], None]] = None,
                 handle_sigterm: bool = True):
        from .framework.core import default_main_program

        self.exe = executor
        self.program = program or default_main_program()
        self.loss_name = loss if isinstance(loss, str) else loss.name
        self.scaler = scaler
        self.on_nonfinite = on_nonfinite
        self.skipped_steps = 0
        # dispatch-time watermark of the last scaler backoff: a skipped
        # step only compounds the backoff if it was DISPATCHED after the
        # previous backoff landed (i.e. it overflowed at the reduced
        # scale).  With the deferred guard, a whole batch of verdicts
        # from one overflow episode resolves at once — steps in flight
        # never saw the backoff, so they must not multiply it
        # (decr_ratio^interval would collapse the scale to ~0).
        self._backoff_watermark = -1
        self.resumed_step: Optional[int] = None
        self.stop_requested = False
        self._finalized = False
        self._ckpt_dir = checkpoint_dir
        self._keep_last_n = keep_last_n
        if checkpoint_dir:
            self.resumed_step = executor.enable_auto_checkpoint(
                checkpoint_dir, interval_steps, program=self.program,
                max_keep=keep_last_n)
        executor.set_nonfinite_guard(self.loss_name,
                                     callback=self._skipped,
                                     program=self.program)
        self._sigterm_installed = False
        self._prev_handler = None
        if handle_sigterm:
            try:
                self._prev_handler = signal.signal(signal.SIGTERM,
                                                   self._on_sigterm)
                self._sigterm_installed = True
            except ValueError:
                # non-main thread can't install handlers; preemption then
                # falls back to the launcher's restart + auto-resume path
                stat_add("train_guard_no_sigterm")

    # -- run loop -----------------------------------------------------------
    def step(self, feed, fetch_list=None, scope=None):
        return self._step(feed, fetch_list, scope, run_async=False)

    def step_async(self, feed, fetch_list=None, scope=None):
        """Asynchronous flavor of :meth:`step`: returns the executor's
        :class:`AsyncRunResult` (lazy fetches + ``sync()`` fence) instead
        of blocking numpy arrays.  Skip-step protection is identical —
        the non-finite verdict stays on device and resolves lazily (fetch
        read / ``FLAGS_guard_resolve_interval`` / checkpoint / close),
        firing the scaler backoff with the original step id."""
        return self._step(feed, fetch_list, scope, run_async=True)

    def _step(self, feed, fetch_list, scope, run_async):
        if fault.fire("step") == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
        if fault.fire("loss") == "nan":
            feed = _poison_nonfinite(feed)
        # the guard keys on the block producing the loss, not on it being
        # fetched — the caller's fetch_list passes through untouched
        runner = self.exe.run_async if run_async else self.exe.run
        out = runner(self.program, feed=feed,
                     fetch_list=list(fetch_list or []) or None,
                     scope=scope)
        if self.stop_requested:
            self.finalize(scope=scope)
            raise TrainingInterrupted(self.exe._step)
        return out

    # -- callbacks ----------------------------------------------------------
    def _on_sigterm(self, signum, frame):
        self.stop_requested = True
        stat_add("sigterm_received")

    def _skipped(self, step: int):
        # `step` is the ORIGINAL step id the verdict belongs to — with the
        # deferred guard, resolution may run many steps later
        self.skipped_steps += 1
        logger.warning("non-finite %r at step %d: update skipped",
                       self.loss_name, step)
        if self.scaler is not None and \
                hasattr(self.scaler, "backoff_on_nonfinite") and \
                step > self._backoff_watermark:
            # mark every step currently in flight as pre-backoff: their
            # verdicts belong to this same overflow episode
            self._backoff_watermark = self.exe._step
            self._backoff(step)
        if self.on_nonfinite is not None:
            self.on_nonfinite(step)

    def _backoff(self, step: int):
        import inspect
        try:
            params = inspect.signature(
                self.scaler.backoff_on_nonfinite).parameters
            takes_step = "step" in params
        except (TypeError, ValueError):
            takes_step = False  # builtins/C callables: play safe
        if takes_step:
            self.scaler.backoff_on_nonfinite(step=step)
        else:
            self.scaler.backoff_on_nonfinite()

    # -- shutdown -----------------------------------------------------------
    def finalize(self, scope=None):
        """Write the final checkpoint (best-effort: a dead store must not
        turn a clean preemption into a crash)."""
        if self._finalized:
            return
        self._finalized = True
        # end-of-run is a guard-resolution point: in-flight verdicts must
        # land (skip counters, scaler backoff) before the final snapshot
        if hasattr(self.exe, "resolve_nonfinite_guard"):
            self.exe.resolve_nonfinite_guard()
        if not self._ckpt_dir:
            return
        from . import checkpoint as ckpt
        try:
            ckpt.save_checkpoint(self._ckpt_dir, self.exe._step,
                                 program=self.program, scope=scope,
                                 keep_last_n=self._keep_last_n)
            stat_add("checkpoint_final")
        except OSError as e:
            stat_add("checkpoint_write_failures")
            logger.error("final checkpoint at step %d failed: %s",
                         self.exe._step, e)

    def close(self):
        """Undo everything the constructor installed on the executor."""
        if self._sigterm_installed:
            signal.signal(signal.SIGTERM,
                          self._prev_handler or signal.SIG_DFL)
            self._sigterm_installed = False
        self.exe.clear_nonfinite_guard()
        if self._ckpt_dir:
            self.exe.disable_auto_checkpoint()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
