"""Runtime lock-order sanitizer (``FLAGS_debug_lock_order=1``).

The static lock-order pass (``tools/graftcheck``, rule ``lock-order``)
proves ordering over the acquisitions it can see; this module checks
the orders that actually *happen*.  While enabled, every
``threading.Lock()`` / ``threading.RLock()`` constructed (and every
``threading.Condition()``, whose default RLock comes from the patched
factory) returns a thin wrapper that:

* records, per thread, the stack of wrapped locks currently held;
* on each acquisition that nests inside another held lock, inserts an
  edge *held-site -> acquired-site* into a global acquisition-order
  graph keyed by **creation site** (``file:line`` of the ``Lock()``
  call), so an A→B in one thread and B→A in another are detected even
  across different *instances* of A and B.  Known limitation: two
  locks from the SAME creation site (two instances of one class)
  nesting in opposite orders are NOT flagged — same-site nesting is
  skipped because instance-ordered nesting (e.g. address-ordered)
  is a legitimate pattern the site key cannot distinguish; the
  static ``lock-order`` pass flags same-lock self-nesting instead;
* asserts the graph stays acyclic: an edge that closes a cycle is a
  **lock-order violation**, recorded in :func:`violations` and (by
  default) raised as :class:`LockOrderError` at the offending
  ``acquire`` — while the thread still holds the evidence;
* at release time, asserts the released lock is actually held: a
  plain ``Lock`` released by a different thread (the legal
  handoff/token pattern) unwinds the acquiring thread's entry, while
  a release no thread can account for is reported (once per creation
  site) — the "release side" assertion.

Overhead: one thread-local list append/remove per acquire/release
plus, on *nested* acquires only, a dict insert and a DFS over the
(site-keyed, therefore tiny) order graph.  Meant for tests and
debugging legs, not the serving hot path; with the flag off nothing
is patched and the cost is zero.

Usage::

    from paddle_tpu import locksan
    locksan.enable()            # or FLAGS_debug_lock_order=1 at import
    ... construct engines, run traffic ...
    assert locksan.violations() == []
    locksan.disable()

``enable()`` only wraps locks constructed *after* it; enable before
building the objects under test.  Locks created while enabled keep
working (as plain pass-throughs) after ``disable()``.
"""
from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["enable", "disable", "enabled", "violations",
           "clear_violations", "LockOrderError", "install_from_flag"]

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

# internal bookkeeping lock: a REAL lock (never wrapped, never part of
# the analyzed graph)
_meta = _REAL_LOCK()

_active = False
_raise_on_violation = True
_edges: Dict[str, Set[str]] = {}            # site -> sites acquired inside
_edge_site: Dict[Tuple[str, str], str] = {}  # edge -> "file:line" of acquire
_violations: List[str] = []
# per-thread held stacks, keyed by thread ident and guarded by _meta —
# global (not thread-local) so a legal cross-thread Lock.release()
# (handoff/token pattern) can unwind the ACQUIRING thread's entry
# instead of leaving a stale one that corrupts later order analysis
_held: Dict[int, list] = {}


class LockOrderError(AssertionError):
    """A lock acquisition closed a cycle in the observed order graph
    (or a wrapped lock was released by a thread not holding it)."""


def _held_stack() -> list:
    """This thread's held stack.  Caller must hold ``_meta``."""
    return _held.setdefault(threading.get_ident(), [])


def _caller_site() -> str:
    """file:line of the frame constructing the lock (first frame
    outside this module and threading.py).  Keeps the last two path
    components: a bare basename would merge e.g. every package's
    ``__init__.py:N`` into one graph node and manufacture false
    cycles."""
    for frame, lineno in traceback.walk_stack(None):
        fn = frame.f_code.co_filename
        if fn.endswith(("locksan.py", "threading.py")):
            continue
        short = "/".join(fn.replace("\\", "/").rsplit("/", 2)[-2:])
        return f"{short}:{lineno}"
    return "<unknown>"


def _reaches(src: str, dst: str) -> bool:
    seen: Set[str] = set()
    stack = [src]
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(_edges.get(n, ()))
    return False


# release-side misuse is reported once per creation site, not per
# occurrence (unbounded growth in a long-running replica otherwise)
_release_reported: Set[str] = set()


class _SanLock:
    """Order-recording wrapper around one real Lock/RLock.  Exposes
    the full lock protocol plus the private hooks
    ``threading.Condition`` delegates to (``_release_save`` /
    ``_acquire_restore`` / ``_is_owned``), so a Condition built on a
    wrapped lock keeps exact RLock semantics across ``wait()``."""

    __slots__ = ("_inner", "_site", "_reentrant")

    def __init__(self, inner, site: str, reentrant: bool):
        self._inner = inner
        self._site = site
        self._reentrant = reentrant

    # -- bookkeeping --------------------------------------------------------
    def _on_acquired(self) -> Optional[str]:
        """Returns a violation message when this acquisition closes a
        cycle (the caller un-acquires and raises); None when clean.

        The held-stack bookkeeping runs even while the sanitizer is
        disabled (wrapped locks outlive enable/disable cycles, and a
        lock acquired while disabled must still be release-matchable
        after a re-enable); only the order-graph analysis is gated."""
        with _meta:
            held = _held_stack()
            if not _active:
                held.append(self)
                return None
            msg = None
            if held and not (self._reentrant
                             and any(h is self for h in held)):
                top = held[-1]
                if top is not self and top._site != self._site:
                    a, b = top._site, self._site
                    new_edge = b not in _edges.get(a, ())
                    if new_edge and _reaches(b, a):
                        back = _edge_site.get(
                            (b, a), "via intermediate locks")
                        msg = (f"lock-order inversion: acquiring "
                               f"{b} while holding {a}, but the "
                               f"opposite order was observed "
                               f"({back}) — deadlock potential")
                        _violations.append(msg)
                    # the edge is recorded either way: a hot-path
                    # inversion in record mode must report ONCE, not
                    # append an identical violation per request
                    _edges.setdefault(a, set()).add(b)
                    _edge_site.setdefault((a, b), _caller_site())
            if msg is not None and _raise_on_violation:
                return msg
            held.append(self)
            return None

    def _on_released(self):
        with _meta:
            held = _held_stack()
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    if not held:  # don't accrete dead-thread entries
                        _held.pop(threading.get_ident(), None)
                    return
            # not held by THIS thread: a plain Lock may legally be
            # released by another thread (handoff pattern) — unwind
            # the acquirer's entry instead of flagging correct code
            if not self._reentrant:
                for stack in _held.values():
                    for i in range(len(stack) - 1, -1, -1):
                        if stack[i] is self:
                            del stack[i]
                            return
            if _active and self._site not in _release_reported:
                _release_reported.add(self._site)
                _violations.append(
                    f"lock {self._site} released by a thread that "
                    f"does not hold it")

    # -- lock protocol ------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            msg = self._on_acquired()
            if msg is not None:
                # a violating acquire FAILS: give the real lock back
                # so the raise leaves no lock silently held
                self._inner.release()
                raise LockOrderError(msg)
        return got

    def release(self):
        self._on_released()
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # -- Condition integration ---------------------------------------------
    def _release_save(self):
        # full release for Condition.wait(): drop every held entry of
        # self (RLock recursion depth included)
        with _meta:
            held = _held_stack()
            n_held = sum(1 for h in held if h is self)
            held[:] = [h for h in held if h is not self]
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        return (state, n_held)

    def _acquire_restore(self, saved):
        state, n_held = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        with _meta:
            _held_stack().extend([self] * max(1, n_held))

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        # plain Lock fallback mirroring threading.Condition's own
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _at_fork_reinit(self):
        # the stdlib (logging handlers, threading internals) calls
        # this in the forked child to unwedge locks held by threads
        # that did not survive the fork; the child is single-threaded
        # here, so mutating bookkeeping without _meta is safe
        if hasattr(self._inner, "_at_fork_reinit"):
            self._inner._at_fork_reinit()
        else:
            self._inner = (_REAL_RLOCK() if self._reentrant
                           else _REAL_LOCK())
        for stack in _held.values():
            stack[:] = [h for h in stack if h is not self]

    def __repr__(self):
        return f"<SanLock {self._site} {self._inner!r}>"


def _reinit_after_fork():
    """Forked child: only the forking thread survives — replace the
    bookkeeping lock (it may have been held at fork time) and drop
    every dead thread's held stack."""
    global _meta
    _meta = _REAL_LOCK()
    tid = threading.get_ident()
    for dead in [t for t in _held if t != tid]:
        del _held[dead]


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reinit_after_fork)


def _lock_factory():
    return _SanLock(_REAL_LOCK(), _caller_site(), reentrant=False)


def _rlock_factory():
    return _SanLock(_REAL_RLOCK(), _caller_site(), reentrant=True)


def enable(raise_on_violation: bool = True):
    """Patch ``threading.Lock``/``threading.RLock`` so every lock
    constructed from here on is order-sanitized.  Idempotent."""
    global _active, _raise_on_violation
    with _meta:
        _raise_on_violation = raise_on_violation
        _active = True
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory


def disable():
    """Restore the real factories and stop recording.  Wrapped locks
    already constructed keep working as pass-throughs."""
    global _active
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    with _meta:
        _active = False


def enabled() -> bool:
    return _active


def violations() -> List[str]:
    with _meta:
        return list(_violations)


def clear_violations():
    """Reset recorded violations AND the observed-order graph (a new
    test leg starts from a clean slate)."""
    with _meta:
        _violations.clear()
        _edges.clear()
        _edge_site.clear()
        _release_reported.clear()


def install_from_flag():
    """Called at ``paddle_tpu`` import: enables the sanitizer when the
    ``FLAGS_debug_lock_order`` env/flag is set, so subprocess replicas
    and test legs opt in without code changes.  Never raises by
    default in flag mode — violations are recorded for the harness to
    assert on (a serving replica should degrade loudly, not crash on
    the recording thread)."""
    from .flags import flag_value

    if flag_value("FLAGS_debug_lock_order"):
        enable(raise_on_violation=False)
