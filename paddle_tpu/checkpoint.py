"""Crash-safe sharded / async checkpointing (orbax-backed).

Reference analog: auto-checkpoint + save_persistables (SURVEY.md §5
checkpoint/resume). On TPU the state is a pytree of (possibly sharded)
jax.Arrays; orbax writes each shard from its owning host and restores
with the target sharding — the reference's per-var save ops can't express
that.

Write protocol (crash-safe)::

    <dir>/.tmp-<step>-<pid>-<attempt>/   serialize payload here
        orbax/... | state.pkl
        MANIFEST.json                    per-file size + sha256, written last
    os.replace(tmp, <dir>/<step>)        atomic publish

A crash at any point leaves either an orphaned ``.tmp-*`` (reaped by
:func:`gc_checkpoints`) or a fully published checkpoint.  Readers verify
the manifest (existence + size + checksum) and fall back to the newest
*valid* checkpoint instead of dying on — or half-restoring from — a torn
one.  Transient write errors retry with exponential backoff
(``FLAGS_checkpoint_retries`` / ``FLAGS_checkpoint_retry_backoff_s``).

Observability (monitor stats): ``checkpoint_writes``,
``checkpoint_retries``, ``checkpoint_fallback`` (orbax → pickle),
``checkpoint_corrupt_skipped``, ``checkpoint_resumes``,
``checkpoints_gc``, ``checkpoint_tmp_cleaned``,
``checkpoint_bytes_written`` (payload bytes per published checkpoint,
cumulative).  Telemetry (paddle_tpu/telemetry.py): ``ckpt/write`` /
``ckpt/publish`` / ``ckpt/gc`` / ``ckpt/restore`` spans, a
``checkpoint_write_ms`` duration histogram, and ``ckpt_publish`` /
``ckpt_resume`` JSONL events.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import fault
from . import telemetry
from .flags import flag_value
from .monitor import stat_add

logger = logging.getLogger("paddle_tpu.checkpoint")

MANIFEST = "MANIFEST.json"
_TMP_PREFIX = ".tmp-"


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed manifest/checksum validation or deserialization."""


def _persistable_state(program, scope) -> Dict[str, object]:
    state = {}
    for v in program.list_vars():
        if v.persistable:
            val = scope.find_var(v.name)
            if val is not None:
                state[v.name] = val
    return state


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------

def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_manifest(ckpt_dir: str, step: int, fmt: str) -> dict:
    files = {}
    for root, _dirs, names in os.walk(ckpt_dir):
        for name in names:
            if name == MANIFEST:
                continue
            p = os.path.join(root, name)
            files[os.path.relpath(p, ckpt_dir)] = {
                "bytes": os.path.getsize(p), "sha256": _sha256(p)}
    manifest = {"step": int(step), "format": fmt, "files": files,
                "time": time.time()}
    mpath = os.path.join(ckpt_dir, MANIFEST)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    return manifest


def verify_checkpoint(directory: str, step: int, deep: bool = True) -> dict:
    """Validate one checkpoint; returns its manifest ({} for legacy
    single-file pickles, which are only verifiable by unpickling).
    deep=False skips the sha256 re-hash (manifest + existence + sizes
    only) — enough for retention/discovery without re-reading gigabytes.
    Raises CheckpointCorrupt / FileNotFoundError."""
    path, kind = _checkpoint_path(directory, step)
    if kind in ("pkl-legacy", "orbax-legacy"):
        return {}
    mpath = os.path.join(path, MANIFEST)
    if not os.path.isfile(mpath):
        raise CheckpointCorrupt(f"{path}: missing {MANIFEST}")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorrupt(f"{mpath}: unreadable manifest: {e}") from e
    for rel, meta in manifest.get("files", {}).items():
        fp = os.path.join(path, rel)
        if not os.path.isfile(fp):
            raise CheckpointCorrupt(f"{path}: missing payload file {rel}")
        if os.path.getsize(fp) != meta["bytes"]:
            raise CheckpointCorrupt(
                f"{path}: torn write in {rel} "
                f"({os.path.getsize(fp)} != {meta['bytes']} bytes)")
        if deep and _sha256(fp) != meta["sha256"]:
            raise CheckpointCorrupt(f"{path}: checksum mismatch in {rel}")
    return manifest


def validate_checkpoint(directory: str, step: int,
                        deep: bool = True) -> bool:
    try:
        verify_checkpoint(directory, step, deep=deep)
        return True
    except (CheckpointCorrupt, FileNotFoundError):
        return False


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------

def save_checkpoint(directory: str, step: int, program=None, scope=None,
                    extra_state: Optional[dict] = None,
                    use_orbax: bool = True,
                    keep_last_n: Optional[int] = None) -> str:
    """Save all persistable vars (+ extra_state) under directory/step,
    atomically, with retry-with-backoff on I/O errors; optionally GC down
    to the newest `keep_last_n` valid checkpoints afterwards."""
    from .framework.core import default_main_program
    from .framework.executor import global_scope

    program = program or default_main_program()
    scope = scope or global_scope()
    state = _persistable_state(program, scope)
    if extra_state:
        state = dict(state, **{f"__extra__{k}": v
                               for k, v in extra_state.items()})
    arrays = {k: np.asarray(v) for k, v in state.items()}

    os.makedirs(directory, exist_ok=True)
    final = os.path.join(directory, str(step))
    retries = int(flag_value("FLAGS_checkpoint_retries") or 0)
    backoff = float(flag_value("FLAGS_checkpoint_retry_backoff_s") or 0)
    last_err: Optional[OSError] = None
    with telemetry.trace_span("ckpt/write", step=int(step)), \
            telemetry.timer("checkpoint_write_ms"):
        for attempt in range(retries + 1):
            if attempt:
                stat_add("checkpoint_retries")
                time.sleep(backoff * (2 ** (attempt - 1)))
            tmp = os.path.join(
                directory, f"{_TMP_PREFIX}{step}-{os.getpid()}-{attempt}")
            try:
                manifest = _write_once(tmp, final, arrays, step, use_orbax)
                stat_add("checkpoint_writes")
                break
            except OSError as e:
                last_err = e
                logger.warning("checkpoint write for step %s failed "
                               "(attempt %d/%d): %s",
                               step, attempt + 1, retries + 1, e)
                shutil.rmtree(tmp, ignore_errors=True)
        else:
            raise last_err
    nbytes = sum(f["bytes"] for f in manifest.get("files", {}).values())
    stat_add("checkpoint_bytes_written", nbytes)
    telemetry.log_event("ckpt_publish", step=int(step), bytes=nbytes,
                        format=manifest.get("format"), attempts=attempt + 1)
    if keep_last_n:
        gc_checkpoints(directory, keep_last_n)
    return final


def _write_once(tmp: str, final: str, arrays: Dict[str, np.ndarray],
                step: int, use_orbax: bool) -> dict:
    kind = fault.fire("ckpt_write")
    if kind == "raise":
        raise fault.InjectedFault(
            f"injected checkpoint write failure (step {step})")
    os.makedirs(tmp, exist_ok=True)
    fmt = "pkl"
    if use_orbax:
        try:
            import orbax.checkpoint as ocp
            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(os.path.abspath(os.path.join(tmp, "orbax")),
                       arrays, force=True)
            fmt = "orbax"
        except Exception as e:
            stat_add("checkpoint_fallback")
            logger.warning("orbax save failed (%s: %s); falling back to "
                           "pickle", type(e).__name__, e)
    if fmt == "pkl":
        import pickle
        with open(os.path.join(tmp, "state.pkl"), "wb") as f:
            pickle.dump(arrays, f, protocol=2)
            f.flush()
            os.fsync(f.fileno())
    manifest = _write_manifest(tmp, step, fmt)
    with telemetry.trace_span("ckpt/publish", step=int(step)):
        if os.path.isdir(final):
            shutil.rmtree(final)
        elif os.path.exists(final):
            os.remove(final)
        os.replace(tmp, final)
    if kind in ("torn", "partial"):
        # simulate storage failure after publish: data never hit the disk
        _inject_corruption(final, kind)
    return manifest


def _inject_corruption(path: str, kind: str):
    if kind == "partial":
        os.remove(os.path.join(path, MANIFEST))
        return
    target, size = None, -1  # torn: truncate the largest payload file
    for root, _dirs, names in os.walk(path):
        for n in names:
            if n == MANIFEST:
                continue
            p = os.path.join(root, n)
            s = os.path.getsize(p)
            if s > size:
                target, size = p, s
    if target is not None:
        with open(target, "r+b") as f:
            f.truncate(max(1, size // 2))


# ---------------------------------------------------------------------------
# discovery / load
# ---------------------------------------------------------------------------

def _entries(directory: str) -> Dict[int, List[str]]:
    """step -> directory-entry names (a step can have both a legacy .pkl
    and a checkpoint dir; the dir wins at load)."""
    out: Dict[int, List[str]] = {}
    for name in os.listdir(directory):
        if name.startswith(_TMP_PREFIX):
            continue
        base = name[:-4] if name.endswith(".pkl") else name
        if base.isdigit():
            out.setdefault(int(base), []).append(name)
    return out


def _checkpoint_path(directory: str, step: int) -> Tuple[str, str]:
    d = os.path.join(directory, str(step))
    if os.path.isdir(d):
        if os.path.isfile(os.path.join(d, MANIFEST)) or \
                os.path.isdir(os.path.join(d, "orbax")) or \
                os.path.isfile(os.path.join(d, "state.pkl")):
            # new layout (a new-layout dir WITHOUT its manifest is torn:
            # the atomic publish always includes it)
            return d, "dir"
        # pre-manifest layout: orbax payload directly under <dir>/<step>
        return d, "orbax-legacy"
    if os.path.isfile(d + ".pkl"):
        return d + ".pkl", "pkl-legacy"
    raise FileNotFoundError(f"no checkpoint for step {step} in {directory}")


def valid_steps(directory: str) -> List[int]:
    """Ascending steps whose checkpoints pass structural (shallow)
    manifest validation; the deep sha256 check runs at load time, before
    any scope mutation, where a same-size bit-flip actually matters."""
    if not os.path.isdir(directory):
        return []
    return [s for s in sorted(_entries(directory))
            if validate_checkpoint(directory, s, deep=False)]


def latest_step(directory: str, validate: bool = True) -> Optional[int]:
    """Newest step — by default the newest that passes validation, so a
    torn/manifest-less write can never be offered for resume."""
    if not os.path.isdir(directory):
        return None
    steps = valid_steps(directory) if validate \
        else sorted(_entries(directory))
    return steps[-1] if steps else None


def _load_state(directory: str, step: int) -> dict:
    """Verify + fully deserialize one checkpoint (no scope mutation)."""
    manifest = verify_checkpoint(directory, step)
    path, kind = _checkpoint_path(directory, step)
    try:
        import pickle
        if kind == "pkl-legacy":
            with open(path, "rb") as f:
                return pickle.load(f)
        if kind == "orbax-legacy":
            import orbax.checkpoint as ocp
            return ocp.PyTreeCheckpointer().restore(os.path.abspath(path))
        if manifest.get("format") == "orbax":
            import orbax.checkpoint as ocp
            ckptr = ocp.PyTreeCheckpointer()
            return ckptr.restore(
                os.path.abspath(os.path.join(path, "orbax")))
        with open(os.path.join(path, "state.pkl"), "rb") as f:
            return pickle.load(f)
    except CheckpointCorrupt:
        raise
    except Exception as e:
        raise CheckpointCorrupt(
            f"checkpoint {path}: failed to deserialize: {e}") from e


def _apply_state(state: dict, program, scope) -> dict:
    extra = {}
    persistable = {v.name for v in program.list_vars() if v.persistable}
    for k, v in state.items():
        if k.startswith("__extra__"):
            extra[k[len("__extra__"):]] = v
        elif k in persistable:
            scope.set_var(k, np.asarray(v))
    return extra


def restore_latest(directory: str, program=None,
                   scope=None) -> Tuple[Optional[int], dict]:
    """Restore the newest checkpoint that fully validates AND loads;
    corrupt/incomplete ones are skipped (logged + counted), newest-first.
    Returns (step, extra_state) or (None, {})."""
    from .framework.core import default_main_program
    from .framework.executor import global_scope

    program = program or default_main_program()
    scope = scope or global_scope()
    if not os.path.isdir(directory):
        return None, {}
    with telemetry.trace_span("ckpt/restore", dir=directory):
        for step in sorted(_entries(directory), reverse=True):
            try:
                state = _load_state(directory, step)
            except (CheckpointCorrupt, FileNotFoundError) as e:
                stat_add("checkpoint_corrupt_skipped")
                logger.warning("skipping corrupt checkpoint step %s: %s",
                               step, e)
                continue
            # only mutate the scope once a checkpoint fully deserialized:
            # a torn read must not leave a half-restored state behind
            extra = _apply_state(state, program, scope)
            stat_add("checkpoint_resumes")
            telemetry.log_event("ckpt_resume", step=int(step),
                                dir=directory)
            return step, extra
    return None, {}


def load_checkpoint(directory: str, step: Optional[int] = None,
                    program=None, scope=None) -> dict:
    """Restore persistable vars into the scope; returns extra_state.

    With step=None the newest *valid* checkpoint is used, falling back
    past corrupt ones; an explicit step is validated up front and raises
    CheckpointCorrupt before touching the scope."""
    from .framework.core import default_main_program
    from .framework.executor import global_scope

    program = program or default_main_program()
    scope = scope or global_scope()
    if step is None:
        found, extra = restore_latest(directory, program=program,
                                      scope=scope)
        if found is None:
            raise FileNotFoundError(f"no valid checkpoints in {directory}")
        return extra
    return _apply_state(_load_state(directory, step), program, scope)


# ---------------------------------------------------------------------------
# retention
# ---------------------------------------------------------------------------

def gc_checkpoints(directory: str, keep_last_n: int) -> int:
    """Keep the newest `keep_last_n` *valid* checkpoints (corrupt entries
    newer than the boundary are left for forensics — loads skip them);
    delete everything older, plus orphaned .tmp-* write dirs."""
    if not os.path.isdir(directory):
        return 0
    removed = 0
    with telemetry.trace_span("ckpt/gc", keep=keep_last_n):
        for name in os.listdir(directory):
            if name.startswith(_TMP_PREFIX):
                shutil.rmtree(os.path.join(directory, name),
                              ignore_errors=True)
                stat_add("checkpoint_tmp_cleaned")
        entries = _entries(directory)
        kept_valid = 0
        for step in sorted(entries, reverse=True):
            if kept_valid < keep_last_n:
                # shallow check: retention ordering must not re-hash
                # every retained checkpoint on every save (load still
                # deep-checks)
                if validate_checkpoint(directory, step, deep=False):
                    kept_valid += 1
                continue
            for name in entries[step]:
                path = os.path.join(directory, name)
                shutil.rmtree(path) if os.path.isdir(path) \
                    else os.remove(path)
                removed += 1
                stat_add("checkpoints_gc")
    return removed
