"""Sharded / async checkpointing (orbax-backed).

Reference analog: auto-checkpoint + save_persistables (SURVEY.md §5
checkpoint/resume). On TPU the state is a pytree of (possibly sharded)
jax.Arrays; orbax writes each shard from its owning host and restores
with the target sharding — the reference's per-var save ops can't express
that.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np


def _persistable_state(program, scope) -> Dict[str, object]:
    state = {}
    for v in program.list_vars():
        if v.persistable:
            val = scope.find_var(v.name)
            if val is not None:
                state[v.name] = val
    return state


def save_checkpoint(directory: str, step: int, program=None, scope=None,
                    extra_state: Optional[dict] = None,
                    use_orbax: bool = True):
    """Save all persistable vars (+ extra_state) under directory/step."""
    from .framework.core import default_main_program
    from .framework.executor import global_scope

    program = program or default_main_program()
    scope = scope or global_scope()
    state = _persistable_state(program, scope)
    if extra_state:
        state = dict(state, **{f"__extra__{k}": v
                               for k, v in extra_state.items()})
    path = os.path.join(directory, str(step))
    if use_orbax:
        try:
            import orbax.checkpoint as ocp
            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(os.path.abspath(path),
                       {k: np.asarray(v) for k, v in state.items()},
                       force=True)
            return path
        except Exception:
            pass  # fall through to pickle
    import pickle
    os.makedirs(directory, exist_ok=True)
    with open(path + ".pkl", "wb") as f:
        pickle.dump({k: np.asarray(v) for k, v in state.items()}, f,
                    protocol=2)
    return path + ".pkl"


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        base = name[:-4] if name.endswith(".pkl") else name
        if base.isdigit():
            steps.append(int(base))
    return max(steps) if steps else None


def load_checkpoint(directory: str, step: Optional[int] = None,
                    program=None, scope=None) -> dict:
    """Restore persistable vars into the scope; returns extra_state."""
    from .framework.core import default_main_program
    from .framework.executor import global_scope

    program = program or default_main_program()
    scope = scope or global_scope()
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {directory}")
    path = os.path.join(directory, str(step))
    state = None
    if os.path.exists(path + ".pkl"):
        import pickle
        with open(path + ".pkl", "rb") as f:
            state = pickle.load(f)
    else:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        state = ckptr.restore(os.path.abspath(path))
    extra = {}
    persistable = {v.name for v in program.list_vars() if v.persistable}
    for k, v in state.items():
        if k.startswith("__extra__"):
            extra[k[len("__extra__"):]] = v
        elif k in persistable:
            scope.set_var(k, np.asarray(v))
    return extra
