"""paddle.static-style namespace (reference python/paddle/static/):
the static-graph API surface under its 2.0 name.
"""
from .framework.core import (Program, default_main_program,  # noqa
                             default_startup_program, program_guard,
                             device_guard)
from .framework.executor import Executor, Scope, global_scope, scope_guard  # noqa
from .framework.compiler import BuildStrategy, CompiledProgram, ExecutionStrategy  # noqa
from .framework.backward import append_backward, gradients  # noqa
from .layers.tensor import create_parameter, data  # noqa
from .io import (load_inference_model, save_inference_model,  # noqa
                 load_persistables as load, save_persistables as save)
from . import nn as _nn  # noqa


class InputSpec:
    """reference paddle.static.InputSpec — shape/dtype/name descriptor."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype!r}, "
                f"name={self.name!r})")
