"""Black-box flight recorder: crash forensics for serving processes.

The observability stack (spans, the device observatory, the fleet
observatory's TSDB) explains *live* processes; this module is the
artifact a **dead** one leaves behind.  It keeps small, bounded,
lock-disciplined rings in memory — the flight recorder — and dumps
them as one JSON postmortem the moment the process (or its
supervisor) knows it is dying:

* **last-K events** — every ``telemetry.log_event`` record is
  mirrored into a ``FLAGS_blackbox_events``-deep ring (the event may
  also land in ``events.jsonl``; the ring works without a metrics
  dir and survives in the dump when the final appends are torn);
* **metric snapshots** — counters + gauges captured on the
  ``FLAGS_metrics_interval`` flush cadence (a short history, so the
  dump shows the *trend* into the crash, not one point);
* **request last words** — per in-flight request: trace id,
  endpoint, slot/bucket, and the admission→now phase, recorded at
  admission and retired at respond (``FLAGS_blackbox_requests`` cap;
  what was the process serving when it stopped?);
* **span ring + counter samples** — read from telemetry at dump
  time (zero extra hot-path cost) and stored as chrome-trace events
  so ``tools/trace_export.py`` merges a dead replica's last seconds
  into the fleet Perfetto timeline.

Dump triggers:

* fatal signals — ``install()`` wires :mod:`faulthandler` (native
  tracebacks to ``postmortem/<pid>-faulthandler.txt``) plus Python
  handlers for SIGABRT/SIGSEGV/SIGBUS/SIGFPE where installable (the
  handler dumps, restores ``SIG_DFL``, and re-raises so the exit
  code still names the signal);
* uncaught scheduler-thread exceptions — the serving dispatch
  workers, the generation scheduler, and the router poll loop call
  :func:`dump_exception` before re-raising (plus a
  ``threading.excepthook`` chain installed by ``install()``);
* watchdog kills — the fleet supervisor calls
  :func:`write_kill_mark` into the victim's metrics dir *before* it
  SIGKILLs a hung replica (a SIGSTOP'd process cannot dump itself);
* the flush cadence — a rolling ``<pid>-rolling.json`` dump, so
  even a SIGKILL'd process (which gets no signal handler) leaves
  its ring as of the last cadence tick;
* explicit request — ``GET /debugz?dump=1`` or a direct
  :func:`dump` call.

Every write is atomic (tmp + ``os.replace``), routed through the
``blackbox_dump`` fault site, and **never raises**: a failed dump
bumps ``blackbox_dump_failures`` and the process dies exactly as it
would have anyway.  ``FLAGS_blackbox=0`` (or ``FLAGS_telemetry=0``)
means zero per-request work — one dict lookup at admission, nothing
recorded, no files (the PR-13 contract).

The supervisor half lives in :func:`harvest` /
:func:`attribute_death`: scan ``postmortem/`` for a dead pid's
artifacts and classify the death — ``clean_exit`` / ``hung_kill``
(the kill mark) / ``signal:<NAME>`` (decoded from the negative
waitpid rc) / ``crash:<reason>`` (a self-dump) / ``unexplained``
(died rc>0 with no self-dump — the count chaos hard-zeroes).

Stats (README catalog): counters ``blackbox_dumps``,
``blackbox_dump_failures``.
"""
from __future__ import annotations

import faulthandler
import json
import logging
import os
import signal
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

from . import fault, telemetry
from .flags import all_flags, flag_value
from .monitor import process_uptime_s, stat_add

__all__ = ["enabled", "record_event", "request_begin", "request_phase",
           "request_end", "snapshot", "dump", "dump_exception",
           "install", "postmortem_dir", "write_kill_mark", "harvest",
           "attribute_death", "signal_name", "reset"]

logger = logging.getLogger("paddle_tpu.blackbox")

# metric-snapshot ring depth: the flush cadence feeds it, so 8 points
# at the default 10s interval is the last ~80s of counter/gauge trend
# — enough to see "queue depth climbing into the crash" without
# bloating every dump
_SNAPSHOT_KEEP = 8

# fatal signals a Python handler can meaningfully intercept; SIGKILL
# and SIGSTOP are uncatchable by definition (the rolling dump and the
# supervisor's kill mark cover those deaths)
_FATAL_SIGNALS = ("SIGABRT", "SIGSEGV", "SIGBUS", "SIGFPE", "SIGILL")


def enabled() -> bool:
    """One-dict-lookup gate (two, counting telemetry's): the recorder
    does per-request work only when both the master telemetry switch
    and ``FLAGS_blackbox`` are on."""
    return bool(flag_value("FLAGS_blackbox")) and telemetry.enabled()


class _Recorder:
    """The process-wide flight recorder: three bounded rings under one
    lock.  Ring appends are O(1) deque ops; nothing here does I/O —
    the only writes happen at dump time."""

    def __init__(self):
        self._lock = threading.Lock()
        ev_cap = int(flag_value("FLAGS_blackbox_events") or 256)
        req_cap = int(flag_value("FLAGS_blackbox_requests") or 64)
        self._events: deque = deque(maxlen=max(1, ev_cap))
        self._snapshots: deque = deque(maxlen=_SNAPSHOT_KEEP)
        self._requests: Dict[int, dict] = {}
        self._req_cap = max(1, req_cap)
        self._req_seq = 0
        self._req_dropped = 0

    # -- feeds --------------------------------------------------------------
    def event(self, kind: str, fields: dict):
        rec = {"ts": round(time.time(), 6), "event": kind}
        if fields:
            rec.update(fields)
        with self._lock:
            self._events.append(rec)

    def metrics_snapshot(self):
        snap = telemetry.metrics.snapshot()
        rec = {"ts": round(time.time(), 3),
               "counters": snap.get("counters", {}),
               "gauges": snap.get("gauges", {})}
        with self._lock:
            self._snapshots.append(rec)

    def req_begin(self, trace_id, endpoint, attrs) -> Optional[int]:
        now = time.monotonic()
        with self._lock:
            if len(self._requests) >= self._req_cap:
                self._req_dropped += 1
                return None
            self._req_seq += 1
            tok = self._req_seq
            rec = {"trace_id": trace_id, "endpoint": endpoint,
                   "t_admit": now, "phase": "admitted"}
            if attrs:
                rec.update(attrs)
            self._requests[tok] = rec
        return tok

    def req_phase(self, tok: int, phase: str, attrs):
        with self._lock:
            rec = self._requests.get(tok)
            if rec is None:
                return
            rec["phase"] = phase
            if attrs:
                rec.update(attrs)

    def req_end(self, tok: int):
        with self._lock:
            self._requests.pop(tok, None)

    # -- reads --------------------------------------------------------------
    def ring(self) -> dict:
        now = time.monotonic()
        with self._lock:
            events = list(self._events)
            snaps = list(self._snapshots)
            reqs = [dict(r) for r in self._requests.values()]
            dropped = self._req_dropped
        for r in reqs:
            # admission→now age replaces the raw monotonic stamp
            # (meaningless outside this process)
            r["age_ms"] = round((now - r.pop("t_admit")) * 1e3, 3)
        return {"events": events, "metric_snapshots": snaps,
                "live_requests": reqs, "requests_dropped": dropped,
                "capacity": {"events": self._events.maxlen,
                             "requests": self._req_cap}}


_recorder: Optional[_Recorder] = None
_recorder_lock = threading.Lock()


def _get() -> _Recorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = _Recorder()
    return _recorder


def reset():
    """Drop the recorder (ring capacities re-read the FLAGS on next
    use) and re-arm the install guard.  Test hook."""
    global _recorder, _installed
    with _recorder_lock:
        _recorder = None
        _installed = False


# ---------------------------------------------------------------------------
# feeds (called from telemetry taps and the serving engines)
# ---------------------------------------------------------------------------

def record_event(kind: str, **fields):
    """Mirror one event record into the ring (telemetry's
    ``log_event`` tap calls this; anything else may too).  No-op when
    disabled."""
    if not enabled():
        return
    _get().event(kind, fields)


def request_begin(trace_id: Optional[str], endpoint: str,
                  **attrs) -> Optional[int]:
    """Record a request's last words at admission; returns an opaque
    token for :func:`request_phase` / :func:`request_end`, or None
    (disabled, or the in-flight cap is reached — the request is
    simply not recorded)."""
    if not enabled():
        return None
    return _get().req_begin(trace_id, endpoint, attrs)


def request_phase(token: Optional[int], phase: str, **attrs):
    """Advance a recorded request's phase (``admitted`` →
    ``executing`` / ``decoding`` ...).  No-op on a None token."""
    if token is None:
        return
    _get().req_phase(token, phase, attrs)


def request_end(token: Optional[int]):
    """Retire a recorded request (it responded — its last words are
    no longer interesting).  No-op on a None token."""
    if token is None:
        return
    _get().req_end(token)


def on_flush():
    """Flush-cadence tap (wired from ``telemetry.flush``): capture a
    counter/gauge snapshot into the ring and refresh the rolling dump
    — the artifact a SIGKILL'd process leaves behind."""
    if not enabled():
        return
    _get().metrics_snapshot()
    if telemetry._metrics_dir() is not None:
        dump("rolling", quiet=True)


# ---------------------------------------------------------------------------
# snapshot + dump
# ---------------------------------------------------------------------------

def snapshot() -> dict:
    """The live ring as one JSON-able doc (the ``/debugz`` payload's
    ``blackbox`` block).  Works disabled too — reports the state, not
    an error."""
    if not enabled():
        return {"enabled": False}
    doc = {"enabled": True, "dump_dir": postmortem_dir()}
    doc.update(_get().ring())
    return doc


def postmortem_dir(metrics_dir: Optional[str] = None) -> Optional[str]:
    """``<metrics_dir>/postmortem`` (None without a metrics dir)."""
    d = metrics_dir if metrics_dir is not None \
        else telemetry._metrics_dir()
    return os.path.join(str(d), "postmortem") if d else None


def _sanitize(reason: str) -> str:
    out = "".join(c if c.isalnum() or c in "_-" else "_"
                  for c in str(reason))
    return out[:64] or "unknown"


def _atomic_dump(path: str, doc: dict) -> bool:
    """tmp + os.replace through the ``blackbox_dump`` fault site;
    never raises (the dump path runs while the process is dying — an
    I/O error must not mask the original failure)."""
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        if fault.fire("blackbox_dump") == "raise":
            raise fault.InjectedFault(
                f"injected blackbox dump failure "
                f"({os.path.basename(path)})")
        text = json.dumps(doc, default=str)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)
        return True
    except (OSError, TypeError, ValueError) as e:
        stat_add("blackbox_dump_failures")
        logger.warning("blackbox dump %s failed: %s", path, e)
        try:
            os.remove(tmp)
        except OSError:
            pass  # ok: tmp may never have been created
        return False


def _exc_block(exc: Optional[BaseException]) -> Optional[dict]:
    if exc is None:
        return None
    return {"type": type(exc).__name__, "message": str(exc),
            "traceback": traceback.format_exception(
                type(exc), exc, exc.__traceback__)}


def dump(reason: str, exc: Optional[BaseException] = None,
         thread: Optional[str] = None,
         quiet: bool = False) -> Optional[str]:
    """Write the full postmortem document to
    ``<metrics_dir>/postmortem/<pid>-<reason>.json`` (atomic; never
    raises).  Returns the path, or None (disabled / no metrics dir /
    write failed).  The doc carries the three rings, the live metric
    registry, every flag's current value, and the span ring + counter
    samples as chrome-trace events (``trace_events``) so
    ``tools/trace_export.py`` can merge the dead process's last
    seconds into a fleet timeline."""
    if not enabled():
        return None
    d = postmortem_dir()
    if d is None:
        return None
    reason = _sanitize(reason)
    trace_events = telemetry.spans_to_chrome_events() \
        + telemetry.counters_to_chrome_events()
    doc = {
        "schema": "paddle_tpu.postmortem.v1",
        "pid": os.getpid(),
        "reason": reason,
        "time": round(time.time(), 6),
        "uptime_s": process_uptime_s(),
        "replica_id": os.environ.get("PADDLE_TPU_REPLICA_ID"),
        "restart_count": os.environ.get("PADDLE_TPU_RESTART_COUNT"),
        "thread": thread or threading.current_thread().name,
        "exception": _exc_block(exc),
        "blackbox": _get().ring(),
        "metrics": telemetry.metrics.snapshot(),
        "flags": all_flags(),
        "trace_events": trace_events,
    }
    path = os.path.join(d, f"{os.getpid()}-{reason}.json")
    if not _atomic_dump(path, doc):
        return None
    stat_add("blackbox_dumps")
    if not quiet:
        logger.warning("blackbox postmortem dumped: %s (reason=%s)",
                       path, reason)
    return path


def dump_exception(where: str, exc: BaseException) -> Optional[str]:
    """Dump for an uncaught scheduler/dispatch-thread exception
    (reason ``uncaught_<where>``).  Callers re-raise afterwards — the
    recorder observes the death, it never absorbs it."""
    return dump(f"uncaught_{where}", exc=exc)


# ---------------------------------------------------------------------------
# fatal-signal + thread-excepthook installation
# ---------------------------------------------------------------------------

_installed = False
_fh_file = None  # keeps the faulthandler fd alive for process lifetime


def _fatal_handler(signum, frame):
    try:
        name = signal.Signals(signum).name
    except ValueError:
        name = str(signum)
    dump(f"signal_{name}", thread=threading.current_thread().name)
    # die by the same signal so the supervisor's waitpid rc still
    # names it (rc = -signum) — the dump must not launder the death
    # into a clean exit
    try:
        signal.signal(signum, signal.SIG_DFL)
        signal.raise_signal(signum)
    except (OSError, ValueError):
        os._exit(128 + int(signum))


def install() -> bool:
    """Wire the process-death triggers (idempotent; replica startup
    calls this).  Returns True when the recorder is active.  Each
    piece is independently best-effort: faulthandler needs a metrics
    dir, Python signal handlers need the main thread, and neither
    failing disables the rings or the explicit/rolling dumps."""
    global _installed, _fh_file
    if not enabled():
        return False
    if _installed:
        return True
    _installed = True
    d = postmortem_dir()
    if d is not None:
        try:
            os.makedirs(d, exist_ok=True)
            _fh_file = open(os.path.join(
                d, f"{os.getpid()}-faulthandler.txt"), "w")
            faulthandler.enable(file=_fh_file)
        except OSError as e:
            logger.warning("faulthandler install failed: %s", e)
    for name in _FATAL_SIGNALS:
        sig = getattr(signal, name, None)
        if sig is None:
            continue
        try:
            signal.signal(sig, _fatal_handler)
        except (ValueError, OSError) as e:
            # non-main thread / platform refusal: faulthandler (native
            # traceback) and the rolling dump still cover this signal
            logger.debug("handler for %s not installable: %s", name, e)
    prev_hook = threading.excepthook

    def _bb_excepthook(args):
        tname = args.thread.name if args.thread is not None else "?"
        if args.exc_value is not None:
            dump(f"uncaught_thread_{_sanitize(tname)}",
                 exc=args.exc_value, thread=tname)
        prev_hook(args)

    threading.excepthook = _bb_excepthook
    if d is not None:
        # seed the rolling dump NOW: a life SIGKILLed before its first
        # flush cadence still leaves a flight-recorder artifact (empty
        # rings beat an unexplained death)
        dump("rolling", quiet=True)
    return True


# ---------------------------------------------------------------------------
# supervisor half: kill marks, harvest, attribution
# ---------------------------------------------------------------------------

def write_kill_mark(metrics_dir: str, pid: int,
                    reason: str = "hung_kill", **fields) -> Optional[str]:
    """Written by the SUPERVISOR into the victim's metrics dir before
    it shoots: a SIGSTOP'd/wedged replica cannot dump itself, so the
    mark is the fault-window evidence its death leaves behind.  Same
    atomic/never-raise discipline (and the same ``blackbox_dump``
    fault site) as a self-dump."""
    if not flag_value("FLAGS_blackbox"):
        return None
    reason = _sanitize(reason)
    d = postmortem_dir(metrics_dir)
    doc = {"schema": "paddle_tpu.postmortem.v1", "pid": int(pid),
           "reason": reason, "time": round(time.time(), 6),
           "written_by": "supervisor", "supervisor_pid": os.getpid()}
    doc.update(fields)
    path = os.path.join(d, f"{int(pid)}-{reason}.json")
    if not _atomic_dump(path, doc):
        return None
    stat_add("blackbox_dumps")
    return path


def harvest(metrics_dir: str, pid: int) -> List[dict]:
    """Collect a dead pid's postmortem artifacts:
    ``{path, reason, written_by}`` per ``<pid>-*.json`` found (the
    faulthandler text rides along as reason ``faulthandler``).
    Read-only and exception-free — harvesting runs inside the crash
    monitor's poll and must never wedge it."""
    d = postmortem_dir(metrics_dir)
    if d is None:
        return []
    prefix = f"{int(pid)}-"
    out = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return []
    for name in names:
        if not name.startswith(prefix):
            continue
        stem, ext = os.path.splitext(name)
        reason = stem[len(prefix):]
        art = {"path": os.path.join(d, name), "reason": reason}
        if ext == ".json":
            doc = None
            try:
                with open(art["path"], encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                art["torn"] = True
            if isinstance(doc, dict):
                art["written_by"] = doc.get("written_by", "self")
                if doc.get("exception"):
                    art["exception"] = doc["exception"].get("type")
        elif stem[len(prefix):] == "faulthandler":
            try:
                art["empty"] = os.path.getsize(art["path"]) == 0
            except OSError:
                art["empty"] = True
        out.append(art)
    return out


def signal_name(rc: Optional[int]) -> Optional[str]:
    """Decode a waitpid return code into the signal that killed the
    process (``rc < 0`` on POSIX ⇒ ``-rc`` is the signal number), or
    None for clean/error exits — the one helper every death-reporting
    site shares so logs, events, and ``/fleetz`` agree."""
    if rc is None or rc >= 0:
        return None
    try:
        return signal.Signals(-rc).name
    except ValueError:
        return f"SIG{-rc}"


def attribute_death(rc: Optional[int],
                    artifacts: List[dict]) -> str:
    """Classify one replica death from its exit code + harvested
    artifacts.  Taxonomy (the README 'Crash forensics' contract):

    * ``hung_kill`` — the supervisor's kill mark is present (the
      liveness watchdog shot it; rc is -SIGKILL underneath);
    * ``clean_exit`` — rc 0 (planned drain or normal exit);
    * ``signal:<NAME>`` — died by signal (the OS names the killer);
    * ``crash:<reason>`` — rc > 0 with a self-dump (the process saw
      its own death and said why);
    * ``unexplained`` — rc > 0 (or unknowable) with NO self-dump:
      the death the flight recorder exists to eliminate.  Rolling
      dumps and faulthandler text are context, not an explanation.
    """
    reasons = {a["reason"] for a in artifacts}
    if "hung_kill" in reasons:
        return "hung_kill"
    if rc == 0:
        return "clean_exit"
    sig = signal_name(rc)
    if sig is not None:
        return f"signal:{sig}"
    self_dumps = sorted(
        r for a in artifacts
        for r in [a["reason"]]
        if a.get("written_by", "self") == "self"
        and r not in ("rolling", "faulthandler") and not a.get("torn"))
    if self_dumps:
        return f"crash:{self_dumps[0]}"
    return "unexplained"


# ---------------------------------------------------------------------------
# telemetry taps (import-time wiring; telemetry never imports us back)
# ---------------------------------------------------------------------------

def _event_tap(kind: str, fields: dict):
    if flag_value("FLAGS_blackbox"):  # telemetry.enabled() already held
        _get().event(kind, fields)


telemetry._blackbox_event_tap = _event_tap
telemetry._blackbox_flush_tap = on_flush
