"""Vision datasets (reference python/paddle/vision/datasets/{mnist,
cifar,folder}.py + python/paddle/dataset/{mnist,cifar}.py parsers).

Zero-egress environment: ``download=True`` is unavailable — every
dataset takes explicit local paths (the reference's
image_path/label_path/data_file arguments with download=False) and
raises a clear error otherwise. File formats match the published
datasets exactly (idx-ubyte for MNIST, python-pickle tar for CIFAR,
class-per-directory for ImageFolder), so real downloaded copies load
unchanged.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..reader import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100",
           "DatasetFolder", "ImageFolder"]


def _need(path, what):
    if not path:
        raise ValueError(
            f"{what}: downloads are unavailable in this environment; "
            "pass the local file path (reference download=False mode)")
    if not os.path.exists(path):
        raise FileNotFoundError(f"{what}: no such file {path!r}")
    return path


def _open_maybe_gz(path):
    return gzip.open(path, "rb") if path.endswith(".gz") \
        else open(path, "rb")


class MNIST(Dataset):
    """idx-ubyte MNIST (reference vision/datasets/mnist.py:30; parser
    semantics from dataset/mnist.py:53-70). Yields (image HW1 float32,
    label int64); `transform` applies to the image."""

    NAME = "MNIST"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        image_path = _need(image_path, f"{self.NAME} images")
        label_path = _need(label_path, f"{self.NAME} labels")
        self.transform = transform
        with _open_maybe_gz(image_path) as f:
            buf = f.read()
        magic, n, rows, cols = struct.unpack_from(">IIII", buf, 0)
        if magic != 2051:
            raise ValueError(
                f"{self.NAME}: bad image-file magic {magic} (expected "
                "2051 — idx3-ubyte)")
        self.images = np.frombuffer(
            buf, np.uint8, count=n * rows * cols,
            offset=struct.calcsize(">IIII")).reshape(n, rows, cols, 1)
        with _open_maybe_gz(label_path) as f:
            buf = f.read()
        magic, n2 = struct.unpack_from(">II", buf, 0)
        if magic != 2049:
            raise ValueError(
                f"{self.NAME}: bad label-file magic {magic} (expected "
                "2049 — idx1-ubyte)")
        self.labels = np.frombuffer(buf, np.uint8, count=n2,
                                    offset=struct.calcsize(">II"))
        if n != n2:
            raise ValueError(
                f"{self.NAME}: {n} images but {n2} labels")

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        # raw uint8 HWC to the transform (the reference hands ToTensor
        # a PIL image; dtype-keyed scaling needs the original dtype)
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)
        return img, np.int64(self.labels[idx])


class FashionMNIST(MNIST):
    NAME = "FashionMNIST"


class _CifarBase(Dataset):
    """python-pickle tar (reference vision/datasets/cifar.py +
    dataset/cifar.py): members data_batch_*/test_batch (cifar-10) or
    train/test (cifar-100)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, members=None, label_key=b"labels"):
        data_file = _need(data_file, type(self).__name__)
        self.transform = transform
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tar:
            names = [m.name for m in tar.getmembers()]
            want = [n for n in names
                    if any(n.endswith(m) for m in members)]
            if not want:
                raise ValueError(
                    f"{type(self).__name__}: no {members} members in "
                    f"{data_file!r} (found {names[:5]}...)")
            for name in sorted(want):
                d = pickle.load(tar.extractfile(name),
                                encoding="bytes")
                images.append(np.asarray(d[b"data"], np.uint8))
                labels.extend(d[label_key])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32) \
            .transpose(0, 2, 3, 1)  # HWC
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar10(_CifarBase):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False):
        members = ["test_batch"] if mode == "test" else \
            [f"data_batch_{i}" for i in range(1, 6)]
        super().__init__(data_file, mode, transform, download,
                         members=members, label_key=b"labels")


class Cifar100(_CifarBase):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False):
        members = ["test"] if mode == "test" else ["train"]
        super().__init__(data_file, mode, transform, download,
                         members=members, label_key=b"fine_labels")


_IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


def _load_image(path):
    if path.endswith(".npy"):
        return np.load(path)
    from PIL import Image

    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"))


class DatasetFolder(Dataset):
    """class-per-subdirectory layout (reference
    vision/datasets/folder.py DatasetFolder)."""

    def __init__(self, root, transform=None, extensions=None,
                 loader=None):
        root = _need(root, "DatasetFolder root")
        self.transform = transform
        self.loader = loader or _load_image
        exts = tuple(extensions or _IMG_EXTENSIONS)
        self.classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        if not self.classes:
            raise ValueError(
                f"DatasetFolder: no class subdirectories in {root!r}")
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        self.samples = []
        for c in self.classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    if fname.lower().endswith(exts):
                        self.samples.append(
                            (os.path.join(dirpath, fname),
                             self.class_to_idx[c]))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)


class ImageFolder(Dataset):
    """flat/recursive image listing without labels (reference
    folder.py ImageFolder)."""

    def __init__(self, root, transform=None, extensions=None,
                 loader=None):
        root = _need(root, "ImageFolder root")
        self.transform = transform
        self.loader = loader or _load_image
        exts = tuple(extensions or _IMG_EXTENSIONS)
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                if fname.lower().endswith(exts):
                    self.samples.append(os.path.join(dirpath, fname))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]
