"""Vision model zoo as dygraph Layers (reference
python/paddle/vision/models/{lenet,vgg,resnet}.py).

Pretrained weights are not downloadable here (zero egress); the
constructors accept ``pretrained=False`` only and load weights via the
normal ``set_state_dict`` path instead.
"""
from __future__ import annotations

from ..dygraph import (BatchNorm, Conv2D, Layer, Linear, Pool2D,
                       Sequential)
from ..dygraph.nn import Flatten

__all__ = ["LeNet", "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
           "ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152"]


def _no_pretrained(pretrained, name):
    if pretrained:
        raise ValueError(
            f"{name}: pretrained weights are not downloadable in this "
            "environment; construct with pretrained=False and load a "
            "local state_dict")


class LeNet(Layer):
    """reference vision/models/lenet.py (28x28 single-channel)."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1, act="relu"),
            Pool2D(2, "max", 2),
            Conv2D(6, 16, 5, stride=1, padding=0, act="relu"),
            Pool2D(2, "max", 2),
        )
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Sequential(
                Flatten(),
                Linear(400, 120, act="relu"),
                Linear(120, 84, act="relu"),
                Linear(84, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.fc(x)
        return x


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512,
          "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512,
          512, "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512,
          512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(Layer):
    """reference vision/models/vgg.py (batch-norm variant)."""

    def __init__(self, cfg, num_classes=1000, with_pool=True):
        super().__init__()
        layers, c_in = [], 3
        for v in cfg:
            if v == "M":
                layers.append(Pool2D(2, "max", 2))
            else:
                layers.append(Conv2D(c_in, v, 3, padding=1))
                layers.append(BatchNorm(v, act="relu"))
                c_in = v
        self.features = Sequential(*layers)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if num_classes > 0:
            self.classifier = Sequential(
                Flatten(),
                Linear(512 * 7 * 7, 4096, act="relu"),
                Linear(4096, 4096, act="relu"),
                Linear(4096, num_classes),
            )

    def forward(self, x):
        from .. import layers as L

        x = self.features(x)
        if self.with_pool:
            # reference vgg.py AdaptiveAvgPool2D((7,7)); static-shape
            # XLA needs the feature map divisible by 7 (224-class
            # inputs; see ops/nn_ops.py adaptive pool)
            x = L.adaptive_pool2d(x, [7, 7], pool_type="avg")
        if self.num_classes > 0:
            x = self.classifier(x)
        return x


def _vgg(cfg_key, pretrained, name, **kw):
    _no_pretrained(pretrained, name)
    return VGG(_VGG_CFGS[cfg_key], **kw)


def vgg11(pretrained=False, **kw):
    return _vgg("A", pretrained, "vgg11", **kw)


def vgg13(pretrained=False, **kw):
    return _vgg("B", pretrained, "vgg13", **kw)


def vgg16(pretrained=False, **kw):
    return _vgg("D", pretrained, "vgg16", **kw)


def vgg19(pretrained=False, **kw):
    return _vgg("E", pretrained, "vgg19", **kw)


class _BasicBlock(Layer):
    expansion = 1

    def __init__(self, c_in, c_out, stride=1, downsample=None):
        super().__init__()
        self.conv1 = Conv2D(c_in, c_out, 3, stride=stride, padding=1,
                            bias_attr=False)
        self.bn1 = BatchNorm(c_out, act="relu")
        self.conv2 = Conv2D(c_out, c_out, 3, stride=1, padding=1,
                            bias_attr=False)
        self.bn2 = BatchNorm(c_out)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = self.bn2(self.conv2(self.bn1(self.conv1(x))))
        from .. import layers as L

        return L.relu(out + identity)


class _BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, c_in, c_out, stride=1, downsample=None):
        super().__init__()
        self.conv1 = Conv2D(c_in, c_out, 1, bias_attr=False)
        self.bn1 = BatchNorm(c_out, act="relu")
        self.conv2 = Conv2D(c_out, c_out, 3, stride=stride, padding=1,
                            bias_attr=False)
        self.bn2 = BatchNorm(c_out, act="relu")
        self.conv3 = Conv2D(c_out, c_out * 4, 1, bias_attr=False)
        self.bn3 = BatchNorm(c_out * 4)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = self.bn1(self.conv1(x))
        out = self.bn2(self.conv2(out))
        out = self.bn3(self.conv3(out))
        from .. import layers as L

        return L.relu(out + identity)


class ResNet(Layer):
    """reference vision/models/resnet.py."""

    def __init__(self, block, depth_cfg, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.conv = Conv2D(3, 64, 7, stride=2, padding=3,
                           bias_attr=False)
        self.bn = BatchNorm(64, act="relu")
        self.maxpool = Pool2D(3, "max", 2, pool_padding=1)
        self.c_in = 64
        self.layer1 = self._make_layer(block, 64, depth_cfg[0], 1)
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], 2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], 2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], 2)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, c_out, blocks, stride):
        downsample = None
        if stride != 1 or self.c_in != c_out * block.expansion:
            downsample = Sequential(
                Conv2D(self.c_in, c_out * block.expansion, 1,
                       stride=stride, bias_attr=False),
                BatchNorm(c_out * block.expansion))
        layers = [block(self.c_in, c_out, stride, downsample)]
        self.c_in = c_out * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.c_in, c_out))
        return Sequential(*layers)

    def forward(self, x):
        from .. import layers as L

        x = self.maxpool(self.bn(self.conv(x)))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = L.reduce_mean(x, dim=[2, 3])  # global average pool
        if self.num_classes > 0:
            x = self.fc(x)
        return x


_RESNET_CFGS = {
    18: (_BasicBlock, [2, 2, 2, 2]),
    34: (_BasicBlock, [3, 4, 6, 3]),
    50: (_BottleneckBlock, [3, 4, 6, 3]),
    101: (_BottleneckBlock, [3, 4, 23, 3]),
    152: (_BottleneckBlock, [3, 8, 36, 3]),
}


def _resnet(depth, pretrained, **kw):
    _no_pretrained(pretrained, f"resnet{depth}")
    block, cfg = _RESNET_CFGS[depth]
    return ResNet(block, cfg, **kw)


def resnet18(pretrained=False, **kw):
    return _resnet(18, pretrained, **kw)


def resnet34(pretrained=False, **kw):
    return _resnet(34, pretrained, **kw)


def resnet50(pretrained=False, **kw):
    return _resnet(50, pretrained, **kw)


def resnet101(pretrained=False, **kw):
    return _resnet(101, pretrained, **kw)


def resnet152(pretrained=False, **kw):
    return _resnet(152, pretrained, **kw)
