"""paddle.vision namespace (reference python/paddle/vision/)."""
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from .models import LeNet, ResNet, VGG  # noqa: F401

__all__ = ["transforms", "datasets", "models", "LeNet", "ResNet", "VGG"]
