"""Vision transforms (reference python/paddle/vision/transforms/
transforms.py:83-1170 + functional.py).

numpy-first: every transform consumes/produces HWC numpy arrays (PIL
images are accepted and converted on entry — the reference's 'pil'
backend); interpolation is implemented directly on arrays so the
pipeline has no hard cv2/PIL dependency.
"""
from __future__ import annotations

import numbers
import random

import numpy as np

__all__ = [
    "Compose", "BaseTransform", "ToTensor", "Resize", "CenterCrop",
    "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
    "Normalize", "Transpose", "Pad", "Grayscale", "BrightnessTransform",
    "ContrastTransform", "RandomResizedCrop",
    "resize", "center_crop", "hflip", "vflip", "normalize", "to_tensor",
]


def _to_hwc(img) -> np.ndarray:
    if isinstance(img, np.ndarray):
        arr = img
    else:  # PIL image
        arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def _pair(size):
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


# ---------------------------------------------------------------------------
# functional ops (reference transforms/functional.py)
# ---------------------------------------------------------------------------

def resize(img, size, interpolation="bilinear") -> np.ndarray:
    """size: int (short side) or (h, w). Bilinear/nearest on numpy."""
    arr = _to_hwc(img)
    h, w = arr.shape[:2]
    if isinstance(size, numbers.Number):
        # reference semantics: resize the SHORT side to `size`, keep AR
        if h <= w:
            oh, ow = int(size), max(1, int(round(w * size / h)))
        else:
            oh, ow = max(1, int(round(h * size / w))), int(size)
    else:
        oh, ow = _pair(size)
    if (oh, ow) == (h, w):
        return arr
    if interpolation == "nearest":
        ys = np.clip(np.round(np.arange(oh) * h / oh).astype(int), 0,
                     h - 1)
        xs = np.clip(np.round(np.arange(ow) * w / ow).astype(int), 0,
                     w - 1)
        return arr[ys][:, xs]
    # bilinear, half-pixel centers
    dt = arr.dtype
    y = (np.arange(oh) + 0.5) * h / oh - 0.5
    x = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(y).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(x).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    ly = np.clip(y - y0, 0, 1)[:, None, None]
    lx = np.clip(x - x0, 0, 1)[None, :, None]
    a = arr.astype(np.float64)
    out = (a[y0][:, x0] * (1 - ly) * (1 - lx)
           + a[y0][:, x1] * (1 - ly) * lx
           + a[y1][:, x0] * ly * (1 - lx)
           + a[y1][:, x1] * ly * lx)
    if np.issubdtype(dt, np.integer):
        out = np.round(out).clip(np.iinfo(dt).min, np.iinfo(dt).max)
    return out.astype(dt)


def center_crop(img, output_size) -> np.ndarray:
    arr = _to_hwc(img)
    th, tw = _pair(output_size)
    h, w = arr.shape[:2]
    i = max(0, (h - th) // 2)
    j = max(0, (w - tw) // 2)
    return arr[i:i + th, j:j + tw]


def hflip(img) -> np.ndarray:
    return _to_hwc(img)[:, ::-1]


def vflip(img) -> np.ndarray:
    return _to_hwc(img)[::-1]


def normalize(img, mean, std, data_format="CHW") -> np.ndarray:
    arr = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (arr - mean[:, None, None]) / std[:, None, None]
    return (arr - mean) / std


def to_tensor(img, data_format="CHW") -> np.ndarray:
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference ToTensor).
    Scaling keys off the INPUT dtype (integer images scale by their
    type range; float images pass through), like the reference."""
    raw = _to_hwc(img)
    arr = raw.astype(np.float32)
    if np.issubdtype(raw.dtype, np.integer):
        arr = arr / float(np.iinfo(raw.dtype).max)
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return arr


# ---------------------------------------------------------------------------
# transform classes
# ---------------------------------------------------------------------------

class BaseTransform:
    """reference transforms.py:134 — callable on an image (and
    optionally more inputs, applied to the first)."""

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, pad_if_needed=True):
        self.size = _pair(size)
        self.pad_if_needed = pad_if_needed

    def _apply_image(self, img):
        arr = _to_hwc(img)
        th, tw = self.size
        h, w = arr.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            ph, pw = max(0, th - h), max(0, tw - w)
            arr = np.pad(arr, ((0, ph), (0, pw), (0, 0)))
            h, w = arr.shape[:2]
        i = random.randint(0, h - th)
        j = random.randint(0, w - tw)
        return arr[i:i + th, j:j + tw]


class RandomResizedCrop(BaseTransform):
    """reference transforms.py:396 — random area/ratio crop + resize."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear"):
        self.size = _pair(size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _to_hwc(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = random.randint(0, h - ch)
                j = random.randint(0, w - cw)
                return resize(arr[i:i + ch, j:j + cw], self.size,
                              self.interpolation)
        return resize(center_crop(arr, (min(h, w), min(h, w))),
                      self.size, self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if random.random() < self.prob else _to_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if random.random() < self.prob else _to_hwc(img)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW"):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = tuple(order)

    def _apply_image(self, img):
        return _to_hwc(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        if isinstance(padding, numbers.Number):
            padding = (padding,) * 4  # left, top, right, bottom
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = _to_hwc(img)
        l, t, r, b = self.padding
        if self.padding_mode == "constant":
            return np.pad(arr, ((t, b), (l, r), (0, 0)),
                          constant_values=self.fill)
        return np.pad(arr, ((t, b), (l, r), (0, 0)),
                      mode=self.padding_mode)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        arr = _to_hwc(img).astype(np.float32)
        if arr.shape[2] == 1:
            g = arr
        else:
            g = (0.299 * arr[:, :, :1] + 0.587 * arr[:, :, 1:2]
                 + 0.114 * arr[:, :, 2:3])
        g = np.round(g).astype(_to_hwc(img).dtype) \
            if np.issubdtype(_to_hwc(img).dtype, np.integer) else g
        return np.repeat(g, self.num_output_channels, axis=2)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        arr = _to_hwc(img)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        out = arr.astype(np.float32) * f
        if np.issubdtype(arr.dtype, np.integer):
            out = out.clip(0, 255)
        return out.astype(arr.dtype)


class ContrastTransform(BaseTransform):
    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        arr = _to_hwc(img)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = arr.astype(np.float32).mean()
        out = (arr.astype(np.float32) - mean) * f + mean
        if np.issubdtype(arr.dtype, np.integer):
            out = out.clip(0, 255)
        return out.astype(arr.dtype)
