"""Dygraph data parallel (reference python/paddle/fluid/dygraph/parallel.py
DataParallel:433 + imperative/nccl_context.cc bootstrap).

TPU-native: a single host process drives all local chips through XLA, so
the reference's one-process-per-GPU + NCCL-allreduce layout collapses.
`DataParallel` here is the API-compatible wrapper; gradient averaging uses
an in-jit psum when running under `to_static`/pjit over a dp mesh, and is
the identity at world_size 1. Multi-host scale-out goes through
jax.distributed (parallel/ package) rather than per-process NCCL rings.
"""
from __future__ import annotations

import os

import numpy as np

from .layers import Layer


class ParallelEnv:
    """reference dygraph.parallel.Env / ParallelEnv: env-var topology."""

    def __init__(self):
        self._rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self._endpoints = [e for e in eps.split(",") if e]
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    # reference-era aliases
    local_rank = rank
    nranks = world_size

    @property
    def trainer_endpoints(self):
        return self._endpoints

    @property
    def current_endpoint(self):
        return self._current_endpoint


Env = ParallelEnv


def prepare_context(strategy=None):
    return ParallelEnv()


class ParallelStrategy:
    def __init__(self):
        env = ParallelEnv()
        self.nranks = env.world_size
        self.local_rank = env.rank
        self.trainer_endpoints = env.trainer_endpoints
        self.current_endpoint = env.current_endpoint


class DataParallel(Layer):
    """Wraps a Layer for data-parallel training.

    reference semantics: scale_loss divides by nranks;
    apply_collective_grads coalesces + allreduces gradients
    (fluid/dygraph/parallel.py:288-339). Here: at world_size 1 (single
    host process driving all chips) both are identity — batch-level
    parallelism happens inside the jitted step via GSPMD instead.
    """

    def __init__(self, layers: Layer, strategy=None):
        super().__init__()
        self._layers = layers
        self._strategy = strategy or ParallelStrategy()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @property
    def nranks(self):
        return self._strategy.nranks

    def scale_loss(self, loss):
        if self._strategy.nranks <= 1:
            return loss
        from .. import layers as L
        return L.scale(loss, scale=1.0 / self._strategy.nranks)

    def apply_collective_grads(self):
        """Allreduce-SUM every parameter gradient across processes
        (reference fluid/dygraph/parallel.py:288 coalesce + allreduce).
        The mean comes from ``scale_loss`` having divided the loss by
        nranks — the canonical sequence is
        ``loss = model.scale_loss(loss); loss.backward();
        model.apply_collective_grads()``."""
        if self._strategy.nranks <= 1:
            return
        import jax
        if jax.process_count() <= 1:
            return
        if jax.process_count() != self._strategy.nranks:
            raise RuntimeError(
                f"ParallelStrategy.nranks={self._strategy.nranks} but "
                f"jax.process_count()={jax.process_count()}; gradient "
                "scaling would be wrong")
        from ..distributed.collective import all_reduce
        for p in self.parameters():
            g = getattr(p, "_grad_value", None)
            # every rank must issue every collective in the same order —
            # a rank whose batch didn't touch p contributes zeros
            # (reference parallel.py fills zero grads for exactly this)
            if g is None:
                from ..framework.core import dtype_to_np
                g = np.zeros(p.shape, dtype_to_np(p.dtype))
            p._grad_value = all_reduce(np.asarray(g))

    # delegate module protocol to the wrapped layers
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix=""):
        return self._layers.named_parameters(prefix)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)

    set_dict = set_state_dict
    load_dict = set_state_dict

    def train(self):
        self.training = True
        self._layers.train()
        return self

    def eval(self):
        self.training = False
        self._layers.eval()
        return self
