"""Runtime conversion dispatchers (reference
dygraph_to_static/convert_operators.py: convert_ifelse:?,
convert_while_loop, convert_logical_and/or/not).

Each dispatcher receives the predicate/closures produced by the AST
rewrite and decides AT TRACE TIME whether to build graph control-flow
ops (predicate is a static-graph Variable) or to execute plain Python
(predicate is a bool/ndarray/eager VarBase — exact Python semantics,
including short-circuit).
"""
from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple

from ...framework.core import Variable


class _Undefined:
    """Placeholder for names not yet bound before the control-flow
    statement (reference UndefinedVar). Using one raises the NameError
    the original (untransformed) code would have raised."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):
        return f"<undefined local {self.name!r}>"

    def _raise(self):
        raise NameError(
            f"local variable {self.name!r} referenced before "
            "assignment (it was only assigned on the other branch of a "
            "converted if/while)")

    def __bool__(self):
        self._raise()

    def __getattr__(self, item):
        self._raise()

    def __getitem__(self, item):
        self._raise()

    def __call__(self, *a, **k):
        self._raise()

    def __iter__(self):
        self._raise()

    def __len__(self):
        self._raise()

    def __float__(self):
        self._raise()

    def __int__(self):
        self._raise()

    def __array__(self, *a, **k):
        self._raise()


UNDEF = _Undefined


def _is_tensor_pred(pred) -> bool:
    return isinstance(pred, Variable)


def _same_value(a, b) -> bool:
    """Identity-or-equality that never raises (ndarray-safe)."""
    if a is b:
        return True
    if isinstance(a, _Undefined) or isinstance(b, _Undefined):
        return False
    try:
        import numpy as np

        if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
            return bool(np.array_equal(a, b))
        return bool(a == b)
    except Exception:
        return False


def _check_defined(vals, names, where):
    for v, n in zip(vals, names):
        if isinstance(v, _Undefined):
            raise NameError(
                f"variable {n!r} is read by the converted {where} but "
                "was never assigned on the executed path")


def _undef_only_slots(names, t_outs, f_outs):
    """Slots where exactly one branch left the name UNDEF and the other
    made it a tensor — a branch-local scratch variable. Merging it as
    UNDEF is sound: a later read raises the may-be-unbound NameError
    the original python would risk, while dead scratch (the common
    continuation-rewrite case) costs nothing."""
    out = []
    for i, (tv, fv) in enumerate(zip(t_outs, f_outs)):
        one_undef = (isinstance(tv, _Undefined)
                     != isinstance(fv, _Undefined))
        other_tensor = isinstance(tv, Variable) or isinstance(fv, Variable)
        if one_undef and other_tensor:
            out.append(i)
    return out


def _drop_slots(fn, names, slots):
    def g(*a):
        outs = list(fn(*a))
        for i in slots:
            outs[i] = _Undefined(names[i])
        return tuple(outs)
    return g


def convert_ifelse(pred, true_fn: Callable, false_fn: Callable,
                   names: Sequence[str], init: Tuple,
                   _retried: bool = False) -> Tuple:
    """Returns the post-if values of `names` (every name either branch
    assigns). Branch functions are pure: they take the pre-branch
    values and return the tuple of post-branch values."""
    if not _is_tensor_pred(pred):
        taken = true_fn if pred else false_fn
        return tuple(taken(*init))

    from ...layers import control_flow

    box = {}
    # cond2 is appended to the program's CURRENT block (which may be a
    # sub-block when this if is nested inside another converted branch)
    # — pred.block can be an outer block and rollback would miss the op
    parent = pred.block.program.current_block()
    n_ops0 = len(parent.ops)

    def wrap(fn, key):
        def run():
            outs = tuple(fn(*init))
            box[key] = outs
            tensors = [o for o in outs if isinstance(o, Variable)]
            return tensors or None
        return run

    def retry_with_undef():
        if _retried or "t" not in box or "f" not in box:
            return None
        slots = _undef_only_slots(names, box["t"], box["f"])
        if not slots:
            return None
        # drop the first attempt's cond2 (and anything after it) from
        # the parent block — leaving it would trace AND execute both
        # branch bodies twice per step (orphaned sub-blocks are dead)
        del parent.ops[n_ops0:]
        return convert_ifelse(
            pred, _drop_slots(true_fn, names, slots),
            _drop_slots(false_fn, names, slots), names, init,
            _retried=True)

    try:
        merged = control_flow.cond(pred, wrap(true_fn, "t"),
                                   wrap(false_fn, "f"))
    except ValueError as e:
        # arity / branch-output mismatch: one branch made a name a
        # tensor (or a different-shaped tensor) the other left alone
        if ("arity" in str(e) or "branch output mismatch" in str(e)) \
                and "t" in box and "f" in box:
            r = retry_with_undef()
            if r is not None:
                return r
            for name, tv, fv in zip(names, box["t"], box["f"]):
                if isinstance(tv, Variable) != isinstance(fv, Variable):
                    raise TypeError(
                        f"converted if: {name!r} is a tensor in one "
                        "branch but not the other; assign it a "
                        "matching tensor in both branches") from e
        raise
    if merged is None:
        merged_list = []
    elif isinstance(merged, Variable):
        merged_list = [merged]
    else:
        merged_list = list(merged)

    # rebuild the full name tuple: tensor slots take the cond-merged
    # outputs positionally; python-value slots must agree between
    # branches (a tensor pred cannot select between python values)
    t_outs, f_outs = box["t"], box["f"]
    if not _retried and _undef_only_slots(names, t_outs, f_outs):
        r = retry_with_undef()
        if r is not None:
            return r
    out, mi = [], 0
    for name, tv, fv in zip(names, t_outs, f_outs):
        t_is, f_is = isinstance(tv, Variable), isinstance(fv, Variable)
        if t_is != f_is:
            raise TypeError(
                f"converted if: {name!r} is a tensor in one branch but "
                f"{'undefined' if isinstance(tv if not t_is else fv, _Undefined) else 'a python value'} "
                "in the other; assign it a matching tensor in both "
                "branches")
        if t_is:
            out.append(merged_list[mi])
            mi += 1
        else:
            same_undef = (isinstance(tv, _Undefined)
                          and isinstance(fv, _Undefined))
            if not same_undef and not _same_value(tv, fv):
                raise TypeError(
                    f"converted if: python value {name!r} differs "
                    f"between branches ({tv!r} vs {fv!r}) under a "
                    "tensor predicate; make it a tensor")
            out.append(tv)
    return tuple(out)


def convert_while(cond_fn: Callable, body_fn: Callable,
                  names: Sequence[str], init: Tuple) -> Tuple:
    """Dispatch a while loop: graph while when the predicate of the
    INITIAL state is a Variable, else plain Python."""
    pred0 = cond_fn(*init)
    if not _is_tensor_pred(pred0):
        vals = tuple(init)
        while cond_fn(*vals):
            vals = tuple(body_fn(*vals))
        return vals

    from ...layers import control_flow

    _check_defined(init, names, "while")
    # python-valued carries cannot change across a graph loop: they are
    # closed over, and loop_body checks they are never rebound
    tensor_idx = [i for i, v in enumerate(init)
                  if isinstance(v, Variable)]
    carries = [init[i] for i in tensor_idx]

    def loop_cond(*c):
        vals = list(init)
        for j, i in enumerate(tensor_idx):
            vals[i] = c[j]
        return cond_fn(*vals)

    def loop_body(*c):
        vals = list(init)
        for j, i in enumerate(tensor_idx):
            vals[i] = c[j]
        outs = body_fn(*vals)
        for i, (a, b) in enumerate(zip(init, outs)):
            if i not in tensor_idx and not _same_value(a, b):
                raise TypeError(
                    f"converted while rebinds python value {names[i]!r}"
                    " inside a tensor loop; make it a tensor (e.g. "
                    "fill_constant) to carry it through the loop")
        return [outs[i] for i in tensor_idx]

    final = control_flow.while_loop(loop_cond, loop_body, carries,
                                    _initial_pred=pred0)
    if isinstance(final, Variable):
        final = [final]
    vals = list(init)
    for j, i in enumerate(tensor_idx):
        vals[i] = final[j]
    return tuple(vals)


def convert_logical_and(lhs, rhs_fn: Callable):
    if isinstance(lhs, Variable):
        from ...layers import tensor as T

        return T.logical_and(lhs, _to_bool_tensor(rhs_fn()))
    return lhs and rhs_fn()


def convert_logical_or(lhs, rhs_fn: Callable):
    if isinstance(lhs, Variable):
        from ...layers import tensor as T

        return T.logical_or(lhs, _to_bool_tensor(rhs_fn()))
    return lhs or rhs_fn()


def convert_logical_not(x):
    if isinstance(x, Variable):
        from ...layers import tensor as T

        return T.logical_not(x)
    return not x


def _to_bool_tensor(v):
    if isinstance(v, Variable):
        return v
    raise TypeError(
        "mixed tensor/python operands in a converted boolean "
        "expression; wrap the python value in a tensor")
