"""AST rewriting for dygraph-to-static.

Reference: dygraph_to_static/program_translator.py:711 (ProgramTranslator
+ StaticLayer), ifelse_transformer.py, loop_transformer.py,
logical_transformer.py — collapsed into one conservative transformer.

The rewrite turns

    if cond:            ->  def __pt_true_1(x, y): ...; return (x, y)
        ...                 def __pt_false_1(x, y): ...; return (x, y)
        ...                 (x, y) = __pt_d2s.convert_ifelse(
    else:                       cond, __pt_true_1, __pt_false_1,
        ...                     ("x", "y"),
                                tuple of current values (UNDEF if unbound))

and analogously ``while`` -> convert_while, ``a and b`` ->
convert_logical_and(a, lambda: b). Statements containing
return/break/continue (or other constructs outside the supported
subset) are left untouched — if their predicate turns out to be a
tensor at trace time, Variable.__bool__ raises the standard loud error.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import warnings
from typing import List, Set

from . import convert_operators as _ops

_HELPER_NAME = "__pt_d2s"


# ---------------------------------------------------------------------------
# analysis helpers
# ---------------------------------------------------------------------------

def _assigned_names(stmts: List[ast.stmt]) -> Set[str]:
    """Simple names bound by a statement list (incl. nested blocks)."""
    names: Set[str] = set()

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                names.add(node.id)

        def visit_FunctionDef(self, node):  # don't descend
            names.add(node.name)

        def visit_AsyncFunctionDef(self, node):
            names.add(node.name)

        def visit_Lambda(self, node):
            pass

    for s in stmts:
        V().visit(s)
    return names


def _contains_flow_escape(stmts: List[ast.stmt]) -> bool:
    """return/break/continue/yield directly inside (not nested defs)."""
    found = False

    class V(ast.NodeVisitor):
        def visit_Return(self, node):
            nonlocal found
            found = True

        def visit_Break(self, node):
            nonlocal found
            found = True

        def visit_Continue(self, node):
            nonlocal found
            found = True

        def visit_Yield(self, node):
            nonlocal found
            found = True

        def visit_FunctionDef(self, node):
            pass

        def visit_AsyncFunctionDef(self, node):
            pass

        def visit_Lambda(self, node):
            pass

    for s in stmts:
        V().visit(s)
    return found


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _helper_attr(fn_name):
    return ast.Attribute(value=_name(_HELPER_NAME), attr=fn_name,
                         ctx=ast.Load())


def _locals_get(varnames):
    """tuple(locals().get('v', UNDEF('v')) for each v) as an AST expr."""
    elts = []
    for v in varnames:
        elts.append(ast.Call(
            func=ast.Attribute(
                value=ast.Call(func=_name("locals"), args=[],
                               keywords=[]),
                attr="get", ctx=ast.Load()),
            args=[ast.Constant(v),
                  ast.Call(func=_helper_attr("UNDEF"),
                           args=[ast.Constant(v)], keywords=[])],
            keywords=[]))
    return ast.Tuple(elts=elts, ctx=ast.Load())


def _make_branch_fn(name, params, body, ret_names):
    """def name(p1, ..., pn): <body>; return (r1, ..., rn)"""
    body = list(body) + [ast.Return(value=ast.Tuple(
        elts=[_name(r) for r in ret_names], ctx=ast.Load()))]
    args = ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=p) for p in params],
        vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
        defaults=[])
    return ast.FunctionDef(name=name, args=args, body=body,
                           decorator_list=[], returns=None,
                           type_params=[])


_RET_NAME = "__d2s_ret__"


def _has_direct_return(stmts) -> bool:
    found = False

    class V(ast.NodeVisitor):
        def visit_Return(self, node):
            nonlocal found
            found = True

        def visit_FunctionDef(self, node):
            pass

        def visit_AsyncFunctionDef(self, node):
            pass

        def visit_Lambda(self, node):
            pass

    for s in stmts:
        V().visit(s)
    return found


def _terminates(stmts) -> bool:
    """Every path through `stmts` ends in a return."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.If):
        return _terminates(last.body) and _terminates(last.orelse)
    return False


class _UnsupportedReturn(Exception):
    pass


def _rewrite_returns(stmts):
    """Continuation-style early-return rewrite (reference
    return_transformer.py, restricted to the always-returns-branch
    shape): an `if` whose taken branch ALWAYS returns absorbs the rest
    of the function into its other branch, and every `return X` becomes
    `__d2s_ret__ = X` — so both branches of the (later-converted) if
    bind the same name and the tensor merge works. One `return
    __d2s_ret__` is appended by the caller.

    Unsupported shapes (a return that does not terminate its branch, a
    return inside a loop) raise _UnsupportedReturn — the function is
    then left untouched, preserving the old loud-error behavior."""
    out = []
    for i, s in enumerate(stmts):
        if isinstance(s, ast.Return):
            out.append(ast.Assign(
                targets=[_name(_RET_NAME, ast.Store())],
                value=s.value or ast.Constant(None)))
            return out                      # anything after is dead
        if isinstance(s, (ast.While, ast.For)) \
                and _has_direct_return([s]):
            raise _UnsupportedReturn("return inside a loop")
        if isinstance(s, ast.If) and _has_direct_return([s]):
            rest = stmts[i + 1:]
            if _terminates(s.body):
                s.body = _rewrite_returns(s.body)
                s.orelse = _rewrite_returns(list(s.orelse) + rest)
                out.append(s)
                return out
            if s.orelse and _terminates(s.orelse):
                s.orelse = _rewrite_returns(s.orelse)
                s.body = _rewrite_returns(list(s.body) + rest)
                out.append(s)
                return out
            raise _UnsupportedReturn(
                "return does not terminate its branch")
        out.append(s)
    return out


def _transform_returns(fd: ast.FunctionDef) -> ast.FunctionDef:
    """Apply the early-return rewrite to a function body when it has
    returns anywhere but the tail; no-op (with the legacy loud-error
    path preserved) when the shape is unsupported."""
    non_tail = _has_direct_return(fd.body[:-1]) or (
        fd.body and isinstance(fd.body[-1], ast.If)
        and _has_direct_return([fd.body[-1]]))
    if not non_tail:
        return fd
    import copy
    try:
        # rewrite a COPY: _rewrite_returns mutates If nodes in place, so
        # bailing out mid-rewrite must not leave a half-transformed tree
        new_body = _rewrite_returns(copy.deepcopy(fd.body))
    except _UnsupportedReturn:
        return fd
    init = ast.Assign(targets=[_name(_RET_NAME, ast.Store())],
                      value=ast.Constant(None))
    fd.body = [init] + new_body + [
        ast.Return(value=_name(_RET_NAME))]
    return fd


class _D2STransformer(ast.NodeTransformer):
    def __init__(self):
        self._n = 0

    def _uid(self):
        self._n += 1
        return self._n

    # -- if/else ---------------------------------------------------------
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        if _contains_flow_escape(node.body) \
                or _contains_flow_escape(node.orelse):
            return node
        names = sorted(n for n in (_assigned_names(node.body)
                                   | _assigned_names(node.orelse))
                       if not n.startswith("__pt_"))
        uid = self._uid()
        tname, fname = f"__pt_true_{uid}", f"__pt_false_{uid}"
        true_fn = _make_branch_fn(tname, names, node.body, names)
        false_fn = _make_branch_fn(fname, names, node.orelse or [ast.Pass()],
                                   names)
        call = ast.Call(
            func=_helper_attr("convert_ifelse"),
            args=[node.test, _name(tname), _name(fname),
                  ast.Tuple(elts=[ast.Constant(n) for n in names],
                            ctx=ast.Load()),
                  _locals_get(names)],
            keywords=[])
        if names:
            assign = ast.Assign(
                targets=[ast.Tuple(elts=[_name(n, ast.Store())
                                         for n in names],
                                   ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        return [true_fn, false_fn, assign]

    # -- while -----------------------------------------------------------
    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse or _contains_flow_escape(node.body):
            return node
        names = sorted(n for n in _assigned_names(node.body)
                       if not n.startswith("__pt_"))
        uid = self._uid()
        cname, bname = f"__pt_cond_{uid}", f"__pt_body_{uid}"
        cond_fn = _make_branch_fn(cname, names, [ast.Pass()], [])
        cond_fn.body = [ast.Return(value=node.test)]
        body_fn = _make_branch_fn(bname, names, node.body, names)
        call = ast.Call(
            func=_helper_attr("convert_while"),
            args=[_name(cname), _name(bname),
                  ast.Tuple(elts=[ast.Constant(n) for n in names],
                            ctx=ast.Load()),
                  _locals_get(names)],
            keywords=[])
        if names:
            assign = ast.Assign(
                targets=[ast.Tuple(elts=[_name(n, ast.Store())
                                         for n in names],
                                   ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        return [cond_fn, body_fn, assign]

    # -- boolean operators ----------------------------------------------
    def visit_BoolOp(self, node: ast.BoolOp):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        expr = node.values[0]
        for rhs in node.values[1:]:
            lam = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                                   kwonlyargs=[], kw_defaults=[],
                                   kwarg=None, defaults=[]),
                body=rhs)
            expr = ast.Call(func=_helper_attr(fn), args=[expr, lam],
                            keywords=[])
        return expr

    def visit_UnaryOp(self, node: ast.UnaryOp):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(func=_helper_attr("convert_logical_not"),
                            args=[node.operand], keywords=[])
        return node


# ---------------------------------------------------------------------------
# translator entry
# ---------------------------------------------------------------------------

class _LiveGlobals(dict):
    """Globals mapping for converted functions: local overlay (helper
    module + closure snapshots) with live delegation to the original
    module globals for everything else. CPython honors __missing__ for
    LOAD_GLOBAL on dict subclasses."""

    def __init__(self, base, overlay):
        super().__init__(overlay)
        self._base = base

    def __missing__(self, key):
        return self._base[key]

def unwrap_decorators(tree: ast.Module) -> ast.FunctionDef:
    fd = tree.body[0]
    assert isinstance(fd, (ast.FunctionDef, ast.AsyncFunctionDef))
    fd.decorator_list = []
    return fd


def convert_to_static(fn):
    """AST-rewrite `fn` into its static-graph-compatible form.

    Falls back to `fn` unchanged (with a warning) when the source is
    unavailable (builtins, lambdas, REPL) or the rewrite fails —
    trace-only conversion still works for tensor-free control flow.
    """
    if getattr(fn, "__pt_converted__", False):
        return fn
    if getattr(fn, "__name__", "") == "<lambda>":
        # lambdas cannot contain statements — nothing to convert (and
        # their extracted source is the enclosing assignment, unparsable
        # as a function)
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
        fd = unwrap_decorators(tree)
        _transform_returns(fd)
        tree = _D2STransformer().visit(tree)
        ast.fix_missing_locations(tree)
        code = compile(tree, filename=f"<d2s {fn.__qualname__}>",
                       mode="exec")
        # the converted function must see the module's globals LIVE
        # (late-bound helpers, monkeypatching, mutual recursion), so
        # lookups delegate to fn.__globals__ at call time; only the
        # collision-proof helper name and snapshot-by-nature closure
        # cells live in the overlay
        glb = _LiveGlobals(fn.__globals__, {_HELPER_NAME: _ops})
        if fn.__closure__:
            for nm, cell in zip(fn.__code__.co_freevars, fn.__closure__):
                try:
                    glb[nm] = cell.cell_contents
                except ValueError:
                    pass  # ok: unbound cell; name resolves via __globals__
        ns: dict = {}
        exec(code, glb, ns)
        new_fn = ns[fn.__name__]
        new_fn = functools.wraps(fn)(new_fn)
        new_fn.__pt_converted__ = True
        return new_fn
    except (OSError, TypeError, SyntaxError) as e:
        warnings.warn(
            f"dygraph_to_static: could not AST-convert "
            f"{getattr(fn, '__qualname__', fn)!r} ({e}); falling back "
            "to trace-only conversion")
        return fn


class ProgramTranslator:
    """reference ProgramTranslator singleton
    (dygraph_to_static/program_translator.py:711): global enable switch
    consulted by @declarative."""

    _instance = None
    enabled = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, flag: bool):
        type(self).enabled = bool(flag)

    def get_func(self, fn):
        return convert_to_static(fn) if self.enabled else fn
