"""Dygraph-to-static AST transpiler.

Reference: fluid/dygraph/dygraph_to_static/program_translator.py:711 and
its transformer stack (ifelse_transformer.py, loop_transformer.py,
logical_transformer.py). Same architecture, jax-era scope: Python
control flow whose predicate is a graph Variable is rewritten into
calls to runtime *conversion dispatchers* that build `cond` / `while`
sub-block ops, while Python-valued predicates keep exact Python
semantics (including short-circuiting) — the dispatch happens at trace
time on the predicate's runtime type, exactly like the reference's
convert_ifelse/convert_while_loop (dygraph_to_static/convert_operators.py).

Supported rewrites:
  * ``if``/``elif``/``else``  -> convert_ifelse(pred, true_fn, false_fn,
    pre-branch values of every name either branch assigns)
  * ``while``                 -> convert_while(cond_fn, body_fn, carries)
  * ``a and b`` / ``a or b`` / ``not a`` -> convert_logical_*

Deliberate restrictions (transform is skipped for that statement and
the existing Variable.__bool__ TypeError fires if the predicate turns
out to be a tensor): ``return``/``break``/``continue`` inside a
converted block, non-name assignment targets (attributes/subscripts)
carrying across branches, ``for`` over a tensor. Python ``for`` over
ranges/lists is left untouched (static unroll at trace time).
"""
from .program_translator import (ProgramTranslator, convert_to_static,
                                 unwrap_decorators)
from . import convert_operators  # noqa: F401

__all__ = ["ProgramTranslator", "convert_to_static", "unwrap_decorators"]
