"""Layer: the dygraph module system.

Reference: python/paddle/fluid/dygraph/layers.py (Layer:867 __call__,
sublayers/parameters/state_dict machinery).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Iterator, Optional, Tuple

import numpy as np

from ..framework.core import unique_name
from ..framework.layer_helper import LayerHelper, ParamAttr
from .varbase import ParamBase, VarBase


class Layer:
    def __init__(self, name_scope: Optional[str] = None,
                 dtype: str = "float32"):
        self._full_name = unique_name(
            name_scope or self.__class__.__name__.lower())
        self._dtype = dtype
        self.training = True
        self._parameters: "OrderedDict[str, ParamBase]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._buffers: "OrderedDict[str, VarBase]" = OrderedDict()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()

    # -- naming -------------------------------------------------------------
    def full_name(self) -> str:
        return self._full_name

    # -- attribute capture --------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        if isinstance(value, ParamBase) and params is not None:
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer) and subs is not None:
            subs[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                del params[name]
            if subs is not None and name in subs:
                del subs[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        params = self.__dict__.get("_parameters")
        if params is not None and name in params:
            return params[name]
        subs = self.__dict__.get("_sub_layers")
        if subs is not None and name in subs:
            return subs[name]
        bufs = self.__dict__.get("_buffers")
        if bufs is not None and name in bufs:
            return bufs[name]
        raise AttributeError(
            f"{self.__class__.__name__} has no attribute {name!r}")

    # -- registration -------------------------------------------------------
    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[name] = sublayer
        return sublayer

    def add_parameter(self, name: str, parameter: ParamBase) -> ParamBase:
        self._parameters[name] = parameter
        return parameter

    def register_buffer(self, name: str, tensor: VarBase,
                        persistable: bool = True):
        tensor.persistable = persistable
        self._buffers[name] = tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> ParamBase:
        helper = LayerHelper(self.full_name())
        return helper.create_parameter(attr, shape, dtype or self._dtype,
                                       is_bias, default_initializer)

    # -- traversal ----------------------------------------------------------
    def children(self) -> Iterator["Layer"]:
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def sublayers(self, include_self: bool = False):
        out = [self] if include_self else []
        for s in self._sub_layers.values():
            out.extend(s.sublayers(include_self=True))
        return out

    def parameters(self, include_sublayers: bool = True):
        return [p for _, p in self.named_parameters()]

    def named_parameters(self, prefix: str = ""):
        seen = set()
        for name, p in self._parameters.items():
            if id(p) not in seen:
                seen.add(id(p))
                yield (f"{prefix}.{name}" if prefix else name), p
        for sname, sub in self._sub_layers.items():
            sub_prefix = f"{prefix}.{sname}" if prefix else sname
            for n, p in sub.named_parameters(sub_prefix):
                if id(p) not in seen:
                    seen.add(id(p))
                    yield n, p

    def named_buffers(self, prefix: str = ""):
        for name, b in self._buffers.items():
            yield (f"{prefix}.{name}" if prefix else name), b
        for sname, sub in self._sub_layers.items():
            sub_prefix = f"{prefix}.{sname}" if prefix else sname
            yield from sub.named_buffers(sub_prefix)

    # -- modes --------------------------------------------------------------
    def train(self):
        self.training = True
        for s in self._sub_layers.values():
            s.train()
        return self

    def eval(self):
        self.training = False
        for s in self._sub_layers.values():
            s.eval()
        return self

    # -- state dict ---------------------------------------------------------
    def state_dict(self, include_sublayers: bool = True,
                   structured_name_prefix: str = ""):
        out = OrderedDict()
        for name, p in self.named_parameters(structured_name_prefix):
            out[name] = p
        for name, b in self.named_buffers(structured_name_prefix):
            out[name] = b
        return out

    def set_state_dict(self, state_dict, include_sublayers=True,
                       use_structured_name=True):
        own = self.state_dict()
        missing = []
        for name, target in own.items():
            if name in state_dict:
                v = state_dict[name]
                if isinstance(v, VarBase):
                    v = v.numpy()
                target.set_value(np.asarray(v))
            else:
                missing.append(name)
        return missing

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        key = len(self._forward_pre_hooks)
        self._forward_pre_hooks[key] = hook
        return _HookRemover(self._forward_pre_hooks, key)

    def register_forward_post_hook(self, hook):
        key = len(self._forward_post_hooks)
        self._forward_post_hooks[key] = hook
        return _HookRemover(self._forward_post_hooks, key)

    # -- call ---------------------------------------------------------------
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def __repr__(self):
        extra = ", ".join(self._sub_layers)
        return f"{self.__class__.__name__}({extra})"


class _HookRemover:
    def __init__(self, store, key):
        self._store, self._key = store, key

    def remove(self):
        self._store.pop(self._key, None)


class Sequential(Layer):
    """reference fluid.dygraph.Sequential."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], (list, tuple)):
            for name, layer in layers[0]:
                self.add_sublayer(str(name), layer)
        else:
            for i, layer in enumerate(layers):
                self.add_sublayer(str(i), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for i, l in enumerate(sublayers or []):
            self.add_sublayer(str(i), l)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __iter__(self):
        return iter(self._sub_layers.values())

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, *a, **kw):
        raise NotImplementedError("LayerList is a container")


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        for i, p in enumerate(parameters or []):
            self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return list(self._parameters.values())[idx]

    def __iter__(self):
        return iter(self._parameters.values())

    def __len__(self):
        return len(self._parameters)
