"""Dygraph save/load (reference fluid/dygraph/checkpoint.py:
save_dygraph -> .pdparams / .pdopt, load_dygraph)."""
from __future__ import annotations

import os
import pickle

import numpy as np

from .varbase import VarBase


def _to_plain(state_dict):
    out = {}
    for k, v in state_dict.items():
        out[k] = v.numpy() if isinstance(v, VarBase) else np.asarray(v)
    return out


def save_dygraph(state_dict, model_path: str):
    """state_dict from Layer.state_dict() (-> .pdparams) or
    Optimizer.state_dict() (-> .pdopt)."""
    is_opt = not any(isinstance(v, VarBase) for v in state_dict.values()) \
        and state_dict  # optimizer dicts hold raw arrays
    suffix = ".pdopt" if is_opt else ".pdparams"
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(model_path + suffix, "wb") as f:
        pickle.dump(_to_plain(state_dict), f, protocol=2)


def load_dygraph(model_path: str):
    """Returns (param_dict or None, opt_dict or None)."""
    params, opt = None, None
    if os.path.exists(model_path + ".pdparams"):
        with open(model_path + ".pdparams", "rb") as f:
            params = pickle.load(f)
    if os.path.exists(model_path + ".pdopt"):
        with open(model_path + ".pdopt", "rb") as f:
            opt = pickle.load(f)
    if params is None and opt is None:
        raise FileNotFoundError(
            f"no checkpoint at {model_path}(.pdparams/.pdopt)")
    return params, opt
