"""VarBase: the eager tensor (reference imperative/layer.h:65 VarBase and
fluid/dygraph dygraph.core.VarBase pybind).

TPU-native: wraps a jax.Array. Autograd is a tape of jax.vjp closures
recorded by the Tracer (see tracer.py) instead of the reference's grad-op
graph + BasicEngine dependency counting (imperative/basic_engine.cc:38).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..framework.core import convert_dtype, unique_name


class VarBase:
    def __init__(self, value=None, name: Optional[str] = None,
                 stop_gradient: bool = False, persistable: bool = False,
                 trainable: bool = True):
        self._value = value
        self.name = name or unique_name("eager_tmp")
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.trainable = trainable
        self._grad_value = None     # accumulated gradient (jax array)
        self._producer = None       # tape node that produced this var

    # -- value access -------------------------------------------------------
    @property
    def value(self):
        return self._value

    def set_value(self, v):
        import jax.numpy as jnp
        if isinstance(v, VarBase):
            v = v._value
        self._value = jnp.asarray(v)

    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    @property
    def shape(self):
        return tuple(np.shape(self._value)) if self._value is not None \
            else None

    @property
    def dtype(self):
        return convert_dtype(np.asarray(self._value).dtype) \
            if self._value is not None else "float32"

    @property
    def is_leaf(self) -> bool:
        return self._producer is None

    # -- autograd -----------------------------------------------------------
    @property
    def grad_value(self):
        return self._grad_value

    def gradient(self):
        return None if self._grad_value is None \
            else np.asarray(self._grad_value)

    @property
    def grad(self):
        return self.gradient()

    def clear_gradient(self):
        self._grad_value = None

    def backward(self, retain_graph: bool = False):
        from .tracer import backward as _backward
        _backward(self, retain_graph=retain_graph)

    def detach(self) -> "VarBase":
        return VarBase(self._value, name=unique_name(self.name + ".detach"),
                       stop_gradient=True, persistable=self.persistable)

    # -- conversions / convenience -----------------------------------------
    def astype(self, dtype) -> "VarBase":
        from .. import layers
        return layers.cast(self, convert_dtype(dtype))

    def reshape(self, shape):
        from .. import layers
        return layers.reshape(self, list(shape))

    def __len__(self):
        s = self.shape
        return int(s[0]) if s else 0

    def __float__(self):
        return float(np.asarray(self._value).reshape(-1)[0])

    def __repr__(self):
        g = "" if self.stop_gradient else ", grad"
        return (f"VarBase(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}{g})\n{self.numpy()!r}")

    # -- math operators (route through the traced op library) ---------------
    def _binary(self, other, fwd, rev=False):
        from .. import layers
        fn = getattr(layers, fwd)
        if isinstance(other, VarBase):
            a, b = (other, self) if rev else (self, other)
            return fn(a, b)
        from .base import to_variable
        o = to_variable(np.asarray(other, dtype=self.dtype))
        o.stop_gradient = True
        a, b = (o, self) if rev else (self, o)
        return fn(a, b)

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", rev=True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elementwise_div", rev=True)

    def __pow__(self, o):
        return self._binary(o, "elementwise_pow")

    def __neg__(self):
        from .. import layers
        return layers.scale(self, scale=-1.0)

    def __matmul__(self, o):
        from .. import layers
        return layers.matmul(self, o)

    def __getitem__(self, idx):
        from .. import layers
        if isinstance(idx, int):
            out = layers.slice(self, axes=[0], starts=[idx], ends=[idx + 1])
            return layers.squeeze(out, [0])
        if isinstance(idx, slice):
            start = idx.start or 0
            stop = idx.stop if idx.stop is not None else int(self.shape[0])
            return layers.slice(self, axes=[0], starts=[start], ends=[stop])
        raise TypeError(f"unsupported index {idx!r}")


class ParamBase(VarBase):
    """Eager parameter (reference ParamBase / dygraph Parameter)."""

    def __init__(self, value=None, name=None, trainable=True, **kw):
        super().__init__(value, name=name, stop_gradient=not trainable,
                         persistable=True, trainable=trainable, **kw)
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False

    def __repr__(self):
        return (f"Parameter(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}, trainable={self.trainable})")
