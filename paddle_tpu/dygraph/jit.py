"""Dygraph-to-static: @declarative / TracedLayer.

Reference: ProgramTranslator + @declarative (fluid/dygraph/jit.py:159,
dygraph_to_static/program_translator.py:711). The reference rewrites Python
ASTs to turn imperative code into Program-building code; here the layer
functions themselves are dual-mode (they append ops when no tracer is
active), so "translation" is simply: run the function in static mode,
capture the eager ParamBases it references as program parameters, compile
via the Executor (one XLA computation), and sync state back.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List

import numpy as np

from ..framework.core import (Program, Variable, _dygraph_tracer,
                              _set_dygraph_tracer, program_guard)
from ..framework.executor import Executor, Scope
from .varbase import VarBase


def _to_numpy(v):
    if isinstance(v, VarBase):
        return np.asarray(v._value)
    return np.asarray(v)


class _StaticFunction:
    def __init__(self, fn):
        self._orig_fn = fn
        self._converted = None
        self._cache: Dict[tuple, tuple] = {}
        self._exe = Executor()
        functools.update_wrapper(self, fn)

    @property
    def _fn(self):
        """AST pass (reference @declarative runs ProgramTranslator
        before tracing): tensor-dependent if/while/and/or become
        cond/while graph ops instead of silently baking one branch in.
        The ProgramTranslator enable flag is consulted per call, like
        the reference singleton."""
        from .dygraph_to_static import ProgramTranslator

        if not ProgramTranslator.enabled:
            return self._orig_fn
        if self._converted is None:
            self._converted = ProgramTranslator.get_instance().get_func(
                self._orig_fn)
        return self._converted

    def __call__(self, *args):
        from .dygraph_to_static import ProgramTranslator

        arrs = [_to_numpy(a) for a in args]
        sig = (ProgramTranslator.enabled,) \
            + tuple((a.shape, str(a.dtype)) for a in arrs)
        entry = self._cache.get(sig)
        if entry is None:
            entry = self._trace(arrs)
            self._cache[sig] = entry
        main, feed_names, out_vars, structure, scope, captures = entry

        feed = dict(zip(feed_names, arrs))
        results = self._exe.run(main, feed=feed, fetch_list=out_vars,
                                scope=scope, return_numpy=False)
        # sync mutated persistable state (params, BN stats) back to eager
        for name, vb in captures.items():
            val = scope.find_var(name)
            if val is not None:
                vb._value = val
        out_vbs = [VarBase(r, stop_gradient=True) for r in results]
        return _unflatten(structure, out_vbs)

    def _trace(self, arrs):
        from ..layers import tensor as T

        main, startup = Program(), Program()
        startup._is_startup = True
        tracer = _dygraph_tracer()
        _set_dygraph_tracer(None)
        try:
            with program_guard(main, startup):
                static_args = []
                feed_names = []
                for i, a in enumerate(arrs):
                    name = f"__ts_arg_{i}"
                    v = T.data(name, list(a.shape), dtype=str(a.dtype),
                               append_batch_size=False)
                    static_args.append(v)
                    feed_names.append(name)
                outs = self._fn(*static_args)
        finally:
            _set_dygraph_tracer(tracer)

        structure, out_vars = _flatten(outs)
        scope = Scope()
        # initialize any params created during the trace itself
        self._exe.run(startup, scope=scope)
        # inject captured eager parameters/buffers
        captures = dict(getattr(main, "_captures", {}))
        for name, vb in captures.items():
            scope.set_var(name, vb._value)
        return main, feed_names, out_vars, structure, scope, captures


def _flatten(outs):
    from .dygraph_to_static.convert_operators import _Undefined

    vals = list(outs) if isinstance(outs, (list, tuple)) else [outs]
    for v in vals:
        if isinstance(v, _Undefined):
            v._raise()  # NameError naming the unbound local
    if isinstance(outs, (list, tuple)):
        return ("seq", type(outs), len(outs)), vals
    return ("one", None, 1), vals


def _unflatten(structure, vals):
    kind, typ, n = structure
    if kind == "one":
        return vals[0]
    return typ(vals)


def declarative(fn=None, input_spec=None):
    """@declarative / @paddle.jit.to_static."""
    if fn is None:
        return lambda f: _StaticFunction(f)
    return _StaticFunction(fn)


to_static = declarative
dygraph_to_static_func = declarative


class TracedLayer:
    """reference fluid.dygraph.TracedLayer (jit.py TracedLayer.trace)."""

    def __init__(self, layer, static_fn):
        self._layer = layer
        self._static_fn = static_fn

    @staticmethod
    def trace(layer, inputs):
        out = layer(*inputs)
        static_fn = _StaticFunction(lambda *a: layer(*a))
        traced = TracedLayer(layer, static_fn)
        return out, traced

    def __call__(self, *inputs):
        return self._static_fn(*inputs)

    def save_inference_model(self, path, feed=None, fetch=None):
        raise NotImplementedError("wired up with io.save_inference_model")
