"""Dygraph-to-static: @declarative / TracedLayer.

Reference: ProgramTranslator + @declarative (fluid/dygraph/jit.py:159,
dygraph_to_static/program_translator.py:711). The reference rewrites Python
ASTs to turn imperative code into Program-building code; here the layer
functions themselves are dual-mode (they append ops when no tracer is
active), so "translation" is simply: run the function in static mode,
capture the eager ParamBases it references as program parameters, compile
via the Executor (one XLA computation), and sync state back.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List

import numpy as np

from ..framework.core import (Program, Variable, _dygraph_tracer,
                              _set_dygraph_tracer, program_guard)
from ..framework.executor import Executor, Scope
from .varbase import VarBase


def _to_numpy(v):
    if isinstance(v, VarBase):
        return np.asarray(v._value)
    return np.asarray(v)


class _StaticFunction:
    def __init__(self, fn):
        self._orig_fn = fn
        self._converted = None
        self._cache: Dict[tuple, tuple] = {}
        self._exe = Executor()
        functools.update_wrapper(self, fn)

    @property
    def _fn(self):
        """AST pass (reference @declarative runs ProgramTranslator
        before tracing): tensor-dependent if/while/and/or become
        cond/while graph ops instead of silently baking one branch in.
        The ProgramTranslator enable flag is consulted per call, like
        the reference singleton."""
        from .dygraph_to_static import ProgramTranslator

        if not ProgramTranslator.enabled:
            return self._orig_fn
        if self._converted is None:
            self._converted = ProgramTranslator.get_instance().get_func(
                self._orig_fn)
        return self._converted

    def __call__(self, *args):
        from .dygraph_to_static import ProgramTranslator

        arrs = [_to_numpy(a) for a in args]
        sig = (ProgramTranslator.enabled,) \
            + tuple((a.shape, str(a.dtype)) for a in arrs)
        entry = self._cache.get(sig)
        if entry is None:
            entry = self._trace(arrs)
            self._cache[sig] = entry
        main, feed_names, out_vars, structure, scope, captures = entry

        feed = dict(zip(feed_names, arrs))
        results = self._exe.run(main, feed=feed, fetch_list=out_vars,
                                scope=scope, return_numpy=False)
        # sync mutated persistable state (params, BN stats) back to eager
        for name, vb in captures.items():
            val = scope.find_var(name)
            if val is not None:
                vb._value = val
        # unwrap lazy FetchHandles: downstream dygraph ops expect raw
        # device arrays on VarBase._value
        out_vbs = [VarBase(getattr(r, "value", r), stop_gradient=True)
                   for r in results]
        return _unflatten(structure, out_vbs)

    def _trace(self, arrs):
        from ..layers import tensor as T

        main, startup = Program(), Program()
        startup._is_startup = True
        tracer = _dygraph_tracer()
        _set_dygraph_tracer(None)
        try:
            with program_guard(main, startup):
                static_args = []
                feed_names = []
                for i, a in enumerate(arrs):
                    name = f"__ts_arg_{i}"
                    v = T.data(name, list(a.shape), dtype=str(a.dtype),
                               append_batch_size=False)
                    static_args.append(v)
                    feed_names.append(name)
                outs = self._fn(*static_args)
        finally:
            _set_dygraph_tracer(tracer)

        structure, out_vars = _flatten(outs)
        scope = Scope()
        # initialize any params created during the trace itself
        self._exe.run(startup, scope=scope)
        # inject captured eager parameters/buffers
        captures = dict(getattr(main, "_captures", {}))
        for name, vb in captures.items():
            scope.set_var(name, vb._value)
        return main, feed_names, out_vars, structure, scope, captures


def _flatten(outs):
    from .dygraph_to_static.convert_operators import _Undefined

    vals = list(outs) if isinstance(outs, (list, tuple)) else [outs]
    for v in vals:
        if isinstance(v, _Undefined):
            v._raise()  # NameError naming the unbound local
    if isinstance(outs, (list, tuple)):
        return ("seq", type(outs), len(outs)), vals
    return ("one", None, 1), vals


def _static_save_inference_model(entry, dirname, feed=None, fetch=None):
    """Save one traced entry via the static io path."""
    from ..framework.executor import scope_guard
    from .. import io as pt_io

    main, feed_names, out_vars, _structure, scope, _captures = entry
    feed_idx = list(feed) if feed is not None else range(len(feed_names))
    fetch_idx = list(fetch) if fetch is not None else range(len(out_vars))
    with scope_guard(scope):
        pt_io.save_inference_model(
            dirname, [feed_names[i] for i in feed_idx],
            [out_vars[j] for j in fetch_idx], Executor(),
            main_program=main)


def _sf_latest_entry(self):
    if not self._cache:
        raise RuntimeError(
            "py trace cache empty: call the traced function once (or "
            "pass input_spec / example inputs) before saving")
    return next(reversed(self._cache.values()))


def _sf_save_inference_model(self, dirname, feed=None, fetch=None):
    _static_save_inference_model(self._latest_entry(), dirname,
                                 feed=feed, fetch=fetch)


_StaticFunction._latest_entry = _sf_latest_entry
_StaticFunction.save_inference_model = _sf_save_inference_model


def save(layer, path, input_spec=None):
    """paddle.jit.save (reference python/paddle/fluid/dygraph/jit.py:159
    `save`): trace a Layer / @declarative function and serialize the
    inference program + params under `path` (a directory here — the
    serde is the repo's JSON program format, not protobuf). Reloadable
    by jit.load, io.load_inference_model, and inference.Predictor."""
    from ..static import InputSpec

    if isinstance(layer, _StaticFunction):
        sf = layer
    elif hasattr(layer, "forward") and isinstance(layer.forward,
                                                  _StaticFunction):
        sf = layer.forward
    else:
        sf = _StaticFunction(lambda *a: layer(*a))
    if input_spec is not None:
        arrs = []
        poly_dims = []
        for spec in input_spec:
            if isinstance(spec, InputSpec):
                shape = [1 if (d is None or d < 0) else int(d)
                         for d in spec.shape]
                arrs.append(np.zeros(shape, spec.dtype))
                poly_dims.append([i for i, d in enumerate(spec.shape)
                                  if d is None or int(d) < 0])
            else:
                arrs.append(_to_numpy(spec))
                poly_dims.append([])
        sf(*arrs)  # ensure a trace exists for this signature
        # Restore polymorphic dims on the traced feed vars: the trace
        # itself must run at a concrete sample size (XLA compiles
        # static shapes), but the EXPORTED contract keeps -1 where the
        # spec said None/-1 — the Executor specializes -1 dims from the
        # feed at compile time, so the loaded program serves any batch
        # instead of being frozen to the sample size.
        main, feed_names = sf._latest_entry()[:2]
        block = main.global_block()
        for name, dims in zip(feed_names, poly_dims):
            if not dims or not block.has_var(name):
                continue
            v = block.var(name)
            shape = list(v.shape)
            for d in dims:
                if d < len(shape):
                    shape[d] = -1
            v.shape = tuple(shape)
    sf.save_inference_model(path)


class TranslatedLayer:
    """Result of jit.load: a callable serving the saved program
    (reference dygraph/io.py TranslatedLayer)."""

    def __init__(self, dirname):
        from ..framework.executor import scope_guard
        from .. import io as pt_io

        self._exe = Executor()
        self._scope = Scope()
        with scope_guard(self._scope):
            prog, feeds, fetches = pt_io.load_inference_model(
                dirname, self._exe)
        self._program, self._feeds, self._fetches = prog, feeds, fetches

    def __call__(self, *inputs):
        feed = {n: _to_numpy(v) for n, v in zip(self._feeds, inputs)}
        res = self._exe.run(self._program, feed=feed,
                            fetch_list=self._fetches, scope=self._scope,
                            return_numpy=False)
        outs = [VarBase(getattr(r, "value", r), stop_gradient=True)
                for r in res]
        return outs[0] if len(outs) == 1 else outs

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer serves inference only "
                           "(reference parity: retraining a loaded "
                           "model goes through the static API)")


def load(path):
    """paddle.jit.load — see `save`."""
    return TranslatedLayer(path)


def _unflatten(structure, vals):
    kind, typ, n = structure
    if kind == "one":
        return vals[0]
    return typ(vals)


def declarative(fn=None, input_spec=None):
    """@declarative / @paddle.jit.to_static."""
    if fn is None:
        return lambda f: _StaticFunction(f)
    return _StaticFunction(fn)


to_static = declarative
dygraph_to_static_func = declarative


class TracedLayer:
    """reference fluid.dygraph.TracedLayer (jit.py TracedLayer.trace)."""

    def __init__(self, layer, static_fn):
        self._layer = layer
        self._static_fn = static_fn

    @staticmethod
    def trace(layer, inputs):
        out = layer(*inputs)
        static_fn = _StaticFunction(lambda *a: layer(*a))
        traced = TracedLayer(layer, static_fn)
        # trace the static program right away (reference trace() builds
        # the ProgramDesc here, not lazily) so save_inference_model can
        # run without another forward
        traced(*inputs)
        return out, traced

    def __call__(self, *inputs):
        return self._static_fn(*inputs)

    def save_inference_model(self, path, feed=None, fetch=None):
        """Serialize the traced program + params so the static
        inference stack (io.load_inference_model / inference.Predictor)
        can serve it (reference dygraph/jit.py TracedLayer.save_
        inference_model; feed/fetch are INDEX lists like the
        reference's)."""
        self._static_fn.save_inference_model(path, feed=feed,
                                             fetch=fetch)
