"""Imperative (dygraph) mode — eager execution with tape autograd.

Reference: paddle/fluid/imperative/ (C++ Tracer/BasicEngine) +
python/paddle/fluid/dygraph/. See tracer.py for the TPU-native design.
"""
from .base import (guard, enabled, enable_dygraph, disable_dygraph,  # noqa
                   no_grad, to_variable)
from .layers import Layer, Sequential, LayerList, ParameterList  # noqa
from .varbase import VarBase, ParamBase  # noqa
from .nn import (Linear, Conv2D, Pool2D, BatchNorm, LayerNorm,  # noqa
                 Embedding, Dropout, GroupNorm, Flatten,
                 SpectralNorm)
from .parallel import (DataParallel, ParallelEnv, prepare_context,  # noqa
                       ParallelStrategy)
from .jit import (declarative, dygraph_to_static_func, TracedLayer,  # noqa
                  TranslatedLayer)
from . import jit  # noqa
from . import dygraph_to_static  # noqa
from .dygraph_to_static import ProgramTranslator  # noqa
from .checkpoint import save_dygraph, load_dygraph  # noqa
from . import amp  # noqa
from .amp import amp_guard, auto_cast, GradScaler  # noqa
