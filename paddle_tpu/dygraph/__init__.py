"""Imperative (dygraph) mode — placeholder, populated in later milestones."""
